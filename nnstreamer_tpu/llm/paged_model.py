"""Paged prefill / decode-step math (pure jax, jitted by llm_exec).

Parity contract (the acceptance gate): at temperature 0 the paged
engine's tokens must equal `transformer.generate`'s token-for-token.
Both functions here therefore mirror `transformer._step_impl`'s cached
attention exactly — the same f32 einsum pair, the same -1e30 additive
mask, softmax in f32 — over a *gathered* KV axis instead of a
contiguous ring. Masked positions (padding, unwritten or stale block
slots) contribute exp(-1e30-…) = exactly 0.0 attention weight, and a
0.0 weight times any finite stale value is exactly 0.0 in the value
contraction, so gathering `max_blocks * block_size` slots instead of a
dense `max_len` window changes no bits of the surviving terms.

Shapes:
- k/v pool: (L, num_blocks, block_size, n_kv, hd)  — PagedKVCache
- prefill:  ids (1, S_b) padded prompt; per-position (block, offset)
  scatter targets (padding targets the scratch block)
- decode:   one token per sequence row; per-row block tables
  (B_b, max_blocks) and positions (B_b,) (padding rows → scratch)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models.transformer import (
    _expand_kv, apply_seq_kv, rmsnorm)


def _proj(store, name, x, dtype):
    """One projection matmul, quant-aware: a store version whose params
    carry ``<name>_scale`` (models/quant.quantize_transformer) routes
    through the W8A8 int8 path; float params take the dense matmul the
    reference always took — for float weights this is bit-identical to
    the inline ``x @ w`` it replaced, so the parity contract is
    untouched."""
    if f"{name}_scale" in store:
        from nnstreamer_tpu.models.quant import w8a8_matmul

        return w8a8_matmul(x, store[name],
                           store[f"{name}_scale"]).astype(dtype)
    return x @ store[name].astype(dtype)


def _mlp_paged(blk, x, dtype):
    """SwiGLU MLP through `_proj` — the quant-aware twin of
    `transformer._mlp` (identical math for float params)."""
    gate_up = _proj(blk, "wi", x, dtype)
    gate, up = jnp.split(gate_up, 2, axis=-1)
    return _proj(blk, "wd", jax.nn.silu(gate) * up, dtype)


def _rope_rows(x, pos):
    """Rotary embedding with a PER-ROW position: x (B, 1, H, D),
    pos (B,). Same f32 angle math as `transformer.rope`, broadcast over
    the batch instead of the sequence axis — row b's values are bit-
    identical to rope(x[b:b+1], pos[b:b+1])."""
    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = pos.astype(jnp.float32)[:, None] * freqs[None, :]   # (B, half)
    cos = jnp.cos(ang)[:, None, None, :]
    sin = jnp.sin(ang)[:, None, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


def paged_prefill(params, ids, blk_idx, blk_off, k_pool, v_pool, last_idx,
                  *, n_heads=4, dtype=jnp.float32):
    """Bucketed prompt prefill: full-sequence forward + KV scatter.

    ids (1, S_b) int32 — the prompt padded to its pow2 bucket;
    blk_idx/blk_off (S_b,) int32 — per-position pool write targets
    (padding positions point at the scratch block); last_idx — index of
    the final real prompt token. Returns (last-token logits (vocab,),
    k_pool, v_pool). Pools are donated by the caller's jit.
    """
    logits, ks, vs = apply_seq_kv(params, ids, n_heads=n_heads,
                                  dtype=dtype)
    # ks/vs: (L, 1, S_b, n_kv, hd) → scatter each position into its
    # (block, offset) slot across all layers at once
    k_pool = k_pool.at[:, blk_idx, blk_off].set(
        ks[:, 0].astype(k_pool.dtype))
    v_pool = v_pool.at[:, blk_idx, blk_off].set(
        vs[:, 0].astype(v_pool.dtype))
    return logits[0, last_idx], k_pool, v_pool


def paged_decode_step(params, cur, tables, pos, k_pool, v_pool,
                      *, n_heads=4, dtype=jnp.float32):
    """One decode step for a bucketed batch over the paged pool.

    cur (B_b,) int32 current tokens; tables (B_b, max_blocks) int32
    per-sequence block tables; pos (B_b,) int32 write positions.
    Returns (logits (B_b, vocab) f32, k_pool, v_pool).

    Mirrors `transformer._step_impl` with three serving deltas: the
    cache axis is gathered through the block tables, positions are
    per-row (sequences at different depths share one step), and there
    is no ring wrap — admission enforces prompt+new <= table capacity.
    """
    b = cur.shape[0]
    n_layers, _, block_size, _, _ = k_pool.shape
    max_blocks = tables.shape[1]
    kv_len = max_blocks * block_size
    rows = jnp.arange(b)
    write_blk = tables[rows, pos // block_size]      # (B,)
    write_off = pos % block_size
    x = params["embed"][cur][:, None, :].astype(dtype)   # (B,1,D)
    # attend over positions <= pos[b] (same inclusive window as
    # _step_impl's `arange(max_len) <= p`)
    mask = (jnp.arange(kv_len)[None, None, None, :] <=
            pos[:, None, None, None])
    for li, blk in enumerate(params["blocks"]):
        h = rmsnorm(x, blk["ln1"].astype(dtype))
        d = x.shape[-1]
        hd = d // n_heads
        qkv = _proj(blk, "wqkv", h, dtype)
        kv_dim = (qkv.shape[-1] - d) // 2
        n_kv = kv_dim // hd
        q = qkv[..., :d].reshape(b, 1, n_heads, hd)
        k = qkv[..., d:d + kv_dim].reshape(b, 1, n_kv, hd)
        v = qkv[..., d + kv_dim:].reshape(b, 1, n_kv, hd)
        q, k = _rope_rows(q, pos), _rope_rows(k, pos)
        k_pool = k_pool.at[li, write_blk, write_off].set(
            k[:, 0].astype(k_pool.dtype))
        v_pool = v_pool.at[li, write_blk, write_off].set(
            v[:, 0].astype(v_pool.dtype))
        # gather this batch's KV through the block tables:
        # (B, max_blocks, block_size, n_kv, hd) → (B, kv_len, n_kv, hd)
        kc = k_pool[li][tables].reshape(b, kv_len, n_kv, hd)
        vc = v_pool[li][tables].reshape(b, kv_len, n_kv, hd)
        kcx = _expand_kv(kc, n_heads).astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       kcx) * hd ** -0.5                # (B,H,1,kv_len)
        s = jnp.where(mask, s, -1e30)
        pattn = jax.nn.softmax(s, axis=-1)
        vcx = _expand_kv(vc, n_heads).astype(jnp.float32)
        attn = jnp.einsum("bhqk,bkhd->bqhd", pattn, vcx).astype(dtype)
        x = x + _proj(blk, "wo", attn.reshape(b, 1, -1), dtype)
        h = rmsnorm(x, blk["ln2"].astype(dtype))
        x = x + _mlp_paged(blk, h, dtype)
    x = rmsnorm(x, params["ln_f"].astype(dtype))
    logits = _proj(params, "head", x[:, 0], dtype).astype(jnp.float32)
    return logits, k_pool, v_pool


def paged_prefill_chunk(params, ids, pos0, blk_idx, blk_off, table,
                        k_pool, v_pool, last_idx,
                        *, n_heads=4, dtype=jnp.float32):
    """One prompt chunk for a single sequence — the XLA reference for
    chunked prefill.

    ids (1, C_b) int32 — this chunk's tokens padded to the chunk
    bucket; pos0 () int32 — absolute position of the chunk's first
    token; blk_idx/blk_off (C_b,) int32 — pool write targets for each
    chunk position (padding → scratch block); table (max_blocks,)
    int32 — the sequence's full block table, through which attention
    reads everything written so far *including this chunk's own
    scatter*; last_idx — index of the final real token in this chunk.

    Causality is positional: query at absolute position p attends to
    pool slots holding absolute positions <= p. Earlier chunks live in
    the pool already (written by previous chunk calls); later slots are
    masked off by the position comparison, so chunked == unchunked up
    to float reassociation.

    Returns (last-token logits (vocab,) f32, k_pool, v_pool).
    """
    c = ids.shape[1]
    n_layers, _, block_size, _, _ = k_pool.shape
    max_blocks = table.shape[0]
    kv_len = max_blocks * block_size
    pos = pos0 + jnp.arange(c)                          # (C,) absolute
    x = params["embed"][ids].astype(dtype)              # (1, C, D)
    # pool slot s of block j holds absolute position j*block_size + s
    # for this sequence (allocator hands blocks out in order); query p
    # attends slots with kvpos <= p. Padding rows (pos past the real
    # chunk) still compute but their writes hit scratch and their
    # logits are never read.
    kvpos = jnp.arange(kv_len)
    mask = kvpos[None, None, None, :] <= pos[None, None, :, None]
    for li, blk in enumerate(params["blocks"]):
        h = rmsnorm(x, blk["ln1"].astype(dtype))
        d = x.shape[-1]
        hd = d // n_heads
        qkv = _proj(blk, "wqkv", h, dtype)
        kv_dim = (qkv.shape[-1] - d) // 2
        n_kv = kv_dim // hd
        q = qkv[..., :d].reshape(1, c, n_heads, hd)
        k = qkv[..., d:d + kv_dim].reshape(1, c, n_kv, hd)
        v = qkv[..., d + kv_dim:].reshape(1, c, n_kv, hd)
        q = _rope_rows(q.transpose(1, 0, 2, 3), pos).transpose(1, 0, 2, 3)
        k = _rope_rows(k.transpose(1, 0, 2, 3), pos).transpose(1, 0, 2, 3)
        k_pool = k_pool.at[li, blk_idx, blk_off].set(
            k[0].astype(k_pool.dtype))
        v_pool = v_pool.at[li, blk_idx, blk_off].set(
            v[0].astype(v_pool.dtype))
        kc = k_pool[li][table].reshape(1, kv_len, n_kv, hd)
        vc = v_pool[li][table].reshape(1, kv_len, n_kv, hd)
        kcx = _expand_kv(kc, n_heads).astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       kcx) * hd ** -0.5               # (1,H,C,kv_len)
        s = jnp.where(mask, s, -1e30)
        pattn = jax.nn.softmax(s, axis=-1)
        vcx = _expand_kv(vc, n_heads).astype(jnp.float32)
        attn = jnp.einsum("bhqk,bkhd->bqhd", pattn, vcx).astype(dtype)
        x = x + _proj(blk, "wo", attn.reshape(1, c, -1), dtype)
        h = rmsnorm(x, blk["ln2"].astype(dtype))
        x = x + _mlp_paged(blk, h, dtype)
    x = rmsnorm(x, params["ln_f"].astype(dtype))
    logits = _proj(params, "head", x[0, last_idx][None, :],
                   dtype).astype(jnp.float32)
    return logits[0], k_pool, v_pool
