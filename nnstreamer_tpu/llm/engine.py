"""Continuous-batching generation engine (host half).

One `step()` is the serving quantum: admit queued requests while blocks
and batch slots allow (each admission runs a bucketed prefill and
yields its first token), then run ONE decode step for every in-flight
sequence — freshly admitted requests merge into the same decode batch
that step, and finished sequences retire immediately, returning their
blocks to the pool. Contrast `static_batching=True`, the A/B baseline:
a batch admits only while the engine is empty and runs to full
completion, so one long request holds the whole batch hostage (exactly
the head-of-line blocking continuous batching removes — bench family
`llm_serve` measures the gap).

Long prompts can optionally *chunk-prefill* (``prefill_chunk=N``): a
prompt longer than N tokens admits into a ``prefilling`` state and
advances one N-token chunk per step — through the executor's one
``llmp_chunk`` bucket — while the decode batch keeps stepping, so an
s8192 prompt stops being head-of-line for every live sequence's
inter-token latency. The final chunk's logits yield the first token.

Sampling is host-side on the step's (vocab,) f32 logits: temperature 0
is `np.argmax`, which shares first-occurrence tie-breaking with the
`jnp.argmax` inside `transformer.generate`'s fused decode — a parity
requirement, not a convenience. Temperature > 0 uses a per-request
seeded Generator so a request's tokens don't depend on its batchmates.

Host syncs are batched: every prefill/chunk launched in a step returns
*device* logits, and one `runtime.sync.device_sync` over the whole
pending set resolves them together — one forced sync for all of a
step's admissions plus one for the decode batch, instead of one per
admitted request.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, List, Optional

import numpy as np

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.runtime.sync import device_sync
from nnstreamer_tpu.runtime.tracing import NULL_TRACER, percentile

log = get_logger("llm.engine")


@dataclass
class LLMRequest:
    """One generation request plus its runtime serving state."""

    req_id: str
    prompt: np.ndarray                  # (plen,) int32, plen >= 1
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0
    eos_id: Optional[int] = None
    pts: Optional[int] = None           # carried through to emissions
    # -- runtime state (engine-owned) --
    tokens: List[int] = field(default_factory=list)
    state: str = "queued"          # queued | prefilling | active | done
    finish_reason: Optional[str] = None  # eos | length
    block_table: List[int] = field(default_factory=list)
    pos: int = 0                        # next cache write position
    t_submit: float = 0.0
    t_first: Optional[float] = None
    t_last: float = 0.0
    itl_ms: List[float] = field(default_factory=list)
    _rng: Any = None

    @property
    def first_token_ms(self) -> Optional[float]:
        if self.t_first is None:
            return None
        return (self.t_first - self.t_submit) * 1e3

    def summary(self) -> dict:
        return {
            "req_id": self.req_id,
            "state": self.state,
            "finish_reason": self.finish_reason,
            "prompt_len": int(self.prompt.shape[0]),
            "n_tokens": len(self.tokens),
            "first_token_ms": self.first_token_ms,
            "itl_p50_ms": percentile(sorted(self.itl_ms), 50)
            if self.itl_ms else None,
        }


@dataclass
class TokenEvent:
    """`step()` output: new tokens for one request (done ⇒ final)."""

    request: LLMRequest
    tokens: List[int]
    done: bool


class LLMEngine:
    """Admission + continuous-batching loop over a PagedLLMExecutor."""

    def __init__(self, model="store://transformer", *, n_heads: int = 4,
                 dtype=None, block_size: int = 16, num_blocks: int = 64,
                 max_batch: int = 8, max_len: int = 128,
                 static_batching: bool = False, prefill_chunk: int = 0,
                 paged_kernel: Optional[str] = None, shards: int = 0,
                 shard_chips=None, ring_prefill_min: int = 0,
                 decode_window: int = 0,
                 tracer=NULL_TRACER, name: str = "llm"):
        from nnstreamer_tpu.backends.llm_exec import PagedLLMExecutor

        self.name = name
        self.tracer = tracer
        self.max_batch = int(max_batch)
        self.static = bool(static_batching)
        self.prefill_chunk = int(prefill_chunk)
        # compiled decode window (executor.decode_multi): when the
        # batch is in steady state — nothing queued or prefilling, all
        # live rows greedy — run up to this many decode steps as ONE
        # jitted lax.scan dispatch. 0 disables. Tokens arrive in
        # window-sized bursts (ITL percentiles reflect that); greedy
        # on-device argmax matches the host sampler bit for bit.
        self.decode_window = int(decode_window)
        if self.decode_window < 0:
            raise BackendError(
                f"decode_window must be >= 0, got {self.decode_window}")
        if self.prefill_chunk < 0:
            raise BackendError(
                f"prefill_chunk must be >= 0, got {self.prefill_chunk}")
        if int(shards) > 0 and self.prefill_chunk > 0:
            raise BackendError(
                f"llm {name}: prefill_chunk and shards are exclusive — "
                f"sharded long prompts go through the sequence-parallel "
                f"ring prefill (ring_prefill_min), not chunking")
        self.executor = PagedLLMExecutor(
            model, n_heads=n_heads, dtype=dtype, block_size=block_size,
            num_blocks=num_blocks, max_len=max_len,
            paged_kernel=paged_kernel, shards=shards,
            shard_chips=shard_chips, ring_prefill_min=ring_prefill_min,
            tracer=tracer, name=name)
        self.cache = self.executor.cache
        self.queue: deque = deque()
        self.active: List[LLMRequest] = []
        self.prefilling: List[LLMRequest] = []
        self._seq = 0
        self.submitted = 0
        self.finished = 0
        self.tokens_out = 0
        self.steps = 0
        self.admission_blocked = 0
        self.decode_windows = 0
        self.window_tokens = 0
        self._first_ms: List[float] = []
        self._itl_ms: List[float] = []

    # -- submission --------------------------------------------------------
    def submit(self, prompt, *, req_id: Optional[str] = None,
               max_new_tokens: int = 32, temperature: float = 0.0,
               top_k: int = 0, seed: int = 0,
               eos_id: Optional[int] = None,
               pts: Optional[int] = None) -> LLMRequest:
        """Queue a request. Rejects (raises) only what can NEVER be
        served — a prompt+budget exceeding per-sequence table capacity;
        a merely-full pool queues instead."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] == 0:
            raise BackendError("llm request needs a non-empty prompt")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise BackendError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        ex = self.executor
        total = int(prompt.shape[0]) + max_new_tokens
        seq_cap = ex.max_blocks * self.cache.block_size
        if total > seq_cap:
            raise BackendError(
                f"request needs {total} token slots but max_len={ex.max_len} "
                f"caps a sequence at {seq_cap}; raise max_len/num_blocks "
                f"or shorten the request")
        if self.cache.blocks_for(total) > self.cache.allocator.total:
            raise BackendError(
                f"request needs {self.cache.blocks_for(total)} blocks but "
                f"the pool only has {self.cache.allocator.total}")
        if req_id is None:
            self._seq += 1
            req_id = f"{self.name}-{self._seq}"
        req = LLMRequest(
            req_id=req_id, prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=float(temperature), top_k=int(top_k),
            seed=int(seed), eos_id=None if eos_id is None else int(eos_id),
            pts=pts)
        req.t_submit = time.perf_counter()
        self.queue.append(req)
        self.submitted += 1
        return req

    def prewarm(self, max_prompt: Optional[int] = None) -> int:
        """Compile all decode buckets (up to max_batch) and prefill
        buckets (up to `max_prompt`, default max_len) ahead of traffic."""
        return self.executor.prewarm_buckets(
            max_batch=self.max_batch,
            max_prompt=max_prompt or self.executor.max_len,
            chunk=self.prefill_chunk)

    # -- the serving quantum ----------------------------------------------
    @property
    def has_work(self) -> bool:
        return bool(self.queue or self.active or self.prefilling)

    def step(self) -> List[TokenEvent]:
        """Admit, prefill (whole or one chunk of a long prompt), one
        decode step, retire. Returns this step's token events (freshly
        admitted requests contribute their prefill token AND their first
        decode token; chunk-prefilling requests emit nothing until their
        final chunk lands)."""
        self.executor.maybe_adopt()
        events: List[TokenEvent] = []
        #: (req, device logits) for every prefill completed this step
        pending: List[tuple] = []
        self._admit(pending)
        self._prefill_chunks(pending)
        self._finish_pending(pending, events)
        self._decode(events)
        self.steps += 1
        return events

    def drain(self, max_steps: int = 100000) -> List[TokenEvent]:
        """Run steps until idle (EOS / element flush path)."""
        events: List[TokenEvent] = []
        steps = 0
        while self.has_work:
            events.extend(self.step())
            steps += 1
            if steps >= max_steps:
                raise BackendError(
                    f"llm drain did not converge in {max_steps} steps "
                    f"({len(self.active)} active, {len(self.queue)} queued)")
        return events

    def _admit(self, pending: List[tuple]) -> None:
        # static A/B mode: the batch forms only from empty, no top-up
        if self.static and (self.active or self.prefilling):
            return
        alloc = self.cache.allocator
        # pending holds this step's already-admitted prefills (they only
        # join active in _finish_pending) — count them against the cap
        while self.queue and (len(self.active) + len(self.prefilling)
                              + len(pending) < self.max_batch):
            req = self.queue[0]
            plen = int(req.prompt.shape[0])
            need = self.cache.blocks_for(plen + req.max_new_tokens)
            blocks = alloc.alloc(need, owner=req.req_id)
            if blocks is None:
                # head-of-line waits for retirements; admitting a
                # smaller later request instead would starve it
                self.admission_blocked += 1
                return
            self.queue.popleft()
            req.block_table = blocks
            if self.prefill_chunk > 0 and plen > self.prefill_chunk:
                # long prompt: prefill one chunk per step alongside the
                # decode batch instead of head-of-line blocking it
                req.state = "prefilling"
                req.pos = 0
                self.prefilling.append(req)
                continue
            req.state = "active"
            logits = self.executor.prefill(req.prompt, blocks,
                                           sync=False)
            req.pos = plen
            pending.append((req, logits))

    def _prefill_chunks(self, pending: List[tuple]) -> None:
        """Advance the oldest chunk-prefilling prompt by ONE chunk (the
        per-step prefill compute budget that keeps decode stepping);
        when its final chunk lands, its logits join this step's pending
        batch and the request enters the decode batch."""
        if not self.prefilling:
            return
        req = self.prefilling[0]
        plen = int(req.prompt.shape[0])
        chunk = req.prompt[req.pos:req.pos + self.prefill_chunk]
        from nnstreamer_tpu.backends.xla import _next_pow2

        logits = self.executor.prefill_chunk(
            chunk, req.pos, req.block_table,
            bucket=_next_pow2(self.prefill_chunk, 8), sync=False)
        req.pos += int(chunk.shape[0])
        if req.pos >= plen:
            self.prefilling.pop(0)
            req.state = "active"
            pending.append((req, logits))

    def _finish_pending(self, pending: List[tuple],
                        events: List[TokenEvent]) -> None:
        """ONE whole-batch device sync over every prefill the step
        launched, then sample first tokens host-side. Freshly finished
        requests join `active` here and merge into the same step's
        decode batch."""
        if not pending:
            return
        arrays = device_sync(
            [lg for _, lg in pending], tracer=self.tracer,
            name=f"{self.name}:prefill_batch")
        for (req, _), lg in zip(pending, arrays):
            tok = self._sample(req, np.asarray(lg))
            self._record_token(req, tok)
            self.active.append(req)
            done = self._maybe_finish(req, tok)
            events.append(TokenEvent(req, [tok], done))

    def _window_len(self, live: List[LLMRequest]) -> int:
        """How many decode steps may run as one compiled window right
        now. 1 means per-step mode; >= 2 enters decode_multi. The
        guards are the LLM analog of the scheduler's bail matrix:
        pending admissions / prefills need per-step batch re-forming
        (cause "shape"), a sampled row needs host RNG per token, and
        the window never outruns any row's remaining budget (rows that
        hit EOS early have their trailing tokens discarded host-side).
        Rounded down to a power of two so the jit cache stays
        O(log window) per batch bucket."""
        if self.decode_window < 2 or self.queue or self.prefilling:
            return 1
        if self.executor.shards:
            return 1       # sharded decode stays on the per-step path
        if any(r.temperature > 0.0 for r in live):
            return 1
        k = min(self.decode_window,
                min(r.max_new_tokens - len(r.tokens) for r in live))
        if k < 2:
            return 1
        return 1 << (k.bit_length() - 1)

    def _decode(self, events: List[TokenEvent]) -> None:
        live = [r for r in self.active if r.state == "active"]
        if not live:
            return
        k = self._window_len(live)
        if k >= 2:
            toks = self.executor.decode_multi(
                [r.tokens[-1] for r in live],
                [r.block_table for r in live],
                [r.pos for r in live], k)
            self.decode_windows += 1
            for j in range(k):
                for i, req in enumerate(live):
                    if req.state != "active":
                        continue   # retired mid-window: discard tail
                    req.pos += 1
                    tok = int(toks[i, j])
                    self._record_token(req, tok)
                    done = self._maybe_finish(req, tok)
                    events.append(TokenEvent(req, [tok], done))
                    self.window_tokens += 1
            return
        logits = self.executor.decode(
            [r.tokens[-1] for r in live],
            [r.block_table for r in live],
            [r.pos for r in live])
        for i, req in enumerate(live):
            req.pos += 1
            tok = self._sample(req, logits[i])
            self._record_token(req, tok)
            done = self._maybe_finish(req, tok)
            events.append(TokenEvent(req, [tok], done))

    # -- helpers -----------------------------------------------------------
    def _sample(self, req: LLMRequest, logits: np.ndarray) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(logits))
        lg = logits.astype(np.float64) / req.temperature
        if req.top_k > 0 and req.top_k < lg.shape[0]:
            kth = np.partition(lg, -req.top_k)[-req.top_k]
            lg = np.where(lg < kth, -np.inf, lg)
        lg -= lg.max()
        p = np.exp(lg)
        p /= p.sum()
        if req._rng is None:
            req._rng = np.random.default_rng(req.seed)
        return int(req._rng.choice(lg.shape[0], p=p))

    def _record_token(self, req: LLMRequest, tok: int) -> None:
        now = time.perf_counter()
        if req.t_first is None:
            req.t_first = now
            self._first_ms.append(req.first_token_ms)
            if self.tracer.active:
                self.tracer.instant(
                    self.name, "first_token", t=now, req=req.req_id,
                    ms=round(req.first_token_ms, 3))
        else:
            itl = (now - req.t_last) * 1e3
            req.itl_ms.append(itl)
            self._itl_ms.append(itl)
        req.t_last = now
        req.tokens.append(tok)
        self.tokens_out += 1

    def _maybe_finish(self, req: LLMRequest, tok: int) -> bool:
        if req.eos_id is not None and tok == req.eos_id:
            req.finish_reason = "eos"
        elif len(req.tokens) >= req.max_new_tokens:
            req.finish_reason = "length"
        else:
            return False
        req.state = "done"
        self.cache.allocator.free_blocks(req.block_table)
        req.block_table = []
        if req in self.active:
            self.active.remove(req)
        self.finished += 1
        if self.tracer.active:
            self.tracer.record_llm_request(
                self.name, req.req_id, time.perf_counter(),
                **{k: v for k, v in req.summary().items()
                   if k != "req_id"})
        return True

    def stats(self) -> dict:
        first = sorted(self._first_ms)
        itl = sorted(self._itl_ms)
        out = {
            "submitted": self.submitted,
            "finished": self.finished,
            "queued": len(self.queue),
            "active": len(self.active),
            "prefilling": len(self.prefilling),
            "tokens_out": self.tokens_out,
            "steps": self.steps,
            "admission_blocked": self.admission_blocked,
            "scheduling": "static" if self.static else "continuous",
            "prefill_chunk": self.prefill_chunk,
            "decode_window": self.decode_window,
            "decode_windows": self.decode_windows,
            "window_tokens": self.window_tokens,
            "cache": self.cache.stats(),
            "executor": self.executor.stats(),
        }
        if first:
            out["first_token_ms"] = {
                "p50": round(percentile(first, 50), 3),
                "p95": round(percentile(first, 95), 3),
                "p99": round(percentile(first, 99), 3)}
        if itl:
            out["inter_token_ms"] = {
                "p50": round(percentile(itl, 50), 3),
                "p95": round(percentile(itl, 95), 3),
                "p99": round(percentile(itl, 99), 3)}
        return out
