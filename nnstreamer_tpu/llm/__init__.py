"""Continuous-batching LLM serving (ROADMAP open item 3).

The prefill/decode split and the streaming transformer
(models/transformer.py) become a first-class serving workload:

- `paged_cache`  — fixed-size-block KV pool + free-list allocator, so
  slot count (not max_len × batch) bounds HBM.
- `paged_model`  — prefill/decode math over the paged pool, formulated
  for token-for-token parity with `transformer.generate`.
- `engine`       — the continuous-batching scheduler loop: admit,
  prefill (pow2-bucketed), merge into the in-flight decode batch,
  retire; plus the static-batching A/B mode the bench compares against.

`elements/llm.py` exposes the engine as the `tensor_llm` pipeline
element; `backends/llm_exec.py` owns the bucketed, version-namespaced
jits underneath it.
"""

from nnstreamer_tpu.llm.engine import LLMEngine, LLMRequest  # noqa: F401
from nnstreamer_tpu.llm.paged_cache import (  # noqa: F401
    BlockAllocator, PagedKVCache)

__all__ = ["BlockAllocator", "LLMEngine", "LLMRequest", "PagedKVCache"]
