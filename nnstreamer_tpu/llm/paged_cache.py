"""Paged KV cache: fixed-size blocks + free-list allocator.

The streaming transformer's `init_cache` reserves (B, max_len) per
sequence up front — fine for one pinned pipeline, hopeless for serving:
a 128-slot server at max_len=2048 would reserve 256k token slots while
typical occupancy is a fraction of that. Paging (vLLM's PagedAttention
idea, PAPERS.md) decouples the two: the pool holds `num_blocks` blocks
of `block_size` token slots each, and every sequence owns an ordered
per-sequence *block table* mapping its positions onto pool blocks.
Memory is bounded by the pool, admission is bounded by free blocks, and
fragmentation is impossible by construction (any free block serves any
sequence — the table, not adjacency, provides ordering).

Block 0 is reserved as the scratch block: padding rows of a bucketed
decode batch and the padded tail of a bucketed prefill write there, so
pow2 padding never corrupts a live sequence's cache.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from nnstreamer_tpu.core.log import get_logger

log = get_logger("llm.cache")

#: pool block index reserved for padding writes (never allocated)
SCRATCH_BLOCK = 0


class BlockAllocator:
    """Free-list allocator over the pool's block indices.

    All-or-nothing `alloc(n)`: a request either gets its whole block
    set or stays queued (None) — partial grants would deadlock two
    half-admitted requests against each other. Single-threaded by
    design: the engine owns it from one scheduler thread.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"paged pool needs >= 2 blocks (1 scratch + 1 usable), "
                f"got {num_blocks}")
        self.num_blocks = num_blocks
        # LIFO free list: recently-freed (cache-warm) blocks reused first
        self._free: List[int] = list(range(num_blocks - 1, SCRATCH_BLOCK, -1))
        self._owner: Dict[int, object] = {}
        self.high_water = 0
        self.alloc_calls = 0
        self.failed_allocs = 0

    @property
    def total(self) -> int:
        """Allocatable blocks (the scratch block is never granted)."""
        return self.num_blocks - 1

    @property
    def free(self) -> int:
        return len(self._free)

    @property
    def used(self) -> int:
        return self.total - len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int, owner: object = None) -> Optional[List[int]]:
        """Grant `n` blocks or None (caller queues — never crashes)."""
        self.alloc_calls += 1
        if n < 0:
            raise ValueError(f"alloc({n}): negative block count")
        if n > len(self._free):
            self.failed_allocs += 1
            return None
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._owner[b] = owner
        if self.used > self.high_water:
            self.high_water = self.used
        return blocks

    def free_blocks(self, blocks: List[int]) -> None:
        for b in blocks:
            if b not in self._owner:
                raise ValueError(
                    f"free of unallocated block {b} (double free, or a "
                    f"block the allocator never granted)")
            del self._owner[b]
            self._free.append(b)

    def stats(self) -> dict:
        return {
            "blocks_total": self.total,
            "blocks_free": self.free,
            "blocks_used": self.used,
            "blocks_high_water": self.high_water,
            "utilization": round(self.used / self.total, 4),
            "alloc_calls": self.alloc_calls,
            "failed_allocs": self.failed_allocs,
        }


class PagedKVCache:
    """The device-resident block pool + its allocator.

    k/v pools: (n_layers, num_blocks, block_size, n_kv, head_dim).
    The pools live here as plain jax arrays and are threaded through the
    executor's donated jit calls (write-in-place on device); this class
    only owns layout and accounting, never math.
    """

    def __init__(self, *, num_blocks: int, block_size: int, n_layers: int,
                 n_kv: int, head_dim: int, dtype=None, placer=None):
        import jax.numpy as jnp

        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.n_layers = int(n_layers)
        self.n_kv = int(n_kv)
        self.head_dim = int(head_dim)
        self.dtype = dtype or jnp.float32
        shape = (self.n_layers, self.num_blocks, self.block_size,
                 self.n_kv, self.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        if placer is not None:
            # sharded serving hands us a device-placement closure (pool
            # sharded along the kv-head axis next to the projections —
            # serving/sharding.kv_pool_placer); allocator/table logic is
            # untouched, only where the bytes live changes
            self.k = placer(self.k)
            self.v = placer(self.v)
        self.allocator = BlockAllocator(self.num_blocks)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold `n_tokens` token slots."""
        return max(1, -(-int(n_tokens) // self.block_size))

    @property
    def tokens_capacity(self) -> int:
        return self.allocator.total * self.block_size

    def stats(self) -> dict:
        out = self.allocator.stats()
        out["block_size"] = self.block_size
        out["tokens_capacity"] = self.tokens_capacity
        return out
