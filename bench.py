"""Benchmark — BASELINE.md config 1 on the real chip.

Runs the flagship streaming pipeline (source → converter-equivalent
normalize → MobileNetV2 → label decode, all fused into one XLA
computation by the graph optimizer) and reports steady-state
frames/sec/chip. Baseline: the driver target of 30 FPS/chip
(BASELINE.json — the reference publishes no numbers of its own;
SURVEY.md §6).

Prints ONE JSON line:
  {"metric": "mobilenet_v2_224_fps_per_chip", "value": N,
   "unit": "frames/s", "vs_baseline": N/30}
"""

from __future__ import annotations

import json
import sys
import time


def bench_pipeline(n_frames: int = 256, warmup: int = 16,
                   batch: int = 1) -> float:
    """Steady-state FPS of the stock pipeline at the given batch size
    (batch>1 = the converter frames-per-tensor streaming-batch config;
    FPS counts individual frames)."""
    import numpy as np

    import nnstreamer_tpu as nns
    from nnstreamer_tpu.elements import (
        AppSrc, FakeSink, TensorFilter, TensorTransform)
    from nnstreamer_tpu.tensor.buffer import TensorBuffer
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    spec = TensorsSpec.of(TensorInfo((batch, 224, 224, 3), DType.UINT8))
    src = AppSrc(spec=spec, name="src")
    # the reference's stock pipeline shape: typecast+normalize, then model
    # (transform fuses into the filter's XLA computation at negotiation)
    trans = TensorTransform(
        name="t", mode="arithmetic",
        option="typecast:float32,add:-127.5,div:127.5")
    filt = TensorFilter(name="f", framework="xla",
                        model=f"zoo://mobilenet_v2?batch={batch}")
    sink = FakeSink(name="sink", sync_device=True)

    pipe = nns.Pipeline("bench")
    for e in (src, trans, filt, sink):
        pipe.add(e)
    pipe.link(src, trans)
    pipe.link(trans, filt)
    pipe.link(filt, sink)

    runner = nns.PipelineRunner(pipe, queue_capacity=4).start()
    frame = np.random.default_rng(0).integers(
        0, 256, (batch, 224, 224, 3), np.uint8)

    def wait_count(target: int, poll: float) -> None:
        while sink.count < target:
            err = runner._error
            if err is not None:  # fail fast, don't spin forever
                runner.stop()
                raise RuntimeError(f"pipeline failed: {err}") from err
            time.sleep(poll)

    # warmup (compile)
    for i in range(warmup):
        src.push(TensorBuffer.of(frame, pts=i))
    wait_count(warmup, 0.005)

    t0 = time.perf_counter()
    for i in range(n_frames):
        src.push(TensorBuffer.of(frame, pts=warmup + i))
    wait_count(warmup + n_frames, 0.002)
    dt = time.perf_counter() - t0
    src.end()
    runner.wait(30)
    return n_frames * batch / dt


def main() -> int:
    try:
        fps = bench_pipeline()
        fps_b8 = bench_pipeline(n_frames=64, batch=8)
        baseline = 30.0  # BASELINE.json driver target, FPS/chip
        print(json.dumps({
            "metric": "mobilenet_v2_224_fps_per_chip",
            "value": round(fps, 2),
            "unit": "frames/s",
            "vs_baseline": round(fps / baseline, 3),
            "batched8_fps": round(fps_b8, 2),
        }))
        return 0
    except Exception as e:  # one JSON line even on failure
        print(json.dumps({
            "metric": "mobilenet_v2_224_fps_per_chip",
            "value": 0.0,
            "unit": "frames/s",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }))
        return 1


if __name__ == "__main__":
    sys.exit(main())
