"""Benchmark — all five BASELINE.md configs on the real chip.

Configs (reference pipeline shapes, BASELINE.md table):
  1. label     — MobileNetV2 224² image labeling. Real quantized weights
                 (reference's own .tflite via modelio) when available;
                 ingest normalize runs as a **compiled Pallas kernel** on
                 TPU (Orc-SIMD analog, gsttensor_transform.c:463-493).
                 `label_device` = same pipeline with device=true decode
                 fused into the filter program (D2H-free headline).
  2. ssd       — SSD-MobileNet 300² + bounding_boxes decoder (NMS);
                 `ssd_device` decodes on-chip (fused top-K + greedy NMS).
  3. posenet   — PoseNet 257² + pose_estimation decoder; `posenet_device`
                 decodes heatmaps on-chip.
  4. composite — 2-tensor demux → 2× tensor_filter (shared device model)
                 → mux, aggregate FPS.
  5. offload   — loopback tensor_query client/server; open-loop FPS with
                 a pipelined client (max_in_flight=8), closed-loop
                 p50/p99 with the reference per-frame-sync client.

Per config: steady-state FPS/chip (open-loop, pipelined) and p50/p99
end-to-end latency (closed-loop, per-frame push→sink). Config 1 adds a
batch sweep {1,8,32,64} with achieved TFLOP/s and MFU (XLA-measured
FLOPs vs the chip's bf16 peak).

Environment note: this driver reaches the chip through a network tunnel
whose D2H reads are expensive (~10ms RTT, ~20MB/s) AND degrade
subsequent dispatch in-process (measured: label_device drops 2846 →
~12 FPS once any readback has happened; slow recovery that in round 3
made the in-process flash numbers land ~3x above quiet-chip). Local TPU
hosts do the same D2H in microseconds. The bench therefore:
(a) runs EVERYTHING that measures — the differencing-method families
    (pallas/flash, transformer_prefill, mxu_peak, batch_sweep, int8),
    each offload batching-delay sweep point, AND each pipeline config —
    in its OWN SUBPROCESS with a fresh TPU client: every number is a
    quiet-chip number by construction, and no measurement's readbacks
    poison another's dispatch (`python bench.py --family X`);
(b) probes the tunnel (`env`) in-process last, so numbers can be
    interpreted.

Kill-resilience contract (round-5): the bench must ship data no matter
when the driver kills it. After EVERY family completes, the full
cumulative result JSON is printed as one flushed line — the driver
keeps the last parseable line, so a kill at any point loses at most the
in-flight family. SIGTERM additionally triggers a final snapshot before
exit. Family subprocesses are bounded by BENCH_FAMILY_TIMEOUT_S
(default 300s) and the whole run by BENCH_BUDGET_S (default 1500s);
long families (batch_sweep, pallas) stream per-step partial results so
even a timed-out family contributes what it measured. The LAST printed
line is the most complete result; intermediate lines carry
"partial": true.
"""

from __future__ import annotations

import json
import os
import sys
import time

NORMALIZE_OPT = "typecast:float32,add:-127.5,div:127.5"
MOBILENET_TFLITE = ("/root/reference/tests/test_models/models/"
                    "mobilenet_v2_1.0_224_quant.tflite")
LABELS = "/root/reference/tests/test_models/labels/labels.txt"
BASELINE_FPS = 30.0          # BASELINE.json driver target, FPS/chip
PEAK_BF16_TFLOPS = 197.0     # TPU v5e public peak, bf16
PEAK_HBM_GBPS = 819.0        # TPU v5e public HBM bandwidth


def _percentile(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1, int(round(p / 100 * (len(sorted_vals) - 1))))
    return sorted_vals[k]


class _Bench:
    """Open-loop FPS + closed-loop latency on one built pipeline.

    `build_lat`: optional second builder for the closed-loop phase (for
    configs whose throughput shape pipelines frames — e.g. a compact
    decoder with max_in_flight>1 — and whose latency must be measured on
    the strict per-frame variant, like the offload config's two
    clients)."""

    def __init__(self, build, frames_per_push=1, build_lat=None, lag=0,
                 runner_kwargs=None):
        import nnstreamer_tpu as nns

        self.pipe, self.src, self.sink, self.frame = build()
        self.frames_per_push = frames_per_push
        self.build_lat = build_lat
        self.lag = lag          # emissions a pipelined stage may withhold
        self.runner = nns.PipelineRunner(self.pipe, queue_capacity=4,
                                         **(runner_kwargs or {})).start()
        self._pts = 0

    def _push(self):
        from nnstreamer_tpu.tensor.buffer import TensorBuffer

        f = self.frame
        self.src.push(TensorBuffer.of(
            *(f if isinstance(f, tuple) else (f,)), pts=self._pts))
        self._pts += 1

    def _wait(self, target, poll=0.002, timeout=300.0):
        t0 = time.perf_counter()
        while self.sink.count < target:
            err = self.runner._error
            if err is not None:
                self.runner.stop()
                raise RuntimeError(f"pipeline failed: {err}") from err
            if time.perf_counter() - t0 > timeout:
                raise RuntimeError(
                    f"bench stalled: sink at {self.sink.count}/{target}")
            time.sleep(poll)

    def _closed_loop(self, n_lat, base=0):
        """Per-frame push→emission latencies; base = emissions already
        counted on this pipeline (0 on a fresh one: the first frame
        warms/compiles and is excluded)."""
        lats = []
        if base == 0:
            self._push()
            self._wait(1)
            base = 1
        for i in range(n_lat):
            t = time.perf_counter()
            self._push()
            self._wait(base + i + 1, poll=0.0005)
            lats.append((time.perf_counter() - t) * 1e3)
        return lats

    def run(self, n_frames=None, warmup=12, n_lat=None):
        if n_frames is None:
            n_frames = 128 if _on_tpu() else 8
        if n_lat is None:
            n_lat = 60 if _on_tpu() else 4
        try:
            return self._run(n_frames, warmup, n_lat)
        except BaseException:
            # tear the pipeline down so a failed config's threads don't
            # keep contending for the chip under later configs
            try:
                self.runner.stop()
            except Exception:
                pass
            raise

    def _run(self, n_frames, warmup, n_lat):
        # a lagging stage withholds its last `lag` emissions until EOS:
        # the warmup must push past the lag or the warmup wait stalls
        warmup = max(warmup, self.lag + 4)
        for _ in range(warmup):
            self._push()
        self._wait(max(warmup - self.lag, 1))
        # open-loop throughput: keep the device fed; a lagging stage
        # withholds the last `lag` emissions until EOS, so the timed
        # segment counts n_frames emissions starting from the lag point
        t0 = time.perf_counter()
        for _ in range(n_frames):
            self._push()
        self._wait(max(warmup - self.lag, 1) + n_frames)
        dt = time.perf_counter() - t0
        fps = n_frames * self.frames_per_push / dt
        # closed-loop latency: one frame in flight (on a fresh strict-
        # variant pipeline when the throughput pipeline lags emissions)
        if self.build_lat is not None:
            self.src.end()
            self.runner.wait(60)
            lat_bench = _Bench(self.build_lat)
            try:
                lats = lat_bench._closed_loop(n_lat)
                lat_bench.src.end()
                lat_bench.runner.wait(60)
            finally:
                lat_bench.runner.stop()
        else:
            lats = self._closed_loop(n_lat, base=warmup + n_frames)
            self.src.end()
            self.runner.wait(60)
        lats.sort()
        return {
            "fps": round(fps, 2),
            "p50_ms": round(_percentile(lats, 50), 3),
            "p99_ms": round(_percentile(lats, 99), 3),
            # composed filter→…→filter device segments in this config
            # (0 = no adjacent-filter runs; see [runtime] device_segments)
            "device_segments": len(self.runner.device_segments()),
            # per-stage trajectory for future perf PRs: the untraced
            # runner's always-on counters (tracing stays off so fps/lat
            # numbers remain comparable across rounds)
            "stages": _stage_summary(self.runner),
        }


def _stage_summary(runner) -> dict:
    """Condense runner.stats() into the per-element numbers worth
    keeping in the BENCH artifact: proctime, queue high-water, drops,
    and backend compile-cache behavior."""
    out = {}
    for name, d in runner.stats().items():
        row = {
            "buffers": d.get("buffers", 0),
            "proctime_total_ms": round(d.get("proctime_total_s", 0.0) * 1e3, 3),
            "proctime_avg_us": round(d.get("proctime_avg_us", 0.0), 1),
            "queue_peak": d.get("queue_peak", 0),
        }
        for k in ("backend_compile_count", "backend_cache_hits",
                  "backend_cache_misses", "timer_fires", "dropped"):
            if d.get(k):
                row[k] = d[k]
        out[name] = row
    return out


def _on_tpu() -> bool:
    import jax

    return jax.default_backend() == "tpu"


#: decoder D2H pipelining depth for the host-decode throughput configs;
#: the bench's emission-lag accounting derives from it (16 absorbs the
#: tunnel's D2H jitter: measured 62 FPS vs 33 at depth 8 on ssd)
SSD_MAX_IN_FLIGHT = 16


# -- config builders ---------------------------------------------------------

def _probe_env():
    """Tunnel D2H characteristics, so FPS numbers are interpretable.

    `d2h_1k_ms` is the STEADY-STATE number: the first read of a fresh
    device array pays one-time transfer-path setup (runs measured it at
    10x+ the warm path, and averaging it in is what drifted the metric
    17ms → 192ms between rounds — the cold share of a 5-read mean
    depends on tunnel state, not on the code under test). The cold
    first read still ships, separately, as `d2h_1k_cold_ms`; the median
    of the warm reads is robust to a single straggler."""
    import jax
    import numpy as np

    x = jax.device_put(np.ones((1, 1001), np.uint8))
    jax.block_until_ready(x)
    t0 = time.perf_counter()
    _ = np.asarray(x)
    cold_ms = (time.perf_counter() - t0) * 1e3
    for _ in range(2):               # settle the transfer path
        _ = np.asarray(x)
    warm = []
    for _ in range(9):
        t0 = time.perf_counter()
        _ = np.asarray(x)
        warm.append((time.perf_counter() - t0) * 1e3)
    warm.sort()
    env = {"d2h_1k_ms": round(warm[len(warm) // 2], 2),
           "d2h_1k_cold_ms": round(cold_ms, 2),
           "backend": jax.default_backend()}
    # toolchain + device identity: MFU / roofline numbers are only
    # comparable between artifacts produced on the same stack
    import jaxlib

    devs = jax.devices()
    env.update({
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib.__version__,
        "platform": devs[0].platform if devs else "none",
        "device_kind": devs[0].device_kind if devs else "none",
        "device_count": len(devs),
    })
    # a live SLO autotuner (serving/autotune.py) mutating knobs during
    # a run would taint comparisons like a degraded tunnel does —
    # record whether one was active in this process
    import threading as _threading
    env["autotune_active"] = any(
        t.name == "slo-autotuner" for t in _threading.enumerate())
    env.update(_probe_lint())
    return env


def _probe_lint() -> dict:
    """`lint_clean` in the env snapshot: was the tree nnlint-clean when
    this artifact was produced (docs/static_analysis.md)?  A dirty tree
    taints comparisons the same way a degraded tunnel does — a finding
    like a stray direct sync IS a host-path change.  Never fails the
    bench: lint breakage reports as lint_clean=False + lint_error."""
    try:
        from nnstreamer_tpu.analysis import lint_report

        root = os.path.dirname(os.path.abspath(__file__))
        report = lint_report(
            ["nnstreamer_tpu"], root=root,
            baseline_path=os.path.join(root, "nnlint_baseline.json"))
        out = {"lint_clean": report.clean}
        if not report.clean:
            out["lint_findings"] = len(report.findings)
        return out
    except Exception as e:          # pragma: no cover - defensive
        return {"lint_clean": False, "lint_error": repr(e)}


def _gate_env(env: dict, errors: dict) -> None:
    """Regression gate on host-path env metrics: a warm D2H read above
    the threshold means the environment (tunnel), not the code, will
    dominate every host-path number in the artifact — record it as an
    error so the run is flagged, never silently blended into history.
    Override with BENCH_ENV_D2H_GATE_MS; 0 disables."""
    # 30ms: healthy runs agree on a warm median well under it (r02
    # 17.32ms, r03 23.42ms) while the one tunnel-degraded run (r05,
    # pre-fix) read 192ms — the old 60ms gate left a 3x grey zone where
    # a half-degraded tunnel would still pass and pollute history
    gate_ms = float(os.environ.get("BENCH_ENV_D2H_GATE_MS", "30"))
    if gate_ms <= 0 or "d2h_1k_ms" not in env:
        return
    env["d2h_gate_ms"] = gate_ms
    env["d2h_gate_ok"] = env["d2h_1k_ms"] <= gate_ms
    if not env["d2h_gate_ok"]:
        errors["env_gate"] = (
            f"steady-state d2h_1k_ms {env['d2h_1k_ms']} exceeds "
            f"{gate_ms:.0f}ms gate: host-path numbers in this run are "
            f"tunnel-dominated")


def _build_label_device():
    """Config 1 without the per-frame host readback: sink blocks on the
    device arrays only (round-1-comparable; a local TPU host's D2H is µs
    so this ≈ the e2e number off the tunnel)."""
    import numpy as np

    import nnstreamer_tpu as nns
    from nnstreamer_tpu.elements import FakeSink, TensorFilter, TensorTransform
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    pipe = nns.Pipeline("label_device")
    src = AppSrc(spec=TensorsSpec.of(
        TensorInfo((1, 224, 224, 3), DType.UINT8)), name="src")
    if os.path.exists(MOBILENET_TFLITE):
        from nnstreamer_tpu.elements.decoder import TensorDecoder

        # full config-1 pipeline incl. the label decode — device=true
        # argmax fuses into the filter program, so it stays D2H-free
        stages = [src, TensorFilter(name="f", model=MOBILENET_TFLITE),
                  TensorDecoder(name="d", mode="image_labeling",
                                device=True)]
    else:
        norm = (TensorFilter(name="n", framework="pallas",
                             model="normalize_u8") if _on_tpu() else
                TensorTransform(name="n", mode="arithmetic",
                                option=NORMALIZE_OPT))
        stages = [src, norm, TensorFilter(name="f",
                                          model="zoo://mobilenet_v2")]
    sink = FakeSink(name="sink", sync_device=True)
    stages.append(sink)
    for e in stages:
        pipe.add(e)
    for a, b in zip(stages, stages[1:]):
        pipe.link(a, b)
    frame = np.random.default_rng(0).integers(
        0, 256, (1, 224, 224, 3), np.uint8)
    return pipe, src, sink, frame


def _build_label(max_in_flight=SSD_MAX_IN_FLIGHT):
    import numpy as np

    import nnstreamer_tpu as nns
    from nnstreamer_tpu.elements import FakeSink, TensorFilter, TensorTransform
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    use_tflite = os.path.exists(MOBILENET_TFLITE)
    pipe = nns.Pipeline("label")
    src = AppSrc(spec=TensorsSpec.of(
        TensorInfo((1, 224, 224, 3), DType.UINT8)), name="src")
    sink = FakeSink(name="sink", sync_device=True)
    stages = [src]
    if use_tflite:
        # real quantized weights; uint8 in, dequant fused into the model
        stages.append(TensorFilter(name="f", model=MOBILENET_TFLITE))
        if os.path.exists(LABELS):
            from nnstreamer_tpu.elements.decoder import TensorDecoder

            stages.append(TensorDecoder(name="d", mode="image_labeling",
                                        option1=LABELS,
                                        max_in_flight=max_in_flight))
    else:
        if _on_tpu():
            # compiled Pallas ingest kernel (normalize_u8) as the filter
            stages.append(TensorFilter(name="n", framework="pallas",
                                       model="normalize_u8"))
        else:
            stages.append(TensorTransform(
                name="n", mode="arithmetic",
                option=NORMALIZE_OPT))
        stages.append(TensorFilter(name="f", model="zoo://mobilenet_v2"))
    stages.append(sink)
    for e in stages:
        pipe.add(e)
    for a, b in zip(stages, stages[1:]):
        pipe.link(a, b)
    frame = np.random.default_rng(0).integers(
        0, 256, (1, 224, 224, 3), np.uint8)
    return pipe, src, sink, frame


def _ingest(dims: str) -> str:
    """uint8 camera-frame ingest with on-device normalize — the reference
    pipeline shape (tensor_converter uint8 → tensor_transform → filter),
    and 4× less H2D than pushing float32: the transform fuses into the
    filter's XLA program, so dequant happens on chip."""
    return (f"appsrc name=src dims={dims} types=uint8 ! "
            f"tensor_transform mode=arithmetic option={NORMALIZE_OPT} ! ")


def _u8_frame(shape, seed):
    import numpy as np

    return np.random.default_rng(seed).integers(0, 256, shape, np.uint8)


def _build_ssd(max_in_flight=SSD_MAX_IN_FLIGHT):
    """Host-decode parity config (BASELINE row 2): threshold, greedy
    NMS and the RGBA overlay run on host exactly as the reference's
    tensordec-boundingbox.c. device=compact reduces the D2H payload to
    the top-100 candidate rows on chip first — same final boxes, the
    raw 1917-anchor grids never cross the wire — and max_in_flight
    pipelines the candidate readbacks across frames (latency is
    measured separately on the strict max_in_flight=1 variant)."""
    import nnstreamer_tpu as nns

    pipe = nns.parse_launch(
        _ingest("3:300:300:1") +
        "tensor_filter model=zoo://ssd_mobilenet ! "
        "tensor_decoder mode=bounding_boxes device=compact "
        f"max_in_flight={max_in_flight} "
        "option1=mobilenet-ssd option3=0.5:0.5 option4=300:300 ! "
        "fakesink name=sink sync-device=true")
    frame = _u8_frame((1, 300, 300, 3), 1)
    return pipe, pipe.get("src"), pipe.get("sink"), frame


def _build_posenet(max_in_flight=SSD_MAX_IN_FLIGHT):
    """Host-decode pose config: heatmap decode on host (reference
    parity), with pipelined async readbacks across frames like the ssd
    and label configs (latency measured on the strict variant)."""
    import nnstreamer_tpu as nns

    pipe = nns.parse_launch(
        _ingest("3:257:257:1") +
        "tensor_filter model=zoo://posenet ! "
        "tensor_decoder mode=pose_estimation option1=257:257 "
        f"option4=0.0 max_in_flight={max_in_flight} ! "
        "fakesink name=sink sync-device=true")
    frame = _u8_frame((1, 257, 257, 3), 2)
    return pipe, pipe.get("src"), pipe.get("sink"), frame


def _build_ssd_device():
    """SSD config with device-side decode: postprocess (top-K, NMS) runs
    as XLA on chip; only a (16,6) box tensor would ever need D2H. This is
    the TPU-first placement of the same bbox decode the host config runs
    (decoders/device.py)."""
    import nnstreamer_tpu as nns

    pipe = nns.parse_launch(
        _ingest("3:300:300:1") +
        "tensor_filter model=zoo://ssd_mobilenet ! "
        "tensor_decoder mode=bounding_boxes device=true "
        "option1=mobilenet-ssd option3=0.5:0.5 option4=300:300 ! "
        "fakesink name=sink sync-device=true")
    frame = _u8_frame((1, 300, 300, 3), 1)
    return pipe, pipe.get("src"), pipe.get("sink"), frame


def _build_posenet_device():
    """PoseNet config with device-side heatmap decode → (17,3) keypoints."""
    import nnstreamer_tpu as nns

    pipe = nns.parse_launch(
        _ingest("3:257:257:1") +
        "tensor_filter model=zoo://posenet ! "
        "tensor_decoder mode=pose_estimation device=true option1=257:257 "
        "option2=257:257 ! "
        "fakesink name=sink sync-device=true")
    frame = _u8_frame((1, 257, 257, 3), 2)
    return pipe, pipe.get("src"), pipe.get("sink"), frame


def _build_composite():
    """2-tensor stream → demux → 2× filter (ONE shared device model) →
    mux → sink (BASELINE config 4)."""
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.elements import (
        FakeSink, TensorDemux, TensorFilter, TensorMux)
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    from nnstreamer_tpu.elements import TensorTransform

    pipe = nns.Pipeline("composite")
    src = AppSrc(spec=TensorsSpec.of(
        TensorInfo((1, 224, 224, 3), DType.UINT8),
        TensorInfo((1, 224, 224, 3), DType.UINT8)), name="src")
    demux = TensorDemux(name="dm")
    # uint8 ingest, per-branch normalize fused into each filter's XLA
    # program (4x less H2D than float32 frames)
    ta = TensorTransform(name="ta", mode="arithmetic", option=NORMALIZE_OPT)
    tb = TensorTransform(name="tb", mode="arithmetic", option=NORMALIZE_OPT)
    model = "zoo://mobilenet_v2?dtype=bfloat16"
    fa = TensorFilter(name="fa", model=model, shared_tensor_filter_key="bench")
    fb = TensorFilter(name="fb", model=model, shared_tensor_filter_key="bench")
    mux = TensorMux(name="mx", sync_mode="nosync")
    sink = FakeSink(name="sink", sync_device=True)
    for e in (src, demux, ta, tb, fa, fb, mux, sink):
        pipe.add(e)
    pipe.link(src, demux)
    pipe.link(demux, ta, 0, 0)
    pipe.link(demux, tb, 1, 0)
    pipe.link(ta, fa)
    pipe.link(tb, fb)
    pipe.link(fa, mux, 0, 0)
    pipe.link(fb, mux, 0, 1)
    pipe.link(mux, sink)
    x = _u8_frame((1, 224, 224, 3), 3)
    return pipe, src, sink, (x, x.copy())


#: MeshDispatcher coalescing windows swept for BASELINE row 5 — each
#: point runs as its own subprocess family (a fresh chip per point: one
#: point's closed-loop readbacks must not poison the next's dispatch).
#: Two points (round-5: the sweep is variance-dominated on the tunnel;
#: median-of-3 runs per point with spread beats more points), chosen
#: from the round-3/4 curves: 0 = latency floor, 3 = throughput knee.
OFFLOAD_DELAYS = (0.0, 3.0)


def _offload_point(delay_ms: float):
    # full round-3 sizing: shorter runs under-amortize the client
    # pipelining ramp (measured: n_frames=32 under-reports ~2x)
    sizes = dict(n_frames=48, n_lat=16) if _on_tpu() else {}
    return offload_bench(max_delay_ms=delay_ms, **sizes)


def _assemble_offload(curve: dict):
    """BASELINE row 5 asks for p50 *reported* — round 3 bought 249 FPS
    with p50 139.8ms via batching and no knob was measured. From the
    per-delay subprocess results, pick the default operating point: the
    lowest-latency delay that still clears ~200 FPS aggregate with
    p50 <= 60ms. The chosen point's numbers are the headline `offload`
    result; the full curve ships alongside so the tradeoff is
    driver-visible."""
    ok = {float(k): v for k, v in curve.items()
          if isinstance(v, dict) and "fps" in v}
    if not ok:
        return {"sweep": curve}
    good = {d: v for d, v in ok.items()
            if v["fps"] >= 200.0 and v["p50_ms"] <= 60.0}
    if good:
        chosen = min(good, key=lambda d: good[d]["p50_ms"])
    else:
        # fall back: among points within 5% of the best throughput,
        # take the lowest p50 (prefer sub-60ms points when any exist)
        sub60 = {d: v for d, v in ok.items() if v["p50_ms"] <= 60.0}
        pool = sub60 or ok
        best_fps = max(v["fps"] for v in pool.values())
        near = {d: v for d, v in pool.items()
                if v["fps"] >= 0.95 * best_fps}
        chosen = min(near, key=lambda d: near[d]["p50_ms"])
    out = dict(ok[chosen])
    out["chosen_delay_ms"] = chosen
    out["sweep"] = curve
    return out


def offload_bench(n_frames=None, n_lat=None, max_delay_ms=3.0):
    """BASELINE row 5: edge offload. Frames from FOUR concurrent client
    pipelines ship to one loopback BatchedQueryServer (MeshDispatcher
    coalesces all clients' frames into dp-sharded batches — SURVEY §3.4
    north star; the reference round-trips one frame per request,
    tensor_query_client.c:657-699). Reports aggregate open-loop FPS over
    all clients + closed-loop p50/p99 on a strict single client."""
    import numpy as np

    import nnstreamer_tpu as nns
    from nnstreamer_tpu.edge import BatchedQueryServer, QueryServer
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    on_tpu = _on_tpu()
    if n_frames is None:
        n_frames = 48 if on_tpu else 6
    if n_lat is None:
        n_lat = 24 if on_tpu else 3
    QueryServer.reset_all()

    def normalize(x):
        import jax.numpy as jnp

        return (x.astype(jnp.float32) - 127.5) / 127.5

    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    bqs = BatchedQueryServer(
        "zoo://mobilenet_v2", sid=9, port=0, bucket=8,
        max_delay_ms=max_delay_ms, pre=normalize,
        in_spec=TensorsSpec.of(TensorInfo((1, 224, 224, 3), DType.UINT8)))
    port = bqs.port
    frame = np.random.default_rng(0).integers(0, 256, (1, 224, 224, 3),
                                              np.uint8)

    def wait(runner, sink, target, timeout=600.0, poll=0.002):
        t0 = time.perf_counter()
        while len(sink.results) < target:
            if runner._error is not None:
                raise RuntimeError(
                    f"offload pipeline failed: {runner._error}"
                ) from runner._error
            if bqs.error is not None:
                raise RuntimeError(
                    f"offload server dispatch failed: {bqs.error}"
                ) from bqs.error
            if time.perf_counter() - t0 > timeout:
                raise RuntimeError(
                    f"offload stalled at {len(sink.results)}/{target}")
            time.sleep(poll)

    n_clients = 4
    runners = []
    r2 = None
    try:
        # dispatcher-only ceiling FIRST (tunnel convention: pure-compute
        # measurements before anything that does per-frame host reads,
        # which degrade subsequent dispatch in-process)
        d = bqs.dispatcher
        direct = np.random.default_rng(1).integers(
            0, 256, (224, 224, 3), np.uint8)
        d.infer(direct)                  # warms the min-bucket program
        full = [d.submit(direct) for _ in range(d.bucket)]
        for f in full:                   # warms the full-bucket program
            f.result(300)                # compile can stall on the
                                         # tunnel's remote-compile hop
        nd = 96 if on_tpu else 8
        t0 = time.perf_counter()
        futs = [d.submit(direct) for _ in range(nd)]
        for f in futs:
            f.result(300)
        dispatch_fps = nd / (time.perf_counter() - t0)
        st0 = bqs.stats()              # snapshot: isolate the 4-client
                                       # phase's coalescing statistics

        # aggregate open-loop throughput: 4 concurrent pipelined clients
        # (max_in_flight=8 each) — the server coalesces their frames
        # into shared batches
        warm = 4
        clients = []
        for c in range(n_clients):
            cp = nns.parse_launch(
                f"appsrc name=src dims=3:224:224:1 types=uint8 ! "
                f"tensor_query_client port={port} timeout=120 "
                f"max_in_flight=8 ! tensor_sink name=sink")
            runners.append(nns.PipelineRunner(cp).start())
            clients.append(cp)
        for c, cp in enumerate(clients):
            for i in range(warm + n_frames):
                cp.get("src").push(TensorBuffer.of(frame, pts=i))
            cp.get("src").end()
        for rn, cp in zip(runners, clients):
            wait(rn, cp.get("sink"), warm)    # compile + ramp complete
        t0 = time.perf_counter()
        for rn, cp in zip(runners, clients):
            wait(rn, cp.get("sink"), warm + n_frames)
        fps = n_clients * n_frames / (time.perf_counter() - t0)
        st1 = bqs.stats()              # end of the 4-client phase
        for rn in runners:
            rn.wait(60)
            rn.stop()

        # closed-loop latency with the reference-semantics client
        # (max_in_flight=1: push -> block for the reply)
        c2 = nns.parse_launch(
            f"appsrc name=src dims=3:224:224:1 types=uint8 ! "
            f"tensor_query_client port={port} timeout=120 ! "
            f"tensor_sink name=sink")
        r2 = nns.PipelineRunner(c2).start()
        src2, sink2 = c2.get("src"), c2.get("sink")
        lats = []
        for i in range(n_lat):
            t = time.perf_counter()
            src2.push(TensorBuffer.of(frame, pts=i))
            wait(r2, sink2, i + 1, poll=0.0005)  # latency-grade poll
            lats.append((time.perf_counter() - t) * 1e3)
        lats.sort()
        src2.end()
        r2.wait(60)
        r2.stop()
        return {"fps": round(fps, 2),
                "dispatch_fps": round(dispatch_fps, 2),
                "p50_ms": round(_percentile(lats, 50), 3),
                "p99_ms": round(_percentile(lats, 99), 3),
                "clients": n_clients,
                "frames_per_batch": round(
                    (st1["frames"] - st0["frames"])
                    / max(st1["batches"] - st0["batches"], 1), 2)}
    finally:
        for rn in runners + [r2]:   # dead clients must not keep threads
            if rn is not None:      # blocked on 120s reply timeouts
                try:
                    rn.stop()
                except Exception:
                    pass
        bqs.close()
        QueryServer.reset_all()


# -- batch sweep + MFU -------------------------------------------------------

def _sync(y) -> float:
    """True execution barrier: 4-byte readback of a value dependent on
    `y` (block_until_ready is not a real barrier on relayed backends —
    the relay acks the dispatch, not the compute)."""
    import jax
    import jax.numpy as jnp

    leaf = jax.tree_util.tree_leaves(y)[0]
    return float(jnp.sum(leaf.astype(jnp.float32).ravel()[:8]))


def _step_ms(f, *args, n1=20, n2=100):
    """Per-step ms via differencing two loop lengths, each closed by the
    readback barrier; differencing cancels the barrier's fixed cost and
    the ramp. Off-TPU the loops shrink — the method's purpose is the
    tunneled chip."""
    if not _on_tpu():
        n1, n2 = max(2, n1 // 10), max(4, n2 // 10)
    _sync(f(*args))          # warmup: compile fn + the sync path

    def run(n):
        t0 = time.perf_counter()
        for _ in range(n):
            y = f(*args)
        _sync(y)
        return time.perf_counter() - t0

    run(n1)                 # second warm pass (cache/queue steady state)
    t_a, t_b = run(n1), run(n2)
    return max((t_b - t_a) / (n2 - n1) * 1e3, 1e-6)


def _med3(f, *a, n1=20, n2=80):
    """Median of three differencing samples: tunnel jitter can make one
    sample implausible (even negative)."""
    return sorted(_step_ms(f, *a, n1=n1, n2=n2) for _ in range(3))[1]


def batch_sweep(batches=None):
    """Fused-forward MobileNetV2 throughput per batch.

    Per batch size, three numbers:
    - `ms` / `fps` / `mfu_pct`: pure-compute step time with the input
      resident on device (XLA-counted FLOPs vs the chip's bf16 peak) —
      the chip-utilization measurement.
    - `piped_fps`: open-loop FPS with host frames staged through the
      double-buffered `prefetch_to_device` input pipeline (H2D overlaps
      compute — the deployable number; on the tunneled dev chip this is
      transfer-bound, on a local TPU host it approaches `fps`).
    - `hbm_gbps` / `hbm_util_pct` / `ai_flops_per_byte`: achieved HBM
      bandwidth (XLA-counted bytes accessed over the measured step) vs
      the chip's 819 GB/s peak, plus arithmetic intensity — the
      roofline evidence for WHY MobileNet's MFU tops out where it does
      (depthwise-separable convs are byte-bound, not FLOP-bound; the
      claim is only honest if the knee runs near the bandwidth peak).
    Knee = batch with best MFU.
    """
    import jax
    import numpy as np

    from nnstreamer_tpu.runtime.input_pipeline import prefetch_to_device

    out = {}
    on_tpu = _on_tpu()
    if batches is None:
        batches = (1, 8, 32, 64, 128, 256) if on_tpu else (1, 8)
    for b in batches:
        if os.path.exists(MOBILENET_TFLITE):
            from nnstreamer_tpu.modelio import load_model_file

            bundle = load_model_file(MOBILENET_TFLITE, batch=b)
        else:
            from nnstreamer_tpu.models.zoo import build_model

            bundle = build_model(f"mobilenet_v2?batch={b}")
        params = jax.device_put(bundle.params)
        fn = jax.jit(bundle.fn)
        x = np.random.default_rng(0).integers(
            0, 256, (b, 224, 224, 3), np.uint8)
        if bundle.in_spec and \
                bundle.in_spec.tensors[0].dtype.np_dtype == np.float32:
            x = ((x.astype(np.float32) - 127.5) / 127.5)
        compiled = fn.lower(params, x).compile()
        cost = compiled.cost_analysis() or {}
        flops = float(cost.get("flops", 0.0))
        hbm_bytes = float(cost.get("bytes accessed", 0.0))
        # pure compute, input resident on device (median of three
        # differencing samples: single samples can be off by 2-8x
        # under tunnel jitter — measured b=8/b=32 inversions)
        xd = jax.device_put(x)
        ms = _med3(fn, params, xd, n1=10, n2=50)
        fps = b / ms * 1e3
        tflops = flops / (ms / 1e3) / 1e12 if flops else 0.0
        # pipelined host→device staging (double-buffered feeder); the
        # timed loop closes with the readback barrier because
        # block_until_ready is not a true barrier on relayed backends
        n_staged = 24 if on_tpu else 4
        it = prefetch_to_device(iter([x] * n_staged), depth=2)
        first = next(it)
        jax.block_until_ready(fn(params, first))   # compile hit + warm
        t0 = time.perf_counter()
        got = 1
        for xd_s in it:
            y = fn(params, xd_s)
            got += 1
        _sync(y)
        piped_fps = (got - 1) * b / max(time.perf_counter() - t0, 1e-9)
        gbps = hbm_bytes / (ms / 1e3) / 1e9 if hbm_bytes else 0.0
        out[str(b)] = {
            "ms": round(ms, 3),
            "fps": round(fps, 1),
            "piped_fps": round(piped_fps, 1),
            "tflops": round(tflops, 3),
            "mfu_pct": round(100 * tflops / PEAK_BF16_TFLOPS, 2)
            if on_tpu and tflops else 0.0,
            "hbm_bytes_per_step": hbm_bytes,
            "hbm_gbps": round(gbps, 1),
            "hbm_util_pct": round(100 * gbps / PEAK_HBM_GBPS, 1)
            if on_tpu and gbps else 0.0,
            "ai_flops_per_byte": round(flops / hbm_bytes, 2)
            if hbm_bytes else 0.0,
        }
        _family_partial(out)     # a timed-out sweep still ships batches
    # knee = best-MFU batch on TPU; off-TPU (mfu is 0) best raw FPS
    key = "mfu_pct" if on_tpu else "fps"
    out["knee_batch"] = max(
        (int(k) for k in out), key=lambda b: out[str(b)][key])
    return out


def int8_native_check():
    """The int8-native quantized execution path (tflite_quant.py):
    TPU agreement against the TFLite interpreter (the authoritative
    int8 semantics for this model file) plus its pure-compute step
    time. The agreement oracle is the interpreter, not an XLA:CPU
    recompile of the same program: the int8-conv CPU compile takes
    ~10 min of host CPU (measured) while interpreter invokes take
    milliseconds — and a shared-program oracle can't catch a lowering
    bug the way an independent implementation can. Perf context: int8
    NHWC convs run ~11× slower than the dequantized bf16 path at the
    same batch (7.2 vs 0.67 ms/step at b=32, measured round 5), so
    int8-native stays a verified feature, not the perf path."""
    import jax
    import numpy as np

    from nnstreamer_tpu.modelio import load_model_file

    if not os.path.exists(MOBILENET_TFLITE):
        return {}
    b = 32
    from nnstreamer_tpu.core.fixtures import synthetic_frames

    bundle = load_model_file(MOBILENET_TFLITE, batch=b,
                             compute_dtype="int8")
    # structured frames (peaked logits), not pure noise — noise gives
    # near-uniform logits whose argmax flips on ±1 quantized steps,
    # misreading rounding-mode skew as model error (fixtures docstring)
    x = synthetic_frames(b, seed=7)
    fn = jax.jit(bundle.fn)
    # stream each milestone so a family timeout still ships whatever
    # completed (this family runs last; ~25s warm-cache since the
    # interpreter-oracle swap, so it fits any plausible budget now)
    got = np.asarray(fn(bundle.params, x)[0])     # TPU compile + run
    out = {}
    params = jax.device_put(bundle.params)
    xd = jax.device_put(x)
    ms = _step_ms(fn, params, xd, n1=10, n2=40)
    out.update(ms_b32=round(ms, 3), fps_b32=round(b / ms * 1e3, 1))
    _family_partial(out)
    try:
        import tensorflow as tf
    except ImportError:
        # a machine-checkable flag, not prose: without the interpreter
        # oracle this family's perf number shipped WITHOUT its agreement
        # check, and the summary must say so (families_with_warnings)
        out["oracle"] = "tensorflow absent; agreement not run here"
        out["unverified"] = True
        return out
    interp = tf.lite.Interpreter(MOBILENET_TFLITE)
    interp.allocate_tensors()
    inp = interp.get_input_details()[0]
    outd = interp.get_output_details()[0]
    ref = np.empty_like(got)
    for i in range(b):
        interp.set_tensor(inp["index"], x[i:i + 1])
        interp.invoke()
        ref[i] = interp.get_tensor(outd["index"])[0]
    out["tpu_vs_tflite_top1"] = round(float(
        (got.argmax(-1) == ref.argmax(-1)).mean()), 3)
    out["max_qdiff"] = int(np.abs(got.astype(np.int32)
                                  - ref.astype(np.int32)).max())
    return out


def _build_dyn_batch(batched: bool, max_batch: int = 64,
                     max_latency_ms: float = 5.0):
    """Same appsrc→filter→sink pipeline, per-frame or micro-batched.

    Frames are pushed as float32 so both arms pay identical H2D cost
    and the comparison isolates the invoke granularity (batch-1 MXU
    launches vs one coalesced batched launch per flush)."""
    import numpy as np

    import nnstreamer_tpu as nns
    from nnstreamer_tpu.elements import FakeSink, TensorFilter
    from nnstreamer_tpu.elements.batch import TensorBatch, TensorUnbatch
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    pipe = nns.Pipeline("dyn_batch" if batched else "per_frame")
    src = AppSrc(spec=TensorsSpec.of(
        TensorInfo((1, 224, 224, 3), DType.FLOAT32)), name="src")
    stages = [src]
    if batched:
        stages.append(TensorBatch(name="batcher", max_batch=max_batch,
                                  max_latency_ms=max_latency_ms))
    stages.append(TensorFilter(name="f", model="zoo://mobilenet_v2"))
    if batched:
        stages.append(TensorUnbatch(name="unbatch"))
    sink = FakeSink(name="sink", sync_device=True)
    stages.append(sink)
    for e in stages:
        pipe.add(e)
    for a, b in zip(stages, stages[1:]):
        pipe.link(a, b)
    frame = np.random.default_rng(0).normal(
        size=(1, 224, 224, 3)).astype(np.float32)
    return pipe, src, sink, frame


def dyn_batch_check():
    """Dynamic micro-batching family: the same MobileNetV2 pipeline
    per-frame vs batched through tensor_batch max-batch=K
    max-latency-ms=5 ! tensor_filter ! tensor_unbatch. Reports both
    fps, the speedup, the achieved batch-occupancy histogram and
    flush-reason counters (from PipelineRunner.stats()), and the
    closed-loop p50/p99 latency the coalescing adds over the per-frame
    arm — the number to hold against the max-latency-ms budget. The
    knee of batch_sweep's piped_fps is what max-batch should be sized
    to; this family shows what occupancy the push rate actually
    achieves against that ceiling."""
    max_batch = 64 if _on_tpu() else 8
    budget_ms = 5.0
    n_frames = 256 if _on_tpu() else 8
    out = {"max_batch": max_batch, "max_latency_ms": budget_ms}
    pf = _Bench(lambda: _build_dyn_batch(False)).run(n_frames=n_frames)
    out["per_frame"] = pf
    _family_partial(out)
    bench = _Bench(lambda: _build_dyn_batch(True, max_batch, budget_ms))
    db = bench.run(n_frames=n_frames)
    st = bench.runner.stats().get("batcher", {})
    out["batched"] = db
    out["speedup"] = round(db["fps"] / pf["fps"], 2) if pf["fps"] else 0.0
    out["occupancy_hist"] = st.get("occupancy_hist", {})
    out["occupancy_avg"] = round(st.get("occupancy_avg", 0.0), 2)
    out["flush_reasons"] = {k: st.get(k, 0) for k in
                            ("flush_full", "flush_deadline", "flush_eos")}
    out["timer_fires"] = st.get("timer_fires", 0)
    # closed-loop frames ride a deadline flush each (nothing to coalesce
    # with), so added p50 ≈ the latency budget — the deadline contract,
    # visible in the artifact
    out["added_p50_ms"] = round(db["p50_ms"] - pf["p50_ms"], 3)
    out["added_p99_ms"] = round(db["p99_ms"] - pf["p99_ms"], 3)
    out["added_p99_vs_budget"] = (round(out["added_p99_ms"] / budget_ms, 2)
                                  if budget_ms else 0.0)
    return out


def pallas_check():
    """Prove the Pallas ingest kernels compile (not interpret) and match
    numpy on this platform (VERDICT r1 item 7)."""
    import jax
    import numpy as np

    from nnstreamer_tpu.backends import pallas_ops

    x = np.random.default_rng(0).integers(0, 256, (224, 224, 3), np.uint8)
    f = jax.jit(lambda a: pallas_ops.normalize_u8(a))
    y = np.asarray(f(x))
    np.testing.assert_allclose(
        y, (x.astype(np.float32) - 127.5) / 127.5, rtol=1e-6)
    g = jax.jit(lambda a: pallas_ops.clamp_scale(a, 0.0, 1.0))
    np.testing.assert_allclose(np.asarray(g(y)), np.clip(y, 0, 1), rtol=1e-6)
    compiled = not pallas_ops._interpret()
    hlo = f.lower(x).compile().as_text()
    out = {
        "platform": jax.default_backend(),
        "compiled": compiled,
        "mosaic_custom_call": ("tpu_custom_call" in hlo) if compiled else False,
        "numerics": "ok",
    }
    if compiled:
        # flash attention: the transformer hot op as a Pallas kernel,
        # timed against XLA's fused softmax attention at S=2048 with the
        # differencing+readback method (_step_ms — block_until_ready is
        # not a true barrier on the relayed backend)
        import jax.numpy as jnp

        from nnstreamer_tpu.parallel.ring_attention import reference_attention

        B, S, H, D = 4, 2048, 8, 128
        key = jax.random.PRNGKey(0)
        q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
                   for kk in jax.random.split(key, 3))
        ff = jax.jit(lambda q, k, v: pallas_ops.flash_attention(
            q, k, v, causal=True))
        fr = jax.jit(lambda q, k, v: reference_attention(q, k, v,
                                                         causal=True))
        err = float(jnp.max(jnp.abs(
            ff(q, k, v).astype(jnp.float32)
            - fr(q, k, v).astype(jnp.float32))))

        ours = _med3(ff, q, k, v)
        xla = _med3(fr, q, k, v)
        flops = 4 * B * H * S * S * D / 2          # causal
        out["flash_attention"] = {
            "s2048_ms": round(ours, 3),
            "xla_attn_ms": round(xla, 3),
            "speedup_vs_xla": round(xla / ours, 2),
            "mfu_pct": round(
                100 * flops / (ours / 1e3) / 1e12 / PEAK_BF16_TFLOPS, 1),
            "max_abs_err": round(err, 4),
        }
        _family_partial(out)     # s2048 survives a long-S timeout
        _flash_long_s(out)
    return out


def _flash_long_s(base_out):
    """Long-sequence flash rows (§5.7 long-context): S=8192 on the plain
    q-block grid (vs the XLA softmax, which still fits), and S=32768
    where the kernel auto-switches to the K-blocked streaming grid
    (per-head K/V = 16MB, past the 8MB VMEM budget; XLA comparison is
    omitted there — the materialized (H,S,S) score tensor is the thing
    the kernel exists to avoid)."""
    import jax
    import jax.numpy as jnp

    from nnstreamer_tpu.backends import pallas_ops
    from nnstreamer_tpu.parallel.ring_attention import reference_attention

    H, D = 8, 128
    out = {}
    base_out["flash_long_s"] = out
    # S=32768: per-head K/V = 2*S*D*2B = 16MB, past the 8MB VMEM budget
    # (S=16384 is exactly AT the budget and still takes the plain grid)
    for S, vs_xla in ((8192, True), (32768, False)):
        key = jax.random.PRNGKey(S)
        q, k, v = (jax.random.normal(kk, (1, S, H, D), jnp.bfloat16)
                   for kk in jax.random.split(key, 3))
        ff = jax.jit(lambda q, k, v: pallas_ops.flash_attention(
            q, k, v, causal=True))
        # loop counts sized so the differencing delta clears the ~17ms
        # readback jitter: s8192 steps are ~1ms (needs many), s32768
        # ~35ms (few suffice)
        n1, n2 = (20, 100) if S <= 8192 else (5, 20)
        ms = _med3(ff, q, k, v, n1=n1, n2=n2)
        flops = 4 * 1 * H * S * S * D / 2          # causal
        row = {
            "ms": round(ms, 3),
            "mfu_pct": round(
                100 * flops / (ms / 1e3) / 1e12 / PEAK_BF16_TFLOPS, 1),
        }
        if vs_xla:
            fr = jax.jit(lambda q, k, v: reference_attention(
                q, k, v, causal=True))
            err = float(jnp.max(jnp.abs(
                ff(q, k, v).astype(jnp.float32)
                - fr(q, k, v).astype(jnp.float32))))
            xla = _med3(fr, q, k, v, n1=2, n2=8)
            row["xla_attn_ms"] = round(xla, 3)
            row["speedup_vs_xla"] = round(xla / ms, 2)
            row["max_abs_err"] = round(err, 4)
        out[f"s{S}"] = row
        _family_partial(base_out)
    return out


def mxu_peak():
    """Chip-ceiling micro-rows: one big matmul in bf16 and in int8.

    Grounds every MFU number in the same methodology (what fraction of
    a measured — not datasheet — ceiling we reach), and demonstrates
    the int8 MXU path the quantized-matmul lowering rides: on v5e,
    int8 4096^3 runs ~2x the bf16 rate (int8 is the *matmul* win on
    this backend; int8 NHWC convs lose to relayout costs, which is why
    tflite_quant keeps bf16 as the conv perf path)."""
    import jax
    import jax.numpy as jnp

    n = 4096 if _on_tpu() else 256
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.bfloat16)
    b = jax.random.normal(jax.random.split(key)[0], (n, n), jnp.bfloat16)
    ai = (a * 16).astype(jnp.int8)
    bi = (b * 16).astype(jnp.int8)
    f_bf16 = jax.jit(lambda a, b: a @ b)
    f_int8 = jax.jit(lambda a, b: jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32))
    flops = 2.0 * n * n * n
    out = {"n": n}
    for name, f, args in (("bf16", f_bf16, (a, b)),
                          ("int8", f_int8, (ai, bi))):
        # sub-ms steps need long loops: short differencing windows
        # under-report by ~15% (measured 221 vs 185-190 TFLOP/s)
        ms = _med3(f, *args, n1=50, n2=200)
        tops = flops / (ms / 1e3) / 1e12
        out[name] = {"ms": round(ms, 3), "tflops": round(tops, 1)}
        _family_partial(out)
    out["bf16"]["mfu_pct"] = round(
        100 * out["bf16"]["tflops"] / PEAK_BF16_TFLOPS, 1)
    out["int8_vs_bf16_peak"] = round(
        out["int8"]["tflops"] / PEAK_BF16_TFLOPS, 2)
    return out


def transformer_prefill():
    """Compute-bound MFU demonstration (VERDICT r3 missing #2): a
    bf16 transformer prefill sized so the MXU matmuls dominate
    (arithmetic intensity ~B*S — far past the HBM roofline knee where
    MobileNet lives). FLOPs are XLA-counted on the all-XLA variant and
    applied to both timings (identical math); `mfu_pct` at top level is
    the best variant, the driver-visible compute-utilization number."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nnstreamer_tpu.models import transformer as T

    on_tpu = _on_tpu()
    if on_tpu:
        d_model, n_heads, n_layers, B, S, vocab = 1024, 8, 4, 8, 2048, 512
    else:   # CI smoke: same code path, toy size
        d_model, n_heads, n_layers, B, S, vocab = 128, 2, 2, 1, 256, 64
    params = T.init_params(d_model=d_model, n_heads=n_heads,
                           n_layers=n_layers, vocab=vocab)
    params = jax.device_put(jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if a.dtype == jnp.float32 else a, params))
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, vocab, (B, S), np.int32))

    def make(attn):
        return jax.jit(lambda p, i: T.apply_seq(
            p, i, n_heads=n_heads, dtype=jnp.bfloat16, attn=attn))

    fx = make("xla")
    compiled = fx.lower(params, ids).compile()
    flops = float((compiled.cost_analysis() or {}).get("flops", 0.0))
    out = {"config": {"d_model": d_model, "n_layers": n_layers,
                      "n_heads": n_heads, "batch": B, "seq": S},
           "flops_per_step": flops}
    best = 0.0
    for name, f in (("xla_attn", fx), ("pallas_attn", make("pallas"))):
        ms = _med3(f, params, ids, n1=5, n2=20)
        tfl = flops / (ms / 1e3) / 1e12 if flops else 0.0
        mfu = round(100 * tfl / PEAK_BF16_TFLOPS, 1) if on_tpu else 0.0
        out[name] = {"ms": round(ms, 3), "tflops": round(tfl, 2),
                     "mfu_pct": mfu,
                     "tokens_per_s": round(B * S / ms * 1e3)}
        best = max(best, mfu)
        out["mfu_pct"] = best
        _family_partial(out)     # prefill rows survive a decode stall
    # streaming decode (§5.7): one token per step through the ring
    # KV cache — the HBM-bound half of the serving story (params are
    # re-read every step; prefill above is the MXU-bound half)
    # bf16 cache STORAGE (decode is HBM-bound by the cache sweep;
    # softmax/accumulators stay f32 on read — parity-tested)
    kc, vc, pos = T.init_cache(batch=B, max_len=min(S, 2048),
                               d_model=d_model, n_heads=n_heads,
                               n_layers=n_layers, dtype=jnp.bfloat16)
    kc, vc = jax.device_put(kc), jax.device_put(vc)
    step_ids = jnp.zeros((B, 1), jnp.int32)

    NSTEP = 32

    def make_dloop(step):
        # a real decode loop: cache threaded through lax.scan, one
        # token per step, logits head sampled per step. One factory
        # for the float and W8A8 variants so NSTEP/carry/logits-slice
        # stay in lockstep and the vs_bf16 ratio is apples-to-apples.
        def dloop(p, i, kc, vc, pos):
            def body(carry, _):
                kc, vc, pos = carry
                logits, kc, vc, pos = step(p, i, kc, vc, pos)
                return (kc, vc, pos), logits[:, :8]
            _, outs = jax.lax.scan(body, (kc, vc, pos), None,
                                   length=NSTEP)
            return outs
        return dloop

    fd = jax.jit(make_dloop(lambda p, i, kc, vc, pos: T.apply_step(
        p, i, kc, vc, pos, n_heads=n_heads, dtype=jnp.bfloat16)))
    dms = _med3(fd, params, step_ids, kc, vc, pos, n1=5, n2=20) / NSTEP
    out["decode"] = {"step_ms": round(dms, 4),
                     "tokens_per_s": round(B / dms * 1e3)}
    _family_partial(out)
    # W8A8 prefill: int8 projections via the fused Pallas row-quant
    # kernel, bf16 inter-op activations (models/quant.py perf note) —
    # same math, measured against the bf16 prefill above
    from nnstreamer_tpu.models.quant import (apply_seq_w8a8,
                                             quantize_transformer)

    fparams = T.init_params(d_model=d_model, n_heads=n_heads,
                            n_layers=n_layers, vocab=vocab)
    pq = jax.device_put(quantize_transformer(fparams))
    fq = jax.jit(lambda p, i: apply_seq_w8a8(
        p, i, n_heads=n_heads, attn="pallas", dtype=jnp.bfloat16))
    qms = _med3(fq, pq, ids, n1=5, n2=20)
    bf_ms = out["pallas_attn"]["ms"]
    out["w8a8_prefill"] = {
        "ms": round(qms, 3),
        "tokens_per_s": round(B * S / qms * 1e3),
        "vs_bf16": round(bf_ms / qms, 2) if qms else 0.0}
    _family_partial(out)
    # W8A8 decode: int8 weights halve the per-step weight sweep
    from nnstreamer_tpu.models.quant import apply_step_w8a8

    kc2, vc2, pos2 = T.init_cache(batch=B, max_len=min(S, 2048),
                                  d_model=d_model, n_heads=n_heads,
                                  n_layers=n_layers, dtype=jnp.bfloat16)
    fqd = jax.jit(make_dloop(lambda p, i, kc, vc, pos: apply_step_w8a8(
        p, i, kc, vc, pos, n_heads=n_heads)))
    qdms = _med3(fqd, pq, step_ids, kc2, vc2, pos2, n1=5, n2=20) / NSTEP
    out["w8a8_decode"] = {
        "step_ms": round(qdms, 4),
        "tokens_per_s": round(B / qdms * 1e3),
        "vs_bf16": round(dms / qdms, 2) if qdms else 0.0}
    return out


#: differencing-method measurement families, each run in its own
#: subprocess with a fresh TPU client (quiet chip per family; no
#: cross-family dispatch poisoning — round-3 lesson)
def _cfg_composite():
    r = _Bench(_build_composite, frames_per_push=2).run()
    # tail guard (VERDICT r2 weak #4: p99 was 24ms in round 2; the
    # scheduler's queue-wait tracing separates starvation from slow
    # elements if this regresses). Informational flag only: a loaded
    # host inflates every e2e config — that must not turn the whole
    # bench red.
    r["p99_over_budget"] = r["p99_ms"] > 10.0
    return r


def _cfg_label():
    # the label pipeline only contains the lagging decoder on the
    # real-model path (tflite + labels present)
    lags = os.path.exists(MOBILENET_TFLITE) and os.path.exists(LABELS)
    return _Bench(_build_label,
                  build_lat=lambda: _build_label(max_in_flight=1),
                  lag=SSD_MAX_IN_FLIGHT - 1 if lags else 0).run()


def _cfg_ssd():
    kw = dict(n_frames=48, n_lat=12) if _on_tpu() else {}
    return _Bench(_build_ssd,
                  build_lat=lambda: _build_ssd(max_in_flight=1),
                  lag=SSD_MAX_IN_FLIGHT - 1).run(**kw)


# -- chaos smoke (docs/robustness.md) ----------------------------------------
#: seeded so a failing chaos run replays exactly (override to explore)
CHAOS_SEED = int(os.environ.get("BENCH_CHAOS_SEED", "1234"))


def _splice_fault(pipe, src, **fault_props):
    """Insert a tensor_fault right after `src` on its first output link
    (the standard chaos splice point: every downstream stage then sees
    the injected faults)."""
    from nnstreamer_tpu.elements.fault import TensorFault
    from nnstreamer_tpu.graph.pipeline import Link

    link = next(l for l in pipe.links if l.src is src)
    pipe.links.remove(link)
    fault = pipe.add(TensorFault(name="chaos", **fault_props))
    pipe.links.append(Link(src, link.src_pad, fault, 0))
    pipe.links.append(Link(fault, 0, link.dst, link.dst_pad))
    return fault


def _build_chaos_synthetic():
    """Model-free chaos target — always runnable, so chaos_smoke can
    never go vacuously green just because model files are absent."""
    import numpy as np

    import nnstreamer_tpu as nns
    from nnstreamer_tpu.elements import FakeSink, TensorTransform
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    pipe = nns.Pipeline("chaos_synthetic")
    src = AppSrc(spec=TensorsSpec.of(
        TensorInfo((1, 16, 16, 3), DType.UINT8)), name="src")
    xf = TensorTransform(name="t", mode="typecast", option="float32")
    sink = FakeSink(name="sink")
    for e in (src, xf, sink):
        pipe.add(e)
    pipe.link(src, xf)
    pipe.link(xf, sink)
    frame = np.random.default_rng(0).integers(
        0, 256, (1, 16, 16, 3), np.uint8)
    return pipe, src, sink, frame


def _chaos_one(build, n_frames):
    """Run one pipeline to EOS with a 1%-raising tensor_fault under
    error-policy=skip; pass iff EOS is reached and every pushed frame is
    accounted for (emitted + skipped == pushed)."""
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    pipe, src, sink, frame = build()
    _splice_fault(pipe, src, mode="raise", probability=0.01,
                  seed=CHAOS_SEED, error_policy="skip")
    runner = nns.PipelineRunner(pipe, queue_capacity=4).start()
    try:
        for i in range(n_frames):
            f = frame if isinstance(frame, tuple) else (frame,)
            src.push(TensorBuffer.of(*f, pts=i))
        src.end()
        runner.wait(timeout=240)
    finally:
        runner.stop()
    skipped = runner.stats()["chaos"]["skipped"]
    return {"frames": n_frames, "emitted": sink.count,
            "faults_injected": pipe.get("chaos").injected,
            "skipped": skipped,
            "ok": sink.count + skipped == n_frames}


def chaos_smoke() -> dict:
    """Seeded chaos smoke over representative bench pipelines: each runs
    once with a spliced tensor_fault (1% raise, error-policy=skip) and
    must complete to EOS with exact buffer conservation. chaos_ok is
    True iff every target completed cleanly (the model targets build
    against the zoo fallback when weight files are absent, and the
    synthetic target needs no model at all, so nothing is skipped).
    BENCH_CHAOS_TARGETS=a,b filters targets (tests use synthetic)."""
    builders = {
        "synthetic": lambda: _chaos_one(_build_chaos_synthetic, 200),
        "label_device": lambda: _chaos_one(
            _build_label_device, 64 if _on_tpu() else 12),
        "label": lambda: _chaos_one(
            _build_label, 64 if _on_tpu() else 12),
    }
    only = os.environ.get("BENCH_CHAOS_TARGETS", "")
    if only:
        keep = {t.strip() for t in only.split(",") if t.strip()}
        builders = {k: v for k, v in builders.items() if k in keep}
    out = {"seed": CHAOS_SEED, "pipelines": {}}
    ran = failed = 0
    for name, fn in builders.items():
        try:
            r = fn()
            out["pipelines"][name] = r
            ran += 1
            if not r["ok"]:
                failed += 1
        except Exception as e:
            out["pipelines"][name] = {
                "error": f"{type(e).__name__}: {e}"}
            failed += 1
    out["chaos_ok"] = ran > 0 and failed == 0
    return out


def _swap_arm(prewarm: bool, n_frames: int) -> dict:
    """One closed-loop run through a store:// pipeline with a hot swap
    at the halfway frame: per-frame latency before/after the epoch
    flip, plus the post-flip compile growth that tells whether the
    swap recompiled on the hot path."""
    import numpy as np

    import nnstreamer_tpu as nns
    from nnstreamer_tpu.elements import FakeSink, TensorFilter
    from nnstreamer_tpu.elements.batch import TensorBatch, TensorUnbatch
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.serving.store import reset_store
    from nnstreamer_tpu.tensor.buffer import TensorBuffer
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    # two store versions of the same architecture: the swap cost under
    # measurement is compilation/adoption, which doesn't care that the
    # weights match
    store = reset_store()
    store.register("bench_swap", "zoo://mobilenet_v2")
    store.register("bench_swap", "zoo://mobilenet_v2")

    pipe = nns.Pipeline("model_swap")
    src = AppSrc(spec=TensorsSpec.of(
        TensorInfo((1, 224, 224, 3), DType.FLOAT32)), name="src")
    stages = [src,
              TensorBatch(name="batcher", max_batch=8, max_latency_ms=5.0),
              TensorFilter(name="f", model="store://bench_swap"),
              TensorUnbatch(name="unbatch"),
              FakeSink(name="sink", sync_device=True)]
    for e in stages:
        pipe.add(e)
    for a, b in zip(stages, stages[1:]):
        pipe.link(a, b)
    sink = pipe.get("sink")
    frame = np.random.default_rng(0).normal(
        size=(1, 224, 224, 3)).astype(np.float32)

    runner = nns.PipelineRunner(pipe, queue_capacity=4).start()
    half = n_frames // 2
    lats = []
    cc_at_flip = None
    try:
        for i in range(n_frames):
            if i == half:
                store.update("bench_swap", prewarm=prewarm)
                # prewarm compiles happen inside update(), before the
                # flip — anything after this point is hot-path cost
                cc_at_flip = pipe.get("f").backend.compile_count
            t0 = time.perf_counter()
            src.push(TensorBuffer.of(frame, pts=i))
            deadline = t0 + 120.0
            while sink.count <= i and time.perf_counter() < deadline:
                time.sleep(0.0002)
            lats.append((time.perf_counter() - t0) * 1e3)
        src.end()
        runner.wait(timeout=240)
    finally:
        runner.stop()
    backend = pipe.get("f").backend
    pre, post = sorted(lats[2:half]), sorted(lats[half:])
    post_flip_compiles = backend.compile_count - cc_at_flip
    return {
        "prewarm": prewarm,
        "frames": n_frames,
        "emitted": sink.count,
        "pre_p50_ms": round(_percentile(pre, 50), 3),
        "pre_p99_ms": round(_percentile(pre, 99), 3),
        "post_p50_ms": round(_percentile(post, 50), 3),
        "post_p99_ms": round(_percentile(post, 99), 3),
        "post_max_ms": round(post[-1], 3) if post else 0.0,
        "post_flip_compiles": post_flip_compiles,
        "swaps_adopted": backend.swap_count,
        "ok": (sink.count == n_frames
               and backend.swap_count == 1
               and (post_flip_compiles == 0 or not prewarm)),
    }


def model_swap() -> dict:
    """Zero-downtime hot-swap family: p99 closed-loop latency through a
    mid-stream ModelStore.update() with and without pre-warm. The
    pre-warmed arm must show no recompile-induced spike (post-flip
    compile growth must be exactly 0 — the same bucket is a staged
    cache hit); the unwarmed arm documents the spike being avoided.
    swap_ok gates on the pre-warmed arm: full conservation, one epoch
    adoption, zero hot-path compiles after the flip."""
    n_frames = 96 if _on_tpu() else 16
    out = {"n_frames": n_frames}
    warm = _swap_arm(True, n_frames)
    out["prewarmed"] = warm
    _family_partial(out)
    cold = _swap_arm(False, n_frames)
    out["unwarmed"] = cold
    out["spike_avoided_ms"] = round(
        cold["post_max_ms"] - warm["post_max_ms"], 3)
    out["swap_ok"] = bool(warm["ok"] and cold["ok"])
    return out


def host_path() -> dict:
    """Host-path tax family (the BENCH_r05 finding: ~34k fps raw device
    invoke vs ~309 piped_fps). Measurements stream as they land:
    scheduler wakeup latency vs the old 100 ms poll floor, per-hop
    overhead through a passthrough chain fused vs unfused, the
    piped_fps A/B on the real label config (chain fusion off/on,
    tracer on, devprof on, compiled steady-state loop off), the
    piped-over-raw ratio, and the same-host shm-vs-pipe hop A/B.
    Reuses tools/profile_hostpath.py (also the tier-1 smoke test) so
    the bench, the profiler, and the test measure one code path."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "profile_hostpath",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "tools", "profile_hostpath.py"))
    ph = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ph)

    out = {"wakeup_latency": ph.measure_wakeup_latency(n=200)}
    _family_partial(out)
    frames = 2000 if _on_tpu() else 1200
    fused = ph.measure_hop_overhead(4, frames, fused=True)
    unfused = ph.measure_hop_overhead(4, frames, fused=False)
    out["hop_overhead"] = {
        "fused": fused,
        "unfused": unfused,
        "fused_speedup": round(
            unfused["per_frame_us"] / fused["per_frame_us"], 2)
        if fused["per_frame_us"] else 0.0,
    }
    _family_partial(out)
    # before/after piped_fps: the same label pipeline, fusion off vs on
    piped = {}
    for key, enabled in (("fusion_off", False), ("fusion_on", True)):
        piped[key] = _Bench(
            _build_label,
            runner_kwargs={"chain_fusion": enabled}).run()
        _family_partial({**out, "piped_fps": piped})
    f_off = piped["fusion_off"].get("fps") or 0.0
    f_on = piped["fusion_on"].get("fps") or 0.0
    piped["fps_delta_pct"] = (round((f_on - f_off) / f_off * 100, 1)
                              if f_off else 0.0)
    out["piped_fps"] = piped
    _family_partial(out)
    # tracer cost A/B: the same fused pipeline with the Tracer ON.
    # fusion_on above IS the tracer-off arm (runner default NULL_TRACER
    # — tests/test_tracing.py pins that arm's hot path does zero
    # tracing work), so the delta prices record_process + ring appends
    # per frame. trace_overhead_pct also lands in the env snapshot:
    # any artifact produced with tracing accidentally enabled carries
    # the discount factor its FPS numbers need.
    piped["traced"] = _Bench(
        _build_label,
        runner_kwargs={"chain_fusion": True, "trace": True}).run()
    f_tr = piped["traced"].get("fps") or 0.0
    piped["trace_overhead_pct"] = (round((f_on - f_tr) / f_on * 100, 1)
                                   if f_on else 0.0)
    _family_partial(out)
    # device-profiler cost A/B: fusion_on again with the devprof plane
    # ON (tracer still NULL) — prices the hot path's enabled check +
    # thread-local dispatch stamp + sample_sync per forced sync, plus
    # the one-off compile capture. The plane must stay under 2%;
    # devprof_overhead_pct lands in the env snapshot next to
    # trace_overhead_pct so any artifact produced with the plane on
    # carries its own discount factor.
    from nnstreamer_tpu.runtime import devprof as _devprof

    prof = _devprof.get()
    prof.reset()
    prof.enable(True)
    try:
        piped["devprof_on"] = _Bench(
            _build_label, runner_kwargs={"chain_fusion": True}).run()
        st = prof.stats()
        piped["devprof_on"]["devprof_evidence"] = {
            "compiles_total": st["compiles_total"],
            "invoke_buckets": len(st["invoke"]),
            "samples_total": sum(r["samples_total"]
                                 for r in st["invoke"]),
        }
    finally:
        prof.enable(False)
        prof.reset()
    f_dp = piped["devprof_on"].get("fps") or 0.0
    piped["devprof_overhead_pct"] = (round((f_on - f_dp) / f_on * 100, 1)
                                     if f_on else 0.0)
    piped["devprof_overhead_ok"] = piped["devprof_overhead_pct"] < 2.0
    _family_partial(out)
    # compiled-loop A/B: fusion_on above already runs with the
    # steady-state compiled loop ON ([runtime] compiled_loop defaults
    # true), so this arm turns it OFF and the delta prices the
    # per-frame Python the lax.scan window amortizes — dispatch
    # decision, tracer stamps, sync-window bookkeeping.
    # loop_overhead_pct is the throughput fraction the per-frame path
    # gives up; it lands in the env snapshot so any artifact produced
    # with compiled_loop=false carries its own discount factor.
    piped["loop_off"] = _Bench(
        _build_label,
        runner_kwargs={"chain_fusion": True,
                       "compiled_loop": False}).run()
    f_lo = piped["loop_off"].get("fps") or 0.0
    piped["loop_overhead_pct"] = (round((f_on - f_lo) / f_on * 100, 1)
                                  if f_on else 0.0)
    _family_partial(out)
    # raw vs piped: the same model invoked straight on the backend with
    # no scheduler in the way — the denominator of the 100x host-path
    # gap (BENCH_r05: ~34k fps raw vs ~309 piped). piped_over_raw → 1.0
    # as segment compilation + async dispatch close the gap.
    out["raw_invoke"] = _raw_invoke_fps()
    raw_fps = out["raw_invoke"].get("fps") or 0.0
    ratio = round(f_on / raw_fps, 4) if raw_fps else 0.0
    out["piped_over_raw"] = ratio
    # env-tunable regression gate (BENCH_HOSTPATH_RATIO_GATE pattern ==
    # BENCH_ENV_D2H_GATE_MS: <=0 disables). On by default at 0.5 now
    # that the compiled loop holds piped within 2x of raw at the knee;
    # export =0 on hosts where the ratio means nothing (no accelerator).
    gate = float(os.environ.get("BENCH_HOSTPATH_RATIO_GATE", "0.5"))
    if gate > 0:
        out["ratio_gate"] = gate
        out["ratio_gate_ok"] = ratio >= gate
        if not out["ratio_gate_ok"]:
            out["errors"] = {"ratio_gate": (
                f"piped_over_raw {ratio} below the "
                f"BENCH_HOSTPATH_RATIO_GATE={gate} floor — the host "
                f"path is re-opening the raw-vs-piped gap")}
    _family_partial(out)
    # same-host transport A/B: one pooled echo hop moving a 64 KiB
    # payload, shm ring lane vs pickle+pipe. Reported, never gated —
    # but shm_ok documents the lane earning its keep.
    try:
        out["shm_transport"] = _shm_hop_ab()
    except Exception as e:
        out["shm_transport"] = {"error": f"{type(e).__name__}: {e}"}
    _family_partial(out)
    # cross-framework point (arXiv 2210.04323 discipline: same model,
    # same open-loop trace): ours vs the plain for-loop serving script
    # in tools/serving_baseline.py. Reported, never gated — it's a
    # comparison point, not an invariant.
    try:
        spec2 = importlib.util.spec_from_file_location(
            "serving_baseline",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools", "serving_baseline.py"))
        sb = importlib.util.module_from_spec(spec2)
        spec2.loader.exec_module(sb)
        out["cross_framework"] = sb.run_ab(
            n=128 if _on_tpu() else 64, small=not _on_tpu())
    except Exception as e:
        out["cross_framework"] = {"error": f"{type(e).__name__}: {e}"}
    return out


def _raw_invoke_fps(iters: int = None) -> dict:
    """Raw async device invoke FPS of the label model (one frame per
    invoke, block once at the end) — what the chip does with zero
    scheduler/host overhead."""
    import jax
    import numpy as np

    from nnstreamer_tpu.backends.xla import XLABackend

    model = (MOBILENET_TFLITE if os.path.exists(MOBILENET_TFLITE)
             else "zoo://mobilenet_v2")
    if iters is None:
        iters = 512 if _on_tpu() else 16
    be = XLABackend()
    try:
        be.open({"model": model, "custom": ""})
        frame = np.random.default_rng(0).integers(
            0, 256, (1, 224, 224, 3), np.uint8)
        out = be.invoke((frame,))
        jax.block_until_ready(tuple(out))          # compile outside
        t0 = time.perf_counter()
        for _ in range(iters):
            out = be.invoke((frame,))
        jax.block_until_ready(tuple(out))
        dt = time.perf_counter() - t0
    finally:
        be.close()
    return {"fps": round(iters / dt, 2), "frames": iters}


def _shm_hop_ab() -> dict:
    """Same-host transport A/B, two layers. `hop` is the closed-loop
    parent↔child round-trip with nothing else on the clock
    (serving/shm.py hop_latency_ab — pickle+pipe vs shm ring + pipe
    control), which is where the lane must win. The pooled arms drive
    a 1-worker echo pool through the full serving path with the lane
    off then on; equal-work arms (same arrival trace, same payload),
    and hop_bytes_per_frame comes from the pool's own shm ledger — the
    bytes that actually rode shared memory, not the nominal payload."""
    import numpy as np

    from nnstreamer_tpu.serving.pool import PooledQueryServer
    from nnstreamer_tpu.serving.shm import hop_latency_ab, shm_supported
    from nnstreamer_tpu.tensor.buffer import TensorBuffer
    from nnstreamer_tpu.traffic import poisson_arrivals, run_open_loop

    n = 240 if _on_tpu() else 60
    x = np.arange(16384, dtype=np.float32).reshape(16384, 1)
    out: dict = {"payload_bytes": int(x.nbytes),
                 "frames": n,
                 "supported": shm_supported()}
    # n floor matters: under ~150 round trips the p50 is scheduler
    # noise, not the lane (measured: n=60 flips the verdict run to run)
    out["hop"] = hop_latency_ab(n=300 if _on_tpu() else 150)
    arrivals = poisson_arrivals(300.0, n)
    for key, enabled in (("pipe", False), ("shm", True)):
        pqs = PooledQueryServer.echo(
            workers=1, service_ms=0.0, dims="16384:1",
            sid=91 + int(enabled), max_pending=256,
            shm_transport=enabled)
        try:
            rep = run_open_loop(
                "127.0.0.1", pqs.port, dims="16384:1",
                arrivals=arrivals,
                make_frame=lambda i: TensorBuffer.of(x, pts=i),
                p99_budget_ms=1000.0)
            st = pqs.pool.stats()["pool"]
            arm = {
                "completed": rep["completed"],
                "lost": rep["lost"],
                "throughput_rps": rep["throughput_rps"],
                "p50_ms": rep.get("latency_ms", {}).get("p50"),
                "p99_ms": rep.get("latency_ms", {}).get("p99"),
                "shm_frames": st["shm_frames"],
                "shm_bytes": st["shm_bytes"],
                "shm_fallbacks": st["shm_fallbacks"],
            }
            if st["shm_frames"]:
                arm["hop_bytes_per_frame"] = round(
                    st["shm_bytes"] / st["shm_frames"], 1)
            out[key] = arm
        finally:
            pqs.close()
    out["hop_speedup"] = out["hop"].get("hop_speedup")
    out["shm_ok"] = bool(out["hop"].get("shm_ok"))
    return out


# -- LLM serving (docs/llm_serving.md) ---------------------------------------

#: p99 completion budget (ms) the goodput metric gates on — a request
#: counts toward goodput only if it finished inside this budget
LLM_P99_BUDGET_MS = float(os.environ.get("BENCH_LLM_P99_BUDGET_MS",
                                         "4000"))


def _llm_serve_arm(scheduling: str, arrivals, prompts,
                   max_news, llm_props=None) -> dict:
    """One open-loop serving run: requests are pushed at their PRE-DRAWN
    Poisson arrival times regardless of completions (closed-loop pushing
    would let a slow server throttle its own offered load and flatter
    its tail). Both arms replay the identical arrival trace. prewarm=
    compiles every bucket at start(), before the clock starts — the
    arms compare scheduling policy, not compile luck. `llm_props`
    overrides/extends the tensor_llm properties (the attn point swaps
    paged_kernel / prefill_chunk on an otherwise identical server)."""
    import threading

    import numpy as np

    import nnstreamer_tpu as nns
    from nnstreamer_tpu.elements import AppSrc, TensorLLM, TensorSink
    from nnstreamer_tpu.tensor.buffer import TensorBuffer
    from nnstreamer_tpu.tensor.info import TensorFormat, TensorsSpec

    props = dict(model="store://transformer", scheduling=scheduling,
                 max_batch=8, block_size=16, num_blocks=96, max_len=128,
                 prewarm=max(len(p) for p in prompts))
    props.update(llm_props or {})
    src = AppSrc(name="src", spec=TensorsSpec(
        tensors=(), format=TensorFormat.FLEXIBLE))
    llm = TensorLLM(name="llm", **props)
    done_at: dict = {}
    tokens_recv = [0]
    lock = threading.Lock()

    def on_chunk(buf):
        m = buf.meta["llm"]
        with lock:
            tokens_recv[0] += int(np.asarray(buf.tensors[0]).shape[0])
            if m["done"]:
                done_at[m["request_id"]] = time.perf_counter()

    sink = TensorSink(name="sink", new_data=on_chunk)
    pipe = nns.Pipeline(f"llm_{scheduling}")
    for e in (src, llm, sink):
        pipe.add(e)
    pipe.link(src, llm)
    pipe.link(llm, sink)
    runner = nns.PipelineRunner(pipe)
    runner.start()
    t0 = time.perf_counter()
    submit_at = {}
    for i, (t_arr, prompt, mnew) in enumerate(
            zip(arrivals, prompts, max_news)):
        now = time.perf_counter() - t0
        if t_arr > now:
            time.sleep(t_arr - now)
        rid = f"r{i}"
        submit_at[rid] = time.perf_counter()
        src.push(TensorBuffer(
            tensors=(prompt,), pts=i,
            meta={"llm": {"request_id": rid,
                          "max_new_tokens": int(mnew)}}))
    src.end()
    runner.wait(240)
    elapsed = time.perf_counter() - t0
    runner.stop()
    lat_ms = sorted((done_at[r] - submit_at[r]) * 1e3
                    for r in submit_at if r in done_at)
    stats = llm.extra_stats()
    within = sum(1 for v in lat_ms if v <= LLM_P99_BUDGET_MS)
    out = {
        "scheduling": scheduling,
        "requests": len(submit_at),
        "completed": len(lat_ms),
        "tokens_out": tokens_recv[0],
        "tokens_per_s": round(tokens_recv[0] / elapsed, 1),
        "elapsed_s": round(elapsed, 2),
        "p99_budget_ms": LLM_P99_BUDGET_MS,
        "goodput_rps": round(within / elapsed, 3),
        "first_token_ms": stats.get("first_token_ms", {}),
        "inter_token_ms": stats.get("inter_token_ms", {}),
        "admission_blocked": stats.get("admission_blocked", 0),
        "kv_blocks_high_water": stats.get("cache", {}).get(
            "blocks_high_water", 0),
        "executor": stats.get("executor", {}),
    }
    if lat_ms:
        out["completion_ms"] = {
            "p50": round(_pctl(lat_ms, 50), 1),
            "p95": round(_pctl(lat_ms, 95), 1),
            "p99": round(_pctl(lat_ms, 99), 1),
            "max": round(lat_ms[-1], 1)}
    return out


def _pctl(sorted_vals, p):
    from nnstreamer_tpu.runtime.tracing import percentile

    return percentile(sorted_vals, p)


def llm_serve() -> dict:
    """Continuous-batching LLM serving family: tokens/s + per-request
    p99 under open-loop Poisson arrivals through the tensor_llm element
    (store://transformer), continuous vs static batching on the SAME
    pre-drawn arrival trace. The continuous arm must win on goodput at
    the fixed p99 budget: static batching's run-to-completion admission
    makes late arrivals wait a full batch generation, which is exactly
    the head-of-line blocking the paged engine removes."""
    import numpy as np

    n_req = 32 if _on_tpu() else 16
    rng = np.random.default_rng(1234)
    # open-loop offered load: mean inter-arrival well under one batch's
    # full generation time, so admission pressure actually happens.
    # Token budgets are deliberately heterogeneous (8..64): a static
    # batch holds every slot until its LONGEST member finishes, which is
    # the head-of-line blocking continuous batching exists to remove —
    # uniform budgets would hide the effect entirely.
    arrivals = np.cumsum(rng.exponential(0.02, size=n_req))
    prompts = [rng.integers(0, 256, size=int(rng.integers(2, 24)))
               .astype(np.int32) for _ in range(n_req)]
    max_news = [8 if i % 4 else 64 for i in range(n_req)]
    out = {"n_requests": n_req,
           "max_new_tokens": sorted(set(max_news))}
    for sched in ("continuous", "static"):
        out[sched] = _llm_serve_arm(sched, arrivals, prompts, max_news)
        _family_partial(dict(out))
    cont, stat = out["continuous"], out["static"]
    out["goodput_win"] = cont["goodput_rps"] >= stat["goodput_rps"]
    out["tokens_per_s_ratio"] = round(
        cont["tokens_per_s"] / stat["tokens_per_s"], 2) \
        if stat["tokens_per_s"] else 0.0
    if not out["goodput_win"]:
        out["unverified"] = True   # ship the numbers, flag the claim
    # paged-kernel point: pallas vs xla on one trace with a long prompt
    # chunk-prefilling under the decode batch. On CPU (interpret-mode
    # Pallas is orders slower than XLA) it is a conservation/parity
    # gate behind BENCH_LLM_ATTN_GATE=1; on TPU it always runs and the
    # ratio is the measurement.
    if os.environ.get("BENCH_LLM_ATTN_GATE") == "1" or _on_tpu():
        out["attn"] = _llm_attn_point(arrivals, prompts, max_news)
        _family_partial(dict(out))
        if not out["attn"]["zero_lost"]:
            out["unverified"] = True
    return out


def _llm_attn_point(arrivals, prompts, max_news) -> dict:
    """pallas-vs-xla serving arms on one arrival trace: identical
    requests plus one long prompt injected at t=0 so chunked prefill
    (prefill_chunk=32) runs concurrently with live decodes. Gate:
    both arms lose zero requests and emit the same token count (no
    EOS ⇒ the count is deterministic); the decode tokens/s ratio is
    the recorded measurement for on-chip runs."""
    import numpy as np

    rng = np.random.default_rng(99)
    long_prompt = rng.integers(0, 256, size=96).astype(np.int32)
    prompts2 = [long_prompt] + list(prompts)
    arrivals2 = [0.0] + [float(a) + 0.05 for a in arrivals]
    max_news2 = [16] + list(max_news)
    res = {"prefill_chunk": 32, "long_prompt_len": 96}
    for kern in ("xla", "pallas"):
        arm = _llm_serve_arm(
            "continuous", arrivals2, prompts2, max_news2,
            llm_props={"paged_kernel": kern, "prefill_chunk": 32})
        res[kern] = arm
        _family_partial(dict(res))
    xla, pal = res["xla"], res["pallas"]
    res["zero_lost"] = (
        xla["completed"] == xla["requests"] and
        pal["completed"] == pal["requests"] and
        xla["tokens_out"] == pal["tokens_out"])
    res["decode_tokens_per_s_ratio"] = round(
        pal["tokens_per_s"] / xla["tokens_per_s"], 3) \
        if xla["tokens_per_s"] else 0.0
    res["pallas_served"] = pal.get("executor", {}).get(
        "kernel_invokes", {})
    res["pallas_fallbacks"] = pal.get("executor", {}).get(
        "kernel_fallback", 0)
    return res


#: traffic family: fraction-of-capacity sweep points. Below-knee points
#: (<1x) should shed nothing; over-capacity points must shed and lose
#: nothing. Trimmed per-point report keys kept in the artifact.
TRAFFIC_LOADS = (0.5, 0.9, 1.5, 2.0)
_TRAFFIC_KEYS = ("offered", "completed", "rejected", "lost",
                 "offered_rate_rps", "throughput_rps", "goodput_rps",
                 "shed_rate", "queue_depth_peak", "server_crashed")


def _traffic_point(report: dict) -> dict:
    out = {k: report[k] for k in _TRAFFIC_KEYS if k in report}
    lat = report.get("latency_ms") or {}
    out["p50_ms"] = lat.get("p50", 0.0)
    out["p99_ms"] = lat.get("p99", 0.0)
    return out


def traffic_serve() -> dict:
    """Admission-control family: open-loop Poisson load against a
    bounded echo query server at fractions of its capacity, plus the
    acceptance A/B — at 2x overload the bounded server must shed (typed
    BUSY), lose nothing, not crash, and its goodput at the p99 budget
    must be >= the unbounded baseline's (whose queue wait blows the
    budget for everyone). BENCH_TRAFFIC_SHED_GATE=1 additionally
    requires zero shed below the knee (<1x points)."""
    from nnstreamer_tpu.traffic import run_against_echo

    service_ms = 5.0
    max_pending = 16
    n = 240
    # one budget for every arm so goodput numbers are comparable:
    # a full bounded queue's wait plus one service time
    budget_ms = (max_pending + 2) * service_ms
    out = {"service_ms": service_ms, "capacity_rps": 1e3 / service_ms,
           "max_pending": max_pending, "p99_budget_ms": budget_ms,
           "n_requests": n}
    for load_x in TRAFFIC_LOADS:
        r = run_against_echo(
            pattern="poisson", load_x=load_x, n=n,
            service_ms=service_ms, max_pending=max_pending,
            p99_budget_ms=budget_ms, seed=42)
        out[f"poisson_x{load_x:g}"] = _traffic_point(r)
        _family_partial(dict(out))
    out["bursty_x2"] = _traffic_point(run_against_echo(
        pattern="bursty", load_x=2.0, n=n, service_ms=service_ms,
        max_pending=max_pending, p99_budget_ms=budget_ms, seed=42))
    _family_partial(dict(out))
    # unbounded baseline for the A/B: same arrivals (same seed), a
    # queue so deep it never refuses — every request is admitted and
    # waits, so p99 explodes past the budget instead of being shed
    unb = run_against_echo(
        pattern="poisson", load_x=2.0, n=n, service_ms=service_ms,
        max_pending=100000, p99_budget_ms=budget_ms, seed=42)
    out["unbounded_x2"] = _traffic_point(unb)
    bnd = out["poisson_x2"]
    out["overload_shed"] = bnd["shed_rate"] > 0
    out["overload_lost"] = bnd["lost"]
    out["overload_crashed"] = bnd["server_crashed"]
    out["goodput_win"] = bnd["goodput_rps"] >= unb["goodput_rps"]
    if not (out["overload_shed"] and out["goodput_win"]
            and bnd["lost"] == 0 and not bnd["server_crashed"]):
        out["unverified"] = True   # ship the numbers, flag the claim
    if os.environ.get("BENCH_TRAFFIC_SHED_GATE") == "1":
        below_knee_shed = sum(
            out[f"poisson_x{x:g}"]["rejected"]
            for x in TRAFFIC_LOADS if x < 1.0)
        out["shed_gate_ok"] = below_knee_shed == 0
        if not out["shed_gate_ok"]:
            out["unverified"] = True
    # worker-kill acceptance point: a 2-worker pool at 1.5x its
    # aggregate capacity takes a SIGKILL mid-flood. Gate: zero lost
    # frames (every one replied or typed-BUSY), conservation exact,
    # back at full capacity within the restart budget, zero orphan
    # processes, and pool goodput at the 90ms p99 budget >= a
    # single-process server facing the same absolute offered rate
    # (for which that rate is 3x capacity)
    from nnstreamer_tpu.traffic import run_against_pool

    pool_ms = 20.0
    kill = run_against_pool(
        pattern="poisson", load_x=1.5, n=240, service_ms=pool_ms,
        workers=2, max_pending=32, p99_budget_ms=90.0, seed=42,
        kills=1)
    pt = _traffic_point(kill)
    pt.update({k: kill[k] for k in (
        "recovered", "recovery_s", "conserved", "kill_schedule",
        "seed")})
    pt["orphans"] = len(kill["orphans"])
    pt["restarts"] = kill["pool"]["pool"]["restarts"]
    out["worker_kill_x1.5"] = pt
    _family_partial(dict(out))
    single = run_against_echo(
        pattern="poisson", load_x=3.0, n=240, service_ms=pool_ms,
        max_pending=32, p99_budget_ms=90.0, seed=42)
    out["single_proc_same_rate"] = _traffic_point(single)
    out["kill_goodput_win"] = (
        pt["goodput_rps"] >=
        out["single_proc_same_rate"]["goodput_rps"])
    if not (kill["lost"] == 0 and kill["recovered"]
            and kill["conserved"] and not kill["orphans"]
            and out["kill_goodput_win"]):
        out["unverified"] = True   # ship the numbers, flag the claim
    # mesh partition acceptance point (BENCH_TRAFFIC_MESH_GATE=1; off
    # by default — it spins 2 pool hosts + a chaos proxy and its
    # lease-expiry wait adds wall time): blackhole one of two hosts
    # mid-flood at 1.5x aggregate capacity. Gate: zero lost, per-host
    # conservation exact, fence within 2x the lease, and at least one
    # cross-host redelivery carrying a single trace id (the frame's
    # story survives the failover).
    if os.environ.get("BENCH_TRAFFIC_MESH_GATE") == "1":
        from nnstreamer_tpu.traffic import run_against_mesh

        mesh = run_against_mesh(
            hosts=2, workers_per_host=1, pattern="poisson",
            load_x=1.5, n=240, service_ms=pool_ms, max_pending=64,
            p99_budget_ms=250.0, seed=42, lease_s=1.0,
            max_redeliver=2)
        mpt = _traffic_point(mesh)
        mpt.update({k: mesh[k] for k in (
            "recovered", "fence_detect_s", "conserved",
            "redelivered", "perhost_replied_sum", "seed")
            if k in mesh})
        mpt["orphans"] = len(mesh["orphans"])
        mpt["cross_host_trace"] = any(
            len(ex.get("hosts", [])) >= 2
            for ex in mesh.get("redelivered_examples", []))
        out["mesh_blackhole_x1.5"] = mpt
        out["mesh_gate_ok"] = (
            mesh["lost"] == 0 and mesh["conserved"]
            and mesh.get("recovered", False)
            and not mesh["orphans"] and mpt["cross_host_trace"])
        if not out["mesh_gate_ok"]:
            out["unverified"] = True   # ship the numbers, flag it
        _family_partial(dict(out))
    return out


def autotune_serve() -> dict:
    """SLO-autotuner family (docs/autotune.md): the same open-loop
    Poisson ramp (0.5→2.5x capacity, same seed → same arrival trace)
    twice against a bounded echo server whose hand-set max_pending is
    deliberately too deep for the declared p99 budget — once static,
    once with the closed-loop controller live. Claims checked (flagged
    `unverified`, never raised; BENCH_AUTOTUNE_GATE=1 records the gate
    verdict explicitly): tuned goodput >= static on the same trace,
    tuned p99 within the declared budget, zero lost either arm,
    admission conservation exact immediately after every applied knob
    change, and every applied decision present in the audit ring."""
    from nnstreamer_tpu.traffic import run_autotune_ramp

    kw = dict(n_per_step=120, service_ms=5.0, static_max_pending=64,
              seed=42)
    static = run_autotune_ramp(tuned=False, **kw)
    out = {"p99_budget_ms": static["p99_budget_ms"],
           "capacity_rps": static["capacity_rps"],
           "ramp": static["ramp"],
           "static_max_pending": static["static_max_pending"],
           "seed": static["seed"],
           "static": _traffic_point(static)}
    _family_partial(dict(out))
    tuned = run_autotune_ramp(tuned=True, **kw)
    tpt = _traffic_point(tuned)
    st = tuned["autotune"]
    tpt["decisions_applied"] = st["applied_total"]
    tpt["decisions"] = st["decisions"]
    tpt["knobs_final"] = st["knobs"]
    out["tuned"] = tpt
    out["goodput_win"] = (
        tpt["goodput_rps"] >= out["static"]["goodput_rps"])
    out["p99_within_budget"] = (
        tpt["p99_ms"] <= tuned["p99_budget_ms"])
    out["conservation_after_apply_ok"] = all(
        tuned.get("conservation_after_apply") or [True])
    out["conservation_final"] = tuned["conservation_final"]
    applied_in_audit = sum(
        1 for r in tuned["audit"] if r["outcome"] == "applied")
    out["audit_complete"] = (
        applied_in_audit == st["applied_total"]
        and st["audit_dropped"] == 0)
    ok = (out["goodput_win"] and out["p99_within_budget"]
          and static["lost"] == 0 and tuned["lost"] == 0
          and out["conservation_after_apply_ok"]
          and out["conservation_final"] and out["audit_complete"]
          and st["applied_total"] > 0
          and not tuned["server_crashed"])
    out["autotune_ok"] = ok
    if not ok:
        out["unverified"] = True   # ship the numbers, flag the claim
    if os.environ.get("BENCH_AUTOTUNE_GATE") == "1":
        out["autotune_gate_ok"] = ok
    _family_partial(dict(out))
    return out


def multitenant_serve() -> dict:
    """Multi-tenant isolation family: a weighted-fair (WFQ) admission
    front over a 2-worker pool, one victim tenant at 0.5x its fair
    share and one flooding tenant at 1x then 3x. Reported per point:
    aggregate goodput plus each tenant's goodput / shed rate / p99.
    BENCH_TRAFFIC_TENANT_GATE=1 additionally runs the noisy-neighbor
    acceptance drill (solo-victim baseline vs contested) and gates on
    victim goodput >= 0.9x solo, victim p99 within its deadline, shed
    attributed to the flooder (tenant_over_share), conservation exact
    per class and summed, and zero lost."""
    from nnstreamer_tpu.traffic import noisy_neighbor_drill, \
        run_multitenant

    service_ms = 8.0
    workers = 2
    max_pending = 24
    budget_ms = (max_pending + 2) * service_ms
    capacity = workers * 1e3 / service_ms
    tenants = {"victim": {"weight": 1.0, "deadline_ms": budget_ms},
               "flood": {"weight": 1.0, "deadline_ms": budget_ms}}
    out = {"service_ms": service_ms, "workers": workers,
           "max_pending": max_pending, "p99_budget_ms": budget_ms,
           "capacity_rps": capacity}

    def _tenant_point(r: dict) -> dict:
        pt = {"goodput_rps": r["goodput_rps"], "lost": r["lost"],
              "conserved": r["conserved"]}
        for name, g in r["groups"].items():
            lat = g.get("latency_ms") or {}
            pt[name] = {"goodput_rps": g["goodput_rps"],
                        "shed_rate": g["shed_rate"],
                        "p99_ms": lat.get("p99", 0.0)}
        return pt

    victim_rate = 0.5 * capacity / 2
    for flood_x in (1.0, 3.0):
        flood_rate = flood_x * capacity / 2
        n_victim = 80
        n_flood = max(1, int(round(n_victim / victim_rate
                                   * flood_rate)))
        r = run_multitenant(
            tenants=tenants,
            n_per_tenant={"victim": n_victim, "flood": n_flood},
            rate_hz={"victim": victim_rate, "flood": flood_rate},
            workers=workers, service_ms=service_ms,
            max_pending=max_pending, p99_budget_ms=budget_ms,
            seed=42)
        out[f"flood_x{flood_x:g}"] = _tenant_point(r)
        _family_partial(dict(out))
    if os.environ.get("BENCH_TRAFFIC_TENANT_GATE") == "1":
        drill = noisy_neighbor_drill(
            victim_x=0.5, flood_x=3.0, n_victim=80,
            workers=workers, service_ms=service_ms,
            max_pending=max_pending, seed=42)
        flood_cont = drill["contested"]["groups"]["flood"]
        out["drill"] = {
            "victim_goodput_ratio": drill["victim_goodput_ratio"],
            "victim_p99_ms": drill["victim_p99_ms"],
            "victim_p99_budget_ms": drill["victim_p99_budget_ms"],
            "flood_shed_rate": flood_cont["shed_rate"],
            "flood_busy_causes": flood_cont["busy_causes"],
            "conserved": drill["conserved"],
            "zero_lost": drill["zero_lost"],
        }
        p99 = drill["victim_p99_ms"]
        out["tenant_gate_ok"] = (
            drill["victim_goodput_ratio"] >= 0.9
            and p99 is not None
            and p99 <= drill["victim_p99_budget_ms"]
            and set(flood_cont["busy_causes"]) <= {"tenant_over_share"}
            and drill["conserved"] and drill["zero_lost"])
        if not out["tenant_gate_ok"]:
            out["unverified"] = True   # ship the numbers, flag it
        _family_partial(dict(out))
    return out


def scenario_serve() -> dict:
    """Adversarial scenario family (nnstreamer_tpu/scenario): seeded
    declarative world drills with ONE property checker (four standing
    invariants from one scrape) and bit-exact replay. Always runs the
    pool drills from the builtin catalog (smoke + worker-kill) and a
    replay of the smoke run. BENCH_SCENARIO_GATE=1 additionally runs
    the composed mesh storm — flash-crowd × blackhole-then-heal ×
    swap-storm × tenant-flood under one root seed — and gates on zero
    lost, all four invariants, recovery, and replay totals matching
    the first run exactly."""
    from nnstreamer_tpu.scenario import (
        builtin_specs, replay_scenario, run_scenario)

    specs = builtin_specs()
    out: dict = {}

    def _point(r: dict) -> dict:
        check = r.get("check") or {}
        return {"totals": r["totals"],
                "capacity_rps": r["capacity_rps"],
                "invariants": check.get("invariants"),
                "ok": check.get("ok"),
                "recovered": r["report"].get("recovered"),
                "violations": check.get("violations") or []}

    r_smoke = run_scenario(specs["smoke_pool"])
    out["smoke_pool"] = _point(r_smoke)
    _family_partial(dict(out))
    rep = replay_scenario(r_smoke)
    out["smoke_replay"] = {"replay_match": rep.get("replay_match"),
                           "replay_diff": rep.get("replay_diff")}
    _family_partial(dict(out))
    r_kill = run_scenario(specs["kill_pool"])
    out["kill_pool"] = _point(r_kill)
    out["scenario_ok"] = bool(
        out["smoke_pool"]["ok"] and out["kill_pool"]["ok"]
        and out["smoke_replay"]["replay_match"])
    if not out["scenario_ok"]:
        out["unverified"] = True   # ship the numbers, flag the claim
    _family_partial(dict(out))
    if os.environ.get("BENCH_SCENARIO_GATE") == "1":
        r1 = run_scenario(specs["composed_storm"])
        out["composed_storm"] = _point(r1)
        _family_partial(dict(out))
        r2 = replay_scenario(r1)
        out["composed_replay"] = {
            "replay_match": r2.get("replay_match"),
            "replay_diff": r2.get("replay_diff")}
        c1 = r1.get("check") or {}
        out["scenario_gate_ok"] = bool(
            c1.get("ok") and r1["totals"]["lost"] == 0
            and all((c1.get("invariants") or {}).values())
            and r1["report"].get("recovered")
            and r2.get("replay_match"))
        if not out["scenario_gate_ok"]:
            out["unverified"] = True   # ship the numbers, flag it
        _family_partial(dict(out))
    return out


def multichip_serve() -> dict:
    """Multi-chip placement family (serving/placement.py), on the
    8-device emulated host mesh (_family_main forces JAX_PLATFORMS=cpu
    + --xla_force_host_platform_device_count=8 for this family BEFORE
    jax loads — real-chip numbers belong to a future multi-TPU rig).

    Two placements measured: (a) data-parallel replicas at 1/2/4/8
    devices — throughput ratio vs the 1-device baseline plus exact
    conservation and bit-parity checks; (b) a profiled segmented
    3-filter pipeline vs the same pipeline unsegmented — throughput
    ratio, planned bubble fraction, and output parity (the MULTICHIP
    dryrun tolerance, max_abs_err <= 1e-6). Host-emulated devices are
    threads on one CPU, so the scaling ratios measure dispatch-path
    overheads, not chip speedup; the correctness checks are exact
    either way. BENCH_MULTICHIP_GATE=1 gates on parity+conservation
    (never on the emulated ratios)."""
    import numpy as np

    from nnstreamer_tpu import PipelineRunner, TensorBuffer, parse_launch
    from nnstreamer_tpu.backends.xla import ModelBundle
    from nnstreamer_tpu.serving.placement import (
        ReplicaSet, plan_from_tracer, visible_devices)
    from nnstreamer_tpu.serving.store import reset_store

    ndev = len(visible_devices())
    out: dict = {"visible_devices": ndev}
    rng = np.random.default_rng(7)
    dim, batch, frames = 192, 8, 160
    w1 = rng.normal(size=(dim, dim)).astype(np.float32) / np.sqrt(dim)
    w2 = rng.normal(size=(dim, dim)).astype(np.float32) / np.sqrt(dim)

    def _mlp(params, x):
        import jax.numpy as jnp

        h = jnp.maximum(x @ params["w1"], 0.0)
        return (h @ params["w2"],)

    bundle = ModelBundle(fn=_mlp, params={"w1": w1, "w2": w2},
                         name="mc_mlp")
    x = rng.normal(size=(batch, dim)).astype(np.float32)

    # (a) dp replicas: scaling efficiency + exact parity/conservation
    dp: dict = {}
    base_fps = None
    base_out = None
    parity_exact = True
    conserved = True
    for n in [d for d in (1, 2, 4, 8) if d <= ndev]:
        rs = ReplicaSet.open("xla", {"model": bundle, "custom": ""}, n,
                             queue_cap=frames + n, name=f"bench-dp{n}")
        try:
            for _ in range(n):          # warm every replica's jit
                rs.invoke((x,))
            t0 = time.perf_counter()
            futs = [rs.submit((x,)) for _ in range(frames)]
            outs = [f.result(60.0) for f in futs]
            dt = time.perf_counter() - t0
            st = rs.stats()
        finally:
            rs.close()
        fps = frames / dt if dt > 0 else 0.0
        if base_out is None:
            base_out = np.asarray(outs[0][0])
        parity_exact &= all(
            np.array_equal(np.asarray(o[0]), base_out) for o in outs)
        conserved &= (sum(r["invokes"] for r in st["replicas"])
                      == frames + n)
        if base_fps is None:
            base_fps = fps
        dp[f"devices_{n}"] = {
            "fps": round(fps, 1),
            "scaling_ratio": round(fps / base_fps, 3) if base_fps else 0.0,
            "per_chip_invokes": [r["invokes"] for r in st["replicas"]],
        }
        out["dp"] = dict(dp, parity_exact=parity_exact,
                         conserved=conserved)
        _family_partial(dict(out))

    # (b) profiled segmentation: plan from a traced run, then compare
    store = reset_store()
    store.register("mc_s1", lambda x: (x @ w1,))
    store.register("mc_s2", lambda x: (np.float32(1.0) * x,))  # light
    store.register("mc_s3", lambda x: (x @ w2,))

    xv = x[0].copy()                    # (dim,) vector frames

    def _seg_pipe():
        return parse_launch(
            f"appsrc name=src dims={dim} types=float32 ! "
            "tensor_filter name=s1 model=store://mc_s1 ! "
            "tensor_filter name=s2 model=store://mc_s2 ! "
            "tensor_filter name=s3 model=store://mc_s3 ! "
            "tensor_sink name=out")

    def _run(pipe, trace, segments=True):
        # the profile pass keeps every filter separate (segments=False)
        # so the tracer sees per-element proctime, not one fused row
        runner = PipelineRunner(pipe, trace=trace,
                                device_segments=segments)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        t0 = time.perf_counter()
        try:
            for i in range(frames):
                src.push(TensorBuffer.of(xv + np.float32(i), pts=i))
            src.end()
            runner.wait(120)
        finally:
            runner.stop()
        dt = time.perf_counter() - t0
        res = {int(b.pts): np.asarray(b.tensors[0])
               for b in sink.results}
        return res, frames / dt if dt > 0 else 0.0, runner

    base_res, base_seg_fps, runner = _run(_seg_pipe(), trace=True,
                                          segments=False)
    names = [n for n in ("s1", "s2", "s3")]
    plan = plan_from_tracer(runner.tracer, names, min(ndev, 4))
    pipe = _seg_pipe()
    from nnstreamer_tpu.serving.placement import apply_plan

    apply_plan(pipe, plan)
    seg_res, seg_fps, _ = _run(pipe, trace=False)
    err = 0.0
    for pts, ref in base_res.items():
        got = seg_res.get(pts)
        if got is None:
            err = float("inf")
            break
        err = max(err, float(np.max(np.abs(got - ref))))
    out["segmented"] = {
        "stages": plan.report()["stages"],
        "bubble_fraction": round(plan.bubble_fraction, 4),
        "unsegmented_fps": round(base_seg_fps, 1),
        "segmented_fps": round(seg_fps, 1),
        "throughput_ratio": round(seg_fps / base_seg_fps, 3)
        if base_seg_fps else 0.0,
        "max_abs_err": err,
        "frames": frames,
    }
    _family_partial(dict(out))

    if os.environ.get("BENCH_MULTICHIP_GATE") == "1":
        out["multichip_gate_ok"] = bool(
            parity_exact and conserved and err <= 1e-6)
        if not out["multichip_gate_ok"]:
            out["unverified"] = True   # ship the numbers, flag the claim
    return out


def sharded_serve() -> dict:
    """Sharded-serving family (serving/sharding.py), on the 8-device
    emulated host mesh (_family_main forces the same env as multichip
    BEFORE jax loads). Three sections: (a) paged LLM decode tokens/s +
    prefill latency at shards 1/2/4/8 with the bit-parity check vs the
    shards=1 blocked reference (the canonical-blocking contract);
    (b) ring prefill latency vs blocked at the same width on a long
    prompt (allclose, not exact — different attention order by design);
    (c) the dense ShardedReplicaSet conservation drill: frames through
    2 groups of 2 chips with ONE member chip fenced mid-stream —
    Σ group invokes must equal frames exactly. Emulated devices are
    host threads, so the per-width ratios measure the shard_map
    dispatch path, not chip speedup; BENCH_SHARDED_GATE=1 gates on
    exact parity + conservation, never on the emulated ratios."""
    import numpy as np

    from nnstreamer_tpu.backends.llm_exec import PagedLLMExecutor
    from nnstreamer_tpu.backends.xla import ModelBundle
    from nnstreamer_tpu.models.transformer import init_params
    from nnstreamer_tpu.serving.placement import visible_devices
    from nnstreamer_tpu.serving.sharding import ShardedReplicaSet

    ndev = len(visible_devices())
    out: dict = {"visible_devices": ndev}
    params = init_params(d_model=64, n_heads=8, n_layers=2, vocab=256)
    rng = np.random.default_rng(11)
    prompt = rng.integers(1, 256, size=24).astype(np.int32)
    decode_steps = 48

    # (a) decode tokens/s + prefill latency per shard width, bit-parity
    widths: dict = {}
    ref_logits = None
    parity_exact = True
    base_tps = None
    for n in [s for s in (1, 2, 4, 8) if s <= ndev]:
        ex = PagedLLMExecutor(dict(params), n_heads=8, block_size=8,
                              num_blocks=16, max_len=128, shards=n,
                              name=f"bench-tp{n}")
        try:
            blocks = ex.cache.allocator.alloc(ex.cache.blocks_for(
                len(prompt)))
            t0 = time.perf_counter()
            lg = ex.prefill(prompt, blocks)
            prefill_ms = (time.perf_counter() - t0) * 1e3
            logits = [np.asarray(lg)]
            tok = int(np.argmax(lg))
            pos = len(prompt)
            t0 = time.perf_counter()
            for _ in range(decode_steps):
                dl = ex.decode([tok], [blocks], [pos])
                logits.append(np.asarray(dl[0]))
                tok = int(np.argmax(dl[0]))
                pos += 1
            dt = time.perf_counter() - t0
        finally:
            ex.close()
        tps = decode_steps / dt if dt > 0 else 0.0
        if ref_logits is None:
            ref_logits = logits          # shards=1: the blocked reference
            base_tps = tps
        else:
            parity_exact &= all(
                np.array_equal(a, b) for a, b in zip(logits, ref_logits))
        widths[f"shards_{n}"] = {
            "decode_tokens_per_s": round(tps, 1),
            "prefill_ms": round(prefill_ms, 1),
            "ratio_vs_shards1": round(tps / base_tps, 3)
            if base_tps else 0.0,
        }
        out["llm"] = dict(widths, parity_exact_vs_shards1=parity_exact)
        _family_partial(dict(out))

    # (b) ring prefill vs blocked at shards=2 on the same long prompt
    ring_ok = True
    if ndev >= 2:
        exr = PagedLLMExecutor(dict(params), n_heads=8, block_size=8,
                               num_blocks=16, max_len=128, shards=2,
                               ring_prefill_min=16, name="bench-ring")
        exb = PagedLLMExecutor(dict(params), n_heads=8, block_size=8,
                               num_blocks=16, max_len=128, shards=2,
                               name="bench-ringref")
        try:
            res = {}
            for tag, ex in (("ring", exr), ("blocked", exb)):
                blocks = ex.cache.allocator.alloc(ex.cache.blocks_for(
                    len(prompt)))
                t0 = time.perf_counter()
                lg = ex.prefill(prompt, blocks)
                res[tag] = (np.asarray(lg),
                            (time.perf_counter() - t0) * 1e3)
            err = float(np.max(np.abs(res["ring"][0]
                                      - res["blocked"][0])))
            ring_ok = err <= 1e-3
            out["ring_prefill"] = {
                "ring_ms": round(res["ring"][1], 1),
                "blocked_ms": round(res["blocked"][1], 1),
                "max_abs_err": err,
            }
        finally:
            exr.close()
            exb.close()
        _family_partial(dict(out))

    # (c) dense conservation through a mid-stream member fence
    conserved = True
    fence_ok = True
    if ndev >= 4:
        w = rng.normal(size=(64, 64)).astype(np.float32) / 8.0
        bundle = ModelBundle(
            fn=lambda p, x: (x @ p["w"],), params={"w": w},
            name="bench_shard_mlp")
        x = rng.normal(size=(8, 64)).astype(np.float32)
        frames = 40
        rs = ShardedReplicaSet.open_sharded(bundle, shards=2, groups=2,
                                            name="bench-shard-fence")
        try:
            for i in range(frames):
                if i == frames // 2:     # mid-stream: fence ONE member
                    fence_ok = rs.fence_device(
                        rs.stats()["replicas"][1]["devices"][0],
                        "bench drill")
                rs.invoke((x,))
            st = rs.stats()
        finally:
            rs.close()
        conserved = sum(
            r["invokes"] for r in st["replicas"]) == frames
        dead = [r for r in st["replicas"] if r["state"] == "fenced"]
        out["fence_drill"] = {
            "frames": frames,
            "group_invokes": [r["invokes"] for r in st["replicas"]],
            "fenced_groups": len(dead),
            "conserved": conserved,
            "leases": st.get("leases"),
        }
        _family_partial(dict(out))

    if os.environ.get("BENCH_SHARDED_GATE") == "1":
        out["sharded_gate_ok"] = bool(
            parity_exact and ring_ok and conserved and fence_ok)
        if not out["sharded_gate_ok"]:
            out["unverified"] = True   # ship the numbers, flag the claim
    return out


#: pipeline configs, each its own subprocess family as well — host-path
#: configs do per-frame D2H, and running them after anything else in
#: one process measured 2x drift (label 157 -> 76 FPS across trials)
_CONFIGS = {
    "label_device": lambda: _Bench(_build_label_device).run(),
    "composite": _cfg_composite,
    "ssd_device": lambda: _Bench(_build_ssd_device).run(),
    "posenet_device": lambda: _Bench(_build_posenet_device).run(),
    "label": _cfg_label,
    "ssd": _cfg_ssd,
    "posenet": lambda: _Bench(
        _build_posenet,
        build_lat=lambda: _build_posenet(max_in_flight=1),
        lag=SSD_MAX_IN_FLIGHT - 1).run(),
}

_FAMILIES = {
    "pallas": lambda: pallas_check(),
    "transformer_prefill": lambda: transformer_prefill(),
    "mxu_peak": lambda: mxu_peak(),
    "batch_sweep": lambda: batch_sweep(),
    "dyn_batch": lambda: dyn_batch_check(),
    "int8_native": lambda: int8_native_check(),
    "chaos_smoke": lambda: chaos_smoke(),
    "model_swap": lambda: model_swap(),
    "host_path": lambda: host_path(),
    "llm_serve": lambda: llm_serve(),
    "traffic": lambda: traffic_serve(),
    "autotune": lambda: autotune_serve(),
    "multitenant": lambda: multitenant_serve(),
    "scenario": lambda: scenario_serve(),
    "multichip": lambda: multichip_serve(),
    "sharded": lambda: sharded_serve(),
}
for _d in OFFLOAD_DELAYS:
    _FAMILIES[f"offload_{_d}"] = (
        lambda _d=_d: _offload_point(_d))
for _name, _fn in _CONFIGS.items():
    _FAMILIES[f"cfg_{_name}"] = _fn

_FAMILY_SENTINEL = "BENCHJSON:"

#: handle of the currently-running family subprocess, so the SIGTERM
#: handler can reap it before the parent exits
_CHILD = None


def _family_partial(result) -> None:
    """Stream a family's partial result to the parent (flushed sentinel
    line). A family subprocess killed mid-run still contributes its
    last streamed state; outside --family mode this is a no-op print
    the parent never sees."""
    try:
        print(_FAMILY_SENTINEL + json.dumps({"partial": result}),
              flush=True)
    except (TypeError, ValueError):
        pass                     # never let telemetry kill measurement


def _run_family_subprocess(name: str, errors: dict, timeout_s: float,
                           timeout_names: set = None):
    """Run one measurement family in a child process; the parent has not
    touched jax yet, so the child owns the chip alone. On timeout the
    child is killed, its last streamed partial result (if any) is kept,
    and `name` is added to `timed_out` (retry decisions key off this
    flag, never off error-message text — a child's own exception may
    legitimately contain the words "timed out")."""
    import subprocess

    global _CHILD
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--family", name],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    _CHILD = proc
    timed_out = False
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        timed_out = True
        proc.kill()
        stdout, stderr = proc.communicate()
    finally:
        _CHILD = None
    final = partial = None
    for line in stdout.decode(errors="replace").splitlines():
        if not line.startswith(_FAMILY_SENTINEL):
            continue
        try:
            payload = json.loads(line[len(_FAMILY_SENTINEL):])
        except json.JSONDecodeError:
            continue             # killed mid-write: keep prior state
        if "result" in payload or "error" in payload:
            final = payload
        elif "partial" in payload:
            partial = payload["partial"]
    if timed_out:
        if timeout_names is not None:
            timeout_names.add(name)
        errors[name] = (f"family subprocess timed out "
                        f"({timeout_s:.0f}s)"
                        + ("; partial result kept" if partial else ""))
        return partial or {}
    if final is not None:
        if "error" in final:
            errors[name] = final["error"]
            return partial or {}
        return final["result"]
    stderr_tail = stderr.decode(errors="replace").strip() \
        .splitlines()[-3:]
    errors[name] = (f"family subprocess exited {proc.returncode} "
                    f"without a result"
                    + (f"; stderr: {' | '.join(stderr_tail)}"
                       if stderr_tail else ""))
    return partial or {}


def _enable_compile_cache() -> None:
    """Point jax at a persistent on-disk compilation cache.

    Compile time is pure overhead against the bench budget — every
    measured number is post-warmup steady state — so caching compiled
    executables across family subprocesses (and across whole runs on
    the same host) is free honesty: it converts ~minutes of repeated
    XLA compilation (the int8-conv family alone compiles ~220-270s)
    into cache hits, letting the full family set fit the 1500s budget.
    Opt out with BENCH_XLA_CACHE=0; relocate with BENCH_XLA_CACHE_DIR.
    Routed through serving/compile_cache.py (the [serving] config
    group), so bench subprocesses share the exact persistent-cache
    wiring — and bucket manifest — production store:// serving uses.
    """
    if os.environ.get("BENCH_XLA_CACHE", "1") == "0":
        return
    cache_dir = os.environ.get(
        "BENCH_XLA_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "nnstpu_xla"))
    os.environ.setdefault("NNSTREAMER_TPU_SERVING_COMPILE_CACHE", "1")
    os.environ.setdefault("NNSTREAMER_TPU_SERVING_COMPILE_CACHE_DIR",
                          cache_dir)
    try:
        from nnstreamer_tpu.serving.compile_cache import (
            maybe_enable_compile_cache,
        )

        if not maybe_enable_compile_cache():
            return
        import jax

        # bench-specific: only cache compiles worth a second — the
        # cache exists to amortize the multi-minute conv/int8 families,
        # not to fill with trivial executables
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
    except Exception:
        pass                     # cache is an optimization, never a gate


def _family_main(name: str) -> int:
    if name in ("multichip", "sharded"):
        # These families measure placement/sharding, not the chip:
        # force the 8-device emulated host mesh (same technique as
        # tests/conftest.py) BEFORE _enable_compile_cache imports jax.
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    _enable_compile_cache()
    if name in ("multichip", "sharded"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    try:
        result = _FAMILIES[name]()
        print(_FAMILY_SENTINEL + json.dumps({"result": result}),
              flush=True)
        return 0
    except Exception as e:
        print(_FAMILY_SENTINEL + json.dumps(
            {"error": f"{type(e).__name__}: {e}"}), flush=True)
        return 1


def _offload_median(runs: list) -> dict:
    """Median-of-N offload point (by fps) with the run-to-run spread in
    the artifact — the tunnel makes single offload runs vary up to 3×
    (round-4: 86-285 FPS across identical quiet runs), so one sample is
    a claim, not a result."""
    ok = [r for r in runs if isinstance(r, dict) and "fps" in r]
    if not ok:
        return {}
    # lower-middle on even counts: a budget-truncated 2-run point must
    # not report its best run as "the median" of a 3x-variance metric
    med = dict(sorted(ok, key=lambda r: r["fps"])[(len(ok) - 1) // 2])
    med["runs"] = len(ok)
    med["fps_spread"] = [min(r["fps"] for r in ok),
                         max(r["fps"] for r in ok)]
    med["p50_spread_ms"] = [min(r["p50_ms"] for r in ok),
                            max(r["p50_ms"] for r in ok)]
    return med


def _ordered_families() -> list:
    """Importance order under the soft budget: the headline config
    first (any kill after ~2 min still ships it), then the
    VERDICT-critical kernel/MFU/roofline families, then the remaining
    BASELINE configs, then the offload sweep and int8 check."""
    if os.environ.get("BENCH_SELFTEST") == "fake":
        return list(_FAMILIES)
    return (["cfg_label_device", "pallas", "transformer_prefill",
             "mxu_peak", "batch_sweep", "dyn_batch", "host_path",
             "llm_serve", "traffic", "multitenant", "scenario",
             "multichip", "sharded", "autotune"]
            + [f"cfg_{n}" for n in _CONFIGS if n != "label_device"]
            + [f"offload_{d}" for d in OFFLOAD_DELAYS]
            + ["int8_native", "model_swap", "chaos_smoke"])


def _has_unverified(v) -> bool:
    """True if any nested dict in `v` carries a truthy "unverified"
    flag (the machine-checkable 'this number shipped without its
    verification' marker families set on themselves)."""
    if isinstance(v, dict):
        return bool(v.get("unverified")) or \
            any(_has_unverified(x) for x in v.values())
    if isinstance(v, list):
        return any(_has_unverified(x) for x in v)
    return False


def _assemble(family_out: dict, errors: dict, env: dict,
              elapsed_s: float, partial: bool) -> dict:
    """Build the full cumulative result JSON from whatever has finished
    so far — called after EVERY family so the last printed line is
    always the most complete record."""
    results = {}
    for name in _CONFIGS:
        r = family_out.get(f"cfg_{name}")
        if r:
            results[name] = r
    offload_curve = {}
    for d in OFFLOAD_DELAYS:
        med = _offload_median(family_out.get(f"offload_{d}") or [])
        offload_curve[str(d)] = med or {
            "error": errors.get(f"offload_{d}", "no result")}
    if any("fps" in v for v in offload_curve.values()):
        results["offload"] = _assemble_offload(offload_curve)
    headline = results.get("label_device", {}).get("fps", 0.0)
    out = {
        "metric": "mobilenet_v2_224_fps_per_chip",
        "value": headline,
        "unit": "frames/s",
        "vs_baseline": round(headline / BASELINE_FPS, 3),
        "configs": results,
        "batch_sweep": family_out.get("batch_sweep", {}),
        "dyn_batch": family_out.get("dyn_batch", {}),
        "int8_native": family_out.get("int8_native", {}),
        "pallas": family_out.get("pallas", {}),
        "transformer_prefill": family_out.get("transformer_prefill", {}),
        "mxu_peak": family_out.get("mxu_peak", {}),
        "env": env,
        "elapsed_s": round(elapsed_s, 1),
        "families_done": sorted(k for k, v in family_out.items() if v),
    }
    chaos = family_out.get("chaos_smoke")
    if chaos:
        out["chaos"] = chaos
        out["chaos_ok"] = bool(chaos.get("chaos_ok"))
    swap = family_out.get("model_swap")
    if swap:
        out["model_swap"] = swap
        out["swap_ok"] = bool(swap.get("swap_ok"))
    llm = family_out.get("llm_serve")
    if llm:
        out["llm_serve"] = llm
        out["llm_goodput_win"] = bool(llm.get("goodput_win"))
    # families that completed but flagged part of their own result as
    # unverified (e.g. int8_native without its interpreter oracle) —
    # surfaced as a count so a "0 errors" run can't silently carry
    # unchecked numbers
    warn = sorted(n for n, v in family_out.items() if _has_unverified(v))
    out["families_with_warnings"] = len(warn)
    if warn:
        out["warning_families"] = warn
    if os.environ.get("BENCH_SELFTEST") == "fake":
        out["families"] = family_out     # raw view for the regression
                                         # tests' snapshot assertions
    if partial:
        out["partial"] = True
    if errors:
        out["errors"] = dict(errors)
    return out


def _partial_path() -> str:
    """Where cumulative snapshots persist (BENCH_PARTIAL_PATH; empty
    disables). A run killed by `timeout` — even SIGKILL, which no
    handler sees — still leaves its last per-family snapshot here
    instead of losing the whole run (BENCH_r04 was rc 124 with nothing
    persisted; this file is the fix)."""
    return os.environ.get("BENCH_PARTIAL_PATH", "BENCH_partial.json")


def _persist(out: dict) -> None:
    path = _partial_path()
    if not path:
        return
    try:
        blob = json.dumps(out)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(blob + "\n")
        os.replace(tmp, path)      # atomic: readers never see a torn file
    except Exception:
        pass                       # persistence is telemetry, not a gate


def _emit(out: dict) -> None:
    print(json.dumps(out), flush=True)
    _persist(out)


def main() -> int:
    if "--chaos" in sys.argv:
        # standalone chaos smoke: run in-process, print the result JSON,
        # exit 0 iff every target survived (CI gate / local repro).
        # Same persistent compile cache as --family children — a chaos
        # repro should not pay the full model-compile bill each run.
        _enable_compile_cache()
        out = chaos_smoke()
        print(json.dumps(out), flush=True)
        return 0 if out.get("chaos_ok") else 1
    if "--family" in sys.argv:
        idx = sys.argv.index("--family") + 1
        if idx >= len(sys.argv) or sys.argv[idx] not in _FAMILIES:
            print(f"usage: bench.py --family "
                  f"{{{','.join(sorted(_FAMILIES))}}}", file=sys.stderr)
            return 2
        return _family_main(sys.argv[idx])

    errors: dict = {}
    family_out: dict = {}
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    family_timeout_s = float(os.environ.get("BENCH_FAMILY_TIMEOUT_S",
                                            "300"))
    t0 = time.monotonic()

    # a SIGTERM (the usual `timeout` kill) must still ship the record:
    # reap the in-flight child, print the cumulative snapshot, exit.
    # SIGKILL can't be trapped — the per-family snapshot lines already
    # printed cover that case (the driver keeps the last parseable one).
    import signal

    def _on_term(signum, frame):
        child = _CHILD
        if child is not None:
            try:
                child.kill()
            except Exception:
                pass
        errors["bench"] = "terminated by SIGTERM"
        snap = _assemble(family_out, errors, {},
                         time.monotonic() - t0, partial=True)
        # async-signal-safe write: print() on buffered stdout raises a
        # reentrant-call RuntimeError if the signal landed mid-print in
        # the main loop. The leading newline detaches the snapshot from
        # any half-written line (which stays unparseable — fine, the
        # driver keeps the last parseable one).
        try:
            os.write(1, ("\n" + json.dumps(snap) + "\n").encode())
        except OSError:
            pass
        # signal-safe persistence: os.open/os.write only (no buffered
        # IO in a handler), then atomic rename over the snapshot file
        path = _partial_path()
        if path:
            try:
                tmp = f"{path}.tmp.{os.getpid()}"
                fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                             0o644)
                os.write(fd, (json.dumps(snap) + "\n").encode())
                os.close(fd)
                os.replace(tmp, path)
            except OSError:
                pass
        os._exit(3)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass                     # non-main thread (tests) — snapshots
                                 # alone carry the contract

    def remaining() -> float:
        return budget_s - (time.monotonic() - t0)

    # thresholds scale with the budget (absolute caps sized for the
    # default 1500s budget) so tiny selftest budgets behave the same
    skip_below = min(45.0, 0.03 * budget_s)
    retry_above = min(120.0, 0.08 * budget_s)
    offload_rerun_above = min(150.0, 0.10 * budget_s)
    timeout_names: set = set()     # families the PARENT timed out —
                                   # never retried (they'd eat the
                                   # budget twice)

    def run_one(name: str) -> dict:
        """One family subprocess, clamped to the remaining budget."""
        floor = min(30.0, family_timeout_s)
        timeout = max(floor, min(family_timeout_s, remaining() + 15.0))
        return _run_family_subprocess(name, errors, timeout,
                                      timeout_names)

    # Phase 1 — one subprocess per family with a fresh client (the
    # parent must not touch jax before these finish: one process owns
    # the chip at a time). After EVERY family the full cumulative JSON
    # is printed (flushed): a hard kill at any point loses at most the
    # in-flight family.
    ordered = _ordered_families()
    for name in ordered:
        if remaining() <= skip_below:
            errors[name] = (f"skipped: bench time budget "
                            f"({budget_s:.0f}s) exhausted")
            continue
        if name.startswith("offload_"):
            # median-of-3 (budget permitting): the offload row is
            # tunnel-variance-dominated; spread ships in the artifact
            runs = []
            for _ in range(3):
                if runs and remaining() <= offload_rerun_above:
                    break
                runs.append(run_one(name))
            family_out[name] = [r for r in runs if r]
            if family_out[name]:
                # the point has data — a failed sibling run (in any
                # order) must not flag the whole point as an error
                errors.pop(name, None)
            elif name not in errors:
                errors[name] = "no successful offload run"
        else:
            family_out[name] = run_one(name)
            if not family_out[name] and name in errors \
                    and "skipped" not in errors[name] \
                    and name not in timeout_names \
                    and remaining() > retry_above:
                # transient failures happen (the tunnel's remote-compile
                # hop stalls intermittently) — one retry, fresh client,
                # still inside the budget
                first_err = errors.pop(name)
                family_out[name] = run_one(name)
                if name in errors:
                    errors[name] = (f"{errors[name]} (first attempt: "
                                    f"{first_err})")
            elif name.startswith("cfg_") \
                    and 0 < family_out[name].get("fps", 30.0) < 30.0 \
                    and remaining() > retry_above:
                # a BASELINE-table config below the 30 FPS/chip target
                # is tunnel pathology, not code (measured: cfg_label
                # 1.94 FPS in a run where the same family standalone
                # does 157). One retry; BOTH results ship so the
                # artifact shows the retry happened.
                first = family_out[name]
                second = run_one(name)
                if second.get("fps", 0.0) > first["fps"]:
                    second["slow_first_attempt"] = first
                    family_out[name] = second
        _emit(_assemble(family_out, errors, {},
                        time.monotonic() - t0, partial=True))

    # Phase 2 — the env probe runs in-process last (its D2H reads can
    # degrade nothing at this point).
    env = {}
    if os.environ.get("BENCH_SELFTEST") != "fake":
        try:
            env = _probe_env()
            _gate_env(env, errors)
        except Exception as e:
            errors["env"] = f"{type(e).__name__}: {e}"
    # lift the host_path tracer A/B into the env snapshot: the tracing
    # discount is environment context for EVERY family's numbers, not
    # just host_path's
    piped = (family_out.get("host_path") or {}).get("piped_fps", {})
    pct = piped.get("trace_overhead_pct")
    if pct is not None:
        env["trace_overhead_pct"] = pct
    # same treatment for the device-profiler arm: the plane's cost is
    # context for any artifact produced with devprof enabled
    dpct = piped.get("devprof_overhead_pct")
    if dpct is not None:
        env["devprof_overhead_pct"] = dpct
    # and for the scheduler-bypass A/B: loop_overhead_pct is the
    # throughput the per-frame path gives up vs the compiled window,
    # hop_bytes_per_frame what the same-host shm lane actually moved —
    # both are environment context for any pooled/piped number
    lpct = piped.get("loop_overhead_pct")
    if lpct is not None:
        env["loop_overhead_pct"] = lpct
    hbpf = ((family_out.get("host_path") or {}).get("shm_transport")
            or {}).get("shm", {}).get("hop_bytes_per_frame")
    if hbpf is not None:
        env["hop_bytes_per_frame"] = hbpf

    out = _assemble(family_out, errors, env, time.monotonic() - t0,
                    partial=False)
    _emit(out)
    return 1 if (errors or not out["value"]) else 0


# -- selftest fakes (kill-resilience regression tests) -----------------------
# BENCH_SELFTEST=fake swaps the measurement families for tiny fakes (no
# jax, no chip) so tests/test_bench_logic.py can drive the FULL
# orchestration loop — budgets, per-family timeouts, partial streaming,
# snapshot-per-family, SIGTERM/SIGKILL — in milliseconds.
if os.environ.get("BENCH_SELFTEST") == "fake":
    def _fake_hang():
        deadline = time.monotonic() + float(
            os.environ.get("BENCH_SELFTEST_HANG_S", "600"))
        _family_partial({"streamed": "before-hang"})
        while time.monotonic() < deadline:   # ignores nothing, just slow
            time.sleep(0.05)
        return {"hung": False}

    def _fake_slow_stream():
        out = {}
        for i in range(40):
            out[f"step{i}"] = i
            _family_partial(dict(out))
            time.sleep(float(os.environ.get(
                "BENCH_SELFTEST_STEP_S", "0.05")))
        return out

    def _fake_flaky_cfg():
        # cross-subprocess call counter (each run is a fresh process)
        p = os.environ.get("BENCH_SELFTEST_STATE", "")
        n = 0
        if p and os.path.exists(p):
            n = int(open(p).read().strip() or 0)
        if p:
            with open(p, "w") as f:
                f.write(str(n + 1))
        return {"fps": 5.0 if n == 0 else 100.0, "p50_ms": 10.0}

    _FAMILIES = {
        "fast_a": lambda: {"v": 1},
        "fast_b": lambda: {"v": 2},
        "boom": lambda: 1 / 0,
        "hang": _fake_hang,
        "slow_stream": _fake_slow_stream,
        "tail_z": lambda: {"v": 3},
        "cfg_flaky": _fake_flaky_cfg,
    }


if __name__ == "__main__":
    sys.exit(main())
