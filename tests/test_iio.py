"""tensor_src_iio tests against a fake sysfs tree — the reference's own
technique (tests/nnstreamer_source_iio builds a mock /sys/bus/iio and a
sample FIFO; SURVEY.md §4)."""

import struct

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.elements.iio import TensorSrcIIO, parse_channel_type


def make_device(tmp_path, name="fake_accel", freq="100",
                channels=(), extras=()):
    """channels: (chan_name, index, type_str[, scale[, offset]])."""
    dev = tmp_path / "iio:device0"
    scan = dev / "scan_elements"
    scan.mkdir(parents=True)
    (dev / "name").write_text(name + "\n")
    (dev / "sampling_frequency").write_text(freq + "\n")
    for spec in channels:
        chan, idx, typ = spec[:3]
        (scan / f"{chan}_en").write_text("1\n")
        (scan / f"{chan}_index").write_text(str(idx) + "\n")
        (scan / f"{chan}_type").write_text(typ + "\n")
        if len(spec) > 3:
            (dev / f"{chan}_scale").write_text(str(spec[3]) + "\n")
        if len(spec) > 4:
            (dev / f"{chan}_offset").write_text(str(spec[4]) + "\n")
    for chan, idx, typ in extras:    # present but disabled
        (scan / f"{chan}_en").write_text("0\n")
        (scan / f"{chan}_index").write_text(str(idx) + "\n")
        (scan / f"{chan}_type").write_text(typ + "\n")
    return dev


def run_src(src: TensorSrcIIO):
    src.out_specs = [src.output_spec()]
    return list(src.generate())


# -- type-string parsing ------------------------------------------------------

def test_parse_channel_type():
    d = parse_channel_type("x", "le:s12/16>>4")
    assert d == dict(used_bits=12, storage_bits=16, shift=4,
                     signed=True, big_endian=False)
    d = parse_channel_type("x", "be:u32/32>>0")
    assert d["big_endian"] and not d["signed"]


@pytest.mark.parametrize("bad", ["s12/16>>4", "le:x12/16>>4", "le:s0/16>>0",
                                 "le:s20/16>>0", "le:s65/128>>0", ""])
def test_parse_channel_type_rejects(bad):
    with pytest.raises(PipelineError):
        parse_channel_type("x", bad)


# -- decode paths -------------------------------------------------------------

def test_basic_capture_with_scale_offset(tmp_path):
    dev = make_device(tmp_path, channels=[
        ("in_accel_x", 0, "le:s16/16>>0", 0.5, 10.0),
        ("in_accel_y", 1, "le:s16/16>>0", 0.5, 10.0)])
    samples = [(-4, 2), (100, -100), (32767, -32768)]
    data = tmp_path / "stream.bin"
    data.write_bytes(b"".join(struct.pack("<hh", x, y) for x, y in samples))
    src = TensorSrcIIO(name="s", device="fake_accel",
                       base_dir=str(tmp_path), data=str(data))
    bufs = run_src(src)
    assert len(bufs) == 3
    # IIO convention: (raw + offset) * scale
    np.testing.assert_allclose(bufs[0].tensors[0],
                               [[(-4 + 10) * .5, (2 + 10) * .5]])
    np.testing.assert_allclose(bufs[2].tensors[0],
                               [[(32767 + 10) * .5, (-32768 + 10) * .5]])


def test_12bit_shifted_sign_extension(tmp_path):
    # 12 used bits stored left-aligned in 16 (>>4), like many ADCs
    dev = make_device(tmp_path, channels=[("in_adc0", 0, "le:s12/16>>4")])
    vals = [-2048, -1, 0, 2047]
    raw = b"".join(struct.pack("<H", (v & 0xFFF) << 4) for v in vals)
    data = tmp_path / "s.bin"
    data.write_bytes(raw)
    src = TensorSrcIIO(name="s", device="iio:device0",
                       base_dir=str(tmp_path), data=str(data),
                       frames_per_tensor=4)
    bufs = run_src(src)
    np.testing.assert_array_equal(bufs[0].tensors[0][:, 0], vals)


def test_mixed_width_alignment_padding(tmp_path):
    """3×16-bit channels + 64-bit timestamp: the kernel pads the u64 to
    an 8-byte boundary, so frames are 16 bytes, not 14
    (gsttensor_srciio.c:1503-1522 alignment rule)."""
    dev = make_device(tmp_path, channels=[
        ("in_accel_x", 0, "le:s16/16>>0"),
        ("in_accel_y", 1, "le:s16/16>>0"),
        ("in_accel_z", 2, "le:s16/16>>0"),
        ("in_timestamp", 3, "le:s64/64>>0")])
    frames = []
    for i in range(3):
        frames.append(struct.pack("<hhh2xq", 10 + i, 20 + i, 30 + i,
                                  1000 + i))
    data = tmp_path / "s.bin"
    data.write_bytes(b"".join(frames))
    src = TensorSrcIIO(name="s", device="fake_accel",
                       base_dir=str(tmp_path), data=str(data))
    assert src.output_spec() and src._frame_bytes == 16
    bufs = run_src(src)
    assert len(bufs) == 3
    np.testing.assert_array_equal(
        bufs[1].tensors[0], [[11.0, 21.0, 31.0, 1001.0]])


def test_channels_ordered_by_index_not_name(tmp_path):
    dev = make_device(tmp_path, channels=[
        ("in_a", 1, "le:u8/8>>0"),      # alphabetically first, index 1
        ("in_b", 0, "le:u8/8>>0")])     # index 0 → first in frame
    data = tmp_path / "s.bin"
    data.write_bytes(bytes([7, 9]))     # frame: [b=7, a=9]
    src = TensorSrcIIO(name="s", device="fake_accel",
                       base_dir=str(tmp_path), data=str(data))
    bufs = run_src(src)
    np.testing.assert_array_equal(bufs[0].tensors[0], [[7.0, 9.0]])


def test_split_channels_and_names(tmp_path):
    dev = make_device(tmp_path, channels=[
        ("in_x", 0, "le:u8/8>>0"), ("in_y", 1, "le:u8/8>>0")])
    data = tmp_path / "s.bin"
    data.write_bytes(bytes([1, 2, 3, 4]))
    src = TensorSrcIIO(name="s", device="fake_accel",
                       base_dir=str(tmp_path), data=str(data),
                       merge_channels=False)
    spec = src.output_spec()
    assert [t.name for t in spec.tensors] == ["in_x", "in_y"]
    src.out_specs = [spec]
    bufs = list(src.generate())
    assert bufs[0].num_tensors == 2
    np.testing.assert_array_equal(bufs[1].tensors[1], [[4.0]])


def test_disabled_channels_ignored_and_big_endian(tmp_path):
    dev = make_device(
        tmp_path,
        channels=[("in_v", 0, "be:u16/16>>0")],
        extras=[("in_skip", 1, "le:u8/8>>0")])
    data = tmp_path / "s.bin"
    data.write_bytes(struct.pack(">H", 0x0102))
    src = TensorSrcIIO(name="s", device="fake_accel",
                       base_dir=str(tmp_path), data=str(data))
    bufs = run_src(src)
    np.testing.assert_array_equal(bufs[0].tensors[0], [[0x0102]])


def test_trailing_partial_frame_discarded(tmp_path):
    dev = make_device(tmp_path, channels=[("in_v", 0, "le:u16/16>>0")])
    data = tmp_path / "s.bin"
    data.write_bytes(b"\x01\x00\x02\x00\x03")   # 2.5 frames
    src = TensorSrcIIO(name="s", device="fake_accel",
                       base_dir=str(tmp_path), data=str(data))
    bufs = run_src(src)
    assert len(bufs) == 2


# -- negotiation / errors -----------------------------------------------------

def test_rate_and_num_buffers(tmp_path):
    dev = make_device(tmp_path, freq="200", channels=[
        ("in_v", 0, "le:u8/8>>0")])
    data = tmp_path / "s.bin"
    data.write_bytes(bytes(range(10)))
    src = TensorSrcIIO(name="s", device="fake_accel",
                       base_dir=str(tmp_path), data=str(data),
                       frames_per_tensor=2, num_buffers=3)
    spec = src.output_spec()
    assert spec.rate == 100          # 200 Hz / 2 frames per tensor
    assert spec.tensors[0].shape == (2, 1)
    src.out_specs = [spec]
    assert len(list(src.generate())) == 3


def test_unknown_device_lists_found(tmp_path):
    make_device(tmp_path, name="other")
    with pytest.raises(PipelineError, match="no IIO device named"):
        TensorSrcIIO(name="s", device="nope",
                     base_dir=str(tmp_path)).output_spec()


def test_no_enabled_channels_fails(tmp_path):
    make_device(tmp_path, channels=[],
                extras=[("in_v", 0, "le:u8/8>>0")])
    with pytest.raises(PipelineError, match="no enabled channels"):
        TensorSrcIIO(name="s", device="fake_accel",
                     base_dir=str(tmp_path)).output_spec()


def test_pipeline_dsl_integration(tmp_path):
    make_device(tmp_path, channels=[("in_v", 0, "le:s16/16>>0", 0.1)])
    data = tmp_path / "s.bin"
    data.write_bytes(struct.pack("<4h", 10, 20, 30, 40))
    pipe = nns.parse_launch(
        f"tensor_src_iio device=fake_accel base_dir={tmp_path} "
        f"data={data} frames_per_tensor=2 ! tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    runner.wait(30)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 2
    np.testing.assert_allclose(res[0].tensors[0][:, 0], [1.0, 2.0])
