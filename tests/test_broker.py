"""EdgeBroker tests: HYBRID discovery, brokered pub/sub (mqtt elements),
and clock alignment — loopback on localhost, the reference's technique
(tests/gstreamer_mqtt + nnstreamer_edge query suites, SURVEY.md §4;
NTP mocking analog: unittest_ntp_util_mock.cc)."""

import time

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.edge import QueryServer
from nnstreamer_tpu.edge.broker import (
    BrokerClient, EdgeBroker, pack_publish, unpack_publish)
from nnstreamer_tpu.edge.wire import decode_buffer, encode_buffer
from nnstreamer_tpu.tensor.buffer import TensorBuffer


@pytest.fixture()
def broker():
    b = EdgeBroker("127.0.0.1", 0)
    yield b
    b.close()


@pytest.fixture(autouse=True)
def _clean_servers():
    yield
    QueryServer.reset_all()


# -- publish framing ----------------------------------------------------------

def test_publish_frame_codec():
    topic, ts, frame = unpack_publish(pack_publish("cam/0", 12345, b"xyz"))
    assert (topic, ts, frame) == ("cam/0", 12345, b"xyz")


def test_publish_frame_rejects_truncation():
    with pytest.raises(StreamError, match="truncated"):
        unpack_publish(b"\xff\xff hi")


# -- discovery ----------------------------------------------------------------

def test_register_lookup_roundtrip(broker):
    srv = BrokerClient("127.0.0.1", broker.port)
    srv.register("infer/mobilenet", "10.0.0.7", 5001)
    cli = BrokerClient("127.0.0.1", broker.port)
    assert cli.lookup("infer/mobilenet") == ("10.0.0.7", 5001)
    srv.close()
    cli.close()


def test_lookup_unknown_name_fails(broker):
    cli = BrokerClient("127.0.0.1", broker.port)
    with pytest.raises(StreamError, match="no service registered"):
        cli.lookup("nope")
    cli.close()


def test_registration_dies_with_owner(broker):
    srv = BrokerClient("127.0.0.1", broker.port)
    srv.register("ephemeral", "127.0.0.1", 9)
    srv.close()          # owner leaves → registration must vanish
    cli = BrokerClient("127.0.0.1", broker.port)
    deadline = time.time() + 5
    while time.time() < deadline:
        try:
            cli.lookup("ephemeral")
            time.sleep(0.05)     # reaper hasn't run yet
        except StreamError:
            break
    else:
        pytest.fail("stale registration survived owner disconnect")
    cli.close()


def test_name_collision_refused(broker):
    a = BrokerClient("127.0.0.1", broker.port)
    b = BrokerClient("127.0.0.1", broker.port)
    a.register("svc", "127.0.0.1", 1)
    with pytest.raises(StreamError, match="already registered"):
        b.register("svc", "127.0.0.1", 2)
    # same owner may re-register (address update)
    a.register("svc", "127.0.0.1", 3)
    assert b.lookup("svc") == ("127.0.0.1", 3)
    a.close()
    b.close()


def test_unregister(broker):
    a = BrokerClient("127.0.0.1", broker.port)
    a.register("svc", "127.0.0.1", 1)
    a.unregister("svc")
    deadline = time.time() + 5
    while time.time() < deadline:
        try:
            a.lookup("svc")
            time.sleep(0.05)
        except StreamError:
            break
    else:
        pytest.fail("unregistered service still resolvable")
    a.close()


# -- clock --------------------------------------------------------------------

def test_clock_offset_near_zero_same_host(broker):
    cli = BrokerClient("127.0.0.1", broker.port)
    off = cli.clock_offset_ns()
    assert abs(off) < 1_000_000_000   # same clock, sub-second bound
    assert abs(cli.broker_now_ns() - time.time_ns()) < 2_000_000_000
    cli.close()


# -- pub/sub ------------------------------------------------------------------

def test_pubsub_fanout_no_self_echo(broker):
    got_a, got_b, got_pub = [], [], []
    a = BrokerClient("127.0.0.1", broker.port)
    b = BrokerClient("127.0.0.1", broker.port)
    pub = BrokerClient("127.0.0.1", broker.port)
    a.subscribe("t", lambda ts, f: got_a.append(f))
    b.subscribe("t", lambda ts, f: got_b.append(f))
    pub.subscribe("t", lambda ts, f: got_pub.append(f))
    time.sleep(0.2)
    frame = encode_buffer(TensorBuffer.of(np.arange(3).astype(np.float32)))
    pub.publish("t", frame)
    deadline = time.time() + 5
    while (len(got_a) < 1 or len(got_b) < 1) and time.time() < deadline:
        time.sleep(0.02)
    assert len(got_a) == 1 and len(got_b) == 1
    assert got_pub == []     # publisher does not hear itself
    out, _ = decode_buffer(got_a[0])
    np.testing.assert_array_equal(out.tensors[0],
                                  np.arange(3).astype(np.float32))
    for c in (a, b, pub):
        c.close()


# -- mqtt elements ------------------------------------------------------------

def test_mqtt_sink_to_src_pipeline(broker):
    recv = nns.parse_launch(
        f"mqttsrc name=in port={broker.port} topic=cam dims=4 "
        f"types=float32 ! tensor_sink name=out")
    rr = nns.PipelineRunner(recv).start()
    time.sleep(0.3)   # subscription in flight
    send = nns.parse_launch(
        f"appsrc name=src dims=4 types=float32 ! "
        f"mqttsink port={broker.port} topic=cam")
    sr = nns.PipelineRunner(send).start()
    src = send.get("src")
    frames = [np.full(4, i, np.float32) for i in range(3)]
    for i, f in enumerate(frames):
        src.push(TensorBuffer.of(f, pts=i * 1000))
    src.end()
    sr.wait(30)
    sink = recv.get("out")
    deadline = time.time() + 10
    while len(sink.results) < 3 and time.time() < deadline:
        time.sleep(0.05)
    sr.stop()
    recv.get("in").interrupt()
    rr.stop()
    assert len(sink.results) == 3
    np.testing.assert_array_equal(sink.results[1].tensors[0], frames[1])
    assert sink.results[1].pts == 1000                  # sync=none keeps PTS
    assert "pub_broker_ns" in sink.results[1].meta      # broker stamp rides


def test_mqtt_sync_broker_rebases_pts(broker):
    recv = nns.parse_launch(
        f"mqttsrc name=in port={broker.port} topic=s dims=1 types=uint8 "
        f"sync=broker ! tensor_sink name=out")
    rr = nns.PipelineRunner(recv).start()
    time.sleep(0.3)
    pub = BrokerClient("127.0.0.1", broker.port)
    for i in range(2):
        pub.publish("s", encode_buffer(
            TensorBuffer.of(np.array([i], np.uint8), pts=999_999)))
        time.sleep(0.05)
    sink = recv.get("out")
    deadline = time.time() + 10
    while len(sink.results) < 2 and time.time() < deadline:
        time.sleep(0.05)
    recv.get("in").interrupt()
    rr.stop()
    pub.close()
    assert len(sink.results) == 2
    # PTS rebased onto the broker timeline: first = 0, second = the
    # publish gap (~50ms), publisher's own PTS discarded
    assert sink.results[0].pts == 0
    assert 0 < sink.results[1].pts < 5_000_000_000


def test_mqttsrc_sniffs_spec(broker):
    pub = BrokerClient("127.0.0.1", broker.port)
    import threading

    def feed():
        for _ in range(20):
            try:
                pub.publish("sniff", encode_buffer(
                    TensorBuffer.of(np.zeros((2, 3), np.int16))))
            except StreamError:
                return   # test closed the client; done feeding
            time.sleep(0.1)

    t = threading.Thread(target=feed, daemon=True)
    recv = nns.parse_launch(
        f"mqttsrc name=in port={broker.port} topic=sniff ! "
        f"tensor_sink name=out")
    t.start()
    rr = nns.PipelineRunner(recv).start()
    sink = recv.get("out")
    deadline = time.time() + 10
    while len(sink.results) < 1 and time.time() < deadline:
        time.sleep(0.05)
    recv.get("in").interrupt()
    rr.stop()
    pub.close()
    t.join(timeout=5)
    assert sink.results and sink.results[0].tensors[0].shape == (2, 3)


# -- HYBRID query discovery ---------------------------------------------------

def test_query_hybrid_discovery_end_to_end(broker):
    from nnstreamer_tpu.backends.custom import register_custom_easy

    register_custom_easy("hybrid_double", lambda t: (t[0] * 2,))
    server = nns.parse_launch(
        f"tensor_query_serversrc name=ssrc id=7 dims=4 types=float32 "
        f"port=0 broker_port={broker.port} topic=infer/double ! "
        f"tensor_filter framework=custom model=hybrid_double ! "
        f"tensor_query_serversink id=7")
    srunner = nns.PipelineRunner(server).start()
    # client knows only the broker address + service name
    client = nns.parse_launch(
        f"appsrc name=in dims=4 types=float32 ! "
        f"tensor_query_client connect_type=hybrid port={broker.port} "
        f"topic=infer/double ! tensor_sink name=out")
    crunner = nns.PipelineRunner(client).start()
    src = client.get("in")
    src.push(TensorBuffer.of(np.arange(4, dtype=np.float32)))
    src.end()
    crunner.wait(30)
    crunner.stop()
    server.get("ssrc").interrupt()
    srunner.stop()
    res = client.get("out").results
    assert len(res) == 1
    np.testing.assert_array_equal(
        res[0].tensors[0], np.arange(4, dtype=np.float32) * 2)


def test_query_hybrid_unknown_topic_fails_negotiation(broker):
    with pytest.raises(nns.core.errors.NegotiationError,
                       match="hybrid discovery"):
        pipe = nns.parse_launch(
            f"appsrc dims=4 types=float32 ! "
            f"tensor_query_client connect_type=hybrid port={broker.port} "
            f"topic=ghost ! fakesink")
        nns.PipelineRunner(pipe).start()


# -- robustness regressions ---------------------------------------------------

def test_broker_survives_malformed_payloads(broker):
    """Garbage JSON / invalid UTF-8 must not kill reader threads or the
    service (standalone brokers face arbitrary network clients)."""
    from nnstreamer_tpu.edge import protocol as P
    import nnstreamer_tpu.edge.broker as B

    evil = P.MsgClient("127.0.0.1", broker.port,
                       on_message=lambda t, p: None)
    evil.send(B.T_LOOKUP, b"\xff\xfe not json")
    evil.send(B.T_LOOKUP, b"[]")            # valid JSON, wrong shape
    evil.send(B.T_SUBSCRIBE, b"\xff\xfe")   # invalid utf8 topic
    evil.send(B.T_UNREGISTER, b"{")
    evil.send(B.T_PUBLISH, b"\xff\xff")     # truncated publish
    time.sleep(0.3)
    # broker still fully functional afterwards
    ok = BrokerClient("127.0.0.1", broker.port)
    ok.register("still/alive", "127.0.0.1", 1)
    assert ok.lookup("still/alive") == ("127.0.0.1", 1)
    evil.close()
    ok.close()


def test_serversrc_refuses_wildcard_advertise(broker):
    pipe = nns.parse_launch(
        f"tensor_query_serversrc name=s id=8 dims=2 types=float32 "
        f"host=0.0.0.0 port=0 broker_port={broker.port} topic=w ! "
        f"fakesink")
    with pytest.raises(nns.core.errors.PipelineError,
                       match="advertise_host"):
        nns.PipelineRunner(pipe).start()


def test_broker_cli_daemon_cross_process():
    """`python -m nnstreamer_tpu --broker` serves discovery to other
    processes (the deployment story for HYBRID/mqtt)."""
    import re
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-m", "nnstreamer_tpu", "--broker", "0",
         "--bind", "127.0.0.1"],
        stderr=subprocess.PIPE, text=True)
    try:
        line = proc.stderr.readline()
        port = int(re.search(r":(\d+)", line).group(1))
        a = BrokerClient("127.0.0.1", port)
        a.register("cli/svc", "127.0.0.1", 42)
        b = BrokerClient("127.0.0.1", port)
        assert b.lookup("cli/svc") == ("127.0.0.1", 42)
        assert abs(b.clock_offset_ns()) < 2_000_000_000
        a.close()
        b.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)
