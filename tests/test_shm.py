"""Same-host shared-memory transport (serving/shm.py + the pool's shm
lane, ISSUE 20): SPSC ring mechanics including wrap-around and the
full/mismatch edges, the pool moving real frames over the rings with
exact admission conservation, transparent pickle fallback when the
child can't attach, segment reclamation through a worker kill, and the
hop-latency A/B harness bench.py reports.
"""

import itertools
import os

import numpy as np
import pytest

from nnstreamer_tpu.edge.query import QueryServer
from nnstreamer_tpu.edge.wire import SHM_REC
from nnstreamer_tpu.serving.shm import (ShmRing, hop_latency_ab,
                                        ring_name, shm_safe,
                                        shm_supported)
from nnstreamer_tpu.serving.pool import PooledQueryServer
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.traffic.loadgen import (poisson_arrivals,
                                            run_against_pool,
                                            run_open_loop)

pytestmark = pytest.mark.skipif(not shm_supported(),
                                reason="POSIX shared memory unavailable")

_sid = itertools.count(7600)
_rid = itertools.count()


@pytest.fixture(autouse=True)
def _clean_servers():
    yield
    QueryServer.reset_all()


def _ring(capacity: int) -> ShmRing:
    return ShmRing.create(ring_name("tu", "shmunit", next(_rid), 0),
                          capacity)


def _conserved(c: dict) -> bool:
    return (c["offered"] == c["admitted"] + sum(c["rejected"].values())
            and c["admitted"] == c["replied"] + sum(c["shed"].values())
            + c["depth"] + c["inflight"])


def _echo_pool(**kw):
    kw.setdefault("workers", 2)
    kw.setdefault("service_ms", 2.0)
    return PooledQueryServer.echo(sid=next(_sid), **kw)


def _drive(pqs, n, rate_hz=150.0):
    x = np.ones((8, 1), np.float32)
    return run_open_loop(
        "127.0.0.1", pqs.port, dims="8:1",
        arrivals=poisson_arrivals(rate_hz, n),
        make_frame=lambda i: TensorBuffer.of(x, pts=i),
        p99_budget_ms=1000.0)


def _our_segments():
    """/dev/shm entries this process created (ring_name suffixes the
    creating pid, so concurrent CI runs never alias)."""
    try:
        names = os.listdir("/dev/shm")
    except OSError:
        return []
    return sorted(n for n in names
                  if n.startswith("nns_") and n.endswith(f"_{os.getpid()}"))


# -- ring mechanics -----------------------------------------------------------

class TestShmRing:
    def test_write_read_round_trip_and_seq(self):
        r = _ring(1024)
        try:
            for i in range(3):
                payload = bytes([i]) * (10 + i)
                seq = r.try_write(payload)
                assert seq == i + 1          # seqs are 1-based, monotone
                assert r.read_record(len(payload), seq) == payload
            assert r.used == 0               # fully drained
        finally:
            r.close()
            r.unlink()

    def test_wrap_across_capacity_boundary(self):
        cap = 128
        r = _ring(cap)
        rec = SHM_REC.size + 40
        try:
            payload = bytes(range(40))
            for _ in range(10):              # 10 * rec >> cap: many wraps
                seq = r.try_write(payload)
                assert seq is not None
                assert r.read_record(len(payload), seq) == payload
            # cursors are monotonic byte counts — the data really did
            # cross the physical end of the segment, repeatedly
            assert 10 * rec > 4 * cap
            assert r.used == 0
        finally:
            r.close()
            r.unlink()

    def test_full_ring_refuses_then_recovers(self):
        cap = 64
        r = _ring(cap)
        try:
            p = b"x" * (cap - SHM_REC.size)  # exactly fills the ring
            seq = r.try_write(p)
            assert seq == 1
            assert r.free == 0
            assert r.try_write(b"y") is None      # full → pipe fallback
            assert r.read_record(len(p), seq) == p
            seq2 = r.try_write(b"y" * 8)          # space reclaimed
            assert seq2 == 2
            assert r.read_record(8, seq2) == b"y" * 8
        finally:
            r.close()
            r.unlink()

    def test_oversized_payload_never_partially_writes(self):
        r = _ring(64)
        try:
            assert r.try_write(b"z" * 256) is None
            assert r.used == 0               # no torn half-record
        finally:
            r.close()
            r.unlink()

    def test_control_message_mismatch_raises(self):
        r = _ring(256)
        try:
            seq = r.try_write(b"abc")
            with pytest.raises(ValueError, match="mismatch"):
                r.read_record(3, seq + 1)    # stale seq from control msg
            with pytest.raises(ValueError, match="mismatch"):
                r.read_record(2, seq)        # wrong promised length
            # the record itself is intact under the true header
            assert r.read_record(3, seq) == b"abc"
        finally:
            r.close()
            r.unlink()

    def test_attach_sees_creator_writes_and_unlink_removes(self):
        r = _ring(256)
        name = r.name
        other = ShmRing.attach(name)
        try:
            seq = r.try_write(b"hello")
            assert other.read_record(5, seq) == b"hello"
        finally:
            other.close()
            r.close()
            r.unlink()
        assert name not in _our_segments()

    def test_ring_names_are_legal_and_unique_per_spawn(self):
        a = ring_name("rq", "we?ird pool/name", 3, 1)
        b = ring_name("rq", "we?ird pool/name", 3, 2)
        assert a != b                        # respawn never aliases
        assert "/" not in a[1:] and " " not in a and "?" not in a
        assert shm_safe("we?ird pool/name") in a


# -- pool shm lane ------------------------------------------------------------

class TestPoolShmLane:
    def test_lane_moves_frames_conserves_and_reclaims(self):
        pqs = _echo_pool(shm_transport=True)
        pool = pqs.pool
        try:
            rep = _drive(pqs, 40)
            assert rep["completed"] == 40 and rep["lost"] == 0
            assert _conserved(pqs.admission_counters())
            p = pool.stats()["pool"]
            # request + result of every hop rode the rings; nothing
            # fell back on a quiet pool with 4MB rings
            assert p["shm_fallbacks"] == 0
            assert p["shm_frames"] >= 2 * rep["completed"]
            assert p["shm_bytes"] > p["shm_frames"] * 8
            # two rings per live worker while running
            assert len(pool.shm_segments()) == 2 * pool.n_workers
        finally:
            pqs.close()
        assert pool.shm_segments() == []     # unlinked at close
        assert _our_segments() == []

    def test_pipe_only_pool_counts_zero_shm(self):
        pqs = _echo_pool(shm_transport=False)
        try:
            rep = _drive(pqs, 20)
            assert rep["completed"] == 20 and rep["lost"] == 0
            assert _conserved(pqs.admission_counters())
            p = pqs.pool.stats()["pool"]
            assert p["shm_frames"] == 0 and p["shm_bytes"] == 0
            assert pqs.pool.shm_segments() == []
        finally:
            pqs.close()

    def test_attach_failure_falls_back_to_pickle(self, monkeypatch):
        """Child can't attach (here: the parent handed it segment names
        that don't exist) → it acks ``shm: False`` and every hop rides
        the pickle pipe, invisibly to the caller."""
        class _GhostRing:
            def __init__(self, name):
                self.name = name

            def close(self):
                pass

            def unlink(self):
                pass

            def try_write(self, payload):
                return None

        monkeypatch.setattr(
            ShmRing, "create",
            classmethod(lambda cls, name, capacity=0:
                        _GhostRing(name + "-ghost")))
        pqs = _echo_pool(shm_transport=True)
        try:
            rep = _drive(pqs, 20)
            assert rep["completed"] == 20 and rep["lost"] == 0
            assert _conserved(pqs.admission_counters())
            p = pqs.pool.stats()["pool"]
            assert p["shm_fallbacks"] >= pqs.pool.n_workers  # per hello
            assert p["shm_frames"] == 0      # nothing rode a ghost ring
        finally:
            pqs.close()
        assert _our_segments() == []


@pytest.mark.chaos
class TestShmKillReclamation:
    def test_worker_kill_zero_lost_zero_orphan_segments(self):
        """The ISSUE 20 drill: SIGKILL a worker mid-flood with the shm
        lane on → conservation exact, pool recovers, zero orphan pids
        AND zero orphan /dev/shm segments (the killed slot's rings are
        unlinked at reap; the respawn gets fresh names)."""
        rep = run_against_pool(
            n=120, service_ms=5.0, workers=2, load_x=1.5, kills=1,
            seed=5, max_pending=32, p99_budget_ms=250.0,
            sid=next(_sid), shm_transport=True)
        assert rep["lost"] == 0
        assert rep["conserved"] and rep["recovered"]
        assert rep["orphans"] == []
        p = rep["pool"]["pool"]
        assert p["shm_frames"] > 0           # the lane was actually hot
        assert p["restarts"] >= 1
        assert _our_segments() == []


# -- hop-latency A/B harness --------------------------------------------------

class TestHopLatencyAB:
    def test_smoke_shape_and_cleanup(self):
        """Tiny run: the harness measures both lanes, reports the
        fields bench.py lifts, and leaves no segment behind. The
        speedup verdict itself is bench territory (it needs real n to
        clear scheduler noise), not a unit assert."""
        out = hop_latency_ab(payload_bytes=4096, n=12)
        assert out["round_trips"] == 12
        assert out["payload_bytes"] == 4096
        for k in ("pipe_p50_ms", "pipe_p99_ms",
                  "shm_p50_ms", "shm_p99_ms", "hop_speedup"):
            assert out[k] > 0, k
        assert isinstance(out["shm_ok"], bool)
        assert not [n_ for n_ in _our_segments() if "_hopab_" in n_]
