"""Fault-tolerance layer tests (docs/robustness.md): error policies,
tensor_fault injection, watchdog, circuit breaker, and the pre-existing
error paths the layer formalizes (source death, element death, wait()
root-cause chaining, repo slot overflow, upstream-event handler errors).

Everything runs on the fake (custom) backend / synthetic streams — no
models, no device."""

import pickle
import queue as _queue
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core import errors as errors_mod
from nnstreamer_tpu import (
    Pipeline,
    PipelineRunner,
    TensorBuffer,
    parse_launch,
    register_custom_easy,
    run_pipeline,
)
from nnstreamer_tpu.backends.base import CircuitBreaker
from nnstreamer_tpu.backends.custom import unregister_custom_easy
from nnstreamer_tpu.core.errors import (
    CircuitOpenError,
    ErrorPolicy,
    FaultInjected,
    PipelineError,
    StreamError,
    WatchdogStall,
)
from nnstreamer_tpu.elements import TensorFault, TensorFilter, TensorSink
from nnstreamer_tpu.elements.repo import REPO, TensorRepoSink
from nnstreamer_tpu.elements.sources import AppSrc
from nnstreamer_tpu.graph.pipeline import Element, SourceElement
from nnstreamer_tpu.tensor.info import TensorsSpec


@pytest.fixture(autouse=True)
def _clean_models():
    names = []

    def reg(name, *a, **kw):
        names.append(name)
        return register_custom_easy(name, *a, **kw)

    yield reg
    for n in names:
        unregister_custom_easy(n)


def _wait_for(cond, timeout=10.0, what="condition"):
    t0 = time.monotonic()
    while not cond():
        assert time.monotonic() - t0 < timeout, f"timed out waiting: {what}"
        time.sleep(0.01)


# -- error-policy grammar ----------------------------------------------------

class TestErrorPolicyParse:
    def test_kinds(self):
        assert ErrorPolicy.parse("fail").kind == "fail"
        assert ErrorPolicy.parse("skip").kind == "skip"
        assert ErrorPolicy.parse("degrade").kind == "degrade"

    def test_retry(self):
        p = ErrorPolicy.parse("retry:3")
        assert (p.kind, p.retries, p.backoff_ms) == ("retry", 3, 10.0)
        p = ErrorPolicy.parse("retry:2:5.5")
        assert (p.retries, p.backoff_ms) == (2, 5.5)

    def test_roundtrip_str(self):
        for s in ("fail", "skip", "degrade", "retry:4:25"):
            assert str(ErrorPolicy.parse(s)) == s

    @pytest.mark.parametrize("bad", ["", "nope", "retry", "retry:0",
                                     "retry:x", "retry:1:-5"])
    def test_rejects(self, bad):
        with pytest.raises(ValueError, match="error-policy"):
            ErrorPolicy.parse(bad)

    def test_element_property(self):
        f = TensorFault(error_policy="retry:2")
        assert f.error_policy.kind == "retry"
        # default stays the fail-fast contract
        assert TensorFault().error_policy.kind == "fail"

    def test_unknown_prop_message_lists_common(self):
        with pytest.raises(PipelineError, match="error-policy"):
            TensorFault(no_such_prop=1)


# -- pre-existing error paths (now under test) -------------------------------

class _BoomSrc(SourceElement):
    """Source that dies after its first buffer (mid-generate failure)."""

    ELEMENT_NAME = "boom_src"

    def output_spec(self):
        return TensorsSpec.from_strings("2:2", "float32")

    def generate(self):
        yield TensorBuffer.of(np.zeros((2, 2), np.float32))
        raise RuntimeError("source exploded mid-stream")


class TestExistingErrorPaths:
    def test_source_raises_mid_generate(self):
        p = Pipeline("boom")
        src = p.add(_BoomSrc(name="src"))
        sink = p.add(TensorSink(name="out"))
        p.link(src, sink)
        with pytest.raises(StreamError, match="source exploded") as ei:
            run_pipeline(p, timeout=10)
        assert isinstance(ei.value.__cause__, RuntimeError)

    def test_element_raises_on_frame_k_fail_fast(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=10 ! "
            "tensor_converter ! tensor_fault mode=raise period=3 ! "
            "tensor_sink name=out")
        with pytest.raises(StreamError, match="injected failure") as ei:
            run_pipeline(p, timeout=10)
        assert isinstance(ei.value.__cause__, FaultInjected)
        # frames past the failure never arrive
        assert len(p.get("out").results) <= 2

    def test_wait_timeout_chains_root_cause(self, _clean_models):
        _clean_models("slowmodel",
                      lambda ts: (time.sleep(3.0), ts)[1])
        # two disjoint chains: one dies instantly, one is stuck in a
        # non-interruptible invoke — wait(timeout) must surface the
        # original error, not a bare timeout
        p = Pipeline("stuck")
        s1 = p.add(AppSrc(name="s1", spec=TensorsSpec.from_strings(
            "2:2", "float32")))
        flt = p.add(TensorFault(name="boom", mode="raise", period=1))
        k1 = p.add(TensorSink(name="k1"))
        p.link(s1, flt)
        p.link(flt, k1)
        s2 = p.add(AppSrc(name="s2", spec=TensorsSpec.from_strings(
            "2:2", "float32")))
        slow = p.add(TensorFilter(name="slow", framework="custom",
                                  model="slowmodel"))
        k2 = p.add(TensorSink(name="k2"))
        p.link(s2, slow)
        p.link(slow, k2)
        runner = PipelineRunner(p).start()
        frame = TensorBuffer.of(np.zeros((2, 2), np.float32))
        s2.push(frame)          # slow branch enters its 3s invoke
        time.sleep(0.3)
        s1.push(frame)          # boom branch fails immediately
        try:
            with pytest.raises(StreamError,
                               match="did not finish within") as ei:
                runner.wait(timeout=1.0)
            assert "injected failure" in str(ei.value)
            assert isinstance(ei.value.__cause__, FaultInjected)
        finally:
            runner.stop()
        time.sleep(2.5)         # let the sleeping invoke drain (daemon)


# -- skip / retry / degrade --------------------------------------------------

class TestPolicies:
    def test_skip_conservation(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=20 ! "
            "tensor_converter ! tensor_fault name=flt mode=raise period=4 "
            "error-policy=skip ! tensor_sink name=out")
        runner = PipelineRunner(p)
        runner.run(timeout=15)
        st = runner.stats()["flt"]
        sink = p.get("out")
        assert sink.eos.is_set()
        assert st["skipped"] == 5          # frames 1,5,9,... wait: 4,8,...
        assert st["errors"] == st["skipped"]
        assert len(sink.results) + st["skipped"] == 20
        assert st["dropped"] == 0

    def test_retry_recovers_transient_failure(self, _clean_models):
        calls = {"n": 0}

        def flaky(ts):
            calls["n"] += 1
            if calls["n"] == 3:            # fail frame 3, first attempt only
                raise RuntimeError("transient")
            return ts

        _clean_models("flaky_once", flaky)
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=5 ! "
            "tensor_converter ! tensor_transform mode=typecast "
            "option=float32 ! tensor_filter name=f framework=custom "
            "model=flaky_once error-policy=retry:2:1 ! tensor_sink "
            "name=out")
        runner = PipelineRunner(p)
        runner.run(timeout=15)
        st = runner.stats()["f"]
        assert len(p.get("out").results) == 5   # nothing lost
        assert st["errors"] == 1
        assert st["retries"] == 1
        assert st["skipped"] == 0

    def test_retry_exhausted_falls_back_to_skip(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=3 ! "
            "tensor_converter ! tensor_fault name=flt mode=raise "
            "probability=1.0 error-policy=retry:2:1 ! tensor_sink name=out")
        runner = PipelineRunner(p)
        runner.run(timeout=15)
        st = runner.stats()["flt"]
        assert len(p.get("out").results) == 0
        assert st["skipped"] == 3              # every buffer abandoned
        assert st["retries"] == 6              # 2 retries per buffer
        assert st["errors"] == 9               # 3 attempts per buffer
        assert p.get("out").eos.is_set()

    def test_degrade_routes_input_to_fallback_pad(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=6 ! "
            "tensor_converter ! tensor_fault name=flt mode=raise period=2 "
            "error-policy=degrade flt.src_0 ! tensor_sink name=ok "
            "flt.src_1 ! tensor_sink name=fb")
        runner = PipelineRunner(p)
        runner.run(timeout=15)
        ok, fb = p.get("ok"), p.get("fb")
        assert len(ok.results) == 3
        assert len(fb.results) == 3            # raw inputs, rerouted
        st = runner.stats()["flt"]
        assert st["degraded"] == 3
        # fallback carries the *unprocessed* input spec
        assert fb.results[0].tensors[0].dtype == np.uint8

    def test_degrade_requires_linked_fallback_pad(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=2 ! "
            "tensor_converter ! tensor_fault mode=raise period=2 "
            "error-policy=degrade ! tensor_sink")
        with pytest.raises(PipelineError, match="fallback"):
            run_pipeline(p, timeout=10)

    def test_policy_on_source_rejected(self):
        p = parse_launch(
            "videotestsrc num-buffers=2 error-policy=skip ! "
            "tensor_converter ! tensor_sink")
        with pytest.raises(PipelineError, match="source"):
            run_pipeline(p, timeout=10)


# -- acceptance: 5% chaos to EOS with exact conservation ---------------------

class TestChaosAcceptance:
    @pytest.mark.parametrize("policy", ["skip", "retry:3:1"])
    def test_five_percent_raise_completes_to_eos(self, policy):
        p = parse_launch(
            f"videotestsrc width=4 height=4 num-buffers=100 ! "
            f"tensor_converter ! tensor_fault name=flt mode=raise "
            f"probability=0.05 seed=7 error-policy={policy} ! "
            f"tensor_sink name=out")
        runner = PipelineRunner(p)
        runner.run(timeout=30)
        sink = p.get("out")
        st = runner.stats()["flt"]
        assert sink.eos.is_set()
        # conservation: emitted + skipped + dropped == generated
        assert len(sink.results) + st["skipped"] + st["dropped"] == 100
        if policy == "skip":
            assert st["errors"] > 0            # seed 7 does inject faults
            assert st["skipped"] == st["errors"]

    def test_escalation_on_poison_stream(self):
        # no other processing element in the chain: the counter resets on
        # ANY successful process() in the pipeline, so e.g. a converter
        # between src and fault would race the escalation
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=20 ! "
            "tensor_fault mode=raise probability=1.0 "
            "error-policy=skip ! tensor_sink")
        runner = PipelineRunner(p, max_consecutive_errors=5)
        with pytest.raises(StreamError, match="consecutive errors"):
            runner.run(timeout=15)


# -- tensor_fault element ----------------------------------------------------

class TestTensorFault:
    def test_seeded_probability_is_deterministic(self):
        def run_once():
            p = parse_launch(
                "videotestsrc width=4 height=4 num-buffers=50 ! "
                "tensor_converter ! tensor_fault name=flt mode=drop "
                "probability=0.2 seed=42 ! tensor_sink name=out")
            run_pipeline(p, timeout=15)
            return len(p.get("out").results), p.get("flt").injected

        a, b = run_once(), run_once()
        assert a == b
        assert a[1] > 0 and a[0] + a[1] == 50

    def test_max_faults_cap(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=10 ! "
            "tensor_converter ! tensor_fault name=flt mode=drop period=2 "
            "max-faults=2 ! tensor_sink name=out")
        run_pipeline(p, timeout=15)
        assert p.get("flt").injected == 2
        assert len(p.get("out").results) == 8

    def test_corrupt_shape_breaks_downstream(self, _clean_models):
        def strict(ts):
            if ts[0].ndim != 4:        # (1, 4, 4, 3) from the converter
                raise RuntimeError(f"unexpected shape {ts[0].shape}")
            return ts

        _clean_models("strict_shape", strict, infer_out=lambda s: s)
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=5 ! "
            "tensor_converter ! tensor_transform mode=typecast "
            "option=float32 ! tensor_fault mode=corrupt-shape period=2 ! "
            "tensor_filter framework=custom model=strict_shape ! "
            "tensor_sink name=out")
        with pytest.raises(StreamError):
            run_pipeline(p, timeout=15)

    def test_bad_mode_rejected_at_negotiation(self):
        p = parse_launch(
            "videotestsrc num-buffers=1 ! tensor_converter ! "
            "tensor_fault mode=wat ! tensor_sink")
        with pytest.raises(Exception, match="unknown mode"):
            run_pipeline(p, timeout=10)


# -- watchdog ----------------------------------------------------------------

class TestWatchdog:
    def test_flags_stalled_element_within_2x_budget(self):
        # each process() parks ~1.1s; budget 0.5s → the watchdog must
        # flag the stall while the call is still in flight (≈2x budget)
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=2 ! "
            "tensor_converter ! tensor_fault name=flt mode=delay "
            "delay-ms=1100 period=1 ! tensor_sink name=out")
        runner = PipelineRunner(p, stall_budget_s=0.5)
        runner.run(timeout=30)
        st = runner.stats()["flt"]
        assert st["watchdog_warnings"] >= 1
        assert p.get("out").eos.is_set()       # warn-only: run completes

    def test_no_false_positives_on_fast_pipeline(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=20 ! "
            "tensor_converter ! tensor_sink name=out")
        runner = PipelineRunner(p, stall_budget_s=0.5)
        runner.run(timeout=15)
        assert all(d["watchdog_warnings"] == 0
                   for d in runner.stats().values())

    def test_action_fail_tears_down_with_watchdog_stall(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=4 ! "
            "tensor_converter ! tensor_fault mode=delay delay-ms=30000 "
            "period=1 ! tensor_sink")
        runner = PipelineRunner(p, stall_budget_s=0.3,
                                watchdog_action="fail")
        with pytest.raises(StreamError, match="stall budget") as ei:
            runner.run(timeout=30)
        assert isinstance(ei.value.__cause__, WatchdogStall)

    def test_bad_action_rejected(self):
        p = parse_launch("videotestsrc num-buffers=1 ! tensor_converter "
                         "! tensor_sink")
        with pytest.raises(PipelineError, match="watchdog_action"):
            PipelineRunner(p, watchdog_action="explode")


# -- circuit breaker ---------------------------------------------------------

class TestCircuitBreakerUnit:
    def test_state_machine_with_fake_clock(self):
        clk = [0.0]
        b = CircuitBreaker(threshold=2, cooldown_s=5.0,
                           clock=lambda: clk[0])
        assert b.state == "closed"
        b.guard("t")                       # closed: no-op
        b.record_failure()
        assert b.state == "closed"         # below threshold
        b.record_failure()
        assert b.state == "open"
        assert b.opened_count == 1
        # open + cooling: guard short-circuits without touching anything
        with pytest.raises(CircuitOpenError, match="circuit open"):
            b.guard("t")
        assert b.short_circuited == 1
        # cooldown elapsed: next guard half-opens (the probe)
        clk[0] = 6.0
        b.guard("t")
        assert b.state == "half_open"
        assert b.probes == 1
        # probe fails → re-open with a fresh cooldown
        b.record_failure()
        assert b.state == "open" and b.opened_count == 2
        clk[0] = 12.0
        b.guard("t")
        b.record_success()                 # probe succeeds → recovery
        assert b.state == "closed"
        assert b.recoveries == 1
        # recovered: failures start from zero again
        b.record_failure()
        assert b.state == "closed"

    def test_threshold_validated(self):
        with pytest.raises(ValueError, match="threshold"):
            CircuitBreaker(threshold=0, cooldown_s=1.0)

    def test_stats_shape(self):
        b = CircuitBreaker(threshold=1, cooldown_s=1.0)
        s = b.stats()
        assert s["state"] == "closed"
        assert set(s) == {"state", "consecutive_failures", "opened",
                          "short_circuited", "probes", "recoveries"}


class TestCircuitBreakerInPipeline:
    def test_open_fallback_probe_recover(self, _clean_models):
        calls = {"n": 0}
        fail = {"on": True}

        def backend_fn(ts):
            calls["n"] += 1
            if fail["on"]:
                raise RuntimeError("backend down")
            return ts

        # infer_out skips the zero-probe at negotiation (the backend is
        # "down" from the start, but negotiation must still succeed)
        _clean_models("breaker_model", backend_fn, infer_out=lambda s: s)
        clk = [0.0]
        p = Pipeline("breaker")
        src = p.add(AppSrc(name="src", spec=TensorsSpec.from_strings(
            "2:2", "float32")))
        flt = p.add(TensorFilter(name="f", framework="custom",
                                 model="breaker_model",
                                 error_policy="skip"))
        sink = p.add(TensorSink(name="out"))
        p.link(src, flt)
        p.link(flt, sink)
        # injected clock makes cooldown fully deterministic
        flt._breaker = CircuitBreaker(threshold=2, cooldown_s=10.0,
                                      clock=lambda: clk[0])
        runner = PipelineRunner(p).start()
        frame = TensorBuffer.of(np.ones((2, 2), np.float32))
        try:
            st = lambda: runner.stats()["f"]
            src.push(frame)                # failure 1 (invoked)
            _wait_for(lambda: st()["errors"] == 1, what="first failure")
            src.push(frame)                # failure 2 → circuit opens
            _wait_for(lambda: st()["errors"] == 2, what="circuit open")
            assert flt._breaker.state == "open"
            assert calls["n"] == 2
            src.push(frame)                # short-circuited, backend idle
            _wait_for(lambda: st()["errors"] == 3, what="short circuit")
            assert calls["n"] == 2         # backend NOT touched
            assert flt._breaker.short_circuited == 1
            # heal the backend, let the cooldown elapse → probe recovers
            fail["on"] = False
            clk[0] = 11.0
            src.push(frame)
            src.end()
            runner.wait(timeout=10)
        finally:
            runner.stop()
        assert len(sink.results) == 1      # the probe frame came through
        d = runner.stats()["f"]
        assert d["skipped"] == 3
        assert d["breaker_state"] == "closed"
        assert d["breaker_opened"] == 1
        assert d["breaker_probes"] == 1
        assert d["breaker_recoveries"] == 1
        assert d["backend_invoke_failures"] == 2

    def test_breaker_props_build_breaker(self, _clean_models):
        _clean_models("ok_model", lambda ts: ts)
        p = parse_launch(
            "appsrc name=src dims=2:2 types=float32 ! "
            "tensor_filter name=f framework=custom model=ok_model "
            "breaker-threshold=3 breaker-cooldown-ms=250 ! "
            "tensor_sink name=out")
        runner = PipelineRunner(p).start()
        try:
            flt = p.get("f")
            assert flt._breaker is not None
            assert flt._breaker.threshold == 3
            assert flt._breaker.cooldown_s == 0.25
            p.get("src").end()
            runner.wait(timeout=10)
        finally:
            runner.stop()


# -- repo slot overflow (stop-aware put) -------------------------------------

class TestRepoSlot:
    def test_full_slot_raises_descriptive_stream_error(self):
        REPO.reset()
        sink = TensorRepoSink(slot=77, put_timeout=0.4)
        q = REPO.slot(77)
        buf = TensorBuffer.of(np.zeros((2,), np.float32))
        while True:                        # fill to capacity (16)
            try:
                q.put_nowait(buf)
            except _queue.Full:
                break
        t0 = time.monotonic()
        with pytest.raises(StreamError, match="slot 77"):
            sink.render(buf)
        assert time.monotonic() - t0 < 5.0  # honored put_timeout, not 10s
        REPO.reset()

    def test_teardown_aborts_blocked_put(self):
        REPO.reset()
        sink = TensorRepoSink(slot=78, put_timeout=30.0)
        evt = threading.Event()
        sink._stop_evt = evt
        q = REPO.slot(78)
        buf = TensorBuffer.of(np.zeros((2,), np.float32))
        while True:
            try:
                q.put_nowait(buf)
            except _queue.Full:
                break
        evt.set()
        t0 = time.monotonic()
        with pytest.raises(StreamError, match="stopping"):
            sink.render(buf)
        assert time.monotonic() - t0 < 5.0  # did not ride out 30s
        REPO.reset()


# -- upstream event errors ---------------------------------------------------

class _BadHandler(Element):
    ELEMENT_NAME = "bad_handler"

    def negotiate(self, in_specs):
        return [in_specs[0]]

    def process(self, pad, buf):
        return [(0, buf)]

    def handle_upstream_event(self, event):
        raise RuntimeError("handler exploded")


class TestUpstreamEventErrors:
    def test_broken_handler_does_not_consume_event(self):
        p = Pipeline("events")
        src = p.add(AppSrc(name="src", spec=TensorsSpec.from_strings(
            "2:2", "float32")))
        mid = p.add(_BadHandler(name="mid"))
        sink = p.add(TensorSink(name="out"))
        p.link(src, mid)
        p.link(mid, sink)
        runner = PipelineRunner(p).start()
        try:
            # QoS event from the sink must walk PAST the broken handler
            # and still reach (and be consumed by) the source
            sink.post_upstream_event(
                {"type": "qos", "min_interval_ns": 12345})
            assert src.qos_min_interval_ns == 12345
            assert runner.stats()["mid"]["event_errors"] == 1
            src.end()
            runner.wait(timeout=10)
        finally:
            runner.stop()


# -- error pickling (worker-pool wire contract) ------------------------------

# serving/pool.py ships exceptions across process boundaries; every
# public error class must survive pickle exactly — args, message, and
# any extra instance state (ServerBusyError.retry_after_ms etc.)
_ERR_INSTANCES = [
    errors_mod.NNStreamerTPUError("base"),
    errors_mod.ConfigError("bad [runtime] key: workers"),
    errors_mod.NegotiationError("dims mismatch 4:1 vs 8:1"),
    errors_mod.PipelineError("unbalanced tee"),
    errors_mod.BackendError("xla open failed"),
    errors_mod.SegmentStageError("conv0", ValueError("bad trace")),
    errors_mod.StreamError("flow error"),
    errors_mod.ServerBusyError(
        "server busy", queue_depth=17, retry_after_ms=12.5,
        cause="worker_lost", pts=42),
    errors_mod.FaultInjected("injected at pts=3"),
    errors_mod.WatchdogStall("element x stalled 2.0s"),
    errors_mod.CircuitOpenError("breaker open, 3 failures"),
]


class TestErrorPickling:
    @pytest.mark.parametrize(
        "exc", _ERR_INSTANCES, ids=lambda e: type(e).__name__)
    def test_round_trip_preserves_type_args_and_state(self, exc):
        back = pickle.loads(pickle.dumps(exc))
        assert type(back) is type(exc)
        assert back.args == exc.args
        assert str(back) == str(exc)
        state = {k: v for k, v in exc.__dict__.items()}
        assert {k: str(v) if isinstance(v, BaseException) else v
                for k, v in back.__dict__.items()} == \
               {k: str(v) if isinstance(v, BaseException) else v
                for k, v in state.items()}

    def test_every_public_error_class_is_covered(self):
        # a new error class must be added to _ERR_INSTANCES above, or
        # it ships without a pickling guarantee
        public = {
            obj for name, obj in vars(errors_mod).items()
            if isinstance(obj, type)
            and issubclass(obj, Exception)
            and not name.startswith("_")
        }
        covered = {type(e) for e in _ERR_INSTANCES}
        assert public == covered, (
            f"uncovered: {public - covered}, stale: {covered - public}")

    def test_rich_state_survives(self):
        e = errors_mod.ServerBusyError(
            "busy", queue_depth=9, retry_after_ms=7.0,
            cause="shutdown", pts=5)
        back = pickle.loads(pickle.dumps(e))
        assert (back.queue_depth, back.retry_after_ms,
                back.cause, back.pts) == (9, 7.0, "shutdown", 5)
        e2 = errors_mod.SegmentStageError("head", KeyError("w"))
        back2 = pickle.loads(pickle.dumps(e2))
        assert back2.member == "head"
        assert "head" in str(back2)
