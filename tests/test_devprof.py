"""Device performance plane + SLO-breach flight recorder
(runtime/devprof.py, runtime/flightrec.py, docs/observability.md).

The contracts that matter: MFU math against an injected peak table
(declared peak → mfu; no peak → mfu 0 + measured calibration), the
dispatch→device_sync sampling choke point, one-scrape export of every
``nns_jit_*`` / ``nns_invoke_*`` / ``nns_device_*`` family with the
invoke-seconds ledger reconcilable against what was sampled, and the
flight recorder's forensic guarantees — exactly one complete bundle
per trigger within a cooldown window, never a partial bundle visible,
nothing at steady state.
"""

import json
import os
import threading

import numpy as np
import pytest

from nnstreamer_tpu.runtime import devprof
from nnstreamer_tpu.runtime.devprof import (
    DeviceProfiler, bucket_label, peak_for)
from nnstreamer_tpu.runtime.flightrec import (
    FlightRecorder, list_bundles, load_bundle)
from nnstreamer_tpu.runtime.sync import device_sync
from nnstreamer_tpu.runtime.tracing import NULL_TRACER, Tracer
from nnstreamer_tpu.serving.metrics import (
    metrics_snapshot, parse_prometheus, render_prometheus)


# -- profiler core -----------------------------------------------------------

class TestDeviceProfiler:
    def test_disabled_profiler_records_nothing(self):
        p = DeviceProfiler()
        p.note_compile("f", "b", seconds=1.0, flops=10.0)
        p.note_dispatch("f", "b")
        p.sample_sync()
        p.note_invoke("f", "b", 0.5)
        st = p.stats()
        assert st["enabled"] is False
        assert st["jit"] == [] and st["invoke"] == []

    def test_compile_registry_overwrites_cost_accumulates_seconds(self):
        p = DeviceProfiler().enable()
        p.note_compile("f", "b", seconds=1.0, flops=100.0,
                       bytes_accessed=50.0)
        p.note_compile("f", "b", seconds=0.5, flops=200.0)
        (row,) = p.stats()["jit"]
        # flops are a property of the program: last estimate wins;
        # wall seconds are spend: they add up
        assert row["flops"] == 200.0 and row["bytes_accessed"] == 50.0
        assert row["compile_s"] == pytest.approx(1.5)
        assert row["compiles"] == 2

    def test_mfu_and_roofline_against_injected_peak(self):
        # 100 TFLOP/s peak, 1000 GB/s peak -> ridge = 100e12/1000e9
        # = 100 flops/byte
        p = DeviceProfiler(peak_tflops=100.0, peak_hbm_gbps=1000.0)
        p.enable()
        # compute-bound bucket: ai = 2e12/1e9 = 2000 >= ridge
        p.note_compile("f", "hot", seconds=0.1, flops=2e12,
                       bytes_accessed=1e9)
        # memory-bound bucket: ai = 1e9/1e9 = 1 < ridge
        p.note_compile("f", "cold", seconds=0.1, flops=1e9,
                       bytes_accessed=1e9)
        for _ in range(5):
            p.note_invoke("f", "hot", 0.040)   # 2e12/0.04 = 50 TFLOP/s
            p.note_invoke("f", "cold", 0.010)
        st = p.stats()
        by_bucket = {r["bucket"]: r for r in st["jit"]}
        assert by_bucket["hot"]["roofline"] == "compute"
        assert by_bucket["cold"]["roofline"] == "memory"
        inv = {r["bucket"]: r for r in st["invoke"]}
        assert inv["hot"]["achieved_tflops"] == pytest.approx(50.0)
        assert inv["hot"]["mfu"] == pytest.approx(0.5)
        assert inv["hot"]["seconds_total"] == pytest.approx(0.2)
        assert inv["hot"]["samples_total"] == 5

    def test_cpu_fallback_mfu_zero_calibrated_set(self):
        # no declared peak (CPU emulation): mfu must report 0 — never a
        # made-up denominator — and mfu_calibrated ratios against the
        # best achieved TFLOP/s so buckets stay comparable
        p = DeviceProfiler(peak_tflops=0.0, peak_hbm_gbps=0.0).enable()
        p.note_compile("f", "fast", seconds=0.1, flops=1e9)
        p.note_compile("f", "slow", seconds=0.1, flops=1e9)
        p.note_invoke("f", "fast", 0.001)
        p.note_invoke("f", "slow", 0.002)
        st = p.stats()
        inv = {r["bucket"]: r for r in st["invoke"]}
        assert all(r["mfu"] == 0.0 for r in st["invoke"])
        assert inv["fast"]["mfu_calibrated"] == pytest.approx(1.0)
        assert inv["slow"]["mfu_calibrated"] == pytest.approx(0.5)
        assert {r["roofline"] for r in st["jit"]} == {"unknown"}
        assert st["calibration_tflops"] > 0

    def test_peak_table_prefix_match(self):
        assert peak_for("TPU v4") == (275.0, 1228.0)
        assert peak_for("TPU v5e") == (197.0, 819.0)
        assert peak_for("TPU v4 pod slice")[0] == 275.0
        assert peak_for("cpu") == (0.0, 0.0)
        assert peak_for("") == (0.0, 0.0)

    def test_bucket_label_forms(self):
        assert bucket_label(()) == "static"
        assert bucket_label(
            ("fix", ((1, 224, 224, 3), "uint8"), "x")) == \
            "fix:1x224x224x3"
        assert bucket_label(("dynb", 8, "y")) == "dynb:8"

    def test_dispatch_sample_closed_by_device_sync(self):
        # the choke-point contract: a thread-local dispatch stamp is
        # closed by the next device_sync on the same thread
        import jax

        prof = devprof.get()
        prof.reset()
        prof.enable(True)
        try:
            x = jax.device_put(np.ones((4,), np.float32))
            prof.note_dispatch("filt", "b")
            device_sync((x,), forced=True)
            st = prof.stats()
            (row,) = st["invoke"]
            assert (row["filter"], row["bucket"]) == ("filt", "b")
            assert row["samples_total"] == 1
            # no pending stamp -> the next sync takes no sample
            device_sync((x,), forced=True)
            assert prof.stats()["invoke"][0]["samples_total"] == 1
        finally:
            prof.enable(False)
            prof.reset()

    def test_sample_is_per_thread(self):
        p = DeviceProfiler().enable()
        p.note_dispatch("f", "b")
        closed = []

        def other():
            p.sample_sync()          # no stamp on THIS thread
            closed.append(p.stats()["invoke"])

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert closed == [[]]        # the other thread took no sample
        p.sample_sync()
        assert p.stats()["invoke"][0]["samples_total"] == 1

    def test_capture_cost_reads_xla_cost_model(self):
        import jax

        p = DeviceProfiler().enable()
        jitted = jax.jit(lambda a, b: a @ b)
        x = np.ones((8, 8), np.float32)
        jitted(x, x)                 # compile
        p.capture_cost("f", "mm", jitted, (x, x), seconds=0.01)
        (row,) = p.stats()["jit"]
        assert row["flops"] > 0      # 8x8x8 matmul: cost model saw it
        assert row["compile_s"] == pytest.approx(0.01)

    def test_capture_cost_failure_degrades_to_seconds_only(self):
        p = DeviceProfiler().enable()
        p.capture_cost("f", "b", object(), (1,), seconds=0.25)
        (row,) = p.stats()["jit"]
        assert row["flops"] == 0.0
        assert row["compile_s"] == pytest.approx(0.25)

    def test_model_attribution_rows_and_weakref_release(self):
        class Backend:
            def resident_bytes(self):
                return 1234

        p = DeviceProfiler().enable()
        be = Backend()
        p.attach_model("m", be)
        rows = [r for r in p.hbm_rows() if r["kind"] == "model:m"]
        assert rows and rows[0]["bytes"] == 1234.0
        del be                       # released model leaves the ledger
        assert not [r for r in p.hbm_rows() if r["kind"] == "model:m"]

    def test_counter_tracks_shapes(self):
        p = DeviceProfiler(peak_tflops=100.0).enable()
        p.note_compile("f", "b", seconds=0.1, flops=1e12)
        p.note_invoke("f", "b", 0.1)
        names = [n for n, _ in p.counter_tracks()]
        assert "mfu:f/b" in names


# -- one-scrape exposition ---------------------------------------------------

def _plane(peak=100.0, bw=1000.0):
    p = DeviceProfiler(peak_tflops=peak, peak_hbm_gbps=bw).enable()
    p.note_compile('we"ird\\f', "b:1", seconds=0.5, flops=2e12,
                   bytes_accessed=1e9)
    for _ in range(3):
        p.note_invoke('we"ird\\f', "b:1", 0.040)
    return p


class TestExposition:
    FAMILIES = ("nns_jit_flops", "nns_jit_bytes_accessed",
                "nns_jit_roofline_info", "nns_compile_seconds_total",
                "nns_compiles_total", "nns_invoke_mfu",
                "nns_invoke_mfu_calibrated", "nns_invoke_tflops",
                "nns_invoke_seconds_total", "nns_invoke_samples_total",
                "nns_device_hbm_bytes", "nns_device_hbm_headroom",
                "nns_device_peak_tflops",
                "nns_device_calibration_tflops")

    def test_every_family_round_trips_with_type_and_help(self):
        text = render_prometheus(metrics_snapshot(
            devprof=_plane().stats()))
        parsed = parse_prometheus(text)
        for fam in self.FAMILIES:
            assert fam in parsed, f"family {fam} missing"
            assert parsed[fam].get("type"), f"no TYPE for {fam}"
            assert parsed[fam].get("help"), f"no HELP for {fam}"
        assert parsed["nns_compile_seconds_total"]["type"] == "counter"
        assert parsed["nns_invoke_seconds_total"]["type"] == "counter"
        assert parsed["nns_invoke_mfu"]["type"] == "gauge"

    def test_label_escaping_round_trips(self):
        text = render_prometheus(metrics_snapshot(
            devprof=_plane().stats()))
        # the filter name carries a quote and a backslash; a scraper
        # must see them escaped, and the parser must round-trip them
        assert '\\"' in text and "\\\\" in text
        parsed = parse_prometheus(text)
        keys = list(parsed["nns_jit_flops"]["samples"])
        # the parser keeps the exposition (escaped) form of the key
        assert any('we\\"ird\\\\f' in k for k in keys), keys

    def test_counters_monotone_across_scrapes(self):
        p = _plane()
        s1 = parse_prometheus(render_prometheus(
            metrics_snapshot(devprof=p.stats())))
        p.note_invoke('we"ird\\f', "b:1", 0.040)
        p.note_compile('we"ird\\f', "b:1", seconds=0.1, flops=2e12)
        s2 = parse_prometheus(render_prometheus(
            metrics_snapshot(devprof=p.stats())))
        for fam in ("nns_compile_seconds_total", "nns_compiles_total",
                    "nns_invoke_seconds_total",
                    "nns_invoke_samples_total"):
            for k, v1 in s1[fam]["samples"].items():
                assert s2[fam]["samples"][k] >= v1, fam

    def test_invoke_seconds_reconcile_with_sampled_ledger(self):
        # the reconciliation contract: Σ nns_invoke_seconds_total from
        # ONE scrape equals exactly the device-seconds the profiler
        # sampled — the same observations a tracer proctime sum is
        # made of when both planes watch the same sync-latency filter
        p = DeviceProfiler(peak_tflops=100.0).enable()
        tr = Tracer()
        durations = [0.010, 0.020, 0.015, 0.040]
        t = 0.0
        for d in durations:
            p.note_invoke("f", "b", d)
            tr.record_process("f", None, t, t + d)
            t += d
        text = render_prometheus(metrics_snapshot(
            tracer=tr, devprof=p.stats()))
        parsed = parse_prometheus(text)
        inv = sum(v for k, v in
                  parsed["nns_invoke_seconds_total"]["samples"].items())
        proc = [v for k, v in
                parsed["nns_element_proctime_seconds"]["samples"].items()
                if k.endswith("_sum}") or "_sum{" in k]
        assert inv == pytest.approx(sum(durations), rel=1e-6)
        assert proc and proc[0] == pytest.approx(inv, rel=1e-6)

    def test_top_families_include_new_rows(self):
        from nnstreamer_tpu.serving.metrics import _TOP_KEY_FAMILIES

        for fam in ("nns_llm_tokens_total", "nns_llm_kernel_invokes_total",
                    "nns_llm_prefilling", "nns_invoke_mfu",
                    "nns_device_hbm_headroom"):
            assert fam in _TOP_KEY_FAMILIES


# -- backend integration -----------------------------------------------------

class TestBackendCapture:
    def test_xla_backend_reports_compile_and_invoke(self):
        from nnstreamer_tpu.backends.xla import XLABackend

        prof = devprof.get()
        prof.reset()
        prof.enable(True)
        try:
            be = XLABackend()
            be.open({"model": "zoo://mobilenet_v2", "custom": ""})
            x = np.zeros((1, 224, 224, 3), np.uint8)
            for _ in range(2):
                out = be.invoke((x,))
                device_sync(out, forced=True)
            st = prof.stats()
            (jit,) = st["jit"]
            assert jit["compiles"] == 1          # bucket cache: one compile
            assert jit["flops"] > 0 and jit["bytes_accessed"] > 0
            assert st["invoke"][0]["samples_total"] >= 1
            # executor-level HBM attribution row present
            assert any(r["kind"].startswith("model:")
                       for r in st["hbm"])
            be.close()
        finally:
            prof.enable(False)
            prof.reset()

    def test_profiler_off_is_default_and_free(self):
        prof = devprof.get()
        assert prof.enabled is False


# -- flight recorder ---------------------------------------------------------

class TestFlightRecorder:
    def _rec(self, tmp_path, **kw):
        clock = [0.0]
        rec = FlightRecorder(str(tmp_path), cooldown_s=60.0,
                             clock=lambda: clock[0], **kw)
        return rec, clock

    def test_steady_state_produces_no_bundle(self, tmp_path):
        rec, _ = self._rec(tmp_path)
        ok = {"offered": 10, "replied": 7, "rejected": {"b": 1},
              "shed": {}, "depth": 1, "inflight": 1}
        for _ in range(5):
            assert rec.scan(admission=ok, p99_ms=50.0,
                            p99_budget_ms=100.0) == []
        assert list_bundles(str(tmp_path)) == []
        assert rec.stats()["dumps_total"] == 0

    def test_slo_breach_one_bundle_per_cooldown_window(self, tmp_path):
        rec, clock = self._rec(tmp_path)
        p1 = rec.note_slo_breach(120.0, 100.0)
        assert p1 and os.path.isdir(p1)
        # within the window: suppressed, counted, no second bundle
        assert rec.note_slo_breach(130.0, 100.0) is None
        assert len(list_bundles(str(tmp_path))) == 1
        clock[0] += 61.0
        assert rec.note_slo_breach(140.0, 100.0) is not None
        assert len(list_bundles(str(tmp_path))) == 2
        st = rec.stats()
        assert st["dumps"]["slo_breach"] == 2
        assert st["suppressed"]["slo_breach"] == 1

    def test_conservation_needs_two_consecutive_scans(self, tmp_path):
        rec, _ = self._rec(tmp_path)
        bad = {"offered": 10, "replied": 5, "rejected": {}, "shed": {},
               "depth": 1, "inflight": 1}
        ok = dict(bad, replied=8)
        assert rec.scan(admission=bad) == []       # first mismatch: slack
        assert rec.scan(admission=ok) == []        # match resets streak
        assert rec.scan(admission=bad) == []
        fired = rec.scan(admission=bad)            # second consecutive
        assert fired == ["conservation"]
        b = list_bundles(str(tmp_path))
        assert [x["kind"] for x in b] == ["conservation"]
        assert b[0]["cause"]["consecutive_scans"] == 2

    def test_watermarked_triggers_baseline_first_observation(self,
                                                             tmp_path):
        rec, _ = self._rec(tmp_path)
        # historical faults at attach time must NOT dump
        wc = {"pool": {"kill": 3}}
        assert rec.scan(worker_counts=wc) == []
        # a RISE past the watermark does
        assert rec.scan(worker_counts={"pool": {"kill": 4}}) == \
            ["worker_fence"]
        # same for kernel fallbacks
        assert rec.scan(kernel_fallbacks=2.0) == []
        assert rec.scan(kernel_fallbacks=3.0) == ["kernel_fallback"]

    def test_bundle_is_complete_and_atomic(self, tmp_path):
        rec, _ = self._rec(tmp_path)
        tr = Tracer()
        tr.record_process("el", None, 0.0, 0.01)
        rec.attach(tracer=tr, prom=lambda: "# scrape\n",
                   env=lambda: {"k": "v"})
        rec.tick({"gauge": 1})
        path = rec.trigger("manual", {"why": "test"})
        # no temp residue, no dot-entries visible
        assert not [n for n in os.listdir(str(tmp_path))
                    if n.startswith(".")]
        b = load_bundle(path)
        assert b["cause"]["kind"] == "manual"
        assert b["cause"]["cause"] == {"why": "test"}
        assert b["env"] == {"k": "v"}
        assert b["metrics.prom"] == "# scrape\n"
        assert b["snapshots"][0]["snapshot"] == {"gauge": 1}
        assert any(ev.get("ph") for ev in b["trace"]["traceEvents"])
        # ... and the dump itself is on the tracer's keep-whole record
        assert [k for k, _, _ in tr.flight_dumps()] == ["manual"]

    def test_failed_dump_does_not_eat_the_cooldown(self, tmp_path,
                                                   monkeypatch):
        rec, _ = self._rec(tmp_path)

        def boom(*a, **k):
            raise OSError("disk full")

        monkeypatch.setattr(rec, "_dump", boom)
        with pytest.raises(RuntimeError):
            rec.trigger("manual", {})
        monkeypatch.undo()
        # the window was not consumed: the next trigger dumps
        assert rec.trigger("manual", {}) is not None

    def test_list_bundles_ignores_dot_and_foreign_entries(self,
                                                          tmp_path):
        rec, _ = self._rec(tmp_path)
        rec.trigger("manual", {})
        os.makedirs(str(tmp_path / ".tmp-flight-9999-manual-1"))
        os.makedirs(str(tmp_path / "not-a-bundle"))
        (tmp_path / "flight-0002-file").write_text("not a dir")
        names = [b["name"] for b in list_bundles(str(tmp_path))]
        assert names == ["flight-0001-manual"]

    def test_autotuner_feeds_slo_breaches(self, tmp_path):
        from nnstreamer_tpu.serving.autotune import AutoTuner, SLOSpec

        class P99Tracer:
            active = True

            def tenant_summary(self):
                return {"t0": {"p99_ms": 250.0}}

        rec, clock = self._rec(tmp_path)
        tuner = AutoTuner(SLOSpec(p99_budget_ms=100.0),
                          tracer=P99Tracer())
        rec.attach(autotune=tuner)
        assert tuner.flight is rec        # attach wires the feed
        tuner.tick()
        b = list_bundles(str(tmp_path))
        assert [x["kind"] for x in b] == ["slo_breach"]
        assert b[0]["cause"]["p99_ms"] == 250.0
        tuner.tick()                      # cooldown: still one bundle
        assert len(list_bundles(str(tmp_path))) == 1

    def test_poll_reads_attached_tracer_counters(self, tmp_path):
        rec, _ = self._rec(tmp_path)
        tr = Tracer()
        rec.attach(tracer=tr)
        # first nonzero observation per source only baselines
        tr.record_worker_event("pool", 0, "kill", 0.0)
        tr.record_watchdog("el", "stall", 0.0)
        assert rec.poll() == []
        tr.record_worker_event("pool", 1, "fence", 1.0)
        assert "worker_fence" in rec.poll()
        tr.record_watchdog("el", "stall", 2.0)
        assert "watchdog" in rec.poll()
        # benign lifecycle kinds (spawn/ready) never count as faults
        tr.record_worker_event("pool", 2, "spawn", 3.0)
        assert rec.poll() == []


# -- tracer hooks ------------------------------------------------------------

class TestTracerHooks:
    def test_null_tracer_twins_noop(self):
        # flightrec + devprof call these unguarded on whatever tracer
        # is wired; the null twin must absorb every one
        NULL_TRACER.record_flight("manual", 0.0, path="/x")
        NULL_TRACER.record_device_counter("mfu:f/b", 0.5, 0.0)
        NULL_TRACER.record_watchdog("el", "stall", 0.0)
        assert NULL_TRACER.flight_dumps() == []
        assert NULL_TRACER.watchdog_counts() == {}
        assert NULL_TRACER.worker_counts() == {}

    def test_watchdog_counts_survive_ring_wrap(self):
        tr = Tracer(max_events=4)
        for _ in range(10):
            tr.record_watchdog("el", "stall", 0.0)
        tr.record_watchdog("el", "queue", 0.0)
        assert tr.watchdog_counts() == {"el": {"stall": 10, "queue": 1}}

    def test_devprof_counter_track_in_chrome_trace(self):
        tr = Tracer()
        tr.record_device_counter("mfu:f/b", 0.5, 0.0)
        tr.record_inflight("el", 3, 0.0)
        evs = tr.to_chrome_trace("t")["traceEvents"]
        c = [e for e in evs if e.get("ph") == "C"]
        dev = [e for e in c if e.get("cat") == "devprof"]
        assert dev and dev[0]["name"] == "mfu:f/b"
        assert dev[0]["args"] == {"value": 0.5}
        # the existing depth-track rendering is untouched
        infl = [e for e in c if e.get("cat") == "inflight"]
        assert infl and infl[0]["args"] == {"depth": 3}

    def test_record_flight_instant_event(self):
        tr = Tracer()
        tr.record_flight("slo_breach", 1.0, path="/p")
        assert tr.flight_dumps() == [("slo_breach", 1.0,
                                      {"path": "/p"})]


# -- CLI ---------------------------------------------------------------------

class TestFlightCLI:
    def test_flight_list_and_inspect(self, tmp_path, capsys):
        from nnstreamer_tpu.__main__ import main

        rec = FlightRecorder(str(tmp_path))
        rec.trigger("manual", {"why": "cli"})
        assert main(["flight", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "flight-0001-manual" in out and "manual" in out
        assert main(["flight", str(tmp_path), "--json"]) == 0
        rows = json.loads(capsys.readouterr().out)
        assert rows[0]["kind"] == "manual"
        assert main(["flight", str(tmp_path),
                     "--inspect", "flight-0001-manual"]) == 0
        b = json.loads(capsys.readouterr().out)
        assert b["cause"]["cause"] == {"why": "cli"}

    def test_flight_empty_dir_exits_nonzero(self, tmp_path, capsys):
        from nnstreamer_tpu.__main__ import main

        assert main(["flight", str(tmp_path)]) == 1
