"""SSAT-style golden pipeline tests: full DSL strings in, byte-compared
output out — the reference's second test tier (SURVEY.md §4: 44
runTest.sh scripts driving gst-launch pipelines), in-process.

Includes negative cases ("passes if launch fails") exactly like SSAT's
gstTest failure-expected mode.
"""

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.core.errors import NegotiationError, PipelineError


def launch_and_run(desc, pushes=None, timeout=60):
    pipe = nns.parse_launch(desc)
    runner = nns.PipelineRunner(pipe)
    runner.start()
    if pushes:
        src = pipe.get(pushes[0])
        for b in pushes[1]:
            src.push(b)
        src.end()
    runner.wait(timeout)
    runner.stop()
    return pipe


# -- golden pipelines --------------------------------------------------------

def test_videotestsrc_convert_transform_golden():
    pipe = launch_and_run(
        "videotestsrc num-buffers=3 pattern=gradient width=8 height=6 ! "
        "tensor_converter ! "
        "tensor_transform mode=typecast option=float32 ! "
        "tensor_sink name=s")
    res = pipe.get("s").results
    assert len(res) == 3
    out = res[0].tensors[0]
    assert out.shape == (1, 6, 8, 3) and out.dtype == np.float32
    # golden: re-derive the expected gradient frame deterministically
    pipe2 = nns.parse_launch(
        "videotestsrc num-buffers=1 pattern=gradient width=8 height=6 ! "
        "tensor_sink name=s")
    nns.run_pipeline(pipe2, timeout=30)
    raw = pipe2.get("s").results[0].tensors[0]
    np.testing.assert_array_equal(out[0], raw.astype(np.float32))


def test_transform_chain_matches_numpy_golden():
    rng = np.random.default_rng(3)
    frames = rng.integers(0, 255, size=(4, 5), dtype=np.uint8)
    import tempfile, os

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "f.npy")
        np.save(path, frames[None])
        pipe = launch_and_run(
            f"filesrc location={path} ! "
            "tensor_transform mode=arithmetic "
            "option=typecast:float32,add:-127.5,div:127.5 ! "
            "tensor_transform mode=clamp option=-0.5:0.5 ! "
            "tensor_sink name=s")
        out = pipe.get("s").results[0].tensors[0]
        golden = np.clip((frames.astype(np.float32) - 127.5) / 127.5,
                         -0.5, 0.5)
        np.testing.assert_allclose(out, golden, rtol=1e-6)


def test_mux_demux_roundtrip_dsl():
    pipe = launch_and_run(
        "videotestsrc num-buffers=2 width=4 height=4 pattern=random ! "
        "tensor_converter ! tee name=t "
        "t. ! queue ! mux.sink_0 "
        "t. ! queue ! tensor_transform mode=typecast option=uint8 ! mux.sink_1 "
        "tensor_mux name=mux sync-mode=nosync ! "
        "tensor_demux name=d tensorpick=1 ! tensor_sink name=s")
    res = pipe.get("s").results
    assert len(res) == 2
    assert res[0].num_tensors == 1


def test_wire_codec_roundtrip_dsl():
    """decoder mode=wire → converter custom:wire restores the stream
    (the flatbuf/protobuf IPC serialization path)."""
    pipe = launch_and_run(
        "videotestsrc num-buffers=2 width=4 height=4 pattern=random ! "
        "tensor_converter ! tee name=t "
        "t. ! queue ! tensor_sink name=orig "
        "t. ! queue ! tensor_decoder mode=wire ! "
        "tensor_converter name=back mode=custom:wire ! tensor_sink name=s")
    orig = pipe.get("orig").results
    back = pipe.get("s").results
    assert len(back) == len(orig) == 2
    for a, b in zip(orig, back):
        np.testing.assert_array_equal(a.tensors[0], b.tensors[0])


def test_ssd_detection_pipeline_dsl():
    """BASELINE.md config 2 shape, tiny width: model → bbox decoder."""
    pipe = launch_and_run(
        "videotestsrc num-buffers=1 width=300 height=300 pattern=solid ! "
        "tensor_converter ! "
        "tensor_transform mode=arithmetic "
        "option=typecast:float32,add:-127.5,div:127.5 ! "
        "tensor_filter model=zoo://ssd_mobilenet?width=0.35&num_classes=4&dtype=float32 ! "
        "tensor_decoder mode=bounding_boxes option1=mobilenet-ssd "
        "option3=0.0:0.5 option4=300:300 ! tensor_sink name=s",
        timeout=300)
    out = pipe.get("s").results[0]
    assert out.tensors[0].shape == (300, 300, 4)  # RGBA overlay
    assert "boxes" in out.meta


def test_posenet_pipeline_dsl():
    """BASELINE.md config 3 shape, tiny width: posenet → pose decoder."""
    pipe = launch_and_run(
        "videotestsrc num-buffers=1 width=129 height=129 pattern=gradient ! "
        "tensor_converter ! "
        "tensor_transform mode=arithmetic "
        "option=typecast:float32,add:-127.5,div:127.5 ! "
        "tensor_filter model=zoo://posenet?width=0.35&input_size=129&dtype=float32 ! "
        "tensor_decoder mode=pose_estimation option1=129:129 option4=0.0 ! "
        "tensor_sink name=s",
        timeout=300)
    out = pipe.get("s").results[0]
    assert out.meta["keypoints"].shape == (17, 3)


def test_composite_mux_two_filters_demux():
    """BASELINE.md config 4 shape: one source, two models, joined."""
    from nnstreamer_tpu.backends.custom import register_custom_easy

    register_custom_easy("branch_a", lambda ts: (ts[0] * 2.0,))
    register_custom_easy("branch_b", lambda ts: (ts[0] + 1.0,))
    pipe = launch_and_run(
        "videotestsrc num-buffers=3 width=4 height=4 pattern=random ! "
        "tensor_converter ! tensor_transform mode=typecast option=float32 ! "
        "tee name=t "
        "t. ! queue ! tensor_filter framework=custom model=branch_a ! mux.sink_0 "
        "t. ! queue ! tensor_filter framework=custom model=branch_b ! mux.sink_1 "
        "tensor_mux name=mux sync-mode=nosync ! tensor_sink name=s")
    res = pipe.get("s").results
    assert len(res) == 3
    a, b = res[0].tensors
    np.testing.assert_allclose(np.asarray(a), np.asarray(b) * 2 - 2)


# -- negative tests (SSAT "passes if launch fails") --------------------------

@pytest.mark.parametrize("desc,match", [
    ("videotestsrc ! tensor_filter model=zoo://mobilenet_v2 ! fakesink",
     "tensor_converter|tensor stream"),         # media straight into filter
    ("videotestsrc ! tensor_converter ! tensor_transform mode=nope ! fakesink",
     "mode"),                                   # bad transform mode
    ("appsrc dims=4 ! tensor_decoder mode=direct_video ! fakesink",
     "uint8"),                                  # wrong dtype for decoder
    ("appsrc dims=4 ! tensor_split tensorseg=9 ! fakesink",
     "tensorseg"),                              # segments don't sum
    ("appsrc dims=4 ! tensor_merge option=channel ! fakesink",
     "rank|axis"),                              # keyword on rank-1
])
def test_negative_pipelines_fail_cleanly(desc, match):
    with pytest.raises((NegotiationError, PipelineError), match=match):
        pipe = nns.parse_launch(desc)
        pipe.negotiate()


def test_unknown_element_error_lists_alternatives():
    with pytest.raises(Exception, match="tensor_filter"):
        nns.parse_launch("videotestsrc ! tensor_fliter ! fakesink")


def test_crop_resize_filter_roi_pipeline():
    """Data-driven ROI inference: crop (flexible) → resize (static) →
    model — SURVEY.md §7 hard part (d) end-to-end."""
    from nnstreamer_tpu.backends.custom import register_custom_easy
    from nnstreamer_tpu.elements import AppSrc, TensorCrop, TensorFilter, TensorSink
    from nnstreamer_tpu.elements.transform import TensorResize
    from nnstreamer_tpu.tensor.buffer import TensorBuffer
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    register_custom_easy("roi_mean", lambda ts: (ts[0].astype(np.float32).mean(
        axis=(0, 1), keepdims=True),))
    raw = AppSrc(spec=TensorsSpec.of(
        TensorInfo((16, 16, 3), DType.UINT8)), name="raw")
    info = AppSrc(spec=TensorsSpec.of(
        TensorInfo((2, 4), DType.UINT32)), name="info")
    crop = TensorCrop(name="c")
    rs = TensorResize(name="r", size="8:8", channels=3)
    f = TensorFilter(name="f", framework="custom", model="roi_mean")
    sink = TensorSink(name="s")
    pipe = nns.Pipeline()
    for e in (raw, info, crop, rs, f, sink):
        pipe.add(e)
    pipe.link(raw, crop, 0, 0)
    pipe.link(info, crop, 0, 1)
    pipe.link(crop, rs)
    pipe.link(rs, f)
    pipe.link(f, sink)
    runner = nns.PipelineRunner(pipe).start()
    img = np.zeros((16, 16, 3), np.uint8)
    img[:8, :8] = 100   # region 1 bright, region 2 dark
    regions = np.array([[0, 0, 8, 8], [8, 8, 8, 8]], np.uint32)
    raw.push(TensorBuffer.of(img, pts=0))
    info.push(TensorBuffer.of(regions, pts=0))
    raw.end(); info.end()
    runner.wait(60)
    res = pipe.get("s").results
    assert len(res) == 2  # one inference per region
    means = sorted(float(r.tensors[0].reshape(-1)[0]) for r in res)
    assert means[0] == 0.0 and means[1] == 100.0
    assert {r.meta["region_index"] for r in res} == {0, 1}


def test_resize_static_bilinear_and_nearest():
    from nnstreamer_tpu.elements.transform import TensorResize
    from nnstreamer_tpu.elements import AppSrc, TensorSink
    from nnstreamer_tpu.tensor.buffer import TensorBuffer
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    for method in ("nearest", "bilinear"):
        src = AppSrc(spec=TensorsSpec.of(
            TensorInfo((1, 4, 4, 1), DType.FLOAT32)), name="src")
        rs = TensorResize(name="r", size="8:8", method=method)
        sink = TensorSink(name="s")
        pipe = nns.Pipeline()
        for e in (src, rs, sink):
            pipe.add(e)
        pipe.link(src, rs)
        pipe.link(rs, sink)
        assert rs.out_specs == []  # not negotiated yet
        runner = nns.PipelineRunner(pipe).start()
        src.push(TensorBuffer.of(
            np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1), pts=0))
        src.end()
        runner.wait(60)
        out = pipe.get("s").results[0].tensors[0]
        assert out.shape == (1, 8, 8, 1)
