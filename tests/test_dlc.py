"""SNPE `.dlc` ingestion goldens.

Uses the reference's own checked-in add2 containers and the reference's
own test semantics (`tests/nnstreamer_filter_snpe/unittest_filter_snpe
.cc:167-258`): y = x + 2 exact — input 0 → 2, 10 → 12, 1 → 3 — with
float32 I/O for add2_float.dlc and uint8 I/O for add2_uint8.dlc (the
reference passes custom "InputType:uint8,OutputType:uint8"; the
container itself marks the input as image-typed, which this loader
honors without the custom property)."""

import os

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.modelio import load_model_file
from nnstreamer_tpu.modelio.dlc import lower_dlc, parse_dlc
from nnstreamer_tpu.tensor.buffer import TensorBuffer

MODELS = "/root/reference/tests/test_models/models"
DLC_FLOAT = os.path.join(MODELS, "add2_float.dlc")
DLC_UINT8 = os.path.join(MODELS, "add2_uint8.dlc")

needs_models = pytest.mark.skipif(
    not (os.path.exists(DLC_FLOAT) and os.path.exists(DLC_UINT8)),
    reason="reference test models absent")


def _run(bundle, x):
    import jax

    return np.asarray(jax.jit(
        lambda p, a: bundle.fn(p, a))(bundle.params, x)[0])


@needs_models
def test_parse_dlc_structure():
    g = parse_dlc(DLC_FLOAT)
    assert [(l.name, l.type) for l in g.layers] == [
        ("X_input", "Input"),
        ("elementwise_sum_0_const", "Const"),
        ("elementwise_sum_0", "ElementwiseBinaryOp")]
    assert g.buffer_dims["X_input"] == (1,)
    assert g.buffer_dims["ADD_TOP"] == (1,)
    w = g.params["elementwise_sum_0_const"]
    np.testing.assert_array_equal(w, np.asarray([2.0], np.float32))
    assert "snpe-tflite-to-dlc" in g.metadata


@needs_models
def test_dlc_float_add2_golden():
    """Reference invoke00: 0→2, 10→12, 1→3, float32 exact."""
    b = load_model_file(DLC_FLOAT)
    assert b.in_spec.tensors[0].dtype.np_dtype == np.float32
    assert b.out_spec.tensors[0].dtype.np_dtype == np.float32
    for xin, want in ((0.0, 2.0), (10.0, 12.0), (1.0, 3.0)):
        y = _run(b, np.asarray([xin], np.float32))
        assert y.shape == (1,)
        assert y[0] == want


@needs_models
def test_dlc_uint8_add2_golden():
    """Reference invoke01: uint8 I/O, 0→2, 10→12, 1→3 exact."""
    b = load_model_file(DLC_UINT8)
    assert b.in_spec.tensors[0].dtype.np_dtype == np.uint8
    assert b.out_spec.tensors[0].dtype.np_dtype == np.uint8
    for xin, want in ((0, 2), (10, 12), (1, 3)):
        y = _run(b, np.asarray([xin], np.uint8))
        assert y.dtype == np.uint8
        assert int(y[0]) == want


@needs_models
def test_dlc_pipeline_end_to_end():
    """tensor_filter auto-detects .dlc by extension and runs it."""
    pipe = nns.parse_launch(
        f"appsrc name=src dims=1 types=float32 ! "
        f"tensor_filter model={DLC_FLOAT} ! tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    pipe.get("src").push(TensorBuffer.of(np.asarray([10.0], np.float32)))
    pipe.get("src").end()
    runner.wait(120)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 1
    assert float(np.asarray(res[0].tensors[0])[0]) == 12.0


@needs_models
def test_dlc_unknown_layer_fails_loud():
    """Unsupported layer types surface at load (the output-shape probe
    traces the whole graph), not at first invoke."""
    g = parse_dlc(DLC_FLOAT)
    g.layers[2].type = "FancyNewLayer"
    with pytest.raises(BackendError, match="FancyNewLayer"):
        lower_dlc(g)


@needs_models
def test_dlc_input_without_dims_fails_loud():
    g = parse_dlc(DLC_FLOAT)
    g.buffer_dims.pop("X_input")
    g.layers[0].attrs.pop("OutputDims", None)
    with pytest.raises(BackendError, match="dims"):
        lower_dlc(g)


@needs_models
def test_dlc_layer_without_outputs_fails_loud():
    g = parse_dlc(DLC_FLOAT)
    g.layers[1].outputs = []
    with pytest.raises(BackendError, match="no.*outputs"):
        lower_dlc(g)


@needs_models
def test_dlc_batch_override_on_rank1_fails_loud():
    with pytest.raises(BackendError, match="rank"):
        lower_dlc(parse_dlc(DLC_FLOAT), batch=4)


def test_dlc_not_a_zip_fails_loud(tmp_path):
    p = tmp_path / "junk.dlc"
    p.write_bytes(b"\x00\x01nope")
    with pytest.raises(BackendError, match="zip"):
        parse_dlc(str(p))


def test_dlc_zip_without_model_fails_loud(tmp_path):
    import zipfile

    p = tmp_path / "empty.dlc"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("other", b"x")
    with pytest.raises(BackendError, match="model"):
        parse_dlc(str(p))


@needs_models
def test_dlc_rejects_compute_dtype():
    with pytest.raises(BackendError, match="dtype"):
        load_model_file(DLC_FLOAT, compute_dtype="bfloat16")
