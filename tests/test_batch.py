"""Dynamic micro-batching runtime: tensor_batch / tensor_unbatch /
batched filter invokes (CPU-only, deterministic where timing allows;
the timing tests use budgets generous enough for CI jitter)."""

import time

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.core.errors import NegotiationError
from nnstreamer_tpu.elements import (
    AppSrc, TensorBatch, TensorFilter, TensorSink, TensorUnbatch)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

SPEC = TensorsSpec.of(TensorInfo((1, 4), DType.FLOAT32))


def _affine(x):
    return x * 2.0 + 1.0


def _frame(v, pts):
    return TensorBuffer.of(np.full((1, 4), float(v), np.float32), pts=pts)


def _chain(pipe, stages):
    for e in stages:
        pipe.add(e)
    for a, b in zip(stages, stages[1:]):
        pipe.link(a, b)


class TestBatchUnbatch:
    def test_full_and_eos_flush_order_and_meta(self):
        """max-batch flushes plus a partial EOS flush; per-frame pts,
        meta and arrival order restored through a batched filter."""
        pipe = nns.Pipeline()
        src = AppSrc("src", spec=SPEC)
        sink = TensorSink("sink")
        _chain(pipe, [src,
                      TensorBatch("b", max_batch=4, max_latency_ms=1000),
                      TensorFilter("f", framework="xla", model=_affine),
                      TensorUnbatch("u"), sink])
        runner = nns.PipelineRunner(pipe).start()
        for i in range(10):
            buf = _frame(i, pts=i)
            buf.meta["tag"] = f"frame{i}"
            src.push(buf)
        src.end()
        runner.wait(60)
        runner.stop()
        assert [o.pts for o in sink.results] == list(range(10))
        for i, o in enumerate(sink.results):
            assert o.tensors[0].shape == (1, 4)
            np.testing.assert_allclose(
                np.asarray(o.tensors[0]), np.full((1, 4), i * 2.0 + 1.0))
            assert o.meta["tag"] == f"frame{i}"
        st = runner.stats()["b"]
        assert st["frames_in"] == 10
        assert st["flush_full"] == 2          # 4 + 4
        assert st["flush_eos"] == 1           # + 2 at EOS
        assert st["occupancy_hist"] == {2: 1, 4: 2}

    def test_partial_batch_flush_at_eos(self):
        """Frames fewer than max-batch must not be stranded: EOS drains
        the half-assembled batch through Element.flush()."""
        pipe = nns.Pipeline()
        src = AppSrc("src", spec=SPEC)
        sink = TensorSink("sink")
        _chain(pipe, [src,
                      TensorBatch("b", max_batch=64, max_latency_ms=60000),
                      TensorUnbatch("u"), sink])
        runner = nns.PipelineRunner(pipe).start()
        for i in range(3):
            src.push(_frame(i, pts=i))
        src.end()
        runner.wait(30)
        runner.stop()
        assert [o.pts for o in sink.results] == [0, 1, 2]
        st = runner.stats()["b"]
        assert st["flush_eos"] == 1 and st["flush_full"] == 0
        assert st["occupancy_hist"] == {3: 1}

    def test_deadline_flush_slow_source(self):
        """A source slower than max-latency-ms must get every frame
        flushed by the scheduler's timer wakeup, not by batch-full or
        EOS — and no frame may wait longer than the budget plus the
        scheduler tick (0.1s) plus CI slack."""
        budget_ms = 150.0
        pipe = nns.Pipeline()
        src = AppSrc("src", spec=SPEC)
        done = []
        sink = TensorSink("sink",
                          new_data=lambda b: done.append(
                              (b.pts, time.perf_counter())))
        _chain(pipe, [src,
                      TensorBatch("b", max_batch=64,
                                  max_latency_ms=budget_ms),
                      TensorUnbatch("u"), sink])
        runner = nns.PipelineRunner(pipe).start()
        pushed = {}
        for i in range(4):
            pushed[i] = time.perf_counter()
            src.push(_frame(i, pts=i))
            time.sleep(0.35)          # > budget: nothing to coalesce with
        src.end()
        runner.wait(30)
        runner.stop()
        st = runner.stats()["b"]
        assert st["flush_deadline"] == 4, st
        assert st["timer_fires"] >= 4
        assert runner.stats()["b"]["occupancy_hist"] == {1: 4}
        waits = {pts: t - pushed[pts] for pts, t in done}
        assert len(waits) == 4
        # budget + one 0.1s scheduler tick + generous CI slack — but far
        # below the 60s EOS horizon, so a flush that only happened at
        # EOS (timer broken) fails loudly
        for pts, w in waits.items():
            assert w < budget_ms / 1e3 + 0.1 + 0.35, (pts, w)

    def test_multi_stream_routes_back_in_order(self):
        """N muxed input streams through tensor_batch → tensor_filter →
        tensor_unbatch: each output pad gets exactly its own stream's
        frames, in arrival order, with per-frame meta restored."""
        pipe = nns.Pipeline()
        s0 = AppSrc("s0", spec=SPEC)
        s1 = AppSrc("s1", spec=SPEC)
        b = TensorBatch("b", max_batch=4, max_latency_ms=1000)
        f = TensorFilter("f", framework="xla", model=_affine)
        u = TensorUnbatch("u")
        k0, k1 = TensorSink("k0"), TensorSink("k1")
        for e in (s0, s1, b, f, u, k0, k1):
            pipe.add(e)
        pipe.link(s0, b, dst_pad=0)
        pipe.link(s1, b, dst_pad=1)
        pipe.link(b, f)
        pipe.link(f, u)
        pipe.link(u, k0, src_pad=0)
        pipe.link(u, k1, src_pad=1)
        runner = nns.PipelineRunner(pipe).start()
        for i in range(4):
            s0.push(_frame(10 + i, pts=100 + i))
            s1.push(_frame(20 + i, pts=200 + i))
        s0.end()
        s1.end()
        runner.wait(60)
        runner.stop()
        assert [o.pts for o in k0.results] == [100, 101, 102, 103]
        assert [o.pts for o in k1.results] == [200, 201, 202, 203]
        for i, o in enumerate(k0.results):
            np.testing.assert_allclose(
                np.asarray(o.tensors[0]), np.full((1, 4), (10 + i) * 2 + 1))
            assert o.meta["stream_id"] == 0
            assert o.meta["batch_seq"] == i
        for o in k1.results:
            assert o.meta["stream_id"] == 1

    def test_non_batch_aware_sink_refused_at_negotiation(self):
        pipe = nns.Pipeline()
        src = AppSrc("src", spec=SPEC)
        sink = TensorSink("sink")
        _chain(pipe, [src, TensorBatch("b", max_batch=4), sink])
        with pytest.raises(NegotiationError, match="tensor_unbatch"):
            pipe.negotiate()

    def test_unbatch_requires_batched_stream(self):
        pipe = nns.Pipeline()
        src = AppSrc("src", spec=SPEC)
        sink = TensorSink("sink")
        _chain(pipe, [src, TensorUnbatch("u"), sink])
        with pytest.raises(NegotiationError, match="not micro-batched"):
            pipe.negotiate()

    def test_per_frame_spec_preserved_downstream(self):
        """The whole point of dyn_batch-as-spec-field: elements after
        tensor_unbatch negotiate the same per-frame spec they would see
        without the batch/unbatch pair."""
        pipe = nns.Pipeline()
        src = AppSrc("src", spec=SPEC)
        b = TensorBatch("b", max_batch=8)
        u = TensorUnbatch("u")
        sink = TensorSink("sink")
        _chain(pipe, [src, b, u, sink])
        pipe.negotiate()
        assert b.out_specs[0].dyn_batch == 8
        assert b.out_specs[0].tensors == SPEC.tensors     # per-frame shapes
        assert u.out_specs[0].dyn_batch == 0
        assert u.out_specs[0].tensors == SPEC.tensors
        assert sink.in_specs[0].is_compatible(SPEC)


class TestBatchedInvokes:
    def _open_backend(self, model, in_spec):
        from nnstreamer_tpu.backends.xla import XLABackend

        be = XLABackend()
        be.open({"model": model})
        be.set_input_info(in_spec)
        return be

    def test_bucketed_compile_count_under_ragged_batches(self):
        """Ragged occupancies (deadline flushes under varying load) must
        reuse power-of-two buckets: occupancies 1..8 may cost at most
        the 4 bucket compilations {1,2,4,8}, not 8."""
        be = self._open_backend(_affine, SPEC)
        for n in (3, 5, 2, 7, 1, 6, 4, 8, 3, 5):
            x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
            out = be.invoke_batched((x,), n, [True])
            assert np.asarray(out[0]).shape == (n, 4)
            np.testing.assert_allclose(np.asarray(out[0]), x * 2.0 + 1.0)
        assert be.compile_count <= 4, be.compile_count
        be.close()

    def test_stack_mode_for_rank_without_leading_one(self):
        """Per-frame tensors whose leading dim isn't 1 batch by stacking
        (rank + 1); outputs come back stacked and slice clean."""
        spec = TensorsSpec.of(TensorInfo((4,), DType.FLOAT32))
        be = self._open_backend(_affine, spec)
        frames = np.stack([np.full(4, i, np.float32) for i in range(3)])
        out = be.invoke_batched((frames,), 3, [False])
        assert np.asarray(out[0]).shape == (3, 4)
        np.testing.assert_allclose(np.asarray(out[0]), frames * 2.0 + 1.0)
        be.close()

    def test_batch_rejecting_model_falls_back_per_frame(self):
        """A model with a baked-in per-frame shape (rejects any batched
        input) must still produce correct batched output via the base
        per-frame fallback — correctness never depends on batchability."""
        def rigid(x):
            import jax.numpy as jnp

            return jnp.reshape(x, (4,)) * 3.0     # only (1, 4) reshapes

        be = self._open_backend(rigid, SPEC)
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        out = be.invoke_batched((x,), 3, [True])
        # per-frame outputs have shape (4,): fallback stacks → (3, 4)
        assert np.asarray(out[0]).shape == (3, 4)
        np.testing.assert_allclose(np.asarray(out[0]), x * 3.0)
        be.close()

    def test_pipeline_batched_filter_compiles_bounded(self):
        """End to end: ragged flush sizes through the pipeline stay
        within the power-of-two compile budget, observable on the
        element's backend."""
        pipe = nns.Pipeline()
        src = AppSrc("src", spec=SPEC)
        f = TensorFilter("f", framework="xla", model=_affine)
        sink = TensorSink("sink")
        _chain(pipe, [src, TensorBatch("b", max_batch=4,
                                       max_latency_ms=1000),
                      f, TensorUnbatch("u"), sink])
        runner = nns.PipelineRunner(pipe).start()
        for i in range(7):                 # 4-full + 3-at-EOS (→ pad 4)
            src.push(_frame(i, pts=i))
        src.end()
        runner.wait(60)
        runner.stop()
        assert len(sink.results) == 7
        assert f.backend.compile_count <= 2   # buckets {4} (3 pads to 4)
