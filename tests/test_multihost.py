"""Two-process multi-host runtime test (VERDICT r2 next #7).

The reference scales across hosts via NCCL/MPI inside the NN frameworks
plus its own TCP/MQTT transports; our DCN story is
`jax.distributed.initialize` + one global mesh (`parallel/multihost.py`,
SURVEY §5.8). Round 2 only ever exercised the single-process fallback —
this test runs the REAL multi-process path: two OS processes, a
localhost coordinator, 4 virtual CPU devices each → an 8-device global
mesh, a cross-process psum, and one `make_train_step` over dp=8 whose
gradient all-reduce spans both processes. Driver-style subprocess
harness (same pattern as `__graft_entry__._dryrun_in_subprocess`).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import json, os, sys
import numpy as np

import jax
import jax.numpy as jnp

pid = int(sys.argv[1])
coord = sys.argv[2]

from nnstreamer_tpu.parallel import multihost
joined = multihost.initialize(coordinator_address=coord,
                              num_processes=2, process_id=pid)
assert joined, "multi-process runtime did not start"
assert jax.process_count() == 2
assert len(jax.devices()) == 8, f"global devices {len(jax.devices())}"

from nnstreamer_tpu.parallel.mesh import MeshSpec
from nnstreamer_tpu.parallel.multihost import global_mesh
mesh = global_mesh(MeshSpec(dp=8))
assert mesh.devices.size == 8

# 1. cross-process collective: psum over dp of a per-device value.
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental import multihost_utils

x_local = np.full((4, 1), float(pid + 1), np.float32)   # 4 local devices
x = multihost.host_local_batch(mesh, x_local)

@jax.jit
def total(v):
    return jnp.sum(v)

# sum over all 8 shards: 4*(1.0) + 4*(2.0) = 12
s = float(total(x))
assert abs(s - 12.0) < 1e-6, f"global sum {s}"

# 2. one train step across processes: dp=8 data-parallel gradient
# all-reduce spans the DCN boundary.
import optax
from nnstreamer_tpu.parallel.train import init_state, make_train_step

w0 = np.arange(4, dtype=np.float32).reshape(4, 1) / 10.0

def loss_fn(params, xb, yb):
    pred = xb @ params["w"]
    return jnp.mean((pred - yb) ** 2)

opt = optax.sgd(0.1)
params = {"w": jnp.asarray(w0)}
state = init_state(params, opt)
step = make_train_step(loss_fn, opt, mesh=mesh,
                       batch_spec=[P("dp"), P("dp")])

rng = np.random.RandomState(0)               # same data on both hosts
xb_all = rng.randn(16, 4).astype(np.float32)
yb_all = rng.randn(16, 1).astype(np.float32)
# each process owns its half of the global batch
xb, yb = multihost.host_local_batch(
    mesh, xb_all[pid * 8:(pid + 1) * 8], yb_all[pid * 8:(pid + 1) * 8])
state2, loss = step(state, xb, yb)
# params are replicated: every process holds the full array
w1 = np.asarray(state2.params["w"].addressable_shards[0].data)

# serial reference on the FULL batch must match the dp-sharded step
def ref_step(w):
    import numpy as _np
    pred = xb_all @ w
    grad = 2.0 * xb_all.T @ (pred - yb_all) / len(xb_all)
    return w - 0.1 * grad

w_ref = ref_step(w0)
err = float(np.abs(w1.reshape(4, 1) - w_ref).max())
assert err < 1e-5, f"train step mismatch {err}"

print(json.dumps({"pid": pid, "sum": s, "loss": float(loss),
                  "err": err}))
"""


def test_two_process_global_mesh_and_train_step(tmp_path):
    from conftest import free_port

    coord = f"127.0.0.1:{free_port()}"
    env = {k: v for k, v in os.environ.items()
           # a tunneled-TPU plugin in the parent env (axon) must not
           # leak into the pure-CPU worker processes
           if not k.startswith(("PALLAS_AXON", "AXON", "TPU_"))}
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        PYTHONPATH=REPO,
    )
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    procs = [
        subprocess.Popen([sys.executable, str(script), str(pid), coord],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True)
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out")
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))
    assert {o["pid"] for o in outs} == {0, 1}
    for o in outs:
        assert abs(o["sum"] - 12.0) < 1e-6
        assert o["err"] < 1e-5
    # both processes computed the identical global loss
    assert abs(outs[0]["loss"] - outs[1]["loss"]) < 1e-6
