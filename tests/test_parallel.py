"""parallel/ tests on the 8-device virtual CPU mesh (conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from nnstreamer_tpu.parallel import (
    MeshSpec,
    make_mesh,
    make_train_step,
    shard_params)
from nnstreamer_tpu.parallel.train import init_state, shard_state


def test_mesh_spec_resolution(eight_cpu_devices):
    assert MeshSpec(dp=-1, tp=2, sp=1).resolve(8) == (4, 2, 1)
    assert MeshSpec(dp=2, tp=2, sp=2).resolve(8) == (2, 2, 2)
    mesh = make_mesh(MeshSpec(dp=2, tp=2, sp=2))
    assert mesh.shape == {"dp": 2, "tp": 2, "sp": 2}
    with pytest.raises(Exception):
        MeshSpec(dp=3, tp=2, sp=1).resolve(8)


def test_shard_params_mobilenet(eight_cpu_devices):
    from nnstreamer_tpu.models import mobilenet_v2 as m

    mesh = make_mesh(MeshSpec(dp=4, tp=2, sp=1))
    params = m.init_params(width=0.35)
    sharded = shard_params(params, mesh)
    # conv kernels with tp-divisible out channels actually shard over tp
    w = sharded["stem"]["conv"]["w"]
    assert w.sharding.spec == P(None, None, None, "tp")
    # numerics unchanged after sharding
    x = jnp.ones((1, 64, 64, 3))
    a = m.apply(params, x, width=0.35, dtype=jnp.float32)
    b = m.apply(sharded, x, width=0.35, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5)


def test_sharded_train_step_runs_and_matches_single(eight_cpu_devices):
    """dp+tp train step: loss must equal the unsharded step's loss."""
    from nnstreamer_tpu.models import mobilenet_v2 as m

    params = m.init_params(width=0.35, num_classes=16)
    opt = optax.sgd(0.1)
    loss_fn = lambda p, x, y: m.loss_fn(p, x, y, width=0.35, dtype=jnp.float32)
    x = jnp.ones((8, 32, 32, 3))
    y = jnp.arange(8) % 16

    # single-device reference
    step0 = make_train_step(loss_fn, opt, donate=False)
    _, loss_ref = step0(init_state(params, opt), x, y)

    mesh = make_mesh(MeshSpec(dp=4, tp=2, sp=1))
    state = shard_state(init_state(params, opt), mesh)
    step = make_train_step(loss_fn, opt, mesh=mesh,
                           batch_spec=(P("dp"), P("dp")), donate=False)
    state2, loss = step(state, x, y)
    assert int(state2.step) == 1
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-4)


def test_ring_attention_matches_reference(eight_cpu_devices):
    from nnstreamer_tpu.parallel.ring_attention import (
        reference_attention, ring_attention)

    mesh = make_mesh(MeshSpec(dp=1, tp=1, sp=8))
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    B, S, H, D = 2, 64, 4, 16
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, H, D), jnp.float32)

    ref = reference_attention(q, k, v)
    out = ring_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_causal(eight_cpu_devices):
    from nnstreamer_tpu.parallel.ring_attention import (
        reference_attention, ring_attention)

    mesh = make_mesh(MeshSpec(dp=1, tp=1, sp=4))
    key = jax.random.PRNGKey(1)
    B, S, H, D = 1, 32, 2, 8
    q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in jax.random.split(key, 3))
    ref = reference_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_mesh_dispatcher_batches(eight_cpu_devices):
    from nnstreamer_tpu.parallel.dispatch import MeshDispatcher

    mesh = make_mesh(MeshSpec(dp=8, tp=1, sp=1))

    def fn(params, x):  # toy model: mean over features + bias
        return x @ params["w"]

    params = {"w": jnp.eye(4)}
    d = MeshDispatcher(fn, params, mesh, bucket=8, max_delay_ms=1.0)
    try:
        futs = [d.submit(np.full((4,), i, np.float32)) for i in range(11)]
        outs = [f.result(30) for f in futs]
        for i, o in enumerate(outs):
            np.testing.assert_allclose(o[0], np.full((4,), i, np.float32))
        assert d.frames == 11
        assert d.batches >= 2
    finally:
        d.shutdown()
