"""parallel/ tests on the 8-device virtual CPU mesh (conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from nnstreamer_tpu.parallel import (
    MeshSpec,
    make_mesh,
    make_train_step,
    shard_params)
from nnstreamer_tpu.parallel.train import init_state, shard_state


def test_mesh_spec_resolution(eight_cpu_devices):
    # resolve order follows AXES = (dp, pp, tp, ep, sp)
    assert MeshSpec(dp=-1, tp=2, sp=1).resolve(8) == (4, 1, 2, 1, 1)
    assert MeshSpec(dp=2, tp=2, sp=2).resolve(8) == (2, 1, 2, 1, 2)
    assert MeshSpec(dp=1, pp=4, ep=2).resolve(8) == (1, 4, 1, 2, 1)
    mesh = make_mesh(MeshSpec(dp=2, tp=2, sp=2))
    assert mesh.shape == {"dp": 2, "pp": 1, "tp": 2, "ep": 1, "sp": 2}
    with pytest.raises(Exception):
        MeshSpec(dp=3, tp=2, sp=1).resolve(8)


def test_shard_params_mobilenet(eight_cpu_devices):
    from nnstreamer_tpu.models import mobilenet_v2 as m

    mesh = make_mesh(MeshSpec(dp=4, tp=2, sp=1))
    params = m.init_params(width=0.35)
    sharded = shard_params(params, mesh)
    # conv kernels with tp-divisible out channels actually shard over tp
    w = sharded["stem"]["conv"]["w"]
    assert w.sharding.spec == P(None, None, None, "tp")
    # numerics unchanged after sharding
    x = jnp.ones((1, 64, 64, 3))
    a = m.apply(params, x, width=0.35, dtype=jnp.float32)
    b = m.apply(sharded, x, width=0.35, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5)


def test_sharded_train_step_runs_and_matches_single(eight_cpu_devices):
    """dp+tp train step: loss must equal the unsharded step's loss."""
    from nnstreamer_tpu.models import mobilenet_v2 as m

    params = m.init_params(width=0.35, num_classes=16)
    opt = optax.sgd(0.1)
    loss_fn = lambda p, x, y: m.loss_fn(p, x, y, width=0.35, dtype=jnp.float32)
    x = jnp.ones((8, 32, 32, 3))
    y = jnp.arange(8) % 16

    # single-device reference
    step0 = make_train_step(loss_fn, opt, donate=False)
    _, loss_ref = step0(init_state(params, opt), x, y)

    mesh = make_mesh(MeshSpec(dp=4, tp=2, sp=1))
    state = shard_state(init_state(params, opt), mesh)
    step = make_train_step(loss_fn, opt, mesh=mesh,
                           batch_spec=(P("dp"), P("dp")), donate=False)
    state2, loss = step(state, x, y)
    assert int(state2.step) == 1
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-4)


def test_ring_attention_matches_reference(eight_cpu_devices):
    from nnstreamer_tpu.parallel.ring_attention import (
        reference_attention, ring_attention)

    mesh = make_mesh(MeshSpec(dp=1, tp=1, sp=8))
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    B, S, H, D = 2, 64, 4, 16
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, H, D), jnp.float32)

    ref = reference_attention(q, k, v)
    out = ring_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_causal(eight_cpu_devices):
    from nnstreamer_tpu.parallel.ring_attention import (
        reference_attention, ring_attention)

    mesh = make_mesh(MeshSpec(dp=1, tp=1, sp=4))
    key = jax.random.PRNGKey(1)
    B, S, H, D = 1, 32, 2, 8
    q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in jax.random.split(key, 3))
    ref = reference_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_mesh_dispatcher_batches(eight_cpu_devices):
    from nnstreamer_tpu.parallel.dispatch import MeshDispatcher

    mesh = make_mesh(MeshSpec(dp=8, tp=1, sp=1))

    def fn(params, x):  # toy model: mean over features + bias
        return x @ params["w"]

    params = {"w": jnp.eye(4)}
    d = MeshDispatcher(fn, params, mesh, bucket=8, max_delay_ms=1.0)
    try:
        futs = [d.submit(np.full((4,), i, np.float32)) for i in range(11)]
        outs = [f.result(30) for f in futs]
        for i, o in enumerate(outs):
            np.testing.assert_allclose(o[0], np.full((4,), i, np.float32))
        assert d.frames == 11
        assert d.batches >= 2
    finally:
        d.shutdown()


def test_mesh_dispatcher_shutdown_idempotent(eight_cpu_devices):
    """Regression: shutdown() must be callable repeatedly (finally
    blocks + supervised teardown paths both call it) without error and
    without re-running the teardown."""
    from nnstreamer_tpu.parallel.dispatch import MeshDispatcher

    mesh = make_mesh(MeshSpec(dp=8, tp=1, sp=1))
    d = MeshDispatcher(lambda p, x: x @ p["w"], {"w": jnp.eye(4)},
                       mesh, bucket=8, max_delay_ms=1.0)
    fut = d.submit(np.ones((4,), np.float32))
    np.testing.assert_allclose(fut.result(30)[0], np.ones(4, np.float32))
    d.shutdown()
    d.shutdown()                             # second call: strict no-op
    d.shutdown()


# -- pipeline parallelism (pp) ------------------------------------------------

def test_pipeline_matches_serial(eight_cpu_devices):
    from nnstreamer_tpu.parallel.pipeline import (
        pipeline_apply, reference_pipeline, stack_stage_params)

    mesh = make_mesh(MeshSpec(dp=1, pp=4))
    key = jax.random.PRNGKey(0)
    d = 16
    per_stage = []
    for i in range(4):
        k1, k2, key = jax.random.split(key, 3)
        per_stage.append({
            "w": jax.random.normal(k1, (d, d)) * d ** -0.5,
            "b": jax.random.normal(k2, (d,)) * 0.1,
        })

    def stage(p, a):
        return jnp.tanh(a @ p["w"] + p["b"])

    x = jax.random.normal(key, (6, 3, d))     # 6 microbatches of 3 tokens
    stacked = stack_stage_params(per_stage)
    got = jax.jit(
        lambda s, x: pipeline_apply(stage, s, x, mesh=mesh))(stacked, x)
    want = reference_pipeline(stage, per_stage, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_single_microbatch(eight_cpu_devices):
    from nnstreamer_tpu.parallel.pipeline import (
        pipeline_apply, reference_pipeline, stack_stage_params)

    mesh = make_mesh(MeshSpec(dp=1, pp=8))
    per_stage = [{"w": jnp.eye(4) * (i + 1)} for i in range(8)]
    stage = lambda p, a: a @ p["w"]
    x = jnp.ones((1, 2, 4))
    got = pipeline_apply(stage, stack_stage_params(per_stage), x, mesh=mesh)
    want = reference_pipeline(stage, per_stage, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))


# -- expert parallelism (ep) --------------------------------------------------

def test_moe_matches_serial_when_capacity_ample(eight_cpu_devices):
    from nnstreamer_tpu.parallel.moe import (
        init_moe_params, moe_apply, moe_param_specs, reference_moe)
    from jax.sharding import NamedSharding

    mesh = make_mesh(MeshSpec(dp=1, ep=8))
    key = jax.random.PRNGKey(1)
    d, h, E, T = 8, 16, 8, 64
    params = init_moe_params(key, d, h, E)
    x = jax.random.normal(jax.random.PRNGKey(2), (T, d))
    specs = moe_param_specs()
    placed = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
              for k, v in params.items()}
    xs = jax.device_put(x, NamedSharding(mesh, P("ep")))
    # capacity ≥ local tokens → zero drops → serial equivalence
    got = jax.jit(lambda p, x: moe_apply(p, x, mesh=mesh,
                                         capacity_factor=float(E)))(placed, xs)
    want = reference_moe(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_moe_capacity_drops_are_bounded_not_wrong(eight_cpu_devices):
    """With a tight capacity, dropped tokens produce zero output (the
    residual path carries them); surviving tokens still match serial."""
    from nnstreamer_tpu.parallel.moe import (
        init_moe_params, moe_apply, moe_param_specs, reference_moe)
    from jax.sharding import NamedSharding

    mesh = make_mesh(MeshSpec(dp=1, ep=8))
    d, h, E, T = 8, 16, 8, 64
    params = init_moe_params(jax.random.PRNGKey(1), d, h, E)
    x = jax.random.normal(jax.random.PRNGKey(2), (T, d))
    specs = moe_param_specs()
    placed = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
              for k, v in params.items()}
    xs = jax.device_put(x, NamedSharding(mesh, P("ep")))
    got = np.asarray(moe_apply(placed, xs, mesh=mesh, capacity_factor=1.0))
    want = np.asarray(reference_moe(params, x))
    for t in range(T):
        if np.allclose(got[t], 0.0):
            continue                     # dropped: zero contribution
        np.testing.assert_allclose(got[t], want[t], rtol=1e-4, atol=1e-4)


def test_moe_rejects_undivisible_experts(eight_cpu_devices):
    from nnstreamer_tpu.parallel.moe import init_moe_params, moe_apply

    mesh = make_mesh(MeshSpec(dp=1, ep=8))
    params = init_moe_params(jax.random.PRNGKey(0), 4, 8, 6)  # 6 % 8 != 0
    with pytest.raises(ValueError, match="experts"):
        moe_apply(params, jnp.ones((16, 4)), mesh=mesh)


# -- multi-host entry points (single-process degenerate case) -----------------

def test_multihost_single_process_fallback(eight_cpu_devices):
    from nnstreamer_tpu.parallel import multihost

    # no coordinator configured → clean single-process fallback
    assert multihost.initialize() is False
    mesh = multihost.global_mesh(MeshSpec(dp=4, tp=2))
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2


def test_multihost_batch_and_fetch(eight_cpu_devices):
    from nnstreamer_tpu.parallel import multihost

    mesh = multihost.global_mesh(MeshSpec(dp=8))
    x = np.arange(16, dtype=np.float32).reshape(8, 2)
    gx = multihost.host_local_batch(mesh, x)
    assert gx.shape == (8, 2)
    y = jax.jit(lambda a: a * 2)(gx)
    out = multihost.fetch_replicated(y)
    np.testing.assert_array_equal(np.asarray(out), x * 2)


def test_transformer_seq_ring_attention_matches_serial(eight_cpu_devices):
    """Full-sequence transformer forward with sp-sharded ring attention
    equals the single-device forward (long-context path end-to-end)."""
    import jax.numpy as jnp

    from nnstreamer_tpu.models import transformer as T

    mesh = make_mesh(MeshSpec(dp=1, sp=8))
    d, H, L, V, S = 32, 4, 2, 64, 32    # S divides sp=8
    params = T.init_params(d_model=d, n_heads=H, n_layers=L, vocab=V)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, V, (1, S)), jnp.int32)
    want = np.asarray(T.apply_seq(params, ids, n_heads=H))
    got = np.asarray(T.apply_seq(params, ids, n_heads=H, mesh=mesh))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_ring_attention_pallas_block_matches_xla(eight_cpu_devices):
    """The Pallas flash block kernel inside the ring (interpret mode on
    the CPU mesh) equals the jnp block path."""
    from nnstreamer_tpu.parallel.ring_attention import (
        reference_attention, ring_attention)

    mesh = make_mesh(MeshSpec(dp=1, tp=1, sp=4))
    key = jax.random.PRNGKey(3)
    B, S, H, D = 1, 64, 2, 16    # s_local=16: kernel blocks of 16
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    for causal in (False, True):
        ref = reference_attention(q, k, v, causal=causal)
        out = ring_attention(q, k, v, mesh=mesh, causal=causal,
                             block_impl="pallas")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


def test_ring_attention_with_batch_axis_dp(eight_cpu_devices):
    """dp×sp composition: batch sharded over dp AND sequence ring-
    attended over sp in one mesh matches the reference."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nnstreamer_tpu.parallel import MeshSpec, make_mesh
    from nnstreamer_tpu.parallel.ring_attention import (
        reference_attention, ring_attention)

    mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
    B, S, H, D = 4, 16, 2, 8
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    qs, ks, vs = (jax.device_put(
        t, NamedSharding(mesh, P("dp", "sp", None, None)))
        for t in (q, k, v))
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh=mesh, axis="sp", batch_axis="dp",
        causal=True))(qs, ks, vs)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)


def test_ring_attention_batch_axis_pallas_block(eight_cpu_devices):
    """Same dp×sp composition through the Pallas block path (interpret
    mode on CPU) — guards the pallas shard_map's batch_axis spec."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nnstreamer_tpu.parallel import MeshSpec, make_mesh
    from nnstreamer_tpu.parallel.ring_attention import (
        reference_attention, ring_attention)

    mesh = make_mesh(MeshSpec(dp=2, tp=1, sp=4))
    B, S, H, D = 2, 32, 1, 8
    key = jax.random.PRNGKey(1)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    qs, ks, vs = (jax.device_put(
        t, NamedSharding(mesh, P("dp", "sp", None, None)))
        for t in (q, k, v))
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh=mesh, axis="sp", batch_axis="dp",
        causal=True, block_impl="pallas"))(qs, ks, vs)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4)


# -- dormant-module smoke (sharded-serving PR satellites) ---------------------

def test_mesh_spec_resolve_edge_cases(eight_cpu_devices):
    from nnstreamer_tpu.core.errors import PipelineError

    # a single wildcard soaks up every remaining device
    assert MeshSpec(dp=-1).resolve(8) == (8, 1, 1, 1, 1)
    assert MeshSpec(dp=1, tp=-1, sp=2).resolve(8) == (1, 1, 4, 1, 2)
    # exact fit with no wildcard
    assert MeshSpec(dp=2, tp=2, sp=2).resolve(8) == (2, 1, 2, 1, 2)
    # two wildcards are ambiguous — refused, never guessed
    with pytest.raises(PipelineError, match="at most one"):
        MeshSpec(dp=-1, tp=-1).resolve(8)
    # fixed axes that do not divide the device count
    with pytest.raises(PipelineError, match="divide"):
        MeshSpec(dp=3, tp=2).resolve(8)
    # oversubscription: more chips demanded than visible
    with pytest.raises(PipelineError):
        MeshSpec(dp=16).resolve(8)
    with pytest.raises(PipelineError):
        MeshSpec(dp=4, tp=4).resolve(8)


def test_compat_shard_map_is_the_single_source():
    """Satellite guard: every shard_map consumer goes through the
    `parallel/_compat` shim (one copy of the jax-version import dance),
    and the shim accepts the modern `check_vma` keyword."""
    from nnstreamer_tpu.parallel import _compat, moe, pipeline, ring_attention

    assert moe.shard_map is _compat.shard_map
    assert pipeline.shard_map is _compat.shard_map
    assert ring_attention.shard_map is _compat.shard_map
    assert callable(_compat.shard_map)


def test_block_attn_streaming_accumulator_matches_reference(
        eight_cpu_devices):
    """`_block_attn` is the online-softmax accumulator both the ring and
    the sharded-serving prefill lean on: feeding the K/V blocks through
    it sequentially (no mesh at all) must reproduce dense attention."""
    from nnstreamer_tpu.parallel.ring_attention import (
        NEG_INF, _block_attn, reference_attention)

    key = jax.random.PRNGKey(5)
    B, S, H, D, nblk = 2, 32, 2, 8, 4
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    m = jnp.full((B, H, S), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, S), jnp.float32)
    o = jnp.zeros((B, S, H, D), jnp.float32)
    step = S // nblk
    for i in range(nblk):
        kb = k[:, i * step:(i + 1) * step]
        vb = v[:, i * step:(i + 1) * step]
        m, l, o = _block_attn(q, kb, vb, m, l, o)
    got = o / l.transpose(0, 2, 1)[..., None]
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_matches_block_accumulator_exactly(
        eight_cpu_devices):
    """Ring attention on the sp mesh vs the same `_block_attn` chain run
    serially in ring-visit order: identical block count and order means
    the mesh only changes *where* blocks live, not the numerics."""
    from nnstreamer_tpu.parallel.ring_attention import (
        NEG_INF, _block_attn, ring_attention)

    n = 4
    mesh = make_mesh(MeshSpec(dp=1, tp=1, sp=n))
    key = jax.random.PRNGKey(6)
    B, S, H, D = 1, 32, 2, 8
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    out = ring_attention(q, k, v, mesh=mesh)
    step = S // n
    rows = []
    for d in range(n):           # device d's query block
        qd = q[:, d * step:(d + 1) * step]
        m = jnp.full((B, H, step), NEG_INF, jnp.float32)
        l = jnp.zeros((B, H, step), jnp.float32)
        o = jnp.zeros((B, step, H, D), jnp.float32)
        for hop in range(n):     # ppermute ring visit order
            src = (d - hop) % n
            kb = k[:, src * step:(src + 1) * step]
            vb = v[:, src * step:(src + 1) * step]
            m, l, o = _block_attn(qd, kb, vb, m, l, o)
        rows.append(o / l.transpose(0, 2, 1)[..., None])
    want = jnp.concatenate(rows, axis=1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_dryrun_composed_dp_tp_sp_numeric(eight_cpu_devices):
    """The driver gate's composed-mesh section (dp×tp×sp in one program
    + in-gate numeric check) on the virtual 8-device mesh."""
    import os
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import __graft_entry__ as g
    import jax

    err, shape = g._composed_dp_tp_sp(jax.devices(), 8)
    assert err < 5e-4
    assert shape == dict(dp=2, tp=2, sp=2)
