"""Graph, DSL parsing, and negotiation tests
(reference: tests/nnstreamer_plugins/unittest_plugins.cc pipeline-parse
and caps-negotiation suites)."""

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.core.errors import NegotiationError, PipelineError
from nnstreamer_tpu.elements.sources import AppSrc, VideoTestSrc
from nnstreamer_tpu.elements.sinks import TensorSink
from nnstreamer_tpu.elements.transform import TensorTransform
from nnstreamer_tpu.graph.pipeline import Pipeline
from nnstreamer_tpu.tensor.dtypes import DType


class TestDSL:
    def test_linear_parse(self):
        p = parse_launch(
            "videotestsrc width=8 height=8 num-buffers=2 ! tensor_converter "
            "! tensor_sink name=out"
        )
        assert len(p.elements) == 3
        assert len(p.links) == 2
        assert "out" in p.elements

    def test_props_with_quotes(self):
        p = parse_launch(
            'appsrc dims=3:4 types=float32 name=a ! tensor_sink name=s'
        )
        assert p.get("a").props["dims"] == "3:4"

    def test_named_ref_forward(self):
        # refs may point at elements defined later (gst-launch parity)
        p = parse_launch(
            "appsrc dims=2:2 name=a ! m.  appsrc dims=2:2 name=b ! m.  "
            "tensor_mux name=m ! tensor_sink name=s",
        ) if _has_mux() else None
        if p is None:
            pytest.skip("tensor_mux not yet implemented")

    def test_unknown_element(self):
        with pytest.raises(PipelineError, match="no element plugin"):
            parse_launch("videotestsrc ! not_an_element ! tensor_sink")

    def test_unknown_property(self):
        with pytest.raises(PipelineError, match="no\\s+property"):
            parse_launch("videotestsrc bogus_prop=1 ! tensor_sink")

    def test_empty(self):
        with pytest.raises(PipelineError):
            parse_launch("   ")

    def test_starts_with_bang(self):
        with pytest.raises(PipelineError):
            parse_launch("! tensor_sink")


def _has_mux():
    from nnstreamer_tpu.core.registry import PluginKind, registry

    return registry.find(PluginKind.ELEMENT, "tensor_mux") is not None


class TestNegotiation:
    def test_video_chain(self):
        p = parse_launch(
            "videotestsrc width=16 height=8 format=RGB ! tensor_converter "
            "! tensor_sink name=s"
        )
        p.negotiate()
        conv = next(e for e in p.elements.values()
                    if e.ELEMENT_NAME == "tensor_converter")
        out = conv.out_specs[0]
        assert out.tensors[0].shape == (1, 8, 16, 3)
        assert out.tensors[0].dtype == DType.UINT8

    def test_transform_typecast_spec(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 ! tensor_converter ! "
            "tensor_transform mode=typecast option=float32 ! tensor_sink name=s"
        )
        p.negotiate()
        t = next(e for e in p.elements.values()
                 if e.ELEMENT_NAME == "tensor_transform")
        assert t.out_specs[0].tensors[0].dtype == DType.FLOAT32

    def test_media_into_transform_fails_actionably(self):
        p = parse_launch(
            "videotestsrc ! tensor_transform mode=typecast option=float32 "
            "! tensor_sink name=s"
        )
        with pytest.raises(NegotiationError, match="tensor_converter"):
            p.negotiate()

    def test_unlinked_src_pad(self):
        p = Pipeline()
        p.add(VideoTestSrc(name="src"))
        with pytest.raises(PipelineError, match="must be linked"):
            p.negotiate()

    def test_cycle_detection(self):
        p = Pipeline()
        a = p.add(TensorTransform(name="a", mode="typecast", option="float32"))
        b = p.add(TensorTransform(name="b", mode="typecast", option="float32"))
        p.add(AppSrc(name="src", dims="2:2"))
        p.link(p.get("src"), a)
        # craft a cycle a->b->a via manual link list surgery
        p.link(a, b)
        from nnstreamer_tpu.graph.pipeline import Link

        p.links.append(Link(b, 0, a, 1))
        with pytest.raises(PipelineError):
            p.negotiate()

    def test_double_link_rejected(self):
        p = Pipeline()
        src = p.add(AppSrc(name="src", dims="2:2"))
        sink = p.add(TensorSink(name="s"))
        p.link(src, sink)
        with pytest.raises(PipelineError, match="already linked"):
            p.link(src, sink, src_pad=0, dst_pad=0)


class TestTransformPrograms:
    def test_arith_chain(self):
        from nnstreamer_tpu.elements.transform import TransformProgram

        prog = TransformProgram("arithmetic", "typecast:float32,add:-127.5,div:127.5")
        x = np.array([0, 127.5, 255], np.uint8)
        out = prog.apply(np, np.array([0, 128, 255], np.uint8))
        np.testing.assert_allclose(out, (np.array([0, 128, 255]) - 127.5) / 127.5)

    def test_transpose_reference_order(self):
        from nnstreamer_tpu.elements.transform import TransformProgram

        # reference option 1:0:2:3 swaps the two innermost dims (ch<->w)
        prog = TransformProgram("transpose", "1:0:2:3")
        x = np.zeros((1, 4, 6, 3))
        y = prog.apply(np, x)
        assert y.shape == (1, 4, 3, 6)
        info = prog.out_info(
            __import__("nnstreamer_tpu").TensorInfo((1, 4, 6, 3))
        )
        assert info.shape == (1, 4, 3, 6)

    def test_clamp(self):
        from nnstreamer_tpu.elements.transform import TransformProgram

        prog = TransformProgram("clamp", "0:1")
        out = prog.apply(np, np.array([-5.0, 0.5, 9.0]))
        np.testing.assert_array_equal(out, [0, 0.5, 1])

    def test_stand_default(self):
        from nnstreamer_tpu.elements.transform import TransformProgram

        prog = TransformProgram("stand", "default")
        out = prog.apply(np, np.arange(10, dtype=np.float32))
        assert abs(out.mean()) < 1e-6 and abs(out.std() - 1) < 1e-3

    def test_bad_mode(self):
        with pytest.raises(PipelineError, match="unknown tensor_transform mode"):
            TensorTransform(mode="wavelet")

    def test_bad_arith_op(self):
        from nnstreamer_tpu.elements.transform import TransformProgram

        with pytest.raises(PipelineError, match="unknown arithmetic op"):
            TransformProgram("arithmetic", "pow:2")

    def test_dimchg(self):
        from nnstreamer_tpu.elements.transform import TransformProgram

        # reference dimchg 0:2: move innermost (channel) to position 2
        prog = TransformProgram("dimchg", "0:2")
        x = np.zeros((1, 4, 6, 3))
        assert prog.apply(np, x).shape == (1, 3, 4, 6)
