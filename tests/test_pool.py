"""Supervised worker-pool tests: crash isolation, restart + circuit,
drain, hot-swap broadcast, and conservation across kills (ISSUE 9).

The chaos tests (marker `chaos`) SIGKILL/hang real child processes and
assert the supervision contract: every offered frame still ends as
exactly one of {replied, rejected, shed}, the pool returns to capacity
within the restart budget, and close() leaves zero orphans (psutil-free
/proc audit). They are tier-1 — fast, deterministic via injected chaos
hooks (WorkerSpec.crash_pts / hang_pts / crash_after_s) — but carry the
marker so a constrained CI lane can deselect them (`-m 'not chaos'`).
"""

import itertools
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.edge.query import QueryServer, TensorQueryServerSrc
from nnstreamer_tpu.serving.pool import (
    DISABLED, PooledQueryServer, WorkerPool, proc_alive)
from nnstreamer_tpu.serving.worker import WorkerSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.traffic.loadgen import (
    poisson_arrivals, run_against_pool, run_open_loop)

_sid = itertools.count(7000)


@pytest.fixture(autouse=True)
def _clean_servers():
    yield
    QueryServer.reset_all()


def _conserved(c: dict) -> bool:
    return (c["offered"] == c["admitted"] + sum(c["rejected"].values())
            and c["admitted"] == c["replied"] + sum(c["shed"].values())
            + c["depth"] + c["inflight"])


def _echo_pool(**kw):
    kw.setdefault("workers", 2)
    kw.setdefault("service_ms", 2.0)
    return PooledQueryServer.echo(sid=next(_sid), **kw)


def _drive(pqs, n, rate_hz=100.0, **kw):
    """Open-loop load against a live pool; returns the SLO report."""
    x = np.ones((8, 1), np.float32)
    return run_open_loop(
        "127.0.0.1", pqs.port, dims="8:1",
        arrivals=poisson_arrivals(rate_hz, n),
        make_frame=lambda i: TensorBuffer.of(x, pts=i),
        p99_budget_ms=kw.pop("p99_budget_ms", 250.0), **kw)


# -- basics -------------------------------------------------------------------

class TestPoolBasics:
    def test_echo_round_trip_and_clean_close(self):
        pqs = _echo_pool()
        pool = pqs.pool
        try:
            rep = _drive(pqs, 40)
            assert rep["completed"] == 40 and rep["lost"] == 0
            assert _conserved(pqs.admission_counters())
            st = pool.stats()
            # least-outstanding routing: per-worker reply counters exist
            # and account for every completion
            assert sum(w["replied"] for w in st["workers"]) == 40
            assert {w["state"] for w in st["workers"]} == {"ready"}
        finally:
            pids = pool.all_pids_ever()
            pqs.close()
        assert pids and not any(proc_alive(p) for p in pids)

    def test_out_spec_adopted_from_worker_hello(self):
        pqs = _echo_pool(dims="4:1")
        try:
            assert pqs.qs.out_spec is not None
            dims, types, _ = pqs.qs.out_spec.to_strings()
            assert dims == "4:1"
        finally:
            pqs.close()

    def test_serversrc_extra_stats_merge_pool_view(self):
        pqs = _echo_pool()
        try:
            src = TensorQueryServerSrc(name="s", id=pqs.sid, dims="8:1")
            out = src.extra_stats()
            assert out["pool_workers"] == 2
            assert out["worker0_state"] == "ready"
            assert "worker1_restarts" in out
        finally:
            pqs.close()

    def test_bad_config_rejected(self):
        with pytest.raises(ValueError, match="workers"):
            WorkerPool(QueryServer.get(next(_sid)), WorkerSpec(), 0)
        with pytest.raises(ValueError, match="kind"):
            WorkerSpec(kind="wat")
        with pytest.raises(ValueError, match="pipeline"):
            WorkerSpec(kind="pipeline")


# -- chaos: crash / hang / circuit -------------------------------------------

@pytest.mark.chaos
class TestCrashRecovery:
    def test_sigkill_mid_flood_conserves_and_recovers(self):
        """The ISSUE 9 acceptance smoke: 2-worker pool at 1.5x load,
        SIGKILL one worker mid-flood → zero lost frames, back at full
        capacity within the restart budget, zero orphans after close
        (/proc audit inside run_against_pool)."""
        rep = run_against_pool(
            n=160, service_ms=10.0, workers=2, load_x=1.5, kills=1,
            seed=3, max_pending=32, p99_budget_ms=90.0)
        assert rep["lost"] == 0
        assert rep["conserved"]
        assert rep["recovered"], rep["pool"]
        assert rep["orphans"] == []
        assert rep["kill_schedule"][0]["pid"] is not None
        assert rep["pool"]["pool"]["restarts"] >= 1
        assert rep["seed"] == 3

    def test_poison_frame_sheds_worker_lost_after_redelivery(self):
        """A frame that kills every worker that touches it must burn
        its redelivery budget and then be shed with BUSY(worker_lost) —
        not crash-loop the pool forever, not vanish in silence."""
        pqs = PooledQueryServer(
            WorkerSpec(kind="echo", service_ms=5.0, crash_pts=3),
            workers=1, sid=next(_sid), max_pending=32,
            restart_backoff_s=0.02)
        try:
            rep = _drive(pqs, 8, rate_hz=50.0, drain_timeout_s=20.0)
            assert rep["lost"] == 0
            assert rep["completed"] == 7
            assert rep["busy_causes"] == {"worker_lost": 1}
            c = pqs.admission_counters()
            assert c["shed"].get("worker_lost") == 1 and _conserved(c)
            # first delivery + one redelivery, each fatal
            assert pqs.pool.stats()["pool"]["restarts"] >= 2
        finally:
            pqs.close()

    def test_hang_detected_by_frame_deadline_not_heartbeat(self):
        """A worker wedged inside service keeps heartbeating (dedicated
        thread) — the per-frame liveness deadline is what must catch
        it, SIGKILL the worker, and shed the frame."""
        pqs = PooledQueryServer(
            WorkerSpec(kind="echo", service_ms=1.0, hang_pts=2),
            workers=1, sid=next(_sid), max_pending=32,
            frame_deadline_s=0.5, max_redeliver=0,
            per_worker_queue=1,   # only the hanging frame is in flight
            restart_backoff_s=0.02)
        try:
            rep = _drive(pqs, 5, rate_hz=100.0, drain_timeout_s=20.0)
            assert rep["lost"] == 0
            assert rep["completed"] == 4
            assert rep["busy_causes"] == {"worker_lost": 1}
            st = pqs.pool.stats()["pool"]
            assert st["kills"] >= 1        # SIGKILLed, not exited
            assert _conserved(pqs.admission_counters())
        finally:
            pqs.close()

    def test_restart_budget_circuit_degrades_instead_of_flapping(self):
        from nnstreamer_tpu.runtime.tracing import Tracer

        tracer = Tracer()
        pqs = PooledQueryServer(
            WorkerSpec(kind="echo", crash_after_s=0.05),
            workers=1, sid=next(_sid), tracer=tracer,
            restart_budget=2, restart_window_s=30.0,
            restart_backoff_s=0.01, ready_timeout_s=0.2)
        try:
            pool = pqs.pool
            deadline = time.monotonic() + 15
            while pool.degraded < 1 and time.monotonic() < deadline:
                time.sleep(0.05)
            st = pool.stats()
            assert st["pool"]["degraded"] == 1, st
            assert st["workers"][0]["state"] == DISABLED
            assert pool.live_workers() == 0
            # a tripped circuit stays tripped: no further restarts
            restarts = st["pool"]["restarts"]
            time.sleep(0.3)
            assert pool.stats()["pool"]["restarts"] == restarts
            # lifecycle surfaced through the tracer
            wc = tracer.summary()["workers"][pool.name]
            assert wc["degraded"] == 1 and wc["restart"] >= 2
        finally:
            pqs.close()


# -- hot swap -----------------------------------------------------------------

class TestPoolSwap:
    def test_two_phase_commit_bumps_epoch_on_all_workers(self):
        pqs = _echo_pool()
        try:
            rep = pqs.swap("m", 1)
            assert rep["ok"] and rep["epoch"] == 1
            assert all(w["prepare_ok"] and w["commit_ok"]
                       for w in rep["workers"].values())
            assert len(rep["workers"]) == 2
        finally:
            pqs.close()

    def test_prepare_failure_aborts_all_epoch_unchanged(self):
        pqs = PooledQueryServer(
            WorkerSpec(kind="echo", service_ms=1.0,
                       swap_fail_version=9),
            workers=2, sid=next(_sid))
        try:
            assert pqs.swap("m", 1)["ok"] and pqs.pool.epoch == 1
            rep = pqs.swap("m", 9)        # injected prepare failure
            assert not rep["ok"]
            assert pqs.pool.epoch == 1    # all-or-none: did not move
            # pool still serves after the aborted swap
            assert _drive(pqs, 10)["completed"] == 10
        finally:
            pqs.close()


# -- drain / close ------------------------------------------------------------

class TestPoolDrain:
    def test_close_drains_inflight_within_budget(self):
        pqs = _echo_pool(service_ms=30.0)
        qs = pqs.qs
        try:
            x = np.ones((8, 1), np.float32)
            for i in range(4):
                assert qs.frames.offer(TensorBuffer.of(x, pts=i)
                                       .with_meta(client_id=1)).admitted
            time.sleep(0.15)              # router dispatches them
        finally:
            pqs.close()
        c = qs.frames.counters()
        # drained, not shed: the frames finished inside the drain budget
        assert c["replied"] == 4 and c["shed"] == {} and _conserved(c)

    def test_close_is_idempotent(self):
        pqs = _echo_pool()
        pqs.close()
        before = pqs.qs.frames.counters()
        pqs.close()                       # second close: strict no-op
        assert pqs.qs.frames.counters() == before
        assert pqs.pool.closed

    def test_close_sheds_queued_frames_as_shutdown(self):
        # no client draining replies, workers too slow to finish:
        # whatever cannot complete inside the drain budget must be shed
        # with a typed cause, never silently dropped
        pqs = _echo_pool(workers=1, service_ms=200.0,
                         drain_timeout_s=0.2)
        qs = pqs.qs
        x = np.ones((8, 1), np.float32)
        for i in range(6):
            qs.frames.offer(TensorBuffer.of(x, pts=i)
                            .with_meta(client_id=1))
        time.sleep(0.05)
        pqs.close()
        c = qs.frames.counters()
        assert _conserved(c) and c["depth"] == 0 and c["inflight"] == 0
        assert c["replied"] + c["shed"].get("shutdown", 0) == 6


@pytest.mark.chaos
class TestNoOrphans:
    def test_two_worker_pool_kill_one_recover_zero_orphans(self):
        """ISSUE 9 satellite: tier-1 smoke — boot a 2-worker pool,
        SIGKILL one, assert recovery and zero orphans via a psutil-free
        /proc check over every pid the pool ever spawned."""
        pqs = _echo_pool(restart_backoff_s=0.02)
        pool = pqs.pool
        try:
            killed = pool.kill_worker()
            assert killed is not None
            # wait for the supervisor to notice, reap, and respawn
            deadline = time.monotonic() + 10
            while pool.stats()["pool"]["restarts"] < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert pool.stats()["pool"]["restarts"] == 1, pool.stats()
            assert pool.wait_ready(10.0), pool.stats()
            rep = _drive(pqs, 20)
            assert rep["completed"] == 20 and rep["lost"] == 0
        finally:
            pids = pool.all_pids_ever()
            pqs.close()
        assert len(pids) == 3             # 2 initial + 1 restart
        assert not any(proc_alive(p) for p in pids)


# -- distributed tracing across the pool (ISSUE 11) --------------------------

@pytest.mark.chaos
class TestPoolTracing:
    def test_redelivery_keeps_trace_id_across_workers(self):
        """ISSUE 11 regression: a frame redelivered after a worker
        SIGKILL keeps its ORIGINAL trace id — the merged timeline shows
        the dead worker's dispatch hop AND the replacement's, under one
        id. A fresh id on re-offer would sever the two attempts."""
        from nnstreamer_tpu.runtime.tracing import Tracer, hop_spans

        tr = Tracer()
        rep = run_against_pool(
            n=160, service_ms=15.0, workers=2, load_x=1.8, kills=1,
            seed=3, max_pending=32, p99_budget_ms=400.0, trace=True,
            tracer=tr)
        assert rep["lost"] == 0
        assert rep["conserved"]
        assert rep["orphans"] == []
        assert rep["pool"]["pool"]["reoffered"] >= 1, \
            "kill landed on an idle worker: no redelivery to test"
        redelivered = []
        for name, tid, t, hops, args in tr.requests():
            disp = [h for h in hops if h.get("hop") == "dispatch"]
            if len(disp) >= 2:
                redelivered.append((tid, hops, disp))
        assert redelivered, "no completed request carries 2 dispatches"
        for tid, hops, disp in redelivered:
            hop_names = [h["hop"] for h in hops]
            assert "reoffer" in hop_names
            # both attempts live under the one id: the dead worker's
            # pid (captured by the parent at dispatch time) differs
            # from the replacement's
            wpids = {h.get("wpid") for h in disp}
            assert len(wpids) == 2, (tid, disp)
            spans = hop_spans(hops)
            assert spans["redeliveries"] >= 1
            # stage math comes from the attempt that replied
            assert spans.get("service_ms", 0) > 0

    def test_worker_tracers_merge_into_pool_summary(self):
        """Each worker's own Tracer ships deltas over the heartbeat
        lane; the parent merges them into one summary and one Chrome
        trace with a track group per worker process."""
        from nnstreamer_tpu.runtime.tracing import (
            Tracer, ensure_trace_ctx)

        tr = Tracer()
        pqs = _echo_pool(service_ms=2.0, tracer=tr)
        try:
            x = np.ones((8, 1), np.float32)

            def mk(i):
                b = TensorBuffer.of(x, pts=i)
                ensure_trace_ctx(b.meta)
                return b

            rep = run_open_loop(
                "127.0.0.1", pqs.port, dims="8:1",
                arrivals=poisson_arrivals(150.0, 30),
                make_frame=mk, p99_budget_ms=500.0)
            assert rep["completed"] == 30 and rep["lost"] == 0
            # heartbeat interval bounds how long a delta can lag
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                kids = tr.children()
                if kids and sum(k["events_total"]
                                for k in kids.values()) >= 30:
                    break
                time.sleep(0.05)
            kids = tr.children()
            assert kids, "no worker shipped a trace delta"
            assert sum(k["events_total"] for k in kids.values()) >= 30
            # per-element histograms arrive namespaced per worker
            hists = tr.hists()
            assert any(n.startswith("w") and n.endswith("/echo")
                       for n in hists)
            assert sum(h["count"] for n, h in hists.items()
                       if "/echo" in n) == 30
            # one process track group per live worker in the export
            doc = tr.to_chrome_trace("pool")
            pids = {e["pid"] for e in doc["traceEvents"]}
            assert len(pids) >= 1 + len(kids)
            # request timelines span admission -> worker -> reply
            assert any(
                {"admit", "worker_recv", "reply"} <=
                {h.get("hop") for h in hops}
                for _, _, _, hops, _ in tr.requests())
        finally:
            pqs.close()
