"""DeepViewRT `.rtm` ingestion goldens.

Uses the reference's own checked-in `mobilenet_v1_0.25_224.rtm` (full
fp32 weights inside the RTMx flatbuffer) and the reference's own test
expectation (`tests/nnstreamer_filter_deepview_rt/runTest.sh:67-75`):
orange.png, normalize x/127.5 - 1, image_labeling → "orange"."""

import os

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.modelio import load_model_file
from nnstreamer_tpu.modelio.rtm import lower_rtm, parse_rtm
from nnstreamer_tpu.tensor.buffer import TensorBuffer

MODELS = "/root/reference/tests/test_models/models"
RTM = os.path.join(MODELS, "mobilenet_v1_0.25_224.rtm")
ORANGE = "/root/reference/tests/test_models/data/orange.png"
LABELS = "/root/reference/tests/test_models/labels/labels.txt"

needs_models = pytest.mark.skipif(
    not (os.path.exists(RTM) and os.path.exists(ORANGE)),
    reason="reference test models absent")


def _orange_rgb() -> np.ndarray:
    from PIL import Image

    return np.asarray(Image.open(ORANGE).convert("RGB"), np.uint8)


@needs_models
def test_parse_rtm_structure():
    g = parse_rtm(RTM)
    assert "DeepViewRT" in g.creator
    types = [lay.type_name for lay in g.layers]
    assert types.count("Const") == 56          # 28 weights + 28 biases
    assert types.count("Conv2D") == 28         # 27 body + logits
    assert types.count("Input") == 1
    assert "Softmax" in types and "Pool" in types
    # depthwise layers carry their real group count
    dw = next(lay for lay in g.layers
              if lay.name.endswith("Conv2d_1_depthwise/Relu6"))
    assert dw.attrs["groups"] == [8]
    w = next(lay for lay in g.layers
             if lay.name.endswith("Conv2d_1_depthwise/depthwise_weights"))
    assert w.tensor.shape == (3, 3, 8, 1)      # HWCM
    inp = next(lay for lay in g.layers if lay.type_name == "Input")
    assert inp.shape == (1, 224, 224, 3)


@needs_models
def test_rtm_classifies_orange():
    """The reference suite's golden: orange.png → 'orange' (951)."""
    import jax

    b = load_model_file(RTM)
    assert b.in_spec.tensors[0].shape == (1, 224, 224, 3)
    assert b.out_spec.tensors[0].shape == (1, 1001)
    x = (_orange_rgb().astype(np.float32) / 127.5 - 1.0) \
        .reshape(1, 224, 224, 3)
    y = np.asarray(jax.jit(b.fn)(b.params, x)[0])
    assert int(y.argmax()) == 951              # 'orange'
    assert float(y.max()) > 0.5                # softmax, decisive
    np.testing.assert_allclose(y.sum(), 1.0, atol=1e-4)


@needs_models
def test_rtm_full_pipeline_reference_transform():
    """End-to-end with the reference runTest.sh's exact transform
    option (typecast:float32,div:127.5,add:-1.0) and labels file."""
    pipe = nns.parse_launch(
        f"appsrc name=src dims=3:224:224:1 types=uint8 ! "
        f"tensor_transform mode=arithmetic "
        f"option=typecast:float32,div:127.5,add:-1.0 ! "
        f"tensor_filter model={RTM} ! "
        f"tensor_decoder mode=image_labeling option1={LABELS} ! "
        f"tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    pipe.get("src").push(
        TensorBuffer.of(_orange_rgb().reshape(1, 224, 224, 3)))
    pipe.get("src").end()
    runner.wait(300)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 1
    assert res[0].meta["label"] == "orange"


def test_rtm_padded_avg_pool_excludes_padding():
    """SAME-padded average pooling must divide each window by its
    VALID element count (TF semantics), not the full kernel size."""
    import jax

    from nnstreamer_tpu.modelio.rtm import RTMGraph, RTMLayer

    g = RTMGraph(creator="test", layers=[
        RTMLayer(index=0, name="input", type=0x01, inputs=[],
                 shape=(1, 4, 4, 1)),
        RTMLayer(index=1, name="net/AvgPool", type=0x3D, inputs=[0],
                 shape=(1, 4, 4, 1),
                 attrs={"ksize": [1, 3, 3, 1],
                        "strides": [1, 1, 1, 1],
                        "head": [0, 1, 1, 0], "tail": [0, 1, 1, 0]}),
    ])
    m = lower_rtm(g)
    x = np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)
    y = np.asarray(jax.jit(m.fn)(m.params, x)[0])
    # manual reference: mean over valid cells only
    xp = np.pad(x[0, :, :, 0], 1, constant_values=np.nan)
    want = np.empty((4, 4), np.float32)
    for i in range(4):
        for j in range(4):
            w = xp[i:i + 3, j:j + 3]
            want[i, j] = np.nanmean(w)
    np.testing.assert_allclose(y[0, :, :, 0], want, rtol=1e-6)


@needs_models
def test_rtm_batch_override_runs():
    """batch= rewrites the input batch; the batch-1 Reshape attr must
    follow the traced batch instead of crashing."""
    import jax

    b = load_model_file(RTM, batch=2)
    assert b.in_spec.tensors[0].shape == (2, 224, 224, 3)
    assert b.out_spec.tensors[0].shape == (2, 1001)
    x = (np.stack([_orange_rgb()] * 2).astype(np.float32) / 127.5
         - 1.0)
    y = np.asarray(jax.jit(b.fn)(b.params, x)[0])
    assert list(y.argmax(-1)) == [951, 951]


@needs_models
def test_rtm_unknown_layer_fails_loud():
    g = parse_rtm(RTM)
    g.layers[-1].type = 0x7777
    with pytest.raises(BackendError, match="type_0x7777"):
        lower_rtm(g)


@needs_models
def test_rtm_const_without_data_fails_loud():
    g = parse_rtm(RTM)
    const = next(lay for lay in g.layers if lay.type_name == "Const")
    const.tensor = None
    with pytest.raises(BackendError, match="no data"):
        lower_rtm(g)


@needs_models
def test_rtm_rejects_compute_dtype():
    with pytest.raises(BackendError, match="dtype"):
        load_model_file(RTM, compute_dtype="bfloat16")


def test_rtm_not_a_model_fails_loud(tmp_path):
    p = tmp_path / "junk.rtm"
    p.write_bytes(b"\x00\x01nope")
    with pytest.raises(BackendError, match="RTMx"):
        parse_rtm(str(p))
