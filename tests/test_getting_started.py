"""docs/getting-started.md must not drift from reality.

Every `python -m nnstreamer_tpu '...'` command in the walkthrough is
extracted verbatim and executed as a real CLI subprocess (sanitized to
the CPU backend, same pattern as test_multihost.py); the doc's expected
outputs are asserted against the files the pipelines write."""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "getting-started.md")
MODELS = "/root/reference/tests/test_models/models"

needs_models = pytest.mark.skipif(
    not os.path.exists(MODELS), reason="reference test models absent")


def _commands():
    text = open(DOC).read()
    # `python -m nnstreamer_tpu '<pipeline>' && cat <file>` lines
    pat = re.compile(
        r"python -m nnstreamer_tpu '([^']+)' && cat (\S+)")
    return pat.findall(text)


def _run_cli(pipeline: str) -> subprocess.CompletedProcess:
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON", "TPU_"))}
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "nnstreamer_tpu", pipeline],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO)


def test_doc_has_all_four_walkthrough_commands():
    cmds = _commands()
    assert len(cmds) == 4
    models = " ".join(p for p, _ in cmds)
    for needle in ("mobilenet_v2_1.0_224_quant.tflite",
                   "pytorch_lenet5.pt", "lenet_iter_9000.caffemodel",
                   "lenet5.uff"):
        assert needle in models


@needs_models
@pytest.mark.parametrize("idx,expected", [
    (0, "orange"), (1, "9"), (2, "9"), (3, "9")])
def test_walkthrough_command_produces_documented_output(
        idx, expected, tmp_path):
    pipeline, outfile = _commands()[idx]
    # keep the doc's /tmp paths out of parallel test runs' way
    private = str(tmp_path / os.path.basename(outfile))
    pipeline = pipeline.replace(outfile, private)
    proc = _run_cli(pipeline)
    assert proc.returncode == 0, proc.stderr[-2000:]
    got = open(private).read().strip()
    assert got == expected
