"""Runtime scheduler + end-to-end slice tests with the fake (custom)
backend — the XLA-free backbone of element testing (SURVEY.md §4
takeaway a: custom-easy functions as fake frameworks)."""

import time

import numpy as np
import pytest

from nnstreamer_tpu import (
    TensorBuffer,
    TensorsSpec,
    parse_launch,
    register_custom_easy,
    run_pipeline,
)
from nnstreamer_tpu.backends.custom import unregister_custom_easy
from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.elements.sources import AppSrc
from nnstreamer_tpu.runtime.scheduler import PipelineRunner


@pytest.fixture(autouse=True)
def _clean_models():
    names = []

    def reg(name, *a, **kw):
        names.append(name)
        return register_custom_easy(name, *a, **kw)

    yield reg
    for n in names:
        unregister_custom_easy(n)


class TestEndToEnd:
    def test_video_to_sink(self):
        p = parse_launch(
            "videotestsrc width=8 height=8 num-buffers=5 ! tensor_converter "
            "! tensor_sink name=out"
        )
        run_pipeline(p, timeout=10)
        sink = p.get("out")
        assert len(sink.results) == 5
        assert sink.results[0].tensors[0].shape == (1, 8, 8, 3)
        assert sink.eos.is_set()

    def test_full_slice_with_fake_filter(self, _clean_models):
        # converter → transform → filter(custom) → sink : the M4 slice
        _clean_models("double", lambda ts: tuple(2 * t for t in ts))
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=3 pattern=solid "
            "solid-color=10 ! tensor_converter ! "
            "tensor_transform mode=typecast option=float32 ! "
            "tensor_filter framework=custom model=double ! tensor_sink name=out"
        )
        run_pipeline(p, timeout=10)
        out = p.get("out").results
        assert len(out) == 3
        np.testing.assert_array_equal(
            out[0].tensors[0], np.full((1, 4, 4, 3), 20.0, np.float32)
        )

    def test_fusion_rewrites_graph_same_result(self, _clean_models):
        _clean_models("plus1", lambda ts: tuple(t + 1 for t in ts))
        desc = (
            "videotestsrc width=4 height=4 num-buffers=2 pattern=solid "
            "solid-color=5 ! tensor_converter ! "
            "tensor_transform mode=typecast option=float32 ! "
            "tensor_transform mode=arithmetic option=mul:2.0 ! "
            "tensor_filter framework=custom model=plus1 ! tensor_sink name=out"
        )
        p_fused = parse_launch(desc)
        run_pipeline(p_fused, timeout=10, optimize=True)
        p_plain = parse_launch(desc)
        run_pipeline(p_plain, timeout=10, optimize=False)
        # fusion removed the transforms from the graph
        assert not any(
            e.ELEMENT_NAME == "tensor_transform" for e in p_fused.elements.values()
        )
        a = p_fused.get("out").results[0].tensors[0]
        b = p_plain.get("out").results[0].tensors[0]
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, np.full((1, 4, 4, 3), 11.0, np.float32))

    def test_appsrc_push(self):
        p = parse_launch("appsrc dims=2:3 types=float32 name=in ! tensor_sink name=out")
        runner = PipelineRunner(p).start()
        src: AppSrc = p.get("in")
        for i in range(4):
            src.push(np.full((3, 2), i, np.float32))
        src.end()
        runner.wait(10)
        assert len(p.get("out").results) == 4

    def test_frames_per_tensor_batching(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=6 ! "
            "tensor_converter frames-per-tensor=3 ! tensor_sink name=out"
        )
        run_pipeline(p, timeout=10)
        out = p.get("out").results
        assert len(out) == 2
        assert out[0].tensors[0].shape == (3, 4, 4, 3)

    def test_error_propagates(self, _clean_models):
        def boom(ts):
            raise RuntimeError("backend exploded")

        # declare passthrough spec so negotiation's zero-probe is skipped
        # and the failure happens in the streaming hot loop
        _clean_models("boom", boom, infer_out=lambda s: s)
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=2 ! tensor_converter ! "
            "tensor_filter framework=custom model=boom ! tensor_sink name=out"
        )
        with pytest.raises(StreamError, match="backend exploded"):
            run_pipeline(p, timeout=10)

    def test_filter_stats(self, _clean_models):
        _clean_models("idle", lambda ts: ts)
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=5 ! tensor_converter ! "
            "tensor_filter framework=custom model=idle name=f ! tensor_sink name=out"
        )
        run_pipeline(p, timeout=10)
        f = p.get("f")
        assert f._invoke_count == 5
        assert f.latency_us >= 0
        assert f.throughput > 0


class TestDecoderSlice:
    def test_image_labeling(self, _clean_models, tmp_path):
        labels = tmp_path / "labels.txt"
        labels.write_text("cat\ndog\nbird\n")

        def classifier(ts):
            scores = np.zeros((1, 3), np.float32)
            scores[0, 1] = 0.9
            return (scores,)

        _clean_models(
            "clf", classifier,
            out_spec=TensorsSpec.from_strings("3:1", "float32"),
        )
        p = parse_launch(
            f"videotestsrc width=4 height=4 num-buffers=2 ! tensor_converter ! "
            f"tensor_filter framework=custom model=clf ! "
            f"tensor_decoder mode=image_labeling option1={labels} ! "
            f"tensor_sink name=out"
        )
        run_pipeline(p, timeout=10)
        res = p.get("out").results
        assert res[0].meta["label"] == "dog"
        assert bytes(res[0].tensors[0].tobytes()) == b"dog"

    def test_missing_labels_file(self):
        from nnstreamer_tpu.core.errors import PipelineError

        with pytest.raises(PipelineError, match="not found"):
            parse_launch(
                "appsrc dims=3:1 ! tensor_decoder mode=image_labeling "
                "option1=/nonexistent/labels.txt ! tensor_sink"
            )


class TestBackpressure:
    def test_slow_sink_does_not_deadlock(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=20 ! tensor_converter "
            "! tensor_sink name=out"
        )
        sink = p.get("out")
        orig = sink.render

        def slow_render(buf):
            time.sleep(0.005)
            orig(buf)

        sink.render = slow_render
        run_pipeline(p, timeout=30)
        assert len(sink.results) == 20


class TestReviewRegressions:
    def test_stop_unblocks_appsrc(self):
        p = parse_launch("appsrc dims=2:2 name=in ! tensor_sink name=out")
        runner = PipelineRunner(p).start()
        p.get("in").push(np.zeros((2, 2), np.float32))
        time.sleep(0.05)
        runner.stop()
        runner.wait(5)  # must not hang

    def test_arith_int_preserves_dtype(self):
        from nnstreamer_tpu.elements.transform import TransformProgram

        prog = TransformProgram("arithmetic", "add:2")
        out = prog.apply(np, np.array([1, 2], np.uint8))
        assert out.dtype == np.uint8
        info = prog.out_info(
            __import__("nnstreamer_tpu").TensorInfo((2,), "uint8"))
        assert info.dtype.type_name == "uint8"

    def test_arith_promoting_matches_spec(self):
        from nnstreamer_tpu.elements.transform import TransformProgram

        prog = TransformProgram("arithmetic", "add:-127.5,div:127.5")
        x = np.array([0, 255], np.uint8)
        out = prog.apply(np, x)
        assert out.dtype == np.float32  # matches declared transfer exactly
        info = prog.out_info(
            __import__("nnstreamer_tpu").TensorInfo((2,), "uint8"))
        assert info.dtype.type_name == "float32"

    def test_audio_adapter(self):

        from nnstreamer_tpu.graph.media import AudioSpec

        spec = AudioSpec(sample_rate=8000, channels=2, sample_format="S16LE")
        p = parse_launch(
            "appsrc name=in ! tensor_converter frames-per-tensor=160 "
            "! tensor_sink name=out")
        p.get("in").set_props(spec=spec)
        runner = PipelineRunner(p).start()
        src = p.get("in")
        for _ in range(4):  # 4 x 100 samples -> 2 x 160 with 80 left over
            src.push(TensorBuffer.of(np.zeros((100, 2), np.int16)))
        src.end()
        runner.wait(10)
        out = p.get("out").results
        assert len(out) == 2
        assert out[0].tensors[0].shape == (160, 2)

    def test_zoo_unknown_model_actionable(self):
        from nnstreamer_tpu.core.errors import NegotiationError

        p = parse_launch(
            "videotestsrc num-buffers=1 ! tensor_converter ! "
            "tensor_filter framework=xla model=zoo://nope ! tensor_sink")
        with pytest.raises(NegotiationError, match="no zoo model"):
            p.negotiate()

    def test_prop_after_ref_rejected(self):
        from nnstreamer_tpu.core.errors import PipelineError

        with pytest.raises(PipelineError, match="pad reference"):
            parse_launch("appsrc dims=2 ! m. foo=1 tensor_sink name=m")


class TestInputPipeline:
    """Double-buffered H2D staging (runtime/input_pipeline.py)."""

    def test_prefetch_yields_all_in_order(self):
        import jax

        from nnstreamer_tpu.runtime import prefetch_to_device

        batches = [np.full((4,), i, np.float32) for i in range(7)]
        out = list(prefetch_to_device(iter(batches), depth=2))
        assert len(out) == 7
        for i, y in enumerate(out):
            assert isinstance(y, jax.Array)
            np.testing.assert_array_equal(np.asarray(y), batches[i])

    def test_prefetch_overlaps_staging(self):
        """The producer runs ahead of the consumer (double buffering):
        with depth=2 the 2nd batch is staged while the 1st is consumed."""
        import threading
        import time

        from nnstreamer_tpu.runtime import prefetch_to_device

        staged = []
        gate = threading.Event()

        def slow_source():
            for i in range(4):
                staged.append(i)
                yield np.full((2,), i, np.float32)
            gate.set()

        it = prefetch_to_device(slow_source(), depth=2)
        first = next(it)
        time.sleep(0.05)            # let the worker run ahead
        assert len(staged) >= 2     # staged beyond what was consumed
        rest = list(it)
        assert len(rest) == 3 and gate.is_set()
        np.testing.assert_array_equal(np.asarray(first), [0, 0])

    def test_prefetch_propagates_source_error(self):
        from nnstreamer_tpu.runtime import prefetch_to_device

        def bad():
            yield np.zeros(2, np.float32)
            raise ValueError("sensor unplugged")

        it = prefetch_to_device(bad(), depth=1)
        next(it)
        with pytest.raises(ValueError, match="sensor unplugged"):
            for _ in it:
                pass

    def test_feeder_push_pull_and_close(self):
        from nnstreamer_tpu.runtime import DeviceFeeder

        f = DeviceFeeder(depth=2)
        f.put(np.arange(3, dtype=np.float32))
        f.put(np.arange(3, dtype=np.float32) * 2)
        f.close()
        a = f.get()
        b = f.get()
        np.testing.assert_array_equal(np.asarray(b), [0.0, 2.0, 4.0])
        assert f.get() is None
        with pytest.raises(RuntimeError, match="closed"):
            f.put(np.zeros(1, np.float32))

    def test_feeder_rejects_bad_depth(self):
        from nnstreamer_tpu.runtime import DeviceFeeder, prefetch_to_device

        with pytest.raises(ValueError, match="depth"):
            DeviceFeeder(depth=0)
        with pytest.raises(ValueError, match="depth"):
            list(prefetch_to_device(iter([]), depth=0))


def test_stats_report_queue_wait():
    """Per-element queue-wait counters (GstShark interlatency analog)
    separate starvation from slow elements in stats()."""
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    pipe = nns.parse_launch(
        "appsrc name=src dims=4:1 types=float32 ! "
        "tensor_transform mode=arithmetic option=add:1.0 name=tr ! "
        "tensor_sink name=out")
    runner = nns.PipelineRunner(pipe, optimize=False).start()
    for i in range(6):
        pipe.get("src").push(TensorBuffer.of(
            np.ones((1, 4), np.float32), pts=i))
    pipe.get("src").end()
    runner.wait(30)
    runner.stop()
    st = runner.stats()
    tr = st["tr"]
    assert tr["buffers"] == 6
    assert "queue_wait_avg_us" in tr and "queue_wait_max_us" in tr
    assert tr["queue_wait_max_us"] >= tr["queue_wait_avg_us"] >= 0.0
    assert tr["proctime_avg_us"] > 0.0
