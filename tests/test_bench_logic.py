"""Unit tests for bench.py's pure decision logic.

The bench mostly measures (driver-run on the real chip), but its
operating-point selection and stats helpers are plain functions whose
regressions would silently misreport results — pin them here (no jax,
no chip)."""

import bench


def test_percentile_bounds_and_interpolation():
    assert bench._percentile([], 50) == 0.0
    assert bench._percentile([7.0], 99) == 7.0
    vals = sorted([1.0, 2.0, 3.0, 4.0])
    assert bench._percentile(vals, 0) == 1.0
    assert bench._percentile(vals, 100) == 4.0
    assert bench._percentile(vals, 50) in (2.0, 3.0)


def _pt(fps, p50):
    return {"fps": fps, "p50_ms": p50}


def test_offload_chooser_prefers_target_box():
    # points meeting fps>=200 and p50<=60 win on lowest p50
    curve = {"0.0": _pt(210.0, 55.0), "3.0": _pt(250.0, 58.0),
             "8.0": _pt(300.0, 70.0)}
    out = bench._assemble_offload(curve)
    assert out["chosen_delay_ms"] == 0.0
    assert out["sweep"] is curve


def test_offload_chooser_near_best_fps_takes_lower_p50():
    # nothing in the target box: within 5% of best fps, lowest p50 wins
    # (trial-4 regression: 283 FPS @ 96ms must beat 285 FPS @ 112ms)
    curve = {"3.0": _pt(283.0, 96.1), "8.0": _pt(284.8, 111.7),
             "32.0": _pt(152.7, 129.7)}
    out = bench._assemble_offload(curve)
    assert out["chosen_delay_ms"] == 3.0


def test_offload_chooser_sub60_pool_preferred():
    # a sub-60ms point exists: the pool narrows to it even at lower fps
    curve = {"0.0": _pt(120.0, 45.0), "8.0": _pt(280.0, 100.0)}
    out = bench._assemble_offload(curve)
    assert out["chosen_delay_ms"] == 0.0


def test_offload_chooser_survives_errors_and_empty():
    curve = {"0.0": {"error": "boom"}, "8.0": _pt(100.0, 90.0)}
    out = bench._assemble_offload(curve)
    assert out["chosen_delay_ms"] == 8.0
    all_bad = {"0.0": {"error": "a"}, "8.0": {"error": "b"}}
    assert bench._assemble_offload(all_bad) == {"sweep": all_bad}


def test_family_registry_covers_main_order():
    ordered = bench._ordered_families()
    assert set(ordered) == set(bench._FAMILIES)
    assert len(ordered) == len(bench._FAMILIES)
    # the headline config must run first: a kill minutes in still ships
    # the driver's headline metric
    assert ordered[0] == "cfg_label_device"


def test_offload_median_spread():
    runs = [_pt(100.0, 50.0), _pt(300.0, 40.0), _pt(200.0, 45.0)]
    med = bench._offload_median(runs)
    assert med["fps"] == 200.0
    assert med["runs"] == 3
    assert med["fps_spread"] == [100.0, 300.0]
    assert med["p50_spread_ms"] == [40.0, 50.0]
    assert bench._offload_median([]) == {}
    assert bench._offload_median([{}, {"error": "x"}]) == {}
    # even count (budget-truncated 2-run point): lower-middle, never
    # the best run of a 3x-variance metric
    two = bench._offload_median([_pt(285.0, 100.0), _pt(86.0, 90.0)])
    assert two["fps"] == 86.0
    assert two["fps_spread"] == [86.0, 285.0]


# -- kill-resilience contract (round-5 VERDICT #1/#6) ------------------------
# The bench must ship data no matter when the driver kills it. These
# drive the REAL orchestration loop (subprocess families, budgets,
# timeouts, snapshot-per-family) with fake measurement families
# (BENCH_SELFTEST=fake — no jax, no chip), in miliseconds not minutes.

import json
import os
import signal
import subprocess
import sys
import time

BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def _blank_sitecustomize_dir():
    """A dir whose empty sitecustomize.py shadows any site-wide one.

    Dev-chip tunnels install a sitecustomize that imports jax on EVERY
    python startup (~2.4s measured) — longer than the selftest's
    per-family timeouts, so the stdlib-only fake families would be
    killed mid-import. PYTHONPATH entries precede site-packages, so an
    empty shadow restores interpreter startup to milliseconds and makes
    these timing contracts machine-independent. Lazy (first _env call,
    not collection) and removed at interpreter exit.
    """
    global _SITE_DIR
    if _SITE_DIR is None:
        import atexit
        import shutil
        import tempfile

        d = tempfile.mkdtemp(prefix="bench_selftest_site_")
        with open(os.path.join(d, "sitecustomize.py"), "w") as f:
            f.write("")
        atexit.register(shutil.rmtree, d, ignore_errors=True)
        _SITE_DIR = d
    return _SITE_DIR


_SITE_DIR = None


def _env(**over):
    e = dict(os.environ, BENCH_SELFTEST="fake")
    pp = e.get("PYTHONPATH", "")
    e["PYTHONPATH"] = _blank_sitecustomize_dir() + (
        os.pathsep + pp if pp else "")
    e.update({k: str(v) for k, v in over.items()})
    return e


def _snapshots(stdout: str):
    """All parseable full-result lines, in order (the driver keeps the
    last parseable line — these are what a kill would leave behind)."""
    out = []
    for line in stdout.splitlines():
        try:
            d = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(d, dict) and "metric" in d:
            out.append(d)
    return out


def test_selftest_run_ships_partials_for_hang_and_error():
    """Full fake run: a hanging family is killed at the per-family
    timeout but its streamed partial survives; a crashing family is
    recorded as an error; every completed family is in the artifact."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        env=_env(BENCH_BUDGET_S=30, BENCH_FAMILY_TIMEOUT_S=2,
                 BENCH_SELFTEST_HANG_S=600, BENCH_SELFTEST_STEP_S=0.01),
        timeout=60)
    wall = time.monotonic() - t0
    snaps = _snapshots(proc.stdout)
    # one snapshot per fake family (6) plus the final line
    assert len(snaps) >= 7
    final = snaps[-1]
    fams = final["families"]
    assert fams["fast_a"] == {"v": 1}
    assert fams["fast_b"] == {"v": 2}
    assert fams["tail_z"] == {"v": 3}
    assert fams["slow_stream"]["step39"] == 39
    # the hang family timed out, but its streamed partial was kept
    assert fams["hang"] == {"streamed": "before-hang"}
    assert "timed out" in final["errors"]["hang"]
    assert "partial result kept" in final["errors"]["hang"]
    assert "ZeroDivisionError" in final["errors"]["boom"]
    # the hang was killed at ~2s, not 600s
    assert wall < 30


def test_budget_exhaustion_skips_tail_loudly():
    """A tight budget skips late families with a recorded reason, and
    wall-clock stays bounded by the budget, not by family count."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        env=_env(BENCH_BUDGET_S=3, BENCH_FAMILY_TIMEOUT_S=2,
                 BENCH_SELFTEST_HANG_S=600, BENCH_SELFTEST_STEP_S=0.2),
        timeout=60)
    wall = time.monotonic() - t0
    final = _snapshots(proc.stdout)[-1]
    assert wall < 20            # 6 families, none allowed to run long
    skipped = [k for k, v in final["errors"].items()
               if "budget" in str(v)]
    assert skipped, f"expected skipped families, errors={final['errors']}"
    # what ran before the budget ran out is still in the artifact
    assert final["families"].get("fast_a") == {"v": 1}


def test_implausibly_slow_cfg_retried_with_both_results_shipped(
        tmp_path):
    """A BASELINE-table config under the 30 FPS target (tunnel
    pathology) is retried once; the artifact carries BOTH results."""
    state = tmp_path / "flaky_count"
    proc = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        env=_env(BENCH_BUDGET_S=60, BENCH_FAMILY_TIMEOUT_S=30,
                 BENCH_SELFTEST_HANG_S=0, BENCH_SELFTEST_STEP_S=0.01,
                 BENCH_SELFTEST_STATE=state),
        timeout=120)
    final = _snapshots(proc.stdout)[-1]
    flaky = final["families"]["cfg_flaky"]
    assert flaky["fps"] == 100.0
    assert flaky["slow_first_attempt"]["fps"] == 5.0


def test_sigkill_mid_run_leaves_parseable_snapshot():
    """SIGKILL (untrappable — the driver's last resort) at an arbitrary
    point: the last fully-printed snapshot line still carries every
    completed family."""
    proc = subprocess.Popen(
        [sys.executable, BENCH], stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
        env=_env(BENCH_BUDGET_S=60, BENCH_FAMILY_TIMEOUT_S=30,
                 BENCH_SELFTEST_HANG_S=0, BENCH_SELFTEST_STEP_S=0.3))
    # wait for the first snapshot (fast_a done), then SIGKILL mid-stream
    lines = []
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line)
        if _snapshots(line):
            break
    proc.kill()
    rest, _ = proc.communicate(timeout=30)
    snaps = _snapshots("".join(lines) + rest)
    assert snaps, "no parseable snapshot survived the SIGKILL"
    assert snaps[-1]["families"].get("fast_a") == {"v": 1}
    assert snaps[-1].get("partial") is True


def test_partial_file_persisted_and_disableable(tmp_path):
    """Every snapshot is also atomically mirrored to BENCH_PARTIAL_PATH
    (round-4 regression: BENCH_r04 hit the driver's `timeout -k` with rc
    124 and shipped NOTHING — stdout dies with the terminal, a file
    survives). Empty path disables the mirror."""
    part = tmp_path / "part.json"
    proc = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        env=_env(BENCH_BUDGET_S=30, BENCH_FAMILY_TIMEOUT_S=2,
                 BENCH_SELFTEST_HANG_S=0, BENCH_SELFTEST_STEP_S=0.01,
                 BENCH_PARTIAL_PATH=part),
        timeout=60)
    with open(part) as f:
        saved = json.load(f)
    # the mirror carries the same cumulative artifact as stdout
    final = _snapshots(proc.stdout)[-1]
    assert saved["families"].get("fast_a") == {"v": 1}
    assert saved["families"] == final["families"]
    # no stray tmp file left behind by the atomic-replace dance
    assert list(tmp_path.iterdir()) == [part]

    off = tmp_path / "off.json"
    subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        env=_env(BENCH_BUDGET_S=30, BENCH_FAMILY_TIMEOUT_S=2,
                 BENCH_SELFTEST_HANG_S=0, BENCH_SELFTEST_STEP_S=0.01,
                 BENCH_PARTIAL_PATH=""),
        timeout=60)
    assert not off.exists()


def test_sigterm_partial_file_written_signal_safely(tmp_path):
    """SIGTERM mid-hang: the handler's os.write path leaves a parseable
    partial file even though normal emission never ran again."""
    part = tmp_path / "term.json"
    proc = subprocess.Popen(
        [sys.executable, BENCH], stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
        env=_env(BENCH_BUDGET_S=120, BENCH_FAMILY_TIMEOUT_S=60,
                 BENCH_SELFTEST_HANG_S=600, BENCH_SELFTEST_STEP_S=0.3,
                 BENCH_PARTIAL_PATH=part))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        d = _snapshots(line)
        if d and "fast_b" in d[-1].get("families_done", []):
            proc.send_signal(signal.SIGTERM)
            break
    proc.communicate(timeout=30)
    assert proc.returncode == 3
    with open(part) as f:
        saved = json.load(f)
    assert saved["families"].get("fast_b") == {"v": 2}
    assert saved["errors"]["bench"] == "terminated by SIGTERM"


def test_sigterm_emits_final_snapshot():
    """SIGTERM (what `timeout` sends first): the handler reaps the
    in-flight child and prints a final cumulative snapshot before
    exiting."""
    proc = subprocess.Popen(
        [sys.executable, BENCH], stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
        env=_env(BENCH_BUDGET_S=120, BENCH_FAMILY_TIMEOUT_S=60,
                 BENCH_SELFTEST_HANG_S=600, BENCH_SELFTEST_STEP_S=0.3))
    saw = []
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        saw.append(line)
        d = _snapshots(line)
        # terminate while the hang family is in flight
        if d and "fast_b" in d[-1].get("families_done", []):
            proc.send_signal(signal.SIGTERM)
            break
    rest, _ = proc.communicate(timeout=30)
    assert proc.returncode == 3
    snaps = _snapshots("".join(saw) + rest)
    final = snaps[-1]
    assert final["errors"]["bench"] == "terminated by SIGTERM"
    assert final["families"].get("fast_a") == {"v": 1}
    assert final["families"].get("fast_b") == {"v": 2}
