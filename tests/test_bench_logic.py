"""Unit tests for bench.py's pure decision logic.

The bench mostly measures (driver-run on the real chip), but its
operating-point selection and stats helpers are plain functions whose
regressions would silently misreport results — pin them here (no jax,
no chip)."""

import bench


def test_percentile_bounds_and_interpolation():
    assert bench._percentile([], 50) == 0.0
    assert bench._percentile([7.0], 99) == 7.0
    vals = sorted([1.0, 2.0, 3.0, 4.0])
    assert bench._percentile(vals, 0) == 1.0
    assert bench._percentile(vals, 100) == 4.0
    assert bench._percentile(vals, 50) in (2.0, 3.0)


def _pt(fps, p50):
    return {"fps": fps, "p50_ms": p50}


def test_offload_chooser_prefers_target_box():
    # points meeting fps>=200 and p50<=60 win on lowest p50
    curve = {"0.0": _pt(210.0, 55.0), "3.0": _pt(250.0, 58.0),
             "8.0": _pt(300.0, 70.0)}
    out = bench._assemble_offload(curve)
    assert out["chosen_delay_ms"] == 0.0
    assert out["sweep"] is curve


def test_offload_chooser_near_best_fps_takes_lower_p50():
    # nothing in the target box: within 5% of best fps, lowest p50 wins
    # (trial-4 regression: 283 FPS @ 96ms must beat 285 FPS @ 112ms)
    curve = {"3.0": _pt(283.0, 96.1), "8.0": _pt(284.8, 111.7),
             "32.0": _pt(152.7, 129.7)}
    out = bench._assemble_offload(curve)
    assert out["chosen_delay_ms"] == 3.0


def test_offload_chooser_sub60_pool_preferred():
    # a sub-60ms point exists: the pool narrows to it even at lower fps
    curve = {"0.0": _pt(120.0, 45.0), "8.0": _pt(280.0, 100.0)}
    out = bench._assemble_offload(curve)
    assert out["chosen_delay_ms"] == 0.0


def test_offload_chooser_survives_errors_and_empty():
    curve = {"0.0": {"error": "boom"}, "8.0": _pt(100.0, 90.0)}
    out = bench._assemble_offload(curve)
    assert out["chosen_delay_ms"] == 8.0
    all_bad = {"0.0": {"error": "a"}, "8.0": {"error": "b"}}
    assert bench._assemble_offload(all_bad) == {"sweep": all_bad}


def test_family_registry_covers_main_order():
    ordered = ([f"cfg_{n}" for n in bench._CONFIGS]
               + ["pallas", "transformer_prefill", "mxu_peak"]
               + [f"offload_{d}" for d in bench.OFFLOAD_DELAYS]
               + ["batch_sweep", "int8_native"])
    assert set(ordered) == set(bench._FAMILIES)
    assert len(ordered) == len(bench._FAMILIES)
