"""SLO autotuner tests (serving/autotune.py): eager spec validation,
the guardrail ladder (clamp → hysteresis → cooldown → bounded step),
dry-run, audit-ring accounting across wrap, staged bucket refinement,
advisory hints, the tracer/metrics surfaces, and the live closed-loop
ramp against a real echo server.

All controller tests drive tick() with an injected fake clock and fake
knob targets — the guardrail semantics are deterministic, no sleeps."""

import json

import pytest

from nnstreamer_tpu.edge import QueryServer
from nnstreamer_tpu.runtime.tracing import NULL_TRACER, Tracer
from nnstreamer_tpu.serving.autotune import (
    DEFAULT_KNOB_RANGES, LITTLE_MARGIN, AutoTuner, KnobRange, SLOSpec)
from nnstreamer_tpu.serving.metrics import (
    metrics_snapshot, parse_prometheus, render_prometheus)
from nnstreamer_tpu.traffic import run_autotune_ramp

pytestmark = pytest.mark.autotune


@pytest.fixture(autouse=True)
def _clean_servers():
    yield
    QueryServer.reset_all()


# -- fakes -------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeAdmission:
    """Just enough of AdmissionQueue for the controller: counters()
    reflecting the injected sensor readings, configure() recording and
    actually moving max_pending (so the loop sees its own effect)."""

    def __init__(self, max_pending=64, ewma=None, depth=0,
                 depth_peak=0, shed_policy="reject-newest"):
        self.max_pending = max_pending
        self.ewma = ewma
        self.depth = depth
        self.depth_peak = depth_peak
        self.shed_policy = shed_policy
        self.configured = []
        self.victims_next = []

    def counters(self):
        return {"max_pending": self.max_pending,
                "ewma_reply_s": self.ewma,
                "depth": self.depth,
                "depth_peak": self.depth_peak,
                "shed_policy": self.shed_policy}

    def configure(self, max_pending=None, **kw):
        self.configured.append(max_pending)
        self.max_pending = max_pending
        v, self.victims_next = self.victims_next, []
        return v


class FakeProps(dict):
    """props dict that journals writes, so tests can assert staging
    happened strictly before the knob flip."""

    def __init__(self, *a, events=None, **kw):
        super().__init__(*a, **kw)
        self.events = events if events is not None else []

    def __setitem__(self, k, v):
        self.events.append(("set", k, v))
        super().__setitem__(k, v)


class FakeBatch:
    def __init__(self, max_latency_ms=4.0, max_batch=16, stats=None,
                 events=None):
        self.name = "batch0"
        self.props = FakeProps(
            {"max_latency_ms": max_latency_ms, "max_batch": max_batch},
            events=events)
        self._stats = stats or {}

    def extra_stats(self):
        return dict(self._stats)


class FakeBackend:
    def __init__(self, hist, events=None):
        self.batch_size_hist = dict(hist)
        self.events = events if events is not None else []

    def stage_bucket(self, nb):
        self.events.append(("stage", nb))
        return True


class FakeFilter:
    def __init__(self, backend):
        self.backend = backend


class FakeTracer:
    active = True

    def __init__(self, p99_ms=None, tenant=None):
        self.p99_ms = p99_ms
        self.tenant = tenant or {}
        self.records = []

    def tenant_summary(self):
        return dict(self.tenant)

    def interlatency(self):
        if self.p99_ms is None:
            return {}
        return {"el": {"p99_ms": self.p99_ms}}

    def record_autotune(self, name, knob, t, **args):
        self.records.append((name, knob, dict(args)))


# -- SLOSpec / KnobRange validation ------------------------------------------

class TestSLOSpecValidation:
    def test_roundtrip_and_accessors(self):
        spec = SLOSpec.from_dict({
            "p99_budget_ms": 90,
            "goodput_floor_rps": 50,
            "tenants": {"acme": {"p99_budget_ms": 50}, "free": 200},
            "knobs": {"max_pending": {"min": 4, "max": 256}}})
        assert spec.p99_budget_ms == 90.0
        assert spec.tenant_budget_ms("acme") == 50.0
        assert spec.tenant_budget_ms("free") == 200.0
        assert spec.tenant_budget_ms("unknown") == 90.0   # falls back
        assert spec.knob_range("max_pending") == \
            KnobRange("max_pending", 4.0, 256.0)
        # undeclared knobs fall back to the conservative defaults
        assert spec.knob_range("max_batch") is \
            DEFAULT_KNOB_RANGES["max_batch"]
        assert SLOSpec.from_dict(spec.to_dict()) == spec

    def test_from_json(self, tmp_path):
        p = tmp_path / "slo.json"
        p.write_text(json.dumps({"p99_budget_ms": 25}))
        assert SLOSpec.from_json(str(p)).p99_budget_ms == 25.0

    @pytest.mark.parametrize("d", [
        [],                                        # not an object
        {},                                        # missing budget
        {"p99_budget_ms": 0},                      # budget must be > 0
        {"p99_budget_ms": -5},
        {"p99_budget_ms": float("nan")},
        {"p99_budget_ms": float("inf")},
        {"p99_budget_ms": True},                   # bool is not a number
        {"p99_budget_ms": "90"},
        {"p99_budget_ms": 90, "goodput_floor_rps": -1},
        {"p99_budget_ms": 90, "tenants": ["acme"]},
        {"p99_budget_ms": 90, "tenants": {"bad name!": 50}},
        {"p99_budget_ms": 90, "tenants": {"acme": 0}},
        {"p99_budget_ms": 90, "tenants": {"acme": {}}},  # needs budget
        {"p99_budget_ms": 90, "knobs": {"warp_factor":  # unknown knob
                                        {"min": 1, "max": 9}}},
        {"p99_budget_ms": 90, "knobs": {"max_pending": {"min": 8}}},
        {"p99_budget_ms": 90, "knobs": {"max_pending":
                                        {"min": 64, "max": 8}}},
    ])
    def test_malformed_specs_fail_eagerly(self, d):
        with pytest.raises(ValueError):
            SLOSpec.from_dict(d)

    def test_knob_range_clamp(self):
        r = KnobRange("max_pending", 4, 64)
        assert r.clamp(1) == 4 and r.clamp(999) == 64
        assert r.clamp(32) == 32
        with pytest.raises(ValueError, match="min 64.* max 8"):
            KnobRange("max_pending", 64, 8)
        with pytest.raises(ValueError):
            KnobRange("max_pending", float("nan"), 8)


# -- the guardrail ladder (fake admission, injected clock) -------------------

class TestGuardrails:
    def _tuner(self, adm, clock, **kw):
        kw.setdefault("slo", SLOSpec(p99_budget_ms=60))
        return AutoTuner(kw.pop("slo"), admission=adm, now=clock, **kw)

    def test_littles_law_convergence_then_hysteresis_hold(self):
        """ewma 5 ms, budget 60 ms → Little's-law target = 6; from 64
        the bounded step walks 64→32→16→8→6 and then the hysteresis
        band holds — the controller settles, it does not hunt."""
        adm = FakeAdmission(max_pending=64, ewma=0.005)
        clock = FakeClock()
        tuner = self._tuner(adm, clock)
        assert LITTLE_MARGIN * 0.060 / 0.005 == 6.0
        for _ in range(4):
            tuner.tick()
            clock.advance(10.0)        # past the cooldown each time
        assert adm.configured == [32, 16, 8, 6]
        for _ in range(5):             # converged: nothing more moves
            tuner.tick()
            clock.advance(10.0)
        assert adm.configured == [32, 16, 8, 6]
        st = tuner.stats()
        assert st["decisions"]["max_pending"]["applied"] == 4
        assert st["decisions"]["max_pending"]["hysteresis"] >= 5
        assert [r["new"] for r in tuner.audit()] == [32.0, 16.0, 8.0, 6.0]

    def test_hysteresis_bounds_flapping_sensor(self):
        """A sensor flapping a few percent around the operating point
        must produce zero knob motion."""
        adm = FakeAdmission(max_pending=6, ewma=0.0048)
        clock = FakeClock()
        tuner = self._tuner(adm, clock)
        for i in range(20):
            adm.ewma = 0.0048 if i % 2 == 0 else 0.0052
            tuner.tick()
            clock.advance(10.0)
        assert adm.configured == []
        st = tuner.stats()
        assert st["applied_total"] == 0
        assert st["decisions"]["max_pending"]["hysteresis"] == 20
        assert st["audit_total"] == 0   # held decisions never hit the ring

    def test_cooldown_blocks_back_to_back_moves(self):
        adm = FakeAdmission(max_pending=64, ewma=0.005)
        clock = FakeClock()
        tuner = self._tuner(adm, clock, cooldown_s=5.0)
        tuner.tick()
        assert adm.configured == [32]
        clock.advance(1.0)             # still inside the cooldown
        tuner.tick()
        assert adm.configured == [32]
        assert tuner.stats()["decisions"]["max_pending"]["cooldown"] == 1
        clock.advance(10.0)
        tuner.tick()
        assert adm.configured == [32, 16]

    def test_dry_run_applies_nothing(self):
        """The dry_run proof the issue demands: the decision stream is
        produced and audited, but no configure() ever lands."""
        adm = FakeAdmission(max_pending=64, ewma=0.005)
        clock = FakeClock()
        tuner = self._tuner(adm, clock, dry_run=True)
        for _ in range(4):
            tuner.tick()
            clock.advance(10.0)
        assert adm.configured == []            # nothing actuated, ever
        assert adm.max_pending == 64
        st = tuner.stats()
        assert st["dry_run"] is True
        assert st["applied_total"] == 0 and st["dry_run_total"] == 4
        assert all(r["outcome"] == "dry_run" for r in tuner.audit())

    def test_step_is_bounded_and_clamped_to_declared_range(self):
        """A wildly wrong sensor cannot slam the knob: one tick moves
        at most step_frac of the current value, and never outside the
        declared range."""
        adm = FakeAdmission(max_pending=64, ewma=10.0)   # target ≈ 0.005
        clock = FakeClock()
        spec = SLOSpec.from_dict({
            "p99_budget_ms": 60,
            "knobs": {"max_pending": {"min": 16, "max": 128}}})
        tuner = self._tuner(adm, clock, slo=spec)
        tuner.tick()
        assert adm.configured == [32]          # one bounded step, not 16
        clock.advance(10.0)
        tuner.tick()
        assert adm.configured == [32, 16]      # clamped at declared min
        clock.advance(10.0)
        tuner.tick()
        assert adm.configured == [32, 16]      # held at the floor

    def test_audit_ring_wraps_with_exact_accounting(self):
        """audit_size=4, cooldown off, sensor flipped hard every tick →
        every tick applies; the ring keeps the newest 4 while the
        totals stay exact: audit_total - audit_len == audit_dropped and
        the outcome counters account for every recorded decision."""
        adm = FakeAdmission(max_pending=64, ewma=0.02)
        clock = FakeClock()
        tuner = self._tuner(adm, clock, cooldown_s=0.0, audit_size=4)
        for i in range(10):
            adm.ewma = 0.02 if i % 2 == 0 else 0.002
            tuner.tick()
            clock.advance(1.0)
        assert len(adm.configured) == 10
        st = tuner.stats()
        assert st["audit_total"] == 10
        assert st["audit_len"] == 4
        assert st["audit_dropped"] == 6
        assert st["audit_total"] - st["audit_len"] == st["audit_dropped"]
        assert st["decisions"]["max_pending"]["applied"] == 10
        # the ring holds exactly the newest 4 applied values
        assert [r["new"] for r in tuner.audit()] == \
            [float(v) for v in adm.configured[-4:]]

    def test_shrink_victims_routed_to_callback(self):
        adm = FakeAdmission(max_pending=64, ewma=0.005)
        adm.victims_next = ["v1", "v2"]
        clock = FakeClock()
        got_victims, got_applied = [], []
        tuner = self._tuner(adm, clock, on_victims=got_victims.extend,
                            on_apply=got_applied.append)
        tuner.tick()
        assert got_victims == ["v1", "v2"]
        assert [r["knob"] for r in got_applied] == ["max_pending"]
        assert got_applied[0]["evidence"]["ewma_reply_s"] == 0.005

    def test_actuation_failure_is_an_error_outcome(self):
        class Broken(FakeAdmission):
            def configure(self, **kw):
                raise RuntimeError("boom")

        adm = Broken(max_pending=64, ewma=0.005)
        clock = FakeClock()
        tuner = self._tuner(adm, clock)
        tuner.tick()                   # must not raise out of the loop
        st = tuner.stats()
        assert st["decisions"]["max_pending"]["error"] == 1
        assert tuner.audit()[-1]["outcome"] == "error"


# -- batch-deadline stage ----------------------------------------------------

class TestBatchDeadline:
    def test_shrinks_deadline_when_budget_threatened(self):
        el = FakeBatch(max_latency_ms=8.0, max_batch=16)
        tuner = AutoTuner(SLOSpec(p99_budget_ms=100),
                          batch_elements=(el,),
                          tracer=FakeTracer(p99_ms=90.0),
                          now=FakeClock())
        recs = tuner.tick()
        assert el.props["max_latency_ms"] == 4.0
        (rec,) = recs
        assert rec["knob"] == "batch_deadline_ms"
        assert rec["target"] == "batch0"       # which element moved
        assert rec["evidence"]["p99_ms"] == 90.0

    def test_grows_deadline_on_headroom_and_half_empty_batches(self):
        el = FakeBatch(max_latency_ms=4.0, max_batch=16,
                       stats={"batches_out": 10, "occupancy_avg": 2.0})
        tuner = AutoTuner(SLOSpec(p99_budget_ms=100),
                          batch_elements=(el,),
                          tracer=FakeTracer(p99_ms=30.0),
                          now=FakeClock())
        tuner.tick()
        assert el.props["max_latency_ms"] == 6.0   # one bounded step up

    def test_holds_inside_the_band(self):
        el = FakeBatch(max_latency_ms=4.0, max_batch=16,
                       stats={"batches_out": 10, "occupancy_avg": 2.0})
        tuner = AutoTuner(SLOSpec(p99_budget_ms=100),
                          batch_elements=(el,),
                          tracer=FakeTracer(p99_ms=60.0),
                          now=FakeClock())
        assert tuner.tick() == []
        assert el.props["max_latency_ms"] == 4.0

    def test_no_tracer_no_motion(self):
        el = FakeBatch(max_latency_ms=4.0)
        tuner = AutoTuner(SLOSpec(p99_budget_ms=100),
                          batch_elements=(el,), now=FakeClock())
        assert tuner.tick() == []


# -- bucket refinement stage -------------------------------------------------

class TestBucketRefinement:
    def test_refines_to_observed_pow2_staging_before_flip(self):
        """p95 observed batch is 3 → bucket 4; from max_batch 16 the
        bounded step walks 16→8→4, and each move stages the bucket on
        the backend strictly before flipping the knob."""
        events = []
        el = FakeBatch(max_batch=16, events=events)
        be = FakeBackend({3: 50}, events=events)
        clock = FakeClock()
        tuner = AutoTuner(SLOSpec(p99_budget_ms=100),
                          batch_elements=(el,),
                          filters=(FakeFilter(be),), now=clock)
        tuner.tick()
        assert events == [("stage", 8), ("set", "max_batch", 8)]
        clock.advance(10.0)
        tuner.tick()
        assert el.props["max_batch"] == 4
        assert events[-2:] == [("stage", 4), ("set", "max_batch", 4)]
        clock.advance(10.0)
        assert tuner.tick() == []      # at the target bucket: settled

    def test_refinement_is_shrink_only(self):
        """Observed batches larger than max_batch never raise it — the
        negotiated ceiling is not the controller's to lift."""
        el = FakeBatch(max_batch=16)
        be = FakeBackend({32: 50})
        tuner = AutoTuner(SLOSpec(p99_budget_ms=100),
                          batch_elements=(el,),
                          filters=(FakeFilter(be),), now=FakeClock())
        assert tuner.tick() == []
        assert el.props["max_batch"] == 16

    def test_needs_enough_signal(self):
        el = FakeBatch(max_batch=16)
        be = FakeBackend({3: 7})       # fewer than 8 observed invokes
        tuner = AutoTuner(SLOSpec(p99_budget_ms=100),
                          batch_elements=(el,),
                          filters=(FakeFilter(be),), now=FakeClock())
        assert tuner.tick() == []


# -- advisory hints (proposed, never actuated) -------------------------------

class TestHints:
    def test_scale_up_proposed_under_goodput_floor(self):
        adm = FakeAdmission(max_pending=8, ewma=0.1, depth=4,
                            depth_peak=8)
        clock = FakeClock()
        # budget picked so the Little's-law target equals the current
        # bound — the admission stage holds and only the hint fires
        tuner = AutoTuner(
            SLOSpec(p99_budget_ms=1600, goodput_floor_rps=50),
            admission=adm, now=clock)
        recs = [r for r in tuner.tick() if r["knob"] == "pool_slots"]
        (rec,) = recs
        assert rec["outcome"] == "proposed" and rec["new"] == "scale_up"
        assert adm.configured == []    # a hint is never actuated
        clock.advance(10.0)
        # same situation → deduped, not re-recorded every tick
        assert [r for r in tuner.tick() if r["knob"] == "pool_slots"] \
            == []
        st = tuner.stats()
        assert st["proposed_total"] == 1
        assert st["hints"] == {"pool_slots": "scale_up"}

    def test_shed_policy_proposed_when_budget_missed_at_saturation(self):
        adm = FakeAdmission(max_pending=8, ewma=None, depth=8,
                            depth_peak=8, shed_policy="reject-newest")
        tuner = AutoTuner(SLOSpec(p99_budget_ms=100), admission=adm,
                          tracer=FakeTracer(p99_ms=150.0),
                          now=FakeClock())
        (rec,) = tuner.tick()
        assert rec["knob"] == "shed_policy"
        assert rec["outcome"] == "proposed"
        assert (rec["old"], rec["new"]) == \
            ("reject-newest", "reject-oldest")
        assert adm.configured == []


# -- tracer + metrics surfaces -----------------------------------------------

class TestObservability:
    def test_decisions_land_on_the_tracer(self):
        tr = Tracer()
        adm = FakeAdmission(max_pending=64, ewma=0.005)
        tuner = AutoTuner(SLOSpec(p99_budget_ms=60), admission=adm,
                          tracer=tr, now=FakeClock())
        tuner.tick()
        ((name, knob, _t, args),) = tr.autotune_events()
        assert (name, knob) == ("autotune", "max_pending")
        assert args["outcome"] == "applied"
        assert (args["old"], args["new"]) == (64.0, 32.0)
        assert tr.autotune_counts() == {"max_pending": {"applied": 1}}
        assert tr.summary()["autotune"] == tr.autotune_counts()

    def test_tracer_ring_wraps_with_exact_counts(self):
        tr = Tracer()
        for i in range(1030):
            tr.record_autotune("autotune", "max_pending", float(i),
                               old=1, new=2, outcome="applied")
        assert len(tr.autotune_events()) == 1030 - 256
        assert tr.autotune_counts() == \
            {"max_pending": {"applied": 1030}}   # exact across the drop

    def test_null_tracer_is_a_no_op(self):
        NULL_TRACER.record_autotune("autotune", "max_pending", 0.0,
                                    old=1, new=2, outcome="applied")

    def test_metrics_snapshot_exports_autotune_series(self):
        adm = FakeAdmission(max_pending=64, ewma=0.005)
        tuner = AutoTuner(SLOSpec(p99_budget_ms=60,
                                  goodput_floor_rps=10),
                          admission=adm, now=FakeClock())
        tuner.tick()
        series = metrics_snapshot(autotune=tuner.stats())
        by_name = {s["name"]: s for s in series}
        assert by_name["nns_autotune_applied_total"]["samples"] == \
            [({}, 1.0)]
        dec = by_name["nns_autotune_decisions_total"]["samples"]
        assert ({"knob": "max_pending", "outcome": "applied"}, 1.0) in dec
        knob = dict((lbl["knob"], v) for lbl, v in
                    by_name["nns_autotune_knob"]["samples"])
        assert knob["max_pending"] == 32.0
        assert by_name["nns_autotune_slo_p99_budget_ms"]["samples"] == \
            [({}, 60.0)]
        assert by_name["nns_autotune_dry_run"]["samples"] == [({}, 0.0)]
        text = render_prometheus(series)
        assert "nns_autotune_decisions_total" in parse_prometheus(text)

    def test_metrics_snapshot_renders_before_any_decision(self):
        tuner = AutoTuner(SLOSpec(p99_budget_ms=60), now=FakeClock())
        series = metrics_snapshot(autotune=tuner.stats())
        by_name = {s["name"]: s for s in series}
        # label-less fallback keeps the family present (and parseable)
        assert by_name["nns_autotune_decisions_total"]["samples"] == \
            [({"knob": "none", "outcome": "none"}, 0.0)]
        render_prometheus(series)


# -- the live closed loop ----------------------------------------------------

class TestClosedLoopRamp:
    def test_tuned_ramp_zero_lost_with_audited_decisions(self):
        """Overload ramp against a real echo server with the tuner
        bound to the live admission queue: every request resolves, the
        books close exactly after every applied knob change, and every
        applied decision is in the audit ring."""
        r = run_autotune_ramp(ramp=(1.5, 2.5), n_per_step=60,
                              service_ms=4.0, static_max_pending=64,
                              tick_interval_s=0.05, cooldown_s=0.1,
                              seed=3)
        assert r["lost"] == 0 and not r["server_crashed"]
        assert r["conservation_final"]
        assert r["conservation_after_apply"], \
            "tuner never applied a decision — loop not closed"
        assert all(r["conservation_after_apply"])
        st = r["autotune"]
        assert st["applied_total"] >= 1
        assert r["admission"]["max_pending"] < 64   # it shrank the queue
        in_ring = [a for a in r["audit"] if a["outcome"] == "applied"]
        assert len(in_ring) == st["applied_total"]
        assert st["audit_dropped"] == 0

    def test_dry_run_ramp_changes_no_knob(self):
        """In-vivo dry-run proof: the same overload produces the same
        decision stream, but the live queue's max_pending never moves
        off the hand-set value."""
        r = run_autotune_ramp(ramp=(1.5, 2.5), n_per_step=60,
                              service_ms=4.0, static_max_pending=64,
                              tick_interval_s=0.05, cooldown_s=0.1,
                              dry_run=True, seed=3)
        assert r["lost"] == 0 and not r["server_crashed"]
        assert r["conservation_final"]
        st = r["autotune"]
        assert st["applied_total"] == 0
        assert st["dry_run_total"] >= 1
        assert r["admission"]["max_pending"] == 64
