"""Streaming-model scenarios: LSTM through a repo feedback loop, audio
windowing into a model — the reference's RNN/LSTM + audio test shapes
(tests/nnstreamer_repo_{rnn,lstm}, audio converter branch)."""

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.elements import (
    REPO,
    AppSrc,
    TensorDemux,
    TensorFilter,
    TensorMux,
    TensorRepoSink,
    TensorRepoSrc,
    TensorSink)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec


def test_lstm_zoo_model_shapes():
    from nnstreamer_tpu.models import lstm

    params = lstm.init_params(d_in=8, d_hidden=16)
    x = np.ones((1, 8), np.float32)
    h = np.zeros((1, 16), np.float32)
    c = np.zeros((1, 16), np.float32)
    y, h2, c2 = lstm.apply(params, x, h, c)
    assert y.shape == (1, 16) and h2.shape == (1, 16) and c2.shape == (1, 16)
    # state actually evolves
    assert float(np.abs(np.asarray(h2)).sum()) > 0


def test_lstm_repo_feedback_pipeline():
    """Full recurrent pipeline: state loops through the repo while the
    input stream drives steps — the reference's LSTM repo test shape."""
    REPO.reset()
    d_in, d_h, steps = 8, 16, 5
    state = TensorRepoSrc(name="state", slot=11,
                          dims=f"{d_h}:1,{d_h}:1", types="float32,float32",
                          count=steps + 1)
    xs = AppSrc(spec=TensorsSpec.of(TensorInfo((1, d_in), DType.FLOAT32)),
                name="xs")
    mux = TensorMux(name="m", sync_mode="nosync")
    f = TensorFilter(
        name="f", framework="xla",
        model=f"zoo://lstm?d_in={d_in}&d_hidden={d_h}")
    demux = TensorDemux(name="d", tensorpick="0,1+2")
    sink = TensorSink(name="s")
    back = TensorRepoSink(name="back", slot=11)
    pipe = nns.Pipeline()
    for e in (state, xs, mux, f, demux, sink, back):
        pipe.add(e)
    pipe.link(xs, mux, 0, 0)     # pad 0: x
    pipe.link(state, mux, 0, 1)  # pad 1: (h, c)
    pipe.link(mux, f)
    pipe.link(f, demux)
    pipe.link(demux, sink, 0, 0)   # y downstream
    pipe.link(demux, back, 1, 0)   # (h', c') feed back
    runner = nns.PipelineRunner(pipe).start()
    rng = np.random.default_rng(0)
    for i in range(steps):
        xs.push(TensorBuffer.of(
            rng.normal(size=(1, d_in)).astype(np.float32), pts=i))
    xs.end()
    runner.wait(120)
    ys = [r.tensors[0] for r in sink.results]
    assert len(ys) == steps
    # recurrence: same-input steps differ because state evolves
    assert not np.allclose(ys[0], ys[-1])


def test_lstm_input_combination_ordering():
    """pipeline LSTM output matches the direct apply() ground truth."""
    from nnstreamer_tpu.models import lstm

    d_in, d_h = 4, 8
    params_ref = lstm.init_params(d_in=d_in, d_hidden=d_h)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(1, d_in)).astype(np.float32)
    h = np.zeros((1, d_h), np.float32)
    c = np.zeros((1, d_h), np.float32)
    y_ref, _, _ = lstm.apply(params_ref, x, h, c)

    REPO.reset()
    state = TensorRepoSrc(name="state", slot=12,
                          dims=f"{d_h}:1,{d_h}:1", types="float32,float32",
                          count=2)
    xs = AppSrc(spec=TensorsSpec.of(TensorInfo((1, d_in), DType.FLOAT32)),
                name="xs")
    mux = TensorMux(name="m", sync_mode="nosync")
    f = TensorFilter(name="f", framework="xla",
                     model=f"zoo://lstm?d_in={d_in}&d_hidden={d_h}")
    demux = TensorDemux(name="d", tensorpick="0,1+2")
    sink = TensorSink(name="s")
    back = TensorRepoSink(name="back", slot=12)
    pipe = nns.Pipeline()
    for e in (state, xs, mux, f, demux, sink, back):
        pipe.add(e)
    pipe.link(xs, mux, 0, 0)
    pipe.link(state, mux, 0, 1)
    pipe.link(mux, f)
    pipe.link(f, demux)
    pipe.link(demux, sink, 0, 0)
    pipe.link(demux, back, 1, 0)
    runner = nns.PipelineRunner(pipe).start()
    xs.push(TensorBuffer.of(x, pts=0))
    xs.end()
    runner.wait(120)
    np.testing.assert_allclose(np.asarray(sink.results[0].tensors[0]),
                               np.asarray(y_ref), rtol=1e-5, atol=1e-5)


def test_audio_pipeline_windowed():
    """audiotestsrc → converter (sample adapter) → aggregator window."""
    pipe = nns.parse_launch(
        "audiotestsrc num-buffers=4 samples-per-buffer=100 wave=sine ! "
        "tensor_converter frames-per-tensor=160 ! "
        "tensor_sink name=s")
    nns.run_pipeline(pipe, timeout=30)
    res = pipe.get("s").results
    # 400 samples in → 2 complete 160-sample tensors (80 dropped at EOS)
    assert len(res) == 2
    assert res[0].tensors[0].shape == (160, 1)
    assert res[0].tensors[0].dtype == np.int16


# -- transformer: KV-cache streaming decode ----------------------------------

def test_transformer_step_matches_full_sequence():
    """Streaming apply_step over a token sequence must produce the same
    logits as the full-sequence forward (KV-cache correctness)."""
    import jax.numpy as jnp

    from nnstreamer_tpu.models import transformer as T

    d, H, L, V, S = 32, 4, 2, 64, 9
    params = T.init_params(d_model=d, n_heads=H, n_layers=L, vocab=V)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, V, (1, S)).astype(np.int32)

    full = np.asarray(T.apply_seq(params, jnp.asarray(ids), n_heads=H))

    kc, vc, pos = T.init_cache(batch=1, max_len=16, d_model=d,
                               n_heads=H, n_layers=L)
    step_logits = []
    for t in range(S):
        logits, kc, vc, pos = T.apply_step(
            params, jnp.asarray(ids[:, t:t + 1]), kc, vc, pos, n_heads=H)
        step_logits.append(np.asarray(logits))
    np.testing.assert_allclose(
        np.stack(step_logits, axis=1), full, rtol=2e-4, atol=2e-4)


def test_transformer_streaming_pipeline_repo_loop():
    """Token-by-token decode as a pipeline: KV cache + position loop
    through tensor_repo while tokens stream in (LSTM test shape scaled
    to the transformer's 3-tensor state)."""
    REPO.reset()
    d, H, L, V, steps, max_len = 32, 4, 2, 64, 5, 16
    hd = d // H
    cache_dims = f"{hd}:{H}:{max_len}:1:{L}"
    state = TensorRepoSrc(
        name="state", slot=21,
        dims=f"{cache_dims},{cache_dims},1",
        types="float32,float32,int32", count=steps + 1)
    xs = AppSrc(spec=TensorsSpec.of(TensorInfo((1, 1), DType.INT32)),
                name="xs")
    mux = TensorMux(name="m", sync_mode="nosync")
    f = TensorFilter(
        name="f", framework="xla",
        model=f"zoo://transformer?d_model={d}&n_heads={H}&n_layers={L}"
              f"&vocab={V}&max_len={max_len}")
    demux = TensorDemux(name="d", tensorpick="0,1+2+3")
    sink = TensorSink(name="s")
    back = TensorRepoSink(name="back", slot=21)
    pipe = nns.Pipeline()
    for e in (state, xs, mux, f, demux, sink, back):
        pipe.add(e)
    pipe.link(xs, mux, 0, 0)
    pipe.link(state, mux, 0, 1)
    pipe.link(mux, f)
    pipe.link(f, demux)
    pipe.link(demux, sink, 0, 0)
    pipe.link(demux, back, 1, 0)
    runner = nns.PipelineRunner(pipe).start()
    rng = np.random.default_rng(3)
    toks = rng.integers(0, V, (steps, 1, 1)).astype(np.int32)
    for i in range(steps):
        xs.push(TensorBuffer.of(toks[i], pts=i))
    xs.end()
    runner.wait(180)
    logits = [r.tensors[0] for r in sink.results]
    assert len(logits) == steps
    assert all(lg.shape == (1, V) for lg in logits)

    # golden: the same tokens through direct apply_step
    import jax.numpy as jnp

    from nnstreamer_tpu.models import transformer as T
    from nnstreamer_tpu.models.zoo import build_model

    bundle = build_model(
        f"transformer?d_model={d}&n_heads={H}&n_layers={L}"
        f"&vocab={V}&max_len={max_len}")
    kc, vc, pos = T.init_cache(batch=1, max_len=max_len, d_model=d,
                               n_heads=H, n_layers=L)
    for i in range(steps):
        want, kc, vc, pos = bundle.fn(bundle.params, jnp.asarray(toks[i]),
                                      kc, vc, pos)
        np.testing.assert_allclose(np.asarray(logits[i]), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


def test_transformer_cache_ring_wraps_to_sliding_window():
    """Past max_len tokens the KV ring wraps: decoding continues with
    sliding-window attention over the last max_len tokens (no silent
    garbage, no unbounded cache)."""
    import jax.numpy as jnp

    from nnstreamer_tpu.models import transformer as T

    d, H, L, V, max_len, S = 32, 4, 2, 64, 4, 7
    params = T.init_params(d_model=d, n_heads=H, n_layers=L, vocab=V)
    rng = np.random.default_rng(5)
    ids = rng.integers(0, V, (1, S)).astype(np.int32)

    kc, vc, pos = T.init_cache(batch=1, max_len=max_len, d_model=d,
                               n_heads=H, n_layers=L)
    snapshots = []
    for t in range(S):
        logits, kc, vc, pos = T.apply_step(
            params, jnp.asarray(ids[:, t:t + 1]), kc, vc, pos, n_heads=H)
        assert np.isfinite(np.asarray(logits)).all(), f"step {t}"
        snapshots.append(np.asarray(kc[0, 0, 0, 0]))   # layer0 slot 0
    # slot 0 is overwritten when the ring wraps at step max_len
    assert np.allclose(snapshots[0], snapshots[max_len - 1])
    assert not np.allclose(snapshots[max_len - 1], snapshots[max_len])
    assert int(np.asarray(pos)[0]) == S   # position keeps counting


def test_transformer_gqa_step_matches_seq_and_narrows_cache():
    """Grouped-query attention: KV cache shrinks by the group factor and
    streaming decode still matches the full-sequence forward."""
    import jax.numpy as jnp

    from nnstreamer_tpu.models import transformer as T

    d, H, KV, L, V, S = 32, 4, 2, 2, 64, 8
    params = T.init_params(d_model=d, n_heads=H, n_layers=L, vocab=V,
                           n_kv_heads=KV)
    ids = np.random.default_rng(1).integers(0, V, (1, S)).astype(np.int32)
    full = np.asarray(T.apply_seq(params, jnp.asarray(ids), n_heads=H))
    kc, vc, pos = T.init_cache(batch=1, max_len=16, d_model=d, n_heads=H,
                               n_layers=L, n_kv_heads=KV)
    assert kc.shape[3] == KV               # cache is group-narrow
    got = []
    for t in range(S):
        lg, kc, vc, pos = T.apply_step(params, jnp.asarray(ids[:, t:t+1]),
                                       kc, vc, pos, n_heads=H)
        got.append(np.asarray(lg))
    np.testing.assert_allclose(np.stack(got, 1), full, rtol=2e-4, atol=2e-4)


def test_transformer_generate_greedy_deterministic():
    from nnstreamer_tpu.models import transformer as T

    params = T.init_params(d_model=32, n_heads=4, n_layers=2, vocab=64)
    prompt = np.array([[1, 2, 3]], np.int32)
    import jax.numpy as jnp

    a = T.generate(params, jnp.asarray(prompt), 6, max_len=32)
    b = T.generate(params, jnp.asarray(prompt), 6, max_len=32)
    assert a.shape == (1, 9)
    np.testing.assert_array_equal(a, b)      # greedy = deterministic
    np.testing.assert_array_equal(a[:, :3], prompt)

    # sampled path runs and respects top-k shape contract
    c = T.generate(params, jnp.asarray(prompt), 4, max_len=32,
                   temperature=0.8, top_k=5, seed=7)
    assert c.shape == (1, 7)


def test_audio_classifier_end_to_end_pipeline():
    """Full audio path: generator → sample adapter → typecast → conv1d
    classifier — the keyword-spotting pipeline shape."""
    pipe = nns.parse_launch(
        "audiotestsrc num-buffers=8 samples-per-buffer=256 wave=sine "
        "freq=880 ! tensor_converter frames-per-tensor=1024 ! "
        "tensor_transform mode=typecast option=float32 ! "
        "tensor_filter model=zoo://audio_classifier?window=1024"
        "&num_classes=12 ! tensor_sink name=s")
    nns.run_pipeline(pipe, timeout=60)
    res = pipe.get("s").results
    assert len(res) == 2            # 2048 samples → 2 windows
    for r in res:
        lg = np.asarray(r.tensors[0])
        assert lg.shape == (12,) and np.isfinite(lg).all()


def test_audio_classifier_trains():
    """loss_fn works with the sharded train step (audio is trainable)."""
    import jax.numpy as jnp
    import optax

    from nnstreamer_tpu.models import audio_classifier as A
    from nnstreamer_tpu.parallel.train import (init_state, make_train_step)

    params = A.init_params(channels=8, num_classes=4)
    opt = optax.sgd(0.05)
    step = make_train_step(
        lambda p, x, y: A.loss_fn(p, x, y), opt, donate=False)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 256, 1)).astype(np.float32)
    y = (np.arange(8) % 4).astype(np.int32)
    state = init_state(params, opt)
    losses = []
    for _ in range(8):
        state, loss = step(state, jnp.asarray(x), jnp.asarray(y))
        losses.append(float(loss))
    assert losses[-1] < losses[0]    # memorizes the fixed batch


def test_audio_classifier_tensor_trainer_pipeline():
    """tensor_trainer accepts the audio model (zoo pass-through kwargs)."""
    from nnstreamer_tpu.elements import AppSrc, TensorSink
    from nnstreamer_tpu.trainer.element import TensorTrainer
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    src = AppSrc(spec=TensorsSpec.of(
        TensorInfo((4, 256, 1), DType.FLOAT32),
        TensorInfo((4,), DType.INT32)), name="src")
    t = TensorTrainer(name="t", model="zoo://audio_classifier?num_classes=4",
                      optimizer="sgd:0.05")
    sink = TensorSink(name="s")
    pipe = nns.Pipeline()
    for e in (src, t, sink):
        pipe.add(e)
    pipe.link(src, t)
    pipe.link(t, sink)
    runner = nns.PipelineRunner(pipe).start()
    rng = np.random.default_rng(0)
    for i in range(3):
        src.push(TensorBuffer.of(
            rng.normal(size=(4, 256, 1)).astype(np.float32),
            (np.arange(4) % 4).astype(np.int32), pts=i))
    src.end()
    runner.wait(120)
    assert len(pipe.get("s").results) == 3


# -- semantic goldens (VERDICT r2 weak #7): independent reference + sampling

def _numpy_transformer(params, ids, n_heads):
    """Pure-numpy re-implementation of the decoder math (RMSNorm, RoPE,
    GQA, causal softmax attention, SwiGLU) written independently of the
    jax code path — the in-repo golden for apply_seq/generate."""
    p = {k: np.asarray(v) if not isinstance(v, (list, dict)) else v
         for k, v in params.items()}
    x = np.asarray(p["embed"])[np.asarray(ids)]          # (B, S, D)
    b, s, d = x.shape
    hd = d // n_heads
    pos = np.arange(s)

    def rms(v, w):
        return v / np.sqrt((v ** 2).mean(-1, keepdims=True) + 1e-6) * w

    def rope_np(t):
        half = t.shape[-1] // 2
        freqs = 1.0 / (10000.0 ** (np.arange(half) / half))
        ang = pos[:, None] * freqs[None, :]
        cos, sin = np.cos(ang)[None, :, None, :], np.sin(ang)[None, :, None, :]
        t1, t2 = t[..., :half], t[..., half:]
        return np.concatenate([t1 * cos - t2 * sin,
                               t1 * sin + t2 * cos], -1)

    def silu(v):
        return v / (1.0 + np.exp(-v))

    for blk in params["blocks"]:
        wqkv = np.asarray(blk["wqkv"])
        kv_dim = (wqkv.shape[1] - d) // 2
        n_kv = kv_dim // hd
        h = rms(x, np.asarray(blk["ln1"]))
        qkv = h @ wqkv
        q = rope_np(qkv[..., :d].reshape(b, s, n_heads, hd))
        k = rope_np(qkv[..., d:d + kv_dim].reshape(b, s, n_kv, hd))
        v = qkv[..., d + kv_dim:].reshape(b, s, n_kv, hd)
        if n_kv != n_heads:
            k = np.repeat(k, n_heads // n_kv, axis=2)
            v = np.repeat(v, n_heads // n_kv, axis=2)
        scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
        mask = np.tril(np.ones((s, s), bool))
        scores = np.where(mask[None, None], scores, -1e30)
        w = np.exp(scores - scores.max(-1, keepdims=True))
        w /= w.sum(-1, keepdims=True)
        attn = np.einsum("bhqk,bkhd->bqhd", w, v).reshape(b, s, d)
        x = x + attn @ np.asarray(blk["wo"])
        h = rms(x, np.asarray(blk["ln2"]))
        gate_up = h @ np.asarray(blk["wi"])
        gate, up = np.split(gate_up, 2, axis=-1)
        x = x + (silu(gate) * up) @ np.asarray(blk["wd"])
    x = rms(x, np.asarray(p["ln_f"]))
    return x @ np.asarray(p["head"])


@pytest.mark.parametrize("n_kv", [None, 2])
def test_transformer_matches_independent_numpy_reference(n_kv):
    """apply_seq (incl. the GQA path) against a from-scratch numpy
    implementation of the same architecture — a true semantic golden,
    not self-consistency."""
    import jax

    from nnstreamer_tpu.models import transformer as T

    params = T.init_params(d_model=32, n_heads=4, n_layers=2, vocab=50,
                           n_kv_heads=n_kv, seed=3)
    ids = np.array([[7, 3, 11, 42, 0, 9]], np.int32)
    ours = np.asarray(jax.jit(
        lambda p, i: T.apply_seq(p, i, n_heads=4, attn="xla"))(params, ids))
    ref = _numpy_transformer(params, ids, n_heads=4)
    np.testing.assert_allclose(ours, ref, atol=2e-4)
    # generate() greedy must follow the numpy reference's argmax chain
    out = T.generate(params, ids, 4, n_heads=4, max_len=32)
    cur = ids
    for _ in range(4):
        nxt = _numpy_transformer(params, cur, 4)[:, -1].argmax(-1)
        cur = np.concatenate([cur, nxt[:, None].astype(np.int32)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), cur)


def test_generate_sampling_distribution_and_top_k():
    """The sampling path (temperature>0) draws from the softmax
    distribution and top_k truncates it — checked statistically against
    the model's own final-token distribution."""
    from nnstreamer_tpu.models import transformer as T

    params = T.init_params(d_model=16, n_heads=2, n_layers=1, vocab=12,
                           seed=1)
    prompt = np.array([[5]], np.int32)
    logits = _numpy_transformer(params, prompt, 2)[0, -1]
    probs = np.exp(logits - logits.max())
    probs /= probs.sum()
    draws = []
    for s in range(300):
        out = T.generate(params, prompt, 1, n_heads=2, max_len=8,
                         temperature=1.0, seed=s)
        draws.append(int(np.asarray(out)[0, -1]))
    counts = np.bincount(draws, minlength=12) / len(draws)
    # loose statistical agreement (300 draws): total variation < 0.2
    assert 0.5 * np.abs(counts - probs).sum() < 0.2, (counts, probs)
    # top_k=2 restricts draws to the two most probable tokens
    top2 = set(np.argsort(probs)[-2:].tolist())
    for s in range(40):
        out = T.generate(params, prompt, 1, n_heads=2, max_len=8,
                         temperature=1.0, top_k=2, seed=s)
        assert int(np.asarray(out)[0, -1]) in top2


def test_transformer_bf16_cache_matches_f32_cache():
    """Cache storage dtype is configurable (decode is HBM-bound by the
    cache sweep; bf16 storage ~halves the bytes). bf16-cache decode
    must track the f32-cache decode closely — the softmax/accumulator
    math stays f32 on read."""
    import jax.numpy as jnp

    from nnstreamer_tpu.models import transformer as T

    d, H, L, V, S = 32, 4, 2, 64, 9
    params = T.init_params(d_model=d, n_heads=H, n_layers=L, vocab=V)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, V, (1, S)).astype(np.int32)

    outs = {}
    for dt in (jnp.float32, jnp.bfloat16):
        kc, vc, pos = T.init_cache(batch=1, max_len=16, d_model=d,
                                   n_heads=H, n_layers=L, dtype=dt)
        assert kc.dtype == dt and vc.dtype == dt
        logits = []
        for t in range(S):
            lg, kc, vc, pos = T.apply_step(
                params, jnp.asarray(ids[:, t:t + 1]), kc, vc, pos,
                n_heads=H)
            assert kc.dtype == dt      # storage dtype survives the step
            logits.append(np.asarray(lg))
        outs[dt] = np.stack(logits, axis=1)
    f32, bf16 = outs[jnp.float32], outs[jnp.bfloat16]
    np.testing.assert_allclose(bf16, f32, rtol=0.05, atol=0.05)
    # same argmax trajectory — bf16 storage must not flip decisions
    np.testing.assert_array_equal(bf16.argmax(-1), f32.argmax(-1))
