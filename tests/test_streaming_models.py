"""Streaming-model scenarios: LSTM through a repo feedback loop, audio
windowing into a model — the reference's RNN/LSTM + audio test shapes
(tests/nnstreamer_repo_{rnn,lstm}, audio converter branch)."""

import numpy as np

import nnstreamer_tpu as nns
from nnstreamer_tpu.elements import (
    REPO,
    AppSrc,
    TensorDemux,
    TensorFilter,
    TensorMux,
    TensorRepoSink,
    TensorRepoSrc,
    TensorSink)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec


def test_lstm_zoo_model_shapes():
    from nnstreamer_tpu.models import lstm

    params = lstm.init_params(d_in=8, d_hidden=16)
    x = np.ones((1, 8), np.float32)
    h = np.zeros((1, 16), np.float32)
    c = np.zeros((1, 16), np.float32)
    y, h2, c2 = lstm.apply(params, x, h, c)
    assert y.shape == (1, 16) and h2.shape == (1, 16) and c2.shape == (1, 16)
    # state actually evolves
    assert float(np.abs(np.asarray(h2)).sum()) > 0


def test_lstm_repo_feedback_pipeline():
    """Full recurrent pipeline: state loops through the repo while the
    input stream drives steps — the reference's LSTM repo test shape."""
    REPO.reset()
    d_in, d_h, steps = 8, 16, 5
    state = TensorRepoSrc(name="state", slot=11,
                          dims=f"{d_h}:1,{d_h}:1", types="float32,float32",
                          count=steps + 1)
    xs = AppSrc(spec=TensorsSpec.of(TensorInfo((1, d_in), DType.FLOAT32)),
                name="xs")
    mux = TensorMux(name="m", sync_mode="nosync")
    f = TensorFilter(
        name="f", framework="xla",
        model=f"zoo://lstm?d_in={d_in}&d_hidden={d_h}")
    demux = TensorDemux(name="d", tensorpick="0,1+2")
    sink = TensorSink(name="s")
    back = TensorRepoSink(name="back", slot=11)
    pipe = nns.Pipeline()
    for e in (state, xs, mux, f, demux, sink, back):
        pipe.add(e)
    pipe.link(xs, mux, 0, 0)     # pad 0: x
    pipe.link(state, mux, 0, 1)  # pad 1: (h, c)
    pipe.link(mux, f)
    pipe.link(f, demux)
    pipe.link(demux, sink, 0, 0)   # y downstream
    pipe.link(demux, back, 1, 0)   # (h', c') feed back
    runner = nns.PipelineRunner(pipe).start()
    rng = np.random.default_rng(0)
    for i in range(steps):
        xs.push(TensorBuffer.of(
            rng.normal(size=(1, d_in)).astype(np.float32), pts=i))
    xs.end()
    runner.wait(120)
    ys = [r.tensors[0] for r in sink.results]
    assert len(ys) == steps
    # recurrence: same-input steps differ because state evolves
    assert not np.allclose(ys[0], ys[-1])


def test_lstm_input_combination_ordering():
    """pipeline LSTM output matches the direct apply() ground truth."""
    from nnstreamer_tpu.models import lstm

    d_in, d_h = 4, 8
    params_ref = lstm.init_params(d_in=d_in, d_hidden=d_h)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(1, d_in)).astype(np.float32)
    h = np.zeros((1, d_h), np.float32)
    c = np.zeros((1, d_h), np.float32)
    y_ref, _, _ = lstm.apply(params_ref, x, h, c)

    REPO.reset()
    state = TensorRepoSrc(name="state", slot=12,
                          dims=f"{d_h}:1,{d_h}:1", types="float32,float32",
                          count=2)
    xs = AppSrc(spec=TensorsSpec.of(TensorInfo((1, d_in), DType.FLOAT32)),
                name="xs")
    mux = TensorMux(name="m", sync_mode="nosync")
    f = TensorFilter(name="f", framework="xla",
                     model=f"zoo://lstm?d_in={d_in}&d_hidden={d_h}")
    demux = TensorDemux(name="d", tensorpick="0,1+2")
    sink = TensorSink(name="s")
    back = TensorRepoSink(name="back", slot=12)
    pipe = nns.Pipeline()
    for e in (state, xs, mux, f, demux, sink, back):
        pipe.add(e)
    pipe.link(xs, mux, 0, 0)
    pipe.link(state, mux, 0, 1)
    pipe.link(mux, f)
    pipe.link(f, demux)
    pipe.link(demux, sink, 0, 0)
    pipe.link(demux, back, 1, 0)
    runner = nns.PipelineRunner(pipe).start()
    xs.push(TensorBuffer.of(x, pts=0))
    xs.end()
    runner.wait(120)
    np.testing.assert_allclose(np.asarray(sink.results[0].tensors[0]),
                               np.asarray(y_ref), rtol=1e-5, atol=1e-5)


def test_audio_pipeline_windowed():
    """audiotestsrc → converter (sample adapter) → aggregator window."""
    pipe = nns.parse_launch(
        "audiotestsrc num-buffers=4 samples-per-buffer=100 wave=sine ! "
        "tensor_converter frames-per-tensor=160 ! "
        "tensor_sink name=s")
    nns.run_pipeline(pipe, timeout=30)
    res = pipe.get("s").results
    # 400 samples in → 2 complete 160-sample tensors (80 dropped at EOS)
    assert len(res) == 2
    assert res[0].tensors[0].shape == (160, 1)
    assert res[0].tensors[0].dtype == np.int16
