"""Sharded serving (serving/sharding.py): tensor/sequence parallelism
in the serving path for models bigger than one chip.

Everything runs on the 8-device emulated host mesh (markers `sharded`
+ `multichip`, fixture `eight_cpu_devices`). The acceptance checks:

- **bit-parity**: `shards=N` (N in {2, 4, 8}) is bit-identical to
  `shards=1` for the dense filter path AND paged LLM decode — the
  canonical-blocking construction makes numerics a function of the
  fixed block count, never the shard count;
- **ring prefill**: long prompts cut over to sequence-parallel ring
  attention (allclose vs blocked — a different attention order by
  design); decode from a ring-filled cache stays bit-exact;
- **group fencing**: fencing ONE member chip fences the whole shard
  group, chips land fenced in the lease ledger, and Σ group invokes ==
  frames replied holds exactly through the mid-stream fence;
- **epoch-atomic group swap**: one store update pre-warms the new
  version on EVERY shard group before anything flips — zero post-flip
  recompiles, one adopted epoch across groups;
- **typed exclusions**: chunked prefill, non-xla frameworks, explicit
  I/O overrides and W8A8 params are refused with typed errors, never
  silently served wrong;
- the `shards=` / `ring_prefill_min=` element properties, the
  TP-vs-segmentation planner (`segment_plan_tp`), and the nns_shard_*
  metric family fed from REAL ShardedReplicaSet stats.
"""

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu import PipelineRunner, TensorBuffer, parse_launch
from nnstreamer_tpu.backends.llm_exec import PagedLLMExecutor
from nnstreamer_tpu.backends.xla import ModelBundle
from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.elements import AppSrc, TensorLLM, TensorSink
from nnstreamer_tpu.models.transformer import init_params
from nnstreamer_tpu.serving import compile_cache
from nnstreamer_tpu.serving.metrics import (
    metrics_snapshot, parse_prometheus, render_prometheus)
from nnstreamer_tpu.serving.placement import (
    ChipLeaseTable, apply_plan, plan_from_tracer, segment_plan_tp)
from nnstreamer_tpu.serving.sharding import (
    SUPPORTED_SHARDS, ShardedReplicaSet, validate_shards)
from nnstreamer_tpu.serving.store import get_store, reset_store
from nnstreamer_tpu.tensor.info import TensorFormat, TensorsSpec

pytestmark = [pytest.mark.sharded, pytest.mark.multichip]

#: the %8-divisible geometry the canonical blocking needs (d_model,
#: head count and vocab all split into FIXED_BLOCKS=8 blocks)
GEOM = dict(d_model=64, n_heads=8, n_layers=2, vocab=256)


@pytest.fixture(autouse=True)
def _fresh_store():
    store = reset_store()
    compile_cache.reset()
    yield store
    reset_store()
    compile_cache.reset()


@pytest.fixture(scope="module")
def llm_params():
    return init_params(**GEOM)


def _bundle(seed=3, dim=16, name="sh_mlp"):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(dim, dim)).astype(np.float32)
    return ModelBundle(fn=lambda p, x: (x @ p["w"],), params={"w": w},
                       name=name), dim


# -- dense path ---------------------------------------------------------------

class TestDenseParity:
    def test_validate_shards(self, eight_cpu_devices):
        assert SUPPORTED_SHARDS == (1, 2, 4, 8)
        for n in SUPPORTED_SHARDS:
            assert validate_shards(n) == n
        with pytest.raises(BackendError):
            validate_shards(3)
        with pytest.raises(BackendError):
            validate_shards(16)

    def test_bit_parity_across_shard_widths(self, eight_cpu_devices):
        """The dense acceptance check: one group of 1/2/4/8 chips
        produces bit-identical outputs — the shard_map body gathers
        each leaf on use and applies the UNMODIFIED model function."""
        bundle, dim = _bundle()
        x = np.linspace(-1, 1, 4 * dim,
                        dtype=np.float32).reshape(4, dim)
        ref = None
        for n in (1, 2, 4, 8):
            rs = ShardedReplicaSet.open_sharded(
                bundle, shards=n, groups=1, name=f"dp{n}")
            try:
                outs = [rs.invoke((x,)) for _ in range(3)]
            finally:
                rs.close()
            if ref is None:
                ref = np.asarray(outs[0][0])
            for o in outs:
                np.testing.assert_array_equal(np.asarray(o[0]), ref)

    def test_groups_compose_and_route(self, eight_cpu_devices):
        """2 groups x 4 chips: both groups serve, every output is
        identical, and the stats rows carry group/devices/shards."""
        bundle, dim = _bundle()
        x = np.ones((2, dim), np.float32)
        rs = ShardedReplicaSet.open_sharded(bundle, shards=4, groups=2,
                                            name="gg")
        try:
            outs = [rs.invoke((x,)) for _ in range(6)]
            st = rs.stats()
        finally:
            rs.close()
        ref = np.asarray(outs[0][0])
        for o in outs:
            np.testing.assert_array_equal(np.asarray(o[0]), ref)
        rows = st["replicas"]
        assert [r["group"] for r in rows] == [0, 1]
        assert rows[0]["devices"] == [0, 1, 2, 3]
        assert rows[1]["devices"] == [4, 5, 6, 7]
        assert st["group_size"] == 4
        assert sum(r["invokes"] for r in rows) == 6
        assert st["leases"] == {"free": 0, "leased": 8, "fenced": 0}

    def test_oversubscription_is_typed(self, eight_cpu_devices):
        bundle, _ = _bundle()
        with pytest.raises(BackendError, match="devices"):
            ShardedReplicaSet.open_sharded(bundle, shards=8, groups=2,
                                           name="over")


class TestGroupFence:
    def test_member_fence_fences_group_conserves(
            self, eight_cpu_devices):
        """Fencing ONE member chip takes the WHOLE group out: its chips
        go fenced in the lease ledger, traffic reroutes to survivors,
        and Σ group invokes == frames stays exact through the fence."""
        bundle, dim = _bundle()
        x = np.ones((2, dim), np.float32)
        rs = ShardedReplicaSet.open_sharded(bundle, shards=2, groups=4,
                                            name="fg")
        try:
            for _ in range(8):
                rs.invoke((x,))
            # chip 5 belongs to group 2 (groups lease chips in order)
            assert rs.group_of(5) == 2
            assert rs.fence_device(5, "drill")
            for _ in range(8):
                rs.invoke((x,))
            st = rs.stats()
        finally:
            rs.close()
        rows = st["replicas"]
        assert sum(r["invokes"] for r in rows) == 16
        dead = [r for r in rows if r["state"] == "fenced"]
        assert [r["group"] for r in dead] == [2]
        assert st["leases"]["fenced"] == 2      # both member chips
        assert st["fences"] == 1

    def test_fence_unknown_chip_is_noop(self, eight_cpu_devices):
        bundle, _ = _bundle()
        rs = ShardedReplicaSet.open_sharded(bundle, shards=4, groups=1,
                                            name="nf")
        try:
            assert rs.group_of(7) is None       # chips 4..7 unleased
            assert rs.fence_device(7) is False
        finally:
            rs.close()

    def test_leases_release_on_close(self, eight_cpu_devices):
        bundle, _ = _bundle()
        leases = ChipLeaseTable(range(8))
        rs = ShardedReplicaSet.open_sharded(bundle, shards=2, groups=2,
                                            leases=leases, name="rl")
        assert leases.snapshot()["counts"]["leased"] == 4
        rs.close()
        for g in range(2):
            leases.release(f"rl/g{g}")
        assert leases.snapshot()["counts"]["free"] == 8


class TestGroupSwap:
    def test_swap_is_epoch_atomic_across_groups(
            self, eight_cpu_devices):
        """One store update = the all-or-none broadcast: every shard
        group pre-warms v2 before the flip, every group adopts the same
        epoch, and post-flip traffic recompiles NOTHING."""
        store = get_store()
        store.register("shsw", lambda x: (x * 2.0,))
        store.register("shsw", lambda x: (x + 100.0,))   # v2
        x = np.linspace(-1, 1, 32, np.float32).reshape(2, 16)
        rs = ShardedReplicaSet.open_sharded("store://shsw", shards=2,
                                            groups=2, name="sw")
        try:
            for _ in range(4):
                (out,) = rs.invoke((x,))
            np.testing.assert_allclose(out, x * 2.0)  # v1 until swap
            rep = rs.swap(2)
            assert rep["handles"] == 2              # both groups warmed
            counts = rs.compile_counts()
            for _ in range(4):
                (out,) = rs.invoke((x,))
            np.testing.assert_allclose(out, x + 100.0)
            assert rs.compile_counts() == counts, "post-flip recompile"
            assert len(set(rs.adopted_epochs())) == 1
        finally:
            rs.close()

    def test_pinned_open_serves_that_version(self, eight_cpu_devices):
        store = get_store()
        store.register("shpin", lambda x: (x * 2.0,))
        store.register("shpin", lambda x: (x + 100.0,))
        x = np.ones((2, 16), np.float32)
        rs = ShardedReplicaSet.open_sharded("store://shpin@1", shards=2,
                                            groups=1, name="pin")
        try:
            (out,) = rs.invoke((x,))
            np.testing.assert_allclose(out, x * 2.0)
        finally:
            rs.close()


# -- paged LLM path -----------------------------------------------------------

def _exec(params, shards, ring_min=0, name=None):
    return PagedLLMExecutor(dict(params), n_heads=8, block_size=8,
                            num_blocks=16, max_len=64, shards=shards,
                            ring_prefill_min=ring_min,
                            name=name or f"tp{shards}")


def _serve(ex, prompt, steps=4):
    blocks = ex.cache.allocator.alloc(ex.cache.blocks_for(len(prompt)))
    lg = ex.prefill(prompt, blocks)
    outs = [np.asarray(lg)]
    tok, pos = int(np.argmax(lg)), len(prompt)
    for _ in range(steps):
        dl = ex.decode([tok], [blocks], [pos])
        outs.append(np.asarray(dl[0]))
        tok, pos = int(np.argmax(dl[0])), pos + 1
    return outs


class TestPagedLLMParity:
    def test_decode_bit_parity_across_widths(self, eight_cpu_devices,
                                             llm_params):
        """The LLM acceptance check: blocked prefill + paged decode at
        shards 2/4/8 is bit-identical to shards=1 (fixed 8-block
        combine order — numerics never see the shard count)."""
        prompt = np.random.default_rng(1).integers(
            1, 256, size=11).astype(np.int32)
        ref = None
        for n in (1, 2, 4, 8):
            ex = _exec(llm_params, n)
            try:
                outs = _serve(ex, prompt)
                st = ex.stats()
            finally:
                ex.close()
            if ref is None:
                ref = outs
                continue
            for a, b in zip(outs, ref):
                np.testing.assert_array_equal(a, b)
            assert st["shards"] == n

    def test_sharded_jit_namespace_is_tp_keyed(self, eight_cpu_devices,
                                               llm_params):
        ex = _exec(llm_params, 2)
        try:
            prompt = np.arange(1, 10, dtype=np.int32)
            _serve(ex, prompt, steps=1)
            assert ex._ns() == ("tp", 2, 0)
            kinds = {k[1] for k in ex._jits}
            assert kinds == {"prefill", "decode"}
            assert all(k[0] == ("tp", 2, 0) for k in ex._jits)
        finally:
            ex.close()

    def test_ring_prefill_cutover(self, eight_cpu_devices, llm_params):
        """Prompts >= ring_prefill_min go through the ring: allclose to
        the blocked prefill (different attention order), decode from
        the ring-filled cache bit-exact, bucket noted as llmr."""
        prompt = np.random.default_rng(7).integers(
            1, 256, size=24).astype(np.int32)
        ex_r = _exec(llm_params, 2, ring_min=16, name="ring")
        ex_b = _exec(llm_params, 2, name="ringref")
        try:
            ring = _serve(ex_r, prompt, steps=2)
            blocked = _serve(ex_b, prompt, steps=2)
            kinds = {k[1] for k in ex_r._jits}
            ref_kinds = {k[1] for k in ex_b._jits}
        finally:
            ex_r.close()
            ex_b.close()
        assert "ring" in kinds and "prefill" not in kinds
        assert ref_kinds == {"prefill", "decode"}
        np.testing.assert_allclose(ring[0], blocked[0],
                                   rtol=1e-4, atol=1e-4)
        # decode-after: same tokens either way (argmax is stable here)
        for a, b in zip(ring[1:], blocked[1:]):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)

    def test_short_prompt_stays_blocked(self, eight_cpu_devices,
                                        llm_params):
        ex = _exec(llm_params, 2, ring_min=16)
        try:
            _serve(ex, np.arange(1, 9, dtype=np.int32), steps=1)
            assert ex.stats()["kernel_invokes"].get("ring", 0) == 0
        finally:
            ex.close()


class TestShardedExclusions:
    def test_chunked_prefill_refused(self, eight_cpu_devices,
                                     llm_params):
        ex = _exec(llm_params, 2)
        try:
            with pytest.raises(BackendError, match="ring"):
                ex.prefill_chunk(np.arange(1, 9, dtype=np.int32),
                                 0, [1])
        finally:
            ex.close()

    def test_engine_refuses_chunk_plus_shards(self, eight_cpu_devices,
                                              llm_params):
        from nnstreamer_tpu.llm import LLMEngine

        with pytest.raises(BackendError, match="exclusive"):
            LLMEngine(llm_params, n_heads=8, block_size=8,
                      num_blocks=16, max_len=64, shards=2,
                      prefill_chunk=8)

    def test_pallas_falls_back_counted(self, eight_cpu_devices,
                                       llm_params):
        ex = PagedLLMExecutor(dict(llm_params), n_heads=8, block_size=8,
                              num_blocks=16, max_len=64, shards=2,
                              paged_kernel="pallas", name="pk")
        try:
            st = ex.stats()
            assert st["paged_kernel"] == "xla"
            assert st["kernel_fallback"] >= 1
        finally:
            ex.close()

    def test_quantized_params_refused_float_only(
            self, eight_cpu_devices, llm_params):
        from nnstreamer_tpu.models.quant import quantize_transformer

        qp = quantize_transformer(llm_params)
        with pytest.raises(BackendError, match="float-only"):
            _exec(qp, 2)


# -- elements -----------------------------------------------------------------

def _run_filter(extra, frames=6, dim=16):
    pipe = parse_launch(
        f"appsrc name=src dims={dim} types=float32 ! "
        f"tensor_filter name=f model=store://shf {extra} ! "
        f"tensor_sink name=out")
    runner = PipelineRunner(pipe)
    runner.start()
    src, sink = pipe.get("src"), pipe.get("out")
    try:
        for i in range(frames):
            src.push(TensorBuffer.of(
                np.full((dim,), float(i), np.float32), pts=i))
        src.end()
        runner.wait(60)
    finally:
        runner.stop()
    return ({int(b.pts): np.asarray(b.tensors[0]) for b in sink.results},
            pipe.get("f"))


class TestFilterElement:
    def test_shards_prop_bit_parity_and_stats(self, eight_cpu_devices):
        get_store().register("shf", lambda x: (x * 2.0 + 1.0,))
        base, _ = _run_filter("")
        got, f = _run_filter("shards=2 devices=4")
        assert got.keys() == base.keys()
        for pts, ref in base.items():
            np.testing.assert_array_equal(got[pts], ref)
        st = f.extra_stats()
        assert st["shards"] == 2
        assert st["shard_groups"] == 2
        assert st["replica_invokes"] == len(base)
        assert st["leases"]["leased"] == 4

    def test_unsupported_width_fails_negotiation(self,
                                                 eight_cpu_devices):
        from nnstreamer_tpu.core.errors import NegotiationError

        get_store().register("shf", lambda x: (x * 2.0,))
        with pytest.raises(NegotiationError):
            _run_filter("shards=3")

    def test_explicit_io_overrides_decline_sharding(
            self, eight_cpu_devices):
        """Explicit I/O override props are single-backend concerns: the
        filter declines sharding and serves single-chip (soft decline,
        not failure) — outputs stay correct."""
        get_store().register("shf", lambda x: (x * 2.0,))
        base, _ = _run_filter("")
        got, f = _run_filter("shards=2 output=16 outputtype=float32")
        for pts, ref in base.items():
            np.testing.assert_array_equal(got[pts], ref)
        assert "shards" not in f.extra_stats()


def _run_llm(prompt, **llm_props):
    src = AppSrc(name="src", spec=TensorsSpec(
        tensors=(), format=TensorFormat.FLEXIBLE))
    llm = TensorLLM(name="g", model="store://shllm", n_heads=8,
                    block_size=8, num_blocks=16, max_len=64,
                    **llm_props)
    sink = TensorSink(name="out")
    pipe = nns.Pipeline()
    for e in (src, llm, sink):
        pipe.add(e)
    pipe.link(src, llm)
    pipe.link(llm, sink)
    runner = PipelineRunner(pipe)
    runner.start()
    try:
        src.push(TensorBuffer(tensors=(prompt,), pts=0,
                              meta={"llm": {"request_id": "r0",
                                            "max_new_tokens": 6}}))
        src.end()
        runner.wait(120)
    finally:
        runner.stop()
    toks = [int(t) for b in sink.results
            for t in np.asarray(b.tensors[0]).reshape(-1)]
    return toks, llm


class TestLLMElement:
    def test_shards_prop_token_parity(self, eight_cpu_devices,
                                      llm_params):
        """tensor_llm shards=N serves the IDENTICAL token stream as the
        single-chip element, and leases its chips as one group."""
        get_store().register(
            "shllm", ModelBundle(fn=None, params=llm_params))
        prompt = np.random.default_rng(3).integers(
            1, 256, 12).astype(np.int32)
        t0, _ = _run_llm(prompt)
        t2, g2 = _run_llm(prompt, shards=2, ring_prefill_min=32)
        t4, _ = _run_llm(prompt, shards=4)
        assert len(t0) == 6
        assert t0 == t2 == t4
        st = g2.extra_stats()
        assert st["executor"]["shards"] == 2
        assert st["leases"] == {"free": 8, "leased": 0, "fenced": 0}

    def test_chunk_plus_shards_fails_negotiation(self,
                                                 eight_cpu_devices,
                                                 llm_params):
        from nnstreamer_tpu.core.errors import NegotiationError

        get_store().register(
            "shllm", ModelBundle(fn=None, params=llm_params))
        prompt = np.arange(1, 9, dtype=np.int32)
        with pytest.raises(NegotiationError):
            _run_llm(prompt, shards=2, prefill_chunk=8)
        with pytest.raises(NegotiationError):
            _run_llm(prompt, ring_prefill_min=16)   # ring without shards


# -- TP-vs-segmentation planner -----------------------------------------------

class TestPlanTP:
    def test_dominant_stage_gets_tp_not_cuts(self):
        plan = segment_plan_tp(
            [("pre", 0.1), ("big", 8.0), ("post", 0.1)], 8)
        assert plan.tp == [8]
        assert len(plan.stages) == 1
        assert plan.report()["chips_total"] == 8

    def test_balanced_chain_gets_cuts_not_tp(self):
        plan = segment_plan_tp([(f"e{i}", 1.0) for i in range(4)], 4)
        assert plan.tp == [1, 1, 1, 1]
        assert len(plan.stages) == 4
        assert plan.bubble_fraction == 0.0

    def test_low_efficiency_never_shards(self):
        # at eff <= 0.5 a doubling buys nothing: 2 * 0.5 = 1x
        plan = segment_plan_tp([("big", 8.0), ("small", 0.1)], 8,
                               tp_efficiency=0.5)
        assert all(t == 1 for t in plan.tp)

    def test_mixed_profile_mixes(self):
        plan = segment_plan_tp(
            [("pre", 0.2), ("h1", 4.0), ("h2", 4.0)], 8)
        assert sum(plan.tp) <= 8
        assert max(plan.tp) >= 2          # somebody got shards
        assert len(plan.stages) >= 2      # and the chain still cut
        # devices are contiguous group starts
        assert plan.devices == [0, plan.tp[0]][:len(plan.stages)]

    def test_plan_from_tracer_tp_kwarg(self, eight_cpu_devices):
        class _T:
            active = True

            def hists(self):
                return {"a": {"sum": 8.0, "count": 1},
                        "b": {"sum": 0.1, "count": 1}}

        plan = plan_from_tracer(_T(), ["a", "b"], 8, tp_efficiency=0.7)
        assert plan.source == "tracer"
        assert max(plan.tp) > 1
        # default stays the pure-segmentation DP (no tp field set)
        plain = plan_from_tracer(_T(), ["a", "b"], 8)
        assert plain.tp == []

    def test_apply_plan_sets_shards_prop(self, eight_cpu_devices):
        get_store().register("shf", lambda x: (x * 2.0,))
        pipe = parse_launch(
            "appsrc name=src dims=16 types=float32 ! "
            "tensor_filter name=f model=store://shf ! "
            "tensor_sink name=out")
        plan = segment_plan_tp([("f", 8.0)], 8)
        assert plan.tp == [8]
        pinned = apply_plan(pipe, plan)
        assert pinned == 1
        assert pipe.get("f").props["shards"] == 8


# -- metrics from real stats --------------------------------------------------

class TestShardMetrics:
    def test_real_stats_round_trip_conservation(self,
                                                eight_cpu_devices):
        """The nns_shard_* family fed from a LIVE ShardedReplicaSet:
        Σ shard group invokes == the filter's invoke count, from one
        render → parse cycle."""
        bundle, dim = _bundle()
        x = np.ones((2, dim), np.float32)
        rs = ShardedReplicaSet.open_sharded(bundle, shards=2, groups=2,
                                            name="ms")
        try:
            for _ in range(10):
                rs.invoke((x,))
            st = rs.stats()
        finally:
            rs.close()
        parsed = parse_prometheus(render_prometheus(metrics_snapshot(
            replicas={"f": st})))
        fam = parsed["nns_shard_group_invokes_total"]["samples"]
        assert sum(fam.values()) == 10.0
        assert parsed["nns_shard_group_size"]["samples"][
            'nns_shard_group_size{filter="f"}'] == 2.0
        leases = parsed["nns_shard_leased_chips"]["samples"]
        assert leases['nns_shard_leased_chips{filter="f",'
                      'state="leased"}'] == 4.0
        ups = parsed["nns_shard_group_up"]["samples"]
        assert all(v == 1.0 for v in ups.values())
