"""Host-path overhaul tests (wake-on-enqueue channel, chain fusion,
zero-redundant staging).

Covers the three layers of the overhaul:

- runtime/channel.py: the condition-variable channel that replaced the
  queue.Queue timeout-poll loops — wakeups on enqueue/dequeue, deadline
  waits, and the close()-based teardown wakeup that cannot be lost
  (the old ``put_nowait`` nudge silently dropped on a full queue);
- scheduler chain fusion: linear runs of cheap single-in/single-out
  fail-fast elements collapse into one worker thread with per-element
  stats/tracing preserved, and every ineligibility rule holds;
- backends/xla.py staging elision + donation: device-committed inputs
  skip ``jax.device_put`` entirely (transfer-counting stub), freshly
  staged micro-batches may donate their buffers.

Plus the watchdog bookkeeping prune and the tools/profile_hostpath.py
smoke (the CPU proxies for the BENCH host-path numbers: wakeup latency
far below the old 100 ms poll floor, fused chain cheaper per frame
than unfused).
"""

import importlib.util
import os
import threading
import time

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu import TensorBuffer, parse_launch, run_pipeline
from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.graph.pipeline import Element
from nnstreamer_tpu.runtime.channel import CLOSED, TIMED_OUT, Channel
from nnstreamer_tpu.runtime.scheduler import PipelineRunner

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_profiler():
    spec = importlib.util.spec_from_file_location(
        "profile_hostpath",
        os.path.join(_REPO, "tools", "profile_hostpath.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- channel unit tests ------------------------------------------------------

class TestChannel:
    def test_fifo_order_and_depth_accounting(self):
        ch = Channel(4)
        assert ch.put("a") == 1
        assert ch.put("b") == 2
        assert ch.qsize() == 2 and ch.peak == 2
        assert ch.get() == ("a", 1)
        assert ch.get() == ("b", 0)
        assert ch.peak == 2          # high-water survives the drain

    def test_rejects_zero_capacity(self):
        with pytest.raises(ValueError, match="capacity"):
            Channel(0)

    def test_put_wakes_blocked_consumer(self):
        ch = Channel(2)
        out = {}

        def consume():
            out["item"], out["depth"] = ch.get()

        t = threading.Thread(target=consume)
        t.start()
        time.sleep(0.05)             # consumer is parked in wait()
        ch.put("x")
        t.join(2.0)
        assert not t.is_alive()
        assert out == {"item": "x", "depth": 0}

    def test_get_wakes_blocked_producer(self):
        ch = Channel(1)
        ch.put("a")
        depths = []

        def produce():
            depths.append(ch.put("b"))

        t = threading.Thread(target=produce)
        t.start()
        time.sleep(0.05)             # producer is parked on full channel
        assert ch.get() == ("a", 0)
        t.join(2.0)
        assert not t.is_alive() and depths == [1]

    def test_close_wakes_blocked_consumer(self):
        ch = Channel(1)
        out = {}

        def consume():
            out["res"] = ch.get()

        t = threading.Thread(target=consume)
        t.start()
        time.sleep(0.05)
        ch.close()
        t.join(2.0)
        assert not t.is_alive() and out["res"] == (CLOSED, 0)

    def test_close_wakes_producer_blocked_on_full_channel(self):
        """The teardown wakeup the old put_nowait nudge lost: close()
        must unblock a producer even when the buffer is at capacity."""
        ch = Channel(1)
        ch.put("a")
        out = {}

        def produce():
            out["res"] = ch.put("b")

        t = threading.Thread(target=produce)
        t.start()
        time.sleep(0.05)
        ch.close()
        t.join(2.0)
        assert not t.is_alive()
        assert out["res"] is None    # refused, not silently dropped
        assert ch.qsize() == 1       # "b" never landed

    def test_buffered_items_survive_close(self):
        ch = Channel(4)
        ch.put("a")
        ch.close()
        assert ch.put("c") is None
        assert ch.get() == ("a", 0)
        assert ch.get() == (CLOSED, 0)

    def test_deadline_expiry_returns_timed_out(self):
        ch = Channel(1)
        t0 = time.perf_counter()
        res = ch.get(deadline=t0 + 0.02)
        dt = time.perf_counter() - t0
        assert res == (TIMED_OUT, 0)
        assert dt < 1.0              # woke at the deadline, not later

    def test_past_deadline_returns_immediately(self):
        ch = Channel(1)
        assert ch.get(deadline=time.perf_counter() - 1.0) == (TIMED_OUT, 0)

    def test_try_put_full_and_closed(self):
        ch = Channel(1)
        assert ch.try_put("a") == 1
        assert ch.try_put("b") is None    # full
        ch.get()
        ch.close()
        assert ch.try_put("c") is None    # closed
        assert ch.closed and ch.capacity == 1


# -- teardown wakeup regression (pipeline level) -----------------------------

class _BlockingSink:
    """tensor_sink whose render parks on an Event — wedges its input
    queue so the upstream worker blocks inside Channel.put."""

    def __new__(cls, name=None):
        from nnstreamer_tpu.graph.pipeline import SinkElement

        class _Impl(SinkElement):
            ELEMENT_NAME = "blocking_sink"

            def __init__(self, name=None):
                super().__init__(name=name)
                self.gate = threading.Event()
                self.count = 0

            def render(self, buf):
                self.gate.wait(30.0)
                self.count += 1

        return _Impl(name=name)


def test_stop_unblocks_worker_blocked_on_full_queue():
    """Regression for the lost teardown wakeup: a worker blocked in
    put() on a full downstream queue must exit promptly on stop() —
    the old scheduler's put_nowait nudge dropped on exactly this state."""
    from nnstreamer_tpu.elements import TensorTransform
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    pipe = nns.Pipeline("wedge")
    src = AppSrc(spec=TensorsSpec.of(
        TensorInfo((1, 4), DType.FLOAT32)), name="src")
    tr = TensorTransform(name="tr", mode="arithmetic", option="add:1.0")
    sink = _BlockingSink(name="sink")
    for e in (src, tr, sink):
        pipe.add(e)
    pipe.link(src, tr)
    pipe.link(tr, sink)
    runner = PipelineRunner(pipe, queue_capacity=1, optimize=False,
                            chain_fusion=False).start()
    frame = np.zeros((1, 4), np.float32)
    for i in range(6):               # sink queue fills; tr blocks in put
        src.push(TensorBuffer.of(frame, pts=i))
    deadline = time.monotonic() + 5.0
    tr_thread = next(t for t in runner._threads if t.name == "elem:tr")
    while runner._queues["sink"].qsize() < 1:
        assert time.monotonic() < deadline, "pipeline never filled"
        time.sleep(0.005)
    time.sleep(0.1)                  # let tr park inside put()
    t0 = time.perf_counter()
    runner.stop()
    tr_thread.join(2.0)
    assert not tr_thread.is_alive(), \
        "transform worker still blocked on a full queue after stop()"
    assert time.perf_counter() - t0 < 2.0
    sink.gate.set()                  # release the sink thread too
    for t in runner._threads:
        t.join(2.0)
        assert not t.is_alive()


# -- wakeup latency & deadline waits -----------------------------------------

class TestWakeupLatency:
    def test_wakeup_latency_beats_old_poll_floor(self):
        """Push→render p50 on an idle pipeline must sit far below the
        old scheduler's 100 ms q.get(timeout=0.1) wakeup floor."""
        ph = _load_profiler()
        res = ph.measure_wakeup_latency(n=60, warmup=10)
        assert res["p50_ms"] < 20.0, res
        assert res["p50_ms"] < ph.OLD_POLL_FLOOR_MS

    def test_batch_deadline_flush_within_budget(self):
        """A half-full tensor_batch must flush ~max-latency-ms after its
        first frame: the deadline-aware channel wait has no poll tick to
        ride out, so the flush lands well inside the old 100 ms floor."""
        p = parse_launch(
            "appsrc name=in dims=4:1 types=float32 ! "
            "tensor_batch name=b max-batch=8 max-latency-ms=25 ! "
            "tensor_unbatch ! tensor_sink name=out")
        runner = PipelineRunner(p, optimize=False).start()
        try:
            out = p.get("out")
            t0 = time.perf_counter()
            p.get("in").push(TensorBuffer.of(
                np.ones((1, 4), np.float32), pts=0))
            while not out.results:
                assert time.perf_counter() - t0 < 5.0, "flush never came"
                time.sleep(0.001)
            dt_ms = (time.perf_counter() - t0) * 1e3
            p.get("in").end()
            runner.wait(10)
        finally:
            runner.stop()
        st = runner.stats()["b"]
        assert st["flush_deadline"] == 1
        # 25 ms budget + scheduler overhead; the old poll loop could
        # add up to 100 ms here
        assert dt_ms < 100.0, f"deadline flush took {dt_ms:.1f} ms"


# -- chain fusion ------------------------------------------------------------

def _passthrough_pipe(n, policy=None, capture=True):
    extra = f" error-policy={policy}" if policy else ""
    chain = " ! ".join(
        f"tensor_transform name=t{i} mode=arithmetic option=add:1.0{extra}"
        for i in range(n))
    sink = "tensor_sink name=out" if capture else "fakesink name=out"
    return parse_launch(
        f"appsrc name=in dims=4:1 types=float32 ! {chain} ! {sink}")


def _run_frames(p, n_frames, **runner_kwargs):
    runner = PipelineRunner(p, optimize=False, **runner_kwargs).start()
    try:
        for i in range(n_frames):
            p.get("in").push(TensorBuffer.of(
                np.full((1, 4), float(i), np.float32), pts=i))
        p.get("in").end()
        runner.wait(30)
    finally:
        runner.stop()
    return runner


class TestChainFusion:
    def test_linear_chain_is_fused_with_correct_output(self):
        p = parse_launch(
            "appsrc name=in dims=4:1 types=float32 ! "
            "tensor_transform name=t0 mode=arithmetic option=add:1.0 ! "
            "tensor_transform name=t1 mode=arithmetic option=mul:2.0 ! "
            "tensor_transform name=t2 mode=arithmetic option=add:-3.0 ! "
            "tensor_sink name=out")
        runner = _run_frames(p, 5)
        assert runner.fused_chains() == [["t0", "t1", "t2"]]
        res = p.get("out").results
        assert len(res) == 5
        for i, b in enumerate(res):   # ((x+1)*2)-3, in order
            np.testing.assert_allclose(
                b.tensors[0], np.full((1, 4), (i + 1) * 2 - 3, np.float32))

    def test_fused_matches_unfused_output_and_stats(self):
        outs = {}
        for fused in (True, False):
            p = _passthrough_pipe(4)
            runner = _run_frames(p, 8, chain_fusion=fused)
            assert bool(runner.fused_chains()) == fused
            outs[fused] = [b.tensors[0] for b in p.get("out").results]
            st = runner.stats()
            for i in range(4):        # per-member attribution preserved
                assert st[f"t{i}"]["buffers"] == 8
                assert st[f"t{i}"]["proctime_total_s"] > 0.0
        assert len(outs[True]) == len(outs[False]) == 8
        for a, b in zip(outs[True], outs[False]):
            np.testing.assert_array_equal(a, b)

    def test_interlatency_traced_per_member(self):
        p = _passthrough_pipe(3)
        runner = _run_frames(p, 6, trace=True)
        assert runner.fused_chains() == [["t0", "t1", "t2"]]
        inter = runner.tracer.interlatency()
        for name in ("t0", "t1", "t2", "out"):
            assert inter[name]["n"] == 6
        # later members accumulate more latency than earlier ones
        assert inter["t2"]["p50_ms"] >= inter["t0"]["p50_ms"]

    def test_flush_emissions_flow_through_chain_at_eos(self):
        """A mid-chain element that withholds its last buffer until
        flush() must still deliver it through the rest of the chain
        before EOS reaches the sink."""

        class HoldLast(Element):
            ELEMENT_NAME = "hold_last"

            def __init__(self, name=None):
                super().__init__(name=name)
                self._held = None

            def negotiate(self, in_specs):
                return [self.expect_tensors(in_specs[0])]

            def process(self, pad, buf):
                held, self._held = self._held, buf
                return [(0, held)] if held is not None else []

            def flush(self):
                held, self._held = self._held, None
                return [(0, held)] if held is not None else []

        from nnstreamer_tpu.elements import TensorTransform
        from nnstreamer_tpu.elements.sinks import TensorSink
        from nnstreamer_tpu.elements.sources import AppSrc
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

        pipe = nns.Pipeline("holdlast")
        src = AppSrc(spec=TensorsSpec.of(
            TensorInfo((1, 4), DType.FLOAT32)), name="in")
        t0 = TensorTransform(name="t0", mode="arithmetic", option="add:1.0")
        hold = HoldLast(name="hold")
        t1 = TensorTransform(name="t1", mode="arithmetic", option="mul:2.0")
        sink = TensorSink(name="out")
        for e in (src, t0, hold, t1, sink):
            pipe.add(e)
        for a, b in zip((src, t0, hold, t1), (t0, hold, t1, sink)):
            pipe.link(a, b)
        runner = PipelineRunner(pipe, optimize=False).start()
        try:
            for i in range(3):
                src.push(TensorBuffer.of(
                    np.full((1, 4), float(i), np.float32), pts=i))
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        assert runner.fused_chains() == [["t0", "hold", "t1"]]
        res = pipe.get("out").results
        # all 3 frames arrive in order — the held one via the EOS flush
        # cascade THROUGH t1, not around it
        assert len(res) == 3 and sink.eos.is_set()
        for i, b in enumerate(res):
            np.testing.assert_allclose(
                b.tensors[0], np.full((1, 4), (i + 1) * 2, np.float32))

    def test_non_fail_policy_not_fused(self):
        p = _passthrough_pipe(3, policy="skip")
        runner = _run_frames(p, 2)
        assert runner.fused_chains() == []

    def test_deadline_element_not_fused(self):
        """tensor_batch overrides next_deadline/on_timer — fusing it
        would lose its timer wakeups, so it must break the chain."""
        p = parse_launch(
            "appsrc name=in dims=4:1 types=float32 ! "
            "tensor_transform name=t0 mode=arithmetic option=add:1.0 ! "
            "tensor_batch name=b max-batch=2 max-latency-ms=5 ! "
            "tensor_unbatch name=u ! "
            "tensor_transform name=t1 mode=arithmetic option=add:1.0 ! "
            "tensor_sink name=out")
        runner = _run_frames(p, 4)
        names = {n for chain in runner.fused_chains() for n in chain}
        assert "b" not in names
        # the unbatch→transform run downstream may still fuse
        assert len(p.get("out").results) == 4

    def test_filter_not_fused(self, tmp_path):
        from nnstreamer_tpu import register_custom_easy
        from nnstreamer_tpu.backends.custom import unregister_custom_easy

        register_custom_easy("hp_ident", lambda ts: ts,
                             infer_out=lambda s: s)
        try:
            p = parse_launch(
                "appsrc name=in dims=4:1 types=float32 ! "
                "tensor_transform name=t0 mode=arithmetic option=add:1.0 ! "
                "tensor_filter framework=custom model=hp_ident name=f ! "
                "tensor_transform name=t1 mode=arithmetic option=add:1.0 ! "
                "tensor_sink name=out")
            runner = _run_frames(p, 3)
            names = {n for chain in runner.fused_chains() for n in chain}
            assert "f" not in names   # CHAIN_FUSABLE=False opt-out
            assert len(p.get("out").results) == 3
        finally:
            unregister_custom_easy("hp_ident")

    def test_fan_out_not_fused(self):
        from nnstreamer_tpu.elements import TensorTransform
        from nnstreamer_tpu.elements.routing import Tee
        from nnstreamer_tpu.elements.sinks import TensorSink
        from nnstreamer_tpu.elements.sources import AppSrc
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

        pipe = nns.Pipeline("fanout")
        src = AppSrc(spec=TensorsSpec.of(
            TensorInfo((1, 4), DType.FLOAT32)), name="in")
        t0 = TensorTransform(name="t0", mode="arithmetic", option="add:1.0")
        tee = Tee(name="tee")
        s1, s2 = TensorSink(name="o1"), TensorSink(name="o2")
        for e in (src, t0, tee, s1, s2):
            pipe.add(e)
        pipe.link(src, t0)
        pipe.link(t0, tee)
        pipe.link(tee, s1, src_pad=0)
        pipe.link(tee, s2, src_pad=1)
        runner = PipelineRunner(pipe, optimize=False).start()
        try:
            src.push(TensorBuffer.of(np.ones((1, 4), np.float32), pts=0))
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        # tee fans out (2 out-links) and t0 alone is a 1-element run:
        # nothing fuses, and both sinks still see the frame
        assert runner.fused_chains() == []
        assert len(pipe.get("o1").results) == 1
        assert len(pipe.get("o2").results) == 1

    def test_chain_error_attributed_to_failing_member(self):

        class Boom(Element):
            ELEMENT_NAME = "boom"

            def negotiate(self, in_specs):
                return [self.expect_tensors(in_specs[0])]

            def process(self, pad, buf):
                raise RuntimeError("chain member exploded")

        from nnstreamer_tpu.elements import TensorTransform
        from nnstreamer_tpu.elements.sinks import TensorSink
        from nnstreamer_tpu.elements.sources import AppSrc
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

        pipe = nns.Pipeline("chainboom")
        src = AppSrc(spec=TensorsSpec.of(
            TensorInfo((1, 4), DType.FLOAT32)), name="in")
        t0 = TensorTransform(name="t0", mode="arithmetic", option="add:1.0")
        boom = Boom(name="boom")
        sink = TensorSink(name="out")
        for e in (src, t0, boom, sink):
            pipe.add(e)
        for a, b in zip((src, t0, boom), (t0, boom, sink)):
            pipe.link(a, b)
        runner = PipelineRunner(pipe, optimize=False).start()
        assert runner.fused_chains() == [["t0", "boom"]]
        src.push(TensorBuffer.of(np.ones((1, 4), np.float32), pts=0))
        src.end()
        with pytest.raises(StreamError, match="chain member exploded"):
            runner.wait(10)
        runner.stop()
        # t0 succeeded before the failure — its work is still attributed
        assert runner.stats()["t0"]["buffers"] == 1

    def test_fused_chain_cheaper_per_frame_than_unfused(self):
        """Acceptance: a fused 4-element passthrough chain must have
        lower per-frame host overhead than the same chain unfused."""
        ph = _load_profiler()
        fused = ph.measure_hop_overhead(4, 1500, fused=True, repeats=4)
        unfused = ph.measure_hop_overhead(4, 1500, fused=False, repeats=4)
        assert fused["per_frame_us"] < unfused["per_frame_us"], \
            (fused, unfused)


# -- staging elision & donation (backends/xla.py) ----------------------------

def _double_bundle():
    from nnstreamer_tpu.backends.xla import ModelBundle

    def fn(params, x):
        return x * 2.0

    return ModelBundle(fn=fn, params=None, name="hp_double")


class TestStagingElision:
    def test_invoke_elides_device_put_for_committed_inputs(self, monkeypatch):
        import jax

        from nnstreamer_tpu.backends.xla import XLABackend

        be = XLABackend()
        be.open({"model": _double_bundle(), "custom": ""})
        x = np.ones((1, 8), np.float32)
        (out,) = be.invoke((x,))             # host input: one transfer
        np.testing.assert_allclose(np.asarray(out), x * 2.0)
        assert be.staging_transfers == 1 and be.staging_elided == 0
        x_dev = jax.device_put(x, be._device)  # committed on the target
        jax.block_until_ready(x_dev)
        # transfer-counting stub: any device_put during the elided
        # invoke is a redundant staging copy — there must be ZERO
        calls = []
        real_put = jax.device_put

        def counting_put(*a, **kw):
            calls.append(a)
            return real_put(*a, **kw)

        monkeypatch.setattr(jax, "device_put", counting_put)
        (out2,) = be.invoke((x_dev,))
        monkeypatch.undo()
        np.testing.assert_allclose(np.asarray(out2), x * 2.0)
        assert be.staging_elided == 1
        assert be.staging_transfers == 1     # unchanged
        assert calls == [], "redundant device_put on committed input"

    def test_uncommitted_inputs_still_staged(self):
        from nnstreamer_tpu.backends.xla import XLABackend

        be = XLABackend()
        be.open({"model": _double_bundle(), "custom": ""})
        for i in range(3):
            be.invoke((np.full((1, 8), float(i), np.float32),))
        assert be.staging_transfers == 3 and be.staging_elided == 0

    def test_invoke_batched_donates_fresh_buffers(self):
        from nnstreamer_tpu.backends.xla import XLABackend

        be = XLABackend()
        be.open({"model": _double_bundle(), "custom": ""})
        be._donate = True                    # forced on (CPU default off)
        x = np.arange(16, dtype=np.float32).reshape(4, 4)
        out = be.invoke_batched((x,), n=4)
        np.testing.assert_allclose(np.asarray(out[0]), x * 2.0)
        assert be.donated_invokes == 1
        # same bucket again: the donating jit variant is cached
        hits0 = be.compile_count
        out = be.invoke_batched((x.copy(),), n=4)
        np.testing.assert_allclose(np.asarray(out[0]), x * 2.0)
        assert be.donated_invokes == 2 and be.compile_count == hits0

    def test_invoke_batched_never_donates_elided_buffers(self):
        import jax

        from nnstreamer_tpu.backends.xla import XLABackend

        be = XLABackend()
        be.open({"model": _double_bundle(), "custom": ""})
        be._donate = True
        x = np.arange(16, dtype=np.float32).reshape(4, 4)
        x_dev = jax.device_put(x, be._device)
        jax.block_until_ready(x_dev)
        out = be.invoke_batched((x_dev,), n=4)
        np.testing.assert_allclose(np.asarray(out[0]), x * 2.0)
        # upstream still owns x_dev: it was elided, so NOT donated —
        # and it must remain readable afterwards
        assert be.donated_invokes == 0 and be.staging_elided == 1
        np.testing.assert_allclose(np.asarray(x_dev), x)


# -- watchdog bookkeeping prune ----------------------------------------------

class TestWatchdogPrune:
    def _runner(self):
        p = parse_launch("appsrc name=in dims=2 ! tensor_sink name=out")
        runner = PipelineRunner(p, optimize=False, watchdog=False,
                                stall_budget_s=0.5,
                                queue_stall_budget_s=0.5).start()
        p.get("in").end()
        runner.wait(10)
        runner.stop()
        return runner

    def test_stall_bookkeeping_pruned_on_recovery(self):
        runner = self._runner()
        runner._inflight["out"] = 1000.0     # synthetic stuck process()
        assert runner._watchdog_scan(1000.9) is False
        assert runner._wd_warned_proc == {"out": 1000.0}
        assert runner.stats()["out"]["watchdog_warnings"] == 1
        # same incident: no re-warn, entry kept
        assert runner._watchdog_scan(1001.5) is False
        assert runner.stats()["out"]["watchdog_warnings"] == 1
        runner._inflight.pop("out")          # the call returned
        assert runner._watchdog_scan(1002.0) is False
        assert runner._wd_warned_proc == {}  # pruned, not retained

    def test_queue_bookkeeping_pruned_on_recovery(self):
        runner = self._runner()
        ch = Channel(1)
        ch.put("wedge")                      # pinned at capacity
        runner._queues["phantom"] = ch
        assert runner._watchdog_scan(2000.0) is False   # arms full_since
        assert runner._wd_q_full_since == {"phantom": 2000.0}
        assert runner._watchdog_scan(2000.9) is False   # past budget
        assert runner._wd_warned_q == {"phantom": 2000.0}
        ch.get()                             # queue drains → recovered
        assert runner._watchdog_scan(2001.0) is False
        assert runner._wd_q_full_since == {}
        assert runner._wd_warned_q == {}


class TestWedgedAdmission:
    """Watchdog wedged-admission incidents: depth pinned at max_pending
    with zero reply progress for the queue stall budget. Synthetic-clock
    tests driving `_watchdog_scan` directly, like TestWatchdogPrune —
    the probe is any pipeline element exposing `admission_counters()`."""

    def _runner(self, counters, **kw):
        from types import SimpleNamespace

        from nnstreamer_tpu.runtime.scheduler import ElementStats
        from nnstreamer_tpu.runtime.tracing import Tracer

        p = parse_launch("appsrc name=in dims=2 ! tensor_sink name=out")
        runner = PipelineRunner(p, optimize=False, watchdog=False,
                                trace=Tracer(),
                                stall_budget_s=0.5,
                                queue_stall_budget_s=0.5, **kw).start()
        p.get("in").end()
        runner.wait(10)
        runner.stop()
        elem = SimpleNamespace(
            name="adm", admission_counters=lambda: dict(counters))
        runner.pipeline.elements["adm"] = elem
        runner._stats.setdefault("adm", ElementStats())
        return runner

    def test_warn_once_rearm_on_progress_prune_on_recovery(self):
        c = {"depth": 8, "max_pending": 8, "replied": 0}
        runner = self._runner(c)
        warns = lambda: runner.stats()["adm"]["watchdog_warnings"]
        assert runner._watchdog_scan(3000.0) is False   # arms
        assert runner._wd_adm_since == {"adm": (3000.0, 0)}
        assert warns() == 0
        assert runner._watchdog_scan(3000.9) is False   # past budget
        assert warns() == 1
        assert runner._wd_warned_adm == {"adm": 3000.0}
        wd = [e for e in runner.tracer.events()
              if e[3] == "watchdog_wedged-admission"]
        assert len(wd) == 1 and wd[0][2] == "adm"
        # same incident: warned once, not every scan
        assert runner._watchdog_scan(3001.5) is False
        assert warns() == 1
        # reply progress while still pinned: incident re-arms
        c["replied"] = 3
        assert runner._watchdog_scan(3002.0) is False
        assert runner._wd_adm_since == {"adm": (3002.0, 3)}
        assert runner._wd_warned_adm == {}
        # wedges again after the re-arm: a second incident, new warning
        assert runner._watchdog_scan(3002.8) is False
        assert warns() == 2
        # depth recovery prunes all bookkeeping, like every _wd_* dict
        c["depth"] = 2
        assert runner._watchdog_scan(3003.0) is False
        assert runner._wd_adm_since == {} and runner._wd_warned_adm == {}

    def test_depth_pinned_but_replies_flowing_never_warns(self):
        # overload with a live service plane is HEALTHY (BUSY at the
        # door is the design) — only zero progress is an incident
        c = {"depth": 8, "max_pending": 8, "replied": 0}
        runner = self._runner(c)
        assert runner._watchdog_scan(4000.0) is False
        for i, t in enumerate((4000.4, 4000.8, 4001.2, 4001.6)):
            c["replied"] = i + 1             # progress before each scan
            assert runner._watchdog_scan(t) is False
        assert runner.stats()["adm"]["watchdog_warnings"] == 0
        assert runner._wd_warned_adm == {}

    def test_action_fail_escalates_to_watchdog_stall(self):
        from nnstreamer_tpu.core.errors import WatchdogStall

        c = {"depth": 4, "max_pending": 4, "replied": 7}
        runner = self._runner(c, watchdog_action="fail")
        assert runner._watchdog_scan(5000.0) is False   # arms
        assert runner._watchdog_scan(5000.9) is True    # escalates
        assert isinstance(runner._error, WatchdogStall)
        assert "wedged-admission" in str(runner._error)


# -- profiler smoke ----------------------------------------------------------

def test_profile_hostpath_smoke():
    """tools/profile_hostpath.py stays runnable end-to-end (tiny sizes);
    the heavy assertions live in the latency/fusion tests above."""
    ph = _load_profiler()
    res = ph.measure_hop_overhead(2, 100, fused=True, repeats=1)
    assert res["hops"] == 3 and res["per_frame_us"] > 0.0
    assert res["per_hop_us"] == pytest.approx(
        res["per_frame_us"] / 3, rel=0.01)
