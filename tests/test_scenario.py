"""Scenario engine tests: spec validation + JSON round-trip, the
four-invariant property checker against hand-built violating scrapes,
flight-bundle forensics on violation, deterministic shrinking, seeded
ChaosProxy programs, replay-from-report, and live executor drills
(ISSUE 18 acceptance)."""

import dataclasses
import json
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.edge import QueryServer
from nnstreamer_tpu.scenario import (
    INVARIANTS, ArrivalProgram, FaultProgram, ScenarioSLO,
    ScenarioSpec, ShrinkBudgetExceeded, Topology, builtin_specs,
    check_result, check_scrape, compile_arrivals, replay_scenario,
    run_scenario, shrink)


@pytest.fixture(autouse=True)
def _clean_servers():
    yield
    QueryServer.reset_all()


def _spec(**kw) -> ScenarioSpec:
    base = dict(
        name="t", seed=5,
        topology=Topology(kind="pool", workers=2, service_ms=2.0),
        arrivals=(ArrivalProgram(kind="constant", n=10, rate_x=0.5),))
    base.update(kw)
    return ScenarioSpec(**base)


# -- spec validation + round-trip --------------------------------------------

class TestSpec:
    def test_json_round_trip_exact(self):
        spec = builtin_specs()["composed_storm"]
        back = ScenarioSpec.from_json(spec.to_json())
        assert back == spec
        assert back.to_json() == spec.to_json()

    def test_labels_assigned_by_position_and_frozen(self):
        spec = builtin_specs()["composed_storm"]
        assert [a.label for a in spec.arrivals] == ["a0", "a1"]
        assert [f.label for f in spec.faults] == ["f0", "f1", "f2"]
        back = ScenarioSpec.from_json(spec.to_json())
        assert back.sub_seed("fault", "f0") == \
            spec.sub_seed("fault", "f0")

    def test_sub_seed_depends_on_root_and_label(self):
        spec = _spec()
        other = dataclasses.replace(spec, seed=6)
        assert spec.sub_seed("arrival", "a0") != \
            other.sub_seed("arrival", "a0")
        assert spec.sub_seed("arrival", "a0") != \
            spec.sub_seed("arrival", "a1")

    def test_unknown_kinds_refused_eagerly(self):
        with pytest.raises(ValueError, match="arrival kind"):
            ArrivalProgram(kind="sawtooth", n=5, rate_x=1.0)
        with pytest.raises(ValueError, match="fault kind"):
            FaultProgram(kind="meteor", at_s=0.1)
        with pytest.raises(ValueError, match="topology kind"):
            Topology(kind="cloud")

    def test_unknown_json_keys_refused(self):
        d = json.loads(_spec().to_json())
        d["arrivals"][0]["typo_key"] = 1
        with pytest.raises(ValueError, match="typo_key"):
            ScenarioSpec.from_dict(d)

    def test_net_fault_requires_mesh(self):
        with pytest.raises(ValueError, match="mesh"):
            _spec(faults=(FaultProgram(kind="blackhole", at_s=0.1),))

    def test_fault_host_bounded_by_topology(self):
        with pytest.raises(ValueError, match="host"):
            _spec(topology=Topology(kind="mesh", hosts=2),
                  faults=(FaultProgram(kind="blackhole", at_s=0.1,
                                       host=5),))

    def test_undeclared_tenant_refused(self):
        with pytest.raises(ValueError, match="unknown tenant"):
            _spec(topology=Topology(kind="pool",
                                    tenants={"paid": {}}),
                  arrivals=(ArrivalProgram(kind="constant", n=5,
                                           rate_x=0.5,
                                           tenant="ghost"),))

    def test_size_counts_programs_and_load(self):
        spec = _spec(faults=(FaultProgram(kind="worker_kill",
                                          at_s=0.1),))
        assert spec.size() == 1 + 1 + 10   # fault + arrival + n


# -- arrival compilation ------------------------------------------------------

class TestCompileArrivals:
    def test_deterministic_and_sorted(self):
        spec = builtin_specs()["composed_storm"]
        a1, o1, seg1 = compile_arrivals(spec)
        a2, o2, _ = compile_arrivals(spec)
        assert np.array_equal(a1, a2) and o1 == o2
        assert np.all(np.diff(a1) >= 0)
        assert len(a1) == len(o1) == 240 + 80 + 60
        assert {s["label"] for s in seg1} == {"a0", "a1", "f2"}

    def test_flood_rides_fault_seed_not_arrival_seed(self):
        spec = builtin_specs()["composed_storm"]
        reseeded = dataclasses.replace(spec, seed=spec.seed + 1)
        a1, _, _ = compile_arrivals(spec)
        a2, _, _ = compile_arrivals(reseeded)
        assert not np.array_equal(a1, a2)


# -- the property checker -----------------------------------------------------

def _clean_admission(n=10):
    return {"offered": n, "admitted": n, "replied": n,
            "rejected": {}, "shed": {}, "depth": 0, "inflight": 0}


def _scrape(**kw):
    s = {"admission": _clean_admission(), "orphans": [],
         "completed": 10, "report": {"lost": 0}}
    s.update(kw)
    return s


class TestChecker:
    def test_clean_scrape_passes_all_four(self):
        v = check_scrape(_scrape())
        assert v["ok"] and all(v["invariants"].values())
        assert set(v["invariants"]) == set(INVARIANTS)

    def test_offered_admitted_violation(self):
        c = _clean_admission()
        c["offered"] = 12              # 2 requests vanished at the door
        v = check_scrape(_scrape(admission=c))
        assert not v["ok"]
        assert not v["invariants"]["offered_admitted"]

    def test_admitted_settled_violation(self):
        c = _clean_admission()
        c["replied"] = 9               # one admitted request unsettled
        v = check_scrape(_scrape(admission=c))
        assert not v["invariants"]["admitted_settled"]

    def test_per_class_books_must_sum_to_global(self):
        c = _clean_admission()
        c["classes"] = {
            "paid": {"offered": 6, "admitted": 6, "replied": 6,
                     "rejected": {}, "shed": {}, "depth": 0,
                     "inflight": 0},
            "free": {"offered": 3, "admitted": 3, "replied": 3,
                     "rejected": {}, "shed": {}, "depth": 0,
                     "inflight": 0}}   # sums 9 != global 10
        v = check_scrape(_scrape(admission=c))
        assert not v["invariants"]["admitted_settled"]
        assert any("class sums" in x["detail"]
                   for x in v["violations"])

    def test_perhost_replied_sum_cross_check(self):
        v = check_scrape(_scrape(perhost_replied_sum=9))
        assert not v["invariants"]["admitted_settled"]

    def test_zero_orphans_violation(self):
        v = check_scrape(_scrape(orphans=[4242]))
        assert not v["invariants"]["zero_orphans"]
        assert "4242" in v["violations"][0]["detail"]

    def test_trace_complete_missing_hop(self):
        hops = [{"hop": h} for h in
                ("admit", "dequeue", "dispatch", "reply")]
        traces = {i: {"id": "x", "hops": hops} for i in range(10)}
        v = check_scrape(_scrape(traces=traces))
        assert not v["invariants"]["trace_complete"]
        assert "worker_recv" in v["violations"][0]["detail"]

    def test_trace_complete_missing_context(self):
        full = [{"hop": h} for h in
                ("admit", "dequeue", "dispatch", "worker_recv",
                 "worker_done", "reply")]
        traces = {i: {"id": "x", "hops": full} for i in range(9)}
        v = check_scrape(_scrape(traces=traces))   # 10 completed
        assert not v["invariants"]["trace_complete"]

    def test_slo_layer_does_not_touch_standing_flags(self):
        v = check_scrape(_scrape(report={"lost": 3}),
                         slo=ScenarioSLO(require_zero_lost=True))
        assert not v["ok"] and all(v["invariants"].values())
        assert v["violations"][0]["invariant"] == "slo"

    def test_violation_dumps_flight_bundle_with_spec(self, tmp_path):
        from nnstreamer_tpu.runtime.flightrec import (
            FlightRecorder, load_bundle)

        spec = _spec()
        c = _clean_admission()
        c["offered"] = 99
        result = {"scenario": spec.name, "seed": spec.seed,
                  "spec": spec.to_dict(), "admission": c,
                  "orphans": [], "report": {"completed": 10}}
        rec = FlightRecorder(str(tmp_path), cooldown_s=0.0)
        v = check_result(result, spec, recorder=rec)
        assert not v["ok"] and v.get("flight_bundle")
        bundle = load_bundle(v["flight_bundle"])
        cause = bundle["cause"]["cause"]
        assert cause["scenario_spec"] == spec.to_dict()
        assert cause["violations"] == v["violations"]


# -- shrinking ----------------------------------------------------------------

class TestShrink:
    def test_deterministic_minimal_repro(self):
        spec = builtin_specs()["composed_storm"]

        def fails(s):
            return any(f.label == "f0" for f in s.faults)

        m1, st1 = shrink(spec, fails)
        m2, st2 = shrink(spec, fails)
        assert m1.to_json() == m2.to_json() and st1 == st2
        assert [f.label for f in m1.faults] == ["f0"]
        assert len(m1.arrivals) == 1 and m1.arrivals[0].n == 1
        assert st1["final_size"] < st1["initial_size"]
        assert fails(m1)

    def test_survivor_sub_seeds_preserved(self):
        spec = builtin_specs()["composed_storm"]
        m, _ = shrink(spec, lambda s: any(f.label == "f0"
                                          for f in s.faults))
        assert m.sub_seed("fault", "f0") == \
            spec.sub_seed("fault", "f0")
        a = m.arrivals[0]
        assert m.sub_seed("arrival", a.label) == \
            spec.sub_seed("arrival", a.label)

    def test_always_failing_drops_every_fault(self):
        spec = builtin_specs()["kill_pool"]
        m, _ = shrink(spec, lambda s: True)
        assert m.faults == () and len(m.arrivals) == 1
        assert m.arrivals[0].n == 1 and m.size() == 2

    def test_non_failing_spec_refused(self):
        with pytest.raises(ValueError, match="does not fail"):
            shrink(_spec(), lambda s: False)

    def test_budget_exceeded_raises(self):
        spec = builtin_specs()["composed_storm"]
        with pytest.raises(ShrinkBudgetExceeded):
            shrink(spec, lambda s: any(f.label == "f0"
                                       for f in s.faults),
                   max_runs=2)

    def test_memoised_candidates_do_not_burn_budget(self):
        spec = _spec()
        calls = []

        def fails(s):
            calls.append(s.to_json())
            return True

        _, st = shrink(spec, fails)
        assert st["runs"] == len(calls) == len(set(calls))


# -- ChaosProxy scheduled programs (satellite c) ------------------------------

class TestChaosProxyProgram:
    def _echo_world(self):
        from nnstreamer_tpu.traffic.loadgen import EchoServer
        from nnstreamer_tpu.traffic.netchaos import ChaosProxy

        es = EchoServer(service_ms=1.0)
        proxy = ChaosProxy("127.0.0.1", es.port, seed=3)
        return es, proxy

    def test_program_validates_eagerly(self):
        es, proxy = self._echo_world()
        try:
            with pytest.raises(ValueError, match="op"):
                proxy.program([(0.1, "meteor")])
            with pytest.raises(ValueError):
                proxy.program([(-0.5, "blackhole")])
        finally:
            proxy.close()
            es.stop()

    def test_scheduled_blackhole_then_heal_applies_in_order(self):
        es, proxy = self._echo_world()
        try:
            proxy.program([(0.05, "blackhole"), (0.15, "heal")])
            assert proxy.wait_program(5.0)
            ops = [e["op"] for e in proxy.program_log]
            assert ops == ["blackhole", "heal"]
            t_bh = proxy.applied("blackhole")
            t_heal = proxy.applied("heal")
            assert t_bh is not None and t_heal is not None
            assert t_heal > t_bh
        finally:
            proxy.close()
            es.stop()

    def test_cancel_program_stops_pending_events(self):
        es, proxy = self._echo_world()
        try:
            proxy.program([(30.0, "blackhole")])
            proxy.cancel_program()
            assert proxy.applied("blackhole") is None
        finally:
            proxy.close()
            es.stop()


# -- replay from report (satellite a) -----------------------------------------

class TestReplayFromReport:
    def test_echo_report_carries_seed_and_schedule(self):
        from nnstreamer_tpu.traffic import (
            replay_report, run_against_echo)

        r1 = run_against_echo(pattern="poisson", load_x=0.3, n=40,
                              service_ms=1.0, seed=9)
        assert r1["seed"] == 9
        assert r1["schedule"]["kind"] == "echo"
        r2 = replay_report(r1)
        # under-capacity + same seed → the ledger reproduces exactly
        assert r2["completed"] == r1["completed"] == 40
        assert r2["lost"] == r1["lost"] == 0
        for k in ("offered", "admitted", "replied"):
            assert r2["admission"][k] == r1["admission"][k]

    def test_replay_refuses_reports_without_block(self):
        from nnstreamer_tpu.traffic import replay_report

        with pytest.raises(ValueError):
            replay_report({"seed": 1})
        with pytest.raises(ValueError):
            replay_report({"schedule": {"kind": "echo"}})


# -- live executor drills -----------------------------------------------------

class TestExecutorPool:
    def test_smoke_pool_all_invariants_and_replay(self):
        r = run_scenario(builtin_specs()["smoke_pool"])
        assert r["check"]["ok"], r["check"]["violations"]
        assert all(r["check"]["invariants"].values())
        assert r["totals"]["lost"] == 0
        r2 = replay_scenario(r)
        assert r2["replay_match"], r2.get("replay_diff")

    @pytest.mark.chaos
    def test_kill_pool_recovers_and_conserves(self):
        r = run_scenario(builtin_specs()["kill_pool"])
        assert r["check"]["ok"], r["check"]["violations"]
        assert r["report"]["recovered"]
        assert r["fault_log"]["kills"][0]["schedule"]
        assert r["totals"]["lost"] == 0

    def test_tenant_classes_scraped_per_class(self):
        spec = _spec(
            topology=Topology(kind="pool", workers=2, service_ms=2.0,
                              tenants={"paid": {"weight": 2.0},
                                       "free": {"weight": 1.0}}),
            arrivals=(
                ArrivalProgram(kind="constant", n=12, rate_x=0.3,
                               tenant="paid"),
                ArrivalProgram(kind="poisson", n=8, rate_x=0.1,
                               tenant="free"),
            ))
        r = run_scenario(spec)
        assert r["check"]["ok"], r["check"]["violations"]
        classes = r["admission"]["classes"]
        assert classes["paid"]["replied"] == 12
        assert classes["free"]["replied"] == 8


@pytest.mark.mesh
@pytest.mark.slow
class TestExecutorMesh:
    def test_flash_mesh_blackhole_heal_zero_lost(self):
        r = run_scenario(builtin_specs()["flash_mesh"])
        assert r["check"]["ok"], r["check"]["violations"]
        assert r["totals"]["lost"] == 0
        assert r["report"]["recovered"]
        log = r["fault_log"]["proxies"]["0"]
        assert [e["op"] for e in log] == ["blackhole", "heal"]
        assert r["perhost_replied_sum"] == r["totals"]["replied"]

    def test_composed_storm_acceptance(self):
        """ISSUE 18 acceptance: flash-crowd × blackhole-then-heal ×
        swap-storm × tenant-flood against a real mesh under one root
        seed — zero lost, four invariants from one scrape, and replay
        reproduces the exact ledger."""
        r = run_scenario(builtin_specs()["composed_storm"])
        assert r["check"]["ok"], r["check"]["violations"]
        assert all(r["check"]["invariants"].values())
        assert r["totals"]["lost"] == 0
        assert r["report"]["recovered"]
        assert {"paid", "free"} <= set(r["admission"]["classes"])
        r2 = replay_scenario(r)
        assert r2["replay_match"], r2.get("replay_diff")
