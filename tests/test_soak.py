"""Soak + fault-injection: stream dynamics the SSAT suites catch
(SURVEY.md §4 negative tests, §5.3 failure detection)."""

import threading
import time

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.backends.custom import register_custom_easy
from nnstreamer_tpu.edge import QueryServer
from nnstreamer_tpu.tensor.buffer import TensorBuffer


@pytest.fixture(autouse=True)
def _clean_servers():
    yield
    QueryServer.reset_all()


def test_soak_thousand_frames_mux_filter_demux():
    """1000 frames through a mux → filter → demux graph: no stall, no
    drop, order preserved, bounded queues hold."""
    register_custom_easy("soak_add", lambda t: (t[0] + t[1],))
    pipe = nns.parse_launch(
        "appsrc name=a dims=8 types=float32 ! mux.sink_0 "
        "appsrc name=b dims=8 types=float32 ! mux.sink_1 "
        "tensor_mux name=mux sync-mode=nosync ! "
        "tensor_filter framework=custom model=soak_add ! "
        "tensor_sink name=s")
    runner = nns.PipelineRunner(pipe, queue_capacity=4).start()
    n = 1000
    a, b = pipe.get("a"), pipe.get("b")

    def feed(src, base):
        for i in range(n):
            src.push(TensorBuffer.of(
                np.full((8,), base + i, np.float32), pts=i))
        src.end()

    ta = threading.Thread(target=feed, args=(a, 0.0), daemon=True)
    tb = threading.Thread(target=feed, args=(b, 1000.0), daemon=True)
    ta.start()
    tb.start()
    runner.wait(300)
    runner.stop()
    res = pipe.get("s").results
    assert len(res) == n
    for i in (0, n // 2, n - 1):    # spot-check order + values
        assert res[i].pts == i
        np.testing.assert_array_equal(
            res[i].tensors[0], np.full((8,), 1000.0 + 2 * i, np.float32))


def test_filter_invoke_failure_stops_pipeline_with_cause():
    """A model that raises mid-stream fails the pipeline loudly (fail-
    loud scheduler, §5.3) with the original cause in the error."""
    calls = {"n": 0}

    def flaky(t):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("injected fault at frame 3")
        return (t[0],)

    register_custom_easy("soak_flaky", flaky)
    pipe = nns.parse_launch(
        "appsrc name=src dims=4 types=float32 ! "
        "tensor_filter framework=custom model=soak_flaky ! "
        "tensor_sink name=s")
    runner = nns.PipelineRunner(pipe).start()
    src = pipe.get("src")
    for i in range(5):
        src.push(TensorBuffer.of(np.zeros((4,), np.float32), pts=i))
    src.end()
    with pytest.raises(Exception, match="injected fault"):
        runner.wait(60)
    runner.stop()


def test_query_server_death_fails_client_cleanly():
    """Killing the server mid-stream surfaces a StreamError at the
    client instead of hanging (edge failure detection)."""
    register_custom_easy("soak_echo", lambda t: (t[0],))
    server = nns.parse_launch(
        "tensor_query_serversrc name=ssrc id=41 dims=4 types=float32 "
        "port=0 ! tensor_filter framework=custom model=soak_echo ! "
        "tensor_query_serversink id=41")
    srunner = nns.PipelineRunner(server).start()
    port = server.get("ssrc").port
    client = nns.parse_launch(
        f"appsrc name=src dims=4 types=float32 ! "
        f"tensor_query_client port={port} timeout=3 ! "
        f"tensor_sink name=s")
    crunner = nns.PipelineRunner(client).start()
    src = client.get("src")
    src.push(TensorBuffer.of(np.ones((4,), np.float32), pts=0))
    deadline = time.time() + 30
    while not client.get("s").results and time.time() < deadline:
        time.sleep(0.02)
    assert client.get("s").results, "first frame should round-trip"
    # kill the server, then push: the client must fail within timeout
    server.get("ssrc").interrupt()
    srunner.stop()
    src.push(TensorBuffer.of(np.ones((4,), np.float32), pts=1))
    src.end()
    with pytest.raises(Exception, match="no reply|closed|failed"):
        crunner.wait(60)
    crunner.stop()
