"""Serving-edge traffic tests: bounded admission, typed BUSY
backpressure, shed policies, and the open-loop harness.

The load-bearing invariant throughout is conservation — every offered
request is exactly one of {replied, rejected, shed, still queued/
inflight}; nothing is ever silently dropped (ISSUE 8 acceptance)."""

import queue as _queue
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.core.errors import ServerBusyError, StreamError
from nnstreamer_tpu.edge import QueryServer
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.traffic import (
    AdmissionQueue, EchoServer, bursty_arrivals, poisson_arrivals,
    run_against_echo)


@pytest.fixture(autouse=True)
def _clean_servers():
    yield
    QueryServer.reset_all()


def _conserved(c: dict) -> bool:
    """Both accounting invariants from the admission contract."""
    return (c["offered"] == c["admitted"] + sum(c["rejected"].values())
            and c["admitted"] == c["replied"] + sum(c["shed"].values())
            + c["depth"] + c["inflight"])


# -- AdmissionQueue unit tests (no sockets) ----------------------------------

class TestAdmissionQueue:
    def test_reject_newest_bounds_queue(self):
        q = AdmissionQueue(max_pending=3)
        for i in range(3):
            assert q.offer(i).admitted
        d = q.offer(99)
        assert not d.admitted and d.cause == "queue_full"
        assert d.queue_depth == 3 and d.retry_after_ms > 0
        c = q.counters()
        assert c["offered"] == 4 and c["admitted"] == 3
        assert c["rejected"] == {"queue_full": 1}
        assert c["depth_peak"] == 3 and _conserved(c)

    def test_reject_oldest_sheds_victim_still_admits(self):
        q = AdmissionQueue(max_pending=2, shed_policy="reject-oldest")
        q.offer("a"), q.offer("b")
        d = q.offer("c")
        assert d.admitted
        assert d.victims == ["a"] and d.victim_cause == "reject_oldest"
        c = q.counters()
        assert c["shed"] == {"reject_oldest": 1} and c["depth"] == 2
        # FIFO order after the shed: b then c
        assert q.get(timeout=1) == "b" and q.get(timeout=1) == "c"
        q.note_replied(), q.note_replied()
        assert _conserved(q.counters())

    def test_deadline_drop_purges_expired(self):
        q = AdmissionQueue(max_pending=8, shed_policy="deadline-drop")
        rushed = SimpleNamespace(meta={"deadline_ms": 5})
        d = q.offer(rushed, now=100.0)
        assert d.admitted
        # 200ms later its 5ms budget is long gone: the next offer purges
        d = q.offer(SimpleNamespace(meta={}), now=100.2)
        assert d.admitted
        assert d.victims == [rushed] and d.victim_cause == "deadline"
        c = q.counters()
        assert c["shed"] == {"deadline": 1} and c["depth"] == 1
        assert _conserved(c)

    def test_deadline_drop_full_without_expiries_rejects_newest(self):
        q = AdmissionQueue(max_pending=1, shed_policy="deadline-drop")
        assert q.offer(SimpleNamespace(meta={}), now=1.0).admitted
        d = q.offer(SimpleNamespace(meta={}), now=1.001)
        assert not d.admitted and d.cause == "queue_full"

    def test_inflight_bound_counts_dequeued_work(self):
        q = AdmissionQueue(max_pending=10, max_inflight=2)
        assert q.offer("a").admitted and q.offer("b").admitted
        assert q.offer("c").cause == "inflight_full"
        q.get(timeout=1)                     # a queued->inflight
        assert q.offer("c").cause == "inflight_full"   # still 2 total
        q.note_replied()                     # a done
        assert q.offer("c").admitted
        assert _conserved(q.counters())

    def test_note_failed_counts_as_shed(self):
        q = AdmissionQueue(max_pending=4)
        q.offer("a")
        q.get(timeout=1)
        q.note_failed("dispatch_error")
        c = q.counters()
        assert c["shed"] == {"dispatch_error": 1}
        assert c["inflight"] == 0 and _conserved(c)

    def test_sentinel_bypasses_admission(self):
        q = AdmissionQueue(max_pending=1)
        assert q.offer("real").admitted
        q.put_nowait(None)                   # full queue must not refuse
        assert q.get(timeout=1) == "real"
        assert q.get(timeout=1) is None
        c = q.counters()
        assert c["offered"] == 1             # sentinel never counted
        assert c["inflight"] == 1            # only the real item

    def test_get_timeout_raises_queue_empty(self):
        with pytest.raises(_queue.Empty):
            AdmissionQueue().get(timeout=0.05)

    def test_shed_remaining_closes_then_reopen(self):
        q = AdmissionQueue(max_pending=8)
        q.offer("a"), q.offer("b")
        assert q.shed_remaining() == ["a", "b"]
        d = q.offer("c")
        assert not d.admitted and d.cause == "shutdown"
        c = q.counters()
        assert c["shed"] == {"shutdown": 2}
        assert c["rejected"] == {"shutdown": 1} and _conserved(c)
        q.reopen()
        assert q.offer("c").admitted

    def test_configure_validates(self):
        q = AdmissionQueue()
        with pytest.raises(ValueError, match="max_pending"):
            q.configure(max_pending=0)
        with pytest.raises(ValueError, match="max_inflight"):
            q.configure(max_inflight=-1)
        with pytest.raises(ValueError, match="shed_policy"):
            q.configure(shed_policy="drop-table")

    def test_retry_after_tracks_service_rate(self):
        q = AdmissionQueue(max_pending=4)
        assert q.offer(0).retry_after_ms == 50.0   # no estimate yet
        for _ in range(3):
            q.get(timeout=1)
            q.note_replied()
            q.offer(0)
        # EWMA exists now: suggestion scales with queue depth, clamped
        d = q.offer(1)
        assert 1.0 <= d.retry_after_ms <= 10_000.0

    def test_retry_after_cold_start_is_finite_positive(self):
        """ISSUE 12 satellite: a freshly started (or freshly joined)
        server has NO reply EWMA yet — every rejection it issues must
        still carry a finite positive retry hint, or a retry:N:backoff
        client divides by it garbage. Degenerate EWMA states (a stuck
        clock, an overflowed estimate) must degrade to the clamps, not
        to inf/NaN on the wire."""
        q = AdmissionQueue(max_pending=1)
        q.offer("a")
        d = q.offer("b")                       # cold: no EWMA at all
        assert not d.admitted
        assert d.retry_after_ms == 50.0        # _DEFAULT_RETRY_MS
        for bad in (0.0, -1.0, float("inf"), float("nan")):
            q._ewma_reply_s = bad
            d = q.offer("b")
            assert d.retry_after_ms == 50.0, \
                f"ewma={bad} leaked a useless hint {d.retry_after_ms}"
        q._ewma_reply_s = 1e306                # est overflows to inf
        d = q.offer("b")
        import math
        assert math.isfinite(d.retry_after_ms)
        assert d.retry_after_ms == 10_000.0    # upper clamp


# -- live max_pending shrink (ISSUE 15 satellite) ----------------------------

class TestConfigureShrink:
    """Shrinking max_pending below the live depth must never strand or
    double-count an entry: under reject-oldest the excess oldest
    entries are shed (cause bound_shrink) and returned as victims; the
    conservation invariants hold exactly at every step."""

    def test_shrink_sheds_oldest_exactly_once(self):
        q = AdmissionQueue(max_pending=8, shed_policy="reject-oldest")
        for i in range(8):
            assert q.offer(i).admitted
        victims = q.configure(max_pending=3)
        assert victims == [0, 1, 2, 3, 4]      # oldest first
        c = q.counters()
        assert c["shed"] == {"bound_shrink": 5}
        assert c["depth"] == 3 and _conserved(c)
        # survivors drain in FIFO order, nothing stranded
        assert [q.get(timeout=0.1) for _ in range(3)] == [5, 6, 7]

    def test_shrink_under_other_policies_drains_naturally(self):
        for policy in ("reject-newest", "deadline-drop"):
            q = AdmissionQueue(max_pending=8, shed_policy=policy)
            for i in range(8):
                assert q.offer(i).admitted
            assert q.configure(max_pending=3) == []
            c = q.counters()
            assert c["depth"] == 8 and c["shed"] == {} and _conserved(c)
            # the bound still applies to new arrivals immediately
            assert not q.offer(99).admitted

    def test_shrink_never_evicts_sentinel(self):
        q = AdmissionQueue(max_pending=8, shed_policy="reject-oldest")
        for i in range(4):
            q.offer(i)
        q.put_nowait(None)                     # teardown sentinel
        victims = q.configure(max_pending=2)
        assert None not in victims
        assert victims == [0, 1, 2]
        drained = [q.get(timeout=0.1) for _ in range(2)]
        assert drained == [3, None]            # sentinel survived

    def test_shrink_grow_shrink_keeps_books_exact(self):
        q = AdmissionQueue(max_pending=16, shed_policy="reject-oldest")
        for i in range(16):
            q.offer(i)
        q.configure(max_pending=5)
        assert _conserved(q.counters())
        q.configure(max_pending=32)            # growth sheds nothing
        c = q.counters()
        assert c["depth"] == 5 and _conserved(c)
        for i in range(100, 110):
            q.offer(i)
        q.configure(max_pending=2)
        c = q.counters()
        assert c["depth"] == 2 and _conserved(c)

    def test_tenant_mode_shrink_trims_per_class_bounds(self):
        from nnstreamer_tpu.serving.tenancy import (
            TENANT_META, TenantTable)
        from nnstreamer_tpu.traffic.loadgen import (
            _tenant_conservation_ok)

        q = AdmissionQueue(max_pending=8, shed_policy="reject-oldest")
        q.set_tenants(TenantTable.from_dict(
            {"default": "a", "tenants": [
                {"name": "a", "weight": 1.0},
                {"name": "b", "weight": 1.0}]}))
        for i in range(4):
            for t in ("a", "b"):
                d = q.offer(SimpleNamespace(meta={TENANT_META: t},
                                            pts=i))
                assert d.admitted
        victims = q.configure(max_pending=4)   # bounds 4+4 -> 2+2
        assert len(victims) == 4
        c = q.counters()
        assert c["shed"] == {"bound_shrink": 4}
        for cls in ("a", "b"):
            assert c["classes"][cls]["shed"] == {"bound_shrink": 2}
            assert c["classes"][cls]["depth"] == 2
        assert _tenant_conservation_ok(c)

    def test_conservation_exact_under_flood_with_live_shrinks(self):
        """The regression the satellite asks for: a producer floods,
        a consumer serves, and the bound is yanked up and down live —
        the books must close exactly at every sampled instant."""
        q = AdmissionQueue(max_pending=32, shed_policy="reject-oldest")
        stop = threading.Event()

        def flood():
            i = 0
            while not stop.is_set():
                q.offer(i)
                i += 1

        def serve():
            while not stop.is_set():
                try:
                    item = q.get(timeout=0.01)
                except _queue.Empty:
                    continue
                if item is not None:
                    q.note_replied()

        threads = [threading.Thread(target=flood, daemon=True),
                   threading.Thread(target=serve, daemon=True)]
        for t in threads:
            t.start()
        try:
            for mp in (4, 32, 3, 16, 2, 32) * 5:
                q.configure(max_pending=mp)
                assert _conserved(q.counters()), \
                    f"books broke right after shrink to {mp}"
                time.sleep(0.002)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=2)
        assert _conserved(q.counters())
        assert q.counters()["shed"].get("bound_shrink", 0) > 0


# -- arrival processes -------------------------------------------------------

class TestArrivals:
    def test_poisson_deterministic_and_on_rate(self):
        a = poisson_arrivals(100.0, 400, np.random.default_rng(7))
        b = poisson_arrivals(100.0, 400, np.random.default_rng(7))
        np.testing.assert_array_equal(a, b)
        assert np.all(np.diff(a) >= 0) and a[0] >= 0
        # 400 samples at 100 rps: mean inter-arrival 10ms +/- 30%
        assert 0.007 < np.mean(np.diff(a)) < 0.013

    def test_bursty_alternates_phases(self):
        a = bursty_arrivals(500, rate_high_hz=500.0, rate_low_hz=10.0,
                            mean_dwell_s=0.05,
                            rng=np.random.default_rng(3))
        b = bursty_arrivals(500, rate_high_hz=500.0, rate_low_hz=10.0,
                            mean_dwell_s=0.05,
                            rng=np.random.default_rng(3))
        np.testing.assert_array_equal(a, b)
        assert np.all(np.diff(a) >= 0)
        gaps = np.diff(a)
        # both phases visible: some burst-rate gaps, some trough gaps
        assert np.min(gaps) < 1 / 100.0 < np.max(gaps)


# -- flood the real server (the ISSUE acceptance scenario) -------------------

class TestFlood:
    def test_overload_sheds_typed_and_loses_nothing(self):
        r = run_against_echo(pattern="poisson", load_x=2.0, n=60,
                             service_ms=5.0, max_pending=4, seed=7)
        assert r["rejected"] > 0, "2x overload must shed"
        assert r["lost"] == 0, "every request replied or typed-rejected"
        assert not r["server_crashed"]
        assert r["busy_causes"].get("queue_full", 0) > 0
        adm = r["admission"]
        assert adm["max_pending"] == 4          # knob reached the queue
        assert adm["offered"] == r["offered"]
        assert _conserved(adm)
        assert r["queue_depth_peak"] <= 4

    def test_deadline_drop_purges_live(self):
        # a 20ms budget against 5ms service + overload: queued frames
        # expire and are shed with the deadline cause, never lost
        r = run_against_echo(pattern="poisson", load_x=2.5, n=60,
                             service_ms=5.0, max_pending=8,
                             shed_policy="deadline-drop",
                             p99_budget_ms=20.0, seed=5)
        assert r["busy_causes"].get("deadline", 0) > 0
        assert r["lost"] == 0 and _conserved(r["admission"])

    def test_below_knee_sheds_nothing(self):
        r = run_against_echo(pattern="poisson", load_x=0.4, n=40,
                             service_ms=5.0, max_pending=8, seed=7)
        assert r["rejected"] == 0 and r["lost"] == 0
        assert r["completed"] == 40


# -- client backpressure through the error-policy machinery ------------------

def _client_pipe(port, policy, n, max_in_flight=2, timeout=30):
    extra = f"error_policy={policy} " if policy else ""
    pipe = nns.parse_launch(
        f"appsrc name=src dims=8:1 types=float32 ! "
        f"tensor_query_client name=qc port={port} timeout={timeout} "
        f"max_in_flight={max_in_flight} {extra}! tensor_sink name=sink")
    rn = nns.PipelineRunner(pipe).start()
    for i in range(n):
        pipe.get("src").push(
            TensorBuffer.of(np.full((8, 1), float(i), np.float32), pts=i))
    pipe.get("src").end()
    return pipe, rn


class TestClientBackpressure:
    def test_retry_policy_recovers_every_frame(self):
        # max_inflight=1: any frame offered while another is queued or
        # in service is refused, so a 2-deep client window guarantees
        # rejections — retry must still deliver all frames, in order
        srv = EchoServer(service_ms=40.0, max_pending=16, max_inflight=1)
        try:
            pipe, rn = _client_pipe(srv.port, "retry:10:30", n=6)
            rn.wait(60)
            st = rn.stats()
            rn.stop()
            res = pipe.get("sink").results
            assert [r.pts for r in res] == list(range(6))
            # the test is vacuous unless BUSY actually happened
            assert st["qc"]["query_busy"] >= 1
            assert st["qc"]["retries"] >= 1
            assert not srv.crashed()
        finally:
            srv.stop()

    def test_skip_policy_sheds_client_side(self):
        srv = EchoServer(service_ms=30.0, max_pending=16, max_inflight=1)
        try:
            pipe, rn = _client_pipe(srv.port, "skip", n=8)
            rn.wait(60)                      # no error: skip absorbs
            st = rn.stats()
            rn.stop()
            res = pipe.get("sink").results
            assert 1 <= len(res) < 8         # some delivered, some shed
            assert st["qc"]["query_busy"] >= 1
            pts = [r.pts for r in res]
            assert pts == sorted(pts)        # gaps allowed, reorder not
        finally:
            srv.stop()

    def test_fail_fast_surfaces_typed_busy(self):
        srv = EchoServer(service_ms=50.0, max_pending=16, max_inflight=1)
        try:
            pipe, rn = _client_pipe(srv.port, None, n=4)
            with pytest.raises(StreamError, match="rejected frame"):
                rn.wait(30)
            assert isinstance(rn._error, ServerBusyError)
            assert rn._error.cause == "inflight_full"
            rn.stop()
        finally:
            srv.stop()


# -- BatchedQueryServer shutdown race + stats snapshot -----------------------

class TestBatchedShutdown:
    def _server(self, **kw):
        import jax.numpy as jnp

        from nnstreamer_tpu.backends.xla import ModelBundle
        from nnstreamer_tpu.edge import BatchedQueryServer
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

        QueryServer.reset_all()
        w = jnp.arange(12, dtype=jnp.float32).reshape(4, 3)
        bundle = ModelBundle(
            fn=lambda p, x: (x @ p["w"],),
            params={"w": w},
            in_spec=TensorsSpec.of(TensorInfo((1, 4), DType.FLOAT32)),
            out_spec=TensorsSpec.of(TensorInfo((1, 3), DType.FLOAT32)),
            name="linear")
        return BatchedQueryServer(bundle, sid=33, port=0, **kw)

    def test_close_mid_stream_answers_or_sheds_every_frame(self):
        """The PR-7 race: close() while frames are queued must neither
        hang a client nor silently drop — each in-flight frame ends as
        RESULT or typed BUSY, and close() returns promptly."""
        import nnstreamer_tpu.edge.protocol as P
        from nnstreamer_tpu.edge.wire import encode_buffer

        srv = self._server(bucket=4, max_delay_ms=50.0)
        done = threading.Event()
        got = {"result": 0, "busy": 0}
        n_sent = 12

        def on_msg(mtype, payload):
            if mtype == P.T_RESULT:
                got["result"] += 1
            elif mtype == P.T_BUSY:
                got["busy"] += 1
            if got["result"] + got["busy"] >= n_sent:
                done.set()

        cli = P.MsgClient("127.0.0.1", srv.port, on_message=on_msg)
        try:
            cli.send(P.T_HELLO, b'{"dims": "1:4", "types": "float32"}')
            time.sleep(0.3)                  # let the ACK land
            x = np.ones((1, 4), np.float32)
            for i in range(n_sent):
                cli.send(P.T_DATA, encode_buffer(
                    TensorBuffer.of(x, pts=i)))
            t0 = time.monotonic()
            srv.close()                      # race: frames still queued
            assert time.monotonic() - t0 < 15
            assert done.wait(10), (
                f"lost frames: {got} of {n_sent} answered")
            assert got["result"] + got["busy"] == n_sent
            st = srv.stats()
            assert st["admitted"] == st["replied"] + st["shed"]
        finally:
            cli.close()

    def test_stats_snapshot_is_thread_safe_under_load(self):
        import nnstreamer_tpu as nns

        srv = self._server(bucket=4, max_delay_ms=5.0)
        errs = []

        def poll():
            for _ in range(200):
                st = srv.stats()
                if not {"frames", "batches", "admitted",
                        "replied"} <= set(st):
                    errs.append(f"missing keys: {sorted(st)}")
                    return
        try:
            poller = threading.Thread(target=poll)
            poller.start()
            pipe = nns.parse_launch(
                f"appsrc name=src dims=4:1 types=float32 ! "
                f"tensor_query_client port={srv.port} timeout=30 "
                f"max_in_flight=4 ! tensor_sink name=sink")
            rn = nns.PipelineRunner(pipe).start()
            for i in range(16):
                pipe.get("src").push(TensorBuffer.of(
                    np.ones((1, 4), np.float32), pts=i))
            pipe.get("src").end()
            rn.wait(30)
            rn.stop()
            poller.join(10)
            assert not errs, errs[0]
            assert len(pipe.get("sink").results) == 16
        finally:
            srv.close()

    def test_close_is_idempotent(self):
        """Regression: a supervisor-driven close racing (or repeating)
        a user close must be a no-op — no double-shed, no error on an
        already-stopped dispatcher, stable stats."""
        srv = self._server(bucket=4, max_delay_ms=5.0)
        srv.close()
        st = srv.stats()
        srv.close()                          # second close: no-op
        assert srv.stats() == st
        srv.close()                          # and a third, for luck


# -- observability ------------------------------------------------------------

class TestShedObservability:
    def test_tracer_counts_sheds_across_ring_wrap(self):
        from nnstreamer_tpu.runtime.tracing import Tracer

        tr = Tracer(max_events=4)            # tiny ring: force wrap
        for i in range(10):
            tr.record_shed("query_server_5", "queue_full",
                           float(i), pts=i)
        tr.record_shed("query_server_5", "shutdown", 11.0)
        counts = tr.shed_counts()
        assert counts["query_server_5"] == {"queue_full": 10,
                                            "shutdown": 1}
        assert tr.summary()["sheds"] == counts

    def test_serversrc_extra_stats_surface_admission(self):
        r = run_against_echo(pattern="poisson", load_x=2.0, n=40,
                             service_ms=5.0, max_pending=4, seed=3)
        adm = r["admission"]
        assert adm["rejected"].get("queue_full", 0) == r["rejected"]


# -- distributed tracing at the serving edge ---------------------------------

from nnstreamer_tpu.runtime.tracing import (  # noqa: E402
    ensure_trace_ctx, get_trace_ctx, hop_spans)


class TestEdgeTracing:
    def test_busy_retry_reuses_trace_id(self):
        """ISSUE 11 regression: a client BUSY-retry re-sends the SAME
        buffer, so the trace context (and its id) must survive — a new
        client_send hop is appended, never a fresh id. A fresh id per
        attempt would shatter one request into unjoinable timelines."""
        srv = EchoServer(service_ms=40.0, max_pending=16, max_inflight=1)
        try:
            pipe = nns.parse_launch(
                f"appsrc name=src dims=8:1 types=float32 ! "
                f"tensor_query_client name=qc port={srv.port} "
                f"timeout=30 max_in_flight=2 error_policy=retry:10:30 "
                f"! tensor_sink name=sink")
            rn = nns.PipelineRunner(pipe).start()
            sent_ids = {}
            for i in range(6):
                buf = TensorBuffer.of(
                    np.full((8, 1), float(i), np.float32), pts=i)
                sent_ids[i] = ensure_trace_ctx(buf.meta)["id"]
                pipe.get("src").push(buf)
            pipe.get("src").end()
            rn.wait(60)
            st = rn.stats()
            rn.stop()
            res = pipe.get("sink").results
            assert [r.pts for r in res] == list(range(6))
            assert st["qc"]["query_busy"] >= 1   # else test is vacuous
            retried_frames = 0
            for r in res:
                ctx = get_trace_ctx(r.meta)
                assert ctx is not None, f"pts={r.pts} lost its context"
                # the invariant under test: id survives the retry
                assert ctx["id"] == sent_ids[int(r.pts)]
                hop_names = [h["hop"] for h in ctx["hops"]]
                assert hop_names.count("client_send") >= 1
                assert "reply" in hop_names
                spans = hop_spans(ctx["hops"])
                if spans.get("retries"):
                    retried_frames += 1
            # at least one frame was BUSY-retried and its timeline
            # shows it as extra client_send hops on ONE id
            assert retried_frames >= 1
            assert not srv.crashed()
        finally:
            srv.stop()

    def test_open_loop_trace_reports_hop_breakdown(self):
        r = run_against_echo(pattern="poisson", load_x=0.5, n=30,
                             service_ms=4.0, max_pending=16, seed=2,
                             trace=True)
        assert r["lost"] == 0
        assert r["traced_replies"] == r["completed"]
        hb = r["hop_breakdown"]
        assert len(hb["trace_id"]) == 16
        assert hb["hops"][0] == "client_send"
        assert hb["hops"][-1] == "client_recv"
        spans = hb["spans"]
        # echo server: admission + service + reply stages must resolve
        assert "admission_wait_ms" in spans
        assert spans["total_ms"] == pytest.approx(
            hb["latency_ms"], rel=0.05, abs=1.0)

    def test_untraced_run_carries_no_ctx(self):
        # tracing stays strictly opt-in: without trace=True nothing in
        # the serving path invents a context (the stamp sites are
        # no-ops), so the known-capacity numbers stay comparable
        r = run_against_echo(pattern="poisson", load_x=0.5, n=20,
                             service_ms=4.0, max_pending=16, seed=2)
        assert "hop_breakdown" not in r
        assert "traced_replies" not in r
