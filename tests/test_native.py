"""Native runtime library tests: shm ring, wire validator, IPC elements.

Builds are a test prerequisite (`make -C native`); tests skip with an
actionable message when the library is absent.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu import native
from nnstreamer_tpu.edge.wire import decode_buffer, encode_buffer
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason="native library not built — run `make -C native`")


_ring_counter = [0]


def _ring_name():
    _ring_counter[0] += 1
    return f"/nnstpu-test-{os.getpid()}-{_ring_counter[0]}"


def test_ring_frame_roundtrip():
    name = _ring_name()
    prod = native.ShmRing(name, create=True, capacity=1 << 16)
    try:
        cons = native.ShmRing(name, create=False)
        prod.write(b"hello")
        prod.write(b"world" * 100)
        assert cons.read(1000) == b"hello"
        assert cons.read(1000) == b"world" * 100
        assert cons.read(timeout_ms=50) is None  # empty → timeout
        cons.close()
    finally:
        prod.close()


def test_ring_wraparound_and_backpressure():
    name = _ring_name()
    prod = native.ShmRing(name, create=True, capacity=1 << 12)  # 4 KiB min
    try:
        cons = native.ShmRing(name, create=False)
        payload = bytes(range(256)) * 4  # 1 KiB
        # push/pull more than capacity total to force wraparound
        for i in range(16):
            prod.write(payload)
            got = cons.read(1000)
            assert got == payload, f"iteration {i}"
        # backpressure: fill until a write would block, expect timeout error
        writes = 0
        with pytest.raises(Exception, match="full|stalled"):
            for _ in range(10):
                prod.write(payload, timeout_ms=100)
                writes += 1
        assert writes >= 2  # a few fit before the ring filled
        cons.close()
    finally:
        prod.close()


def test_ring_eos():
    name = _ring_name()
    prod = native.ShmRing(name, create=True, capacity=1 << 14)
    try:
        cons = native.ShmRing(name, create=False)
        prod.write(b"last")
        prod.close_write()
        assert cons.read(1000) == b"last"  # drains before EOF
        with pytest.raises(EOFError):
            cons.read(1000)
        cons.close()
    finally:
        prod.close()


def test_native_wire_validator_agrees_with_python():
    buf = TensorBuffer.of(np.arange(6, dtype=np.float32).reshape(2, 3),
                          np.array([1, 2], np.uint8), pts=5)
    frame = encode_buffer(buf, client_id=7)
    assert native.wire_frame_size(frame) == len(frame)
    # truncation → incomplete (0), never a bogus success
    for cut in (4, 20, len(frame) - 1):
        assert native.wire_frame_size(frame[:cut]) == 0
    # corrupt magic → -1
    bad = b"XXXX" + frame[4:]
    assert native.wire_frame_size(bad) == -1


def test_ipc_elements_pipeline_roundtrip():
    from nnstreamer_tpu.elements.ipc import IpcSink, IpcSrc
    from nnstreamer_tpu.elements import AppSrc, TensorSink

    name = _ring_name()
    spec = TensorsSpec.of(TensorInfo((2, 2), DType.FLOAT32))

    # producer pipeline
    psrc = AppSrc(spec=spec, name="psrc")
    isink = IpcSink(name="isink", ring=name)
    ppipe = nns.Pipeline("prod")
    ppipe.add(psrc)
    ppipe.add(isink)
    ppipe.link(psrc, isink)
    prunner = nns.PipelineRunner(ppipe).start()

    # consumer pipeline (sniffs spec from frame 1)
    isrc = IpcSrc(name="isrc", ring=name)
    sink = TensorSink(name="s")
    cpipe = nns.Pipeline("cons")
    cpipe.add(isrc)
    cpipe.add(sink)
    cpipe.link(isrc, sink)

    for i in range(4):
        psrc.push(TensorBuffer.of(np.full((2, 2), i, np.float32), pts=i))
    crunner = nns.PipelineRunner(cpipe).start()
    psrc.end()
    prunner.wait(30)
    crunner.wait(30)
    assert isrc.out_specs[0].tensors[0].shape == (2, 2)
    vals = [float(r.tensors[0][0, 0]) for r in sink.results]
    assert vals == [0.0, 1.0, 2.0, 3.0]
    assert all(r.pts == i for i, r in enumerate(sink.results))


def test_ipc_cross_process():
    """True cross-process IPC: a subprocess produces, we consume."""
    name = _ring_name()
    child = subprocess.Popen(
        [sys.executable, "-c", f"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
import numpy as np
import time
from nnstreamer_tpu.native import ShmRing
from nnstreamer_tpu.edge.wire import encode_buffer
from nnstreamer_tpu.tensor.buffer import TensorBuffer
ring = ShmRing({name!r}, create=True, capacity=1<<16)
time.sleep(0.3)  # let the parent open it... parent retries anyway
for i in range(5):
    ring.write(encode_buffer(TensorBuffer.of(np.full((3,), i, np.float32), pts=i)))
ring.close_write()
time.sleep(1.0)  # keep segment alive while parent drains
ring.close()
"""],
    )
    try:
        ring = None
        for _ in range(100):  # wait for the child to create the segment
            try:
                ring = native.ShmRing(name, create=False)
                break
            except Exception:
                time.sleep(0.05)
        assert ring is not None, "child never created the ring"
        got = []
        while True:
            try:
                frame = ring.read(timeout_ms=500)
            except EOFError:
                break
            if frame is None:
                continue
            buf, _ = decode_buffer(frame)
            got.append(float(buf.tensors[0][0]))
        assert got == [0.0, 1.0, 2.0, 3.0, 4.0]
        ring.close()
    finally:
        child.wait(15)
