"""Continuous-batching LLM serving (nnstreamer_tpu/llm, tensor_llm).

The gate that matters: paged decode must equal `transformer.generate`
token-for-token at temperature 0 — the paged formulation (gathered KV,
per-row positions, scratch-block padding) is only a serving layout
change, never a numerics change. Around it: block-allocator
invariants, admission under a full pool (queue, never crash), EOS /
max-token retirement returning blocks, the manifest round-trip for LLM
buckets, and the tier-1 smoke pushing concurrent requests through the
tensor_llm element.
"""

import time

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.elements import AppSrc, TensorLLM, TensorSink
from nnstreamer_tpu.llm import BlockAllocator, LLMEngine
from nnstreamer_tpu.models.transformer import generate, init_params
from nnstreamer_tpu.serving.store import get_store, reset_store
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorFormat, TensorsSpec


@pytest.fixture(scope="module")
def params():
    return init_params(vocab=61, d_model=32, n_layers=2, n_heads=4,
                       n_kv_heads=2, seed=0)


@pytest.fixture(scope="module")
def engine(params):
    """Shared continuous engine (module scope amortizes jit compiles)."""
    return LLMEngine(params, n_heads=4, block_size=4, num_blocks=32,
                     max_batch=4, max_len=64)


def _ref(params, prompt, n):
    return np.asarray(
        generate(params, np.asarray(prompt)[None, :], n,
                 n_heads=4, max_len=64))[0, len(prompt):]


# -- block allocator ---------------------------------------------------------

def test_allocator_alloc_free_invariants():
    a = BlockAllocator(8)            # 1 scratch + 7 usable
    assert a.total == 7 and a.free == 7 and a.used == 0
    got = a.alloc(3, owner="r1")
    assert len(got) == 3 and 0 not in got        # scratch never granted
    assert a.used == 3 and a.high_water == 3
    # all-or-nothing: 5 > 4 free -> None, nothing consumed
    assert a.alloc(5) is None
    assert a.free == 4 and a.failed_allocs == 1
    a.free_blocks(got)
    assert a.free == 7 and a.used == 0
    assert a.high_water == 3                     # high-water sticks
    # freed blocks are reusable
    again = a.alloc(7)
    assert sorted(set(again)) == sorted(again) and len(again) == 7


def test_allocator_double_free_raises():
    a = BlockAllocator(4)
    got = a.alloc(2)
    a.free_blocks(got)
    with pytest.raises(ValueError):
        a.free_blocks(got)
    with pytest.raises(ValueError):
        a.free_blocks([0])           # scratch was never granted


def test_allocator_rejects_degenerate_pool():
    with pytest.raises(ValueError):
        BlockAllocator(1)            # scratch only: nothing allocatable


def test_allocator_stats_utilization():
    a = BlockAllocator(11)
    a.alloc(5)
    s = a.stats()
    assert s["blocks_total"] == 10 and s["blocks_used"] == 5
    assert s["utilization"] == 0.5


# -- manifest round-trip -----------------------------------------------------

def test_llm_bucket_manifest_roundtrip():
    from nnstreamer_tpu.serving.compile_cache import (
        _bucket_from_json, _bucket_to_json)

    for bk in (("llmp", 16), ("llmd", 4), ("llmp_chunk", 32)):
        jb = _bucket_to_json(bk)
        assert jb is not None
        assert _bucket_from_json(jb) == bk
    # the existing kinds still round-trip (no regression)
    fix = ("fix", ((1, 3), "float32"))
    assert _bucket_from_json(_bucket_to_json(fix)) == fix


# -- decode parity vs transformer.generate -----------------------------------

def test_paged_parity_single_request(engine, params):
    prompt = np.array([5, 17, 3], np.int32)
    req = engine.submit(prompt, max_new_tokens=8)
    engine.drain()
    assert req.finish_reason == "length"
    assert np.array_equal(np.array(req.tokens), _ref(params, prompt, 8))


def test_paged_parity_interleaved_lengths(engine, params):
    """Concurrent requests with different prompt lengths interleave in
    one continuous batch; each stream must still match its own
    single-sequence generate() bit-for-bit."""
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 61, size=n).astype(np.int32)
               for n in (1, 4, 7, 11)]
    reqs = [engine.submit(p, max_new_tokens=6) for p in prompts]
    engine.drain()
    for p, r in zip(prompts, reqs):
        assert np.array_equal(np.array(r.tokens), _ref(params, p, 6)), \
            f"plen={len(p)}"
    # every retirement returned its blocks
    assert engine.cache.allocator.used == 0


def test_paged_parity_staggered_admission(engine, params):
    """A request admitted mid-flight (merged into a running decode
    batch) produces the same tokens as one served alone."""
    a = engine.submit(np.array([9, 2, 40, 11], np.int32),
                      max_new_tokens=10)
    engine.step()                    # a is prefilled + decoding
    b = engine.submit(np.array([33, 1], np.int32), max_new_tokens=5)
    engine.drain()
    assert np.array_equal(np.array(a.tokens),
                          _ref(params, a.prompt, 10))
    assert np.array_equal(np.array(b.tokens),
                          _ref(params, b.prompt, 5))


# -- admission / retirement --------------------------------------------------

def test_admission_queues_when_pool_full(params):
    """More requests than the pool can hold: latecomers queue (never
    crash) and complete as retirements free blocks."""
    eng = LLMEngine(params, n_heads=4, block_size=4, num_blocks=8,
                    max_batch=8, max_len=16)
    # each request needs ceil((2+6)/4)=2 blocks; pool has 7 usable ->
    # at most 3 resident; 6 requests => queueing is guaranteed
    reqs = [eng.submit(np.array([i + 1, i + 2], np.int32),
                       max_new_tokens=6) for i in range(6)]
    eng.drain()
    assert all(r.finish_reason == "length" for r in reqs)
    assert all(len(r.tokens) == 6 for r in reqs)
    assert eng.admission_blocked > 0
    assert eng.cache.allocator.failed_allocs > 0
    assert eng.cache.allocator.used == 0
    for r in reqs:                   # queueing must not corrupt streams
        assert np.array_equal(
            np.array(r.tokens),
            np.asarray(generate(params, r.prompt[None, :], 6,
                                n_heads=4, max_len=16))[0, 2:])


def test_submit_rejects_unservable_request(params):
    eng = LLMEngine(params, n_heads=4, block_size=4, num_blocks=8,
                    max_batch=2, max_len=16)
    with pytest.raises(BackendError):
        eng.submit(np.arange(10, dtype=np.int32), max_new_tokens=20)
    with pytest.raises(BackendError):
        eng.submit(np.array([], np.int32))
    with pytest.raises(BackendError):
        eng.submit(np.array([1], np.int32), max_new_tokens=0)


def test_eos_retires_and_frees_blocks(engine, params):
    """Run once to learn a token the model actually emits, then rerun
    with that token as eos_id: the request must stop AT the eos token
    and return its blocks."""
    prompt = np.array([12, 30], np.int32)
    probe = engine.submit(prompt, max_new_tokens=8)
    engine.drain()
    eos = probe.tokens[3]            # a token known to appear mid-stream
    req = engine.submit(prompt, max_new_tokens=8, eos_id=eos)
    engine.drain()
    assert req.finish_reason == "eos"
    assert req.tokens[-1] == eos
    assert len(req.tokens) == probe.tokens.index(eos) + 1
    assert engine.cache.allocator.used == 0


def test_static_batching_runs_to_completion(params):
    """static mode: nothing is admitted while a batch is in flight; the
    tokens still match generate()."""
    eng = LLMEngine(params, n_heads=4, block_size=4, num_blocks=32,
                    max_batch=2, max_len=64, static_batching=True)
    reqs = [eng.submit(np.array([7 * (i + 1)], np.int32),
                       max_new_tokens=4) for i in range(3)]
    eng.step()                       # admits exactly max_batch
    assert len(eng.active) == 2 and len(eng.queue) == 1
    eng.step()
    assert len(eng.queue) == 1       # no top-up mid-batch
    eng.drain()
    for r in reqs:
        assert np.array_equal(np.array(r.tokens),
                              _ref(params, r.prompt, 4))


# -- store integration -------------------------------------------------------

def test_store_hot_swap_adopts_new_weights(params):
    """tensor_llm's executor rides the model-store epoch contract: after
    update(), the next step serves the new version's weights."""
    reset_store()
    try:
        store = get_store()
        from nnstreamer_tpu.backends.xla import ModelBundle

        p2 = init_params(vocab=61, d_model=32, n_layers=2, n_heads=4,
                         n_kv_heads=2, seed=9)
        store.register("llm_swap_t", ModelBundle(fn=None, params=params))
        eng = LLMEngine("store://llm_swap_t", n_heads=4, block_size=4,
                        num_blocks=32, max_batch=4, max_len=64)
        prompt = np.array([3, 44, 8], np.int32)
        r1 = eng.submit(prompt, max_new_tokens=5)
        eng.drain()
        assert np.array_equal(np.array(r1.tokens), _ref(params, prompt, 5))
        store.register("llm_swap_t", ModelBundle(fn=None, params=p2))
        store.update("llm_swap_t")
        r2 = eng.submit(prompt, max_new_tokens=5)
        eng.drain()
        assert eng.executor.swap_count == 1
        assert np.array_equal(np.array(r2.tokens), _ref(p2, prompt, 5))
    finally:
        reset_store()


def test_tracer_records_llm_requests(params):
    from nnstreamer_tpu.runtime.tracing import Tracer

    tr = Tracer()
    eng = LLMEngine(params, n_heads=4, block_size=4, num_blocks=32,
                    max_batch=4, max_len=64, tracer=tr, name="e")
    eng.submit(np.array([1, 2], np.int32), max_new_tokens=3)
    eng.drain()
    recs = tr.llm_requests()
    assert len(recs) == 1
    name, req_id, t, args = recs[0]
    assert name == "e" and args["n_tokens"] == 3
    assert args["first_token_ms"] is not None
    assert tr.summary()["llm_requests"] == 1


# -- tensor_llm element (tier-1 smoke) ---------------------------------------

def _llm_pipeline(params, **llm_props):
    reset_store()
    from nnstreamer_tpu.backends.xla import ModelBundle

    get_store().register("llm_el_t", ModelBundle(fn=None, params=params))
    src = AppSrc(name="src", spec=TensorsSpec(
        tensors=(), format=TensorFormat.FLEXIBLE))
    llm = TensorLLM(name="llm", model="store://llm_el_t", block_size=4,
                    num_blocks=32, max_batch=4, max_len=64, **llm_props)
    sink = TensorSink(name="sink")
    pipe = nns.Pipeline()
    for e in (src, llm, sink):
        pipe.add(e)
    pipe.link(src, llm)
    pipe.link(llm, sink)
    return pipe, src, llm, sink


def test_tensor_llm_smoke_concurrent_requests(params):
    """Tier-1 smoke: 4 concurrent requests through the element; every
    request terminates with exactly its token budget, streamed
    incrementally, matching generate()."""
    budgets = {"r0": 3, "r1": 6, "r2": 2, "r3": 5}
    pipe, src, llm, sink = _llm_pipeline(params)
    runner = nns.PipelineRunner(pipe)
    runner.start()
    try:
        rng = np.random.default_rng(11)
        prompts = {}
        for rid, budget in budgets.items():
            p = rng.integers(0, 61, size=int(rng.integers(1, 9))) \
                .astype(np.int32)
            prompts[rid] = p
            src.push(TensorBuffer(
                tensors=(p,), pts=0,
                meta={"llm": {"request_id": rid,
                              "max_new_tokens": budget}}))
        src.end()
        runner.wait(120)
    finally:
        runner.stop()
    got = {}
    finals = {}
    for b in sink.results:
        m = b.meta["llm"]
        got.setdefault(m["request_id"], []).extend(
            int(t) for t in np.asarray(b.tensors[0]))
        if m["done"]:
            finals[m["request_id"]] = m
    assert set(got) == set(budgets)
    for rid, budget in budgets.items():
        assert len(got[rid]) == budget, rid
        assert finals[rid]["finish_reason"] == "length"
        assert np.array_equal(np.array(got[rid]),
                              _ref(params, prompts[rid], budget))
    stats = llm.extra_stats()
    assert stats["finished"] == 4
    assert stats["cache"]["blocks_used"] == 0
    reset_store()


def test_tensor_llm_element_properties_registered():
    from nnstreamer_tpu.core.registry import PluginKind, registry

    cls = registry.get(PluginKind.ELEMENT, "tensor_llm")
    assert cls is TensorLLM
    for prop in ("model", "scheduling", "block_size", "num_blocks",
                 "max_batch", "max_new_tokens", "admit_window_ms",
                 "paged_kernel", "prefill_chunk"):
        assert prop in cls.PROPS


def test_tensor_llm_pallas_chunked_matches_generate(params):
    """Element-level twin of the smoke test with the Pallas kernel and
    chunked prefill enabled: tokens are identical to generate() and the
    executor reports pallas invokes with no fallback."""
    budgets = {"p0": 4, "p1": 3}
    pipe, src, llm, sink = _llm_pipeline(
        params, paged_kernel="pallas", prefill_chunk=4)
    runner = nns.PipelineRunner(pipe)
    runner.start()
    try:
        rng = np.random.default_rng(23)
        prompts = {}
        for rid, budget in budgets.items():
            p = rng.integers(0, 61, size=int(rng.integers(5, 12))) \
                .astype(np.int32)
            prompts[rid] = p
            src.push(TensorBuffer(
                tensors=(p,), pts=0,
                meta={"llm": {"request_id": rid,
                              "max_new_tokens": budget}}))
        src.end()
        runner.wait(120)
    finally:
        runner.stop()
    got = {}
    for b in sink.results:
        m = b.meta["llm"]
        got.setdefault(m["request_id"], []).extend(
            int(t) for t in np.asarray(b.tensors[0]))
    for rid, budget in budgets.items():
        assert np.array_equal(np.array(got[rid]),
                              _ref(params, prompts[rid], budget)), rid
    stats = llm.extra_stats()
    ex = stats["executor"]
    assert ex["paged_kernel"] == "pallas"
    assert ex["kernel_invokes"]["pallas"] > 0
    assert ex["kernel_fallback"] == 0
    reset_store()


@pytest.mark.slow
def test_tensor_llm_open_loop_arrivals(params):
    """Open-loop Poisson arrivals through the element (the llm_serve
    bench family's shape, scaled down): every request completes and
    continuous batching keeps the pool bounded."""
    pipe, src, llm, sink = _llm_pipeline(params, prewarm=8)
    runner = nns.PipelineRunner(pipe)
    runner.start()
    try:
        rng = np.random.default_rng(5)
        arrivals = np.cumsum(rng.exponential(0.01, size=10))
        t0 = time.perf_counter()
        for i, t_arr in enumerate(arrivals):
            dt = t_arr - (time.perf_counter() - t0)
            if dt > 0:
                time.sleep(dt)
            src.push(TensorBuffer(
                tensors=(rng.integers(0, 61, size=3).astype(np.int32),),
                pts=i, meta={"llm": {"request_id": f"q{i}",
                                     "max_new_tokens": 4}}))
        src.end()
        runner.wait(120)
    finally:
        runner.stop()
    done = [b.meta["llm"] for b in sink.results if b.meta["llm"]["done"]]
    assert len(done) == 10
    stats = llm.extra_stats()
    assert stats["cache"]["blocks_high_water"] <= \
        stats["cache"]["blocks_total"]
    reset_store()
