"""Flexible-shape inference + shared device-param table (VERDICT r1
items 4 & 5).

- invoke-dynamic: FLEXIBLE streams (tensor_crop regions) through
  tensor_filter with batch-stacked, bucketed, bounded recompiles —
  compile-count assertions prove the bucketing policy.
- shared-tensor-filter-key: N filters on one model hold ONE device
  params copy; hot reload through one holder propagates to all
  (reference tensor_filter_common.c:2911-3046).
"""

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.backends.xla import ModelBundle, XLABackend, _shared_models
from nnstreamer_tpu.core.errors import NegotiationError, PipelineError
from nnstreamer_tpu.elements import AppSrc, TensorCrop, TensorSink
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorFormat

from test_elements import run_graph, spec_of


def _poly_sum_bundle():
    """Shape-polymorphic, padding-invariant toy model: spatial sum →
    fixed 5-dim projection. Zero-padding spatial dims does not change
    the output, so bucket padding is exactly testable."""
    import jax.numpy as jnp

    W = np.linspace(-1, 1, 3 * 5, dtype=np.float32).reshape(3, 5)

    def fn(params, x):
        s = jnp.sum(x.astype(jnp.float32), axis=(-3, -2))   # (..., C)
        return s @ params["w"]

    return ModelBundle(fn=fn, params={"w": W}, name="poly_sum")


def _regions(shapes, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 255, s).astype(np.uint8) for s in shapes]


def _flex_buf(regions, pts=0):
    return TensorBuffer(tensors=tuple(regions), pts=pts,
                        format=TensorFormat.FLEXIBLE)


# -- backend-level: bucketing policy ----------------------------------------

def test_invoke_flexible_batches_same_shape_regions():
    be = XLABackend()
    be.open({"model": _poly_sum_bundle(), "custom": ""})
    regions = _regions([(1, 8, 8, 3)] * 3)
    out = be.invoke_flexible(list(regions))
    assert len(out) == 3
    # one batched compile for the whole same-shape group
    assert be.compile_count == 1
    for r, o in zip(regions, out):
        expect = r.astype(np.float32).sum((1, 2)) @ np.linspace(
            -1, 1, 15, dtype=np.float32).reshape(3, 5)
        np.testing.assert_allclose(np.asarray(o), expect, rtol=1e-5)
    # 2 regions of the same shape: batch bucket 2 ⇒ new compile;
    # repeating either count reuses the cache
    be.invoke_flexible(list(_regions([(1, 8, 8, 3)] * 2)))
    assert be.compile_count == 2
    be.invoke_flexible(list(_regions([(1, 8, 8, 3)] * 4)))
    be.invoke_flexible(list(_regions([(1, 8, 8, 3)] * 3)))
    assert be.compile_count == 2  # 3 pads into the 4-bucket


def test_invoke_flexible_spatial_bucketing():
    be = XLABackend()
    be.open({"model": _poly_sum_bundle(),
             "custom": "dynamic_spatial=true"})
    # 20x30 and 25x31 both bucket to 32x32 ⇒ ONE compile
    be.invoke_flexible(_regions([(1, 20, 30, 3)]))
    n0 = be.compile_count
    be.invoke_flexible(_regions([(1, 25, 31, 3)]))
    assert be.compile_count == n0
    # 50x60 buckets to 64x64 ⇒ one more
    be.invoke_flexible(_regions([(1, 50, 60, 3)]))
    assert be.compile_count == n0 + 1
    # padding-invariant model ⇒ padded result equals direct eval
    r = _regions([(1, 17, 9, 3)], seed=3)[0]
    (o,) = be.invoke_flexible([r])
    expect = r.astype(np.float32).sum((1, 2)) @ np.linspace(
        -1, 1, 15, dtype=np.float32).reshape(3, 5)
    np.testing.assert_allclose(np.asarray(o), expect, rtol=1e-5)


def test_invoke_flexible_cache_is_bounded():
    be = XLABackend()
    be.open({"model": _poly_sum_bundle(),
             "custom": "dynamic_spatial=true"})
    be._dyn_cache_max = 2
    shapes = [(1, 20, 20, 3), (1, 50, 50, 3), (1, 100, 100, 3)]
    for s in shapes:
        be.invoke_flexible(_regions([s]))
    n = be.compile_count
    assert len(be._dyn_jits) <= 2
    # the oldest bucket was evicted ⇒ revisiting it recompiles
    be.invoke_flexible(_regions([shapes[0]]))
    assert be.compile_count == n + 1


def test_invoke_flexible_sequential_fallback_for_fixed_batch_model():
    """A model with a baked-in batch (shape-checked) can't be stacked:
    the eval_shape probe fails and regions run one-by-one."""
    import jax.numpy as jnp

    def rigid(params, x):
        assert x.shape[0] == 1, "batch is baked in"
        return jnp.sum(x.astype(jnp.float32), axis=(1, 2))

    be = XLABackend()
    be.open({"model": ModelBundle(fn=rigid, params=None), "custom": ""})
    out = be.invoke_flexible(list(_regions([(1, 4, 4, 3)] * 3)))
    assert len(out) == 3 and np.asarray(out[0]).shape == (1, 3)


# -- pipeline-level: crop → filter (invoke-dynamic) --------------------------

def test_crop_filter_invoke_dynamic_pipeline():
    raw_spec = spec_of((1, 16, 16, 3), dtype=DType.UINT8)
    src = AppSrc(spec=raw_spec, name="raw")
    info = AppSrc(spec=spec_of((2, 4), dtype=DType.UINT32), name="info")
    crop = TensorCrop(name="c")
    filt = TensorFilter(name="f", model=_poly_sum_bundle(),
                        invoke_dynamic="true",
                        custom="dynamic_spatial=true")
    sink = TensorSink(name="s")
    img = np.arange(16 * 16 * 3, dtype=np.uint8).reshape(1, 16, 16, 3)
    regions = np.array([[2, 1, 4, 3], [0, 0, 8, 8]], np.uint32)
    pipe = run_graph(
        [src, info, crop, filt, sink],
        [(src, crop, 0, 0), (info, crop, 0, 1), (crop, filt), (filt, sink)],
        {"raw": [TensorBuffer.of(img, pts=0)],
         "info": [TensorBuffer.of(regions, pts=0)]})
    out = pipe.get("s").results[0]
    assert out.format == TensorFormat.FLEXIBLE
    assert len(out.tensors) == 2
    W = np.linspace(-1, 1, 15, dtype=np.float32).reshape(3, 5)
    for (x, y, w, h), o in zip(regions, out.tensors):
        patch = img[:, y:y + h, x:x + w]
        np.testing.assert_allclose(
            np.asarray(o), patch.astype(np.float32).sum((1, 2)) @ W,
            rtol=1e-5)


def test_crop_resize_filter_static_pipeline():
    """The semantic fixed-model path: crop → tensor_resize → filter."""
    from nnstreamer_tpu.backends.custom import register_custom_easy

    register_custom_easy("mean8", lambda ts: (
        np.asarray(ts[0], np.float32).mean(axis=(0, 1), keepdims=False)[None],))
    raw_spec = spec_of((16, 16, 3), dtype=DType.UINT8)
    src = AppSrc(spec=raw_spec, name="raw")
    info = AppSrc(spec=spec_of((2, 4), dtype=DType.UINT32), name="info")
    crop = TensorCrop(name="c")
    from nnstreamer_tpu.elements.transform import TensorResize

    rs = TensorResize(name="r", size="8:8", channels=3)
    filt = TensorFilter(name="f", framework="custom", model="mean8")
    sink = TensorSink(name="s")
    img = np.arange(16 * 16 * 3, dtype=np.uint8).reshape(16, 16, 3)
    regions = np.array([[0, 0, 8, 8], [4, 4, 12, 12]], np.uint32)
    pipe = run_graph(
        [src, info, crop, rs, filt, sink],
        [(src, crop, 0, 0), (info, crop, 0, 1), (crop, rs), (rs, filt),
         (filt, sink)],
        {"raw": [TensorBuffer.of(img, pts=0)],
         "info": [TensorBuffer.of(regions, pts=0)]})
    res = pipe.get("s").results
    assert len(res) == 2  # one STATIC buffer per region
    assert res[0].meta["num_regions"] == 2


def test_flexible_without_invoke_dynamic_fails_actionably():
    raw_spec = spec_of((1, 8, 8, 3), dtype=DType.UINT8)
    src = AppSrc(spec=raw_spec, name="raw")
    info = AppSrc(spec=spec_of((1, 4), dtype=DType.UINT32), name="info")
    crop = TensorCrop(name="c")
    filt = TensorFilter(name="f", model=_poly_sum_bundle())
    sink = TensorSink(name="s")
    pipe = nns.Pipeline()
    for e in (src, info, crop, filt, sink):
        pipe.add(e)
    pipe.link(src, crop, 0, 0)
    pipe.link(info, crop, 0, 1)
    pipe.link(crop, filt)
    pipe.link(filt, sink)
    with pytest.raises((NegotiationError, PipelineError),
                       match="invoke.dynamic|tensor_resize"):
        nns.PipelineRunner(pipe).start()


# -- shared device-param table ----------------------------------------------

def test_shared_key_dedupes_device_params():
    _shared_models.clear()
    b1 = XLABackend()
    b2 = XLABackend()
    bundle = _poly_sum_bundle()
    b1.open({"model": bundle, "shared_tensor_filter_key": "k1"})
    b2.open({"model": bundle, "shared_tensor_filter_key": "k1"})
    # literally the same device arrays (one HBM copy)
    assert b1._current_params()["w"] is b2._current_params()["w"]
    x = np.ones((1, 4, 4, 3), np.uint8)
    np.testing.assert_allclose(np.asarray(b1.invoke((x,))[0]),
                               np.asarray(b2.invoke((x,))[0]))
    b1.close()
    assert "k1" in _shared_models      # still held by b2
    b2.close()
    assert "k1" not in _shared_models  # refcount reached zero


def test_shared_key_reload_propagates_to_all_holders():
    _shared_models.clear()
    b1, b2 = XLABackend(), XLABackend()
    b1.open({"model": _poly_sum_bundle(), "shared_tensor_filter_key": "k2"})
    b2.open({"model": _poly_sum_bundle(), "shared_tensor_filter_key": "k2"})
    x = np.ones((1, 2, 2, 3), np.uint8)
    before = np.asarray(b2.invoke((x,))[0])

    import jax.numpy as jnp

    swapped = ModelBundle(
        fn=lambda p, t: jnp.sum(t.astype(jnp.float32), axis=(1, 2)) @ p["w"],
        params={"w": np.zeros((3, 5), np.float32)}, name="zeros")
    b1.reload(swapped)
    after = np.asarray(b2.invoke((x,))[0])   # holder b2 sees the swap
    assert not np.allclose(before, after)
    np.testing.assert_allclose(after, 0.0)
    b1.close()
    b2.close()


def test_pipeline_two_filters_share_one_model():
    _shared_models.clear()
    bundle = _poly_sum_bundle()
    src = AppSrc(spec=spec_of((1, 4, 4, 3), dtype=DType.UINT8), name="a")
    from nnstreamer_tpu.elements import Tee

    tee = Tee(name="t")
    f1 = TensorFilter(name="f1", model=bundle,
                      shared_tensor_filter_key="pk")
    f2 = TensorFilter(name="f2", model=bundle,
                      shared_tensor_filter_key="pk")
    s1, s2 = TensorSink(name="s1"), TensorSink(name="s2")
    x = np.ones((1, 4, 4, 3), np.uint8)
    pipe = run_graph(
        [src, tee, f1, f2, s1, s2],
        [(src, tee), (tee, f1), (tee, f2), (f1, s1), (f2, s2)],
        {"a": [TensorBuffer.of(x, pts=0)]})
    p1 = pipe.get("f1").backend._current_params()
    p2 = pipe.get("f2").backend._current_params()
    assert p1["w"] is p2["w"]
    np.testing.assert_allclose(np.asarray(pipe.get("s1").results[0].tensors[0]),
                               np.asarray(pipe.get("s2").results[0].tensors[0]))
