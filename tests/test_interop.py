"""Interop serialization + gRPC tests.

The load-bearing property here is *externality*: frames our codecs emit
must parse in a process that knows nothing about nnstreamer_tpu (only
the published schema / a stock flexbuffers or gRPC library), and frames
such a process emits must parse in ours. Reference analog:
tests/nnstreamer_converter_{protobuf,flexbuf}, nnstreamer_decoder_*,
nnstreamer_grpc (SURVEY.md §4).
"""

import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.interop.flatbuf_codec import decode_flatbuf, encode_flatbuf
from nnstreamer_tpu.interop.flexbuf_codec import decode_flexbuf, encode_flexbuf
from nnstreamer_tpu.interop.gst_meta import (
    pack_gst_meta, parse_gst_meta, shape_from_wire, wire_dims)
from nnstreamer_tpu.interop.protobuf_codec import (
    decode_protobuf, encode_protobuf)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorFormat

INTEROP_DIR = "nnstreamer_tpu/interop"


from conftest import free_port  # noqa: E402 (shared helper)


# -- GstTensorMetaInfo header -------------------------------------------------

def test_gst_meta_roundtrip_preserves_rank():
    for shape in [(7,), (3, 4), (1, 8, 8, 3), (2, 1, 1, 1, 5)]:
        hdr = pack_gst_meta(shape, DType.FLOAT32)
        assert len(hdr) == 128
        out_shape, dt, fmt, _, _, off = parse_gst_meta(hdr + b"payload")
        assert out_shape == shape
        assert dt == DType.FLOAT32 and off == 128


def test_gst_meta_rejects_garbage_and_zero_dims():
    with pytest.raises(StreamError, match="version"):
        parse_gst_meta(b"\x00" * 128)
    with pytest.raises(StreamError, match="zero"):
        pack_gst_meta((0, 3), DType.UINT8)
    with pytest.raises(StreamError, match="small"):
        parse_gst_meta(b"\xde\x00\x00\x00")


def test_wire_dims_convention():
    # innermost-first, 1-padded to rank 4 (reference pad convention)
    assert wire_dims((1, 224, 224, 3)) == [3, 224, 224, 1]
    assert wire_dims((5,)) == [5, 1, 1, 1]
    assert shape_from_wire([3, 224, 224, 1]) == (224, 224, 3)
    assert shape_from_wire([5, 1, 1, 1]) == (5,)


# -- codec roundtrips ---------------------------------------------------------

CODECS = [(encode_protobuf, decode_protobuf, "protobuf"),
          (encode_flexbuf, decode_flexbuf, "flexbuf"),
          (encode_flatbuf, decode_flatbuf, "flatbuf")]


@pytest.mark.parametrize("enc,dec,name", CODECS)
def test_static_roundtrip_multi_tensor(enc, dec, name):
    buf = TensorBuffer.of(
        np.arange(24, dtype=np.float32).reshape(2, 3, 4),
        np.arange(6, dtype=np.uint8),
        np.array([1.5, -2.5], np.float64))
    out = dec(enc(buf, rate=(30, 1)))
    assert out.num_tensors == 3 and out.format == TensorFormat.STATIC
    for got, want in zip(out.tensors, buf.tensors):
        np.testing.assert_array_equal(got, want)
        assert got.dtype == want.dtype


@pytest.mark.parametrize("enc,dec,name", CODECS)
def test_static_leading_one_dims_canonicalize(enc, dec, name):
    # rank is not on the static wire (fixed rank-4, 1-padded dims), so a
    # leading batch-1 dim canonicalizes away; FLEXIBLE preserves it
    buf = TensorBuffer.of(np.zeros((1, 2), np.float64))
    out = dec(enc(buf))
    assert out.tensors[0].shape == (2,)


@pytest.mark.parametrize("enc,dec,name", CODECS)
def test_flexible_roundtrip_preserves_exact_shape(enc, dec, name):
    # leading-1 rank would be lost in padded dims; the GstTensorMetaInfo
    # prefix must preserve it on FLEXIBLE streams
    buf = TensorBuffer.of(np.ones((1, 8, 8, 3), np.uint8),
                          format=TensorFormat.FLEXIBLE)
    out = dec(enc(buf))
    assert out.tensors[0].shape == (1, 8, 8, 3)
    assert out.format == TensorFormat.FLEXIBLE


@pytest.mark.parametrize("enc,dec,name", CODECS)
def test_tensor_names_travel(enc, dec, name):
    buf = TensorBuffer.of(np.zeros(3, np.int32))
    buf.meta["tensor_names"] = {0: "logits"}
    out = dec(enc(buf))
    assert out.meta["tensor_names"][0] == "logits"


@pytest.mark.parametrize("enc,dec,name", CODECS)
def test_bfloat16_rejected_with_typecast_hint(enc, dec, name):
    import ml_dtypes
    buf = TensorBuffer.of(np.zeros(4, dtype=ml_dtypes.bfloat16))
    with pytest.raises(StreamError, match="typecast"):
        enc(buf)


@pytest.mark.parametrize("dec", [decode_protobuf, decode_flexbuf,
                                 decode_flatbuf])
def test_corrupt_frames_rejected(dec):
    with pytest.raises(StreamError, match="corrupt|payload bytes"):
        dec(b"\xff" * 64)


def test_protobuf_payload_size_mismatch_rejected():
    from nnstreamer_tpu.interop import tensors_pb2 as pb
    msg = pb.Tensors(num_tensor=1)
    t = msg.tensor.add()
    t.type = int(DType.FLOAT32)
    t.dimension.extend([4, 1, 1, 1])
    t.data = b"\x00" * 7   # 4 floats need 16 bytes
    with pytest.raises(StreamError, match="payload bytes"):
        decode_protobuf(msg.SerializeToString())


# -- externality: a process that never imports nnstreamer_tpu ----------------

def _run_external(script: str, stdin: bytes = b"") -> bytes:
    """Run a python snippet with the repo OFF sys.path except the interop
    dir (for the generated pb2 module only)."""
    proc = subprocess.run(
        [sys.executable, "-c", textwrap.dedent(script)],
        input=stdin, capture_output=True, timeout=60, cwd="/tmp")
    assert proc.returncode == 0, proc.stderr.decode()
    return proc.stdout


def test_external_process_parses_our_protobuf_frames(tmp_path):
    buf = TensorBuffer.of(np.arange(12, dtype=np.float32).reshape(3, 4))
    frame = encode_protobuf(buf, rate=(30, 1))
    out = _run_external(f"""
        import sys
        sys.path.insert(0, {str(nns.__path__[0] + '/interop')!r})
        import numpy as np
        import tensors_pb2  # generated from the published schema only
        msg = tensors_pb2.Tensors()
        msg.ParseFromString(sys.stdin.buffer.read())
        assert msg.num_tensor == 1
        assert msg.fr.rate_n == 30 and msg.fr.rate_d == 1
        t = msg.tensor[0]
        assert list(t.dimension) == [4, 3, 1, 1]
        arr = np.frombuffer(t.data, np.float32)
        sys.stdout.buffer.write(arr.tobytes())
    """, stdin=frame)
    np.testing.assert_array_equal(
        np.frombuffer(out, np.float32).reshape(3, 4), buf.tensors[0])


def test_our_decoder_parses_external_protobuf_frames():
    frame = _run_external(f"""
        import sys
        sys.path.insert(0, {str(nns.__path__[0] + '/interop')!r})
        import numpy as np
        import tensors_pb2
        msg = tensors_pb2.Tensors(num_tensor=1)
        msg.fr.rate_n, msg.fr.rate_d = 15, 1
        t = msg.tensor.add()
        t.name = "ext"
        t.type = 7  # NNS_FLOAT32
        t.dimension.extend([2, 5, 1, 1])   # innermost-first
        t.data = np.arange(10, dtype=np.float32).tobytes()
        sys.stdout.buffer.write(msg.SerializeToString())
    """)
    out = decode_protobuf(frame)
    np.testing.assert_array_equal(
        out.tensors[0], np.arange(10, dtype=np.float32).reshape(5, 2))
    assert out.meta["tensor_names"][0] == "ext"


def test_external_process_parses_our_flexbuf_frames():
    buf = TensorBuffer.of(np.arange(6, dtype=np.uint8).reshape(2, 3))
    frame = encode_flexbuf(buf, rate=(10, 1))
    out = _run_external("""
        import sys
        from flatbuffers import flexbuffers  # stock library, no schema
        root = flexbuffers.GetRoot(bytearray(sys.stdin.buffer.read())).AsMap
        assert root["num_tensors"].AsInt == 1
        assert root["rate_n"].AsInt == 10
        vec = root["tensor_0"].AsVector
        assert [e.AsInt for e in vec[2].AsTypedVector] == [3, 2, 1, 1]
        sys.stdout.buffer.write(bytes(vec[3].AsBlob))
    """, stdin=frame)
    np.testing.assert_array_equal(
        np.frombuffer(out, np.uint8).reshape(2, 3), buf.tensors[0])


def test_our_converter_parses_external_flexbuf_frames():
    frame = _run_external("""
        import sys
        import numpy as np
        from flatbuffers import flexbuffers
        fbb = flexbuffers.Builder()
        with fbb.Map():
            fbb.Key("num_tensors"); fbb.UInt(1)
            fbb.Key("rate_n"); fbb.Int(0)
            fbb.Key("rate_d"); fbb.Int(1)
            fbb.Key("format"); fbb.Int(0)
            fbb.Key("tensor_0")
            with fbb.Vector():
                fbb.String(""); fbb.Int(5)  # NNS_UINT8
                fbb.TypedVectorFromElements([4, 2, 1, 1])
                fbb.Blob(np.arange(8, dtype=np.uint8).tobytes())
        sys.stdout.buffer.write(bytes(fbb.Finish()))
    """)
    out = decode_flexbuf(frame)
    np.testing.assert_array_equal(
        out.tensors[0], np.arange(8, dtype=np.uint8).reshape(2, 4))


# -- pipeline integration -----------------------------------------------------

@pytest.mark.parametrize("codec", ["protobuf", "flexbuf", "flatbuf"])
def test_pipeline_decoder_converter_roundtrip(codec):
    pipe = nns.parse_launch(
        f"appsrc name=in dims=3:4 types=float32 ! "
        f"tensor_decoder mode={codec} ! "
        f"tensor_converter mode=custom:{codec} ! tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    src = pipe.get("in")
    frames = [np.random.default_rng(i).standard_normal((4, 3)).astype(np.float32)
              for i in range(3)]
    for f in frames:
        src.push(TensorBuffer.of(f, pts=1000))
    src.end()
    runner.wait(30)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 3
    for got, want in zip(res, frames):
        np.testing.assert_array_equal(got.tensors[0], want)
        assert got.pts == 1000  # PTS survives the byte hop


# -- gRPC elements ------------------------------------------------------------

def _grpc_channel(port):
    import grpc
    from google.protobuf import empty_pb2
    from nnstreamer_tpu.interop import tensors_pb2 as pb
    chan = grpc.insecure_channel(f"127.0.0.1:{port}")
    grpc.channel_ready_future(chan).result(timeout=10)
    return chan, pb, empty_pb2


def test_grpc_sink_server_streams_to_external_client():
    port = free_port()
    pipe = nns.parse_launch(
        f"appsrc name=in dims=4:2 types=float32 ! "
        f"tensor_sink_grpc name=out port={port} server=true")
    runner = nns.PipelineRunner(pipe).start()
    chan, pb, empty_pb2 = _grpc_channel(port)
    recv = chan.unary_stream(
        "/nnstreamer.protobuf.TensorService/RecvTensors",
        request_serializer=empty_pb2.Empty.SerializeToString,
        response_deserializer=pb.Tensors.FromString)
    got = []
    stream = recv(empty_pb2.Empty())
    collector = threading.Thread(
        target=lambda: [got.append(m) for m in stream], daemon=True)
    collector.start()
    time.sleep(0.3)   # let the client subscribe before frames flow
    src = pipe.get("in")
    frames = [np.full((2, 4), i, np.float32) for i in range(4)]
    for f in frames:
        src.push(TensorBuffer.of(f))
    src.end()
    runner.wait(30)
    runner.stop()      # EOS closes client streams
    collector.join(timeout=10)
    chan.close()
    assert len(got) == 4
    arr = np.frombuffer(got[2].tensor[0].data, np.float32)
    np.testing.assert_array_equal(arr, np.full(8, 2, np.float32))


def test_grpc_src_server_accepts_external_client_stream():
    port = free_port()
    pipe = nns.parse_launch(
        f"tensor_src_grpc name=in port={port} server=true dims=4:2 "
        f"types=float32 ! tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    chan, pb, empty_pb2 = _grpc_channel(port)
    send = chan.stream_unary(
        "/nnstreamer.protobuf.TensorService/SendTensors",
        request_serializer=pb.Tensors.SerializeToString,
        response_deserializer=empty_pb2.Empty.FromString)

    def frames():
        for i in range(3):
            msg = pb.Tensors(num_tensor=1)
            t = msg.tensor.add()
            t.type = 7
            t.dimension.extend([4, 2, 1, 1])
            t.data = np.full((2, 4), i, np.float32).tobytes()
            yield msg

    send(frames())
    deadline = time.time() + 15
    sink = pipe.get("out")
    while len(sink.results) < 3 and time.time() < deadline:
        time.sleep(0.05)
    pipe.get("in").interrupt()
    runner.stop()
    chan.close()
    assert len(sink.results) == 3
    np.testing.assert_array_equal(
        sink.results[1].tensors[0], np.full((2, 4), 1, np.float32))


def test_grpc_pipeline_to_pipeline_bridge():
    """sink(client) --SendTensors--> src(server): two pipelines bridged
    over real gRPC, the reference's grpc loopback test shape."""
    port = free_port()
    recv_pipe = nns.parse_launch(
        f"tensor_src_grpc name=in port={port} server=true dims=3 "
        f"types=int32 ! tensor_sink name=out")
    recv_runner = nns.PipelineRunner(recv_pipe).start()

    send_pipe = nns.parse_launch(
        f"appsrc name=src dims=3 types=int32 ! "
        f"tensor_sink_grpc port={port} server=false")
    send_runner = nns.PipelineRunner(send_pipe).start()
    src = send_pipe.get("src")
    for i in range(5):
        src.push(TensorBuffer.of(np.array([i, i + 1, i + 2], np.int32)))
    src.end()
    send_runner.wait(30)

    sink = recv_pipe.get("out")
    deadline = time.time() + 15
    while len(sink.results) < 5 and time.time() < deadline:
        time.sleep(0.05)
    send_runner.stop()
    recv_pipe.get("in").interrupt()
    recv_runner.stop()
    assert len(sink.results) == 5
    np.testing.assert_array_equal(sink.results[4].tensors[0],
                                  np.array([4, 5, 6], np.int32))


def test_gst_meta_rejects_superset_tag_bytes():
    """0xFF/0xFE tags share all bits of 0xDE and must still be refused
    (mask-as-value bug regression)."""
    import struct
    for tag in (0xFF001000, 0xFE001000, 0xDF001000):
        hdr = bytearray(pack_gst_meta((3,), DType.UINT8))
        struct.pack_into("<I", hdr, 0, tag)
        with pytest.raises(StreamError, match="version"):
            parse_gst_meta(bytes(hdr))


def test_external_process_parses_our_flatbuf_frames():
    """An independent reader using only the stock flatbuffers Table API
    and the published nnstreamer.fbs slot layout parses our frames."""
    buf = TensorBuffer.of(np.arange(10, dtype=np.int16).reshape(5, 2))
    frame = encode_flatbuf(buf, rate=(24, 1))
    out = _run_external("""
        import sys
        import flatbuffers
        from flatbuffers import number_types as NT
        from flatbuffers.table import Table
        raw = bytearray(sys.stdin.buffer.read())
        root = flatbuffers.encode.Get(flatbuffers.packer.uoffset, raw, 0)
        tab = Table(raw, root)
        def slot(t, i): return t.Offset(4 + 2 * i)
        o = slot(tab, 0)
        assert tab.Get(NT.Int32Flags, o + tab.Pos) == 1       # num_tensor
        fo = slot(tab, 1)                                     # fr struct
        assert tab.Get(NT.Int32Flags, fo + tab.Pos) == 24     # rate_n
        assert tab.Get(NT.Int32Flags, fo + tab.Pos + 4) == 1  # rate_d
        vo = slot(tab, 2)
        x = tab.Vector(vo)
        ttab = Table(raw, tab.Indirect(x))
        to = slot(ttab, 1)
        assert ttab.Get(NT.Int32Flags, to + ttab.Pos) == 2    # NNS_INT16
        do = slot(ttab, 2)
        dims = [ttab.Get(NT.Uint32Flags, ttab.Vector(do) + k*4)
                for k in range(ttab.VectorLen(do))]
        assert dims == [2, 5, 1, 1]
        bo = slot(ttab, 3)
        s = ttab.Vector(bo)
        sys.stdout.buffer.write(bytes(raw[s:s + ttab.VectorLen(bo)]))
    """, stdin=frame)
    np.testing.assert_array_equal(
        np.frombuffer(out, np.int16).reshape(5, 2), buf.tensors[0])


@pytest.mark.parametrize("codec_name,enc,dec", [
    ("protobuf", encode_protobuf, decode_protobuf),
    ("flexbuf", encode_flexbuf, decode_flexbuf),
    ("flatbuf", encode_flatbuf, decode_flatbuf)])
def test_truncated_payload_raises_stream_error(codec_name, enc, dec):
    """A frame whose data vector claims more bytes than present must
    fail as StreamError (codec contract), not a raw numpy ValueError."""
    buf = TensorBuffer.of(np.ones((1, 8, 8, 3), np.uint8),
                          format=TensorFormat.FLEXIBLE)
    frame = bytearray(enc(buf))
    # chop the tail: header parses, payload short
    with pytest.raises(StreamError):
        dec(bytes(frame[:len(frame) // 2]))


# -- from-scratch flexbuffers reader vs the stock builder ---------------------

def test_flexbuf_read_matches_stock_builder():
    """interop/flexbuf_read.py (dependency-free) must decode buffers
    produced by the stock flatbuffers builder across the type zoo:
    nested maps/vectors, typed vectors, bools, floats, strings, blobs,
    indirect scalars — so custom-op options and flexbuf frames parse
    identically with or without the external package installed."""
    from flatbuffers import flexbuffers

    from nnstreamer_tpu.interop.flexbuf_read import flexbuf_loads

    fbb = flexbuffers.Builder()
    with fbb.Map():
        fbb.Key("i"); fbb.Int(-42)
        fbb.Key("u"); fbb.UInt(2 ** 40)          # forces 8-byte width
        fbb.Key("f"); fbb.Float(1.5)
        fbb.Key("b_true"); fbb.Bool(True)
        fbb.Key("b_false"); fbb.Bool(False)
        fbb.Key("s"); fbb.String("hello flex")
        fbb.Key("blob"); fbb.Blob(b"\x00\x01\xfe\xff")
        fbb.Key("tv"); fbb.TypedVectorFromElements([3, 1, 4, 1, 5])
        fbb.Key("vec")
        with fbb.Vector():
            fbb.Int(7)
            fbb.String("mixed")
            fbb.Float(0.25)
        fbb.Key("nested")
        with fbb.Map():
            fbb.Key("x"); fbb.Int(1)
            fbb.Key("y"); fbb.Float(-2.0)
    out = flexbuf_loads(bytes(fbb.Finish()))
    assert out == {
        "i": -42, "u": 2 ** 40, "f": 1.5,
        "b_true": True, "b_false": False,
        "s": "hello flex", "blob": b"\x00\x01\xfe\xff",
        "tv": [3, 1, 4, 1, 5],
        "vec": [7, "mixed", 0.25],
        "nested": {"x": 1, "y": -2.0},
    }
    assert isinstance(out["b_true"], bool) and isinstance(out["i"], int)


def test_flexbuf_read_scalar_roots_and_errors():
    from flatbuffers import flexbuffers

    from nnstreamer_tpu.interop.flexbuf_read import (
        FlexDecodeError,
        flexbuf_loads,
    )

    for v in (0, -1, 3.75, True, "root-string"):
        fbb = flexbuffers.Builder()
        if isinstance(v, bool):
            fbb.Bool(v)
        elif isinstance(v, int):
            fbb.Int(v)
        elif isinstance(v, float):
            fbb.Float(v)
        else:
            fbb.String(v)
        assert flexbuf_loads(bytes(fbb.Finish())) == v
    with pytest.raises(FlexDecodeError):
        flexbuf_loads(b"")
    with pytest.raises(FlexDecodeError):
        flexbuf_loads(b"\x00\x00\x07")   # byte width 7 is invalid
