"""Device-resident segment compilation + async dispatch.

Covers graph/optimize.fuse_segments (filter→transform→filter runs
collapsing into one head filter), backends/xla.compose_segment (one
bucketed jit per segment, member params as jit arguments), the host
fallback when the backend declines composition (bit-identical results),
the scheduler's DEVICE_RESIDENT bounded in-flight window, chaos
conservation with segments in the graph, member store:// hot-swap
adoption at segment-invoke boundaries, and the forced_syncs /
inflight_dispatch observability surface.
"""

import time

import numpy as np
import pytest

from nnstreamer_tpu import PipelineRunner, TensorBuffer, parse_launch
from nnstreamer_tpu.graph.optimize import fuse_segments
from nnstreamer_tpu.serving import compile_cache
from nnstreamer_tpu.serving.store import reset_store


def _v_double(x):
    return (x * 2.0,)


def _v_inc(x):
    return (x + 1.0,)


def _v_inc100(x):
    return (x + 100.0,)


def _v_neg(x):
    return (-x,)


@pytest.fixture(autouse=True)
def _fresh_store():
    store = reset_store()
    compile_cache.reset()
    yield store
    reset_store()
    compile_cache.reset()


def _push_frames(src, n, shape=(4,), start=0):
    for i in range(start, start + n):
        src.push(TensorBuffer.of(np.full(shape, float(i), np.float32),
                                 pts=i))


def _vals(sink):
    return [float(np.asarray(b.tensors[0]).ravel()[0])
            for b in sink.results]


def _wait_for(cond, timeout=15.0, what="condition"):
    t0 = time.monotonic()
    while not cond():
        assert time.monotonic() - t0 < timeout, f"timed out: {what}"
        time.sleep(0.01)


def _two_filter_pipe(store, mid_transform=True):
    store.register("seg_m1", _v_double)
    store.register("seg_m2", _v_inc)
    mid = ("tensor_transform mode=arithmetic option=mul:0.5 ! "
           if mid_transform else "")
    return parse_launch(
        "appsrc name=src dims=4 types=float32 ! "
        "tensor_filter name=f1 model=store://seg_m1 ! "
        + mid +
        "tensor_filter name=f2 model=store://seg_m2 ! tensor_sink name=out")


# -- discovery / graph splice ------------------------------------------------

class TestFuseSegments:
    def test_filter_transform_filter_splices(self, _fresh_store):
        pipe = _two_filter_pipe(_fresh_store)
        removed = fuse_segments(pipe)
        assert removed == 2                     # transform + member filter
        assert set(pipe.elements) == {"src", "f1", "out"}
        f1 = pipe.get("f1")
        assert f1.segment_name() == "f1+f2"
        # spliced link: f1 feeds the sink directly now
        (out_link,) = pipe.links_from(f1)
        assert out_link.dst.name == "out"

    def test_three_filter_run_one_head(self, _fresh_store):
        store = _fresh_store
        store.register("seg_a", _v_double)
        store.register("seg_b", _v_inc)
        store.register("seg_c", _v_neg)
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_filter name=fa model=store://seg_a ! "
            "tensor_filter name=fb model=store://seg_b ! "
            "tensor_transform mode=arithmetic option=add:3.0 ! "
            "tensor_filter name=fc model=store://seg_c ! "
            "tensor_sink name=out")
        fuse_segments(pipe)
        assert set(pipe.elements) == {"src", "fa", "out"}
        assert pipe.get("fa").segment_name() == "fa+fb+fc"

    def test_member_with_own_policy_stays(self, _fresh_store):
        store = _fresh_store
        store.register("seg_m1", _v_double)
        store.register("seg_m2", _v_inc)
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_filter name=f1 model=store://seg_m1 ! "
            "tensor_filter name=f2 model=store://seg_m2 "
            "error-policy=skip ! tensor_sink name=out")
        assert fuse_segments(pipe) == 0
        assert "f2" in pipe.elements
        assert pipe.get("f1").segment_name() == ""

    def test_mid_transform_with_policy_blocks_run(self, _fresh_store):
        store = _fresh_store
        store.register("seg_m1", _v_double)
        store.register("seg_m2", _v_inc)
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_filter name=f1 model=store://seg_m1 ! "
            "tensor_transform mode=arithmetic option=mul:0.5 "
            "error-policy=skip ! "
            "tensor_filter name=f2 model=store://seg_m2 ! "
            "tensor_sink name=out")
        # a mid transform with its own error policy must keep its own
        # element (its failures are policied there), so no run forms
        assert fuse_segments(pipe) == 0
        assert "f2" in pipe.elements

    def test_runner_fuses_by_default_and_reports(self, _fresh_store):
        pipe = _two_filter_pipe(_fresh_store)
        runner = PipelineRunner(pipe, trace=True)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        try:
            _push_frames(src, 3)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        segs = runner.device_segments()
        assert segs == [{"head": "f1", "segment": "f1+f2", "size": 2,
                         "composed": True}]
        st = runner.stats()["f1"]
        assert st["segment"] == "f1+f2"
        assert st["segment_size"] == 2
        assert st["segment_composed"] == 1
        # fused-away members never show up as stats rows
        assert "f2" not in runner.stats()
        rep = runner.report()
        assert "device segments" in rep
        assert "f1+f2" in rep

    def test_device_segments_off_keeps_elements(self, _fresh_store):
        pipe = _two_filter_pipe(_fresh_store)
        runner = PipelineRunner(pipe, device_segments=False)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        try:
            _push_frames(src, 3)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        assert runner.device_segments() == []
        assert "f2" in runner.stats()


# -- numerical parity --------------------------------------------------------

class TestSegmentParity:
    def _run(self, n=16, **runner_kwargs):
        store = reset_store()
        pipe = _two_filter_pipe(store)
        runner = PipelineRunner(pipe, **runner_kwargs)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        try:
            _push_frames(src, n)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        return [np.asarray(b.tensors[0]) for b in sink.results], runner

    def test_bit_identical_on_vs_off(self):
        on, r_on = self._run(device_segments=True)
        off, r_off = self._run(device_segments=False)
        assert r_on.device_segments() and not r_off.device_segments()
        assert len(on) == len(off) == 16
        for a, b in zip(on, off):
            assert a.dtype == b.dtype and a.shape == b.shape
            assert np.array_equal(a, b)      # bitwise, not allclose

    def test_one_compile_per_bucket(self, _fresh_store):
        pipe = _two_filter_pipe(_fresh_store)
        runner = PipelineRunner(pipe)
        runner.start()
        src = pipe.get("src")
        try:
            _push_frames(src, 10)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        # ONE composed jit serves both models for the steady bucket
        assert runner.stats()["f1"]["backend_compile_count"] == 1

    def test_decline_falls_back_host_side_identical(self, _fresh_store):
        """A member whose backend declines composition (canary routing
        needs per-invoke version picks) still fuses in the graph; the
        head applies member stages host-side, bit-identical."""
        store = _fresh_store
        store.register("seg_m1", _v_double)
        store.register("seg_m2", _v_inc)
        store.register("seg_m2", _v_inc100)   # v2 exists; canary ref
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_filter name=f1 model=store://seg_m1 ! "
            "tensor_filter name=f2 model=store://seg_m2@2:0.01 ! "
            "tensor_sink name=out")
        runner = PipelineRunner(pipe)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        try:
            _push_frames(src, 8)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        st = runner.stats()["f1"]
        assert st["segment"] == "f1+f2"
        assert st["segment_composed"] == 0       # backend declined
        assert _vals(sink) == [i * 2.0 + 1.0 for i in range(8)]


# -- async dispatch window ---------------------------------------------------

class TestAsyncDispatch:
    def test_source_order_retirement_at_sink(self, _fresh_store):
        pipe = _two_filter_pipe(_fresh_store)
        runner = PipelineRunner(pipe, max_inflight=4)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        try:
            _push_frames(src, 32)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        # retirement at the sink is source order, even with up to 4
        # unresolved dispatches in flight
        assert [b.pts for b in sink.results] == list(range(32))
        assert _vals(sink) == [i * 2.0 * 0.5 + 1.0 for i in range(32)]

    def test_eos_drains_window_and_gauge_bounded(self, _fresh_store):
        pipe = _two_filter_pipe(_fresh_store)
        runner = PipelineRunner(pipe, trace=True, max_inflight=2)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        try:
            _push_frames(src, 24)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        assert len(sink.results) == 24
        assert sink.eos.is_set()
        gauges = runner.tracer.inflight_gauges()
        assert gauges, "DEVICE_RESIDENT filter never recorded its window"
        assert all(g["peak"] <= 2 for g in gauges.values()), gauges
        # the EOS drain records the window returning to 0
        depths = [ev[6] for ev in runner.tracer.events()
                  if ev[1] == "inflight"]
        assert depths and depths[-1] == 0

    def test_max_inflight_zero_syncs_every_dispatch(self, _fresh_store):
        pipe = _two_filter_pipe(_fresh_store)
        runner = PipelineRunner(pipe, trace=True, max_inflight=0)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        try:
            _push_frames(src, 6)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        assert _vals(sink) == [i * 2.0 * 0.5 + 1.0 for i in range(6)]
        assert all(g["peak"] == 0
                   for g in runner.tracer.inflight_gauges().values())


# -- chaos: conservation with segments in the graph --------------------------

class TestChaosWithSegments:
    def test_conservation_with_upstream_faults(self, _fresh_store):
        store = _fresh_store
        store.register("seg_m1", _v_double)
        store.register("seg_m2", _v_inc)
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_fault name=flt mode=raise probability=0.15 seed=11 "
            "error-policy=skip ! "
            "tensor_filter name=f1 model=store://seg_m1 ! "
            "tensor_filter name=f2 model=store://seg_m2 ! "
            "tensor_sink name=out")
        runner = PipelineRunner(pipe)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        try:
            _push_frames(src, 60)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        assert runner.device_segments()          # segment really formed
        st = runner.stats()["flt"]
        assert st["errors"] > 0
        # no buffer lost in flight: emitted + skipped == generated
        assert len(sink.results) + st["skipped"] == 60
        assert sink.eos.is_set()

    def test_segment_failure_attributed_to_member(self, _fresh_store):
        from nnstreamer_tpu.core.errors import StreamError

        store = _fresh_store
        armed = {"on": False}     # negotiation traces fine; runtime fails

        def boom(x):
            if armed["on"]:
                raise RuntimeError("member model exploded")
            return (x + 1.0,)

        store.register("seg_m1", _v_double)
        store.register("seg_boom", boom)
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_filter name=f1 model=store://seg_m1 ! "
            "tensor_filter name=f2 model=store://seg_boom ! "
            "tensor_sink name=out")
        runner = PipelineRunner(pipe)
        with pytest.raises(StreamError, match="f2"):
            runner.start()
            armed["on"] = True
            src = pipe.get("src")
            try:
                _push_frames(src, 2)
                src.end()
                runner.wait(30)
            finally:
                runner.stop()


# -- member hot swap ---------------------------------------------------------

class TestMemberSwap:
    def test_member_adopts_at_segment_boundary(self, _fresh_store):
        store = _fresh_store
        store.register("seg_m1", _v_double)
        store.register("seg_m2", _v_inc)
        store.register("seg_m2", _v_inc100)
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_filter name=f1 model=store://seg_m1 ! "
            "tensor_filter name=f2 model=store://seg_m2 ! "
            "tensor_sink name=out")
        runner = PipelineRunner(pipe, trace=True)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        try:
            for _ in range(8):
                src.push(TensorBuffer.of(np.ones((4,), np.float32)))
            _wait_for(lambda: len(sink.results) >= 8, what="v1 frames")
            store.update("seg_m2", wait_s=None)
            for _ in range(8):
                src.push(TensorBuffer.of(np.ones((4,), np.float32)))
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        vals = _vals(sink)
        assert len(vals) == 16
        # 1*2 + 1 before the flip, 1*2 + 100 after — never a blend,
        # adoption lands exactly at a segment-invoke boundary
        assert set(vals) == {3.0, 102.0}
        flip = vals.index(102.0)
        assert all(v == 3.0 for v in vals[:flip])
        assert all(v == 102.0 for v in vals[flip:])
        # the member's swap shows on the head's stats row
        assert runner.stats()["f1"]["backend_swaps"] == 1


# -- forced-sync observability -----------------------------------------------

class TestForcedSyncs:
    def test_latency_mode_sync_counts_forced_syncs(self, _fresh_store):
        store = _fresh_store
        store.register("seg_solo", _v_double)
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_filter name=f model=store://seg_solo "
            "latency-mode=sync ! tensor_sink name=out")
        runner = PipelineRunner(pipe, trace=True)
        runner.start()
        src = pipe.get("src")
        try:
            _push_frames(src, 5)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        assert runner.stats()["f"]["forced_syncs"] == 5
        assert runner.tracer.forced_syncs().get("f") == 5

    def test_fakesink_sync_device_counts(self, _fresh_store):
        store = _fresh_store
        store.register("seg_solo", _v_double)
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_filter name=f model=store://seg_solo ! "
            "fakesink name=snk sync-device=true")
        runner = PipelineRunner(pipe, trace=True)
        runner.start()
        src = pipe.get("src")
        try:
            _push_frames(src, 4)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        assert pipe.get("snk").count == 4
        assert runner.tracer.forced_syncs().get("snk") == 4
        from nnstreamer_tpu.runtime.sync import forced_sync_count
        assert forced_sync_count() > 0


# -- one dispatch end-to-end -------------------------------------------------

class TestOneDispatch:
    def test_transform_filter_transform_filter_decoder_single_jit(
            self, _fresh_store):
        """The tentpole shape: t → f1 → t → f2 → decoder(device=true)
        lowers to ONE compiled computation — segment fusion folds f2
        into f1, then transform fusion folds the pre/post chains and
        the device decoder into the same jit."""
        store = _fresh_store
        store.register("seg_m1", _v_double)
        # 4 "class" scores; argmax decode runs on device
        store.register("seg_m2", lambda x: (x + np.arange(
            4, dtype=np.float32),))
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_transform mode=arithmetic option=add:1.0 ! "
            "tensor_filter name=f1 model=store://seg_m1 ! "
            "tensor_transform mode=arithmetic option=mul:2.0 ! "
            "tensor_filter name=f2 model=store://seg_m2 ! "
            "tensor_decoder mode=image_labeling device=true ! "
            "tensor_sink name=out")
        runner = PipelineRunner(pipe)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        try:
            _push_frames(src, 6)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        # everything between src and sink collapsed into f1
        assert set(pipe.elements) == {"src", "f1", "out"}
        st = runner.stats()["f1"]
        assert st["segment"] == "f1+f2"
        assert st["segment_composed"] == 1
        assert st["backend_compile_count"] == 1      # ONE dispatch
        # argmax of (i+1)*2*2 + [0..3] is always class 3
        assert all(int(np.asarray(b.tensors[0]).ravel()[0]) == 3
                   for b in sink.results)
