"""MQTT 3.1.1 wire protocol tests (VERDICT r2 next #6).

Three layers: frame-level spec vectors (encodings match the OASIS
3.1.1 byte layout), an external raw-socket MQTT client against the
EdgeBroker's MQTT listener (stands in for a stock paho client — paho is
not installed in this image), and the mqttsink/mqttsrc pipeline path in
protocol=mqtt mode, including the MQTT↔edge-protocol topic bridge.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.edge import mqtt_wire as M
from nnstreamer_tpu.edge.broker import BrokerClient, EdgeBroker


# -- spec vectors -----------------------------------------------------------

def test_remaining_length_vectors():
    # §2.2.3 table: 0, 127 → 1 byte; 128, 16383 → 2; 16384 → 3
    assert M._encode_remaining(0) == b"\x00"
    assert M._encode_remaining(127) == b"\x7f"
    assert M._encode_remaining(128) == b"\x80\x01"
    assert M._encode_remaining(16383) == b"\xff\x7f"
    assert M._encode_remaining(16384) == b"\x80\x80\x01"
    assert M._encode_remaining(268_435_455) == b"\xff\xff\xff\x7f"
    for n in (0, 1, 127, 128, 16383, 16384, 2_097_151, 268_435_455):
        enc = M._encode_remaining(n)
        assert M.decode_remaining(b"\x00" + enc, 1) == (n, len(enc))
    with pytest.raises(StreamError):
        M._encode_remaining(268_435_456)
    with pytest.raises(StreamError):
        M.decode_remaining(b"\x80\x80\x80\x80\x01", 0)


def test_connect_packet_layout():
    pkt = M.encode_connect("cid", keepalive=60, clean_session=True)
    # fixed header: type 1 << 4, then remaining length
    assert pkt[0] == 0x10
    body = pkt[2:]
    # variable header: len(4) "MQTT" level=4 flags=0x02 keepalive=60
    assert body[:6] == b"\x00\x04MQTT"
    assert body[6] == 4
    assert body[7] == 0x02
    assert body[8:10] == struct.pack(">H", 60)
    assert body[10:] == b"\x00\x03cid"
    (p,) = M.PacketSplitter().feed(pkt)
    cid, ka, clean = M.parse_connect(p)
    assert (cid, ka, clean) == ("cid", 60, True)


def test_publish_roundtrip_qos0_and_qos1():
    pkt = M.encode_publish("a/b", b"payload", qos=0)
    assert pkt[0] == 0x30
    (p,) = M.PacketSplitter().feed(pkt)
    M.parse_publish(p)
    assert (p.topic, p.payload, p.qos) == ("a/b", b"payload", 0)

    pkt1 = M.encode_publish("t", b"x" * 300, qos=1, packet_id=7)
    assert pkt1[0] == 0x32                    # qos1 flag
    (p1,) = M.PacketSplitter().feed(pkt1)
    M.parse_publish(p1)
    assert (p1.topic, p1.packet_id, p1.qos) == ("t", 7, 1)
    assert p1.payload == b"x" * 300


def test_subscribe_suback_layout():
    pkt = M.encode_subscribe(5, [("sensors/+/temp", 1), ("all/#", 0)])
    assert pkt[0] == 0x82                     # type 8 | reserved 0x02
    (p,) = M.PacketSplitter().feed(pkt)
    pid, topics = M.parse_subscribe(p)
    assert pid == 5
    assert topics == [("sensors/+/temp", 1), ("all/#", 0)]
    sub = M.encode_suback(5, [1, 0])
    (ps,) = M.PacketSplitter().feed(sub)
    assert ps.ptype == M.SUBACK and ps.body == b"\x00\x05\x01\x00"


def test_splitter_handles_fragmentation_and_coalescing():
    frames = (M.encode_publish("t", b"A" * 1000) + M.encode_pingreq()
              + M.encode_publish("u", b"B"))
    split = M.PacketSplitter()
    got = []
    for i in range(0, len(frames), 7):        # drip-feed 7-byte chunks
        got.extend(split.feed(frames[i:i + 7]))
    assert [p.ptype for p in got] == [M.PUBLISH, M.PINGREQ, M.PUBLISH]
    assert M.parse_publish(got[0]).payload == b"A" * 1000


def test_topic_matches():
    assert M.topic_matches("a/b", "a/b")
    assert not M.topic_matches("a/b", "a/c")
    assert M.topic_matches("a/+", "a/b")
    assert not M.topic_matches("a/+", "a/b/c")
    assert M.topic_matches("a/#", "a/b/c")
    assert M.topic_matches("#", "anything/at/all")
    assert not M.topic_matches("a/b/#", "a")


# -- external raw-socket client vs the EdgeBroker MQTT listener -------------

class _RawMqtt:
    """Stands in for an unmodified external client (paho analog)."""

    def __init__(self, port):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=5)
        self.split = M.PacketSplitter()
        self.inbox = []

    def send(self, data):
        self.sock.sendall(data)

    def expect(self, ptype, timeout=5.0):
        deadline = time.monotonic() + timeout
        while True:
            for i, p in enumerate(self.inbox):
                if p.ptype == ptype:
                    return self.inbox.pop(i)
            self.sock.settimeout(max(deadline - time.monotonic(), 0.01))
            data = self.sock.recv(1 << 16)
            if not data:
                raise AssertionError("connection closed")
            self.inbox.extend(self.split.feed(data))

    def close(self):
        self.sock.close()


@pytest.fixture()
def broker():
    b = EdgeBroker(port=0, mqtt_port=0)
    yield b
    b.close()


def test_external_mqtt_client_roundtrip(broker):
    """CONNECT → SUBSCRIBE → (second client) PUBLISH → receive."""
    sub = _RawMqtt(broker.mqtt_port)
    sub.send(M.encode_connect("ext-sub"))
    ack = sub.expect(M.CONNACK)
    assert ack.body[1] == M.CONNACK_ACCEPTED
    sub.send(M.encode_subscribe(1, [("demo/frames", 0)]))
    sa = sub.expect(M.SUBACK)
    assert sa.body[:2] == b"\x00\x01"

    pub = _RawMqtt(broker.mqtt_port)
    pub.send(M.encode_connect("ext-pub"))
    pub.expect(M.CONNACK)
    pub.send(M.encode_publish("demo/frames", b"hello tensor", qos=1,
                              packet_id=9))
    pa = pub.expect(M.PUBACK)
    assert pa.body == b"\x00\x09"

    got = sub.expect(M.PUBLISH)
    M.parse_publish(got)
    assert got.topic == "demo/frames" and got.payload == b"hello tensor"
    # keepalive works
    sub.send(M.encode_pingreq())
    sub.expect(M.PINGRESP)
    sub.close()
    pub.close()


def test_mqtt_wildcard_subscription(broker):
    sub = _RawMqtt(broker.mqtt_port)
    sub.send(M.encode_connect("w"))
    sub.expect(M.CONNACK)
    sub.send(M.encode_subscribe(2, [("sensors/#", 0)]))
    sub.expect(M.SUBACK)
    pub = _RawMqtt(broker.mqtt_port)
    pub.send(M.encode_connect("p"))
    pub.expect(M.CONNACK)
    pub.send(M.encode_publish("sensors/cam0/frames", b"F"))
    got = sub.expect(M.PUBLISH)
    M.parse_publish(got)
    assert got.topic == "sensors/cam0/frames"
    sub.close()
    pub.close()


def test_packet_before_connect_is_rejected(broker):
    c = _RawMqtt(broker.mqtt_port)
    c.send(M.encode_publish("t", b"x"))       # no CONNECT first
    # listener drops the connection
    c.sock.settimeout(5)
    assert c.sock.recv(100) == b""


def test_mqtt_bridges_to_edge_protocol(broker):
    """A stock-MQTT publish reaches edge-protocol subscribers and
    vice versa (one topic space across both domains)."""
    got = []
    evt = threading.Event()
    bc = BrokerClient("127.0.0.1", broker.port)
    bc.subscribe("bridge/t", lambda ns, frame: (got.append(frame),
                                                evt.set()))
    time.sleep(0.1)
    pub = _RawMqtt(broker.mqtt_port)
    pub.send(M.encode_connect("b"))
    pub.expect(M.CONNACK)
    pub.send(M.encode_publish("bridge/t", b"from-mqtt"))
    assert evt.wait(5)
    assert got == [b"from-mqtt"]

    # reverse: edge publish → mqtt subscriber
    sub = _RawMqtt(broker.mqtt_port)
    sub.send(M.encode_connect("s"))
    sub.expect(M.CONNACK)
    sub.send(M.encode_subscribe(3, [("bridge/u", 0)]))
    sub.expect(M.SUBACK)
    bc.publish("bridge/u", b"from-edge")
    gp = sub.expect(M.PUBLISH)
    M.parse_publish(gp)
    assert gp.payload == b"from-edge"
    bc.close()
    pub.close()
    sub.close()


# -- pipeline path: mqttsink/mqttsrc protocol=mqtt --------------------------

def test_mqtt_pipeline_roundtrip(broker):
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    recv = nns.parse_launch(
        f"mqttsrc name=src protocol=mqtt port={broker.mqtt_port} "
        f"topic=pipe/t dims=4:1 types=float32 ! tensor_sink name=out")
    rr = nns.PipelineRunner(recv).start()
    send = nns.parse_launch(
        f"appsrc name=in dims=4:1 types=float32 ! "
        f"mqttsink protocol=mqtt qos=1 port={broker.mqtt_port} "
        f"topic=pipe/t")
    rs = nns.PipelineRunner(send).start()
    time.sleep(0.3)                          # subscriber attach
    x = np.arange(4, dtype=np.float32).reshape(1, 4)
    for i in range(3):
        send.get("in").push(TensorBuffer.of(x + i, pts=i))
    send.get("in").end()
    rs.wait(30)
    deadline = time.monotonic() + 15
    sink = recv.get("out")
    while len(sink.results) < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    recv.get("src").interrupt()
    rr.stop()
    rs.stop()
    assert len(sink.results) == 3
    np.testing.assert_array_equal(
        np.asarray(sink.results[2].tensors[0]), x + 2)
    assert sink.results[2].pts == 2          # sender PTS travels


def test_mqtt_src_rejects_broker_sync():
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.core.errors import PipelineError

    with pytest.raises(PipelineError, match="sync=broker"):
        nns.parse_launch(
            "mqttsrc protocol=mqtt sync=broker port=1 topic=t "
            "dims=1 types=uint8 ! tensor_sink")
