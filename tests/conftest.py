"""Test config: force an 8-device virtual CPU mesh before jax loads.

Multi-chip TPU hardware is not available in CI; sharding/collective tests
run on XLA's host platform with 8 virtual devices (same technique the
driver's dryrun uses). Bench (bench.py) runs on the real chip instead.
"""

import os

# Force CPU even when the session points JAX_PLATFORMS at a real TPU
# (e.g. "axon"): unit tests must be hermetic and fast; bench.py is the
# real-chip path. The TPU tunnel's sitecustomize sets the jax_platforms
# *config* programmatically, which outranks the env var — so set both.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_cpu_devices():
    """The multichip fixture (pytest.ini marker `multichip`): tests
    needing real multi-device placement take this and get the 8-device
    emulated mesh, or a skip when the env override above lost (e.g. jax
    was imported before conftest in an exotic runner). Subprocess tests
    (pool workers, bench families) must instead ship BOTH env vars to
    the child BEFORE it imports jax — see bench.py's multichip family
    for the pattern."""
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip(f"need 8 virtual devices, got {len(devs)}")
    return devs


def free_port() -> int:
    """Ephemeral TCP port for loopback test servers (shared helper)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
