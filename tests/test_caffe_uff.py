"""Caffe (.caffemodel) and TensorRT-UFF (.uff) ingestion goldens.

Both use the reference's own checked-in lenet weights and the
reference's own test semantics:

* ``lenet_iter_9000.caffemodel`` + ``9.raw`` with (x-127.5)/127.5
  normalization → argmax 9 (the armnn suite's golden,
  unittest_filter_armnn.cc:580).
* ``lenet5.uff`` + ``{1,9}.pgm`` with 1 - x/255 normalization →
  argmax {1,9} (the tensorrt suite's golden, runTest.sh:68 — the same
  ``div:-255.0,add:1`` transform option string, even).
"""

import os

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.modelio import load_model_file
from nnstreamer_tpu.tensor.buffer import TensorBuffer

MODELS = "/root/reference/tests/test_models/models"
DATA = "/root/reference/tests/test_models/data"
CAFFE_LENET = os.path.join(MODELS, "lenet_iter_9000.caffemodel")
UFF_LENET = os.path.join(MODELS, "lenet5.uff")

needs_models = pytest.mark.skipif(
    not all(os.path.exists(p) for p in
            (CAFFE_LENET, UFF_LENET,
             os.path.join(DATA, "9.raw"), os.path.join(DATA, "1.pgm"),
             os.path.join(DATA, "9.pgm"))),
    reason="reference test models/data absent")


def _pgm_digit(name):
    raw = open(os.path.join(DATA, name), "rb").read()
    return np.frombuffer(raw[-784:], np.uint8).reshape(28, 28)


def _run_bundle(bundle, *inputs):
    import jax

    return jax.jit(lambda p, *xs: bundle.fn(p, *xs))(
        bundle.params, *inputs)


# -- caffe -------------------------------------------------------------------

@needs_models
def test_caffemodel_lenet_classifies_nine():
    """armnn-suite golden: 9.raw, (x-127.5)/127.5, prob argmax 9."""
    b = load_model_file(CAFFE_LENET)
    x = np.fromfile(os.path.join(DATA, "9.raw"), np.uint8)
    x = ((x.astype(np.float32) - 127.5) / 127.5).reshape(1, 1, 28, 28)
    y = np.asarray(_run_bundle(b, x)[0])
    assert y.shape == (1, 10)
    assert int(y.argmax()) == 9
    assert y[0, 9] > 0.99           # softmax probability, decisive
    np.testing.assert_allclose(y.sum(), 1.0, atol=1e-4)


@needs_models
def test_caffemodel_full_pipeline():
    """End-to-end with the reference normalization as a fused
    tensor_transform (extension auto-detect, declared Input shape)."""
    pipe = nns.parse_launch(
        f"appsrc name=src dims=28:28:1:1 types=uint8 ! "
        f"tensor_transform mode=arithmetic "
        f"option=typecast:float32,add:-127.5,div:127.5 ! "
        f"tensor_filter model={CAFFE_LENET} ! tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    x = np.fromfile(os.path.join(DATA, "9.raw"), np.uint8)
    pipe.get("src").push(TensorBuffer.of(x.reshape(1, 1, 28, 28)))
    pipe.get("src").end()
    runner.wait(120)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 1
    assert int(np.asarray(res[0].tensors[0]).argmax()) == 9


@needs_models
def test_caffemodel_unknown_layer_fails_loud(tmp_path):
    from nnstreamer_tpu.modelio.caffe import lower_caffe, parse_caffemodel

    net = parse_caffemodel(CAFFE_LENET)
    net.layers[1].type = "FancyNewLayer"
    # the shape probe inside lower_caffe already walks the graph
    with pytest.raises(BackendError, match="FancyNewLayer"):
        lower_caffe(net)


def test_caffemodel_not_a_model_fails_loud(tmp_path):
    p = tmp_path / "junk.caffemodel"
    p.write_bytes(b"\x00\x01nope")
    with pytest.raises(Exception):
        load_model_file(str(p))


@needs_models
@pytest.mark.parametrize("path", [CAFFE_LENET, UFF_LENET])
def test_compute_dtype_rejected_for_fixed_dtype_formats(path):
    """custom=dtype= is not consumed by .caffemodel/.uff/.pb lowerings;
    silently ignoring it would break the loader's fail-loud convention
    (round-4 ADVICE)."""
    with pytest.raises(BackendError, match="dtype"):
        load_model_file(path, compute_dtype="bfloat16")


# -- uff ---------------------------------------------------------------------

@needs_models
@pytest.mark.parametrize("digit", [1, 9])
def test_uff_lenet_classifies_reference_digits(digit):
    """tensorrt-suite golden: {1,9}.pgm, 1 - x/255, argmax {1,9}."""
    b = load_model_file(UFF_LENET)
    img = _pgm_digit(f"{digit}.pgm").astype(np.float32)
    x = (1.0 - img / 255.0).reshape(1, 28, 28, 1)
    y = np.asarray(_run_bundle(b, x)[0])
    assert y.shape == (1, 10)
    assert int(y.argmax()) == digit
    assert y[0, digit] > 5.0        # logits, decisive


@needs_models
def test_uff_full_pipeline_reference_transform():
    """End-to-end with the reference's exact transform option string
    (runTest.sh: typecast:float32,div:-255.0,add:1)."""
    pipe = nns.parse_launch(
        f"appsrc name=src dims=1:28:28:1 types=uint8 ! "
        f"tensor_transform mode=arithmetic "
        f"option=typecast:float32,div:-255.0,add:1 ! "
        f"tensor_filter model={UFF_LENET} ! tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    pipe.get("src").push(
        TensorBuffer.of(_pgm_digit("9.pgm").reshape(1, 28, 28, 1)))
    pipe.get("src").end()
    runner.wait(120)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 1
    assert int(np.asarray(res[0].tensors[0]).argmax()) == 9


@needs_models
def test_uff_structure():
    from nnstreamer_tpu.modelio.uff import parse_uff

    g = parse_uff(UFF_LENET)
    assert g.outputs == ["out"]
    assert "in" in g.nodes and g.nodes["in"].op == "Input"
    ops = {n.op for n in g.nodes.values()}
    assert {"Conv", "Pool", "FullyConnected", "Binary",
            "Activation"} <= ops


@needs_models
def test_uff_unknown_op_fails_loud():
    import jax

    from nnstreamer_tpu.modelio.uff import lower_uff, parse_uff

    g = parse_uff(UFF_LENET)
    g.nodes["relu"].op = "MysteryOp"
    m = lower_uff(g)
    x = np.zeros((1, 28, 28, 1), np.float32)
    with pytest.raises(BackendError, match="MysteryOp"):
        jax.jit(m.fn)(m.params, x)


@needs_models
def test_uff_inputname_outputname_binding():
    """The reference's exact tensorrt invocation uses inputname=in
    outputname=out (runTest.sh:68) — binding must validate and select."""
    b = load_model_file(UFF_LENET, input_names=["in"],
                        output_names=["out"])
    img = _pgm_digit("9.pgm").astype(np.float32)
    y = np.asarray(_run_bundle(b, (1.0 - img / 255.0)
                               .reshape(1, 28, 28, 1))[0])
    assert int(y.argmax()) == 9
    with pytest.raises(BackendError, match="no-such-node"):
        load_model_file(UFF_LENET, output_names=["no-such-node"])
    with pytest.raises(BackendError, match="Input node"):
        load_model_file(UFF_LENET, input_names=["wrong"])


def test_caffe_pool_ceil_and_clip_rule():
    """Caffe pooling output sizing: CEIL, then the clip rule — the last
    window must start inside image+pad (pooling_layer.cpp). H=3,k=2,
    s=2,p=1: ceil gives 3 but the clip drops to 2."""
    import jax.numpy as jnp

    from nnstreamer_tpu.modelio.caffe import _pool2d

    x = jnp.arange(9, dtype=jnp.float32).reshape(1, 1, 3, 3)
    out = _pool2d(jnp, x, "max", (2, 2), (2, 2), (1, 1))
    assert out.shape == (1, 1, 2, 2)
    # windows: [-1..0]x[-1..0] -> 0 ; [-1..0]x[1..2] -> 2 ;
    #          [1..2]x[-1..0]  -> 6 ; [1..2]x[1..2]  -> 8
    np.testing.assert_array_equal(
        np.asarray(out)[0, 0], [[0, 2], [6, 8]])
    # no padding, divisible: plain 2x2/2 pooling unchanged
    out2 = _pool2d(jnp, jnp.ones((1, 1, 4, 4)), "ave", (2, 2), (2, 2),
                   (0, 0))
    assert out2.shape == (1, 1, 2, 2)
    np.testing.assert_allclose(np.asarray(out2), 1.0)


@needs_models
def test_singleshot_runs_all_new_formats():
    """The pipeline-less SingleShot API (tensor_filter_single parity)
    accepts every round-4 format, including the shape-less bundles
    that negotiate from the first invoke's input."""
    from nnstreamer_tpu.single import SingleShot

    nine = np.fromfile(os.path.join(DATA, "9.raw"), np.uint8)
    pgm9 = _pgm_digit("9.pgm").astype(np.float32)
    cases = (
        (CAFFE_LENET,
         ((nine.astype(np.float32) - 127.5) / 127.5).reshape(1, 1, 28, 28)),
        (os.path.join(MODELS, "pytorch_lenet5.pt"),
         nine.reshape(1, 28, 28, 1)),
        (UFF_LENET, (1.0 - pgm9 / 255.0).reshape(1, 28, 28, 1)),
    )
    for path, x in cases:
        out = SingleShot(path).invoke(x)
        assert int(np.asarray(out[0]).argmax()) == 9, path
