"""Decoder-family tests with synthetic tensors (SURVEY.md §4: goldens are
synthetic rasters, no real models needed)."""

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.elements import AppSrc, TensorDecoder, TensorSink
from nnstreamer_tpu.graph.media import OctetSpec, VideoSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec


def decode_one(dec_props, spec, buffers):
    src = AppSrc(spec=spec, name="src")
    dec = TensorDecoder(name="dec", **dec_props)
    sink = TensorSink(name="s")
    pipe = nns.Pipeline()
    for e in (src, dec, sink):
        pipe.add(e)
    pipe.link(src, dec)
    pipe.link(dec, sink)
    runner = nns.PipelineRunner(pipe).start()
    for b in buffers:
        src.push(b)
    src.end()
    runner.wait(30)
    return dec, sink.results


# -- direct_video ------------------------------------------------------------

def test_direct_video_rgb():
    spec = TensorsSpec.of(TensorInfo((1, 6, 8, 3), DType.UINT8))
    img = np.arange(6 * 8 * 3, dtype=np.uint8).reshape(1, 6, 8, 3)
    dec, res = decode_one({"mode": "direct_video"}, spec,
                          [TensorBuffer.of(img, pts=0)])
    out_spec = dec.out_specs[0]
    assert isinstance(out_spec, VideoSpec)
    assert (out_spec.width, out_spec.height, out_spec.format) == (8, 6, "RGB")
    np.testing.assert_array_equal(res[0].tensors[0], img[0])


def test_direct_video_rejects_float():
    spec = TensorsSpec.of(TensorInfo((4, 4, 3), DType.FLOAT32))
    with pytest.raises(Exception, match="uint8"):
        decode_one({"mode": "direct_video"}, spec,
                   [TensorBuffer.of(np.zeros((4, 4, 3), np.float32))])


# -- image_labeling (existing decoder, regression) ---------------------------

def test_image_labeling_argmax(tmp_path):
    labels = tmp_path / "labels.txt"
    labels.write_text("cat\ndog\nbird\n")
    spec = TensorsSpec.of(TensorInfo((3,), DType.FLOAT32))
    scores = np.array([0.1, 0.9, 0.2], np.float32)
    dec, res = decode_one({"mode": "image_labeling", "option1": str(labels)},
                          spec, [TensorBuffer.of(scores, pts=0)])
    assert res[0].meta["label"] == "dog"
    assert bytes(res[0].tensors[0]).decode() == "dog"


# -- bounding boxes ----------------------------------------------------------

def test_bbox_postprocess_scheme_draws_and_reports():
    # 2 boxes normalized [ymin,xmin,ymax,xmax] + per-class scores
    boxes = np.array([[0.1, 0.1, 0.5, 0.5],
                      [0.6, 0.6, 0.9, 0.9]], np.float32)
    scores = np.array([[0.1, 0.95], [0.8, 0.1]], np.float32)
    spec = TensorsSpec.of(TensorInfo((2, 4), DType.FLOAT32),
                          TensorInfo((2, 2), DType.FLOAT32))
    dec, res = decode_one(
        {"mode": "bounding_boxes", "option1": "mobilenet-ssd-postprocess",
         "option3": "0.5:0.5", "option4": "100:100"},
        spec,
        [TensorBuffer.of(boxes, scores, pts=0)])
    out = res[0]
    img = out.tensors[0]
    assert img.shape == (100, 100, 4)
    det = out.meta["boxes"]
    assert det.shape[0] == 2
    # box edges drawn: border pixel non-transparent
    y0, x0 = int(det[0][0]), int(det[0][1])
    assert img[y0, x0, 3] == 255
    # pixels well outside any box remain transparent
    assert img[99, 0, 3] == 0


def test_bbox_nms_suppresses_overlaps():
    from nnstreamer_tpu.decoders.boundingbox import nms

    boxes = np.array([[0, 0, 1, 1], [0.05, 0.05, 1.0, 1.0],
                      [0.5, 0.5, 0.6, 0.6]], np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    keep = nms(boxes, scores, iou_thresh=0.5)
    assert list(keep) == [0, 2]


def test_bbox_mobilenet_ssd_with_anchors():
    from nnstreamer_tpu.models.ssd_mobilenet import generate_anchors

    anchors = generate_anchors()
    n = anchors.shape[0]
    loc = np.zeros((1, n, 4), np.float32)       # boxes = anchors
    logits = np.full((1, n, 3), -10.0, np.float32)
    logits[0, 100, 1] = 10.0                    # one confident class-1 hit
    spec = TensorsSpec.of(TensorInfo((1, n, 4), DType.FLOAT32),
                          TensorInfo((1, n, 3), DType.FLOAT32))
    dec, res = decode_one(
        {"mode": "bounding_boxes", "option1": "mobilenet-ssd",
         "option3": "0.5:0.5", "option4": "300:300"},
        spec, [TensorBuffer.of(loc, logits, pts=0)])
    det = res[0].meta["boxes"]
    assert det.shape[0] == 1
    assert int(det[0][5]) == 1  # class id


def test_bbox_yolov5_scheme():
    # one prediction row: cx,cy,w,h (normalized), obj, 2 class probs
    pred = np.zeros((1, 2, 7), np.float32)
    pred[0, 0] = [0.5, 0.5, 0.2, 0.2, 0.9, 0.1, 0.8]
    pred[0, 1] = [0.2, 0.2, 0.1, 0.1, 0.05, 0.9, 0.1]  # low obj → dropped
    spec = TensorsSpec.of(TensorInfo((1, 2, 7), DType.FLOAT32))
    dec, res = decode_one(
        {"mode": "bounding_boxes", "option1": "yolov5",
         "option3": "0.5:0.5", "option4": "100:100"},
        spec, [TensorBuffer.of(pred, pts=0)])
    det = res[0].meta["boxes"]
    assert det.shape[0] == 1
    assert int(det[0][5]) == 1


# -- pose --------------------------------------------------------------------

def test_pose_decoder_keypoints():
    k = 17
    hm = np.zeros((1, 10, 10, k), np.float32)
    for i in range(k):
        hm[0, i % 10, (i * 2) % 10, i] = 1.0
    spec = TensorsSpec.of(TensorInfo((1, 10, 10, k), DType.FLOAT32))
    dec, res = decode_one(
        {"mode": "pose_estimation", "option1": "100:100", "option4": "0.5"},
        spec, [TensorBuffer.of(hm, pts=0)])
    kps = res[0].meta["keypoints"]
    assert kps.shape == (k, 3)
    # keypoint 3 is at grid (3, 6) → center pixel ((6+.5)/10*100, (3+.5)/10*100)
    np.testing.assert_allclose(kps[3, :2], [65.0, 35.0], atol=1e-4)
    img = res[0].tensors[0]
    assert img.shape == (100, 100, 4)
    assert (img[:, :, 3] > 0).sum() > 0  # something drawn


def test_pose_decoder_with_offsets():
    k = 2
    hm = np.zeros((1, 4, 4, k), np.float32)
    hm[0, 1, 1, 0] = 1.0
    hm[0, 2, 3, 1] = 1.0
    off = np.zeros((1, 4, 4, 2 * k), np.float32)
    off[0, 1, 1, 0] = 0.5   # y-offset half a cell
    spec = TensorsSpec.of(TensorInfo((1, 4, 4, k), DType.FLOAT32),
                          TensorInfo((1, 4, 4, 2 * k), DType.FLOAT32))
    dec, res = decode_one(
        {"mode": "pose_estimation", "option1": "80:80"},
        spec, [TensorBuffer.of(hm, off, pts=0)])
    kps = res[0].meta["keypoints"]
    # base y = (1+0.5)/4*80 = 30, +0.5 cell (=1/4 grid *80 /4... offset*stride)
    assert kps[0, 1] > 30.0


# -- segmentation ------------------------------------------------------------

def test_segment_tflite_deeplab_argmax():
    scores = np.zeros((1, 4, 4, 3), np.float32)
    scores[0, :2, :, 1] = 1.0   # top half class 1
    scores[0, 2:, :, 2] = 1.0   # bottom half class 2
    spec = TensorsSpec.of(TensorInfo((1, 4, 4, 3), DType.FLOAT32))
    dec, res = decode_one(
        {"mode": "image_segment", "option1": "tflite-deeplab"},
        spec, [TensorBuffer.of(scores, pts=0)])
    cm = res[0].meta["class_map"]
    assert cm.shape == (4, 4)
    assert (cm[:2] == 1).all() and (cm[2:] == 2).all()
    img = res[0].tensors[0]
    assert img.shape == (4, 4, 4)
    # two distinct colors, both opaque
    assert img[0, 0, 3] == 255 and img[3, 0, 3] == 255
    assert not np.array_equal(img[0, 0], img[3, 0])


def test_segment_index_variant():
    idx_map = np.array([[0, 1], [2, 3]], np.uint8)
    spec = TensorsSpec.of(TensorInfo((2, 2), DType.UINT8))
    dec, res = decode_one(
        {"mode": "image_segment", "option1": "index", "option2": "4"},
        spec, [TensorBuffer.of(idx_map, pts=0)])
    assert res[0].meta["class_map"].tolist() == [[0, 1], [2, 3]]
    assert res[0].tensors[0][0, 0, 3] == 0  # background transparent


# -- octet -------------------------------------------------------------------

def test_octet_stream_concat():
    spec = TensorsSpec.of(TensorInfo((2,), DType.UINT8),
                          TensorInfo((2,), DType.UINT8))
    b = TensorBuffer.of(np.array([1, 2], np.uint8),
                        np.array([3, 4], np.uint8), pts=0)
    dec, res = decode_one({"mode": "octet_stream"}, spec, [b])
    assert isinstance(dec.out_specs[0], OctetSpec)
    np.testing.assert_array_equal(res[0].tensors[0], [1, 2, 3, 4])


# -- font --------------------------------------------------------------------

def test_font_renders_text():
    from nnstreamer_tpu.decoders.font import blit_text, render_text

    bm = render_text("AB1")
    assert bm.shape == (8, 24)
    assert bm.sum() > 0
    img = np.zeros((10, 30, 4), np.uint8)
    blit_text(img, "HI", 1, 1)
    assert (img[:, :, 0] == 255).sum() > 0
    # clipping never raises
    blit_text(img, "CLIPPED", 25, 8)


# -- device-side decode (tensor_decoder device=true) -------------------------

class TestDeviceDecode:
    def _ssd_io(self, seed=0, objects=6):
        """Realistic raw SSD outputs: background-dominant logits with a
        handful of planted confident detections."""
        from nnstreamer_tpu.models.ssd_mobilenet import generate_anchors

        rng = np.random.default_rng(seed)
        n = generate_anchors().shape[0]
        loc = rng.normal(0, 0.3, (1, n, 4)).astype(np.float32)
        logits = rng.normal(-9, 0.5, (1, n, 91)).astype(np.float32)
        for i in rng.choice(n, objects, replace=False):
            logits[0, i, rng.integers(1, 91)] = rng.uniform(2.0, 5.0)
        return loc, logits

    def test_ssd_device_matches_host_nms(self):
        """Device decode's surviving boxes equal the host decoder's
        (same order: score-desc) in output-pixel coordinates."""
        from nnstreamer_tpu.decoders.boundingbox import BoundingBoxes
        from nnstreamer_tpu.tensor.buffer import TensorBuffer
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

        loc, logits = self._ssd_io()
        props = {"option1": "mobilenet-ssd", "option3": "0.5:0.5",
                 "option4": "300:300"}
        host = BoundingBoxes()
        host.init(dict(props))
        spec = TensorsSpec.of(TensorInfo(loc.shape, DType.FLOAT32),
                              TensorInfo(logits.shape, DType.FLOAT32))
        host.negotiate(spec)
        host_out = host.decode(TensorBuffer.of(loc, logits))
        host_boxes = host_out.meta["boxes"]          # (N,6) px, score desc

        dev = BoundingBoxes()
        dev.init(dict(props))
        dev.device_negotiate(spec)
        (det,) = dev.device_decode((loc, logits))
        det = np.asarray(det)
        kept = det[det[:, 4] > 0]
        assert len(kept) == len(host_boxes)
        # host layout [ymin,xmin,ymax,xmax,score,cls] in px — same here
        np.testing.assert_allclose(kept, host_boxes, rtol=1e-4, atol=1e-2)

    def test_ssd_device_pipeline(self):
        import nnstreamer_tpu as nns
        from nnstreamer_tpu.tensor.buffer import TensorBuffer

        loc, logits = self._ssd_io(1)
        pipe = nns.parse_launch(
            f"appsrc name=src dims=4:{loc.shape[1]}:1,91:{loc.shape[1]}:1 "
            f"types=float32,float32 ! "
            f"tensor_decoder mode=bounding_boxes device=true "
            f"option1=mobilenet-ssd option3=0.3:0.5 option4=300:300 ! "
            f"tensor_sink name=out")
        runner = nns.PipelineRunner(pipe).start()
        src = pipe.get("src")
        src.push(TensorBuffer.of(loc, logits))
        src.end()
        runner.wait(60)
        runner.stop()
        res = pipe.get("out").results
        assert len(res) == 1 and res[0].tensors[0].shape == (16, 6)

    def test_pose_device_matches_host(self):
        from nnstreamer_tpu.decoders.pose import PoseEstimation
        from nnstreamer_tpu.tensor.buffer import TensorBuffer
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

        rng = np.random.default_rng(3)
        hm = rng.uniform(0, 1, (1, 9, 9, 17)).astype(np.float32)
        off = rng.normal(0, 4, (1, 9, 9, 34)).astype(np.float32)
        props = {"option1": "257:257", "option2": "257:257",
                 "option4": "0.0"}
        host = PoseEstimation()
        host.init(dict(props))
        spec = TensorsSpec.of(TensorInfo(hm.shape, DType.FLOAT32),
                              TensorInfo(off.shape, DType.FLOAT32))
        host.negotiate(spec)
        want = host._keypoints(TensorBuffer.of(hm, off))   # (K,3) px

        dev = PoseEstimation()
        dev.init(dict(props))
        dev.device_negotiate(spec)
        (got,) = dev.device_decode((hm, off))
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-3)

    def test_label_device_argmax(self):
        from nnstreamer_tpu.decoders.label import ImageLabeling
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

        scores = np.zeros((1, 10), np.float32)
        scores[0, 7] = 5.0
        sub = ImageLabeling()
        sub.init({})
        sub.device_negotiate(TensorsSpec.of(
            TensorInfo((1, 10), DType.FLOAT32)))
        (idx,) = sub.device_decode((scores,))
        assert int(np.asarray(idx)[0]) == 7

    def test_device_unsupported_scheme_fails_cleanly(self):
        import nnstreamer_tpu as nns
        with pytest.raises(nns.core.errors.NegotiationError,
                           match="host"):
            pipe = nns.parse_launch(
                "appsrc dims=7:10:1 types=float32 ! "
                "tensor_decoder mode=bounding_boxes device=true "
                "option1=ov-person-detection ! fakesink")
            nns.PipelineRunner(pipe).start()

    def test_device_decoder_fuses_into_filter(self):
        """transform + filter + device decoder collapse into one element;
        results match the unfused pipeline."""
        import nnstreamer_tpu as nns
        from nnstreamer_tpu.backends.custom import register_custom_easy
        from nnstreamer_tpu.models.ssd_mobilenet import generate_anchors
        from nnstreamer_tpu.tensor.buffer import TensorBuffer

        loc, logits = self._ssd_io(5)

        # fake "model" emitting fixed SSD raw outputs regardless of input
        register_custom_easy(
            "fake_ssd", lambda t: (loc, logits),
        )
        desc = ("appsrc name=src dims=4 types=float32 ! "
                "tensor_filter name=f framework=custom model=fake_ssd "
                "output=4:{n}:1,91:{n}:1 outputtype=float32,float32 ! "
                "tensor_decoder mode=bounding_boxes device=true "
                "option1=mobilenet-ssd option3=0.3:0.5 option4=300:300 ! "
                "tensor_sink name=out").format(n=loc.shape[1])

        def run(optimize):
            pipe = nns.parse_launch(desc)
            runner = nns.PipelineRunner(pipe, optimize=optimize).start()
            src = pipe.get("src")
            src.push(TensorBuffer.of(np.zeros(4, np.float32)))
            src.end()
            runner.wait(60)
            runner.stop()
            return pipe

    # fused: decoder element disappears from the graph
        fused_pipe = run(True)
        assert not any(e.ELEMENT_NAME == "tensor_decoder"
                       for e in fused_pipe.elements.values())
        plain_pipe = run(False)
        a = np.asarray(fused_pipe.get("out").results[0].tensors[0])
        b = np.asarray(plain_pipe.get("out").results[0].tensors[0])
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-3)

    def test_segment_device_argmax_map(self):
        import nnstreamer_tpu as nns
        from nnstreamer_tpu.tensor.buffer import TensorBuffer

        scores = np.zeros((1, 4, 4, 3), np.float32)
        scores[0, :2, :, 1] = 1.0
        scores[0, 2:, :, 2] = 1.0
        pipe = nns.parse_launch(
            "appsrc name=in dims=3:4:4:1 types=float32 ! "
            "tensor_decoder mode=image_segment device=true "
            "option1=tflite-deeplab ! tensor_sink name=out")
        runner = nns.PipelineRunner(pipe).start()
        src = pipe.get("in")
        src.push(TensorBuffer.of(scores))
        src.end()
        runner.wait(30)
        runner.stop()
        cm = np.asarray(pipe.get("out").results[0].tensors[0])
        assert cm.shape == (4, 4) and cm.dtype == np.uint8
        assert (cm[:2] == 1).all() and (cm[2:] == 2).all()


class TestCompactDecode:
    """tensor_decoder device=compact: on-chip top-K candidate reduction
    + unchanged host threshold/NMS/overlay semantics."""

    def _ssd_io(self, seed=0, objects=6):
        from nnstreamer_tpu.models.ssd_mobilenet import generate_anchors

        rng = np.random.default_rng(seed)
        n = generate_anchors().shape[0]
        loc = rng.normal(0, 0.3, (1, n, 4)).astype(np.float32)
        logits = rng.normal(-9, 0.5, (1, n, 91)).astype(np.float32)
        for i in rng.choice(n, objects, replace=False):
            logits[0, i, rng.integers(1, 91)] = rng.uniform(2.0, 5.0)
        return loc, logits

    def test_compact_matches_full_host_decode(self):
        """Final boxes through the compact path equal the plain host
        path exactly (top-100 covers everything above threshold)."""
        from nnstreamer_tpu.decoders.boundingbox import BoundingBoxes
        from nnstreamer_tpu.tensor.buffer import TensorBuffer
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

        loc, logits = self._ssd_io()
        props = {"option1": "mobilenet-ssd", "option3": "0.5:0.5",
                 "option4": "300:300"}
        spec = TensorsSpec.of(TensorInfo(loc.shape, DType.FLOAT32),
                              TensorInfo(logits.shape, DType.FLOAT32))
        host = BoundingBoxes()
        host.init(dict(props))
        host.negotiate(spec)
        host_out = host.decode(TensorBuffer.of(loc, logits))

        comp = BoundingBoxes()
        comp.init(dict(props))
        comp.negotiate(spec)
        (det,) = comp.device_compact(
            (loc, logits), {"anchors": comp._anchors})
        comp.consume_compact = True
        comp_out = comp.decode(TensorBuffer.of(np.asarray(det)))
        np.testing.assert_allclose(
            comp_out.meta["boxes"], host_out.meta["boxes"],
            rtol=1e-4, atol=1e-2)
        # overlay pixels identical too (same boxes, same draw path)
        np.testing.assert_array_equal(
            np.asarray(comp_out.tensors[0]), np.asarray(host_out.tensors[0]))

    def test_compact_pipeline_end_to_end(self):
        import nnstreamer_tpu as nns
        from nnstreamer_tpu.tensor.buffer import TensorBuffer

        loc, logits = self._ssd_io(1)
        pipe = nns.parse_launch(
            f"appsrc name=src dims=4:{loc.shape[1]}:1,91:{loc.shape[1]}:1 "
            f"types=float32,float32 ! "
            f"tensor_decoder mode=bounding_boxes device=compact "
            f"option1=mobilenet-ssd option3=0.3:0.5 option4=300:300 ! "
            f"tensor_sink name=out")
        runner = nns.PipelineRunner(pipe).start()
        src = pipe.get("src")
        src.push(TensorBuffer.of(loc, logits))
        src.end()
        runner.wait(60)
        runner.stop()
        res = pipe.get("out").results
        assert len(res) == 1
        img = np.asarray(res[0].tensors[0])
        assert img.shape == (300, 300, 4) and img.dtype == np.uint8
        assert len(res[0].meta["boxes"]) >= 1    # planted objects found

    def test_compact_decoder_not_fused_away(self):
        """The optimizer must keep a device=compact decoder in the graph
        (its host decode stage still has work to do)."""
        import nnstreamer_tpu as nns

        pipe = nns.parse_launch(
            "appsrc name=src dims=3:300:300:1 types=uint8 ! "
            "tensor_transform mode=arithmetic "
            "option=typecast:float32,add:-127.5,div:127.5 ! "
            "tensor_filter model=zoo://ssd_mobilenet ! "
            "tensor_decoder name=dec mode=bounding_boxes device=compact "
            "option1=mobilenet-ssd option3=0.5:0.5 option4=300:300 ! "
            "fakesink")
        pipe.negotiate()
        assert pipe.get("dec") is not None

    def test_compact_k_option_and_validation(self):
        from nnstreamer_tpu.core.errors import PipelineError
        from nnstreamer_tpu.decoders.boundingbox import BoundingBoxes

        b = BoundingBoxes()
        b.init({"option1": "mobilenet-ssd", "option7": "25"})
        assert b._compact_k == 25
        b2 = BoundingBoxes()
        with pytest.raises(PipelineError, match="option7"):
            b2.init({"option1": "mobilenet-ssd", "option7": "0"})

    def test_compact_unsupported_scheme_fails_cleanly(self):
        from nnstreamer_tpu.core.errors import PipelineError
        from nnstreamer_tpu.decoders.boundingbox import BoundingBoxes

        b = BoundingBoxes()
        b.init({"option1": "yolov5"})
        with pytest.raises(PipelineError, match="compact"):
            b.device_compact((np.zeros((1, 5, 85), np.float32),))


def test_host_decode_pipelined_window_matches_strict():
    """max_in_flight>1 on a PLAIN host decoder pipelines the readbacks
    but emits identical results in identical order (flush drains)."""
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    def run(extra):
        pipe = nns.parse_launch(
            f"appsrc name=src dims=10:1 types=float32 ! "
            f"tensor_decoder mode=image_labeling {extra} ! "
            f"tensor_sink name=out")
        r = nns.PipelineRunner(pipe).start()
        rng = np.random.default_rng(0)
        for i in range(7):
            pipe.get("src").push(TensorBuffer.of(
                rng.normal(0, 1, (1, 10)).astype(np.float32), pts=i))
        pipe.get("src").end()
        r.wait(60)
        r.stop()
        return [(b.pts, b.meta["label_index"])
                for b in pipe.get("out").results]

    strict = run("")
    piped = run("max_in_flight=4")
    assert len(strict) == 7
    assert piped == strict
