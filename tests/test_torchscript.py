"""TorchScript (.pt) ingestion tests (VERDICT r3 missing #1).

Golden strategy mirrors the reference's pytorch filter suite
(tests/nnstreamer_filter_pytorch/runTest.sh): run the reference's own
checked-in .pt models and compare against an independent execution.
Two independent oracles are used:

* ``torch.jit.load`` CPU execution (torch 2.x can load the *modern*
  archive format) — exact-match goldens, including fresh models scripted
  in-test so the op table is checked against torch itself;
* the reference's semantic data goldens (9.raw → digit 9) for the
  *legacy* archive format, which installed torch ≥1.3 refuses to load —
  there our from-scratch parser is the only runnable path.
"""

import os

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.modelio import load_model_file
from nnstreamer_tpu.tensor.buffer import TensorBuffer

MODELS = "/root/reference/tests/test_models/models"
LENET5_PT = os.path.join(MODELS, "pytorch_lenet5.pt")
SAMPLE_PT = os.path.join(MODELS, "sample_3x4_two_input_two_output.pt")
NINE_RAW = "/root/reference/tests/test_models/data/9.raw"

needs_models = pytest.mark.skipif(
    not os.path.exists(LENET5_PT), reason="reference test models absent")

# torch is the *oracle* only — the loader itself is torch-free, and the
# legacy-format tests must keep running on torch-less deployments
try:
    import torch
except ImportError:          # pragma: no cover - torch present in CI
    torch = None

needs_torch = pytest.mark.skipif(torch is None,
                                 reason="torch oracle not installed")


def _run_bundle(bundle, *inputs):
    import jax

    return jax.jit(lambda p, *xs: bundle.fn(p, *xs))(
        bundle.params, *inputs)


# -- legacy archive format (model.json): reference lenet5 --------------------

@needs_models
@needs_torch
def test_legacy_archive_refused_by_torch():
    """Precondition of the golden strategy: installed torch cannot load
    the legacy archive, so the from-scratch parser is load-bearing."""
    with pytest.raises(RuntimeError):
        torch.jit.load(LENET5_PT)


@needs_models
def test_lenet5_classifies_reference_digit():
    """Reference runTest.sh golden: 9.raw through pytorch_lenet5.pt
    scores digit 9 (uint8 softmax scale, NHWC input as the pipeline
    supplies it — the model transposes to NCHW internally)."""
    b = load_model_file(LENET5_PT)
    x = np.fromfile(NINE_RAW, np.uint8).reshape(1, 28, 28, 1)
    out = np.asarray(_run_bundle(b, x)[0])
    assert out.shape == (1, 10) and out.dtype == np.uint8
    assert int(out.argmax()) == 9
    assert out[0, 9] > 200          # confident, not a coin flip


@needs_models
def test_lenet5_full_pipeline():
    """End-to-end: .pt auto-detected by extension, shapes negotiated
    from pipeline caps (TorchScript has no input shape metadata, like
    the reference where dims come from caps)."""
    pipe = nns.parse_launch(
        f"appsrc name=src dims=1:28:28:1 types=uint8 ! "
        f"tensor_filter model={LENET5_PT} ! tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    raw = np.fromfile(NINE_RAW, np.uint8).reshape(1, 28, 28, 1)
    pipe.get("src").push(TensorBuffer.of(raw))
    pipe.get("src").end()
    runner.wait(120)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 1
    assert int(np.asarray(res[0].tensors[0]).argmax()) == 9


@needs_models
def test_lenet5_bfloat16_optin():
    """custom=dtype=bfloat16 runs the MXU-native type; the semantic
    golden must survive reduced precision."""
    b = load_model_file(LENET5_PT, compute_dtype="bfloat16")
    x = np.fromfile(NINE_RAW, np.uint8).reshape(1, 28, 28, 1)
    out = np.asarray(_run_bundle(b, x)[0])
    assert int(out.argmax()) == 9


# -- modern archive format: reference sample + torch oracles -----------------

@needs_models
@needs_torch
def test_sample_two_input_two_output_vs_torch():
    """Reference multi-I/O golden (runTest.sh case 3): both outputs
    match torch.jit.load CPU execution exactly."""
    b = load_model_file(SAMPLE_PT)
    rng = np.random.RandomState(7)
    xa = rng.randn(3, 4).astype(np.float32)
    xb = rng.randn(3, 4).astype(np.float32)
    ours = _run_bundle(b, xa, xb)
    assert len(ours) == 2
    ref = torch.jit.load(SAMPLE_PT)(torch.from_numpy(xa),
                                    torch.from_numpy(xb))
    for o, r in zip(ours, ref):
        np.testing.assert_allclose(np.asarray(o), r.numpy(), rtol=1e-6)


def _script_and_load(tmp_path, model, name="m.pt"):
    path = str(tmp_path / name)
    torch.jit.save(torch.jit.script(model), path)
    return load_model_file(path)


@needs_torch
def test_scripted_convnet_matches_torch(tmp_path):
    """Fresh scripted conv/bn/pool/linear net: our AST-interpreted
    lowering matches torch execution (fp32, tight tolerance)."""
    import torch.nn as tnn

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = tnn.Conv2d(3, 8, 3, stride=2, padding=1)
            self.bn = tnn.BatchNorm2d(8)
            self.conv2 = tnn.Conv2d(8, 16, 3, padding=1, groups=2)
            self.fc = tnn.Linear(16 * 4 * 4, 5)

        def forward(self, x):
            x = torch.relu(self.bn(self.conv1(x)))
            x = torch.max_pool2d(self.conv2(x), 2, 2)
            x = x.reshape(x.shape[0], -1)
            return torch.log_softmax(self.fc(x), dim=1)

    net = Net().eval()
    b = _script_and_load(tmp_path, net)
    x = np.random.RandomState(0).randn(2, 3, 16, 16).astype(np.float32)
    ours = np.asarray(_run_bundle(b, x)[0])
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


@needs_torch
def test_scripted_shape_ops_match_torch(tmp_path):
    """Permute/cat/slice/pad/interpolate closure against torch."""
    import torch.nn as tnn
    import torch.nn.functional as F

    class Net(tnn.Module):
        def forward(self, x):
            a = x.permute(0, 2, 1)
            b = torch.cat([a, a * 2.0], dim=1)
            c = b[:, 1:5, :]
            d = F.pad(c, [1, 2], value=0.5)
            return torch.tanh(d).flatten(1)

    net = Net().eval()
    b = _script_and_load(tmp_path, net)
    x = np.random.RandomState(1).randn(2, 6, 5).astype(np.float32)
    ours = np.asarray(_run_bundle(b, x)[0])
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)


@needs_torch
def test_traced_module_matches_torch(tmp_path):
    """torch.jit.trace output (the other exporter path) loads too."""
    import torch.nn as tnn

    net = tnn.Sequential(
        tnn.Conv2d(1, 4, 3, padding=1), tnn.ReLU(),
        tnn.AdaptiveAvgPool2d((1, 1)), tnn.Flatten(),
        tnn.Linear(4, 3), tnn.Sigmoid()).eval()
    x0 = torch.zeros(1, 1, 8, 8)
    path = str(tmp_path / "traced.pt")
    torch.jit.save(torch.jit.trace(net, x0), path)
    b = load_model_file(path)
    x = np.random.RandomState(2).randn(1, 1, 8, 8).astype(np.float32)
    ours = np.asarray(_run_bundle(b, x)[0])
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


@needs_torch
def test_multi_output_tuple(tmp_path):
    import torch.nn as tnn

    class Net(tnn.Module):
        def forward(self, x):
            return torch.mean(x, dim=1), torch.topk(x, 2, dim=1)[0]

    net = Net().eval()
    b = _script_and_load(tmp_path, net)
    x = np.random.RandomState(3).randn(4, 6).astype(np.float32)
    outs = _run_bundle(b, x)
    with torch.no_grad():
        r1, r2 = net(torch.from_numpy(x))
    np.testing.assert_allclose(np.asarray(outs[0]), r1.numpy(),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(outs[1]), r2.numpy(),
                               rtol=1e-6)


# -- negative cases ----------------------------------------------------------

@needs_torch
def test_unsupported_op_fails_loud(tmp_path):
    """An op outside the lowering table must raise BackendError naming
    the op — never run silently wrong."""
    import torch.nn as tnn

    class Net(tnn.Module):
        def forward(self, x):
            return torch.lgamma(x)

    b = _script_and_load(tmp_path, Net().eval())
    x = np.zeros((2, 2), np.float32)
    with pytest.raises(BackendError, match="lgamma"):
        _run_bundle(b, x)


def test_not_an_archive_fails_loud(tmp_path):
    p = tmp_path / "junk.pt"
    p.write_bytes(b"not a zip at all")
    with pytest.raises(BackendError, match="TorchScript"):
        load_model_file(str(p))


def test_wrong_input_shape_fails_at_negotiation():
    if not os.path.exists(SAMPLE_PT):
        pytest.skip("reference test models absent")
    pipe = nns.parse_launch(
        f"appsrc name=src dims=4:3 types=float32 ! "
        f"tensor_filter model={SAMPLE_PT} ! tensor_sink name=out")
    # forward takes TWO tensors; feeding one must fail loudly at
    # negotiation (eval_shape), not produce garbage
    with pytest.raises(Exception):
        nns.PipelineRunner(pipe).start()


@needs_torch
def test_chunk_and_ceil_avgpool_match_torch(tmp_path):
    """torch.chunk's ceil-sized split (7/3 -> [3,3,1]) and AvgPool2d
    ceil_mode+count_include_pad divisor semantics, vs torch."""
    import torch.nn as tnn

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.pool = tnn.AvgPool2d(3, stride=2, padding=1,
                                      ceil_mode=True)

        def forward(self, x):
            a, b, c = torch.chunk(x, 3, dim=1)
            return self.pool(a + b[:, :a.shape[1]]), c

    net = Net().eval()
    b = _script_and_load(tmp_path, net)
    x = np.random.RandomState(4).randn(1, 7, 6, 6).astype(np.float32)
    outs = _run_bundle(b, x)
    with torch.no_grad():
        r1, r2 = net(torch.from_numpy(x))
    np.testing.assert_allclose(np.asarray(outs[0]), r1.numpy(),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(outs[1]), r2.numpy(),
                               rtol=1e-6)


@needs_torch
def test_conv_transpose_and_upsample_match_torch(tmp_path):
    """ConvTranspose2d WITH bias (decoder/upsampling heads) and
    anisotropic nearest upsampling, vs torch."""
    import torch.nn as tnn
    import torch.nn.functional as F

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.up = tnn.ConvTranspose2d(6, 3, 4, stride=2, padding=1,
                                          bias=True)

        def forward(self, x):
            y = torch.relu(self.up(x))
            return F.interpolate(y, scale_factor=(2.0, 3.0),
                                 mode="nearest")

    net = Net().eval()
    b = _script_and_load(tmp_path, net)
    x = np.random.RandomState(5).randn(1, 6, 5, 7).astype(np.float32)
    ours = np.asarray(_run_bundle(b, x)[0])
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


@needs_torch
@pytest.mark.parametrize("bidir", [False, True])
def test_scripted_lstm_matches_torch(tmp_path, bidir):
    """Scripted nn.LSTM (torch.lstm op): output + final states match
    torch, incl. two layers and bidirectional."""
    import torch.nn as tnn

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.rnn = tnn.LSTM(6, 5, num_layers=2,
                                batch_first=True,
                                bidirectional=bidir)
            self.fc = tnn.Linear(5 * (2 if bidir else 1), 3)

        def forward(self, x):
            y, (h, c) = self.rnn(x)
            return self.fc(y[:, -1]), h, c

    net = Net().eval()
    b = _script_and_load(tmp_path, net, name=f"lstm{bidir}.pt")
    x = np.random.RandomState(8).randn(2, 7, 6).astype(np.float32)
    outs = _run_bundle(b, x)
    with torch.no_grad():
        refs = net(torch.from_numpy(x))
    assert len(outs) == 3
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(np.asarray(o), r.numpy(),
                                   rtol=1e-4, atol=1e-5)


@needs_torch
def test_scripted_gru_matches_torch(tmp_path):
    import torch.nn as tnn

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.rnn = tnn.GRU(4, 8, batch_first=True)

        def forward(self, x):
            y, h = self.rnn(x)
            return y, h

    net = Net().eval()
    b = _script_and_load(tmp_path, net, name="gru.pt")
    x = np.random.RandomState(9).randn(3, 5, 4).astype(np.float32)
    outs = _run_bundle(b, x)
    with torch.no_grad():
        refs = net(torch.from_numpy(x))
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(np.asarray(o), r.numpy(),
                                   rtol=1e-4, atol=1e-5)


@needs_torch
def test_scripted_text_classifier_matches_torch(tmp_path):
    """Embedding + LSTM + Linear over int32 token ids — the text-model
    shape (integer pipeline inputs end-to-end)."""
    import torch.nn as tnn

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.emb = tnn.Embedding(50, 12)
            self.rnn = tnn.LSTM(12, 9, batch_first=True)
            self.fc = tnn.Linear(9, 4)

        def forward(self, ids):
            x = self.emb(ids)
            y, _ = self.rnn(x)
            return torch.softmax(self.fc(y[:, -1]), dim=1)

    net = Net().eval()
    b = _script_and_load(tmp_path, net, name="text.pt")
    ids = np.random.RandomState(10).randint(
        0, 50, (3, 11)).astype(np.int32)
    ours = np.asarray(_run_bundle(b, ids)[0])
    with torch.no_grad():
        ref = net(torch.from_numpy(ids.astype(np.int64))).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


@needs_torch
@pytest.mark.parametrize("causal", [False, True])
def test_scripted_attention_block_matches_torch(tmp_path, causal):
    """A scripted self-attention block using
    F.scaled_dot_product_attention — the modern exported attention op
    (torch 2.x) — matches torch, causal and full."""
    import torch.nn as tnn
    import torch.nn.functional as F

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.qkv = tnn.Linear(32, 96)
            self.out = tnn.Linear(32, 32)
            self.causal = causal

        def forward(self, x):
            B, S, D = x.shape[0], x.shape[1], x.shape[2]
            qkv = self.qkv(x).reshape(B, S, 3, 4, 8)
            q = qkv[:, :, 0].transpose(1, 2)
            k = qkv[:, :, 1].transpose(1, 2)
            v = qkv[:, :, 2].transpose(1, 2)
            a = F.scaled_dot_product_attention(q, k, v,
                                               is_causal=self.causal)
            a = a.transpose(1, 2).reshape(B, S, D)
            return self.out(a)

    net = Net().eval()
    b = _script_and_load(tmp_path, net, name=f"attn{causal}.pt")
    x = np.random.RandomState(11).randn(2, 10, 32).astype(np.float32)
    ours = np.asarray(_run_bundle(b, x)[0])
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


@needs_torch
def test_scripted_sdpa_causal_cross_length_matches_torch(tmp_path):
    """is_causal with Lq != Lk (KV-cached decode export shape): torch
    defines the mask as ones(L, S).tril(diagonal=0) — top-left aligned,
    not bottom-right (round-4 ADVICE)."""
    import torch.nn.functional as F

    class Net(torch.nn.Module):
        def forward(self, q, k, v):
            return F.scaled_dot_product_attention(q, k, v,
                                                  is_causal=True)

    net = Net().eval()
    b = _script_and_load(tmp_path, net, name="sdpa_cross.pt")
    rs = np.random.RandomState(13)
    q = rs.randn(2, 4, 6, 8).astype(np.float32)
    k = rs.randn(2, 4, 10, 8).astype(np.float32)
    v = rs.randn(2, 4, 10, 8).astype(np.float32)
    ours = np.asarray(_run_bundle(b, q, k, v)[0])
    with torch.no_grad():
        ref = net(torch.from_numpy(q), torch.from_numpy(k),
                  torch.from_numpy(v)).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


@needs_torch
def test_scripted_multihead_attention_matches_torch(tmp_path):
    """nn.MultiheadAttention scripts through its fused fast path
    (_native_multi_head_attention) — packed-QKV self-attention must
    match torch."""
    import torch.nn as tnn

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.mha = tnn.MultiheadAttention(32, 4, batch_first=True)
            self.ln = tnn.LayerNorm(32)

        def forward(self, x):
            y, _ = self.mha(x, x, x, need_weights=False)
            return self.ln(x + y)

    net = Net().eval()
    b = _script_and_load(tmp_path, net, name="mha.pt")
    x = np.random.RandomState(12).randn(2, 9, 32).astype(np.float32)
    ours = np.asarray(_run_bundle(b, x)[0])
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


@needs_torch
@pytest.mark.parametrize("norm_first,act", [(False, "gelu"),
                                            (True, "relu")])
def test_scripted_transformer_encoder_matches_torch(tmp_path,
                                                    norm_first, act):
    """nn.TransformerEncoder scripts through the fused
    _transformer_encoder_layer_fwd fast path — both norm orders and
    activations must match torch."""
    import torch.nn as tnn

    layer = tnn.TransformerEncoderLayer(
        d_model=32, nhead=4, dim_feedforward=64, batch_first=True,
        activation=act, norm_first=norm_first)
    net = tnn.TransformerEncoder(layer, num_layers=2).eval()
    path = str(tmp_path / f"enc{norm_first}{act}.pt")
    torch.jit.save(torch.jit.script(net), path)
    b = load_model_file(path)
    x = np.random.RandomState(13).randn(2, 9, 32).astype(np.float32)
    ours = np.asarray(_run_bundle(b, x)[0])
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


@needs_torch
def test_scripted_transformer_decoder_matches_torch(tmp_path):
    """nn.TransformerDecoder (self + cross attention through SDPA,
    two-input forward) matches torch."""
    import torch.nn as tnn

    layer = tnn.TransformerDecoderLayer(
        d_model=32, nhead=4, dim_feedforward=64, batch_first=True)
    net = tnn.TransformerDecoder(layer, num_layers=2).eval()
    path = str(tmp_path / "dec.pt")
    torch.jit.save(torch.jit.script(net), path)
    b = load_model_file(path)
    tgt = np.random.RandomState(14).randn(2, 7, 32).astype(np.float32)
    mem = np.random.RandomState(15).randn(2, 9, 32).astype(np.float32)
    ours = np.asarray(_run_bundle(b, tgt, mem)[0])
    with torch.no_grad():
        ref = net(torch.from_numpy(tgt), torch.from_numpy(mem)).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


@needs_torch
def test_scripted_residual_cnn_matches_torch(tmp_path):
    """ResNet-pattern residual blocks (conv+bn chains, strided
    downsample shortcut, adaptive pool head) — the deep-CNN shape,
    hand-built since torchvision is absent."""
    import torch.nn as tnn

    class Block(tnn.Module):
        def __init__(self, cin, cout, stride):
            super().__init__()
            self.c1 = tnn.Conv2d(cin, cout, 3, stride, 1, bias=False)
            self.b1 = tnn.BatchNorm2d(cout)
            self.c2 = tnn.Conv2d(cout, cout, 3, 1, 1, bias=False)
            self.b2 = tnn.BatchNorm2d(cout)
            self.down = (tnn.Sequential(
                tnn.Conv2d(cin, cout, 1, stride, bias=False),
                tnn.BatchNorm2d(cout))
                if stride != 1 or cin != cout else tnn.Identity())

        def forward(self, x):
            h = torch.relu(self.b1(self.c1(x)))
            h = self.b2(self.c2(h))
            return torch.relu(h + self.down(x))

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.stem = tnn.Conv2d(3, 8, 3, 1, 1)
            self.b1 = Block(8, 8, 1)
            self.b2 = Block(8, 16, 2)
            self.pool = tnn.AdaptiveAvgPool2d((1, 1))
            self.fc = tnn.Linear(16, 5)

        def forward(self, x):
            h = torch.relu(self.stem(x))
            h = self.b2(self.b1(h))
            return self.fc(self.pool(h).flatten(1))

    net = Net().eval()
    b = _script_and_load(tmp_path, net, name="resnet.pt")
    x = np.random.RandomState(16).randn(2, 3, 16, 16).astype(np.float32)
    ours = np.asarray(_run_bundle(b, x)[0])
    with torch.no_grad():
        ref = net(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)
