"""Compiled steady-state loop (runtime/compiled_loop.py + the
scheduler's window path, ISSUE 20): detector/signature/ledger units,
the full entry/bail matrix (shape change, window error, pending swap,
armed timer, EOS drain) driven through a real PipelineRunner with a
deterministic window-capable element, bit-parity of compiled-loop mode
vs per-frame mode (both the scheduler plumbing and the backend's
lax.scan window against per-frame invokes), and the paged-LLM decode
window's token parity.

Determinism note: each scenario pushes its whole trace (and EOS) into
AppSrc *before* the runner starts and gives the element a process()
sleep long enough that the source pump finishes enqueueing while the
first frame is in flight — so window collection always sees the full
queue and the bail points land exactly where the trace puts them.
"""

import time

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.elements.sinks import TensorSink
from nnstreamer_tpu.elements.sources import AppSrc
from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.graph.pipeline import Element
from nnstreamer_tpu.runtime.compiled_loop import (
    BAIL_CAUSES, LoopStats, SteadyStateDetector, frame_signature)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec


# -- pure units ---------------------------------------------------------------

class TestFrameSignature:
    def test_shape_dtype_identity(self):
        a = TensorBuffer.of(np.ones((2, 3), np.float32))
        b = TensorBuffer.of(np.zeros((2, 3), np.float32))
        c = TensorBuffer.of(np.ones((2, 4), np.float32))
        d = TensorBuffer.of(np.ones((2, 3), np.int32))
        assert frame_signature(a) == frame_signature(b)   # values ignored
        assert frame_signature(a) != frame_signature(c)   # shape matters
        assert frame_signature(a) != frame_signature(d)   # dtype matters

    def test_dyn_batch_count_is_part_of_identity(self):
        x = np.ones((4, 2), np.float32)
        a = TensorBuffer.of(x)
        b = TensorBuffer.of(x)
        b.meta["dyn_batch"] = {"n": 3}
        c = TensorBuffer.of(x)
        c.meta["dyn_batch"] = {"n": 2}
        assert frame_signature(a) != frame_signature(b)
        assert frame_signature(b) != frame_signature(c)

    def test_non_tensor_payload_stays_per_frame(self):
        assert frame_signature(object()) is None


class TestDetector:
    def test_arms_after_streak_and_resets_on_divergence(self):
        det = SteadyStateDetector(arm_after=3)
        sig_a = (((2, 3), "float32"),)
        sig_b = (((2, 4), "float32"),)
        assert [det.observe(sig_a) for _ in range(3)] == \
            [False, False, True]
        assert det.armed
        assert not det.observe(sig_b)        # divergence restarts streak
        assert not det.armed
        assert not det.observe(sig_b)
        assert det.observe(sig_b)            # re-arms on the new shape
        det.reset()
        assert not det.armed

    def test_none_signature_disarms(self):
        det = SteadyStateDetector(arm_after=1)
        assert det.observe((((1,), "f32"),))
        assert not det.observe(None)
        assert not det.armed


class TestLoopStats:
    def test_ledger_snapshot(self):
        ls = LoopStats()
        ls.entries += 2
        ls.steps += 9
        ls.bail("eos")
        ls.bail("shape")
        ls.bail("shape")
        snap = ls.snapshot()
        assert snap == {"loop_entries": 2, "compiled_steps": 9,
                        "loop_bails": {"eos": 1, "shape": 2}}
        assert set(snap["loop_bails"]) <= set(BAIL_CAUSES)


# -- scheduler bail matrix ----------------------------------------------------

class Doubler(Element):
    """Deterministic window-capable element: y = 2x, with injectable
    bail triggers. Mirrors exactly the surface the scheduler probes on
    tensor_filter (window_capable / swap_pending / process_window)."""

    ELEMENT_NAME = "test_doubler"
    CHAIN_FUSABLE = False      # keep a real worker thread + channel

    def __init__(self, name=None, *, sleep_s=0.02, fail_pts=(),
                 swap_bails=0, timer_after=None, **props):
        super().__init__(name, **props)
        self.calls = []                   # ("pf", pts) | ("win", [pts])
        self._sleep = sleep_s
        self._fail_pts = set(fail_pts)
        self._swap_bails = swap_bails
        self._timer_after = timer_after
        self._done = 0

    def negotiate(self, in_specs):
        return [in_specs[0]]

    def window_capable(self):
        return True

    def swap_pending(self):
        if self._swap_bails > 0:
            self._swap_bails -= 1
            return True
        return False

    def next_deadline(self):
        if self._timer_after is not None and \
                self._done >= self._timer_after:
            return time.perf_counter() + 60.0
        return None

    def _one(self, buf):
        out = TensorBuffer.of(np.asarray(buf.tensors[0]) * 2,
                              pts=buf.pts)
        return out

    def process(self, pad, buf):
        self.calls.append(("pf", buf.pts))
        if buf.pts in self._fail_pts:
            raise RuntimeError(f"boom at pts {buf.pts}")
        if self._sleep:
            time.sleep(self._sleep)
            self._sleep = 0.0             # only the head-start frame
        self._done += 1
        return [(0, self._one(buf))]

    def process_window(self, pad, bufs):
        pts = [b.pts for b in bufs]
        self.calls.append(("win", pts))
        if self._fail_pts.intersection(pts):
            raise RuntimeError(f"window boom at {pts}")
        self._done += len(bufs)
        return [(0, self._one(b)) for b in bufs]


def _run(frames, *, compiled=True, arm=2, window=4,
         expect_fail=False, **doubler_kw):
    """Push `frames` (np arrays, pts = index) + EOS, run to EOS, return
    (sink results, element, loop-stats dict)."""
    pipe = nns.Pipeline("cl_test")
    spec = TensorsSpec.of(TensorInfo(
        frames[0].shape, DType.from_name(frames[0].dtype.name)))
    src = AppSrc(spec=spec, name="src")
    dbl = Doubler(name="d", **doubler_kw)
    sink = TensorSink(name="out")
    for e in (src, dbl, sink):
        pipe.add(e)
    pipe.link(src, dbl)
    pipe.link(dbl, sink)
    for i, x in enumerate(frames):
        src.push(TensorBuffer.of(x, pts=i))
    src.end()                             # full trace queued before start
    r = nns.PipelineRunner(pipe, compiled_loop=compiled,
                           compiled_loop_arm=arm,
                           compiled_loop_window=window,
                           queue_capacity=max(16, len(frames) + 2))
    r.start()
    if expect_fail:
        with pytest.raises(StreamError):
            r.wait(60)
    else:
        r.wait(60)
    st = r.stats().get("d", {})
    loops = {k: st.get(k) for k in
             ("loop_entries", "compiled_steps", "loop_bails")}
    return sink.results, dbl, loops


def _frames(n, shape=(4, 2), dtype=np.float32, base=0):
    return [np.full(shape, base + i, dtype) for i in range(n)]


class TestBailMatrix:
    def test_steady_state_windows_with_exact_accounting(self):
        res, dbl, st = _run(_frames(10), arm=2, window=4)
        # trace: pts0 per-frame (streak 1), [1..4] and [5..8] windowed,
        # collection for pts9 hits EOS → per-frame 9, drain
        assert [b.pts for b in res] == list(range(10))
        assert st["loop_entries"] == 2
        assert st["compiled_steps"] == 8
        assert st["loop_bails"] == {"eos": 1}
        assert dbl.calls == [("pf", 0), ("win", [1, 2, 3, 4]),
                             ("win", [5, 6, 7, 8]), ("pf", 9)]

    def test_bit_parity_with_per_frame_mode(self):
        frames = _frames(12)
        res_on, _, st_on = _run(frames, compiled=True)
        res_off, _, st_off = _run(frames, compiled=False)
        assert st_on["compiled_steps"] > 0
        assert st_off["loop_entries"] is None     # loop never built
        assert len(res_on) == len(res_off) == 12
        for a, b in zip(res_on, res_off):
            assert a.pts == b.pts
            np.testing.assert_array_equal(np.asarray(a.tensors[0]),
                                          np.asarray(b.tensors[0]))

    def test_shape_change_bails_and_preserves_order(self):
        frames = _frames(5) + _frames(1, shape=(3, 3), base=50) \
            + _frames(4, base=100)
        res, dbl, st = _run(frames, arm=2, window=8)
        # the (3,3) frame at pts5 diverges mid-collection: parked, runs
        # per-frame AFTER the partial window, order preserved end-to-end
        assert st["loop_bails"].get("shape", 0) >= 1
        assert st["loop_entries"] >= 1
        assert [b.pts for b in res] == list(range(10))
        assert ("pf", 5) in dbl.calls         # divergent frame per-frame
        assert all(5 not in c[1] for c in dbl.calls if c[0] == "win")
        for b in res:                          # every value still 2x
            exp = np.asarray(frames[b.pts]) * 2
            np.testing.assert_array_equal(np.asarray(b.tensors[0]), exp)

    def test_window_error_reruns_per_frame_and_lands_exactly(self):
        # pts3 poisons both paths: the window [1..4] raises, every
        # frame re-runs per-frame, 1 and 2 still emit, the error policy
        # (fail-fast) fires on precisely pts3
        res, dbl, st = _run(_frames(10), arm=2, window=4,
                            fail_pts={3}, expect_fail=True)
        assert st["loop_bails"].get("error", 0) == 1
        assert st["loop_entries"] == 0         # the window never landed
        # the element's own log is the deterministic record: the window
        # raised, 1 and 2 re-ran (and emitted), 3 faulted per-frame —
        # nothing past the faulting frame ever ran
        assert dbl.calls == [("pf", 0), ("win", [1, 2, 3, 4]),
                             ("pf", 1), ("pf", 2), ("pf", 3)]
        # sink delivery during failure teardown is best-effort, but
        # whatever arrived is an in-order prefix of the pre-fault frames
        assert [b.pts for b in res] == list(range(len(res)))
        assert len(res) <= 3

    def test_window_only_error_recovers_completely(self):
        # poison pts -99 never matches a per-frame pts, but monkeypatch
        # the window to raise once: the re-run serves every frame
        class FlakyWindow(Doubler):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self._boomed = False

            def process_window(self, pad, bufs):
                if not self._boomed:
                    self._boomed = True
                    self.calls.append(("win", [b.pts for b in bufs]))
                    raise RuntimeError("transient window fault")
                return super().process_window(pad, bufs)

        pipe = nns.Pipeline("cl_flaky")
        frames = _frames(10)
        spec = TensorsSpec.of(TensorInfo(
            frames[0].shape, DType.from_name(frames[0].dtype.name)))
        src = AppSrc(spec=spec, name="src")
        dbl = FlakyWindow(name="d")
        sink = TensorSink(name="out")
        for e in (src, dbl, sink):
            pipe.add(e)
        pipe.link(src, dbl)
        pipe.link(dbl, sink)
        for i, x in enumerate(frames):
            src.push(TensorBuffer.of(x, pts=i))
        src.end()
        r = nns.PipelineRunner(pipe, compiled_loop=True,
                               compiled_loop_arm=2,
                               compiled_loop_window=4,
                               queue_capacity=16)
        r.start()
        r.wait(60)
        st = r.stats()["d"]
        assert st["loop_bails"].get("error", 0) == 1
        assert [b.pts for b in sink.results] == list(range(10))
        # the errored window's frames all re-ran per-frame, in order
        pf = [c[1] for c in dbl.calls if c[0] == "pf"]
        assert pf[:5] == [0, 1, 2, 3, 4]

    def test_swap_pending_is_a_transient_bail(self):
        res, dbl, st = _run(_frames(10), arm=2, window=4, swap_bails=1)
        # the first armed attempt bails (swap adoption happens
        # per-frame), the detector stays armed, the next frame windows
        assert st["loop_bails"].get("swap", 0) == 1
        assert st["loop_entries"] >= 1
        assert [b.pts for b in res] == list(range(10))

    def test_armed_timer_bails_to_per_frame(self):
        # after 3 frames the element holds a (future) deadline: every
        # armed attempt from then on bails — deadline-owning elements
        # must flush on time, which per-frame mode guarantees
        res, dbl, st = _run(_frames(10), arm=2, window=4, timer_after=3)
        assert st["loop_bails"].get("timer", 0) >= 1
        assert [b.pts for b in res] == list(range(10))
        assert all(len(c[1]) <= 4 for c in dbl.calls if c[0] == "win")

    def test_eos_drains_partial_window(self):
        # 4 frames, window 8: the one window collection runs into EOS,
        # pow2 round-down windows [1,2], the leftover (3) and the EOS
        # drain per-frame behind it
        res, dbl, st = _run(_frames(4), arm=2, window=8)
        assert st["loop_bails"] == {"eos": 1}
        assert st["loop_entries"] == 1
        assert st["compiled_steps"] == 2
        assert dbl.calls == [("pf", 0), ("win", [1, 2]), ("pf", 3)]
        assert [b.pts for b in res] == list(range(4))

    def test_pow2_round_down_leftover_stays_ordered(self):
        # 8 frames, window 8: pts0 per-frame, collection sweeps [1..7]
        # (7 frames) + EOS → k=4 window, leftover [5,6,7] per-frame
        res, dbl, st = _run(_frames(8), arm=2, window=8)
        assert st["compiled_steps"] == 4
        assert dbl.calls == [("pf", 0), ("win", [1, 2, 3, 4]),
                             ("pf", 5), ("pf", 6), ("pf", 7)]
        assert [b.pts for b in res] == list(range(8))


# -- real backend: lax.scan window vs per-frame invokes -----------------------

class TestBackendWindowParity:
    def test_invoke_window_bit_identical_to_per_frame(self):
        """The scan body IS the per-frame jitted fn — same weights,
        same frame order, byte-identical logits."""
        from nnstreamer_tpu.elements import TensorFilter

        filt = TensorFilter(
            name="f", compiled_loop=True,
            model="zoo://mobilenet_v2?width=0.35&input_size=32"
                  "&dtype=float32")
        spec = TensorsSpec.of(TensorInfo((1, 32, 32, 3), DType.FLOAT32))
        filt.negotiate([spec])
        filt.start()
        try:
            assert filt.window_capable()
            rng = np.random.default_rng(0)
            frames = [rng.normal(size=(1, 32, 32, 3)).astype(np.float32)
                      for _ in range(4)]
            bufs = [TensorBuffer.of(x, pts=i)
                    for i, x in enumerate(frames)]
            per = [filt.process(0, b)[0][1] for b in bufs]
            bufs2 = [TensorBuffer.of(x, pts=i)
                     for i, x in enumerate(frames)]
            win = [b for _, b in filt.process_window(0, bufs2)]
            assert len(win) == len(per) == 4
            for a, b in zip(per, win):
                assert a.pts == b.pts
                for ta, tb in zip(a.tensors, b.tensors):
                    np.testing.assert_array_equal(np.asarray(ta),
                                                  np.asarray(tb))
            be = filt.backend
            assert be.window_invokes >= 1
            assert be.window_frames >= 4
        finally:
            filt.stop()


# -- paged-LLM decode window --------------------------------------------------

class TestLLMDecodeWindowParity:
    def _engine(self, window):
        from nnstreamer_tpu.llm.engine import LLMEngine
        from nnstreamer_tpu.models.transformer import init_params

        params = init_params(vocab=61, d_model=32, n_layers=2,
                             n_heads=4, n_kv_heads=2, seed=0)
        return LLMEngine(params, n_heads=4, block_size=4, num_blocks=32,
                         max_batch=4, max_len=64, decode_window=window)

    def test_token_parity_mixed_budgets(self):
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, 60, size=s).tolist()
                   for s in (5, 9, 3)]
        n_new = [12, 7, 10]

        def run(window):
            eng = self._engine(window)
            reqs = [eng.submit(p, max_new_tokens=m, eos_id=None)
                    for p, m in zip(prompts, n_new)]
            eng.drain()
            return [list(r.tokens) for r in reqs], eng.stats()

        toks_win, st_win = run(8)
        toks_ref, st_ref = run(0)
        assert st_win["decode_windows"] > 0
        assert st_ref["decode_windows"] == 0
        assert toks_win == toks_ref
        assert [len(t) for t in toks_win] == n_new

    def test_eos_mid_window_truncates_identically(self):
        rng = np.random.default_rng(3)
        prompt = rng.integers(1, 60, size=5).tolist()
        outs = {}
        for window in (8, 0):
            eng = self._engine(window)
            r = eng.submit(prompt, max_new_tokens=12, eos_id=7)
            eng.drain()
            outs[window] = (list(r.tokens), r.finish_reason)
        assert outs[8] == outs[0]
