"""Element-set tests: routing, sync policies, aggregator, control flow,
repo loops, sparse codec elements, debug.

Technique mirrors the reference (SURVEY.md §4): deterministic synthetic
buffers through in-process pipelines; fake 'models' are plain callables
(custom-easy analog) so no XLA is needed for element logic.
"""

import time

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.elements import (
    AppSrc,
    Join,
    Tee,
    TensorAggregator,
    TensorCrop,
    TensorDebug,
    TensorDemux,
    TensorIf,
    TensorMerge,
    TensorMux,
    TensorRate,
    TensorRepoSink,
    TensorRepoSrc,
    TensorSink,
    TensorSparseDec,
    TensorSparseEnc,
    TensorSplit,
    register_if_condition)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorFormat, TensorInfo, TensorsSpec


def spec_of(*shapes, dtype=DType.FLOAT32):
    return TensorsSpec.of(*(TensorInfo(s, dtype) for s in shapes))


def run_graph(elements, links, pushes, timeout=30):
    """Build/run a pipeline; pushes = {src_name: [buffers]}. Returns the
    pipeline (sinks hold .results)."""
    pipe = nns.Pipeline()
    for e in elements:
        pipe.add(e)
    for a, b, *pads in links:
        pipe.link(a, b, *(pads or []))
    runner = nns.PipelineRunner(pipe)
    runner.start()
    for name, bufs in pushes.items():
        src = pipe.get(name)
        for b in bufs:
            src.push(b)
        src.end()
    runner.wait(timeout)
    return pipe


def buf(val, shape=(2, 2), pts=0, dtype=np.float32):
    return TensorBuffer.of(np.full(shape, val, dtype), pts=pts)


# -- mux / sync policies -----------------------------------------------------

def test_mux_nosync_pairs_fifo():
    a = AppSrc(spec=spec_of((2, 2)), name="a")
    b = AppSrc(spec=spec_of((3,)), name="b")
    mux = TensorMux(name="m", sync_mode="nosync")
    sink = TensorSink(name="s")
    pipe = run_graph(
        [a, b, mux, sink],
        [(a, mux, 0, 0), (b, mux, 0, 1), (mux, sink)],
        {"a": [buf(1, pts=0), buf(2, pts=50)],
         "b": [buf(10, (3,), pts=0), buf(20, (3,), pts=60)]},
    )
    res = sink.results
    assert len(res) == 2
    assert res[0].num_tensors == 2
    np.testing.assert_array_equal(res[0].tensors[0], np.full((2, 2), 1))
    np.testing.assert_array_equal(res[1].tensors[1], np.full((3,), 20))


def test_mux_slowest_drops_stale_frames():
    a = AppSrc(spec=spec_of((1,)), name="a")
    b = AppSrc(spec=spec_of((1,)), name="b")
    mux = TensorMux(name="m", sync_mode="slowest")
    sink = TensorSink(name="s")
    # pad a at 10Hz (0,100,200ms), pad b slow (0, 200ms): frame 100 on a
    # must be dropped when pairing for base 200. Push a's frames first and
    # let them drain into the mux before b's arrive, so the stale-frame
    # decision sees the catch-up queue (deterministic ordering).
    ns = 1_000_000
    pipe = nns.Pipeline()
    for e in (a, b, mux, sink):
        pipe.add(e)
    pipe.link(a, mux, 0, 0)
    pipe.link(b, mux, 0, 1)
    pipe.link(mux, sink)
    runner = nns.PipelineRunner(pipe).start()
    for bb in (buf(0, (1,), pts=0), buf(1, (1,), pts=100 * ns),
               buf(2, (1,), pts=200 * ns)):
        a.push(bb)
    time.sleep(0.2)  # a's frames reach the mux queue first
    b.push(buf(10, (1,), pts=0))
    b.push(buf(11, (1,), pts=200 * ns))
    a.end()
    b.end()
    runner.wait(30)
    res = sink.results
    assert len(res) == 2
    np.testing.assert_array_equal(res[0].tensors[0], [0])
    np.testing.assert_array_equal(res[1].tensors[0], [2])  # 1 dropped
    np.testing.assert_array_equal(res[1].tensors[1], [11])


def test_merge_concat_axis():
    a = AppSrc(spec=spec_of((2, 3)), name="a")
    b = AppSrc(spec=spec_of((2, 5)), name="b")
    merge = TensorMerge(name="m", option="1", sync_mode="nosync")
    sink = TensorSink(name="s")
    pipe = run_graph(
        [a, b, merge, sink],
        [(a, merge, 0, 0), (b, merge, 0, 1), (merge, sink)],
        {"a": [buf(1, (2, 3))], "b": [buf(2, (2, 5))]},
    )
    assert merge.out_specs[0].tensors[0].shape == (2, 8)
    out = sink.results[0].tensors[0]
    assert out.shape == (2, 8)
    np.testing.assert_array_equal(out[:, :3], np.full((2, 3), 1))


def test_demux_tensorpick_reorder():
    src = AppSrc(spec=spec_of((1,), (2,), (3,)), name="src")
    demux = TensorDemux(name="d", tensorpick="2,0")
    s1 = TensorSink(name="s1")
    s2 = TensorSink(name="s2")
    three = TensorBuffer.of(np.zeros((1,), np.float32),
                            np.ones((2,), np.float32),
                            np.full((3,), 2, np.float32), pts=0)
    pipe = run_graph(
        [src, demux, s1, s2],
        [(src, demux), (demux, s1, 0, 0), (demux, s2, 1, 0)],
        {"src": [three]},
    )
    assert s1.results[0].tensors[0].shape == (3,)
    assert s2.results[0].tensors[0].shape == (1,)


def test_split_segments():
    src = AppSrc(spec=spec_of((2, 8)), name="src")
    split = TensorSplit(name="sp", tensorseg="3:5", axis=1)
    s1 = TensorSink(name="s1")
    s2 = TensorSink(name="s2")
    arr = np.arange(16, dtype=np.float32).reshape(2, 8)
    pipe = run_graph(
        [src, split, s1, s2],
        [(src, split), (split, s1, 0, 0), (split, s2, 1, 0)],
        {"src": [TensorBuffer.of(arr, pts=0)]},
    )
    np.testing.assert_array_equal(s1.results[0].tensors[0], arr[:, :3])
    np.testing.assert_array_equal(s2.results[0].tensors[0], arr[:, 3:])


def test_split_then_merge_roundtrip():
    src = AppSrc(spec=spec_of((4, 6)), name="src")
    split = TensorSplit(name="sp", tensorseg="2:4", axis=1)
    merge = TensorMerge(name="mg", option="1", sync_mode="nosync")
    sink = TensorSink(name="s")
    arr = np.arange(24, dtype=np.float32).reshape(4, 6)
    pipe = run_graph(
        [src, split, merge, sink],
        [(src, split), (split, merge, 0, 0), (split, merge, 1, 1),
         (merge, sink)],
        {"src": [TensorBuffer.of(arr, pts=0)]},
    )
    np.testing.assert_array_equal(sink.results[0].tensors[0], arr)


def test_tee_duplicates_and_join_rejoins():
    src = AppSrc(spec=spec_of((2,)), name="src")
    tee = Tee(name="t")
    j = Join(name="j")
    sink = TensorSink(name="s")
    pipe = run_graph(
        [src, tee, j, sink],
        [(src, tee), (tee, j, 0, 0), (tee, j, 1, 1), (j, sink)],
        {"src": [buf(5, (2,))]},
    )
    assert len(sink.results) == 2  # both branches delivered


# -- aggregator --------------------------------------------------------------

def test_aggregator_tumbling_window():
    src = AppSrc(spec=spec_of((1, 4)), name="src")
    agg = TensorAggregator(name="agg", frames_out=3, frames_dim=0)
    sink = TensorSink(name="s")
    bufs = [TensorBuffer.of(np.full((1, 4), i, np.float32), pts=i)
            for i in range(7)]
    pipe = run_graph([src, agg, sink], [(src, agg), (agg, sink)],
                     {"src": bufs})
    assert agg.out_specs[0].tensors[0].shape == (3, 4)
    res = sink.results
    assert len(res) == 2  # 7 frames → 2 windows of 3, 1 leftover dropped
    np.testing.assert_array_equal(res[0].tensors[0][:, 0], [0, 1, 2])
    np.testing.assert_array_equal(res[1].tensors[0][:, 0], [3, 4, 5])


def test_aggregator_sliding_window():
    src = AppSrc(spec=spec_of((1, 2)), name="src")
    agg = TensorAggregator(name="agg", frames_out=2, frames_flush=1,
                           frames_dim=0)
    sink = TensorSink(name="s")
    bufs = [TensorBuffer.of(np.full((1, 2), i, np.float32), pts=i)
            for i in range(4)]
    pipe = run_graph([src, agg, sink], [(src, agg), (agg, sink)],
                     {"src": bufs})
    res = sink.results
    # windows: [0,1] [1,2] [2,3]
    assert len(res) == 3
    np.testing.assert_array_equal(res[1].tensors[0][:, 0], [1, 2])


# -- tensor_if ---------------------------------------------------------------

def test_tensor_if_then_else_branching():
    src = AppSrc(spec=spec_of((2,)), name="src")
    tif = TensorIf(name="if", compared_value="a_value",
                   compared_value_option="0:0", operator="gt",
                   supplied_value=5.0, then="passthrough", else_="passthrough")
    st = TensorSink(name="st")
    se = TensorSink(name="se")
    pipe = run_graph(
        [src, tif, st, se],
        [(src, tif), (tif, st, 0, 0), (tif, se, 1, 0)],
        {"src": [buf(9, (2,), pts=0), buf(1, (2,), pts=1)]},
    )
    assert len(st.results) == 1 and len(se.results) == 1
    np.testing.assert_array_equal(st.results[0].tensors[0], [9, 9])
    np.testing.assert_array_equal(se.results[0].tensors[0], [1, 1])


def test_tensor_if_average_fill_zero():
    src = AppSrc(spec=spec_of((4,)), name="src")
    tif = TensorIf(name="if", compared_value="average",
                   compared_value_option="0", operator="ge",
                   supplied_value=2.0, then="fill_zero", else_="skip")
    sink = TensorSink(name="s")
    pipe = run_graph(
        [src, tif, sink],
        [(src, tif), (tif, sink)],
        {"src": [buf(3, (4,), pts=0), buf(1, (4,), pts=1)]},
    )
    assert len(sink.results) == 1  # second skipped
    np.testing.assert_array_equal(sink.results[0].tensors[0], np.zeros(4))


def test_tensor_if_custom_condition():
    register_if_condition("evens", lambda b: int(b.pts or 0) % 2 == 0)
    src = AppSrc(spec=spec_of((1,)), name="src")
    tif = TensorIf(name="if", compared_value="custom",
                   compared_value_option="evens")
    sink = TensorSink(name="s")
    pipe = run_graph(
        [src, tif, sink], [(src, tif), (tif, sink)],
        {"src": [buf(i, (1,), pts=i) for i in range(5)]},
    )
    assert len(sink.results) == 3  # pts 0,2,4


# -- tensor_rate -------------------------------------------------------------

def test_tensor_rate_downsample():
    ns = 1_000_000_000
    src = AppSrc(spec=spec_of((1,)), name="src")
    rate = TensorRate(name="r", framerate="1/1")  # 1 fps
    sink = TensorSink(name="s")
    # 4 fps input over 2s
    bufs = [TensorBuffer.of(np.full((1,), i, np.float32), pts=i * ns // 4)
            for i in range(8)]
    pipe = run_graph([src, rate, sink], [(src, rate), (rate, sink)],
                     {"src": bufs})
    res = sink.results
    assert 2 <= len(res) <= 3
    assert rate.dropped > 0
    # slot PTS are exact multiples of 1s
    assert all((b.pts % ns) == 0 for b in res)


def test_tensor_rate_upsample_duplicates():
    ns = 1_000_000_000
    src = AppSrc(spec=spec_of((1,)), name="src")
    rate = TensorRate(name="r", framerate="4/1")
    sink = TensorSink(name="s")
    bufs = [TensorBuffer.of(np.full((1,), i, np.float32), pts=i * ns)
            for i in range(2)]  # 1 fps input
    pipe = run_graph([src, rate, sink], [(src, rate), (rate, sink)],
                     {"src": bufs})
    assert len(sink.results) >= 4  # 0..1s at 4fps

# -- tensor_crop -------------------------------------------------------------

def test_tensor_crop_regions():
    raw_spec = spec_of((8, 8, 3), dtype=DType.UINT8)
    src = AppSrc(spec=raw_spec, name="raw")
    info = AppSrc(spec=spec_of((1, 4), dtype=DType.UINT32), name="info")
    crop = TensorCrop(name="c")
    sink = TensorSink(name="s")
    img = np.arange(8 * 8 * 3, dtype=np.uint8).reshape(8, 8, 3)
    region = np.array([[2, 1, 4, 3]], np.uint32)  # x,y,w,h
    pipe = run_graph(
        [src, info, crop, sink],
        [(src, crop, 0, 0), (info, crop, 0, 1), (crop, sink)],
        {"raw": [TensorBuffer.of(img, pts=0)],
         "info": [TensorBuffer.of(region, pts=0)]},
    )
    out = sink.results[0]
    assert out.format == TensorFormat.FLEXIBLE
    assert out.tensors[0].shape == (3, 4, 3)  # h=3, w=4
    np.testing.assert_array_equal(out.tensors[0], img[1:4, 2:6])


# -- repo loop ---------------------------------------------------------------

def test_repo_feedback_loop_accumulates():
    """reposrc primes zeros; filter adds input; reposink feeds back.
    Chain: reposrc → (state) mux with appsrc → custom add → tee →
    [reposink, sink]. After 3 inputs the state is the running sum."""
    from nnstreamer_tpu.backends.custom import register_custom_easy
    from nnstreamer_tpu.elements import REPO, TensorFilter

    REPO.reset()
    register_custom_easy("add_pair", lambda ts: (ts[0] + ts[1],))
    state = TensorRepoSrc(name="state", slot=7, dims="4", count=4)
    xs = AppSrc(spec=spec_of((4,)), name="xs")
    mux = TensorMux(name="m", sync_mode="nosync")
    f = TensorFilter(name="f", framework="custom", model="add_pair")
    tee = Tee(name="t")
    back = TensorRepoSink(name="back", slot=7)
    sink = TensorSink(name="s")
    pipe = run_graph(
        [state, xs, mux, f, tee, back, sink],
        [(state, mux, 0, 0), (xs, mux, 0, 1), (mux, f), (f, tee),
         (tee, back, 0, 0), (tee, sink, 1, 0)],
        {"xs": [buf(1, (4,), pts=i) for i in range(4)]},
    )
    sums = [r.tensors[0][0] for r in sink.results]
    assert sums == [1, 2, 3, 4]


# -- sparse ------------------------------------------------------------------

def test_sparse_enc_dec_roundtrip():
    src = AppSrc(spec=spec_of((4, 4)), name="src")
    enc = TensorSparseEnc(name="e")
    dec = TensorSparseDec(name="d")
    sink = TensorSink(name="s")
    arr = np.zeros((4, 4), np.float32)
    arr[1, 2] = 5.0
    arr[3, 3] = -1.5
    pipe = run_graph(
        [src, enc, dec, sink],
        [(src, enc), (enc, dec), (dec, sink)],
        {"src": [TensorBuffer.of(arr, pts=0)]},
    )
    np.testing.assert_array_equal(sink.results[0].tensors[0], arr)
    assert enc.out_specs[0].format == TensorFormat.SPARSE


# -- debug -------------------------------------------------------------------

def test_debug_passthrough_captures():
    src = AppSrc(spec=spec_of((2,)), name="src")
    dbg = TensorDebug(name="dbg", capture=True, verbose=True)
    sink = TensorSink(name="s")
    pipe = run_graph([src, dbg, sink], [(src, dbg), (dbg, sink)],
                     {"src": [buf(7, (2,))]})
    assert len(sink.results) == 1
    assert any("float32[2]" in l for l in dbg.lines)
    assert any("max=7" in l for l in dbg.lines)


def test_mux_basepad_expires_unmatchable_heads():
    """A permanently-laggy partner pad must not stall the group: when the
    partner's oldest frame is already past base+window, the base head is
    dropped and collection proceeds (VERDICT r1 weak #7)."""
    a = AppSrc(spec=spec_of((1,)), name="a")
    b = AppSrc(spec=spec_of((1,)), name="b")
    mux = TensorMux(name="m", sync_mode="basepad", sync_option="0:10")
    sink = TensorSink(name="s")
    pipe = nns.Pipeline()
    for e in (a, b, mux, sink):
        pipe.add(e)
    pipe.link(a, mux, 0, 0)
    pipe.link(b, mux, 0, 1)
    pipe.link(mux, sink)
    runner = nns.PipelineRunner(pipe).start()
    # base pad: pts 0, 100, 200; partner: pts 5 then jumps to 1000.
    for bb in (buf(0, (1,), pts=0), buf(1, (1,), pts=100),
               buf(2, (1,), pts=200)):
        a.push(bb)
    time.sleep(0.2)
    b.push(buf(10, (1,), pts=5))     # pairs with base pts=0 (within ±10)
    time.sleep(0.2)
    b.push(buf(11, (1,), pts=1000))  # bases 100 & 200 become unmatchable
    a.push(buf(3, (1,), pts=995))    # pairs with partner pts=1000
    a.end()
    b.end()
    runner.wait(30)
    res = sink.results
    # progress despite the gap: (0,5) emitted, 100/200 expired, (995,1000)
    assert len(res) == 2
    assert [float(r.tensors[0][0]) for r in res] == [0.0, 3.0]
    assert [float(r.tensors[1][0]) for r in res] == [10.0, 11.0]


def test_parse_bad_pad_reference_raises():
    """Malformed direction-qualified pads (e.g. 'mux.foo_1') must raise,
    not silently fall back to next-free-pad (ADVICE r1)."""
    from nnstreamer_tpu.core.errors import PipelineError as PE

    with pytest.raises(PE, match="pad reference"):
        nns.parse_launch(
            "appsrc dims=2 name=a ! m.foo_1 tensor_mux name=m ! fakesink")


# -- tensor_if range operators + fill actions (VERDICT r1 item 8) -----------

def _if_graph(iff, bufs, two_branches=True):
    src = AppSrc(spec=spec_of((4,)), name="src")
    s_then = TensorSink(name="then_s")
    elems = [src, iff, s_then]
    links = [(src, iff), (iff, s_then, 0, 0)]
    s_else = None
    if two_branches:
        s_else = TensorSink(name="else_s")
        elems.append(s_else)
        links.append((iff, s_else, 1, 0))
    pipe = run_graph(elems, links, {"src": bufs})
    return s_then, s_else


def _val_buf(v, pts=0):
    return TensorBuffer.of(np.full((4,), v, np.float32), pts=pts)


def test_if_range_inclusive_routes_both_branches():
    iff = TensorIf(name="i", compared_value="a_value",
                   compared_value_option="0:0",
                   operator="range_inclusive", supplied_value="2:5",
                   else_="passthrough")
    s_then, s_else = _if_graph(
        iff, [_val_buf(2, 0), _val_buf(5, 1), _val_buf(6, 2)])
    assert len(s_then.results) == 2       # 2 and 5 inclusive
    assert len(s_else.results) == 1       # 6 outside


def test_if_range_exclusive_and_not_in_range():
    iff = TensorIf(name="i", operator="range_exclusive",
                   supplied_value="2:5", else_="passthrough")
    s_then, s_else = _if_graph(iff, [_val_buf(2, 0), _val_buf(3, 1)])
    assert len(s_then.results) == 1 and len(s_else.results) == 1
    iff2 = TensorIf(name="i2", operator="not_in_range_inclusive",
                    supplied_value="2:5", else_="passthrough")
    s_then, s_else = _if_graph(iff2, [_val_buf(2, 0), _val_buf(9, 1)])
    assert len(s_then.results) == 1       # 9 not in [2,5]
    assert float(s_then.results[0].tensors[0][0]) == 9.0


def test_if_range_needs_two_values():
    with pytest.raises(nns.core.errors.PipelineError, match="2 supplied"):
        TensorIf(name="i", operator="range_inclusive", supplied_value="3")
    with pytest.raises(nns.core.errors.PipelineError, match="lo.*hi|> hi"):
        TensorIf(name="i", operator="range_inclusive", supplied_value="5:2")


def test_if_fill_values_broadcast_and_per_tensor():
    iff = TensorIf(name="i", operator="gt", supplied_value="10",
                   then="passthrough", else_="fill_values",
                   else_option="7.5")
    s_then, s_else = _if_graph(iff, [_val_buf(1, 0)])
    np.testing.assert_array_equal(s_else.results[0].tensors[0],
                                  np.full((4,), 7.5, np.float32))


def test_if_fill_values_wrong_count_fails():
    iff = TensorIf(name="i", operator="gt", supplied_value="10",
                   else_="fill_values", else_option="1,2,3")
    from nnstreamer_tpu.core.errors import StreamError

    with pytest.raises((nns.core.errors.PipelineError, StreamError),
                       match="fill_values"):
        _if_graph(iff, [_val_buf(1, 0)])


def test_if_fill_with_file(tmp_path):
    payload = np.arange(4, dtype=np.float32)
    f = tmp_path / "fill.raw"
    f.write_bytes(payload.tobytes())
    iff = TensorIf(name="i", operator="gt", supplied_value="10",
                   else_="fill_with_file", else_option=str(f))
    s_then, s_else = _if_graph(iff, [_val_buf(1, 0)])
    np.testing.assert_array_equal(s_else.results[0].tensors[0], payload)


def test_if_fill_with_file_too_small(tmp_path):
    f = tmp_path / "small.raw"
    f.write_bytes(b"\x00" * 4)   # needs 16
    iff = TensorIf(name="i", operator="gt", supplied_value="10",
                   else_="fill_with_file", else_option=str(f))
    from nnstreamer_tpu.core.errors import StreamError

    with pytest.raises((nns.core.errors.PipelineError, StreamError),
                       match="fill file"):
        _if_graph(iff, [_val_buf(1, 0)])


def test_if_fill_with_file_missing_fails_at_build():
    with pytest.raises(nns.core.errors.PipelineError, match="cannot read"):
        TensorIf(name="i", else_="fill_with_file",
                 else_option="/nonexistent/fill.raw")


def test_if_repeat_previous_no_history_skips():
    iff = TensorIf(name="i", operator="gt", supplied_value="5",
                   then="repeat_previous", else_="skip")
    src = AppSrc(spec=spec_of((4,)), name="src")
    s_then, s_else = TensorSink(name="t"), TensorSink(name="e")
    pipe = run_graph(
        [src, iff, s_then, s_else],
        [(src, iff), (iff, s_then, 0, 0), (iff, s_else, 1, 0)],
        {"src": [_val_buf(9, 0), _val_buf(8, 1)]})
    # nothing was ever forwarded, so there is nothing to repeat
    assert len(pipe.get("t").results) == 0
    assert len(pipe.get("e").results) == 0


def test_if_repeat_previous_repeats_last_forwarded():
    """else=repeat_previous re-sends the last good (then) frame with the
    failing frame's PTS — the hold-last-value idiom."""
    iff = TensorIf(name="i", operator="gt", supplied_value="5",
                   then="passthrough", else_="repeat_previous")
    src = AppSrc(spec=spec_of((4,)), name="src")
    s_then, s_else = TensorSink(name="t"), TensorSink(name="e")
    pipe = run_graph(
        [src, iff, s_then, s_else],
        [(src, iff), (iff, s_then, 0, 0), (iff, s_else, 1, 0)],
        {"src": [_val_buf(9, 0), _val_buf(1, 1), _val_buf(7, 2),
                 _val_buf(2, 3)]})
    t_res, e_res = pipe.get("t").results, pipe.get("e").results
    assert len(t_res) == 2                           # 9, 7 pass
    assert len(e_res) == 2                           # 1, 2 repeat history
    np.testing.assert_array_equal(e_res[0].tensors[0],
                                  t_res[0].tensors[0])   # repeats the 9
    np.testing.assert_array_equal(e_res[1].tensors[0],
                                  t_res[1].tensors[0])   # repeats the 7
    assert e_res[0].pts == _val_buf(1, 1).pts        # current frame's PTS


def test_if_fill_actions_are_per_branch(tmp_path):
    """then and else each have their own fill material (regression: a
    shared attribute let else's file clobber then's)."""
    a, b = (np.full(4, 11, np.float32), np.full(4, 22, np.float32))
    fa, fb = tmp_path / "a.raw", tmp_path / "b.raw"
    fa.write_bytes(a.tobytes())
    fb.write_bytes(b.tobytes())
    iff = TensorIf(name="i", operator="gt", supplied_value="5",
                   then="fill_with_file", then_option=str(fa),
                   else_="fill_with_file", else_option=str(fb))
    src = AppSrc(spec=spec_of((4,)), name="src")
    s_then, s_else = TensorSink(name="t"), TensorSink(name="e")
    pipe = run_graph(
        [src, iff, s_then, s_else],
        [(src, iff), (iff, s_then, 0, 0), (iff, s_else, 1, 0)],
        {"src": [_val_buf(9, 0), _val_buf(1, 1)]})
    np.testing.assert_array_equal(pipe.get("t").results[0].tensors[0], a)
    np.testing.assert_array_equal(pipe.get("e").results[0].tensors[0], b)


def test_if_fill_values_bad_option_fails_at_build():
    with pytest.raises(nns.core.errors.PipelineError, match="fill_values"):
        TensorIf(name="i", else_="fill_values", else_option="1,x")


# -- tensor_rate upstream QoS (skip-before-compute) --------------------------

def test_rate_throttle_posts_qos_and_source_skips():
    pipe = nns.parse_launch(
        "videotestsrc num-buffers=40 framerate=100/1 pattern=solid ! "
        "tensor_converter ! "
        "tensor_rate name=r framerate=10/1 throttle=true ! "
        "tensor_sink name=s")
    nns.run_pipeline(pipe, timeout=60)
    src = next(e for e in pipe.elements.values()
               if e.ELEMENT_NAME == "videotestsrc")
    rate = pipe.get("r")
    # the source stopped generating frames that would be dropped: after
    # the first drop triggers QoS, generation paces at 10/1
    assert src.qos_skipped > 10
    # only the in-flight window (bounded queues) could still drop —
    # far fewer than the ~36 drops without throttle
    assert rate.dropped < 15


def test_rate_no_throttle_source_never_skips():
    pipe = nns.parse_launch(
        "videotestsrc num-buffers=40 framerate=100/1 pattern=solid ! "
        "tensor_converter ! "
        "tensor_rate name=r framerate=10/1 throttle=false ! "
        "tensor_sink name=s")
    nns.run_pipeline(pipe, timeout=60)
    src = next(e for e in pipe.elements.values()
               if e.ELEMENT_NAME == "videotestsrc")
    assert src.qos_skipped == 0
    assert pipe.get("r").dropped > 20


def test_if_repeat_previous_rejects_tensorpick_pairing():
    """Cross-branch replay is only spec-safe for shape-preserving
    partners; tensorpick would leak a subset onto the full-spec pad."""
    iff = TensorIf(name="i", operator="gt", supplied_value="5",
                   then="tensorpick", then_option="0",
                   else_="repeat_previous")
    src = AppSrc(spec=spec_of((4,)), name="src")
    s_then, s_else = TensorSink(name="t"), TensorSink(name="e")
    with pytest.raises(nns.core.errors.NegotiationError,
                       match="repeat_previous cannot pair"):
        run_graph([src, iff, s_then, s_else],
                  [(src, iff), (iff, s_then, 0, 0), (iff, s_else, 1, 0)],
                  {"src": []})
