"""Tests: tensor_trainer, SingleShot, CLI, filesrc, per-element stats."""

import json
import subprocess
import sys

import numpy as np

import nnstreamer_tpu as nns
from nnstreamer_tpu.elements import AppSrc, TensorSink
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec


def test_trainer_element_loss_decreases(tmp_path):
    from nnstreamer_tpu.trainer import TensorTrainer

    spec = TensorsSpec.of(TensorInfo((4, 32, 32, 3), DType.FLOAT32),
                          TensorInfo((4,), DType.INT32))
    src = AppSrc(spec=spec, name="src")
    tr = TensorTrainer(
        name="tr", model="zoo://mobilenet_v2?width=0.35&num_classes=8",
        optimizer="adam:0.01",
        checkpoint_dir=str(tmp_path), checkpoint_every=6)
    sink = TensorSink(name="s")
    pipe = nns.Pipeline()
    for e in (src, tr, sink):
        pipe.add(e)
    pipe.link(src, tr)
    pipe.link(tr, sink)
    runner = nns.PipelineRunner(pipe).start()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 32, 32, 3)).astype(np.float32)
    y = np.array([0, 1, 2, 3], np.int32)
    for i in range(6):
        src.push(TensorBuffer.of(x, y, pts=i))  # same batch → must overfit
    src.end()
    runner.wait(120)
    losses = [float(r.tensors[0][0]) for r in sink.results]
    assert len(losses) == 6
    assert losses[-1] < losses[0], losses  # learning happened
    assert tr.steps == 6
    # checkpoint written at step 6
    assert (tmp_path / "step_6").exists()


def test_trainer_sharded_on_mesh(eight_cpu_devices):
    from nnstreamer_tpu.trainer import TensorTrainer

    spec = TensorsSpec.of(TensorInfo((8, 16, 16, 3), DType.FLOAT32),
                          TensorInfo((8,), DType.INT32))
    src = AppSrc(spec=spec, name="src")
    tr = TensorTrainer(
        name="tr", model="zoo://mobilenet_v2?width=0.35&num_classes=8",
        optimizer="sgd:0.01", mesh="dp=4,tp=2")
    sink = TensorSink(name="s")
    pipe = nns.Pipeline()
    for e in (src, tr, sink):
        pipe.add(e)
    pipe.link(src, tr)
    pipe.link(tr, sink)
    runner = nns.PipelineRunner(pipe).start()
    x = np.ones((8, 16, 16, 3), np.float32)
    y = np.arange(8, dtype=np.int32) % 8
    src.push(TensorBuffer.of(x, y, pts=0))
    src.end()
    runner.wait(120)
    assert len(sink.results) == 1
    assert np.isfinite(sink.results[0].tensors[0][0])


def test_single_shot_runner():
    from nnstreamer_tpu.single import SingleShot

    with SingleShot(
            model="zoo://mobilenet_v2?width=0.35&input_size=64&dtype=float32"
    ) as runner:
        assert runner.input_info is not None
        out, = runner.invoke(np.zeros((1, 64, 64, 3), np.float32))
        assert out.shape == (1, 1001)
        assert runner.output_info.tensors[0].shape == (1, 1001)


def test_single_shot_custom_backend_and_fusion():
    from nnstreamer_tpu.backends.custom import register_custom_easy
    from nnstreamer_tpu.single import SingleShot
    from nnstreamer_tpu.tensor.info import TensorsSpec, TensorInfo

    register_custom_easy("ss_add1", lambda ts: (ts[0] + 1,))
    r = SingleShot(model="ss_add1", framework="custom",
                   input_spec=TensorsSpec.of(TensorInfo((3,), DType.FLOAT32)))
    out, = r.invoke(np.zeros((3,), np.float32))
    np.testing.assert_array_equal(np.asarray(out), [1, 1, 1])
    r.close()


def test_filesrc_npy_and_raw(tmp_path):
    arr = np.arange(24, dtype=np.float32).reshape(4, 3, 2)
    npy = tmp_path / "frames.npy"
    np.save(npy, arr)
    pipe = nns.parse_launch(
        f"filesrc location={npy} ! tensor_sink name=s")
    nns.run_pipeline(pipe, timeout=30)
    res = pipe.get("s").results
    assert len(res) == 4
    np.testing.assert_array_equal(res[2].tensors[0], arr[2])

    raw = tmp_path / "frames.raw"
    raw.write_bytes(np.arange(12, dtype=np.uint8).tobytes())
    pipe2 = nns.parse_launch(
        f"filesrc location={raw} dims=4 types=uint8 ! tensor_sink name=s")
    nns.run_pipeline(pipe2, timeout=30)
    res2 = pipe2.get("s").results
    assert len(res2) == 3
    np.testing.assert_array_equal(res2[0].tensors[0], [0, 1, 2, 3])


def test_runner_stats_counts_buffers():
    spec = TensorsSpec.of(TensorInfo((2,), DType.FLOAT32))
    src = AppSrc(spec=spec, name="src")
    from nnstreamer_tpu.elements import TensorTransform

    t = TensorTransform(name="t", mode="arithmetic", option="add:1.0")
    sink = TensorSink(name="s")
    pipe = nns.Pipeline()
    for e in (src, t, sink):
        pipe.add(e)
    pipe.link(src, t)
    pipe.link(t, sink)
    runner = nns.PipelineRunner(pipe, optimize=False).start()
    for i in range(5):
        src.push(TensorBuffer.of(np.zeros(2, np.float32), pts=i))
    src.end()
    runner.wait(30)
    stats = runner.stats()
    assert stats["t"]["buffers"] == 5
    assert stats["s"]["buffers"] == 5
    assert stats["t"]["proctime_avg_us"] > 0


def test_cli_inspect_and_pipeline(tmp_path):
    env = {"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
           "PYTHONPATH": "/root/repo"}
    out = subprocess.run(
        [sys.executable, "-m", "nnstreamer_tpu", "--inspect"],
        capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0
    assert "tensor_filter" in out.stdout
    assert "bounding_boxes" in out.stdout

    out2 = subprocess.run(
        [sys.executable, "-m", "nnstreamer_tpu", "--models"],
        capture_output=True, text=True, env=env, timeout=120)
    assert "zoo://mobilenet_v2" in out2.stdout

    arr = np.ones((2, 2, 2), np.float32)
    np.save(tmp_path / "x.npy", arr)
    out3 = subprocess.run(
        [sys.executable, "-m", "nnstreamer_tpu", "--stats",
         f"filesrc location={tmp_path}/x.npy ! tensor_debug ! fakesink"],
        capture_output=True, text=True, env=env, timeout=120)
    assert out3.returncode == 0, out3.stderr
    stats = json.loads(out3.stdout)
    assert any(v["buffers"] == 2 for v in stats.values())


def test_trainer_checkpoint_resume_full_state(tmp_path):
    """Resume restores params AND optimizer moments AND step: continuing
    from a checkpoint matches an uninterrupted run exactly."""
    from nnstreamer_tpu.elements import AppSrc, TensorSink
    from nnstreamer_tpu.trainer.element import TensorTrainer
    from nnstreamer_tpu.tensor.buffer import TensorBuffer
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    rng = np.random.default_rng(0)
    frames = [(rng.normal(size=(4, 16, 16, 3)).astype(np.float32),
               (np.arange(4) % 8).astype(np.int32)) for _ in range(6)]

    def run(trainer, batch):
        src = AppSrc(spec=TensorsSpec.of(
            TensorInfo((4, 16, 16, 3), DType.FLOAT32),
            TensorInfo((4,), DType.INT32)), name="src")
        sink = TensorSink(name="s")
        pipe = nns.Pipeline()
        for e in (src, trainer, sink):
            pipe.add(e)
        pipe.link(src, trainer)
        pipe.link(trainer, sink)
        runner = nns.PipelineRunner(pipe).start()
        for x, y in batch:
            src.push(TensorBuffer.of(x, y))
        src.end()
        runner.wait(120)
        return [float(r.tensors[0][0]) for r in sink.results]

    model = "zoo://mobilenet_v2?width=0.35&num_classes=8"
    opt = "adam:0.01"   # adam: moments matter for exactness
    # uninterrupted 6-step run
    losses_full = run(TensorTrainer(name="t0", model=model, optimizer=opt),
                      frames)
    # 3 steps + checkpoint
    t1 = TensorTrainer(name="t1", model=model, optimizer=opt,
                       checkpoint_dir=str(tmp_path), checkpoint_every=3)
    losses_a = run(t1, frames[:3])
    # resume and finish
    t2 = TensorTrainer(name="t2", model=model, optimizer=opt,
                       resume_from=str(tmp_path / "step_3"))
    losses_b = run(t2, frames[3:])
    assert t2.steps == 6
    np.testing.assert_allclose(losses_a + losses_b, losses_full,
                               rtol=1e-4, atol=1e-5)


def test_trainer_resume_on_mesh_keeps_sharding(eight_cpu_devices, tmp_path):
    """Resume under mesh= re-places the restored state: params must come
    back tp-sharded, not silently replicated."""
    from jax.sharding import PartitionSpec as P

    from nnstreamer_tpu.elements import AppSrc, TensorSink
    from nnstreamer_tpu.trainer.element import TensorTrainer
    from nnstreamer_tpu.tensor.buffer import TensorBuffer
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    def run(trainer, n):
        src = AppSrc(spec=TensorsSpec.of(
            TensorInfo((8, 16, 16, 3), DType.FLOAT32),
            TensorInfo((8,), DType.INT32)), name="src")
        sink = TensorSink(name="s")
        pipe = nns.Pipeline()
        for e in (src, trainer, sink):
            pipe.add(e)
        pipe.link(src, trainer)
        pipe.link(trainer, sink)
        runner = nns.PipelineRunner(pipe).start()
        rng = np.random.default_rng(7)
        for _ in range(n):
            src.push(TensorBuffer.of(
                rng.normal(size=(8, 16, 16, 3)).astype(np.float32),
                (np.arange(8) % 8).astype(np.int32)))
        src.end()
        runner.wait(180)

    model = "zoo://mobilenet_v2?width=0.35&num_classes=8"
    t1 = TensorTrainer(name="t1", model=model, mesh="dp=4,tp=2",
                       checkpoint_dir=str(tmp_path), checkpoint_every=1)
    run(t1, 1)
    t2 = TensorTrainer(name="t2", model=model, mesh="dp=4,tp=2",
                       resume_from=str(tmp_path / "step_1"))
    run(t2, 1)
    assert t2.steps == 2
    w = t2.params["stem"]["conv"]["w"]
    assert w.sharding.spec == P(None, None, None, "tp")


def test_new_plugin_scaffolds_are_runnable(tmp_path):
    """tools/new_plugin.py output registers and runs in a pipeline."""
    import subprocess
    from pathlib import Path
    import sys

    for kind, name in (("decoder", "gen_dec"), ("converter", "gen_conv"),
                       ("filter", "gen_fil"), ("element", "gen_elem")):
        tool = str(Path(__file__).resolve().parents[1] / "tools"
                   / "new_plugin.py")
        out = subprocess.run(
            [sys.executable, tool, kind, name, str(tmp_path)],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
    sys.path.insert(0, str(tmp_path))
    try:
        import gen_conv_converter  # noqa: F401 (registers converter)
        import gen_dec_decoder   # noqa: F401  (registers decoder)
        import gen_elem_element  # noqa: F401 (registers element)
        import gen_fil_filter   # noqa: F401  (registers custom model)

        from nnstreamer_tpu.core.registry import PluginKind, registry

        assert "gen_dec" in registry.names(PluginKind.DECODER)
        assert "gen_conv" in registry.names(PluginKind.CONVERTER)

        from nnstreamer_tpu.tensor.buffer import TensorBuffer

        pipe = nns.parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_filter framework=custom model=gen_fil ! "
            "gen_elem ! tensor_sink name=s")
        runner = nns.PipelineRunner(pipe).start()
        src = pipe.get("src")
        src.push(TensorBuffer.of(np.arange(4, dtype=np.float32)))
        src.end()
        runner.wait(30)
        runner.stop()
        assert len(pipe.get("s").results) == 1
    finally:
        sys.path.remove(str(tmp_path))
