"""Edge/distributed tests — loopback on localhost, the reference's own
technique (SURVEY.md §4: background server pipeline + byte-compare; no
cluster needed)."""

import threading
import time

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.backends.custom import register_custom_easy
from nnstreamer_tpu.edge import (
    EdgeSink, EdgeSrc, QueryServer, TensorQueryClient, TensorQueryServerSink,
    TensorQueryServerSrc, decode_buffer, encode_buffer)
from nnstreamer_tpu.elements import AppSrc, TensorFilter, TensorSink
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec


@pytest.fixture(autouse=True)
def _clean_servers():
    yield
    QueryServer.reset_all()


def test_wire_roundtrip_preserves_everything():
    buf = TensorBuffer.of(
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.array([1, 2, 3], np.uint8),
        pts=123456789)
    buf = buf.with_meta(label="cat", score=0.75)
    data = encode_buffer(buf, client_id=42)
    out, cid = decode_buffer(data)
    assert cid == 42
    assert out.pts == 123456789
    assert out.meta["label"] == "cat"
    assert out.meta["score"] == 0.75
    np.testing.assert_array_equal(out.tensors[0], buf.tensors[0])
    np.testing.assert_array_equal(out.tensors[1], buf.tensors[1])


def test_wire_preserves_ndarray_meta():
    """Decoder outputs (boxes/keypoints) ride meta across transports."""
    boxes = np.array([[1.0, 2.0, 3.0, 4.0, 0.9, 7.0]], np.float32)
    buf = TensorBuffer.of(np.zeros((2,), np.uint8)).with_meta(
        boxes=boxes, label="person", n=3)
    out, _ = decode_buffer(encode_buffer(buf))
    np.testing.assert_array_equal(out.meta["boxes"], boxes)
    assert out.meta["boxes"].dtype == np.float32
    assert out.meta["label"] == "person" and out.meta["n"] == 3


def test_wire_rejects_corrupt_frames():
    buf = TensorBuffer.of(np.zeros((2, 2), np.float32))
    data = bytearray(encode_buffer(buf))
    data[0] ^= 0xFF  # clobber magic
    with pytest.raises(ValueError, match="magic"):
        decode_buffer(bytes(data))
    with pytest.raises(ValueError):
        decode_buffer(encode_buffer(buf)[:10])


def _start_echo_server(transform=None):
    """Server pipeline: serversrc → filter(custom fn) → serversink."""
    register_custom_easy("edge_double", lambda ts: (ts[0] * 2.0,))
    ssrc = TensorQueryServerSrc(name="ssrc", id=5, dims="4", types="float32",
                                port=0)
    f = TensorFilter(name="f", framework="custom", model="edge_double")
    ssink = TensorQueryServerSink(name="ssink", id=5)
    pipe = nns.Pipeline("server")
    for e in (ssrc, f, ssink):
        pipe.add(e)
    pipe.link(ssrc, f)
    pipe.link(f, ssink)
    runner = nns.PipelineRunner(pipe).start()
    return pipe, runner, ssrc


def test_query_offload_roundtrip():
    server_pipe, server_runner, ssrc = _start_echo_server()
    try:
        port = ssrc.port
        # client pipeline: appsrc → query_client → sink
        spec = TensorsSpec.of(TensorInfo((4,), DType.FLOAT32))
        src = AppSrc(spec=spec, name="src")
        qc = TensorQueryClient(name="qc", port=port, timeout=15)
        sink = TensorSink(name="s")
        pipe = nns.Pipeline("client")
        for e in (src, qc, sink):
            pipe.add(e)
        pipe.link(src, qc)
        pipe.link(qc, sink)
        runner = nns.PipelineRunner(pipe).start()
        for i in range(3):
            src.push(TensorBuffer.of(
                np.full((4,), i + 1, np.float32), pts=i))
        src.end()
        runner.wait(30)
        assert len(sink.results) == 3
        for i, r in enumerate(sink.results):
            np.testing.assert_array_equal(
                r.tensors[0], np.full((4,), 2.0 * (i + 1), np.float32))
            assert r.pts == i
            assert "client_id" not in r.meta
    finally:
        server_runner.stop()


def test_query_client_caps_rejection():
    server_pipe, server_runner, ssrc = _start_echo_server()
    try:
        spec = TensorsSpec.of(TensorInfo((7,), DType.FLOAT32))  # wrong dims
        src = AppSrc(spec=spec, name="src")
        qc = TensorQueryClient(name="qc", port=ssrc.port, timeout=15)
        sink = TensorSink(name="s")
        pipe = nns.Pipeline()
        for e in (src, qc, sink):
            pipe.add(e)
        pipe.link(src, qc)
        pipe.link(qc, sink)
        with pytest.raises(Exception, match="incompatible|rejected"):
            pipe.negotiate()
    finally:
        server_runner.stop()


def test_query_two_clients_routed_separately():
    server_pipe, server_runner, ssrc = _start_echo_server()
    try:
        port = ssrc.port
        results = {}

        def run_client(tag, value):
            spec = TensorsSpec.of(TensorInfo((4,), DType.FLOAT32))
            src = AppSrc(spec=spec, name="src")
            qc = TensorQueryClient(name="qc", port=port, timeout=15)
            sink = TensorSink(name="s")
            pipe = nns.Pipeline(tag)
            for e in (src, qc, sink):
                pipe.add(e)
            pipe.link(src, qc)
            pipe.link(qc, sink)
            runner = nns.PipelineRunner(pipe).start()
            for i in range(4):
                src.push(TensorBuffer.of(
                    np.full((4,), value, np.float32), pts=i))
            src.end()
            runner.wait(30)
            results[tag] = [float(r.tensors[0][0]) for r in sink.results]

        t1 = threading.Thread(target=run_client, args=("c1", 10.0))
        t2 = threading.Thread(target=run_client, args=("c2", 100.0))
        t1.start(); t2.start()
        t1.join(30); t2.join(30)
        assert results["c1"] == [20.0] * 4   # never c2's answers
        assert results["c2"] == [200.0] * 4
    finally:
        server_runner.stop()


def test_edge_pubsub_stream_bridging():
    # publisher pipeline: appsrc → edgesink
    spec = TensorsSpec.of(TensorInfo((2, 2), DType.FLOAT32))
    psrc = AppSrc(spec=spec, name="psrc")
    esink = EdgeSink(name="pub", port=0)
    ppipe = nns.Pipeline("pub")
    ppipe.add(psrc)
    ppipe.add(esink)
    ppipe.link(psrc, esink)
    prunner = nns.PipelineRunner(ppipe).start()
    port = esink.port

    # subscriber pipeline: edgesrc → sink (caps from handshake)
    esrc = EdgeSrc(name="sub", port=port, timeout=15)
    sink = TensorSink(name="s")
    spipe = nns.Pipeline("sub")
    spipe.add(esrc)
    spipe.add(sink)
    spipe.link(esrc, sink)
    srunner = nns.PipelineRunner(spipe).start()
    assert esrc.out_specs[0].tensors[0].shape == (2, 2)

    time.sleep(0.3)  # let subscription settle before publishing
    for i in range(5):
        psrc.push(TensorBuffer.of(np.full((2, 2), i, np.float32), pts=i))
    psrc.end()
    prunner.wait(30)
    prunner.stop()    # closes the publisher socket…
    srunner.wait(30)  # …which is the subscriber's EOS
    vals = [float(r.tensors[0][0, 0]) for r in sink.results]
    assert vals == [0.0, 1.0, 2.0, 3.0, 4.0]


def test_query_client_pipelined_in_flight_preserves_order():
    """max_in_flight>1 overlaps requests; results keep frame order and
    EOS flush drains every in-flight frame."""
    register_custom_easy("pipelined_inc", lambda t: (t[0] + 1,))
    server = nns.parse_launch(
        "tensor_query_serversrc name=ssrc id=31 dims=4 types=float32 "
        "port=0 ! tensor_filter framework=custom model=pipelined_inc ! "
        "tensor_query_serversink id=31")
    srunner = nns.PipelineRunner(server).start()
    port = server.get("ssrc").port
    client = nns.parse_launch(
        f"appsrc name=src dims=4 types=float32 ! "
        f"tensor_query_client port={port} max_in_flight=4 ! "
        f"tensor_sink name=sink")
    crunner = nns.PipelineRunner(client).start()
    src = client.get("src")
    n = 11   # not a multiple of the window: tail drains via flush
    for i in range(n):
        src.push(TensorBuffer.of(np.full((4,), i, np.float32), pts=i * 10))
    src.end()
    crunner.wait(60)
    crunner.stop()
    server.get("ssrc").interrupt()
    srunner.stop()
    res = client.get("sink").results
    assert len(res) == n
    for i, r in enumerate(res):
        assert r.pts == i * 10                       # order preserved
        np.testing.assert_array_equal(r.tensors[0],
                                      np.full((4,), i + 1, np.float32))


class TestBatchedQueryServer:
    """MeshDispatcher wired into the query transport (VERDICT r2 #9)."""

    def _server(self, **kw):
        from nnstreamer_tpu.edge import BatchedQueryServer, QueryServer

        QueryServer.reset_all()
        # tiny model: y = x @ w (batch-polymorphic)
        import jax.numpy as jnp

        from nnstreamer_tpu.backends.xla import ModelBundle
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

        w = jnp.arange(12, dtype=jnp.float32).reshape(4, 3)
        bundle = ModelBundle(
            fn=lambda p, x: (x @ p["w"],),
            params={"w": w},
            in_spec=TensorsSpec.of(TensorInfo((1, 4), DType.FLOAT32)),
            out_spec=TensorsSpec.of(TensorInfo((1, 3), DType.FLOAT32)),
            name="linear")
        return BatchedQueryServer(bundle, sid=31, port=0, **kw), w

    def test_four_clients_coalesce_and_route_correctly(self):
        import concurrent.futures as cf

        import nnstreamer_tpu as nns
        from nnstreamer_tpu.edge import QueryServer
        from nnstreamer_tpu.tensor.buffer import TensorBuffer

        srv, w = self._server(bucket=8, max_delay_ms=10.0)
        try:
            def run_client(cid):
                pipe = nns.parse_launch(
                    f"appsrc name=src dims=4:1 types=float32 ! "
                    f"tensor_query_client port={srv.port} timeout=60 "
                    f"max_in_flight=4 ! tensor_sink name=sink")
                rn = nns.PipelineRunner(pipe).start()
                xs = [np.full((1, 4), float(cid * 10 + i), np.float32)
                      for i in range(6)]
                for i, x in enumerate(xs):
                    pipe.get("src").push(TensorBuffer.of(x, pts=i))
                pipe.get("src").end()
                rn.wait(60)
                rn.stop()
                return cid, xs, pipe.get("sink").results

            with cf.ThreadPoolExecutor(4) as ex:
                results = list(ex.map(run_client, range(4)))
            for cid, xs, res in results:
                assert len(res) == 6
                for x, r in zip(xs, res):
                    np.testing.assert_allclose(
                        np.asarray(r.tensors[0]),
                        x @ np.asarray(w), rtol=1e-6)
            st = srv.stats()
            assert st["frames"] == 24
            # coalescing happened: fewer batches than frames
            assert st["batches"] < st["frames"]
        finally:
            srv.close()
            QueryServer.reset_all()

    def test_caps_handshake_and_pts_roundtrip(self):
        import nnstreamer_tpu as nns
        from nnstreamer_tpu.edge import QueryServer
        from nnstreamer_tpu.tensor.buffer import TensorBuffer

        srv, w = self._server(bucket=4)
        try:
            # wrong caps are NAK'd exactly like the pipeline server
            import pytest as _pytest

            from nnstreamer_tpu.core.errors import NegotiationError

            bad = nns.parse_launch(
                f"appsrc dims=5:1 types=float32 ! "
                f"tensor_query_client port={srv.port} timeout=10 ! "
                f"tensor_sink")
            with _pytest.raises(NegotiationError, match="incompatible"):
                bad.negotiate()

            pipe = nns.parse_launch(
                f"appsrc name=src dims=4:1 types=float32 ! "
                f"tensor_query_client port={srv.port} timeout=60 ! "
                f"tensor_sink name=sink")
            rn = nns.PipelineRunner(pipe).start()
            x = np.ones((1, 4), np.float32)
            pipe.get("src").push(TensorBuffer.of(x, pts=77))
            pipe.get("src").end()
            rn.wait(60)
            rn.stop()
            res = pipe.get("sink").results
            assert len(res) == 1 and res[0].pts == 77
        finally:
            srv.close()
            QueryServer.reset_all()
