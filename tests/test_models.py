"""Model zoo tests (CPU, small shapes — conftest forces JAX_PLATFORMS=cpu).

Mirrors the reference's approach of tiny deterministic models as test
fixtures (SURVEY.md §4): shapes and determinism are validated here; the
real-chip perf path is bench.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models import layers as L
from nnstreamer_tpu.models.zoo import build_model, list_models


def test_zoo_lists_flagships():
    models = list_models()
    for name in ("mobilenet_v2", "ssd_mobilenet", "posenet"):
        assert name in models


def test_mobilenet_v2_forward_shape_and_determinism():
    from nnstreamer_tpu.models import mobilenet_v2 as m

    params = m.init_params(seed=0)
    x = jnp.ones((2, 96, 96, 3), jnp.float32)
    logits = m.apply(params, x, dtype=jnp.float32)
    assert logits.shape == (2, 1001)
    assert logits.dtype == jnp.float32
    # deterministic init
    params2 = m.init_params(seed=0)
    logits2 = m.apply(params2, x, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(logits2))
    # param count ~3.5M at width 1.0
    n = L.count_params(params)
    assert 3_000_000 < n < 4_500_000, n


def test_mobilenet_v2_width_multiplier():
    from nnstreamer_tpu.models import mobilenet_v2 as m

    params = m.init_params(width=0.35)
    x = jnp.ones((1, 96, 96, 3))
    logits = m.apply(params, x, width=0.35, dtype=jnp.float32)
    assert logits.shape == (1, 1001)
    assert L.count_params(params) < 2_000_000


def test_mobilenet_v2_bundle_eval_shape():
    bundle = build_model("mobilenet_v2?input_size=96&dtype=float32")
    out = jax.eval_shape(
        lambda p, x: bundle.fn(p, x),
        jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), bundle.params),
        jax.ShapeDtypeStruct((1, 96, 96, 3), jnp.float32),
    )
    assert out.shape == (1, 1001)


def test_mobilenet_loss_grad():
    from nnstreamer_tpu.models import mobilenet_v2 as m

    params = m.init_params(width=0.35)
    x = jnp.ones((2, 64, 64, 3))
    y = jnp.array([1, 2])
    loss, grads = jax.value_and_grad(m.loss_fn)(
        params, x, y, width=0.35, dtype=jnp.float32)
    assert jnp.isfinite(loss)
    g = grads["classifier"]["w"]
    assert float(jnp.abs(g).sum()) > 0.0


def test_ssd_anchors_canonical_count():
    from nnstreamer_tpu.models.ssd_mobilenet import generate_anchors

    anchors = generate_anchors()
    assert anchors.shape == (1917, 4)
    assert np.all(anchors[:, 2:] > 0)  # h, w positive
    assert np.all(anchors[:, :2] >= 0) and np.all(anchors[:, :2] <= 1)


def test_ssd_box_decode_roundtrip_identity():
    from nnstreamer_tpu.models.ssd_mobilenet import decode_boxes, generate_anchors

    anchors = generate_anchors()[:8]
    # zero deltas decode to the anchors themselves
    boxes = decode_boxes(np.zeros((8, 4), np.float32), anchors)
    np.testing.assert_allclose(boxes[:, 2] - boxes[:, 0], anchors[:, 2], atol=1e-6)
    np.testing.assert_allclose(
        (boxes[:, 1] + boxes[:, 3]) / 2, anchors[:, 1], atol=1e-6)


@pytest.mark.slow
def test_ssd_mobilenet_forward():
    from nnstreamer_tpu.models import ssd_mobilenet as s

    params = s.init_params(num_classes=11, width=0.35)
    x = jnp.ones((1, 300, 300, 3))
    loc, cls = s.apply(params, x, num_classes=11, width=0.35, dtype=jnp.float32)
    assert loc.shape == (1, 1917, 4)
    assert cls.shape == (1, 1917, 11)


def test_posenet_forward():
    from nnstreamer_tpu.models import posenet as p

    params = p.init_params(width=0.35)
    x = jnp.ones((1, 97, 97, 3))
    heat, off = p.apply(params, x, width=0.35, dtype=jnp.float32)
    assert heat.shape[-1] == 17
    assert off.shape[-1] == 34
    assert heat.shape[1:3] == off.shape[1:3]
    assert float(heat.min()) >= 0.0 and float(heat.max()) <= 1.0


def test_model_in_pipeline_via_zoo_uri():
    """End-to-end: appsrc → filter(zoo model) → sink, tiny mobilenet."""
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.elements import AppSrc, TensorFilter, TensorSink
    from nnstreamer_tpu.tensor.buffer import TensorBuffer
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec
    from nnstreamer_tpu.tensor.dtypes import DType

    spec = TensorsSpec.of(TensorInfo((1, 64, 64, 3), DType.FLOAT32))
    src = AppSrc(spec=spec, name="src")
    filt = TensorFilter(
        name="f", framework="xla",
        model="zoo://mobilenet_v2?width=0.35&input_size=64&dtype=float32")
    out = []
    sink = TensorSink(name="sink", new_data=lambda b: out.append(b))
    pipe = nns.Pipeline()
    for e in (src, filt, sink):
        pipe.add(e)
    pipe.link(src, filt)
    pipe.link(filt, sink)
    runner = nns.PipelineRunner(pipe).start()
    src.push(TensorBuffer.of(np.zeros((1, 64, 64, 3), np.float32), pts=0))
    src.end()
    runner.wait(60)
    assert len(out) == 1
    assert out[0].tensors[0].shape == (1, 1001)
