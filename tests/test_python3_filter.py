"""python3 scripted-filter backend: runs the reference's own scripts.

The reference embeds CPython (`tensor_filter_python3.cc`) and ships
test scripts under `tests/test_models/models/`; these tests execute
those unmodified scripts through `framework=python3` with the
reference runTest.sh semantics (passthrough byte-identity; scaler
nearest-neighbor checked against an independent numpy port of
`checkScaledTensor.py`)."""

import os

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.tensor.buffer import TensorBuffer

MODELS = "/root/reference/tests/test_models/models"
PASSTHROUGH = os.path.join(MODELS, "passthrough.py")
SCALER = os.path.join(MODELS, "scaler.py")

needs_models = pytest.mark.skipif(
    not (os.path.exists(PASSTHROUGH) and os.path.exists(SCALER)),
    reason="reference test scripts absent")


def _run_pipeline(launch, frame):
    pipe = nns.parse_launch(launch)
    runner = nns.PipelineRunner(pipe).start()
    pipe.get("src").push(TensorBuffer.of(frame))
    pipe.get("src").end()
    runner.wait(120)
    runner.stop()
    return pipe.get("out").results


@needs_models
def test_reference_passthrough_script_byte_identity():
    """runTest.sh testcase 1: passthrough.py declares 3:280:40:1 uint8
    static dims; output bytes == input bytes."""
    frame = np.random.default_rng(0).integers(
        0, 256, (1, 40, 280, 3), np.uint8)
    res = _run_pipeline(
        f"appsrc name=src dims=3:280:40:1 types=uint8 ! "
        f"tensor_filter framework=python3 model={PASSTHROUGH} ! "
        f"tensor_sink name=out", frame)
    assert len(res) == 1
    np.testing.assert_array_equal(np.asarray(res[0].tensors[0]), frame)


def _nn_scale(img, out_w, out_h):
    """Independent nearest-neighbor port of checkScaledTensor.py."""
    _, in_h, in_w, ch = img.shape
    out = np.empty((1, out_h, out_w, ch), img.dtype)
    for y in range(out_h):
        for x in range(out_w):
            out[0, y, x] = img[0, int(y * in_h / out_h),
                               int(x * in_w / out_w)]
    return out


@needs_models
@pytest.mark.parametrize("out_w,out_h", [(32, 24), (128, 96)])
def test_reference_scaler_script_matches_independent_decode(out_w,
                                                            out_h):
    """runTest.sh testcases 2/3 (down- and up-scale), sized down for CI
    speed — scaler.py adapts to any input via setInputDim."""
    frame = np.random.default_rng(1).integers(
        0, 256, (1, 48, 64, 3), np.uint8)
    res = _run_pipeline(
        f"appsrc name=src dims=3:64:48:1 types=uint8 ! "
        f"tensor_filter framework=python3 model={SCALER} "
        f"custom={out_w}x{out_h} ! tensor_sink name=out", frame)
    assert len(res) == 1
    got = np.asarray(res[0].tensors[0])
    assert got.shape == (1, out_h, out_w, 3)
    np.testing.assert_array_equal(got, _nn_scale(frame, out_w, out_h))


@needs_models
def test_vendor_framework_aliases_run_reference_recipes():
    """Reference pipeline strings with explicit vendor framework names
    run verbatim: the zoo collapses into the xla backend's ingestion."""
    res = _run_pipeline(
        f"appsrc name=src dims=1 types=float32 ! "
        f"tensor_filter framework=snpe "
        f"model={MODELS}/add2_float.dlc ! tensor_sink name=out",
        np.asarray([40.0], np.float32))
    assert float(np.asarray(res[0].tensors[0])[0]) == 42.0

    res = _run_pipeline(
        f"appsrc name=src dims=1:28:28:1 types=uint8 ! "
        f"tensor_filter framework=pytorch "
        f"model={MODELS}/pytorch_lenet5.pt ! tensor_sink name=out",
        np.fromfile("/root/reference/tests/test_models/data/9.raw",
                    np.uint8).reshape(1, 28, 28, 1))
    assert int(np.asarray(res[0].tensors[0]).argmax()) == 9


CONVERTER_SCRIPT = os.path.join(MODELS, "custom_converter.py")
DECODER_SCRIPT = os.path.join(MODELS, "custom_decoder.py")

needs_codec_scripts = pytest.mark.skipif(
    not (os.path.exists(CONVERTER_SCRIPT)
         and os.path.exists(DECODER_SCRIPT)),
    reason="reference codec scripts absent")


@needs_codec_scripts
def test_reference_codec_scripts_roundtrip():
    """decoder_python3/converter_python3 runTest semantics: tensors →
    CustomDecoder (flexbuf bytes) → CustomConverter → original tensors,
    both the reference's unmodified scripts."""
    frame = np.random.default_rng(2).integers(
        0, 256, (1, 4, 6, 3), np.uint8)
    res = _run_pipeline(
        f"appsrc name=src dims=3:6:4:1 types=uint8 ! "
        f"tensor_decoder mode=python3 option1={DECODER_SCRIPT} ! "
        f"tensor_converter mode=custom-script:{CONVERTER_SCRIPT} ! "
        f"tensor_sink name=out", frame)
    assert len(res) == 1
    got = np.asarray(res[0].tensors[0])
    np.testing.assert_array_equal(got.reshape(frame.shape), frame)


@needs_codec_scripts
def test_script_decoder_interops_with_native_flexbuf_converter():
    """The script decoder's wire bytes parse with THIS repo's flexbuf
    converter, and vice versa — same flexbuffers schema."""
    frame = np.random.default_rng(3).integers(
        0, 256, (1, 4, 6, 3), np.uint8)
    res = _run_pipeline(
        f"appsrc name=src dims=3:6:4:1 types=uint8 ! "
        f"tensor_decoder mode=python3 option1={DECODER_SCRIPT} ! "
        f"tensor_converter mode=custom:flexbuf ! tensor_sink name=out",
        frame)
    got = np.asarray(res[0].tensors[0])
    np.testing.assert_array_equal(got.reshape(frame.shape), frame)

    res = _run_pipeline(
        f"appsrc name=src dims=3:6:4:1 types=uint8 ! "
        f"tensor_decoder mode=flexbuf ! "
        f"tensor_converter mode=custom-script:{CONVERTER_SCRIPT} ! "
        f"tensor_sink name=out", frame)
    got = np.asarray(res[0].tensors[0])
    np.testing.assert_array_equal(got.reshape(frame.shape), frame)


def test_reference_json_converter_script_two_tensors():
    """custom_converter_json.py (reference fixture): a JSON frame
    becomes two uint8 text tensors — multi-tensor scripted convert."""
    import json as jsonlib

    script = os.path.join(MODELS, "custom_converter_json.py")
    if not os.path.exists(script):
        pytest.skip("json converter fixture absent")
    payload = jsonlib.dumps({
        "json_string": "string_example", "json_number": 100,
        "json_array": [1, 2, 3, 4, 5],
        "json_object": {"name": "John", "age": 30},
        "json_bool": True}).encode()
    frame = np.frombuffer(payload, np.uint8)
    res = _run_pipeline(
        f"appsrc name=src dims={len(payload)} types=uint8 ! "
        f"tensor_converter mode=custom-script:{script} ! "
        f"tensor_sink name=out", frame)
    assert len(res) == 1
    t0, t1 = res[0].tensors
    assert bytes(np.asarray(t0).ravel()) == b"string_example\0"
    assert jsonlib.loads(bytes(np.asarray(t1).ravel())) == {
        "name": "John", "age": 30}
    assert res[0].meta["rate"] == (10, 1)


@needs_codec_scripts
def test_reference_invalid_class_script_fails_loud():
    """The reference's own negative fixture: a converter script whose
    class has the wrong name must fail at negotiation, loudly."""
    invalid = os.path.join(MODELS, "invalid_class_custom_converter.py")
    if not os.path.exists(invalid):
        pytest.skip("invalid-class fixture absent")
    from nnstreamer_tpu.core.errors import PipelineError

    with pytest.raises((BackendError, PipelineError),
                       match="CustomConverter"):
        _run_pipeline(
            f"appsrc name=src dims=4 types=uint8 ! "
            f"tensor_converter mode=custom-script:{invalid} ! "
            f"tensor_sink name=out",
            np.zeros(4, np.uint8))


@needs_models
def test_python3_reload_preserves_custom_args_and_negotiation():
    """Hot-swap (is-updatable analog): reload must carry custom= args
    and re-drive setInputDim so an adaptive script keeps working."""
    from nnstreamer_tpu.backends.python3_script import (
        Python3ScriptBackend)
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    b = Python3ScriptBackend()
    b.open({"model": SCALER, "custom": "8x6"})
    spec = TensorsSpec.of(TensorInfo((1, 12, 16, 3), DType.UINT8))
    out = b.set_input_info(spec)
    assert out.tensors[0].shape == (1, 6, 8, 3)
    x = np.random.default_rng(4).integers(0, 256, (1, 12, 16, 3),
                                          np.uint8)
    y1 = b.invoke((x,))[0]
    b.reload(SCALER)
    y2 = b.invoke((x,))[0]
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


def test_converter_script_may_return_bytes_raw_data(tmp_path):
    """raw_data entries may be bytes (the wire blob IS bytes) — the
    natural thing for a script author to return."""
    script = tmp_path / "bytes_conv.py"
    script.write_text(
        "import numpy as np\n"
        "import nnstreamer_python as nns\n"
        "class CustomConverter(object):\n"
        "    def convert(self, input_array):\n"
        "        data = input_array[0].tobytes()\n"
        "        info = [nns.TensorShape([len(data), 1, 1, 1],"
        " np.uint8)]\n"
        "        return info, [data], 30, 1\n")
    import nnstreamer_tpu as nns_pkg  # noqa: F401

    from nnstreamer_tpu.elements.script_codec import Python3Converter
    from nnstreamer_tpu.tensor.info import TensorFormat

    conv = Python3Converter(str(script))
    frame = np.arange(12, dtype=np.uint8)
    out = conv.convert(TensorBuffer.of(frame))
    got = np.asarray(out.tensors[0])
    assert got.shape == (1, 1, 1, 12)      # reference 4-dim wire
    np.testing.assert_array_equal(got.ravel(), frame)
    assert out.format == TensorFormat.FLEXIBLE
    assert out.meta["rate"] == (30, 1)


def test_python3_script_without_customfilter_fails_loud(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text("x = 1\n")
    from nnstreamer_tpu.backends.python3_script import (
        Python3ScriptBackend)

    b = Python3ScriptBackend()
    with pytest.raises(BackendError, match="CustomFilter"):
        b.open({"model": str(p)})


def test_python3_non_script_fails_loud():
    from nnstreamer_tpu.backends.python3_script import (
        Python3ScriptBackend)

    b = Python3ScriptBackend()
    with pytest.raises(BackendError, match="\\.py"):
        b.open({"model": "model.tflite"})
