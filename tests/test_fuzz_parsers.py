"""Adversarial bytes against the from-scratch wire readers.

The importers promise typed, loud failures on corrupt input (BackendError
naming the file; FlexDecodeError for flexbuffers; ValueError for the raw
protowire layer) — never raw IndexError/struct.error/UnicodeDecodeError
escaping from parser internals, and never a hang. Random buffers and
bit-flipped valid files pin that contract.
"""

import os

import numpy as np
import pytest

from nnstreamer_tpu.core.errors import BackendError

MODELS = "/root/reference/tests/test_models/models"
N_RANDOM = 400
N_MUTATED = 400


def _random_bufs(seed, n, max_len=96):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        ln = int(rng.integers(3, max_len))
        yield bytes(rng.integers(0, 256, ln, dtype=np.uint8))


def _mutations(seed, valid, n):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        m = bytearray(valid)
        for _ in range(int(rng.integers(1, 5))):
            m[int(rng.integers(0, len(m)))] = int(rng.integers(0, 256))
        yield bytes(m)


def test_flexbuf_reader_contract():
    from flatbuffers import flexbuffers

    from nnstreamer_tpu.interop.flexbuf_read import (
        FlexDecodeError,
        flexbuf_loads,
    )

    for buf in _random_bufs(0, N_RANDOM * 4):
        try:
            flexbuf_loads(buf)      # decoding garbage MAY succeed...
        except FlexDecodeError:
            pass                    # ...or fail with the typed error
    fbb = flexbuffers.Builder()
    with fbb.Map():
        fbb.Key("a")
        fbb.Int(1)
        fbb.Key("s")
        fbb.String("hello")
        fbb.Key("v")
        fbb.TypedVectorFromElements([1, 2, 3])
    valid = bytes(fbb.Finish())
    for buf in _mutations(1, valid, N_MUTATED * 4):
        try:
            flexbuf_loads(buf)
        except FlexDecodeError:
            pass


def test_protowire_contract():
    from nnstreamer_tpu.modelio import protowire as pw

    for buf in _random_bufs(2, N_RANDOM * 4):
        try:
            pw.fields_dict(buf)
        except ValueError:          # the module's single error type
            pass


def _file_parser_contract(parse_from_path, valid_path, seed, tmp_path,
                          suffix):
    valid = open(valid_path, "rb").read()[:4096] if valid_path else None
    cases = list(_random_bufs(seed, N_RANDOM))
    if valid:
        cases += list(_mutations(seed + 1, valid, N_MUTATED))
    target = tmp_path / f"fuzz{suffix}"
    for buf in cases:
        target.write_bytes(buf)
        try:
            parse_from_path(str(target))
        except BackendError:
            pass                    # the loader's documented error


@pytest.mark.skipif(not os.path.exists(MODELS),
                    reason="reference models absent")
def test_caffemodel_parser_contract(tmp_path):
    from nnstreamer_tpu.modelio.caffe import parse_caffemodel

    _file_parser_contract(
        parse_caffemodel,
        os.path.join(MODELS, "lenet_iter_9000.caffemodel"),
        3, tmp_path, ".caffemodel")


@pytest.mark.skipif(not os.path.exists(MODELS),
                    reason="reference models absent")
def test_uff_parser_contract(tmp_path):
    from nnstreamer_tpu.modelio.uff import parse_uff

    _file_parser_contract(
        parse_uff, os.path.join(MODELS, "lenet5.uff"), 4, tmp_path,
        ".uff")


@pytest.mark.skipif(not os.path.exists(MODELS),
                    reason="reference models absent")
def test_graphdef_parser_contract(tmp_path):
    from nnstreamer_tpu.modelio.graphdef import parse_graphdef

    _file_parser_contract(
        parse_graphdef, os.path.join(MODELS, "mnist.pb"), 5, tmp_path,
        ".pb")


@pytest.mark.skipif(not os.path.exists(MODELS),
                    reason="reference models absent")
def test_dlc_parser_contract(tmp_path):
    from nnstreamer_tpu.modelio.dlc import parse_dlc

    _file_parser_contract(
        parse_dlc, os.path.join(MODELS, "add2_float.dlc"), 7, tmp_path,
        ".dlc")


@pytest.mark.skipif(not os.path.exists(MODELS),
                    reason="reference models absent")
def test_rtm_parser_contract(tmp_path):
    from nnstreamer_tpu.modelio.rtm import parse_rtm

    _file_parser_contract(
        parse_rtm, os.path.join(MODELS, "mobilenet_v1_0.25_224.rtm"),
        8, tmp_path, ".rtm")


def test_torchscript_loader_contract(tmp_path):
    from nnstreamer_tpu.modelio.torchscript import load_torchscript

    for buf in _random_bufs(6, N_RANDOM // 4):
        target = tmp_path / "fuzz.pt"
        target.write_bytes(buf)
        try:
            load_torchscript(str(target))
        except BackendError:
            pass


@pytest.mark.skipif(not os.path.exists(MODELS),
                    reason="reference models absent")
def test_tflite_parser_contract(tmp_path):
    from nnstreamer_tpu.modelio import parse_tflite

    _file_parser_contract(
        parse_tflite, os.path.join(MODELS, "add.tflite"), 7, tmp_path,
        ".tflite")
