"""Model store & zero-downtime hot-swap serving (docs/serving.md):
versioned registry + ``store://`` refs, epoch-based swap with pre-warmed
buckets (recompile-free hot path), canary routing, per-version stats,
and the persistent compile cache manifest.

Models are tiny jax callables so every version is distinguishable by
output value alone: v1 = x*2, v2 = x*3 + 10."""

import json
import os
import time

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu import PipelineRunner, TensorBuffer, parse_launch
from nnstreamer_tpu.backends.xla import XLABackend
from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.serving import compile_cache
from nnstreamer_tpu.serving.store import (
    get_store,
    parse_store_ref,
    reset_store,
)


def _v1(x):
    return (x * 2.0,)


def _v2(x):
    return (x * 3.0 + 10.0,)


V1 = 2.0    # value of v1 on an all-ones frame
V2 = 13.0   # value of v2 on an all-ones frame


@pytest.fixture(autouse=True)
def _fresh_store():
    store = reset_store()
    compile_cache.reset()
    yield store
    reset_store()
    compile_cache.reset()


def _open_backend(ref, **props):
    b = XLABackend()
    b.open({"model": ref, "accelerator": "", "canary_seed": 0, **props})
    return b


def _push_ones(src, n, shape=(4,)):
    for _ in range(n):
        src.push(TensorBuffer.of(np.ones(shape, np.float32)))


def _out_vals(sink):
    return [float(np.asarray(b.tensors[0]).ravel()[0]) for b in sink.results]


def _wait_for(cond, timeout=10.0, what="condition"):
    t0 = time.monotonic()
    while not cond():
        assert time.monotonic() - t0 < timeout, f"timed out waiting: {what}"
        time.sleep(0.01)


# -- store:// reference grammar ----------------------------------------------

class TestParseStoreRef:
    def test_track_current(self):
        r = parse_store_ref("store://det")
        assert (r.name, r.version, r.canary_version) == ("det", None, None)

    def test_latest_is_track(self):
        assert parse_store_ref("store://det@latest").version is None

    def test_pinned_int(self):
        assert parse_store_ref("store://det@3").version == 3

    def test_pinned_alias(self):
        assert parse_store_ref("store://det@prod").version == "prod"

    def test_canary(self):
        r = parse_store_ref("store://det@2:0.05")
        assert (r.canary_version, r.canary_ratio) == (2, 0.05)
        assert r.version is None          # the 95% side tracks current

    @pytest.mark.parametrize("bad,msg", [
        ("zoo://det", "not a store reference"),
        ("store://", "no model name"),
        ("store://det@2:zzz", "bad canary ratio"),
        ("store://det@2:1.5", "out of range"),
        ("store://det@2:0", "out of range"),
        ("store://det@latest:0.2", "needs an explicit version"),
    ])
    def test_errors(self, bad, msg):
        with pytest.raises(BackendError, match=msg):
            parse_store_ref(bad)


# -- registry ----------------------------------------------------------------

class TestRegistry:
    def test_register_auto_versions_first_is_current(self, _fresh_store):
        store = _fresh_store
        assert store.register("det", _v1) == 1
        assert store.register("det", _v2) == 2
        # zero-downtime contract: registration never changes what serves
        assert store.entry("det").current == 1

    def test_update_default_latest(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        store.register("det", _v2)
        rep = store.update("det")
        assert (rep["from_version"], rep["to_version"]) == (1, 2)
        assert store.entry("det").current == 2
        assert store.entry("det").epoch == 1

    def test_duplicate_version_raises_naming_collision(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1, version=3)
        with pytest.raises(BackendError, match=r"'det'@3.*immutable"):
            store.register("det", _v2, version=3)

    def test_alias_pins(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        store.register("det", _v2)
        store.alias("det", "prod", 1)
        assert store.entry("det").resolve_version("prod") == 1
        with pytest.raises(BackendError, match="no version alias"):
            store.entry("det").resolve_version("staging")

    def test_unknown_name_lists_registered(self, _fresh_store):
        _fresh_store.register("det", _v1)
        with pytest.raises(BackendError, match="no model named 'nope'"):
            _fresh_store.entry("nope")

    def test_describe(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        store.register("det", _v2)
        store.update("det")
        d = store.describe("det")
        assert d["current"] == 2 and d["epoch"] == 1
        assert sorted(d["versions"]) == [1, 2]
        assert len(d["swaps"]) == 1

    def test_zoo_builtin_seeds_as_version_zero(self, _fresh_store):
        e = _fresh_store.entry("mobilenet_v2")
        assert 0 in e.versions
        assert e.versions[0].source == "zoo://mobilenet_v2"
        # lazy: describing must not build the actual model
        assert _fresh_store.describe("mobilenet_v2")["versions"][0][
            "built"] is False

    def test_zoo_duplicate_name_raises(self):
        from nnstreamer_tpu.models.zoo import register_model

        with pytest.raises(BackendError, match="already registered"):
            register_model("mobilenet_v2")(lambda **kw: None)

    def test_store_ref_cannot_nest_as_version_source(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        store.register("indirect", "store://det")
        with pytest.raises(BackendError, match="cannot nest"):
            store.update("indirect")


# -- hot swap mid-stream -----------------------------------------------------

class TestSwapMidStream:
    def test_no_torn_version_and_report(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        store.register("det", _v2)
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_filter name=f model=store://det ! tensor_sink name=out")
        runner = PipelineRunner(pipe, trace=True)
        runner.start()
        src, sink, f = pipe.get("src"), pipe.get("out"), pipe.get("f")
        try:
            _push_ones(src, 10)
            _wait_for(lambda: len(sink.results) >= 10, what="v1 frames")
            rep = store.update("det", wait_s=None)
            _push_ones(src, 10)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        assert rep["prewarmed_buckets"] >= 1
        vals = _out_vals(sink)
        assert len(vals) == 20
        # every output is exactly one version's math — never a blend —
        # and the flip is monotone (old then new, adoption is ordered)
        assert set(vals) == {V1, V2}
        flip = vals.index(V2)
        assert all(v == V1 for v in vals[:flip])
        assert all(v == V2 for v in vals[flip:])
        # observability: swap rendered in the report + per-version rows
        report = runner.report()
        assert "model swaps" in report
        assert "v1 → v2" in report
        st = runner.stats()["f"]
        assert st["backend_v1_invokes"] == 10
        assert st["backend_v2_invokes"] == 10
        assert st["backend_swaps"] == 1

    def test_swap_through_dyn_batch_path(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        store.register("det", _v2)
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_batch max-batch=4 max-latency-ms=20 ! "
            "tensor_filter model=store://det ! tensor_unbatch ! "
            "tensor_sink name=out")
        runner = PipelineRunner(pipe)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        try:
            _push_ones(src, 12)
            _wait_for(lambda: len(sink.results) >= 12, what="v1 frames")
            store.update("det")
            _push_ones(src, 12)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        vals = _out_vals(sink)
        assert len(vals) == 24
        assert set(vals) == {V1, V2}
        flip = vals.index(V2)
        assert all(v == V1 for v in vals[:flip])
        assert all(v == V2 for v in vals[flip:])

    def test_pinned_ref_is_immune_to_swap(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        store.register("det", _v2)
        b = _open_backend("store://det@1")
        try:
            assert b.tracks_store_epoch is False
            store.update("det")
            out = b.invoke((np.ones(4, np.float32),))
            assert float(np.asarray(out[0])[0]) == V1
            assert b.swap_count == 0
        finally:
            b.close()

    def test_swap_barrier_completes_under_traffic(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        store.register("det", _v2)
        pipe = parse_launch(
            "videotestsrc width=2 height=2 num-buffers=400 ! "
            "tensor_converter ! "
            "tensor_filter name=f model=store://det ! tensor_sink name=out")
        runner = PipelineRunner(pipe)
        runner.start()
        sink = pipe.get("out")
        try:
            _wait_for(lambda: len(sink.results) >= 5, what="traffic")
            rep = store.update("det", wait_s=10.0)
        finally:
            runner.wait(30)
            runner.stop()
        assert rep["barrier_ok"] is True
        assert pipe.get("f").backend.adopted_epoch == rep["epoch"]


# -- chaos: swap with fault injection, conservation across the flip ----------

class TestChaosSwap:
    def test_conservation_across_flip(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        store.register("det", _v2)
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_fault name=flt mode=raise probability=0.08 seed=7 "
            "error-policy=skip ! "
            "tensor_filter model=store://det ! tensor_sink name=out")
        runner = PipelineRunner(pipe)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        try:
            _push_ones(src, 40)
            _wait_for(lambda: len(sink.results) >= 20, what="pre-swap flow")
            store.update("det")
            _push_ones(src, 40)
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        st = runner.stats()["flt"]
        assert sink.eos.is_set()
        # PR-3 conservation invariant holds across the epoch flip:
        # emitted + skipped + dropped == generated
        assert len(sink.results) + st["skipped"] + st["dropped"] == 80
        assert st["errors"] > 0 and st["skipped"] == st["errors"]
        # surviving frames still carry exactly one version's math
        vals = _out_vals(sink)
        assert set(vals) <= {V1, V2} and V2 in vals


# -- pre-warmed swap: recompile-free hot path --------------------------------

class TestPrewarm:
    def _serve_buckets(self, b):
        """Serve two dyn_batch buckets + one fixed bucket; return the
        math value observed (all-ones input)."""
        vals = set()
        for n in (3, 6):
            out = b.invoke_batched((np.ones((n, 4), np.float32),), n,
                                   keepdims=(False,))
            vals.add(float(np.asarray(out[0])[0, 0]))
        out = b.invoke((np.ones(4, np.float32),))
        vals.add(float(np.asarray(out[0])[0]))
        assert len(vals) == 1
        return vals.pop()

    def test_prewarmed_swap_hits_cache_only(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        b = _open_backend("store://det")
        try:
            assert self._serve_buckets(b) == V1
            store.register("det", _v2)
            rep = store.update("det")
            # all three served buckets compiled before the flip
            assert rep["prewarmed_buckets"] == 3
            cc0, ch0 = b.compile_count, b.cache_hits
            assert self._serve_buckets(b) == V2
            # the acceptance gate: same bucket set, post-flip, is pure
            # cache hits — zero recompiles on the hot path
            assert b.compile_count == cc0
            assert b.cache_hits == ch0 + 3
            assert b.swap_count == 1
        finally:
            b.close()

    def test_unwarmed_swap_recompiles(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        b = _open_backend("store://det")
        try:
            self._serve_buckets(b)
            store.register("det", _v2)
            rep = store.update("det", prewarm=False)
            assert rep["prewarmed_buckets"] == 0
            cc0 = b.compile_count
            assert self._serve_buckets(b) == V2
            assert b.compile_count == cc0 + 3   # the spike prewarm avoids
        finally:
            b.close()

    def test_incompatible_version_aborts_before_flip(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        b = _open_backend("store://det")
        try:
            self._serve_buckets(b)

            def bad(x):
                return (x @ np.ones((5, 5), np.float32),)   # wrong shape

            store.register("det", bad)
            with pytest.raises(BackendError, match="swap aborted"):
                store.update("det")
            # nothing flipped: still serving v1
            assert store.entry("det").current == 1
            assert self._serve_buckets(b) == V1
            assert b.swap_count == 0
        finally:
            b.close()


# -- canary routing ----------------------------------------------------------

class TestCanary:
    def _routed_vals(self, seed, n=300):
        b = _open_backend("store://det@2:0.25", canary_seed=seed)
        try:
            vals = []
            for _ in range(n):
                out = b.invoke((np.ones(4, np.float32),))
                vals.append(float(np.asarray(out[0])[0]))
            return vals
        finally:
            b.close()

    def test_ratio_within_tolerance_and_deterministic(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        store.register("det", _v2)
        vals = self._routed_vals(seed=7)
        share = vals.count(V2) / len(vals)
        assert 0.15 < share < 0.35      # 0.25 target, seeded sample
        # determinism: same seed → the exact same routing sequence
        assert self._routed_vals(seed=7) == vals
        assert self._routed_vals(seed=8) != vals

    def test_per_version_stats_split(self, _fresh_store):
        store = _fresh_store
        store.register("det", _v1)
        store.register("det", _v2)
        b = _open_backend("store://det@2:0.25", canary_seed=3)
        try:
            for _ in range(100):
                b.invoke((np.ones(4, np.float32),))
            vs = b.version_stats()
            assert vs[1]["invokes"] + vs[2]["invokes"] == 100
            assert vs[2]["invokes"] > 0
            assert vs[1]["errors"] == vs[2]["errors"] == 0
            assert vs[1]["p95_us"] > 0
        finally:
            b.close()

    def test_canary_version_must_differ_from_base(self, _fresh_store):
        _fresh_store.register("det", _v1)
        with pytest.raises(BackendError, match="canary"):
            _open_backend("store://det@1:0.25")


# -- persistent compile cache + bucket manifest ------------------------------

class TestCompileCache:
    def test_manifest_roundtrip_and_warm_start(self, _fresh_store,
                                               tmp_path, monkeypatch):
        monkeypatch.setenv("NNSTREAMER_TPU_SERVING_COMPILE_CACHE", "1")
        monkeypatch.setenv("NNSTREAMER_TPU_SERVING_COMPILE_CACHE_DIR",
                           str(tmp_path))
        compile_cache.reset()
        import jax
        try:
            store = _fresh_store
            store.register("det", _v1)
            b = _open_backend("store://det")
            b.invoke_batched((np.ones((3, 4), np.float32),), 3,
                             keepdims=(False,))
            b.invoke((np.ones(4, np.float32),))
            b.close()
            with open(tmp_path / "manifest.json") as f:
                man = json.load(f)
            kinds = sorted(r["kind"] for r in man["det@1"])
            assert kinds == ["dynb", "fix"]

            # "next process": fresh store + backend replay the manifest
            store = reset_store()
            store.register("det", _v1)
            b2 = _open_backend("store://det")
            assert b2.warm_start() == 2
            cc0 = b2.compile_count
            out = b2.invoke_batched((np.ones((3, 4), np.float32),), 3,
                                    keepdims=(False,))
            assert float(np.asarray(out[0])[0, 0]) == V1
            b2.invoke((np.ones(4, np.float32),))
            assert b2.compile_count == cc0    # warm start covered both
            b2.close()
        finally:
            compile_cache.reset()
            jax.config.update("jax_compilation_cache_dir", None)

    def test_disabled_by_default(self, _fresh_store):
        assert compile_cache.maybe_enable_compile_cache() is False
        assert compile_cache.cache_dir() is None
        store = _fresh_store
        store.register("det", _v1)
        b = _open_backend("store://det")
        try:
            assert b.warm_start() == 0     # nothing recorded, no replay
        finally:
            b.close()


# -- guard rails -------------------------------------------------------------

class TestGuards:
    def test_reload_on_store_filter_points_to_update(self, _fresh_store):
        _fresh_store.register("det", _v1)
        b = _open_backend("store://det")
        try:
            with pytest.raises(BackendError, match="ModelStore.update"):
                b.reload(_v2)
        finally:
            b.close()

    def test_shared_key_rejected(self, _fresh_store):
        _fresh_store.register("det", _v1)
        b = XLABackend()
        with pytest.raises(BackendError, match="shared-tensor-filter-key"):
            b.open({"model": "store://det", "accelerator": "",
                    "canary_seed": 0, "shared_tensor_filter_key": "k"})

    def test_cli_models_list_and_describe(self, _fresh_store, capsys):
        from nnstreamer_tpu.__main__ import main

        _fresh_store.register("det", _v1)
        assert main(["models", "list"]) == 0
        assert "store://det" in capsys.readouterr().out
        assert main(["models", "describe", "det"]) == 0
        assert '"current": 1' in capsys.readouterr().out
        assert main(["models", "swap", "det", "1"]) == 0
        assert '"to_version": 1' in capsys.readouterr().out
