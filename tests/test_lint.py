"""nnlint — per-rule fixtures, suppression/baseline machinery, and the
tier-1 gate that keeps the tree clean (docs/static_analysis.md).

Each rule gets a known-bad snippet it must fire on and a known-good one
it must stay silent on: the bad fixture pins the detector, the good one
pins the false-positive budget.  Fixtures are in-memory sources — the
linter is pure AST, nothing here is imported or executed.
"""

import json

import pytest

from nnstreamer_tpu.analysis import (
    SCHEMA_VERSION, element_contract, iter_rules, lint_report,
    load_baseline, project_from_sources, run_rules, write_baseline)
from nnstreamer_tpu.analysis.rules import ALL_RULES

REPO_PATHS = {
    "elem": "nnstreamer_tpu/elements/fix.py",
    "backend": "nnstreamer_tpu/backends/fix.py",
    "runtime": "nnstreamer_tpu/runtime/fix.py",
    "errors": "nnstreamer_tpu/core/errors.py",
}


def findings_for(rule_id, sources):
    project = project_from_sources(sources)
    report = run_rules(project, iter_rules([rule_id]))
    return report


def assert_fires(rule_id, sources, n_min=1):
    report = findings_for(rule_id, sources)
    assert len(report.findings) >= n_min, \
        f"{rule_id} should fire on the bad fixture"
    assert all(f.rule == rule_id for f in report.findings)
    return report.findings


def assert_silent(rule_id, sources):
    report = findings_for(rule_id, sources)
    assert report.clean, \
        f"{rule_id} false positives: {[str(f) for f in report.findings]}"


# -- NNL001 element-contract -------------------------------------------------

BAD_ELEMENT = '''
from nnstreamer_tpu.graph.pipeline import DYNAMIC, Element, SinkElement

class HalfTimer(Element):
    NUM_SINK_PADS = DYNAMIC
    def next_deadline(self):
        return None

class FusedTimer(Element):
    CHAIN_FUSABLE = True
    def next_deadline(self):
        return None
    def on_timer(self, now):
        pass

class ResidentSink(SinkElement):
    DEVICE_RESIDENT = True

class Mutator(Element):
    def __init__(self):
        self.CHAIN_FUSABLE = False
'''

GOOD_ELEMENT = '''
from nnstreamer_tpu.graph.pipeline import DYNAMIC, Element, SinkElement

class Batchy(Element):
    NUM_SINK_PADS = DYNAMIC
    CHAIN_FUSABLE = False
    def next_deadline(self):
        return None
    def on_timer(self, now):
        pass

class PlainSink(SinkElement):
    pass

class CallThrough(Element):
    NUM_SINK_PADS = 1
    NUM_SRC_PADS = 1
'''


def test_nnl001_fires_on_contract_violations():
    found = assert_fires("NNL001", {REPO_PATHS["elem"]: BAD_ELEMENT},
                         n_min=4)
    msgs = " ".join(f.message for f in found)
    assert "next_deadline without on_timer" in msgs
    assert "CHAIN_FUSABLE = False" in msgs
    assert "DEVICE_RESIDENT" in msgs
    assert "mutated per-instance" in msgs


def test_nnl001_silent_on_declared_contracts():
    assert_silent("NNL001", {REPO_PATHS["elem"]: GOOD_ELEMENT})


# -- NNL002 forced-sync ------------------------------------------------------

BAD_SYNC = '''
import jax
import numpy as np

def f(x):
    jax.block_until_ready(x)
    y = jax.device_get(x)
    return np.asarray(x)
'''

GOOD_SYNC = '''
import numpy as np
from nnstreamer_tpu.runtime.sync import device_sync

def f(x, tracer):
    out = np.asarray(device_sync(x, tracer=tracer, name="f"))
    table = np.asarray([1, 2], np.int32)   # 2-arg dtype conversion
    return out, table
'''


def test_nnl002_fires_on_direct_syncs():
    found = assert_fires("NNL002", {REPO_PATHS["backend"]: BAD_SYNC},
                         n_min=3)
    msgs = " ".join(f.message for f in found)
    assert "block_until_ready" in msgs
    assert "device_get" in msgs
    assert "np.asarray" in msgs


def test_nnl002_silent_on_device_sync_idiom():
    assert_silent("NNL002", {REPO_PATHS["backend"]: GOOD_SYNC})


def test_nnl002_asarray_scoped_to_device_layers():
    # elements/ consume host arrays the scheduler already resolved —
    # a bare asarray there is not a hidden sync
    assert_silent("NNL002", {
        REPO_PATHS["elem"]: "import numpy as np\n"
                            "def f(x):\n    return np.asarray(x)\n"})
    # runtime/sync.py itself is the one place the primitives live
    assert_silent("NNL002", {
        "nnstreamer_tpu/runtime/sync.py":
            "import jax\n"
            "def device_sync(t):\n"
            "    jax.block_until_ready(t)\n    return t\n"})


# -- NNL003 lock-discipline --------------------------------------------------

BAD_LOCK = '''
import time

class C:
    def f(self):
        with self._lock:
            time.sleep(0.1)

    def g(self, q):
        with self._state_lock:
            return q.get(timeout=1.0)

    def h(self, t):
        with self._lock:
            t.join()
'''

GOOD_LOCK = '''
import time

class C:
    def f(self):
        with self._lock:
            snapshot = dict(self._state)
        time.sleep(0.1)                     # blocking OUTSIDE the lock
        return snapshot

    def g(self):
        with self._lock:
            v = self._cache.get("key")      # dict.get, not a queue
        return v

    def h(self, data):
        with self.send_lock:
            self.sock.sendall(data)         # write-serialization lock

    def i(self, cv):
        with self._lock:
            def cb():
                time.sleep(1)               # nested def: not run here
            return cb
'''


def test_nnl003_fires_on_blocking_under_lock():
    found = assert_fires("NNL003", {REPO_PATHS["runtime"]: BAD_LOCK},
                         n_min=3)
    msgs = " ".join(f.message for f in found)
    assert "time.sleep" in msgs
    assert "queue/channel get()" in msgs
    assert "join" in msgs


def test_nnl003_silent_on_disciplined_locking():
    assert_silent("NNL003", {REPO_PATHS["runtime"]: GOOD_LOCK})


# -- NNL004 jit-purity -------------------------------------------------------

BAD_JIT = '''
import time
import jax

def impure(x):
    return x * time.time()

fast = jax.jit(impure)

@jax.jit
def also_impure(x):
    import random
    return x + random.random()
'''

BAD_JIT_CROSS_MAIN = '''
import jax
from nnstreamer_tpu.jhelp import helper

fast = jax.jit(helper)
'''

BAD_JIT_CROSS_HELPER = '''
import time

def helper(x):
    return x * time.perf_counter()
'''

GOOD_JIT = '''
import jax
import jax.numpy as jnp

def pure(x):
    return jnp.tanh(x) * 2.0

fast = jax.jit(pure)

@jax.jit
def also_pure(x):
    return pure(x) + 1.0
'''


def test_nnl004_fires_on_impure_jit():
    found = assert_fires("NNL004", {REPO_PATHS["runtime"]: BAD_JIT},
                         n_min=2)
    msgs = " ".join(f.message for f in found)
    assert "time.time" in msgs
    assert "random.random" in msgs


def test_nnl004_follows_cross_module_imports():
    assert_fires("NNL004", {
        REPO_PATHS["runtime"]: BAD_JIT_CROSS_MAIN,
        "nnstreamer_tpu/jhelp.py": BAD_JIT_CROSS_HELPER})


def test_nnl004_silent_on_pure_jit():
    assert_silent("NNL004", {REPO_PATHS["runtime"]: GOOD_JIT})


# -- NNL005 spawn-safety -----------------------------------------------------

WORKER = "nnstreamer_tpu/serving/worker.py"

BAD_SPAWN = {
    WORKER: "from nnstreamer_tpu.serving import spawn_helper\n",
    "nnstreamer_tpu/serving/spawn_helper.py":
        "import jax\n"
        "WARM = jax.jit(lambda x: x)\n",
}

GOOD_SPAWN = {
    WORKER: "from nnstreamer_tpu.serving import spawn_helper\n",
    "nnstreamer_tpu/serving/spawn_helper.py":
        "def warm(x):\n"
        "    import jax\n"          # lazy: runs on first call, not import
        "    return jax.jit(lambda y: y)(x)\n",
}


def test_nnl005_fires_on_module_scope_jax_in_worker_closure():
    found = assert_fires("NNL005", BAD_SPAWN, n_min=2)
    assert {f.path for f in found} == \
        {"nnstreamer_tpu/serving/spawn_helper.py"}


def test_nnl005_silent_on_lazy_imports():
    assert_silent("NNL005", GOOD_SPAWN)


def test_nnl005_ignores_modules_outside_the_closure():
    # same jax-at-import sin, but nothing the worker imports
    assert_silent("NNL005", {
        WORKER: "import os\n",
        "nnstreamer_tpu/elements/heavy.py": "import jax\n"})


# -- NNL006 picklable-errors -------------------------------------------------

BAD_ERRORS = '''
class NakedError(Exception):
    def __init__(self, what, code):
        super().__init__(f"{what} [{code}]")
'''

GOOD_ERRORS = '''
def _rebuild(cls, args):
    return cls.__new__(cls)

class BaseError(Exception):
    def __reduce__(self):
        return (_rebuild, (type(self), self.args))

class ChildError(BaseError):
    def __init__(self, what, code):
        super().__init__(f"{what} [{code}]")

class _PrivateScratch(Exception):
    pass

class NotAnError:
    pass
'''


def test_nnl006_fires_on_unpicklable_error():
    found = assert_fires("NNL006", {REPO_PATHS["errors"]: BAD_ERRORS})
    assert "NakedError" in found[0].message


def test_nnl006_silent_on_reduce_chain():
    assert_silent("NNL006", {REPO_PATHS["errors"]: GOOD_ERRORS})


def test_nnl006_only_checks_errors_modules():
    assert_silent("NNL006", {REPO_PATHS["runtime"]: BAD_ERRORS})


# -- NNL007 thread-audit -----------------------------------------------------

BAD_THREAD = '''
import threading

def fire_and_forget(fn):
    threading.Thread(target=fn).start()
    threading.Timer(5.0, fn).start()
'''

GOOD_THREAD = '''
import threading

class Owner:
    def start(self, fn):
        self._t = threading.Thread(target=fn, daemon=True)
        self._t.start()
        self._timer = threading.Timer(5.0, fn)
        self._timer.daemon = True
        self._timer.start()
        self._j = threading.Thread(target=fn)
        self._j.start()

    def close(self):
        self._timer.cancel()
        self._j.join()

class Looper(threading.Thread):
    def __init__(self):
        super().__init__(daemon=True)
'''


def test_nnl007_fires_on_orphan_threads():
    assert_fires("NNL007", {REPO_PATHS["runtime"]: BAD_THREAD}, n_min=2)


def test_nnl007_silent_on_owned_threads():
    assert_silent("NNL007", {REPO_PATHS["runtime"]: GOOD_THREAD})


# -- NNL008 socket-audit -----------------------------------------------------

EDGE_PATH = "nnstreamer_tpu/edge/fix.py"

BAD_SOCKET = '''
import socket

def dial(host, port):
    return socket.create_connection((host, port))   # unbounded dial

class Poller:
    def __init__(self):
        self._sock = socket.socket()                # no deadline, no owner

    def poll(self):
        return self._sock.recv(4)
'''

GOOD_SOCKET = '''
import socket
import threading

def dial(host, port):
    return socket.create_connection((host, port), 5.0)

def dial_kw(host, port):
    return socket.create_connection((host, port), timeout=5.0)

class Poller:
    def __init__(self):
        self._sock = socket.socket()
        self._sock.settimeout(2.0)                  # bounded

class Server:
    def __init__(self):
        self._srv = socket.socket()                 # accept-thread-owned
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while True:
            self._srv.accept()
'''


def test_nnl008_fires_on_unbounded_sockets():
    findings = assert_fires("NNL008", {EDGE_PATH: BAD_SOCKET}, n_min=2)
    msgs = " | ".join(f.message for f in findings)
    assert "connect timeout" in msgs       # the dial arm
    assert "deadline discipline" in msgs   # the raw-socket arm


def test_nnl008_silent_on_bounded_or_thread_owned():
    assert_silent("NNL008", {EDGE_PATH: GOOD_SOCKET})


def test_nnl008_scoped_to_serving_path():
    # the same unbounded sockets outside edge/serving/traffic are
    # someone else's problem (tooling, tests, offline scripts)
    assert_silent("NNL008", {REPO_PATHS["runtime"]: BAD_SOCKET})


# -- NNL009 placement-audit --------------------------------------------------

BAD_PLACEMENT = '''
import jax

def pin():
    d = jax.devices()[0]                 # explicit ordinal pick
    e = jax.local_devices()[2]
    return d, e
'''

GOOD_PLACEMENT = '''
import jax

def enumerate_all():
    n = len(jax.devices())               # counting is fine
    head = jax.devices()[:n]             # slices keep the set, not a pick
    return head
'''


def test_nnl009_fires_on_explicit_device_pick():
    findings = assert_fires(
        "NNL009", {REPO_PATHS["backend"]: BAD_PLACEMENT}, n_min=2)
    assert all("placement" in f.message for f in findings)


def test_nnl009_silent_on_enumeration_and_slices():
    assert_silent("NNL009", {REPO_PATHS["backend"]: GOOD_PLACEMENT})


def test_nnl009_blessed_in_placement_and_parallel():
    # serving/placement.py and parallel/ ARE the placement subsystem —
    # the rule exists to keep device picks from leaking anywhere else
    assert_silent("NNL009", {
        "nnstreamer_tpu/serving/placement.py": BAD_PLACEMENT,
        "nnstreamer_tpu/parallel/mesh.py": BAD_PLACEMENT,
    })


# -- NNL010 device-accounting ------------------------------------------------

BAD_ACCOUNTING = '''
import jax

PEAK_BF16_TFLOPS = 275.0                 # second peak table: drift bait

def probe(jitted, args):
    cost = jitted.lower(*args).cost_analysis()   # cost-model read
    ms = jax.devices()[0].memory_stats()         # memory ledger read
    return cost, ms
'''

GOOD_ACCOUNTING = '''
from nnstreamer_tpu.runtime import devprof

def probe(jitted, args, dt):
    prof = devprof.get()
    prof.capture_cost("f", "static", jitted, args, seconds=dt)
    return prof.stats()
'''


def test_nnl010_fires_on_accounting_outside_devprof():
    findings = assert_fires(
        "NNL010", {REPO_PATHS["backend"]: BAD_ACCOUNTING}, n_min=3)
    msgs = " ".join(f.message for f in findings)
    assert "cost_analysis" in msgs and "memory_stats" in msgs
    assert "PEAK_BF16_TFLOPS" in msgs


def test_nnl010_silent_on_profiler_reporting():
    assert_silent("NNL010", {REPO_PATHS["backend"]: GOOD_ACCOUNTING})


def test_nnl010_blessed_in_devprof_and_bench():
    # runtime/devprof.py IS the accounting site; bench.py keeps its
    # sweep-local peak table by design (it lives outside the package)
    assert_silent("NNL010", {
        "nnstreamer_tpu/runtime/devprof.py": BAD_ACCOUNTING,
        "bench.py": BAD_ACCOUNTING,
    })


# -- NNL011 seeded-chaos -----------------------------------------------------

BAD_CHAOS_RNG = '''
import random
import numpy as np

def schedule_faults():
    jitter = random.Random()                 # OS-entropy: no replay
    rng = np.random.default_rng()            # ditto
    return jitter.random(), rng.random()
'''

GOOD_CHAOS_RNG = '''
import random
import numpy as np

def schedule_faults(seed):
    jitter = random.Random(seed)
    rng = np.random.default_rng(seed + 1)
    kw = np.random.default_rng(seed=seed)
    return jitter.random(), rng.random(), kw.random()
'''


def test_nnl011_fires_on_unseeded_rng_in_chaos_paths():
    for path in ("nnstreamer_tpu/traffic/fix.py",
                 "nnstreamer_tpu/scenario/fix.py",
                 "nnstreamer_tpu/serving/worker.py"):
        findings = assert_fires("NNL011", {path: BAD_CHAOS_RNG},
                                n_min=2)
        msgs = " ".join(f.message for f in findings)
        assert "random.Random" in msgs and "default_rng" in msgs


def test_nnl011_silent_on_seeded_rng():
    assert_silent("NNL011",
                  {"nnstreamer_tpu/traffic/fix.py": GOOD_CHAOS_RNG})


def test_nnl011_silent_outside_the_chaos_paths():
    # an unseeded rng elsewhere is someone else's design decision
    assert_silent("NNL011", {REPO_PATHS["backend"]: BAD_CHAOS_RNG,
                             REPO_PATHS["elem"]: BAD_CHAOS_RNG})


# -- NNL012 shard-safety -----------------------------------------------------

BAD_SHARDING = '''
import jax
from jax.sharding import NamedSharding, PartitionSpec

def place(mesh, tree, fn):
    spec = PartitionSpec("tp")                       # private mesh program
    placed = jax.device_put(tree, NamedSharding(mesh, spec))
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec,),
                         out_specs=spec)(placed)
'''

GOOD_SHARDING = '''
from nnstreamer_tpu.serving import sharding

def place(params, mesh, n_heads):
    placed, specs = sharding.shard_llm_params(params, mesh,
                                              n_heads=n_heads)
    return placed, sharding.kv_pool_placer(mesh)
'''


def test_nnl012_fires_on_mesh_program_outside_subsystem():
    findings = assert_fires(
        "NNL012", {REPO_PATHS["backend"]: BAD_SHARDING}, n_min=4)
    msgs = " ".join(f.message for f in findings)
    # both arms: the jax import and every construction site
    assert "from jax.sharding import" in msgs
    assert "shard_map" in msgs and "NamedSharding" in msgs \
        and "PartitionSpec" in msgs


def test_nnl012_silent_on_consuming_the_subsystem():
    assert_silent("NNL012", {REPO_PATHS["backend"]: GOOD_SHARDING})


def test_nnl012_blessed_in_parallel_and_sharding():
    # parallel/ and serving/sharding.py ARE the sharding subsystem —
    # the rule keeps private mesh programs from leaking anywhere else
    assert_silent("NNL012", {
        "nnstreamer_tpu/serving/sharding.py": BAD_SHARDING,
        "nnstreamer_tpu/parallel/ring_attention.py": BAD_SHARDING,
        "nnstreamer_tpu/parallel/_compat.py": BAD_SHARDING,
    })


# -- NNL013 shm-safety -------------------------------------------------------

BAD_SHM = '''
import mmap
import pickle
from multiprocessing import shared_memory

def open_segment(name, frames):
    seg = shared_memory.SharedMemory(name=name, create=True, size=4096)
    ring = mmap.mmap(-1, 4096)                     # second lifetime story
    for f in frames:
        blob = pickle.dumps(f)                     # per-frame re-serialize
        seg.buf[:len(blob)] = blob
    return seg, ring
'''

GOOD_SHM = '''
import pickle
from nnstreamer_tpu.serving.shm import ShmRing, ring_name

def open_rings(pool, wid, spawn, frames):
    ring = ShmRing.create(ring_name("rq", pool, wid, spawn))
    blob = pickle.dumps(frames)          # hoisted: once per batch
    for _ in frames:
        ring.try_write(blob)
    return ring
'''


def test_nnl013_fires_on_segment_lifetime_outside_shm_module():
    findings = assert_fires(
        "NNL013", {"nnstreamer_tpu/serving/fix.py": BAD_SHM}, n_min=4)
    msgs = " ".join(f.message for f in findings)
    # all three arms: the import, each construction site, and the
    # per-frame pickle.dumps in the hot loop
    assert "multiprocessing.shared_memory" in msgs
    assert "SharedMemory" in msgs and "mmap.mmap" in msgs
    assert "pickle.dumps" in msgs


def test_nnl013_silent_on_routing_through_shm_ring():
    assert_silent("NNL013",
                  {"nnstreamer_tpu/serving/fix.py": GOOD_SHM})


def test_nnl013_blessed_in_the_shm_module_itself():
    # serving/shm.py IS the lifetime owner — the rule keeps segments
    # from being constructed anywhere else. (The hot-loop pickle arm
    # still applies there, so strip the loop body for this fixture.)
    segments_only = BAD_SHM.replace("blob = pickle.dumps(f)",
                                    "blob = bytes(f)")
    assert_silent("NNL013",
                  {"nnstreamer_tpu/serving/shm.py": segments_only})


def test_nnl013_per_frame_pickle_scoped_to_serving():
    # a pickle loop outside serving/ is someone else's trade-off; the
    # segment-construction arm still applies everywhere
    assert_silent("NNL013", {REPO_PATHS["runtime"]: GOOD_SHM})
    findings = assert_fires("NNL013", {REPO_PATHS["runtime"]: BAD_SHM},
                            n_min=3)
    assert not any("pickle.dumps" in f.message for f in findings)


# -- suppressions ------------------------------------------------------------

def test_inline_suppression_waives_a_finding():
    src = BAD_SYNC.replace(
        "jax.block_until_ready(x)",
        "jax.block_until_ready(x)  # nnlint: disable=NNL002 warm path")
    report = findings_for("NNL002", {REPO_PATHS["backend"]: src})
    assert len(report.suppressed) == 1
    assert report.suppressed[0].rule == "NNL002"
    # the other two sites still fire
    assert len(report.findings) == 2


def test_disable_all_and_unrelated_rule():
    src = ("import time\n"
           "class C:\n"
           "    def f(self):\n"
           "        with self._lock:\n"
           "            time.sleep(1)  # nnlint: disable=all wedge drill\n")
    assert_silent("NNL003", {REPO_PATHS["runtime"]: src})
    src_wrong = src.replace("disable=all", "disable=NNL001")
    report = findings_for("NNL003", {REPO_PATHS["runtime"]: src_wrong})
    assert len(report.findings) == 1   # NNL001 disable does not cover 003


# -- baseline ----------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    sources = {REPO_PATHS["backend"]: BAD_SYNC}
    report = findings_for("NNL002", sources)
    assert not report.clean
    bl = tmp_path / "baseline.json"
    write_baseline(bl, report.findings)
    report2 = run_rules(project_from_sources(sources),
                        iter_rules(["NNL002"]), load_baseline(bl))
    assert report2.clean
    assert report2.baselined == len(report.findings)


def test_fingerprint_survives_line_shifts(tmp_path):
    report = findings_for("NNL002", {REPO_PATHS["backend"]: BAD_SYNC})
    bl = tmp_path / "baseline.json"
    write_baseline(bl, report.findings)
    shifted = "# one\n# two\n# three\n" + BAD_SYNC
    report2 = run_rules(
        project_from_sources({REPO_PATHS["backend"]: shifted}),
        iter_rules(["NNL002"]), load_baseline(bl))
    assert report2.clean, "baseline must match across pure line shifts"


# -- report schema / rule catalog -------------------------------------------

def test_json_report_schema():
    report = findings_for("NNL002", {REPO_PATHS["backend"]: BAD_SYNC})
    d = json.loads(json.dumps(report.to_json()))
    assert d["version"] == SCHEMA_VERSION
    assert set(d) == {"version", "clean", "files", "rules", "counts",
                      "baselined", "suppressed", "findings"}
    assert d["clean"] is False
    assert d["counts"] == {"NNL002": len(d["findings"])}
    for f in d["findings"]:
        assert set(f) == {"rule", "path", "line", "col", "message",
                          "fingerprint", "suppressed"}
        assert f["line"] > 0 and len(f["fingerprint"]) == 16


def test_rule_catalog_complete():
    ids = [r.rule_id for r in ALL_RULES]
    assert ids == sorted(set(ids)), "rule ids unique and ordered"
    assert len(ids) >= 7
    for r in ALL_RULES:
        assert r.title and r.rationale
    with pytest.raises(ValueError):
        iter_rules(["NNL999"])


def test_syntax_error_becomes_nnl000(tmp_path):
    from nnstreamer_tpu.analysis.core import build_project
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    p = build_project([str(bad)], root=tmp_path)
    r = run_rules(p, iter_rules(None))
    assert [f.rule for f in r.findings] == ["NNL000"]


# -- contract introspection (docs + linter share one truth) ------------------

def test_element_contract_introspection():
    from nnstreamer_tpu.elements.batch import TensorBatch
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.routing import Tee

    c = element_contract(TensorBatch)
    assert c["timer"] is True
    assert c["chain_fusable"] is False
    assert c["sink_pads"] == "dynamic"

    c = element_contract(TensorFilter)
    assert c["device_resident"] is True
    assert c["chain_fusable"] is False

    c = element_contract(Tee)
    assert c["timer"] is False
    assert c["src_pads"] == "dynamic"


# -- the tier-1 gate ---------------------------------------------------------

def test_tree_is_lint_clean():
    """The whole package must lint clean against the committed (empty)
    baseline: new findings are fixed or inline-justified, never
    accumulated."""
    import pathlib
    root = pathlib.Path(__file__).resolve().parents[1]
    report = lint_report(["nnstreamer_tpu"], root=root,
                         baseline_path=root / "nnlint_baseline.json")
    assert report.files > 100
    assert report.clean, "unbaselined findings:\n" + "\n".join(
        str(f) for f in report.findings)
    assert report.baselined == 0, \
        "the committed baseline must stay empty (fix or inline-suppress)"
