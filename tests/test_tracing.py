"""Tracing subsystem: span events, interlatency percentiles, queue
gauges, Chrome-trace export, drop/error accounting (CPU-only; timing
assertions use budgets generous enough for CI jitter)."""

import json
import time

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu import parse_launch, register_custom_easy, run_pipeline
from nnstreamer_tpu.backends.custom import unregister_custom_easy
from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.runtime.scheduler import PipelineRunner
from nnstreamer_tpu.runtime.tracing import (
    NULL_TRACER, SOURCE_TS_META, NullTracer, Tracer, percentile)


@pytest.fixture(autouse=True)
def _clean_models():
    names = []

    def reg(name, *a, **kw):
        names.append(name)
        return register_custom_easy(name, *a, **kw)

    yield reg
    for n in names:
        unregister_custom_easy(n)


def _run_traced(desc, timeout=30, **kw):
    p = parse_launch(desc)
    runner = PipelineRunner(p, trace=True, **kw).start()
    try:
        runner.wait(timeout)
    finally:
        runner.stop()
    return p, runner


SLEEP_S = 0.01


def _sleepy(ts):
    time.sleep(SLEEP_S)
    return ts


class TestTracerCore:
    def test_default_is_noop(self):
        p = parse_launch("videotestsrc width=4 height=4 num-buffers=2 "
                         "! tensor_converter ! tensor_sink")
        runner = PipelineRunner(p)
        assert runner.tracer is NULL_TRACER
        assert runner.tracer.active is False
        runner.start()
        runner.wait(10)
        runner.stop()
        # a NullTracer records nothing and has no ring to inspect
        assert isinstance(runner.tracer, NullTracer)

    def test_percentile_nearest_rank(self):
        vals = sorted(float(i) for i in range(1, 101))
        assert percentile(vals, 50) == 50.0
        assert percentile(vals, 99) == 99.0
        assert percentile(vals, 100) == 100.0
        assert percentile([], 50) == 0.0

    def test_process_spans_per_element_ordered(self):
        p, runner = _run_traced(
            "videotestsrc width=4 height=4 num-buffers=6 ! "
            "tensor_converter name=conv ! tensor_sink name=out")
        spans = {}
        for ph, cat, name, label, ts, dur, args in runner.tracer.events():
            if ph == "X" and label == "process":
                spans.setdefault(name, []).append((ts, dur))
        # every non-source element got one process span per buffer...
        assert len(spans["conv"]) == 6
        assert len(spans["out"]) == 6
        # ...in monotonically increasing start order (one worker thread
        # per element: spans on one track never interleave)
        for name, ss in spans.items():
            starts = [t for t, _ in ss]
            assert starts == sorted(starts)
            assert all(d >= 0.0 for _, d in ss)

    def test_interlatency_percentiles_sleep_element(self, _clean_models):
        _clean_models("sleepy", _sleepy)
        p, runner = _run_traced(
            "videotestsrc width=4 height=4 num-buffers=8 ! tensor_converter "
            "! tensor_transform mode=typecast option=float32 "
            "! tensor_filter framework=custom model=sleepy "
            "! tensor_sink name=out")
        inter = runner.tracer.interlatency()
        assert "out" in inter
        r = inter["out"]
        assert r["n"] == 8
        # every frame crossed the sleeping filter: end-to-end latency at
        # the sink is at least the sleep, and percentiles are ordered
        assert r["p50_ms"] >= SLEEP_S * 1e3
        assert r["p50_ms"] <= r["p95_ms"] <= r["p99_ms"] <= r["max_ms"]
        # the source-side converter saw the frame before the sleep: its
        # median must come in under the sink's
        conv = [v for k, v in inter.items() if k != "out"]
        assert conv and min(c["p50_ms"] for c in conv) < r["p50_ms"]

    def test_source_ts_stamped_in_meta(self):
        p, runner = _run_traced(
            "videotestsrc width=4 height=4 num-buffers=2 ! "
            "tensor_converter ! tensor_sink name=out")
        for buf in p.get("out").results:
            assert SOURCE_TS_META in buf.meta

    def test_queue_highwater_under_backpressure(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=20 ! "
            "tensor_converter ! tensor_sink name=out")
        sink = p.get("out")
        orig = sink.render

        def slow_render(buf):
            time.sleep(0.005)
            orig(buf)

        sink.render = slow_render
        runner = PipelineRunner(p, queue_capacity=2, trace=True).start()
        runner.wait(30)
        runner.stop()
        assert len(sink.results) == 20
        # the slow sink's queue filled to capacity — visible both in the
        # tracer gauge and the always-on stats high-water mark
        assert runner.tracer.queue_gauges()["out"]["peak"] >= 2
        assert runner.stats()["out"]["queue_peak"] >= 2

    def test_event_ring_is_bounded(self):
        tr = Tracer(max_events=16)
        for i in range(100):
            tr.instant("e", "tick", t=float(i))
        assert len(tr.events()) == 16
        assert tr.events_dropped == 84
        # the ring keeps the newest events
        assert tr.events()[-1][4] == 99.0


class TestChromeTrace:
    def test_schema_and_one_track_per_element(self, _clean_models):
        _clean_models("ident", lambda ts: ts)
        p, runner = _run_traced(
            "videotestsrc width=4 height=4 num-buffers=4 ! "
            "tensor_converter name=conv "
            "! tensor_transform mode=typecast option=float32 "
            "! tensor_filter framework=custom model=ident name=filt "
            "! tensor_sink name=out")
        doc = runner.tracer.to_chrome_trace("demo")
        # valid JSON round-trip of the Trace Event Format container
        doc = json.loads(json.dumps(doc))
        assert isinstance(doc["traceEvents"], list)
        assert doc["displayTimeUnit"] == "ms"
        tracks = {}
        for ev in doc["traceEvents"]:
            assert ev["ph"] in ("M", "X", "C", "i")
            assert "pid" in ev
            if ev["ph"] == "M" and ev["name"] == "thread_name":
                tracks[ev["args"]["name"]] = ev["tid"]
            if ev["ph"] == "X":
                assert ev["ts"] >= 0.0 and ev["dur"] >= 0.0
            if ev["ph"] == "C":
                assert "depth" in ev["args"]
        # one named track per element that produced events, unique tids
        for name in ("conv", "filt", "out"):
            assert name in tracks
        assert len(set(tracks.values())) == len(tracks)
        # spans reference declared tracks only
        declared = set(tracks.values())
        for ev in doc["traceEvents"]:
            if ev["ph"] == "X":
                assert ev["tid"] in declared

    def test_batch_flush_markers_and_batched_interlatency(self):
        from nnstreamer_tpu.tensor.buffer import TensorBuffer
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

        desc = ("appsrc name=in dims=4 types=float32 ! "
                "tensor_batch name=b max-batch=4 max-latency-ms=1000 ! "
                "tensor_unbatch ! tensor_sink name=out")
        p = parse_launch(desc)
        runner = PipelineRunner(p, trace=True).start()
        src = p.get("in")
        for i in range(10):
            src.push(TensorBuffer.of(np.full((4,), float(i), np.float32),
                                     pts=i))
        src.end()
        runner.wait(30)
        runner.stop()
        flushes = [(name, label, args) for ph, cat, name, label, ts, dur,
                   args in runner.tracer.events()
                   if ph == "i" and label.startswith("flush_")]
        # 10 frames at max-batch=4 → two full flushes + one EOS flush
        assert [l for _, l, _ in flushes].count("flush_full") == 2
        assert [l for _, l, _ in flushes].count("flush_eos") == 1
        assert {a["n"] for _, _, a in flushes} == {4, 2}
        # interlatency survives batch→unbatch: per-frame source stamps
        # ride in the dyn_batch frame metas and are restored downstream
        inter = runner.tracer.interlatency()
        assert inter["out"]["n"] == 10
        # the batcher's own interlatency comes from the oldest frame in
        # each batch (the deadline-bound one)
        assert inter["b"]["n"] == 10

    def test_backend_spans_and_cache_counters(self):
        from nnstreamer_tpu.backends.xla import XLABackend
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

        be = XLABackend()
        be.open({"model": lambda x: x * 2.0})
        be.set_input_info(TensorsSpec.of(TensorInfo((1, 4), DType.FLOAT32)))
        tr = Tracer()
        be.tracer = tr
        be.trace_name = "filt"
        try:
            for n in (3, 3, 5):
                x = np.ones((n, 4), np.float32)
                be.invoke_batched((x,), n, [True])
        finally:
            be.close()
        # 3→bucket 4 (miss), 3→bucket 4 (hit), 5→bucket 8 (miss)
        assert be.cache_misses == 2
        assert be.cache_hits == 1
        spans = [(name, label, args) for ph, cat, name, label, ts, dur,
                 args in tr.events() if ph == "X" and cat == "backend"]
        assert len(spans) == 3
        assert all(name == "filt" and label == "invoke_batched"
                   for name, label, _ in spans)
        assert [a["cache"] for _, _, a in spans] == ["miss", "hit", "miss"]
        assert [a["bucket"] for _, _, a in spans] == [4, 4, 8]


class TestReport:
    def test_report_table_and_sections(self, _clean_models):
        _clean_models("sleepy", _sleepy)
        p, runner = _run_traced(
            "videotestsrc width=4 height=4 num-buffers=4 ! tensor_converter "
            "! tensor_transform mode=typecast option=float32 "
            "! tensor_filter framework=custom model=sleepy name=filt "
            "! tensor_sink name=out")
        rep = runner.report()
        assert "element report" in rep
        assert "queue high-water" in rep
        assert "interlatency" in rep
        assert "(sink)" in rep
        for col in ("buffers", "total ms", "q.peak", "p50", "p99"):
            assert col in rep
        # sorted by total proctime: the sleeping filter leads the table
        table_rows = [l for l in rep.splitlines()
                      if l.startswith(("filt", "out", "conv"))]
        assert table_rows and table_rows[0].startswith("filt")

    def test_report_without_tracer_still_has_proctime(self):
        p = parse_launch("videotestsrc width=4 height=4 num-buffers=2 "
                         "! tensor_converter ! tensor_sink name=out")
        runner = PipelineRunner(p).start()
        runner.wait(10)
        runner.stop()
        rep = runner.report()
        assert "element report" in rep
        assert "queue high-water" in rep
        assert "interlatency" not in rep


class TestSchedulerAccounting:
    def test_wait_timeout_chains_pending_error(self, _clean_models):
        def boom(ts):
            raise RuntimeError("model exploded")

        _clean_models("boom", boom, infer_out=lambda s: s)
        # appsrc never ends: the source pump stays alive after the filter
        # fails, so wait() hits the timeout path WITH a pending error —
        # the root cause must surface, not a bare timeout
        p = parse_launch(
            "appsrc name=in dims=4 types=float32 ! "
            "tensor_filter framework=custom model=boom ! tensor_sink")
        runner = PipelineRunner(p).start()
        p.get("in").push(np.zeros((4,), np.float32))
        time.sleep(0.2)
        with pytest.raises(StreamError, match="model exploded"):
            runner.wait(0.5)
        runner.stop()

    def test_teardown_drop_counter(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=40 ! "
            "tensor_converter name=conv ! tensor_sink name=out")
        sink = p.get("out")
        orig = sink.render

        def crawl(buf):
            time.sleep(0.2)
            orig(buf)

        sink.render = crawl
        runner = PipelineRunner(p, queue_capacity=1).start()
        time.sleep(0.5)   # producers are now blocked on the full queue
        runner.stop()
        runner.wait(10)
        st = runner.stats()
        # the aborted put loop counted its lost buffer on the producer
        assert sum(d["dropped"] for d in st.values()) >= 1
        # clean EOS runs never drop (covered by every other test here,
        # asserted once explicitly):
        p2, r2 = _run_traced("videotestsrc width=4 height=4 num-buffers=3 "
                             "! tensor_converter ! tensor_sink")
        assert all(d["dropped"] == 0 for d in r2.stats().values())

    def test_noop_tracer_overhead_smoke(self, _clean_models):
        _clean_models("sleepy", _sleepy)
        desc = ("videotestsrc width=4 height=4 num-buffers=12 ! "
                "tensor_converter "
                "! tensor_transform mode=typecast option=float32 "
                "! tensor_filter framework=custom model=sleepy name=filt "
                "! tensor_sink")

        def proctime(trace):
            p = parse_launch(desc)
            runner = PipelineRunner(p, trace=trace).start()
            runner.wait(30)
            runner.stop()
            return runner.stats()["filt"]["proctime_avg_us"]

        off = proctime(False)
        on = proctime(True)
        # the filter's work is a 10ms sleep: tracing (off OR on) must be
        # invisible at this scale — generous 1.5x bound for CI jitter,
        # the real claim (≤10%) is held by the dyn_batch bench family
        assert off < SLEEP_S * 1e6 * 1.5
        assert on < off * 1.5


class TestDebugCapture:
    def test_capture_bounded_and_extra_stats(self):
        p = parse_launch(
            "videotestsrc width=4 height=4 num-buffers=12 ! "
            "tensor_converter ! "
            "tensor_debug name=dbg capture=true capture-limit=5 ! "
            "tensor_sink name=out")
        runner = PipelineRunner(p).start()
        runner.wait(10)
        runner.stop()
        dbg = p.get("dbg")
        assert len(dbg.lines) == 5            # bounded: oldest dropped
        st = runner.stats()["dbg"]
        assert st["buffers_seen"] == 12
        assert st["captured_lines"] == 5
        # 12 buffer lines + 1 negotiation line, 5 kept
        assert st["capture_dropped"] == 8
        # the deque keeps the newest lines: the (earliest) negotiation
        # line is among the dropped
        assert not any("negotiated" in l for l in dbg.lines)


class TestCLI:
    def test_trace_subcommand_writes_valid_trace(self, tmp_path, capsys):
        from nnstreamer_tpu.__main__ import main

        out = tmp_path / "trace.json"
        rc = main(["trace",
                   "videotestsrc width=4 height=4 num-buffers=3 ! "
                   "tensor_converter ! tensor_sink",
                   "--out", str(out), "--timeout", "30"])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
        names = {ev["args"]["name"] for ev in doc["traceEvents"]
                 if ev["ph"] == "M" and ev["name"] == "thread_name"}
        assert len(names) >= 2       # converter + sink tracks at least
        captured = capsys.readouterr()
        assert "element report" in captured.out
        assert "interlatency" in captured.out


# -- distributed tracing (request contexts, child shipping, merge) -----------

from nnstreamer_tpu.runtime.tracing import (  # noqa: E402
    HIST_BOUNDS_S, TRACE_CTX_META, ensure_trace_ctx, get_trace_ctx,
    hop_spans, merge_chrome_traces, stamp_hop)
from nnstreamer_tpu.tensor.buffer import TensorBuffer  # noqa: E402


class TestTraceContext:
    def test_ensure_creates_once_and_reuses_id(self):
        meta = {}
        ctx = ensure_trace_ctx(meta)
        assert len(ctx["id"]) == 16 and ctx["hops"] == []
        # the retry invariant: a re-offered buffer keeps its id
        assert ensure_trace_ctx(meta)["id"] == ctx["id"]
        assert meta[TRACE_CTX_META] is ctx

    def test_get_never_creates(self):
        meta = {}
        assert get_trace_ctx(meta) is None
        assert meta == {}
        assert get_trace_ctx(None) is None
        assert get_trace_ctx({"_trace_ctx": "junk"}) is None

    def test_stamp_is_noop_without_ctx(self):
        # the tracer-off hot path: stamping sites run unguarded on
        # every frame, so without a context they must not mutate meta,
        # allocate a context, or return a record
        meta = {"pts": 3}
        assert stamp_hop(meta, "admit") is None
        assert meta == {"pts": 3}
        assert stamp_hop(None, "admit") is None
        assert stamp_hop("not-a-dict", "admit") is None

    def test_stamp_appends_with_extras(self):
        meta = {}
        ensure_trace_ctx(meta)
        rec = stamp_hop(meta, "dispatch", wid=1, attempt=0)
        assert rec["hop"] == "dispatch" and rec["wid"] == 1
        assert rec["pid"] > 0 and rec["t"] > 0
        assert get_trace_ctx(meta)["hops"] == [rec]

    def test_hop_spans_decomposition(self):
        hops = [{"hop": h, "t": t} for h, t in (
            ("client_send", 1.000), ("admit", 1.001), ("dequeue", 1.003),
            ("dispatch", 1.004), ("worker_recv", 1.010),
            ("worker_done", 1.030), ("reply", 1.031))]
        s = hop_spans(hops)
        assert s["admission_wait_ms"] == pytest.approx(2.0, abs=1e-6)
        assert s["route_ms"] == pytest.approx(1.0, abs=1e-6)
        assert s["worker_queue_ms"] == pytest.approx(6.0, abs=1e-6)
        assert s["service_ms"] == pytest.approx(20.0, abs=1e-6)
        assert s["reply_ms"] == pytest.approx(1.0, abs=1e-6)
        assert s["total_ms"] == pytest.approx(31.0, abs=1e-6)
        assert "retries" not in s and "redeliveries" not in s

    def test_hop_spans_redelivery_last_attempt_wins(self):
        hops = [{"hop": h, "t": t} for h, t in (
            ("client_send", 0.0), ("client_send", 0.050),   # one retry
            ("admit", 0.051), ("dequeue", 0.052),
            ("dispatch", 0.053), ("reoffer", 0.080),        # dead worker
            ("dispatch", 0.081), ("worker_recv", 0.082),
            ("worker_done", 0.092), ("reply", 0.093))]
        s = hop_spans(hops)
        assert s["retries"] == 1
        assert s["redeliveries"] == 1
        # stage math uses the LAST dispatch, not the dead one
        assert s["worker_queue_ms"] == pytest.approx(1.0, abs=1e-6)

    def test_hop_spans_lists_hosts_in_first_dispatch_order(self):
        """ISSUE 12 satellite: a cross-host redelivered request keeps
        ONE trace whose dispatch hops name every host it touched —
        the span view surfaces them in first-dispatch order."""
        hops = [{"hop": "client_send", "t": 0.0},
                {"hop": "admit", "t": 0.001},
                {"hop": "dispatch", "t": 0.002, "host": "hB"},
                {"hop": "reoffer", "t": 0.050, "cause": "host_lost"},
                {"hop": "dispatch", "t": 0.051, "host": "hA"},
                # second attempt on the same host must not duplicate
                {"hop": "dispatch", "t": 0.052, "host": "hA"},
                {"hop": "worker_recv", "t": 0.053},
                {"hop": "worker_done", "t": 0.060},
                {"hop": "reply", "t": 0.061}]
        s = hop_spans(hops)
        assert s["hosts"] == ["hB", "hA"]
        assert s["redeliveries"] == 1
        # single-host (or pool-local, no host key) traces stay clean
        assert "hosts" not in hop_spans(
            [{"hop": "dispatch", "t": 0.0}, {"hop": "reply", "t": 0.1}])

    def test_wire_codec_carries_nested_ctx(self):
        from nnstreamer_tpu.edge.wire import decode_buffer, encode_buffer

        buf = TensorBuffer.of(np.ones((4,), np.float32), pts=7)
        ensure_trace_ctx(buf.meta)
        stamp_hop(buf.meta, "client_send", pts=7)
        out, _ = decode_buffer(encode_buffer(buf))
        ctx = get_trace_ctx(out.meta)
        assert ctx is not None
        assert ctx["id"] == get_trace_ctx(buf.meta)["id"]
        assert ctx["hops"][0]["hop"] == "client_send"


class TestChildShipping:
    def _child_with_work(self, n=5):
        child = Tracer()
        child.enable_shipping()
        buf = TensorBuffer.of(np.ones((2,), np.float32))
        t0 = time.perf_counter()
        for i in range(n):
            child.record_process("echo", buf, t0 + i, t0 + i + 0.001)
        return child

    def test_ship_delta_then_quiet_returns_none(self):
        child = self._child_with_work()
        delta = child.ship_delta()
        assert delta["events_total_delta"] == 5
        assert delta["hists"]["echo"]["count"] == 5
        assert child.ship_delta() is None      # nothing new

    def test_deltas_not_cumulative(self):
        child = self._child_with_work(3)
        child.ship_delta()
        buf = TensorBuffer.of(np.ones((2,), np.float32))
        t0 = time.perf_counter()
        child.record_process("echo", buf, t0, t0 + 0.001)
        d2 = child.ship_delta()
        assert d2["events_total_delta"] == 1
        assert d2["hists"]["echo"]["count"] == 1

    def test_parent_merge_namespaces_and_counts(self):
        parent = Tracer()
        child = self._child_with_work(4)
        parent.ingest_child(0, 111, child.ship_delta(), label="pool-w0")
        assert parent.hists()["w0/echo"]["count"] == 4
        kids = parent.children()
        assert kids[0]["pid"] == 111 and kids[0]["events_total"] == 4
        assert kids[0]["events_dropped"] == 0
        assert parent.summary()["children"]["0"]["label"] == "pool-w0"

    def test_restart_resumes_totals_monotone(self):
        # a replacement worker ships deltas from zero; parent totals
        # must keep rising, never reset
        parent = Tracer()
        child = self._child_with_work(3)
        parent.ingest_child(0, 111, child.ship_delta())
        total_before = parent.total_events
        replacement = self._child_with_work(2)     # fresh process
        parent.ingest_child(0, 222, replacement.ship_delta())
        assert parent.total_events == total_before + 2
        assert parent.hists()["w0/echo"]["count"] == 5
        assert parent.children()[0]["pid"] == 222  # new pid tracked

    def test_clock_offset_applied_to_child_events(self):
        parent = Tracer()
        buf = TensorBuffer.of(np.ones((2,), np.float32))
        t0 = time.perf_counter()
        parent.record_process("router", buf, t0, t0 + 1e-4)
        child = self._child_with_work(1)
        parent.ingest_child(0, 111, child.ship_delta(), offset_s=100.0)
        doc = parent.to_chrome_trace("p")
        parent_spans = [e for e in doc["traceEvents"]
                        if e.get("ph") == "X" and e.get("pid") == 0]
        child_spans = [e for e in doc["traceEvents"]
                       if e.get("ph") == "X" and e.get("pid") == 1]
        assert parent_spans and child_spans
        # Chrome ts is µs (normalized to trace start): the 100s skew
        # correction must push the child span ~100s past the parent's
        gap_us = child_spans[0]["ts"] - parent_spans[0]["ts"]
        assert gap_us >= 99.0 * 1e6

    def test_ring_wrap_keeps_child_drop_accounting_exact(self):
        # satellite: child batches arriving after the PARENT ring
        # wrapped must keep events_dropped and per-element counters
        # exact — the per-child ring has its own drop budget
        parent = Tracer(max_events=64)     # child rings: max(1024, 16)
        # wrap the parent's own ring completely
        buf = TensorBuffer.of(np.ones((2,), np.float32))
        t0 = time.perf_counter()
        for i in range(200):
            parent.record_process("parent_el", buf, t0, t0 + 1e-4)
        assert parent.events_dropped > 0
        parent_dropped = parent.events_dropped
        # now a child ships MORE events than its parent-side ring holds
        child = Tracer()
        child.enable_shipping()
        for i in range(1500):
            child.record_process("echo", buf, t0, t0 + 1e-4)
        parent.ingest_child(0, 111, child.ship_delta())
        kids = parent.children()
        assert kids[0]["events_total"] == 1500
        assert kids[0]["events_kept"] == 1024
        assert kids[0]["events_dropped"] == 1500 - 1024
        # pool-level totals: monotone counter and exact drop sum
        assert parent.total_events == 200 + 1500
        assert parent.events_dropped == parent_dropped + (1500 - 1024)
        # histogram counters survive wrap exactly (kept-whole, not ring)
        assert parent.hists()["w0/echo"]["count"] == 1500

    def test_child_ring_wrap_reported_by_child(self):
        # the CHILD's own ring can wrap between ships: its self-reported
        # drop delta must flow into the parent's accounting
        parent = Tracer()
        child = Tracer(max_events=32)
        child.enable_shipping()
        buf = TensorBuffer.of(np.ones((2,), np.float32))
        t0 = time.perf_counter()
        for i in range(100):
            child.record_process("echo", buf, t0, t0 + 1e-4)
        delta = child.ship_delta()
        assert delta["events_dropped_delta"] == 100 - 32
        parent.ingest_child(0, 111, delta)
        assert parent.children()[0]["events_dropped"] == 100 - 32
        assert parent.events_dropped >= 100 - 32

    def test_requests_merge_with_offset(self):
        parent = Tracer()
        child = Tracer()
        child.enable_shipping()
        hops = [{"hop": "worker_recv", "t": 1.0},
                {"hop": "worker_done", "t": 1.002}]
        child.record_request("svc", "abcd1234abcd1234", hops, 1.002)
        parent.ingest_child(1, 99, child.ship_delta(), offset_s=2.0)
        reqs = parent.requests()
        assert len(reqs) == 1
        name, tid, t, _, _ = reqs[0]
        assert name == "w1/svc" and tid == "abcd1234abcd1234"
        assert t == pytest.approx(3.002)


class TestMergeChromeTraces:
    def test_pid_remap_no_collisions(self):
        def mkdoc():
            tr = Tracer()
            child = Tracer()
            child.enable_shipping()
            buf = TensorBuffer.of(np.ones((2,), np.float32))
            t0 = time.perf_counter()
            child.record_process("echo", buf, t0, t0 + 1e-4)
            tr.record_process("router", buf, t0, t0 + 1e-4)
            tr.ingest_child(0, 1, child.ship_delta())
            return tr.to_chrome_trace("p")

        a, b = mkdoc(), mkdoc()
        merged = merge_chrome_traces([a, b], labels=["runA", "runB"])
        pids = {e["pid"] for e in merged["traceEvents"]}
        names = {e["pid"]: e["args"]["name"]
                 for e in merged["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        assert len(pids) == 4            # 2 docs x (parent + 1 worker)
        assert sum(1 for n in names.values()
                   if n.startswith("runA/")) == 2
        assert sum(1 for n in names.values()
                   if n.startswith("runB/")) == 2
        total = len(a["traceEvents"]) + len(b["traceEvents"])
        assert len(merged["traceEvents"]) == total


class TestHistBounds:
    def test_bounds_cover_service_range(self):
        assert HIST_BOUNDS_S[0] == pytest.approx(1e-5)
        assert HIST_BOUNDS_S[-1] == pytest.approx(10.0)
        assert list(HIST_BOUNDS_S) == sorted(HIST_BOUNDS_S)
