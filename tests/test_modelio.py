"""Model-file ingestion tests (VERDICT r1 missing #1).

Golden strategy mirrors the reference's filter-subplugin suites
(tests/nnstreamer_filter_tensorflow_lite/runTest.sh): load the
reference's own checked-in tiny models, compare semantics against an
independent CPU implementation (tf.lite.Interpreter when available),
plus format/negative cases.
"""

import os

import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.modelio import (
    load_model_file,
    load_params,
    lower_tflite,
    parse_loader_opts,
    parse_tflite,
    save_params,
)

MODELS = "/root/reference/tests/test_models/models"
MOBILENET = os.path.join(MODELS, "mobilenet_v2_1.0_224_quant.tflite")
ADD = os.path.join(MODELS, "add.tflite")
LABELS = "/root/reference/tests/test_models/labels/labels.txt"

needs_models = pytest.mark.skipif(
    not os.path.exists(MOBILENET), reason="reference test models absent")


def _tflite_interpreter(path):
    tf = pytest.importorskip("tensorflow")
    interp = tf.lite.Interpreter(path)
    interp.allocate_tensors()
    return interp


def _synthetic_images(n, seed=42):
    """Deterministic structured images with peaked logits — the shared
    generator (core.fixtures), yielded one (1, 224, 224, 3) frame at a
    time to match the single-frame interpreter loops here."""
    from nnstreamer_tpu.core.fixtures import synthetic_frames

    for frame in synthetic_frames(n, seed=seed):
        yield frame[None]


# -- flatbuffer parsing ------------------------------------------------------

@needs_models
def test_parse_tflite_structure():
    g = parse_tflite(MOBILENET)
    assert {o.name for o in g.ops} == {
        "CONV_2D", "DEPTHWISE_CONV_2D", "ADD", "AVERAGE_POOL_2D", "RESHAPE"}
    (i,) = g.inputs
    (o,) = g.outputs
    assert g.tensors[i].shape == (1, 224, 224, 3)
    assert g.tensors[i].dtype == np.uint8 and g.tensors[i].quantized
    assert g.tensors[o].shape == (1, 1001)
    # uint8-quant model: weights present and quantized
    n_const = sum(1 for t in g.tensors if t.buffer is not None)
    assert n_const > 100


@needs_models
def test_parse_tflite_rejects_garbage(tmp_path):
    bad = tmp_path / "x.tflite"
    bad.write_bytes(b"\x00" * 64)
    with pytest.raises(BackendError, match="TFL3"):
        parse_tflite(str(bad))


def test_load_model_file_missing():
    with pytest.raises(BackendError, match="does not exist"):
        load_model_file("/nonexistent/model.tflite")


def test_load_model_file_bad_ext(tmp_path):
    p = tmp_path / "m.weird"
    p.write_bytes(b"x")
    with pytest.raises(BackendError, match="unsupported model file"):
        load_model_file(str(p))


def test_parse_loader_opts():
    opts = parse_loader_opts("batch=8, dtype=float32, quantize_output=false")
    assert opts == {"batch": 8, "compute_dtype": "float32",
                    "quantize_output": False}
    assert parse_loader_opts("") == {}


# -- add.tflite: float model golden -----------------------------------------

@needs_models
def test_add_tflite_golden_vs_interpreter():
    import jax

    m = lower_tflite(parse_tflite(ADD), compute_dtype="float32")
    x = np.array([3.5], np.float32)
    ours = np.asarray(jax.jit(m.fn)(m.params, x)[0])

    interp = _tflite_interpreter(ADD)
    d = interp.get_input_details()[0]
    interp.set_tensor(d["index"], x)
    interp.invoke()
    ref = interp.get_tensor(interp.get_output_details()[0]["index"])
    np.testing.assert_allclose(ours, ref, rtol=1e-6)


# -- quantized mobilenet: the flagship golden -------------------------------

@pytest.fixture(scope="module")
def mobilenet_lowered():
    if not os.path.exists(MOBILENET):
        pytest.skip("reference test models absent")
    g = parse_tflite(MOBILENET)
    return {
        "float32": lower_tflite(g, compute_dtype="float32"),
        "bfloat16": lower_tflite(g, compute_dtype="bfloat16"),
    }


@needs_models
@pytest.mark.parametrize("dtype,min_agree", [("float32", 9), ("bfloat16", 8)])
def test_mobilenet_quant_top1_golden(mobilenet_lowered, dtype, min_agree):
    """Top-1 label agreement with the TFLite CPU interpreter on 10
    deterministic images (VERDICT r1 item 2 done-criterion)."""
    import jax

    interp = _tflite_interpreter(MOBILENET)
    ind = interp.get_input_details()[0]["index"]
    outd = interp.get_output_details()[0]["index"]
    m = mobilenet_lowered[dtype]
    fn = jax.jit(m.fn)
    agree = 0
    for x in _synthetic_images(10):
        interp.set_tensor(ind, x)
        interp.invoke()
        ref = interp.get_tensor(outd)[0]
        ours = np.asarray(fn(m.params, x)[0])[0]
        assert ours.dtype == np.uint8 and ours.shape == (1001,)
        agree += int(ref.argmax()) == int(ours.argmax())
    assert agree >= min_agree, f"{dtype}: top-1 agreement {agree}/10"


@needs_models
def test_mobilenet_batch_override(mobilenet_lowered):
    """custom=batch=N reshapes the graph for batched invoke."""
    import jax

    m4 = lower_tflite(parse_tflite(MOBILENET), batch=4,
                      compute_dtype="float32")
    assert m4.in_shapes == [(4, 224, 224, 3)]
    assert m4.out_shapes == [(4, 1001)]
    x1 = next(iter(_synthetic_images(1)))
    x4 = np.concatenate([x1] * 4, axis=0)
    out4 = np.asarray(jax.jit(m4.fn)(m4.params, x4)[0])
    m1 = mobilenet_lowered["float32"]
    out1 = np.asarray(jax.jit(m1.fn)(m1.params, x1)[0])
    for row in out4:
        # same image in each batch slot ⇒ same quantized logits (±1 lsb
        # for XLA batched-vs-single conv reassociation)
        assert np.abs(row.astype(int) - out1[0].astype(int)).max() <= 1


# -- int8-native execution (tflite_quant.py) --------------------------------

@needs_models
def test_mobilenet_int8_native_top1_golden():
    """The int8-native lowering (integer convs on the MXU path, ones-
    channel zero-point augmentation, int16-folded depthwise) must agree
    with the TFLite interpreter at least as well as the float path."""
    import jax

    from nnstreamer_tpu.modelio.tflite_quant import (
        lower_tflite_quant, quantized_graph_supported)

    g = parse_tflite(MOBILENET)
    assert quantized_graph_supported(g)
    m = lower_tflite_quant(g)
    assert m.in_dtypes == [np.dtype(np.uint8)]
    assert m.out_dtypes == [np.dtype(np.uint8)]
    interp = _tflite_interpreter(MOBILENET)
    ind = interp.get_input_details()[0]["index"]
    outd = interp.get_output_details()[0]["index"]
    fn = jax.jit(m.fn)
    agree = 0
    worst = 0
    for x in _synthetic_images(10):
        interp.set_tensor(ind, x)
        interp.invoke()
        ref = interp.get_tensor(outd)[0]
        ours = np.asarray(fn(m.params, x)[0])[0]
        assert ours.dtype == np.uint8 and ours.shape == (1001,)
        agree += int(ref.argmax()) == int(ours.argmax())
        worst = max(worst, np.abs(ref.astype(int)
                                  - ours.astype(int)).max())
    assert agree >= 9, f"int8-native top-1 agreement {agree}/10"
    # integer pipeline tracks the interpreter to a few quantized units
    # (ties in the last bit differ: f32 multiplier vs fixed-point)
    assert worst <= 4, f"worst quantized-output diff {worst}"


@needs_models
def test_int8_native_via_load_model_file_and_batch():
    import jax

    m = load_model_file(MOBILENET, batch=3, compute_dtype="int8")
    assert m.in_spec.tensors[0].shape == (3, 224, 224, 3)
    x1 = next(iter(_synthetic_images(1)))
    x3 = np.concatenate([x1] * 3, axis=0)
    out3 = np.asarray(jax.jit(m.fn)(m.params, x3)[0])
    assert out3.shape == (3, 1001) and out3.dtype == np.uint8
    # batch slots are independent in a feedforward net
    assert np.array_equal(out3[0], out3[1])


@needs_models
def test_int8_native_rejects_float_graph():
    deeplab = os.path.join(MODELS, "deeplabv3_257_mv_gpu.tflite")
    if not os.path.exists(deeplab):
        pytest.skip("deeplab model absent")
    with pytest.raises(BackendError, match="int8-native|fully-quantized"):
        load_model_file(deeplab, compute_dtype="int8")
    # auto mode falls back to the float lowering instead
    m = load_model_file(deeplab, compute_dtype="auto")
    assert m.fn is not None


# -- deeplab: float model with resize/concat ---------------------------------

@needs_models
def test_deeplab_float_golden_vs_interpreter():
    """Float model exercising RESIZE_BILINEAR + CONCATENATION paths."""
    import jax

    path = os.path.join(MODELS, "deeplabv3_257_mv_gpu.tflite")
    m = lower_tflite(parse_tflite(path), compute_dtype="float32")
    rng = np.random.RandomState(1)
    x = rng.rand(1, 257, 257, 3).astype(np.float32)
    ours = np.asarray(jax.jit(m.fn)(m.params, x)[0])

    interp = _tflite_interpreter(path)
    interp.set_tensor(interp.get_input_details()[0]["index"], x)
    interp.invoke()
    ref = interp.get_tensor(interp.get_output_details()[0]["index"])
    np.testing.assert_allclose(ours, ref, atol=5e-4)


# -- through the pipeline (tensor_filter model=path) -------------------------

@needs_models
def test_pipeline_tflite_model_produces_correct_label():
    """`tensor_filter model=/path/mobilenet.tflite` + image_labeling
    decoder emit the interpreter's label (end-to-end done-criterion)."""
    import importlib.util

    imgs = list(_synthetic_images(3))
    pipe = nns.parse_launch(
        f"appsrc name=in dims=3:224:224:1 types=uint8 ! "
        f"tensor_filter model={MOBILENET} custom=dtype=float32 ! "
        f"tensor_decoder mode=image_labeling option1={LABELS} ! "
        f"tensor_sink name=out")
    runner = nns.PipelineRunner(pipe)
    runner.start()
    src = pipe.get("in")
    for x in imgs:
        src.push(x)
    src.end()
    runner.wait(120)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 3

    labels = [ln.strip() for ln in open(LABELS)]
    if importlib.util.find_spec("tensorflow") is not None:
        interp = _tflite_interpreter(MOBILENET)
        ind = interp.get_input_details()[0]["index"]
        outd = interp.get_output_details()[0]["index"]
        agree = 0
        for x, r in zip(imgs, res):
            interp.set_tensor(ind, x)
            interp.invoke()
            scores = interp.get_tensor(outd)[0]
            if r.meta["label"] == labels[int(scores.argmax())]:
                agree += 1
            else:
                # quantization-borderline: ours must still be in the
                # interpreter's top-5
                top5 = [labels[i] for i in scores.argsort()[-5:]]
                assert r.meta["label"] in top5, (r.meta["label"], top5)
        assert agree >= 2, f"only {agree}/3 exact label agreement"
    else:
        for r in res:
            assert r.meta["label"] in labels


@needs_models
def test_filter_autodetects_xla_for_tflite_ext():
    from nnstreamer_tpu.elements.filter import TensorFilter

    f = TensorFilter(model=MOBILENET)
    assert f._framework_name() == "xla"


# -- npz params format -------------------------------------------------------

def test_npz_roundtrip_preserves_tree(tmp_path):
    params = {"layer": {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                        "b": np.zeros(3, np.int8)},
              "scales": [np.float32(1.5), np.ones(2)],
              "none_leaf": None,
              "tup": (np.uint8(7),)}
    p = str(tmp_path / "m.npz")
    save_params(p, "zoo://mobilenet_v2?width=0.35", params)
    arch, loaded = load_params(p)
    assert arch == "zoo://mobilenet_v2?width=0.35"
    assert loaded["none_leaf"] is None
    assert isinstance(loaded["tup"], tuple)
    np.testing.assert_array_equal(loaded["layer"]["w"], params["layer"]["w"])
    assert loaded["layer"]["b"].dtype == np.int8


def test_npz_rejects_foreign_archive(tmp_path):
    p = str(tmp_path / "foreign.npz")
    np.savez(p, a=np.ones(3))
    with pytest.raises(BackendError, match="__meta__"):
        load_params(p)


def test_npz_model_file_runs_zoo_arch(tmp_path):
    """model=saved.npz rebuilds the zoo fn with the *stored* params."""
    from nnstreamer_tpu.models.zoo import build_model
    from nnstreamer_tpu.single import SingleShot

    bundle = build_model("mobilenet_v2?width=0.35&num_classes=10")
    p = str(tmp_path / "m.npz")
    save_params(p, "zoo://mobilenet_v2?width=0.35&num_classes=10",
                bundle.params)
    shot = SingleShot(p)
    x = np.zeros((1, 32, 32, 3), np.float32)
    got = shot.invoke(x)
    ref = SingleShot(bundle).invoke(x)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref[0]),
                               rtol=2e-2, atol=1e-3)


# -- TF frozen GraphDef ingestion (graphdef.py) ------------------------------

MNIST_PB = os.path.join(MODELS, "mnist.pb")
CONV_ACTIONS_PB = os.path.join(MODELS, "conv_actions_frozen.pb")
NINE_RAW = "/root/reference/tests/test_models/data/9.raw"
YES_WAV = "/root/reference/tests/test_models/data/yes.wav"


@needs_models
def test_graphdef_mnist_digit():
    """Reference runTest.sh case 1: 9.raw → normalize → mnist.pb
    (inputname=input outputname=softmax) classifies digit 9."""
    import jax

    from nnstreamer_tpu.modelio.graphdef import (
        lower_graphdef, parse_graphdef)

    m = lower_graphdef(parse_graphdef(MNIST_PB), input_names=["input"],
                       output_names=["softmax"])
    assert m.in_shapes == [(1, 784)]
    assert m.out_shapes == [(1, 10)]
    raw = np.fromfile(NINE_RAW, np.uint8).astype(np.float32)
    x = ((raw - 127.5) / 127.5).reshape(1, 784)
    y = np.asarray(jax.jit(m.fn)(m.params, x)[0])
    assert int(y.argmax()) == 9


@needs_models
def test_graphdef_mnist_golden_vs_tf():
    tf = pytest.importorskip("tensorflow")
    import jax

    from nnstreamer_tpu.modelio.graphdef import (
        lower_graphdef, parse_graphdef)

    m = lower_graphdef(parse_graphdef(MNIST_PB), input_names=["input"],
                       output_names=["softmax"])
    x = np.random.RandomState(0).uniform(-1, 1, (1, 784)).astype(np.float32)
    ours = np.asarray(jax.jit(m.fn)(m.params, x)[0])
    gd = tf.compat.v1.GraphDef()
    gd.ParseFromString(open(MNIST_PB, "rb").read())
    with tf.Graph().as_default() as g:
        tf.import_graph_def(gd, name="")
        with tf.compat.v1.Session(graph=g) as sess:
            ref = sess.run("softmax:0", {"input:0": x})
    np.testing.assert_allclose(ours, ref, atol=1e-5)


@needs_models
def test_graphdef_speech_command_yes():
    """Reference runTest.sh case 3: yes.wav raw bytes (int16 tensor,
    header included) → conv_actions_frozen.pb → label 2 ('yes'). The
    DecodeWav entry decodes host-side; spectrogram+MFCC+conv run as one
    XLA program."""
    import jax

    from nnstreamer_tpu.modelio.graphdef import (
        lower_graphdef, parse_graphdef)

    m = lower_graphdef(parse_graphdef(CONV_ACTIONS_PB),
                       input_names=["wav_data"],
                       output_names=["labels_softmax"])
    wav = open(YES_WAV, "rb").read()
    raw = np.frombuffer(wav, np.int16)[None, :]
    (audio,) = m.host_pre((raw,))
    assert audio.shape == (16000, 1)
    y = np.asarray(jax.jit(m.fn)(m.params, audio)[0])
    assert y.shape == (1, 12)
    assert int(y.argmax()) == 2


@needs_models
def test_graphdef_audio_frontend_golden_vs_tf_kernels():
    tf = pytest.importorskip("tensorflow")
    import jax.numpy as jnp

    from nnstreamer_tpu.modelio.graphdef import (
        audio_spectrogram, decode_wav_bytes, mfcc)

    rng = np.random.default_rng(0)
    audio = rng.normal(0, 0.1, (4000, 1)).astype(np.float32)
    spec_tf = tf.raw_ops.AudioSpectrogram(
        input=audio, window_size=320, stride=160,
        magnitude_squared=True).numpy()
    spec_us = np.asarray(audio_spectrogram(jnp, jnp.asarray(audio),
                                           320, 160, True))
    np.testing.assert_allclose(spec_us, spec_tf, rtol=2e-3, atol=1e-4)
    mf_tf = tf.raw_ops.Mfcc(
        spectrogram=spec_tf, sample_rate=16000,
        upper_frequency_limit=4000.0, lower_frequency_limit=20.0,
        filterbank_channel_count=40, dct_coefficient_count=13).numpy()
    mf_us = np.asarray(mfcc(jnp, jnp.asarray(spec_tf), 16000,
                            upper_hz=4000.0, lower_hz=20.0,
                            fb_channels=40, dct_count=13))
    np.testing.assert_allclose(mf_us, mf_tf, atol=0.05)
    wav = open(YES_WAV, "rb").read()
    a_tf = tf.raw_ops.DecodeWav(contents=wav, desired_samples=16000,
                                desired_channels=1)
    a_us, rate = decode_wav_bytes(wav, 16000, 1)
    assert rate == int(a_tf.sample_rate)
    np.testing.assert_allclose(a_us, a_tf.audio.numpy(), atol=1e-6)


@needs_models
def test_graphdef_pipeline_mnist():
    """Full pipeline with the reference's property surface: inputname/
    outputname bind graph nodes (tensor_filter_tensorflow.cc parity)."""
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    pipe = nns.parse_launch(
        f"appsrc name=src dims=784:1 types=uint8 ! "
        f"tensor_transform mode=arithmetic "
        f"option=typecast:float32,add:-127.5,div:127.5 ! "
        f"tensor_filter model={MNIST_PB} inputname=input "
        f"outputname=softmax ! tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    raw = np.fromfile(NINE_RAW, np.uint8).reshape(1, 784)
    pipe.get("src").push(TensorBuffer.of(raw))
    pipe.get("src").end()
    runner.wait(120)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 1
    assert int(np.asarray(res[0].tensors[0]).argmax()) == 9


@needs_models
def test_graphdef_pipeline_speech_wav():
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    wav = open(YES_WAV, "rb").read()
    n16 = len(wav) // 2
    pipe = nns.parse_launch(
        f"appsrc name=src dims=1:{n16} types=int16 ! "
        f"tensor_filter model={CONV_ACTIONS_PB} inputname=wav_data "
        f"outputname=labels_softmax ! tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    raw = np.frombuffer(wav[:n16 * 2], np.int16).reshape(n16, 1)
    pipe.get("src").push(TensorBuffer.of(raw))
    pipe.get("src").end()
    runner.wait(120)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 1
    assert int(np.asarray(res[0].tensors[0]).argmax()) == 2


def test_graphdef_rejects_garbage(tmp_path):
    from nnstreamer_tpu.modelio.graphdef import parse_graphdef

    p = tmp_path / "junk.pb"
    p.write_bytes(b"\xff\xfe definitely not a graphdef \x00\x01")
    with pytest.raises(BackendError, match="GraphDef"):
        parse_graphdef(str(p))


@needs_models
def test_graphdef_unsupported_op_fails_loudly(tmp_path):
    """A graph containing an op outside the vocabulary must name it."""
    import jax

    from nnstreamer_tpu.modelio.graphdef import (
        lower_graphdef, parse_graphdef)

    nodes = parse_graphdef(MNIST_PB)
    bad = [n for n in nodes]
    bad[-3].op = "SomeExoticOp"       # the MatMul node
    # the lowering's shape probe walks the graph, so the unsupported op
    # is reported at load time, naming the op
    with pytest.raises(BackendError, match="SomeExoticOp"):
        lower_graphdef(bad, input_names=["input"],
                       output_names=["softmax"])


# -- converter-built models: custom detection op + control-flow LSTM ---------

@pytest.fixture(scope="module")
def built_models(tmp_path_factory):
    """Tiny models built in-test with the TF converter (VERDICT r2 next
    #3): a detection head ending in the TFLite_Detection_PostProcess
    CUSTOM op (the reference query-server demo's model shape) and a
    keras LSTM (converts to a WHILE control-flow graph)."""
    tf = pytest.importorskip("tensorflow")
    d = tmp_path_factory.mktemp("built_tflite")

    # detection: frozen GraphDef with the custom op, TF1-style convert
    N, C = 96, 4
    gd = tf.compat.v1.GraphDef()

    def node(name, op, inputs=(), **attrs):
        n = gd.node.add()
        n.name = name
        n.op = op
        n.input.extend(inputs)
        for k, v in attrs.items():
            if isinstance(v, bool):
                n.attr[k].b = v
            elif isinstance(v, int):
                n.attr[k].i = v
            elif isinstance(v, float):
                n.attr[k].f = v
            elif isinstance(v, np.ndarray):
                n.attr[k].tensor.CopyFrom(tf.make_tensor_proto(v))
        n.attr.get_or_create("T")
        return n

    pl = node("box_encodings", "Placeholder")
    pl.attr["dtype"].type = tf.float32.as_datatype_enum
    pl.attr["shape"].shape.CopyFrom(tf.TensorShape((1, N, 4)).as_proto())
    pl2 = node("class_predictions", "Placeholder")
    pl2.attr["dtype"].type = tf.float32.as_datatype_enum
    pl2.attr["shape"].shape.CopyFrom(
        tf.TensorShape((1, N, C + 1)).as_proto())
    rng = np.random.default_rng(0)
    anch = np.concatenate([rng.uniform(0.1, 0.9, (N, 2)),
                           rng.uniform(0.1, 0.3, (N, 2))],
                          axis=1).astype(np.float32)
    cn = node("anchors", "Const", value=anch)
    cn.attr["dtype"].type = tf.float32.as_datatype_enum
    node("TFLite_Detection_PostProcess", "TFLite_Detection_PostProcess",
         ["box_encodings", "class_predictions", "anchors"],
         max_detections=10, max_classes_per_detection=1,
         nms_score_threshold=0.3, nms_iou_threshold=0.5, num_classes=C,
         y_scale=10.0, x_scale=10.0, h_scale=5.0, w_scale=5.0,
         use_regular_nms=False, detections_per_class=100)
    pb = d / "detect.pb"
    pb.write_bytes(gd.SerializeToString())
    conv = tf.compat.v1.lite.TFLiteConverter.from_frozen_graph(
        str(pb), ["box_encodings", "class_predictions"],
        ["TFLite_Detection_PostProcess", "TFLite_Detection_PostProcess:1",
         "TFLite_Detection_PostProcess:2",
         "TFLite_Detection_PostProcess:3"],
        input_shapes={"box_encodings": [1, N, 4],
                      "class_predictions": [1, N, C + 1]})
    conv.allow_custom_ops = True
    det = d / "detect.tflite"
    det.write_bytes(conv.convert())

    # LSTM: keras → WHILE-loop tflite (frozen consts)
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    m = tf.keras.Sequential([
        tf.keras.layers.Input((8, 6), batch_size=1),
        tf.keras.layers.LSTM(5, return_sequences=False),
        tf.keras.layers.Dense(3, activation="softmax"),
    ])
    f = tf.function(lambda x: m(x),
                    input_signature=[tf.TensorSpec((1, 8, 6), tf.float32)])
    frozen = convert_variables_to_constants_v2(f.get_concrete_function())
    c2 = tf.lite.TFLiteConverter.from_concrete_functions([frozen], m)
    lstm = d / "lstm.tflite"
    lstm.write_bytes(c2.convert())
    return {"detect": str(det), "lstm": str(lstm), "anchors": anch,
            "N": N, "C": C}


def _detection_case(N, C, n_objects, seed):
    rng = np.random.default_rng(seed)
    be = rng.normal(0, 0.5, (1, N, 4)).astype(np.float32)
    sc = rng.uniform(0, 0.25, (1, N, C + 1)).astype(np.float32)
    for i in rng.choice(N, n_objects, replace=False):
        sc[0, i, rng.integers(1, C + 1)] = rng.uniform(0.6, 0.99)
    return be, sc


def test_detection_postprocess_custom_op_golden(built_models):
    """Importer vs interpreter on the custom-op model: identical
    detections (count, boxes, classes, scores)."""
    tf = pytest.importorskip("tensorflow")
    import jax

    m = load_model_file(built_models["detect"], compute_dtype="float32")
    interp = tf.lite.Interpreter(model_path=built_models["detect"])
    interp.allocate_tensors()
    ids = interp.get_input_details()
    ods = interp.get_output_details()
    fn = jax.jit(m.fn)
    for trial in range(4):
        be, sc = _detection_case(built_models["N"], built_models["C"],
                                 6, 10 + trial)
        interp.set_tensor(ids[0]["index"], be)
        interp.set_tensor(ids[1]["index"], sc)
        interp.invoke()
        ref = [interp.get_tensor(dd["index"]) for dd in ods]
        ours = [np.asarray(t) for t in fn(m.params, be, sc)]
        nd = int(ref[3][0])
        assert int(ours[3][0]) == nd
        np.testing.assert_allclose(ours[0][0][:nd], ref[0][0][:nd],
                                   atol=1e-4)
        np.testing.assert_array_equal(ours[1][0][:nd], ref[1][0][:nd])
        np.testing.assert_allclose(ours[2][0][:nd], ref[2][0][:nd],
                                   atol=1e-5)


def test_lstm_while_loop_golden(built_models):
    """Control-flow TFLite (WHILE + cond/body subgraphs + GATHER/SPLIT/
    STRIDED_SLICE) matches the interpreter."""
    tf = pytest.importorskip("tensorflow")
    import jax

    m = load_model_file(built_models["lstm"], compute_dtype="float32")
    g = parse_tflite(built_models["lstm"])
    assert len(g.subgraphs) == 3          # main + while cond + body
    interp = tf.lite.Interpreter(model_path=built_models["lstm"])
    interp.allocate_tensors()
    x = np.random.default_rng(5).normal(0, 1, (1, 8, 6)).astype(np.float32)
    interp.set_tensor(interp.get_input_details()[0]["index"], x)
    interp.invoke()
    ref = interp.get_tensor(interp.get_output_details()[0]["index"])
    ours = np.asarray(jax.jit(m.fn)(m.params, x)[0])
    np.testing.assert_allclose(ours, ref, atol=1e-5)


def test_detect_decode_pipeline_correct_boxes(built_models):
    """Real detect→decode pipeline: the custom-op model's detections
    flow through tensor_decoder mode=bounding_boxes (postprocess
    scheme) and come out as the same boxes the interpreter finds."""
    tf = pytest.importorskip("tensorflow")
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    N, C = built_models["N"], built_models["C"]
    be, sc = _detection_case(N, C, 5, 99)
    pipe = nns.parse_launch(
        f"appsrc name=src dims=4:{N}:1,{C + 1}:{N}:1 "
        f"types=float32,float32 ! "
        f"tensor_filter model={built_models['detect']} "
        f"custom=dtype=float32 ! "
        f"tensor_decoder mode=bounding_boxes "
        f"option1=mobilenet-ssd-postprocess option3=0.5:0.5 "
        f"option4=200:200 ! tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    pipe.get("src").push(TensorBuffer.of(be, sc))
    pipe.get("src").end()
    runner.wait(120)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 1
    got = res[0].meta["boxes"]            # (K,6) output-pixel coords

    interp = tf.lite.Interpreter(model_path=built_models["detect"])
    interp.allocate_tensors()
    ids = interp.get_input_details()
    ods = interp.get_output_details()
    interp.set_tensor(ids[0]["index"], be)
    interp.set_tensor(ids[1]["index"], sc)
    interp.invoke()
    rb = interp.get_tensor(ods[0]["index"])[0]
    rs = interp.get_tensor(ods[2]["index"])[0]
    nd = int(interp.get_tensor(ods[3]["index"])[0])
    keep = rs[:nd] >= 0.5
    exp = rb[:nd][keep] * 200.0           # expected pixel boxes
    assert len(got) == keep.sum()
    np.testing.assert_allclose(
        np.sort(got[:, :4], axis=0), np.sort(exp, axis=0), atol=0.05)


def test_custom_op_unregistered_fails_loudly(built_models, tmp_path):
    from nnstreamer_tpu.modelio.tflite import TFLITE_CUSTOM_OPS

    saved = TFLITE_CUSTOM_OPS.pop("TFLite_Detection_PostProcess")
    try:
        import jax

        m = load_model_file(built_models["detect"],
                            compute_dtype="float32")
        be, sc = _detection_case(built_models["N"], built_models["C"],
                                 2, 1)
        with pytest.raises(BackendError, match="no registered lowering"):
            jax.eval_shape(m.fn, m.params, be, sc)
    finally:
        TFLITE_CUSTOM_OPS["TFLite_Detection_PostProcess"] = saved


# -- caffe2 NetDef pair ingestion (caffe2.py) --------------------------------

C2_INIT = os.path.join(MODELS, "caffe2_init_net.pb")
C2_PRED = os.path.join(MODELS, "caffe2_predict_net.pb")
C2_DATA = "/root/reference/tests/test_models/data/5"


@needs_models
def test_caffe2_pair_classifies_reference_sample():
    """The reference's own CIFAR ResNet pair classifies its own data/5
    sample as label 5 — the exact expectation its checkLabel.py
    asserts (tests/nnstreamer_filter_caffe2/runTest.sh)."""
    import jax

    m = load_model_file(f"{C2_INIT},{C2_PRED}")
    assert m.in_spec.tensors[0].shape == (1, 3, 32, 32)
    assert m.out_spec.tensors[0].shape == (1, 10)
    raw = np.fromfile(C2_DATA, np.float32).reshape(1, 3, 32, 32)
    y = np.asarray(jax.jit(m.fn)(m.params, raw)[0])
    assert int(y.argmax()) == 5
    assert y[0, 5] > 0.5


@needs_models
def test_caffe2_pipeline_reference_shape():
    """Pipeline parity with the reference test: octet data → converter →
    caffe2 pair filter → label 5 out."""
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    pipe = nns.parse_launch(
        f"appsrc name=src dims=32:32:3:1 types=float32 ! "
        f"tensor_filter model={C2_INIT},{C2_PRED} ! "
        f"tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    raw = np.fromfile(C2_DATA, np.float32).reshape(1, 3, 32, 32)
    pipe.get("src").push(TensorBuffer.of(raw))
    pipe.get("src").end()
    runner.wait(120)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 1
    assert int(np.asarray(res[0].tensors[0]).argmax()) == 5


def test_caffe2_pair_errors():
    with pytest.raises(BackendError, match="exactly"):
        load_model_file("a.pb,b.pb,c.pb")
    with pytest.raises(BackendError, match="does not exist"):
        load_model_file("/nope/i.pb,/nope/p.pb")


@needs_models
def test_singleshot_runs_all_ingestion_formats():
    """SingleShot (the pipeline-less C-API analog) reaches every
    model-file route through the same backend resolver."""
    from nnstreamer_tpu.single import SingleShot

    # TF GraphDef
    s1 = SingleShot(model=MNIST_PB)
    raw = np.fromfile(NINE_RAW, np.uint8).astype(np.float32)
    (y1,) = s1.invoke(((raw - 127.5) / 127.5).reshape(1, 784))
    assert int(np.asarray(y1).argmax()) == 9
    s1.close()

    # caffe2 pair
    s2 = SingleShot(model=f"{C2_INIT},{C2_PRED}")
    x = np.fromfile(C2_DATA, np.float32).reshape(1, 3, 32, 32)
    (y2,) = s2.invoke(x)
    assert int(np.asarray(y2).argmax()) == 5
    s2.close()

    # int8-native TFLite
    s3 = SingleShot(model=MOBILENET, custom="dtype=int8")
    img = next(iter(_synthetic_images(1)))
    (y3,) = s3.invoke(img)
    assert np.asarray(y3).shape == (1, 1001)
    s3.close()

    # SNPE DLC (add2 golden: y = x + 2)
    s4 = SingleShot(model=os.path.join(MODELS, "add2_float.dlc"))
    (y4,) = s4.invoke(np.asarray([10.0], np.float32))
    assert float(np.asarray(y4)[0]) == 12.0
    s4.close()


# -- converter-built op-breadth goldens --------------------------------------

def _convert_fn(tf, fn, sig, path):
    f = tf.function(fn, input_signature=sig)
    c = tf.lite.TFLiteConverter.from_concrete_functions(
        [f.get_concrete_function()])
    path.write_bytes(c.convert())
    return str(path)


def _golden_vs_interpreter(tf, path, *xs, atol=1e-4):
    import jax

    m = load_model_file(path, compute_dtype="float32")
    interp = tf.lite.Interpreter(model_path=path)
    interp.allocate_tensors()
    for d, x in zip(interp.get_input_details(), xs):
        interp.set_tensor(d["index"], x)
    interp.invoke()
    refs = [interp.get_tensor(d["index"])
            for d in interp.get_output_details()]
    ours = [np.asarray(t) for t in jax.jit(m.fn)(m.params, *xs)]
    assert len(refs) == len(ours)
    for r, o in zip(refs, ours):
        np.testing.assert_allclose(o, r, atol=atol, rtol=1e-4)


def test_tflite_elementwise_reduce_select_breadth(tmp_path):
    """~20 builtins in one converter-built graph (EXP/LOG/SQRT/RSQRT/
    POW/SQUARED_DIFFERENCE/FLOOR/CEIL/NEG/SIN/COS/ELU/GELU/SELECT/
    REDUCE_MAX/MIN/PROD/ARG_MIN/CAST/TILE/MIRROR_PAD) — golden vs the
    interpreter."""
    tf = pytest.importorskip("tensorflow")

    def sink(x):
        a = tf.exp(x) + tf.math.log(tf.abs(x) + 1.0)
        b = tf.sqrt(tf.abs(x)) * tf.math.rsqrt(tf.abs(x) + 1.0)
        c = tf.pow(x, 3.0) - tf.math.squared_difference(x, 2.0)
        d = tf.floor(x) + tf.math.ceil(x) - (-x)
        e = tf.sin(x) + tf.cos(x) + tf.nn.elu(x) + tf.nn.gelu(x)
        f = tf.where(x > 0, a, b)
        g = tf.reduce_max(c, axis=1, keepdims=True) \
            + tf.reduce_min(d, axis=1, keepdims=True)
        h = tf.reduce_prod(tf.clip_by_value(x, 0.5, 1.5), axis=1,
                           keepdims=True)
        i = tf.cast(tf.argmin(x, axis=1), tf.float32)
        j = tf.tile(g + h, [1, 8])
        k = tf.pad(e, [[0, 0], [2, 2]], mode="REFLECT")
        return f + j, k, i

    path = _convert_fn(tf, sink, [tf.TensorSpec((2, 8), tf.float32)],
                       tmp_path / "sink1.tflite")
    x = np.random.default_rng(0).normal(0, 1, (2, 8)).astype(np.float32)
    _golden_vs_interpreter(tf, path, x)


def test_tflite_spatial_breadth(tmp_path):
    """DEPTH_TO_SPACE/SPACE_TO_DEPTH/L2_NORMALIZATION/UNPACK/
    TRANSPOSE_CONV — golden vs the interpreter (transpose conv is built
    as the VJP of the forward conv, exact by construction)."""
    tf = pytest.importorskip("tensorflow")
    rng = np.random.default_rng(0)
    w = tf.constant(rng.normal(0, 0.3, (2, 2, 3, 8)).astype(np.float32))

    def sink(x):
        a = tf.nn.depth_to_space(x, 2)
        b = tf.nn.space_to_depth(a, 2)
        c = tf.math.l2_normalize(b, axis=-1)
        parts = tf.unstack(c, axis=3)
        d = tf.nn.conv2d_transpose(c, w, [1, 8, 8, 3], [1, 2, 2, 1])
        return d, parts[0] + parts[1]

    path = _convert_fn(tf, sink,
                       [tf.TensorSpec((1, 4, 4, 8), tf.float32)],
                       tmp_path / "sink2.tflite")
    x = rng.normal(0, 1, (1, 4, 4, 8)).astype(np.float32)
    _golden_vs_interpreter(tf, path, x)


@needs_models
def test_pipeline_classifies_reference_orange_sample():
    """Real-image semantic parity: the reference's own orange.raw
    through the full pipeline (filter + image_labeling decoder with its
    labels file) yields label 951 'orange' — the exact expectation of
    the reference's tflite checkLabel tests."""
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    orange = "/root/reference/tests/test_models/data/orange.raw"
    if not os.path.exists(orange):
        pytest.skip("orange.raw absent")
    pipe = nns.parse_launch(
        f"appsrc name=src dims=3:224:224:1 types=uint8 ! "
        f"tensor_filter model={MOBILENET} ! "
        f"tensor_decoder mode=image_labeling option1={LABELS} ! "
        f"tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    raw = np.fromfile(orange, np.uint8).reshape(1, 224, 224, 3)
    pipe.get("src").push(TensorBuffer.of(raw))
    pipe.get("src").end()
    runner.wait(120)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 1
    assert res[0].meta["label"] == "orange"
    assert res[0].meta["label_index"] == 951


# -- real-weights pose golden (VERDICT r3 missing #3) ------------------------

@pytest.fixture(scope="module")
def pose_model(tmp_path_factory):
    """Tiny converter-built PoseNet-style head: image → conv backbone →
    (1,8,8,K) sigmoid heatmaps + (1,8,8,2K) linear offsets."""
    tf = pytest.importorskip("tensorflow")
    d = tmp_path_factory.mktemp("pose_tflite")
    K = 5
    rng_init = tf.keras.initializers.RandomNormal(stddev=0.15, seed=11)
    inp = tf.keras.Input((64, 64, 3), batch_size=1)
    x = tf.keras.layers.Conv2D(8, 3, strides=4, padding="same",
                               activation="relu",
                               kernel_initializer=rng_init)(inp)
    x = tf.keras.layers.Conv2D(16, 3, strides=2, padding="same",
                               activation="relu",
                               kernel_initializer=rng_init)(x)
    hm = tf.keras.layers.Conv2D(K, 1, activation="sigmoid",
                                kernel_initializer=rng_init,
                                name="heatmaps")(x)
    off = tf.keras.layers.Conv2D(2 * K, 1,
                                 kernel_initializer=rng_init,
                                 name="offsets")(x)
    model = tf.keras.Model(inp, [hm, off])
    path = str(d / "pose.tflite")
    open(path, "wb").write(_convert_frozen(tf, model, (1, 64, 64, 3)))
    return {"path": path, "K": K}


def _convert_frozen(tf, model, in_shape):
    """keras → frozen-consts concrete function → tflite (the conversion
    path whose blobs the stock interpreter executes correctly; plain
    from_keras_model leaves resource-variable captures that the
    interpreter resolves to zeros in this TF build)."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    f = tf.function(lambda x: model(x),
                    input_signature=[tf.TensorSpec(in_shape, tf.float32)])
    frozen = convert_variables_to_constants_v2(f.get_concrete_function())
    conv = tf.lite.TFLiteConverter.from_concrete_functions([frozen], model)
    return conv.convert()


def _pose_reference_decode(hm, off, in_px=64, out_px=64):
    """Independent numpy PoseNet decode (tensordec-pose.c:845 rule):
    per-channel heatmap argmax + short-range offset refinement, written
    from the spec — NOT the decoder under test."""
    h, w, k = hm.shape
    flat = hm.reshape(-1, k)
    idx = flat.argmax(0)
    ys, xs = np.unravel_index(idx, (h, w))
    score = flat[idx, np.arange(k)]
    fy = (ys + 0.5) / h + off[ys, xs, np.arange(k)] / in_px
    fx = (xs + 0.5) / w + off[ys, xs, k + np.arange(k)] / in_px
    return np.stack([fx * out_px, fy * out_px, score], axis=1)


@pytest.mark.parametrize("device", [False, True])
def test_pose_pipeline_real_weights_golden(pose_model, device):
    """Real-weights pose golden: the converter-built model runs through
    tensor_filter → tensor_decoder mode=pose_estimation (host AND
    device variants) and the keypoints match an independent decode of
    the tf.lite.Interpreter's own outputs."""
    tf = pytest.importorskip("tensorflow")
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    x = np.random.default_rng(21).normal(
        0, 1, (1, 64, 64, 3)).astype(np.float32)

    # BUILTIN_WITHOUT_DEFAULT_DELEGATES: this TF build's XNNPACK
    # delegate miscomputes the strided-conv chain (returns bias-only
    # outputs); the plain builtin kernels match keras execution
    interp = tf.lite.Interpreter(
        model_path=pose_model["path"],
        experimental_op_resolver_type=tf.lite.experimental
        .OpResolverType.BUILTIN_WITHOUT_DEFAULT_DELEGATES)
    interp.allocate_tensors()
    interp.set_tensor(interp.get_input_details()[0]["index"], x)
    interp.invoke()
    outs = {tuple(o["shape"]): interp.get_tensor(o["index"])
            for o in interp.get_output_details()}
    K = pose_model["K"]
    hm = outs[(1, 8, 8, K)][0]
    off = outs[(1, 8, 8, 2 * K)][0]
    exp = _pose_reference_decode(hm, off)

    # the converter serializes its own output order; the decoder wants
    # (heatmaps, offsets) — reorder with the reference's
    # output-combination property when needed
    first = tuple(interp.get_output_details()[0]["shape"])
    combo = "" if first == (1, 8, 8, K) else "output_combination=o1,o0 "
    dev = "device=true " if device else ""
    pipe = nns.parse_launch(
        f"appsrc name=src dims=3:64:64:1 types=float32 ! "
        f"tensor_filter model={pose_model['path']} "
        f"custom=dtype=float32 {combo}! "
        f"tensor_decoder mode=pose_estimation {dev}option1=64:64 "
        f"option2=64:64 option4=0.0 ! tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    pipe.get("src").push(TensorBuffer.of(x))
    pipe.get("src").end()
    runner.wait(120)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 1
    got = (np.asarray(res[0].tensors[0]) if device
           else res[0].meta["keypoints"])
    assert got.shape == (K, 3)
    np.testing.assert_allclose(got, exp, atol=0.05)


# -- mobilenet-ssd anchors-scheme golden (VERDICT r3 missing/weak #6) --------

@pytest.fixture(scope="module")
def raw_ssd_model(tmp_path_factory):
    """Converter-built raw-grid SSD head: image → conv → dense →
    (1,1917,4) box deltas + (1,1917,5) class logits — the layout the
    `mobilenet-ssd` scheme decodes with in-code anchors + NMS."""
    tf = pytest.importorskip("tensorflow")
    from nnstreamer_tpu.models.ssd_mobilenet import generate_anchors

    A = int(generate_anchors().shape[0])       # 1917
    C = 5
    d = tmp_path_factory.mktemp("rawssd_tflite")
    init = tf.keras.initializers.RandomNormal(stddev=0.05, seed=13)
    inp = tf.keras.Input((8, 8, 3), batch_size=1)
    x = tf.keras.layers.Conv2D(4, 3, padding="same", activation="relu",
                               kernel_initializer=init)(inp)
    x = tf.keras.layers.Flatten()(x)
    loc = tf.keras.layers.Reshape((A, 4))(
        tf.keras.layers.Dense(A * 4, kernel_initializer=init)(x))
    logits = tf.keras.layers.Reshape((A, C))(
        tf.keras.layers.Dense(A * C, kernel_initializer=init)(x))
    model = tf.keras.Model(inp, [loc, logits])
    path = str(d / "raw_ssd.tflite")
    open(path, "wb").write(_convert_frozen(tf, model, (1, 8, 8, 3)))
    return {"path": path, "A": A, "C": C}


def _ssd_reference_decode(loc, logits, anchors, score_thresh, iou_thresh,
                          out_px):
    """Independent numpy mobilenet-ssd decode, written from the
    reference's box-prior spec (tensordec-boundingbox.c:143-158):
    sigmoid scores, skip background class 0, box-coder (10,10,5,5)
    decode against [cy,cx,h,w] priors, global greedy NMS."""
    sc = 1.0 / (1.0 + np.exp(-logits))
    cls = sc[:, 1:].argmax(-1) + 1
    score = sc[np.arange(len(cls)), cls]
    keep = score >= score_thresh
    loc, cls, score, anchors = (loc[keep], cls[keep], score[keep],
                                anchors[keep])
    cy = loc[:, 0] / 10.0 * anchors[:, 2] + anchors[:, 0]
    cx = loc[:, 1] / 10.0 * anchors[:, 3] + anchors[:, 1]
    h = anchors[:, 2] * np.exp(loc[:, 2] / 5.0)
    w = anchors[:, 3] * np.exp(loc[:, 3] / 5.0)
    boxes = np.stack([cy - h / 2, cx - w / 2, cy + h / 2, cx + w / 2], 1)
    # greedy NMS, independent re-implementation
    order = np.argsort(-score)
    chosen = []
    for i in order:
        ok = True
        for j in chosen:
            y0 = max(boxes[i, 0], boxes[j, 0])
            x0 = max(boxes[i, 1], boxes[j, 1])
            y1 = min(boxes[i, 2], boxes[j, 2])
            x1 = min(boxes[i, 3], boxes[j, 3])
            inter = max(0.0, y1 - y0) * max(0.0, x1 - x0)
            ai = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
            aj = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
            union = ai + aj - inter
            if union > 0 and inter / union > iou_thresh:
                ok = False
                break
        if ok:
            chosen.append(i)
    det = np.concatenate(
        [boxes[chosen], score[chosen, None],
         cls[chosen, None].astype(np.float32)], axis=1)
    det[:, [0, 2]] *= out_px
    det[:, [1, 3]] *= out_px
    return det


@pytest.mark.parametrize("compact", [False, True])
def test_raw_ssd_anchors_scheme_golden(raw_ssd_model, compact):
    """The anchors path of scheme=mobilenet-ssd (raw loc+score grids +
    generated priors + decoder NMS) against an independent numpy decode
    of the interpreter's outputs — round 3 only goldened the
    postprocess scheme. Also checks device=compact parity."""
    tf = pytest.importorskip("tensorflow")
    import nnstreamer_tpu as nns
    from nnstreamer_tpu.models.ssd_mobilenet import generate_anchors
    from nnstreamer_tpu.tensor.buffer import TensorBuffer

    x = np.random.default_rng(31).normal(
        0, 1, (1, 8, 8, 3)).astype(np.float32)
    interp = tf.lite.Interpreter(
        model_path=raw_ssd_model["path"],
        experimental_op_resolver_type=tf.lite.experimental
        .OpResolverType.BUILTIN_WITHOUT_DEFAULT_DELEGATES)
    interp.allocate_tensors()
    interp.set_tensor(interp.get_input_details()[0]["index"], x)
    interp.invoke()
    A, C = raw_ssd_model["A"], raw_ssd_model["C"]
    outs = {tuple(o["shape"]): interp.get_tensor(o["index"])
            for o in interp.get_output_details()}
    loc = outs[(1, A, 4)][0]
    logits = outs[(1, A, C)][0]
    exp = _ssd_reference_decode(loc, logits, generate_anchors(),
                                score_thresh=0.6, iou_thresh=0.5,
                                out_px=300)
    assert len(exp) >= 3          # the golden must actually exercise NMS

    first = tuple(interp.get_output_details()[0]["shape"])
    combo = "" if first == (1, A, 4) else "output_combination=o1,o0 "
    dev = "device=compact " if compact else ""
    pipe = nns.parse_launch(
        f"appsrc name=src dims=3:8:8:1 types=float32 ! "
        f"tensor_filter model={raw_ssd_model['path']} "
        f"custom=dtype=float32 {combo}! "
        f"tensor_decoder mode=bounding_boxes {dev}option1=mobilenet-ssd "
        f"option3=0.6:0.5 option4=300:300 ! tensor_sink name=out")
    runner = nns.PipelineRunner(pipe).start()
    pipe.get("src").push(TensorBuffer.of(x))
    pipe.get("src").end()
    runner.wait(120)
    runner.stop()
    res = pipe.get("out").results
    assert len(res) == 1
    got = res[0].meta["boxes"]
    assert got.shape == exp.shape
    order_g = np.argsort(-got[:, 4])
    order_e = np.argsort(-exp[:, 4])
    np.testing.assert_allclose(got[order_g], exp[order_e], atol=0.1)
