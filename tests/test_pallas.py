"""Pallas kernel + backend tests (interpret mode on the CPU mesh)."""

import jax.numpy as jnp
import numpy as np
import pytest

import nnstreamer_tpu as nns
from nnstreamer_tpu.backends.pallas_backend import register_pallas_filter
from nnstreamer_tpu.elements import AppSrc, TensorFilter, TensorSink
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec


def test_normalize_u8_kernel_matches_numpy():
    from nnstreamer_tpu.backends.pallas_ops import normalize_u8

    x = np.arange(256, dtype=np.uint8).reshape(2, 128)
    out = np.asarray(normalize_u8(x))
    ref = (x.astype(np.float32) - 127.5) / 127.5
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_clamp_scale_kernel():
    from nnstreamer_tpu.backends.pallas_ops import clamp_scale

    x = np.linspace(-4, 4, 256, dtype=np.float32).reshape(2, 128)
    out = np.asarray(clamp_scale(x, -1.0, 1.0, scale=2.0, offset=1.0))
    ref = np.clip(x, -1, 1) * 2 + 1
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_sparse_to_dense_device_scatter():
    from nnstreamer_tpu.backends.pallas_ops import sparse_to_dense
    import jax.numpy as jnp

    vals = jnp.array([5.0, -2.0])
    idx = jnp.array([1, 6])
    dense = np.asarray(sparse_to_dense(vals, idx, (2, 4)))
    ref = np.zeros((2, 4), np.float32)
    ref[0, 1], ref[1, 2] = 5.0, -2.0
    np.testing.assert_array_equal(dense, ref)


def test_pallas_backend_in_pipeline():
    spec = TensorsSpec.of(TensorInfo((2, 128), DType.UINT8))
    src = AppSrc(spec=spec, name="src")
    f = TensorFilter(name="f", framework="pallas", model="normalize_u8")
    sink = TensorSink(name="s")
    pipe = nns.Pipeline()
    for e in (src, f, sink):
        pipe.add(e)
    pipe.link(src, f)
    pipe.link(f, sink)
    assert f is pipe.get("f")
    runner = nns.PipelineRunner(pipe).start()
    x = np.full((2, 128), 255, np.uint8)
    src.push(TensorBuffer.of(x, pts=0))
    src.end()
    runner.wait(60)
    out = sink.results[0].tensors[0]
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, np.full((2, 128), 1.0), rtol=1e-6)


def test_pallas_backend_custom_registration():
    @register_pallas_filter("double_it")
    def double_it(ts):
        return tuple(t * 2 for t in ts)

    spec = TensorsSpec.of(TensorInfo((4,), DType.FLOAT32))
    src = AppSrc(spec=spec, name="src")
    f = TensorFilter(name="f", framework="pallas", model="double_it")
    sink = TensorSink(name="s")
    pipe = nns.Pipeline()
    for e in (src, f, sink):
        pipe.add(e)
    pipe.link(src, f)
    pipe.link(f, sink)
    runner = nns.PipelineRunner(pipe).start()
    src.push(TensorBuffer.of(np.full((4,), 3.0, np.float32), pts=0))
    src.end()
    runner.wait(30)
    np.testing.assert_array_equal(sink.results[0].tensors[0],
                                  np.full((4,), 6.0))


def test_pallas_backend_unknown_kernel_actionable_error():
    spec = TensorsSpec.of(TensorInfo((4,), DType.FLOAT32))
    src = AppSrc(spec=spec, name="src")
    f = TensorFilter(name="f", framework="pallas", model="nope")
    sink = TensorSink(name="s")
    pipe = nns.Pipeline()
    for e in (src, f, sink):
        pipe.add(e)
    pipe.link(src, f)
    pipe.link(f, sink)
    with pytest.raises(Exception, match="register_pallas_filter"):
        pipe.negotiate()


class TestFlashAttention:
    def _qkv(self, B=2, S=64, H=2, D=16, seed=0):
        import jax

        key = jax.random.PRNGKey(seed)
        return tuple(jax.random.normal(kk, (B, S, H, D), jnp.float32)
                     for kk in jax.random.split(key, 3))

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        from nnstreamer_tpu.backends.pallas_ops import flash_attention
        from nnstreamer_tpu.parallel.ring_attention import reference_attention

        q, k, v = self._qkv()
        got = flash_attention(q, k, v, causal=causal,
                              block_q=32, block_k=32)
        want = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)

    def test_causal_split_loop_path_matches_reference(self):
        """n_kb >= 8 engages the two-loop causal body (unmasked full
        blocks + masked diagonal blocks); its block-boundary arithmetic
        must match the single-loop reference bit-for-bit — an off-by-one
        in `full` would silently attend above the diagonal at long S."""
        from nnstreamer_tpu.backends.pallas_ops import flash_attention
        from nnstreamer_tpu.parallel.ring_attention import reference_attention

        q, k, v = self._qkv(S=64)
        got = flash_attention(q, k, v, causal=True,
                              block_q=16, block_k=8)      # n_kb = 8
        want = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)

    def test_causal_misaligned_boundary_block_q_lt_block_k(self):
        """block_q < block_k makes the diagonal cut THROUGH k-blocks at
        q-block granularity: with S=128, bq=8, bk=16 (n_kb = 8, split
        loop engaged) every odd q-block's diagonal lands mid-k-block, so
        `full = (qi*bq)//bk` must floor — rounding up would count the
        half-covered diagonal block as fully below the diagonal and
        attend to future positions."""
        from nnstreamer_tpu.backends.pallas_ops import flash_attention
        from nnstreamer_tpu.parallel.ring_attention import reference_attention

        q, k, v = self._qkv(S=128)
        got = flash_attention(q, k, v, causal=True,
                              block_q=8, block_k=16)
        want = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)

    def test_uneven_blocks_rejected(self):
        from nnstreamer_tpu.backends.pallas_ops import flash_attention

        q, k, v = self._qkv(S=48)
        with pytest.raises(ValueError, match="divisible"):
            flash_attention(q, k, v, block_q=32, block_k=32)

    def test_transformer_pallas_attn_matches_xla(self):
        import jax

        from nnstreamer_tpu.models import transformer as T

        params = T.init_params(d_model=32, n_heads=2, n_layers=2, vocab=64)
        ids = jax.numpy.asarray(
            np.random.default_rng(0).integers(0, 64, (1, 128)), jnp.int32)
        want = np.asarray(T.apply_seq(params, ids, n_heads=2, attn="xla"))
        got = np.asarray(T.apply_seq(params, ids, n_heads=2, attn="pallas"))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_flash_plan_block_defaults():
    """Pin the per-path auto-block defaults the round-5 quiet-chip sweep
    landed on (flash_attention docstring): 512^2 while K/V fit VMEM,
    1024^2 on the K-blocked streaming grid, explicit blocks override,
    and odd lengths fall to the largest dividing power of two."""
    from nnstreamer_tpu.backends.pallas_ops import _flash_plan

    # bf16 (itemsize 2), D=128: resident until 2*S*128*2 > 8MiB (S=16k)
    assert _flash_plan(2048, 128, 2) == (False, 512, 512)
    assert _flash_plan(8192, 128, 2) == (False, 512, 512)
    assert _flash_plan(32768, 128, 2) == (True, 1024, 1024)
    # explicit blocks override the per-path defaults on both paths
    assert _flash_plan(2048, 128, 2, 256, 1024) == (False, 256, 1024)
    assert _flash_plan(32768, 128, 2, 512, 512) == (True, 512, 512)
    # non-power-of-two-divisible lengths shrink to a dividing block
    assert _flash_plan(24576, 128, 2)[1:] == (1024, 1024)   # 24k % 1024 == 0
    assert _flash_plan(1536, 128, 2)[1:] == (512, 512)
    assert _flash_plan(640, 128, 2)[1:] == (128, 128)  # 640 = 5 * 128
    assert _flash_plan(96, 128, 2)[1:] == (96, 96)     # S <= want: one block
    # wider heads cross the VMEM budget earlier
    assert _flash_plan(8192, 128, 4)[0] is False            # fp32, 8MiB
    assert _flash_plan(16384, 128, 4)[0] is True


def test_flash_attention_kgrid_long_context_path(monkeypatch):
    """The K-blocked streaming path (engaged when a head's K/V exceeds
    the VMEM budget — S>=32k on the real chip) matches the reference;
    forced here via a tiny budget so it runs in interpret mode."""
    import jax
    import jax.numpy as jnp

    from nnstreamer_tpu.backends import pallas_ops
    from nnstreamer_tpu.parallel.ring_attention import reference_attention

    monkeypatch.setattr(pallas_ops, "_FLASH_VMEM_KV_BYTES", 1)
    B, S, H, D = 2, 64, 2, 8
    key = jax.random.PRNGKey(3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    for causal in (True, False):
        out = pallas_ops.flash_attention(q, k, v, causal=causal,
                                         block_q=16, block_k=16)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
