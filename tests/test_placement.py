"""Multi-chip device placement (serving/placement.py, ISSUE 14).

Covers the three placement surfaces on the 8-device emulated host mesh
(marker `multichip`, fixture `eight_cpu_devices`):

- data-parallel replicas: bit-parity with the single-device path at
  devices=1/2/4/8, least-outstanding spread, exact invoke conservation
  across a chaos fence, store-integrated epoch-atomic hot swap with
  zero post-flip recompiles, and the tensor_filter `devices=` property
  (routing, stats, soft declines);
- profiled segmentation: the linear-partition DP, tracer-profiled
  plans, plan-aware fuse_segments cuts, and end-to-end parity of a
  segmented pipeline vs the unsegmented one;
- chip leases: the supervisor-side ChipLeaseTable (fence + re-lease
  preference), WorkerPool chip partitioning across slots, and the
  ScalingController's chip-weighted capacity math;

plus the metrics plane: replica/segment series survive render → parse
with Σ per-chip invokes equal to the filter's invoke count.
"""

import itertools
import time

import numpy as np
import pytest

from nnstreamer_tpu import PipelineRunner, TensorBuffer, parse_launch
from nnstreamer_tpu.backends.xla import ModelBundle
from nnstreamer_tpu.core.errors import BackendError, StreamError
from nnstreamer_tpu.edge.query import QueryServer
from nnstreamer_tpu.graph.optimize import fuse_segments
from nnstreamer_tpu.serving import compile_cache
from nnstreamer_tpu.serving.metrics import (
    metrics_snapshot, parse_prometheus, render_prometheus, top_table)
from nnstreamer_tpu.serving.placement import (
    ChipLeaseTable, ReplicaSet, accelerator_for, apply_plan, device_of,
    plan_from_tracer, segment_plan, visible_devices)
from nnstreamer_tpu.serving.pool import PooledQueryServer, WorkerPool
from nnstreamer_tpu.serving.store import reset_store
from nnstreamer_tpu.serving.tenancy import ScalingController, TenantTable
from nnstreamer_tpu.serving.worker import WorkerSpec

pytestmark = pytest.mark.multichip

_sid = itertools.count(9000)


@pytest.fixture(autouse=True)
def _fresh_store():
    store = reset_store()
    compile_cache.reset()
    yield store
    reset_store()
    compile_cache.reset()
    QueryServer.reset_all()


def _bundle(seed=3, dim=16):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(dim, dim)).astype(np.float32)

    def fn(params, x):
        return (x @ params["w"],)

    return ModelBundle(fn=fn, params={"w": w}, name="plc_mlp"), dim


def _open(n, bundle, **kw):
    return ReplicaSet.open("xla", {"model": bundle, "custom": ""}, n,
                           name=f"rs{n}", **kw)


# -- device enumeration -------------------------------------------------------

class TestDevices:
    def test_emulated_mesh_visible(self, eight_cpu_devices):
        assert len(visible_devices()) >= 8

    def test_accelerator_for_pins_platform_and_ordinal(
            self, eight_cpu_devices):
        assert accelerator_for(3) == f"{device_of(3).platform}:3"

    def test_out_of_range_is_typed(self, eight_cpu_devices):
        with pytest.raises(BackendError, match="out of range"):
            device_of(10_000)


# -- data-parallel replicas ---------------------------------------------------

class TestReplicaSet:
    def test_bit_parity_across_device_counts(self, eight_cpu_devices):
        """The acceptance check: devices=1/2/4/8 produce bit-identical
        outputs — each replica IS the single-device program, placed
        elsewhere."""
        bundle, dim = _bundle()
        x = np.linspace(-1, 1, 4 * dim,
                        dtype=np.float32).reshape(4, dim)
        ref = None
        for n in (1, 2, 4, 8):
            rs = _open(n, bundle)
            try:
                outs = [rs.invoke((x,)) for _ in range(2 * n)]
            finally:
                rs.close()
            if ref is None:
                ref = np.asarray(outs[0][0])
            for o in outs:
                np.testing.assert_array_equal(np.asarray(o[0]), ref)

    def test_round_robin_spreads_idle_load(self, eight_cpu_devices):
        bundle, dim = _bundle()
        x = np.ones((1, dim), np.float32)
        rs = _open(4, bundle)
        try:
            for _ in range(12):
                rs.invoke((x,))
            st = rs.stats()
        finally:
            rs.close()
        assert [r["invokes"] for r in st["replicas"]] == [3, 3, 3, 3]
        assert st["routed"] == 12 and st["live"] == 4

    def test_fence_conserves_invokes_exactly(self, eight_cpu_devices):
        """Σ replica invokes == frames served, exactly, through a chip
        loss — the fenced replica stops, survivors absorb the rest."""
        bundle, dim = _bundle()
        x = np.ones((1, dim), np.float32)
        rs = _open(4, bundle)
        try:
            for _ in range(4):
                rs.invoke((x,))
            assert rs.fence(0, "test chaos")
            assert not rs.fence(0, "twice")   # idempotent
            for _ in range(6):
                rs.invoke((x,))
            st = rs.stats()
        finally:
            rs.close()
        assert sum(r["invokes"] for r in st["replicas"]) == 10
        assert st["live"] == 3 and rs.live_replicas() == 3
        dead = next(r for r in st["replicas"] if r["device"] == 0)
        assert dead["state"] == "fenced" and not dead["up"]
        # nothing routed to the fenced chip after the fence
        assert dead["invokes"] == 1

    def test_all_fenced_rejects_typed(self, eight_cpu_devices):
        bundle, dim = _bundle()
        rs = _open(2, bundle)
        try:
            rs.fence(0)
            rs.fence(1)
            fut = rs.submit((np.ones((1, dim), np.float32),))
            with pytest.raises(StreamError, match="no live replica"):
                fut.result(5.0)
            assert rs.stats()["rejected"] == 1
        finally:
            rs.close()

    def test_too_many_devices_is_typed(self, eight_cpu_devices):
        bundle, _ = _bundle()
        with pytest.raises(BackendError, match="only"):
            _open(len(visible_devices()) + 1, bundle)

    def test_swap_requires_store_backing(self, eight_cpu_devices):
        bundle, _ = _bundle()
        rs = _open(2, bundle)
        try:
            with pytest.raises(BackendError, match="store"):
                rs.swap()
        finally:
            rs.close()


class TestReplicaHotSwap:
    def test_epoch_atomic_swap_zero_postflip_recompiles(
            self, eight_cpu_devices, _fresh_store):
        """The acceptance check: after one store update every replica
        serves the new version in the SAME epoch, and the flip costs
        zero compiles — prepare pre-warmed the exact jits on every
        chip before anything moved."""
        _fresh_store.register("plc_m", lambda x: (x * 2.0,))
        _fresh_store.register("plc_m", lambda x: (x + 100.0,))  # v2
        x = np.full((4,), 3.0, np.float32)
        rs = ReplicaSet.open("xla", {"model": "store://plc_m",
                                     "custom": ""}, 4, name="swap4")
        try:
            for _ in range(8):                # warm every replica
                (out,) = rs.invoke((x,))
            np.testing.assert_allclose(np.asarray(out), x * 2.0)
            assert len(set(rs.adopted_epochs())) == 1
            rep = rs.swap(2)
            assert rep["to_version"] == 2
            assert rep["handles"] == 4       # every chip attached
            counts_at_flip = rs.compile_counts()
            outs = [rs.invoke((x,)) for _ in range(8)]
            for (o,) in outs:
                np.testing.assert_allclose(np.asarray(o), x + 100.0)
            # all four chips landed in the same epoch, with no compile
            # after the flip (prewarm staged them)
            assert len(set(rs.adopted_epochs())) == 1
            assert rs.compile_counts() == counts_at_flip
        finally:
            rs.close()


class TestFilterDevicesProp:
    def _pipe(self, store, devices, name="f"):
        store.register("plc_p", lambda x: (x * 2.0 + 1.0,))
        return parse_launch(
            f"appsrc name=src dims=4 types=float32 ! "
            f"tensor_filter name={name} model=store://plc_p "
            f"devices={devices} ! tensor_sink name=out")

    def _run(self, pipe, frames=12):
        runner = PipelineRunner(pipe, trace=True)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        try:
            for i in range(frames):
                src.push(TensorBuffer.of(
                    np.full((4,), float(i), np.float32), pts=i))
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        return ({int(b.pts): np.asarray(b.tensors[0])
                 for b in sink.results}, runner)

    def test_pipeline_parity_and_conservation(
            self, eight_cpu_devices, _fresh_store):
        base, _ = self._run(self._pipe(_fresh_store, devices=0))
        store = reset_store()
        pipe = self._pipe(store, devices=4)
        rep, _ = self._run(pipe)
        assert rep.keys() == base.keys()
        for pts, ref in base.items():
            np.testing.assert_array_equal(rep[pts], ref)
        st = pipe.get("f").extra_stats()
        assert st["replica_devices"] == 4 and st["replica_live"] == 4
        assert st["replica_invokes"] == 12
        assert sum(r["invokes"] for r in st["replicas"]) == 12

    def test_fence_mid_stream_conserves(self, eight_cpu_devices,
                                        _fresh_store):
        """Σ replica replied == filter replied, exactly, across a
        chaos fence injected mid-stream at the pipeline level."""
        pipe = self._pipe(_fresh_store, devices=2)
        runner = PipelineRunner(pipe)
        runner.start()
        src, sink = pipe.get("src"), pipe.get("out")
        f = pipe.get("f")
        try:
            for i in range(6):
                src.push(TensorBuffer.of(
                    np.full((4,), float(i), np.float32), pts=i))
            while len(sink.results) < 6:
                time.sleep(0.005)
            assert f.replicas.fence(0, "test chaos")
            for i in range(6, 12):
                src.push(TensorBuffer.of(
                    np.full((4,), float(i), np.float32), pts=i))
            src.end()
            runner.wait(30)
        finally:
            runner.stop()
        assert len(sink.results) == 12
        st = f.extra_stats()
        assert st["replica_invokes"] == 12      # exact, no dupes/loss
        assert st["replica_live"] == 1 and st["replica_fences"] == 1

    def test_explicit_accelerator_declines_softly(
            self, eight_cpu_devices, _fresh_store):
        _fresh_store.register("plc_p", lambda x: (x * 2.0 + 1.0,))
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_filter name=f model=store://plc_p devices=2 "
            "accelerator=cpu:0 ! tensor_sink name=out")
        rep, _ = self._run(pipe)
        assert len(rep) == 12                    # single-device served
        f = pipe.get("f")
        assert f.replicas is None
        assert "accelerator" in f.extra_stats()["replica_decline"]

    def test_canary_split_declines_softly(self, eight_cpu_devices,
                                          _fresh_store):
        _fresh_store.register("plc_c", lambda x: (x * 2.0,))
        _fresh_store.register("plc_c", lambda x: (x * 3.0,))
        pipe = parse_launch(
            "appsrc name=src dims=4 types=float32 ! "
            "tensor_filter name=f model=store://plc_c@2:0.5 devices=2 "
            "! tensor_sink name=out")
        rep, _ = self._run(pipe)
        assert len(rep) == 12
        f = pipe.get("f")
        assert f.replicas is None
        assert "canary" in f.extra_stats()["replica_decline"]


# -- profiled segmentation ----------------------------------------------------

class TestSegmentPlanDP:
    def test_balanced_cut_minimizes_bottleneck(self):
        plan = segment_plan(
            [("a", 1.0), ("b", 3.0), ("c", 1.0), ("d", 1.0)], 2)
        assert plan.stages == [["a", "b"], ["c", "d"]]
        assert plan.devices == [0, 1]
        assert plan.stage_times_s == [4.0, 2.0]
        assert plan.bubble_fraction == pytest.approx(0.25)
        assert plan.total_s == pytest.approx(6.0)

    def test_dominant_element_prefers_fewest_stages(self):
        # the bottleneck is element a no matter how many cuts; extra
        # cuts buy nothing but handoffs, so the plan stays at 2 stages
        plan = segment_plan(
            [("a", 10.0), ("b", 0.1), ("c", 0.1)], 3)
        assert len(plan.stages) == 2
        assert plan.stages[0] == ["a"]

    def test_more_elements_than_devices(self):
        plan = segment_plan(
            [(f"e{i}", 1.0) for i in range(6)], 2)
        assert len(plan.stages) == 2
        assert sorted(n for g in plan.stages for n in g) == \
            sorted(f"e{i}" for i in range(6))

    def test_zero_profile_collapses_to_one_stage(self):
        plan = segment_plan([("a", 0.0), ("b", 0.0)], 4)
        assert plan.stages == [["a", "b"]]
        assert plan.bubble_fraction == 0.0

    def test_empty_profile_is_typed(self):
        with pytest.raises(BackendError, match="empty"):
            segment_plan([], 2)

    def test_stage_of_and_report_shape(self):
        plan = segment_plan([("a", 2.0), ("b", 2.0)], 2)
        assert plan.stage_of() == {"a": 0, "b": 1}
        rep = plan.report()
        assert rep["bottleneck_s"] == 2.0
        assert [r["elements"] for r in rep["stages"]] == [["a"], ["b"]]


def _three_filter_pipe(store):
    store.register("plc_s1", lambda x: (x * 2.0,))
    store.register("plc_s2", lambda x: (x + 1.0,))
    store.register("plc_s3", lambda x: (-x,))
    return parse_launch(
        "appsrc name=src dims=4 types=float32 ! "
        "tensor_filter name=s1 model=store://plc_s1 ! "
        "tensor_filter name=s2 model=store://plc_s2 ! "
        "tensor_filter name=s3 model=store://plc_s3 ! "
        "tensor_sink name=out")


def _push_and_collect(pipe, frames=10, **runner_kw):
    runner = PipelineRunner(pipe, **runner_kw)
    runner.start()
    src, sink = pipe.get("src"), pipe.get("out")
    try:
        for i in range(frames):
            src.push(TensorBuffer.of(
                np.full((4,), float(i), np.float32), pts=i))
        src.end()
        runner.wait(30)
    finally:
        runner.stop()
    return ({int(b.pts): np.asarray(b.tensors[0])
             for b in sink.results}, runner)


class TestSegmentedPipeline:
    def test_profiled_plan_and_parity(self, eight_cpu_devices,
                                      _fresh_store):
        """The acceptance check: trace → plan → apply → rerun matches
        the unsegmented pipeline within 1e-6, with each stage pinned to
        its own device."""
        base, runner = _push_and_collect(
            _three_filter_pipe(_fresh_store), trace=True,
            device_segments=False)
        plan = plan_from_tracer(runner.tracer, ["s1", "s2", "s3"], 4)
        assert plan.source == "tracer"
        assert sum(len(g) for g in plan.stages) == 3
        store = reset_store()
        pipe = _three_filter_pipe(store)
        pinned = apply_plan(pipe, plan)
        assert pinned == 3
        assert pipe.segment_plan is plan
        # each planned stage landed on its own device ordinal
        accels = {pipe.get(g[0]).props["accelerator"]
                  for g in plan.stages}
        assert len(accels) == len(plan.stages)
        seg, _ = _push_and_collect(pipe)
        assert seg.keys() == base.keys()
        for pts, ref in base.items():
            assert float(np.max(np.abs(seg[pts] - ref))) <= 1e-6

    def test_fuse_segments_respects_plan_cut(self, eight_cpu_devices,
                                             _fresh_store):
        pipe = _three_filter_pipe(_fresh_store)
        plan = segment_plan(
            [("s1", 1.0), ("s2", 1.0), ("s3", 1.0)], 3)
        apply_plan(pipe, plan)
        # every adjacent pair sits across a cut: nothing may fuse
        assert fuse_segments(pipe) == 0
        assert set(pipe.elements) >= {"s1", "s2", "s3"}

    def test_fuse_segments_fuses_within_stage(self, eight_cpu_devices,
                                              _fresh_store):
        pipe = _three_filter_pipe(_fresh_store)
        plan = segment_plan(
            [("s1", 1.0), ("s2", 1.0), ("s3", 4.0)], 2)
        assert plan.stages == [["s1", "s2"], ["s3"]]
        apply_plan(pipe, plan)
        # s1+s2 share a stage and fuse; the s2|s3 cut holds
        assert fuse_segments(pipe) == 1
        assert "s3" in pipe.elements and "s2" not in pipe.elements

    def test_measured_report_reads_live_profile(self, eight_cpu_devices,
                                                _fresh_store):
        base, runner = _push_and_collect(
            _three_filter_pipe(_fresh_store), trace=True,
            device_segments=False)
        plan = plan_from_tracer(runner.tracer, ["s1", "s2", "s3"], 3)
        rep = plan.measured_report(runner.tracer)
        assert all(r["measured_s"] > 0 for r in rep["stages"])
        assert 0.0 <= rep["measured_bubble_fraction"] < 1.0


# -- chip leases --------------------------------------------------------------

class TestChipLeaseTable:
    def test_lease_fence_release_prefers_own_chips(self):
        t = ChipLeaseTable(range(8))
        a = t.lease("w0", 4)
        b = t.lease("w1", 4)
        assert a == (0, 1, 2, 3) and b == (4, 5, 6, 7)
        assert t.fence("w0") == (0, 1, 2, 3)
        assert t.snapshot()["counts"] == {"free": 0, "leased": 4,
                                          "fenced": 4}
        # the restarted owner gets its own chips back, not w1's
        assert t.lease("w0", 4) == (0, 1, 2, 3)
        assert t.snapshot()["counts"]["leased"] == 8
        assert t.snapshot()["fences_total"] == 4

    def test_shortfall_is_typed_not_silent(self):
        t = ChipLeaseTable(range(4))
        t.lease("w0", 3)
        with pytest.raises(BackendError, match="wanted 2"):
            t.lease("w1", 2)
        # the failed lease took nothing
        assert t.snapshot()["counts"]["free"] == 1

    def test_release_returns_chips_to_pool(self):
        t = ChipLeaseTable(range(4))
        t.lease("w0", 4)
        t.fence("w0")
        assert t.release("w0") == (0, 1, 2, 3)
        assert t.chips_of("w0") == ()
        # a different owner can lease them now
        assert t.lease("w1", 4) == (0, 1, 2, 3)
        assert t.snapshot()["releases_total"] == 4


class TestPoolChips:
    def test_chips_must_divide_evenly(self):
        with pytest.raises(ValueError, match="divide"):
            WorkerPool(QueryServer.get(next(_sid)),
                       WorkerSpec(kind="echo"), 2, chips=[0, 1, 2])

    def test_partition_weights_and_stats(self):
        pqs = PooledQueryServer.echo(
            sid=next(_sid), workers=2, service_ms=1.0,
            chips=list(range(8)))
        try:
            pool = pqs.pool
            assert pool.capacity_slots == 8
            assert pool.slot_weights() == {0: 4, 1: 4}
            st = pool.stats()
            owned = [tuple(w["chips"]) for w in st["workers"]]
            assert owned == [(0, 1, 2, 3), (4, 5, 6, 7)]
            assert st["chips"]["counts"] == {"free": 0, "leased": 8,
                                             "fenced": 0}
        finally:
            pqs.close()

    @pytest.mark.chaos
    def test_crashed_worker_releases_then_reowns_chips(self):
        """A dead worker's chips are fenced at reap and re-leased to
        the replacement process — 'worker wid owns chips i..j' survives
        the crash, and capacity never counts a dead chip."""
        pqs = PooledQueryServer(
            WorkerSpec(kind="echo", service_ms=1.0, crash_after_s=0.3),
            workers=2, sid=next(_sid), restart_backoff_s=0.02,
            chips=list(range(8)))
        try:
            pool = pqs.pool
            before = {w["wid"]: tuple(w["chips"])
                      for w in pool.stats()["workers"]}
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                st = pool.stats()
                if pool.chip_table.fences_total >= 4 and \
                        st["chips"]["counts"]["leased"] == 8:
                    break
                time.sleep(0.05)
            st = pool.stats()
            assert pool.chip_table.fences_total >= 4
            assert st["chips"]["counts"]["leased"] == 8
            after = {w["wid"]: tuple(w["chips"]) for w in st["workers"]}
            assert after == before           # same chips, same owners
        finally:
            pqs.close()


# -- chip-weighted scaling ----------------------------------------------------

class _WeightedStubPool:
    def __init__(self, weights):
        self._w = dict(weights)
        self._b = {i: None for i in weights}
        self.calls = []

    @property
    def size(self):
        return len(self._w)

    @property
    def capacity_slots(self):
        return sum(self._w.values())

    def slot_weights(self):
        return dict(self._w)

    def bindings(self):
        return dict(self._b)

    def rebind(self, mapping, **kw):
        self.calls.append(dict(mapping))
        self._b.update(mapping)
        return {"ok": True}


class _StubTracer:
    def __init__(self, rates):
        self.rates = rates

    def tenant_summary(self):
        return {t: {"count": 10, "rate_hz": r, "p50_ms": 1.0,
                    "p99_ms": 2.0}
                for t, r in self.rates.items()}


class TestWeightedScaler:
    def _ctrl(self, weights, rates):
        table = TenantTable.from_dict({"tenants": [
            {"name": "a", "model": "m1"},
            {"name": "b", "model": "m2"}]})
        pool = _WeightedStubPool(weights)
        return ScalingController(pool, table, _StubTracer(rates),
                                 interval_s=999.0), pool

    def test_k_chip_slot_counts_as_k_capacity(self):
        """The regression the satellite pins: a 4-chip slot is 4 units
        of allocation budget, so the hot model claims the heavy slot
        while the light model rides the 1-chip slot."""
        ctrl, pool = self._ctrl({0: 4, 1: 1}, {"a": 40.0, "b": 10.0})
        assert ctrl.tick() == {"m1": 3, "m2": 2}   # of 5 capacity units
        assert pool.bindings() == {0: "m1", 1: "m2"}

    def test_traffic_flip_moves_the_heavy_slot(self):
        ctrl, pool = self._ctrl({0: 4, 1: 1}, {"a": 40.0, "b": 10.0})
        ctrl.tick()
        assert pool.bindings()[0] == "m1"
        ctrl.tracer = _StubTracer({"a": 1.0, "b": 100.0})
        ctrl.tick()
        assert pool.bindings()[0] == "m2"

    def test_weightless_pool_budget_unchanged(self):
        # no slot_weights surface → every slot weighs 1, same plan the
        # pre-placement controller produced (regression guard)
        class _Plain(_WeightedStubPool):
            slot_weights = None
            capacity_slots = 0

        table = TenantTable.from_dict({"tenants": [
            {"name": "a", "model": "m1"},
            {"name": "b", "model": "m2"}]})
        pool = _Plain({0: 1, 1: 1, 2: 1, 3: 1})
        ctrl = ScalingController(pool, table,
                                 _StubTracer({"a": 30.0, "b": 10.0}),
                                 interval_s=999.0)
        assert ctrl.tick() == {"m1": 3, "m2": 1}


# -- metrics plane ------------------------------------------------------------

class TestReplicaMetrics:
    def test_replica_series_round_trip_and_conservation(
            self, eight_cpu_devices):
        """ISSUE 14 satellite: per-chip series survive render → parse
        with device labels intact, and Σ nns_replica_invokes_total over
        devices equals the filter's invoke count — the replica
        conservation check, as scraped."""
        bundle, dim = _bundle()
        x = np.ones((1, dim), np.float32)
        rs = _open(4, bundle)
        try:
            for _ in range(10):
                rs.invoke((x,))
            rs.fence(3, "scrape me")
            st = rs.stats()
        finally:
            rs.close()
        plan = segment_plan([("s1", 2.0), ("s2", 1.0)], 2)
        parsed = parse_prometheus(render_prometheus(metrics_snapshot(
            replicas={"f": st}, segments={"p0": plan.report()})))
        inv = parsed["nns_replica_invokes_total"]
        assert inv["type"] == "counter"
        by_dev = {k: v for k, v in inv["samples"].items()}
        assert len(by_dev) == 4
        assert sum(by_dev.values()) == 10.0 \
            == sum(r["invokes"] for r in st["replicas"])
        # the fenced chip is visible as up=0 with its state label
        up = parsed["nns_replica_up"]["samples"]
        down = [k for k, v in up.items() if v == 0.0]
        assert len(down) == 1
        assert 'device="3"' in down[0] and 'state="fenced"' in down[0]
        assert parsed["nns_replica_queue_depth"]["type"] == "gauge"
        # segment plan series
        stage = parsed["nns_segment_stage_seconds"]["samples"]
        assert {('stage="0"' in k, 'stage="1"' in k)
                for k in stage} == {(True, False), (False, True)}
        bub = parsed["nns_segment_bubble_fraction"]["samples"]
        assert list(bub.values()) == [pytest.approx(0.25)]

    def test_replica_rows_in_top_view(self, eight_cpu_devices):
        bundle, dim = _bundle()
        rs = _open(2, bundle)
        try:
            rs.invoke((np.ones((1, dim), np.float32),))
            st = rs.stats()
        finally:
            rs.close()
        cur = parse_prometheus(render_prometheus(metrics_snapshot(
            replicas={"f": st})))
        lines = "\n".join(top_table({}, cur, 1.0))
        assert "nns_replica_invokes_total" in lines
        assert "nns_replica_queue_depth" in lines
