"""Tensor core unit tests (reference: tests/common/unittest_common.cc)."""

import numpy as np
import pytest

from nnstreamer_tpu.tensor import (
    DType,
    MediaType,
    MetaHeader,
    TensorBuffer,
    TensorFormat,
    TensorInfo,
    TensorsSpec,
)
from nnstreamer_tpu.tensor.info import (
    parse_dim_string,
    shapes_compatible,
    to_dim_string,
)
from nnstreamer_tpu.tensor.sparse import sparse_decode, sparse_encode, sparse_nbytes


class TestDTypes:
    def test_roundtrip_names(self):
        for dt in DType:
            assert DType.from_name(dt.type_name) is dt

    def test_np_roundtrip(self):
        for dt in DType:
            if dt == DType.BFLOAT16:
                continue
            assert DType.from_np(dt.np_dtype) is dt

    def test_bfloat16(self):
        dt = DType.BFLOAT16
        assert dt.itemsize == 2
        assert DType.from_name("bfloat16") is dt

    def test_unknown_name(self):
        with pytest.raises(ValueError, match="unknown tensor dtype"):
            DType.from_name("float128")

    def test_wire_values_stable(self):
        # Wire enum encoding must not drift (serialized stream compat).
        assert DType.INT32 == 0
        assert DType.UINT8 == 5
        assert DType.FLOAT32 == 7
        assert DType.FLOAT16 == 10
        assert DType.BFLOAT16 == 11


class TestDimStrings:
    def test_parse_reference_order(self):
        # reference: "3:224:224:1" = ch:w:h:batch innermost-first
        assert parse_dim_string("3:224:224:1") == (1, 224, 224, 3)

    def test_roundtrip(self):
        for s in ["1", "3:224:224:1", "10:1:1:1", "5:4:3:2:1"]:
            assert to_dim_string(parse_dim_string(s)) == s

    def test_bad_dims(self):
        for bad in ["", "0:3", "-1:2", "a:b", ":" , "3:?"]:
            with pytest.raises(ValueError):
                parse_dim_string(bad)

    def test_rank_limit(self):
        with pytest.raises(ValueError, match="rank"):
            parse_dim_string(":".join(["2"] * 17))

    def test_compat_ignores_padding(self):
        assert shapes_compatible((1, 224, 224, 3), (224, 224, 3))
        assert shapes_compatible((1, 1, 5), (5,))
        assert not shapes_compatible((2, 5), (5,))


class TestTensorInfo:
    def test_size(self):
        ti = TensorInfo.from_dim_string("3:224:224:1", "uint8")
        assert ti.nbytes == 224 * 224 * 3
        assert ti.num_elements == 224 * 224 * 3

    def test_compat(self):
        a = TensorInfo((1, 10), DType.FLOAT32)
        b = TensorInfo((10,), DType.FLOAT32)
        c = TensorInfo((10,), DType.UINT8)
        assert a.is_compatible(b)
        assert not a.is_compatible(c)

    def test_bad_shape(self):
        with pytest.raises(ValueError):
            TensorInfo((0, 5))


class TestTensorsSpec:
    def test_from_strings_multi(self):
        spec = TensorsSpec.from_strings("3:224:224:1,1001:1", "uint8,float32")
        assert spec.num_tensors == 2
        assert spec.tensors[0].dtype == DType.UINT8
        assert spec.tensors[1].shape == (1, 1001)

    def test_type_broadcast(self):
        spec = TensorsSpec.from_strings("4:4,2:2", "float32")
        assert all(t.dtype == DType.FLOAT32 for t in spec.tensors)

    def test_mismatched_lists(self):
        with pytest.raises(ValueError, match="entries"):
            TensorsSpec.from_strings("4:4,2:2,1:1", "float32,uint8")

    def test_hashable(self):
        a = TensorsSpec.from_strings("3:4:5", "float32")
        b = TensorsSpec.from_strings("3:4:5", "float32")
        assert a == b and hash(a) == hash(b)
        assert {a: 1}[b] == 1

    def test_max_tensors(self):
        infos = tuple(TensorInfo((1,)) for _ in range(17))
        with pytest.raises(ValueError, match="exceeds limit"):
            TensorsSpec(tensors=infos)

    def test_flexible_matches_anything(self):
        flex = TensorsSpec.of(TensorInfo((1,)), format=TensorFormat.FLEXIBLE)
        stat = TensorsSpec.from_strings("3:224:224:1", "uint8")
        assert flex.is_compatible(stat)

    def test_roundtrip_strings(self):
        spec = TensorsSpec.from_strings("3:224:224:1,1001:1", "uint8,float32", "img,logits")
        dims, types, names = spec.to_strings()
        spec2 = TensorsSpec.from_strings(dims, types, names)
        assert spec == spec2


class TestMetaHeader:
    def test_roundtrip(self):
        hdr = MetaHeader(shape=(1, 224, 224, 3), dtype=DType.UINT8,
                         media=MediaType.VIDEO)
        data = hdr.pack() + b"payload"
        parsed, off = MetaHeader.unpack(data)
        assert parsed == hdr
        assert data[off:] == b"payload"

    def test_bad_magic(self):
        with pytest.raises(ValueError, match="magic"):
            MetaHeader.unpack(b"\x00" * 64)

    def test_truncated(self):
        hdr = MetaHeader(shape=(4, 4), dtype=DType.FLOAT32).pack()
        with pytest.raises(ValueError):
            MetaHeader.unpack(hdr[:8])

    def test_info_roundtrip(self):
        ti = TensorInfo((7, 5), DType.INT16)
        hdr = MetaHeader.for_info(ti)
        assert hdr.to_info() == ti


class TestSparse:
    def test_roundtrip(self):
        rng = np.random.default_rng(0)
        dense = rng.random((8, 16)).astype(np.float32)
        dense[dense < 0.9] = 0
        frame = sparse_encode(dense)
        out = sparse_decode(frame)
        np.testing.assert_array_equal(out, dense)

    def test_int_dtype(self):
        dense = np.zeros((4, 4), dtype=np.int16)
        dense[1, 2] = -7
        np.testing.assert_array_equal(sparse_decode(sparse_encode(dense)), dense)

    def test_all_zero(self):
        dense = np.zeros((3, 3), dtype=np.float32)
        np.testing.assert_array_equal(sparse_decode(sparse_encode(dense)), dense)

    def test_size_win(self):
        dense = np.zeros((100, 100), dtype=np.float32)
        dense[0, 0] = 1
        sp, dn = sparse_nbytes(dense)
        assert sp < dn

    def test_reject_dense_frame(self):
        hdr = MetaHeader(shape=(2, 2), dtype=DType.FLOAT32).pack()
        with pytest.raises(ValueError, match="not a sparse"):
            sparse_decode(hdr + b"\x00" * 16)


class TestTensorBuffer:
    def test_spec(self):
        buf = TensorBuffer.of(np.zeros((1, 4), np.float32), np.zeros((2,), np.uint8))
        spec = buf.spec()
        assert spec.num_tensors == 2
        assert spec.tensors[1].dtype == DType.UINT8

    def test_subset(self):
        buf = TensorBuffer.of(*(np.full((1,), i) for i in range(4)))
        sub = buf.subset([2, 0])
        assert sub.tensors[0][0] == 2 and sub.tensors[1][0] == 0
        with pytest.raises(IndexError, match="out of range"):
            buf.subset([7])

    def test_meta_update(self):
        buf = TensorBuffer.of(np.zeros(1), pts=123)
        b2 = buf.with_meta(client_id=9)
        assert b2.meta["client_id"] == 9 and b2.pts == 123
        assert "client_id" not in buf.meta

    def test_host_passthrough(self):
        buf = TensorBuffer.of(np.zeros(3))
        assert buf.to_host() is buf
        assert not buf.on_device


class TestCorruptWire:
    """Regression tests for malformed-wire handling (review findings)."""

    def test_sparse_oob_index(self):
        from nnstreamer_tpu.tensor.info import TensorFormat
        hdr = MetaHeader(shape=(2, 2), dtype=DType.FLOAT32,
                         format=TensorFormat.SPARSE, extra=1)
        frame = hdr.pack() + np.float32(1.0).tobytes() + np.uint32(100).tobytes()
        with pytest.raises(ValueError, match="out of range"):
            sparse_decode(frame)

    def test_sparse_nnz_too_large(self):
        from nnstreamer_tpu.tensor.info import TensorFormat
        hdr = MetaHeader(shape=(2, 2), dtype=DType.FLOAT32,
                         format=TensorFormat.SPARSE, extra=10**6)
        with pytest.raises(ValueError, match="nnz"):
            sparse_decode(hdr.pack() + b"\x00" * 64)

    def test_sparse_0d(self):
        scalar = np.array(3.0, dtype=np.float32)
        out = sparse_decode(sparse_encode(scalar))
        assert out.reshape(()) == scalar

    def test_empty_dim_segment(self):
        for bad in ["3::4", "3:224:224:1:", ":3"]:
            with pytest.raises(ValueError, match="empty segment"):
                parse_dim_string(bad)

    def test_sparse_giant_shape_refused(self):
        from nnstreamer_tpu.tensor.info import TensorFormat
        hdr = MetaHeader(shape=(1 << 22, 1 << 22), dtype=DType.FLOAT32,
                         format=TensorFormat.SPARSE, extra=0)
        with pytest.raises(ValueError, match="decode limit"):
            sparse_decode(hdr.pack())

    def test_format_mismatch_incompatible(self):
        stat = TensorsSpec.of(TensorInfo((4,)))
        sp = TensorsSpec.of(TensorInfo((4,)), format=TensorFormat.SPARSE)
        assert not stat.is_compatible(sp)

    def test_meta_not_shared_across_derived(self):
        buf = TensorBuffer.of(np.zeros(1), np.zeros(1))
        d = buf.subset([0])
        d.meta["x"] = 1
        assert "x" not in buf.meta
        d2 = buf.with_tensors([np.ones(1)])
        d2.meta["y"] = 2
        assert "y" not in buf.meta

    def test_subset_rejects_negative(self):
        buf = TensorBuffer.of(np.zeros(1), np.zeros(1))
        with pytest.raises(IndexError):
            buf.subset([-1])

    def test_sparse_nbytes_matches_encode(self):
        for arr in [np.array(3.0, np.float32),
                    np.eye(5, dtype=np.float32)]:
            sp, dn = sparse_nbytes(arr)
            assert sp == len(sparse_encode(arr))
