"""Metrics export plane (serving/metrics.py): snapshot flattening,
Prometheus text exposition (parsed line-by-line), the stdlib HTTP
endpoint, and the scrape-monotonicity contract — two consecutive
scrapes under load never see a counter or histogram bucket decrease."""

import re
import time

import numpy as np
import pytest

from nnstreamer_tpu.runtime.tracing import Tracer
from nnstreamer_tpu.serving.metrics import (
    MetricsServer, escape_label_value, metrics_snapshot,
    parse_prometheus, render_prometheus, scrape, top_table)
from nnstreamer_tpu.tensor.buffer import TensorBuffer


def _admission(offered=10, admitted=8, replied=7, depth=1, inflight=0):
    return {"offered": offered, "admitted": admitted, "replied": replied,
            "rejected": {"queue_full": offered - admitted},
            "shed": {"expired": admitted - replied - depth - inflight},
            "depth": depth, "inflight": inflight, "depth_peak": 4,
            "max_pending": 8, "max_inflight": 0,
            "shed_policy": "reject-newest"}


def _pool(replied=(4, 3)):
    return {"pool": {"workers": len(replied), "live": len(replied),
                     "ready": len(replied), "degraded": 0, "restarts": 1,
                     "kills": 0, "reoffered": 2, "pending": 0,
                     "epoch": 0},
            "workers": [{"wid": i, "pid": 100 + i, "state": "ready",
                         "inflight": 0, "hb_age_ms": 1.0, "restarts": i,
                         "kills": 0, "replied": r}
                        for i, r in enumerate(replied)]}


def _mesh(replied=(9, 31)):
    """router.stats()-shaped snapshot: per-host replied sums to the
    admission plane's replied — the cross-host conservation check."""
    return {
        "mesh": {"hosts": len(replied), "ready": len(replied) - 1,
                 "fenced": 1, "epoch": 2, "reoffered": 3,
                 "busy_reroutes": 1, "stale_results": 0, "pending": 0,
                 "lease_s": 1.0},
        "hosts": [
            {"host": f"host{i}", "state": "READY" if i else "FENCED",
             "zone": "", "capacity_rps": 100.0, "outstanding": i,
             "replied": r, "busies": i, "lease_age_ms": 12.5,
             "fence_cause": None if i else "lease_expired",
             "versions": {},
             "remote": {"offered": r + 1, "admitted": r,
                        "replied": r - 1}}
            for i, r in enumerate(replied)],
        "admission": _admission(offered=41, admitted=40,
                                replied=sum(replied), depth=0,
                                inflight=0),
    }


def _traced(n=5, name="echo"):
    tr = Tracer()
    buf = TensorBuffer.of(np.ones((2,), np.float32))
    t0 = time.perf_counter()
    for i in range(n):
        tr.record_process(name, buf, t0, t0 + 1e-4 * (i + 1))
    return tr


class TestExposition:
    def test_type_and_help_line_per_family(self):
        text = render_prometheus(metrics_snapshot(
            tracer=_traced(), admission=_admission(), pool=_pool()))
        parsed = parse_prometheus(text)
        for fam in ("nns_admission_offered_total",
                    "nns_admission_rejected_total",
                    "nns_admission_depth",
                    "nns_pool_restarts_total",
                    "nns_worker_replied_total",
                    "nns_element_proctime_seconds",
                    "nns_trace_events_total"):
            assert fam in parsed, f"family {fam} missing"
            assert parsed[fam].get("type"), f"no TYPE line for {fam}"
            assert parsed[fam].get("help"), f"no HELP line for {fam}"
        # _total families are counters; bare gauges are gauges
        assert parsed["nns_admission_offered_total"]["type"] == "counter"
        assert parsed["nns_admission_depth"]["type"] == "gauge"
        assert parsed["nns_element_proctime_seconds"]["type"] \
            == "histogram"
        # every non-comment line is "name{labels} value" — no stray
        # formats a scraper would reject
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            assert re.match(
                r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? \S+$', line), \
                f"malformed exposition line: {line!r}"

    def test_label_escaping_round_trips(self):
        assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
        tr = _traced(2, name='we"ird\\el\nem')
        text = render_prometheus(metrics_snapshot(tracer=tr))
        # raw newline inside a quoted label value would break
        # line-oriented parsers
        for line in text.splitlines():
            assert "\r" not in line
        assert '\\"ird' in text and "\\\\el" in text and "\\nem" in text

    def test_histogram_buckets_cumulative_with_inf(self):
        tr = _traced(4)
        text = render_prometheus(metrics_snapshot(tracer=tr))
        fam = parse_prometheus(text)["nns_element_proctime_seconds"]
        buckets = sorted(
            (float("inf") if 'le="+Inf"' in k else
             float(re.search(r'le="([^"]+)"', k).group(1)), v)
            for k, v in fam["samples"].items() if "_bucket{" in k)
        vals = [v for _, v in buckets]
        assert vals == sorted(vals)          # cumulative ⇒ monotone
        assert buckets[-1][0] == float("inf")
        assert vals[-1] == 4                 # +Inf bucket == _count
        count = [v for k, v in fam["samples"].items()
                 if k.endswith("_count}") or "_count{" in k]
        assert count == [4]

    def test_counter_families_never_negative(self):
        series = metrics_snapshot(admission=_admission(), pool=_pool())
        for s in series:
            if s["type"] == "counter":
                for _, v in s["samples"]:
                    assert v >= 0, s["name"]

    def test_parse_handles_bucket_sum_count_suffixes(self):
        tr = _traced(3)
        parsed = parse_prometheus(render_prometheus(
            metrics_snapshot(tracer=tr)))
        fam = parsed["nns_element_proctime_seconds"]
        # suffixed sample lines are attributed to the base family, not
        # invented as families of their own
        assert "nns_element_proctime_seconds_bucket" not in parsed
        assert any("_sum{" in k or k.endswith("_sum}")
                   for k in fam["samples"])


class TestMetricsServer:
    def test_scrapes_are_monotone_under_load(self):
        tr = _traced(2)
        state = {"offered": 10}

        def collect():
            return metrics_snapshot(
                tracer=tr, admission=_admission(state["offered"]),
                pool=_pool())

        srv = MetricsServer(collect)
        try:
            url = f"http://127.0.0.1:{srv.port}/metrics"
            p1 = parse_prometheus(scrape(url))
            # the plane keeps counting between scrapes
            state["offered"] += 7
            buf = TensorBuffer.of(np.ones((2,), np.float32))
            t0 = time.perf_counter()
            for _ in range(3):
                tr.record_process("echo", buf, t0, t0 + 2e-4)
            p2 = parse_prometheus(scrape(url))
            for fam, info in p1.items():
                if info.get("type") not in ("counter", "histogram"):
                    continue
                for k, v in info["samples"].items():
                    v2 = p2[fam]["samples"].get(k)
                    assert v2 is not None and v2 >= v, (fam, k, v, v2)
            # and actually increased where we counted
            assert p2["nns_admission_offered_total"]["samples"][
                "nns_admission_offered_total"] == 17.0
        finally:
            srv.close()

    def test_healthz_and_unknown_path(self):
        import json
        import urllib.error
        import urllib.request

        srv = MetricsServer(lambda: [],
                            health=lambda: {"workers": 2})
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with urllib.request.urlopen(f"{base}/healthz",
                                        timeout=5) as r:
                info = json.loads(r.read().decode())
            assert info["ok"] and info["workers"] == 2
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/nope", timeout=5)
            assert ei.value.code == 404
        finally:
            srv.close()

    def test_collect_failure_yields_503_not_crash(self):
        import urllib.error
        import urllib.request

        calls = {"n": 0}

        def collect():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("boom")
            return metrics_snapshot(admission=_admission())

        srv = MetricsServer(collect)
        try:
            url = f"http://127.0.0.1:{srv.port}/metrics"
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url, timeout=5)
            assert ei.value.code == 503
            # endpoint survives and serves the next scrape
            assert "nns_admission_offered_total" in scrape(url)
        finally:
            srv.close()

    def test_content_type_is_exposition_format(self):
        import urllib.request

        srv = MetricsServer(lambda: metrics_snapshot(
            admission=_admission()))
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics",
                    timeout=5) as r:
                ctype = r.headers["Content-Type"]
            assert ctype.startswith("text/plain")
            assert "version=0.0.4" in ctype
        finally:
            srv.close()


class TestMeshExposition:
    def test_host_labels_round_trip(self):
        """ISSUE 12 satellite: per-host series survive the full
        render → parse cycle with their host labels intact, and the
        per-host goodput sums to the admission plane's replied — the
        cross-host conservation check, as scraped."""
        snap = _mesh(replied=(9, 31))
        parsed = parse_prometheus(render_prometheus(metrics_snapshot(
            admission=snap["admission"], mesh=snap)))
        rep = parsed["nns_host_replied_total"]["samples"]
        by_host = {re.search(r'host="([^"]+)"', k).group(1): v
                   for k, v in rep.items()}
        assert by_host == {"host0": 9.0, "host1": 31.0}
        adm = parsed["nns_admission_replied_total"]["samples"][
            "nns_admission_replied_total"]
        assert sum(by_host.values()) == adm == 40.0
        # mesh-level counters/gauges made it through too
        assert parsed["nns_mesh_reoffered_total"]["samples"][
            "nns_mesh_reoffered_total"] == 3.0
        assert parsed["nns_mesh_fenced"]["samples"][
            "nns_mesh_fenced"] == 1.0
        # up gauge keys on host AND state so a flap is visible as a
        # label change, not a silent value swap
        up = parsed["nns_host_up"]["samples"]
        fenced = [k for k, v in up.items() if v == 0.0]
        assert len(fenced) == 1
        assert 'host="host0"' in fenced[0]
        assert 'state="FENCED"' in fenced[0]

    def test_lease_carried_remote_counters_exported(self):
        snap = _mesh(replied=(9, 31))
        parsed = parse_prometheus(render_prometheus(metrics_snapshot(
            mesh=snap)))
        for key in ("offered", "admitted", "replied"):
            fam = parsed[f"nns_host_local_{key}_total"]
            assert fam["type"] == "counter"
            assert len(fam["samples"]) == 2
        local = parsed["nns_host_local_replied_total"]["samples"]
        assert sum(local.values()) == (9 - 1) + (31 - 1)


class TestLoopAndShmExposition:
    def test_shm_transport_counters_round_trip(self):
        """ISSUE 20 satellite: the pool's shm-lane counters survive
        render → parse, typed as counters, and the lane split
        (shm_frames vs shm_fallbacks) is visible from one scrape."""
        pool = _pool()
        pool["pool"].update(shm_frames=80, shm_bytes=5_242_880,
                            shm_fallbacks=2)
        parsed = parse_prometheus(render_prometheus(metrics_snapshot(
            pool=pool)))
        for fam, want in (("nns_shm_frames_total", 80.0),
                          ("nns_shm_bytes_total", 5242880.0),
                          ("nns_shm_fallbacks_total", 2.0)):
            assert parsed[fam]["type"] == "counter"
            assert parsed[fam].get("help")
            assert parsed[fam]["samples"][fam] == want

    def test_pipe_only_pool_still_exports_zeroed_lane(self):
        # a pool that never used shm still exposes the families at 0 —
        # dashboards don't need existence checks
        parsed = parse_prometheus(render_prometheus(metrics_snapshot(
            pool=_pool())))
        assert parsed["nns_shm_frames_total"]["samples"][
            "nns_shm_frames_total"] == 0.0

    def test_compiled_loop_counters_round_trip(self):
        """Windows entered / frames windowed / bails-by-cause as
        recorded by the scheduler's tracer hooks, scraped back."""
        tr = _traced(6, name="f")
        t0 = time.perf_counter()
        tr.record_compiled_window("f", 4, t0, t0 + 1e-3)
        tr.record_compiled_window("f", 2, t0, t0 + 2e-3)
        tr.record_loop_bail("f", "eos", t0)
        tr.record_loop_bail("f", "shape", t0)
        tr.record_loop_bail("f", "shape", t0)
        parsed = parse_prometheus(render_prometheus(metrics_snapshot(
            tracer=tr)))
        assert parsed["nns_loop_entries_total"]["samples"][
            'nns_loop_entries_total{element="f"}'] == 2.0
        assert parsed["nns_compiled_steps_total"]["samples"][
            'nns_compiled_steps_total{element="f"}'] == 6.0
        fam = parsed["nns_loop_bails_total"]
        assert fam["type"] == "counter"
        by_cause = {re.search(r'cause="([^"]+)"', k).group(1): v
                    for k, v in fam["samples"].items()}
        assert by_cause == {"eos": 1.0, "shape": 2.0}


def _sharded_replicas(invokes=(6, 4), fenced=None):
    """Synthetic ShardedReplicaSet.stats() — the shape placement's
    ReplicaSet emits plus the shard-group keys sharding.py adds."""
    rows = []
    for g, inv in enumerate(invokes):
        state = "fenced" if g == fenced else "ready"
        rows.append({"device": g * 2, "platform": "cpu",
                     "invokes": inv, "batches": inv, "errors": 0,
                     "queue_depth": 0, "up": state == "ready",
                     "state": state, "compile_count": 1,
                     "adopted_epoch": 1,
                     "group": g, "devices": [g * 2, g * 2 + 1],
                     "shards": 2})
    return {"replicas": rows, "devices": len(invokes),
            "live": sum(1 for r in rows if r["up"]),
            "routed": sum(invokes), "reoffers": 0, "rejected": 0,
            "fences": 1 if fenced is not None else 0,
            "group_size": 2,
            "leases": {"free": 8 - 2 * len(invokes),
                       "leased": 2 * len(invokes), "fenced": 0}}


class TestShardExposition:
    def test_shard_family_round_trips_and_conserves(self):
        """Sharded-serving satellite: nns_shard_* series survive
        render → parse with group/devices labels intact, and Σ shard
        group invokes == the filter's replica invokes — tensor-parallel
        conservation from one scrape."""
        st = _sharded_replicas(invokes=(6, 4))
        parsed = parse_prometheus(render_prometheus(metrics_snapshot(
            replicas={"f": st})))
        fam = parsed["nns_shard_group_invokes_total"]
        assert fam["type"] == "counter"
        by_group = {re.search(r'group="([^"]+)"', k).group(1): v
                    for k, v in fam["samples"].items()}
        assert by_group == {"0": 6.0, "1": 4.0}
        # the per-chip replica family carries the same rows, so the
        # shard sum equals the replica sum equals filter invokes
        rep = parsed["nns_replica_invokes_total"]["samples"]
        assert sum(by_group.values()) == sum(rep.values()) == 10.0
        # devices label names every member chip of the group
        assert any('devices="0,1"' in k for k in fam["samples"])
        # width + lease ledger exported as gauges
        assert parsed["nns_shard_group_size"]["samples"][
            'nns_shard_group_size{filter="f"}'] == 2.0
        leases = parsed["nns_shard_leased_chips"]["samples"]
        assert leases['nns_shard_leased_chips{filter="f",'
                      'state="leased"}'] == 4.0
        # adopted epoch: one distinct value across groups == atomic swap
        epochs = set(parsed["nns_shard_group_adopted_epoch"]
                     ["samples"].values())
        assert epochs == {1.0}

    def test_member_fence_shows_as_group_down(self):
        st = _sharded_replicas(invokes=(6, 4), fenced=1)
        parsed = parse_prometheus(render_prometheus(metrics_snapshot(
            replicas={"f": st})))
        up = parsed["nns_shard_group_up"]["samples"]
        down = [k for k, v in up.items() if v == 0.0]
        assert len(down) == 1
        assert 'group="1"' in down[0] and 'state="fenced"' in down[0]

    def test_unsharded_stats_emit_no_shard_family(self):
        st = _sharded_replicas(invokes=(3,))
        for r in st["replicas"]:
            for k in ("group", "devices", "shards"):
                r.pop(k)
        st.pop("group_size"); st.pop("leases")
        parsed = parse_prometheus(render_prometheus(metrics_snapshot(
            replicas={"f": st})))
        assert "nns_replica_invokes_total" in parsed
        assert not any(f.startswith("nns_shard_") for f in parsed)

    def test_shard_rows_appear_in_top_table(self):
        cur = parse_prometheus(render_prometheus(metrics_snapshot(
            replicas={"f": _sharded_replicas()})))
        lines = "\n".join(top_table({}, cur, 1.0))
        assert "nns_shard_group_invokes_total" in lines
        assert "nns_shard_group_up" in lines


class TestTopView:
    def test_counter_rates_and_gauges(self):
        p1 = parse_prometheus(render_prometheus(metrics_snapshot(
            admission=_admission(offered=100))))
        p2 = parse_prometheus(render_prometheus(metrics_snapshot(
            admission=_admission(offered=150))))
        lines = "\n".join(top_table(p1, p2, dt_s=2.0))
        # 50 more offered over 2s → 25.0/s
        m = re.search(r"nns_admission_offered_total\s+150\s+25\.0",
                      lines)
        assert m, lines
        assert "nns_admission_depth" in lines

    def test_histogram_families_stay_out_of_table(self):
        cur = parse_prometheus(render_prometheus(metrics_snapshot(
            tracer=_traced())))
        lines = "\n".join(top_table({}, cur, 1.0))
        assert "proctime_seconds_bucket" not in lines
