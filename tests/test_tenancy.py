"""Multi-tenant serving tests (ISSUE 13): weighted-fair admission,
per-class conservation, model multiplexing with LRU jit residency,
tenant-aware rebind, and the SLO-scaling controller.

The accounting contract these pin down: the two admission conservation
invariants hold EXACTLY per tenant class and summed across classes —
including under a noisy-neighbor flood, where the overage is shed from
the flooding class only (cause ``tenant_over_share``) and the victim's
goodput/p99 stay where a solo run put them. Multiplexing is correctness
-first: LRU eviction of a model's compiled entries is a counted
recompile on its next request, never a wrong answer.
"""

import itertools
import json
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.edge import protocol as P
from nnstreamer_tpu.edge.query import QueryServer
from nnstreamer_tpu.edge.wire import decode_buffer, encode_buffer
from nnstreamer_tpu.serving.pool import PooledQueryServer, proc_alive
from nnstreamer_tpu.serving.tenancy import (
    CLASS_META, INVALID_CLASS, TENANT_META, ModelResidency,
    ScalingController, TenantClass, TenantTable, validate_tenant_name)
from nnstreamer_tpu.serving.worker import WorkerSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.traffic.admission import (
    DEADLINE_META, AdmissionQueue)
from nnstreamer_tpu.traffic.loadgen import (
    _tenant_conservation_ok, noisy_neighbor_drill)

pytestmark = pytest.mark.tenant

_sid = itertools.count(7700)


@pytest.fixture(autouse=True)
def _clean_servers():
    yield
    QueryServer.reset_all()


def _buf(i, tenant=None):
    b = TensorBuffer.of(np.ones((8, 1), np.float32), pts=i)
    if tenant is not None:
        b = b.with_meta(**{TENANT_META: tenant})
    return b


def _table(**weights) -> TenantTable:
    return TenantTable([TenantClass(n, weight=w)
                        for n, w in weights.items()])


# -- tenant names / table -----------------------------------------------------

class TestTenantNames:
    def test_valid_charset(self):
        for name in ("a", "A-b_9", "x" * 64, "team-a", "0"):
            assert validate_tenant_name(name)

    def test_invalid_refused(self):
        for name in ("", "x" * 65, "a b", "a/b", "tenant!", "Ω", None,
                     42, "a\n"):
            assert not validate_tenant_name(name)

    def test_tenant_class_validates_eagerly(self):
        with pytest.raises(ValueError):
            TenantClass("bad name")
        with pytest.raises(ValueError):
            TenantClass("a", weight=0.0)
        with pytest.raises(ValueError):
            TenantClass("a", weight=float("nan"))
        with pytest.raises(ValueError):
            TenantClass("a", deadline_ms=0.0)
        with pytest.raises(ValueError):
            TenantClass("a", max_pending=0)


class TestTenantTable:
    def test_from_dict_and_routing(self):
        t = TenantTable.from_dict({
            "default": "team-a",
            "tenants": [
                {"name": "team-a", "weight": 2.0, "model": "m1"},
                {"name": "team-b", "model": "m2"},
                {"name": "team-c", "model": "m1"},
            ]})
        assert t.class_of("team-b").name == "team-b"
        # undeclared and missing tenants fall to the default class
        assert t.class_of("stranger").name == "team-a"
        assert t.class_of(None).name == "team-a"
        assert t.model_of("team-b") == "m2"
        assert t.model_of(None) == "m1"
        # distinct bound models, declaration order
        assert t.models() == ["m1", "m2"]
        # to_dict round-trips
        t2 = TenantTable.from_dict(t.to_dict())
        assert sorted(t2.names()) == sorted(t.names())

    def test_duplicate_tenant_rejected(self):
        with pytest.raises(ValueError):
            TenantTable([TenantClass("a"), TenantClass("a")])


# -- weighted-fair admission --------------------------------------------------

class TestWFQAdmission:
    def _queue(self, table, **kw):
        kw.setdefault("max_pending", 64)
        q = AdmissionQueue(**kw)
        q.set_tenants(table)
        return q

    def test_dequeue_follows_weights(self):
        q = self._queue(_table(a=3.0, b=1.0))
        for i in range(12):
            assert q.offer(_buf(i, "a")).admitted
        for i in range(12, 18):
            assert q.offer(_buf(i, "b")).admitted
        order = []
        for _ in range(12):
            item = q.get(timeout=1.0)
            order.append(item.meta[CLASS_META])
            q.note_replied(cls=item.meta[CLASS_META])
        # SFQ: over any backlogged prefix the service ratio tracks the
        # 3:1 weights (within one quantum)
        for k in (4, 8, 12):
            served_a = order[:k].count("a")
            assert abs(served_a - 3 * k / 4) <= 1, order
        assert _tenant_conservation_ok(q.counters())

    def test_class_stamped_and_replied_lands_on_class(self):
        q = self._queue(_table(a=1.0))
        assert q.offer(_buf(0, "a")).admitted
        item = q.get(timeout=1.0)
        assert item.meta[CLASS_META] == "a"
        c = q.counters()["classes"]["a"]
        assert c["inflight"] == 1 and c["depth"] == 0
        q.note_replied(cls="a")
        c = q.counters()["classes"]["a"]
        assert c["replied"] == 1 and c["inflight"] == 0
        assert _tenant_conservation_ok(q.counters())

    def test_bad_tenant_refused_and_charged_to_invalid_class(self):
        q = self._queue(_table(a=1.0))
        d = q.offer(_buf(0, "not a name!"))
        assert not d.admitted and d.cause == "bad_tenant"
        c = q.counters()
        inv = c["classes"][INVALID_CLASS]
        assert inv["rejected"] == {"bad_tenant": 1}
        assert inv["offered"] == 1 and inv["admitted"] == 0
        assert c["rejected"] == {"bad_tenant": 1}
        assert _tenant_conservation_ok(c)

    def test_undeclared_tenant_uses_default_class(self):
        q = self._queue(_table(a=1.0))
        assert q.offer(_buf(0, "stranger")).admitted
        assert q.counters()["classes"]["default"]["admitted"] == 1

    def test_over_share_sheds_own_class_only(self):
        # fair share with a=1, b=1 (+ implicit default) over
        # max_pending=6 is ceil(6/3)=2 per class
        q = self._queue(_table(a=1.0, b=1.0), max_pending=6,
                        shed_policy="reject-oldest")
        assert q.offer(_buf(0, "a")).admitted
        assert q.offer(_buf(1, "a")).admitted
        d = q.offer(_buf(2, "a"))     # over a's share: displace a's oldest
        assert d.admitted
        assert [v.pts for v in d.victims] == [0]
        assert d.victim_cause == "tenant_over_share"
        c = q.counters()
        assert c["classes"]["a"]["shed"] == {"tenant_over_share": 1}
        assert c["classes"]["a"]["depth"] == 2
        # b is untouched and still has its full share
        assert q.offer(_buf(3, "b")).admitted
        assert c["classes"]["b"]["shed"] == {}
        assert _tenant_conservation_ok(q.counters())

    def test_over_share_refused_under_reject_newest(self):
        q = self._queue(_table(a=1.0, b=1.0), max_pending=6,
                        shed_policy="reject-newest")
        assert q.offer(_buf(0, "a")).admitted
        assert q.offer(_buf(1, "a")).admitted
        d = q.offer(_buf(2, "a"))
        assert not d.admitted and d.cause == "tenant_over_share"
        c = q.counters()
        assert c["classes"]["a"]["rejected"] == {"tenant_over_share": 1}
        assert _tenant_conservation_ok(c)

    def test_global_full_never_displaces_another_class(self):
        # explicit per-class bounds above the global bound: the global
        # limit is what refuses, and it must NOT shed a's entries to
        # make room for b
        t = TenantTable([TenantClass("a", max_pending=5),
                         TenantClass("b", max_pending=5)])
        q = self._queue(t, max_pending=2, shed_policy="reject-oldest")
        assert q.offer(_buf(0, "a")).admitted
        assert q.offer(_buf(1, "a")).admitted
        d = q.offer(_buf(2, "b"))
        assert not d.admitted and d.cause == "queue_full"
        assert not d.victims
        c = q.counters()
        assert c["classes"]["a"]["depth"] == 2
        assert c["classes"]["a"]["shed"] == {}
        assert c["classes"]["b"]["rejected"] == {"queue_full": 1}
        assert _tenant_conservation_ok(c)

    def test_class_deadline_default_applies(self):
        t = TenantTable([TenantClass("a", deadline_ms=1.0)])
        q = self._queue(t, shed_policy="deadline-drop")
        assert q.offer(_buf(0, "a")).admitted
        time.sleep(0.01)
        d = q.offer(_buf(1, "a"))     # purge on next offer
        assert d.admitted
        assert [v.pts for v in d.victims] == [0]
        assert d.victim_cause == "deadline"
        c = q.counters()
        assert c["classes"]["a"]["shed"] == {"deadline": 1}
        assert _tenant_conservation_ok(c)

    def test_sentinel_bypasses_admission(self):
        q = self._queue(_table(a=1.0))
        q.put_nowait(None)
        assert q.offer(_buf(0, "a")).admitted
        assert q.get(timeout=1.0) is None       # sentinel first
        assert q.get(timeout=1.0).pts == 0
        c = q.counters()
        assert c["offered"] == 1 and c["admitted"] == 1


# -- configure() mid-stream policy change (regression) ------------------------

class TestConfigurePolicyChange:
    def test_switch_to_deadline_drop_purges_expired_legacy(self):
        q = AdmissionQueue(max_pending=8, shed_policy="reject-newest")
        for i in range(3):
            b = _buf(i).with_meta(**{DEADLINE_META: 1.0})
            assert q.offer(b).admitted
        assert q.offer(_buf(3)).admitted        # no budget: never purged
        time.sleep(0.01)
        victims = q.configure(shed_policy="deadline-drop")
        assert sorted(v.pts for v in victims) == [0, 1, 2]
        c = q.counters()
        assert c["shed"] == {"deadline": 3}
        assert c["depth"] == 1
        assert c["offered"] == c["admitted"] + sum(c["rejected"].values())
        assert c["admitted"] == c["replied"] + sum(c["shed"].values()) \
            + c["depth"] + c["inflight"]

    def test_same_policy_reconfigure_is_noop(self):
        q = AdmissionQueue(max_pending=8, shed_policy="deadline-drop")
        b = _buf(0).with_meta(**{DEADLINE_META: 1.0})
        assert q.offer(b).admitted
        time.sleep(0.01)
        # same policy: no snapshot re-evaluation, no victims — expiry
        # still lands on the next offer() as usual
        assert q.configure(shed_policy="deadline-drop") == []
        assert q.configure(max_pending=16) == []
        assert q.counters()["depth"] == 1

    def test_switch_purges_tenant_classes_too(self):
        t = TenantTable([TenantClass("a", deadline_ms=1.0),
                         TenantClass("b")])
        q = AdmissionQueue(max_pending=16, shed_policy="reject-newest")
        q.set_tenants(t)
        assert q.offer(_buf(0, "a")).admitted
        assert q.offer(_buf(1, "b")).admitted   # no deadline: survives
        time.sleep(0.01)
        victims = q.configure(shed_policy="deadline-drop")
        assert [v.pts for v in victims] == [0]
        assert victims[0].meta[CLASS_META] == "a"
        c = q.counters()
        assert c["classes"]["a"]["shed"] == {"deadline": 1}
        assert c["classes"]["b"]["depth"] == 1
        assert _tenant_conservation_ok(c)


# -- model residency (LRU) ----------------------------------------------------

class _FakeBackend:
    """Stands in for XLABackend's residency hooks; release frees its
    bytes too, modelling a backend whose eviction relieves pressure."""

    def __init__(self, nbytes=100):
        self.entries = 0
        self.nbytes = nbytes
        self._full_bytes = nbytes
        self.released = 0

    def compile(self, n=2):
        self.entries = n
        self.nbytes = self._full_bytes

    def jit_cache_size(self):
        return self.entries

    def resident_bytes(self):
        return self.nbytes

    def release_compiled(self):
        n, self.entries = self.entries, 0
        self.nbytes = 0
        self.released += 1
        return n


class TestModelResidency:
    def test_lru_evicts_coldest_not_current(self):
        r = ModelResidency(max_models=2)
        backends = {}
        for name in ("a", "b", "c"):
            backends[name] = _FakeBackend()
            r.register(name, backends[name])
        backends["a"].compile()
        r.touch("a")
        backends["b"].compile()
        r.touch("b")
        backends["c"].compile()
        evicted = r.touch("c")        # 3 live > 2: coldest (a) goes
        assert evicted == ["a"]
        assert backends["a"].entries == 0 and backends["a"].released == 1
        assert backends["b"].entries > 0 and backends["c"].entries > 0
        st = r.stats()
        assert st["jit_evictions"] == 1 and st["entries_evicted"] == 2
        # "recompile" a: now b is coldest
        backends["a"].compile()
        assert r.touch("a") == ["b"]
        assert r.stats()["jit_evictions"] == 2

    def test_current_model_never_evicted(self):
        r = ModelResidency(max_models=1)
        a, b = _FakeBackend(), _FakeBackend()
        r.register("a", a)
        r.register("b", b)
        a.compile()
        b.compile()
        assert r.touch("b") == ["a"]
        # even at bound 1, the model being served survives
        assert b.entries > 0

    def test_bytes_bound(self):
        r = ModelResidency(max_bytes=250)
        a, b, c = (_FakeBackend(nbytes=100) for _ in range(3))
        for name, be in (("a", a), ("b", b), ("c", c)):
            r.register(name, be)
            be.compile()
        assert r.touch("c") == ["a"]  # 300 bytes > 250: shed coldest

    def test_unbounded_never_evicts(self):
        r = ModelResidency()
        bs = [_FakeBackend() for _ in range(5)]
        for i, be in enumerate(bs):
            r.register(f"m{i}", be)
            be.compile()
            assert r.touch(f"m{i}") == []
        assert r.stats()["jit_evictions"] == 0


# -- in-process multiplex service: routing + evict->recompile -----------------

_MUX_TENANTS = {
    "default": "team-a",
    "tenants": [
        {"name": "team-a", "weight": 2.0, "model": "probe_scale"},
        {"name": "team-b", "model": "probe_negate"},
        {"name": "team-c", "model": "probe_offset"},
    ]}

_X = np.arange(8, dtype=np.float32).reshape(8, 1)

#: tenant -> expected output for input _X (probe model arithmetic)
_EXPECT = {
    "team-a": _X * 2.0,
    "team-b": -_X,
    "team-c": _X + 10.0,
}


def _mux_service(**spec_kw):
    from nnstreamer_tpu.serving.worker import _MultiplexService

    spec = WorkerSpec(kind="multiplex", dims="8:1", types="float32",
                      tenants=_MUX_TENANTS, **spec_kw)
    return _MultiplexService(spec)


def _serve_one(svc, i, tenant):
    out = []
    buf = _buf(i, tenant).with_tensors((_X,), pts=i)
    svc.serve(i, encode_buffer(buf), lambda msg: out.append(msg))
    tag, rid, payload = out[0]
    assert tag == "res" and rid == i
    res, _ = decode_buffer(payload)
    return res


class TestMultiplexService:
    def test_routes_by_tenant_known_answers(self):
        svc = _mux_service()
        try:
            for i, (tenant, want) in enumerate(_EXPECT.items()):
                res = _serve_one(svc, i, tenant)
                np.testing.assert_allclose(res.tensors[0], want)
            # unknown/missing tenant falls to the default class model
            res = _serve_one(svc, 10, None)
            np.testing.assert_allclose(res.tensors[0], _EXPECT["team-a"])
            assert svc.residency_stats()["jit_evictions"] == 0
        finally:
            svc.close()

    def test_eviction_is_counted_recompile_never_wrong(self):
        svc = _mux_service(resident_models=1)
        try:
            i = 0
            for _round in range(2):   # second round re-serves evicted
                for tenant, want in _EXPECT.items():
                    res = _serve_one(svc, i, tenant)
                    np.testing.assert_allclose(res.tensors[0], want)
                    i += 1
            st = svc.residency_stats()
            # each model switch past the bound evicted the previous one
            assert st["jit_evictions"] >= 3
            assert st["invokes_by_model"] == {
                "probe_scale": 2, "probe_negate": 2, "probe_offset": 2}
        finally:
            svc.close()


# -- multiplex pool e2e: wire round trip, hot swap, rebind --------------------

class _Client:
    """Minimal query-wire client that keeps decoded RESULT buffers
    (loadgen discards payloads; known-answer tests need them)."""

    def __init__(self, port, dims="8:1", types="float32"):
        self.results = {}
        self.busy = {}
        self._evt = threading.Event()
        self._hello = threading.Event()
        self._want = 0
        self._lock = threading.Lock()
        self.c = P.MsgClient("127.0.0.1", port, on_message=self._on)
        self.c.send(P.T_HELLO,
                    json.dumps({"dims": dims, "types": types}).encode())
        assert self._hello.wait(10)

    def _on(self, mtype, payload):
        if mtype in (P.T_HELLO_ACK, P.T_HELLO_NAK):
            self._hello.set()
            return
        with self._lock:
            if mtype == P.T_RESULT:
                buf, _ = decode_buffer(payload)
                self.results[int(buf.pts)] = buf
            elif mtype == P.T_BUSY:
                info = json.loads(payload.decode())
                if info.get("pts") is not None:
                    self.busy[int(info["pts"])] = info
            if len(self.results) + len(self.busy) >= self._want:
                self._evt.set()

    def ask(self, frames):
        with self._lock:
            self._want = len(self.results) + len(self.busy) + len(frames)
            self._evt.clear()
        for b in frames:
            self.c.send(P.T_DATA, encode_buffer(b))
        assert self._evt.wait(30), "pool did not answer in time"

    def close(self):
        self.c.close()


def _mux_pool(workers=2, **kw):
    table = TenantTable.from_dict(_MUX_TENANTS)
    spec = WorkerSpec(kind="multiplex", dims="8:1", types="float32",
                      tenants=table.to_dict(), **kw)
    return PooledQueryServer(spec, workers=workers, sid=next(_sid),
                             tenants=table)


def _tenant_frame(i, tenant):
    return _buf(i, tenant).with_tensors((_X,), pts=i)


class TestMultiplexPool:
    def test_one_pool_serves_three_models_routed_by_tenant(self):
        pqs = _mux_pool()
        try:
            cli = _Client(pqs.port)
            try:
                frames, want = [], {}
                i = 0
                for _ in range(3):
                    for tenant, exp in _EXPECT.items():
                        frames.append(_tenant_frame(i, tenant))
                        want[i] = exp
                        i += 1
                cli.ask(frames)
                assert not cli.busy
                for pts, exp in want.items():
                    np.testing.assert_allclose(
                        cli.results[pts].tensors[0], exp)
                c = pqs.admission_counters()
                assert c["classes"]["team-a"]["replied"] == 3
                assert c["classes"]["team-b"]["replied"] == 3
                assert c["classes"]["team-c"]["replied"] == 3
                assert _tenant_conservation_ok(c)
            finally:
                cli.close()
        finally:
            pids = pqs.pool.all_pids_ever()
            pqs.close()
        assert pids and not any(proc_alive(p) for p in pids)

    def test_hot_swap_one_model_leaves_others_unperturbed(self):
        # preload recipe: each spawned child can lazily build
        # probe_scale@1 (scale=3) from the zoo on swap commit
        pqs = _mux_pool(
            preload=(("probe_scale", 1, "zoo://probe_scale?scale=3.0"),))
        try:
            cli = _Client(pqs.port)
            try:
                cli.ask([_tenant_frame(0, "team-a")])
                np.testing.assert_allclose(
                    cli.results[0].tensors[0], _X * 2.0)
                rep = pqs.swap("probe_scale", 1)
                assert rep["ok"], rep
                assert pqs.pool.epoch == 1      # all-or-none bump
                assert all(w["prepare_ok"] and w["commit_ok"]
                           for w in rep["workers"].values())
                cli.ask([_tenant_frame(i, t) for i, t in
                         ((1, "team-a"), (2, "team-b"), (3, "team-c"))])
                # swapped tenant sees @1; the other tenants' models are
                # untouched by the store epoch flip
                np.testing.assert_allclose(
                    cli.results[1].tensors[0], _X * 3.0)
                np.testing.assert_allclose(
                    cli.results[2].tensors[0], -_X)
                np.testing.assert_allclose(
                    cli.results[3].tensors[0], _X + 10.0)
            finally:
                cli.close()
        finally:
            pqs.close()

    def test_swap_unknown_version_aborts_all(self):
        pqs = _mux_pool()
        try:
            rep = pqs.swap("probe_scale", 7)    # no such version
            assert not rep["ok"]
            assert pqs.pool.epoch == 0          # epoch did not move
            cli = _Client(pqs.port)
            try:
                cli.ask([_tenant_frame(0, "team-b")])
                np.testing.assert_allclose(
                    cli.results[0].tensors[0], -_X)
            finally:
                cli.close()
        finally:
            pqs.close()

    def test_rebind_two_phase_epoch_and_bindings(self):
        pqs = _mux_pool()
        try:
            rep = pqs.rebind({0: "probe_scale", 1: "probe_negate"})
            assert rep["ok"], rep
            assert pqs.pool.epoch == 1
            assert pqs.pool.bindings() == {0: "probe_scale",
                                           1: "probe_negate"}
            # unknown model: every worker aborts, nothing changes
            rep = pqs.rebind({0: "nope"})
            assert not rep["ok"]
            assert pqs.pool.epoch == 1
            assert pqs.pool.bindings() == {0: "probe_scale",
                                           1: "probe_negate"}
            # bound workers are preferred for their model's tenants,
            # and the pool still answers everyone correctly
            cli = _Client(pqs.port)
            try:
                cli.ask([_tenant_frame(i, t) for i, t in
                         ((0, "team-a"), (1, "team-b"), (2, "team-c"))])
                np.testing.assert_allclose(
                    cli.results[0].tensors[0], _X * 2.0)
                np.testing.assert_allclose(
                    cli.results[1].tensors[0], -_X)
            finally:
                cli.close()
        finally:
            pqs.close()


# -- scaling controller -------------------------------------------------------

class _StubPool:
    def __init__(self, n=4):
        self.n = n
        self._b = {i: None for i in range(n)}
        self.calls = []

    @property
    def size(self):
        return self.n

    def bindings(self):
        return dict(self._b)

    def rebind(self, mapping, **kw):
        self.calls.append(dict(mapping))
        self._b.update(mapping)
        return {"ok": True}


class _StubTracer:
    def __init__(self, rates):
        self.rates = rates

    def tenant_summary(self):
        return {t: {"count": 10, "rate_hz": r, "p50_ms": 1.0,
                    "p99_ms": 2.0}
                for t, r in self.rates.items()}


class TestScalingController:
    def _ctrl(self, rates, n=4):
        table = TenantTable.from_dict({"tenants": [
            {"name": "a", "model": "m1"},
            {"name": "b", "model": "m2"}]})
        pool = _StubPool(n)
        ctrl = ScalingController(pool, table,
                                 _StubTracer(rates), interval_s=999.0)
        return ctrl, pool

    def _counts(self, pool):
        counts = {}
        for m in pool.bindings().values():
            counts[m] = counts.get(m, 0) + 1
        return counts

    def test_tick_allocates_slots_by_traffic(self):
        # m1 carries 3x m2's rate; 4 slots, floor 1 each -> 3:1
        ctrl, pool = self._ctrl({"a": 30.0, "b": 10.0})
        assert ctrl.tick()
        assert self._counts(pool) == {"m1": 3, "m2": 1}
        st = ctrl.stats()
        assert st["decisions"] == 1 and st["rebinds"] == 1

    def test_steady_state_does_not_rebind(self):
        ctrl, pool = self._ctrl({"a": 30.0, "b": 10.0})
        assert ctrl.tick()
        calls = len(pool.calls)
        ctrl.tick()                    # same rates: plan == current
        assert len(pool.calls) == calls
        assert ctrl.stats()["rebinds"] == 1

    def test_traffic_shift_rebinds(self):
        ctrl, pool = self._ctrl({"a": 30.0, "b": 10.0})
        ctrl.tick()
        ctrl.tracer = _StubTracer({"a": 5.0, "b": 50.0})
        assert ctrl.tick()
        assert self._counts(pool) == {"m1": 1, "m2": 3}

    def test_no_demand_no_decision(self):
        ctrl, pool = self._ctrl({})
        assert not ctrl.tick()
        assert not pool.calls

    def test_start_stop_thread(self):
        ctrl, _ = self._ctrl({"a": 1.0})
        ctrl.start()
        t = ctrl._thread
        try:
            assert t is not None and t.daemon
        finally:
            ctrl.stop()
        assert not t.is_alive()


# -- noisy-neighbor acceptance drill ------------------------------------------

class TestNoisyNeighbor:
    def test_victim_isolated_from_flooding_tenant(self):
        out = noisy_neighbor_drill(
            victim_x=0.5, flood_x=3.0, n_victim=80,
            workers=2, service_ms=8.0, max_pending=24, seed=3)
        cont = out["contested"]
        v = cont["groups"]["victim"]
        f = cont["groups"]["flood"]
        # nothing lost anywhere, invariants exact per class and summed
        assert out["zero_lost"]
        assert out["conserved"]
        # victim keeps its service: everything completes, p99 within
        # its deadline budget, goodput >= 0.9x its solo run
        assert v["rejected"] == 0 and v["lost"] == 0
        assert v["completed"] == v["offered"]
        assert out["victim_p99_ms"] <= out["victim_p99_budget_ms"]
        assert out["victim_goodput_ratio"] >= 0.9, out
        # the overage is shed from the flooder, typed tenant_over_share
        assert f["rejected"] > 0
        assert set(f["busy_causes"]) == {"tenant_over_share"}
        cc = cont["admission"]["classes"]
        shed_f = cc["flood"]["shed"].get("tenant_over_share", 0)
        rej_f = cc["flood"]["rejected"].get("tenant_over_share", 0)
        assert shed_f + rej_f == f["rejected"]
        # the victim class was never shed or refused
        assert cc["victim"]["shed"] == {} and cc["victim"]["rejected"] == {}
