"""Paged Pallas attention kernels vs the XLA paged reference.

Tier-1's half of the ISSUE-16 acceptance gate: the Pallas paged decode
and prefill kernels (backends/pallas_paged.py) run here in interpret
mode and must match `llm/paged_model.py`'s XLA reference to <= 1e-5 on
logits across the block-table shapes serving actually produces —
non-contiguous tables (holes), staggered per-row depths, pow2-padded
batch rows writing to the scratch block, and multi-chunk prefill over
previously written pool blocks. The chip-only compiled run is the
`pallas`-marked test at the bottom (skipped off-TPU).
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from nnstreamer_tpu.backends import pallas_paged  # noqa: E402
from nnstreamer_tpu.backends.pallas_paged import (  # noqa: E402
    paged_flash_decode_step, paged_flash_prefill_chunk)
from nnstreamer_tpu.llm.engine import LLMEngine  # noqa: E402
from nnstreamer_tpu.llm.paged_model import (  # noqa: E402
    paged_decode_step, paged_prefill, paged_prefill_chunk)
from nnstreamer_tpu.models.transformer import init_params  # noqa: E402

TOL = 1e-5
L, NB, BS, NKV, HD, MB = 2, 16, 8, 2, 16, 4     # pool geometry


@pytest.fixture(scope="module")
def params():
    return init_params(vocab=61, d_model=64, n_layers=L, n_heads=4,
                       n_kv_heads=NKV, seed=3)


def _pools():
    z = jnp.zeros((L, NB, BS, NKV, HD), jnp.float32)
    return z, z


def _targets(n, blocks, s_b, pos0=0):
    """Per-position (block, offset) scatter targets; padding → scratch."""
    bi = np.zeros(s_b, np.int32)
    bo = ((pos0 + np.arange(s_b)) % BS).astype(np.int32)
    for j in range(n):
        bi[j] = blocks[(pos0 + j) // BS]
    return jnp.asarray(bi), jnp.asarray(bo)


def _table(blocks):
    t = np.zeros(MB, np.int32)
    t[:len(blocks)] = blocks
    return jnp.asarray(t)


def _prompt(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(1, 60, size=n).astype(np.int32)


def _prefill_ref(params, prompt, blocks, kp, vp):
    n = len(prompt)
    s_b = max(8, 1 << (n - 1).bit_length())
    ids = jnp.asarray(np.pad(prompt, (0, s_b - n))[None, :], jnp.int32)
    bi, bo = _targets(n, blocks, s_b)
    return paged_prefill(params, ids, bi, bo, kp, vp, n - 1)


def test_available_in_interpret_mode():
    assert pallas_paged.available()


# -- decode parity -----------------------------------------------------------

def test_decode_parity_holey_staggered_padded(params):
    """The full serving batch shape at once: two live rows at different
    depths, non-contiguous (hole-y) block tables, and pow2 padding rows
    whose table is all scratch."""
    kp, vp = _pools()
    # seq0: 12 tokens over blocks [3, 9] (hole); seq1: 5 over [7]
    _, kp, vp = _prefill_ref(params, _prompt(12, 1), [3, 9], kp, vp)
    _, kp, vp = _prefill_ref(params, _prompt(5, 2), [7], kp, vp)
    tabs = np.zeros((4, MB), np.int32)
    tabs[0, :2] = [3, 9]
    tabs[1, 0] = 7
    tabs = jnp.asarray(tabs)
    cur = jnp.asarray([17, 23, 0, 0], jnp.int32)
    pos = jnp.asarray([12, 5, 0, 0], jnp.int32)
    ref, kr, vr = paged_decode_step(params, cur, tabs, pos, kp, vp)
    fl, kf, vf = paged_flash_decode_step(params, cur, tabs, pos, kp, vp)
    assert float(jnp.max(jnp.abs(ref[:2] - fl[:2]))) <= TOL
    # the write-through halves are identical (live blocks only; the
    # scratch block absorbs different padding garbage by design)
    assert float(jnp.max(jnp.abs(kr[:, 1:] - kf[:, 1:]))) <= TOL
    assert float(jnp.max(jnp.abs(vr[:, 1:] - vf[:, 1:]))) <= TOL
    # and a second, deeper step over the updated pools still agrees
    # (seq0 crosses into its second block's tail)
    cur2 = jnp.asarray([9, 11, 0, 0], jnp.int32)
    pos2 = pos + jnp.asarray([1, 1, 0, 0], jnp.int32)
    ref2 = paged_decode_step(params, cur2, tabs, pos2, kr, vr)[0]
    fl2 = paged_flash_decode_step(params, cur2, tabs, pos2, kf, vf)[0]
    assert float(jnp.max(jnp.abs(ref2[:2] - fl2[:2]))) <= TOL


def test_decode_parity_row_at_block_boundary(params):
    """pos exactly at a block edge: the write lands in a fresh block
    while attention spans the full previous one — the off-by-one spot
    for the inclusive <= pos mask."""
    kp, vp = _pools()
    _, kp, vp = _prefill_ref(params, _prompt(BS, 4), [5], kp, vp)
    tabs = jnp.asarray(np.array([[5, 11, 0, 0]], np.int32))
    cur = jnp.asarray([7], jnp.int32)
    pos = jnp.asarray([BS], jnp.int32)          # first slot of block 11
    ref = paged_decode_step(params, cur, tabs, pos, kp, vp)[0]
    fl = paged_flash_decode_step(params, cur, tabs, pos, kp, vp)[0]
    assert float(jnp.max(jnp.abs(ref - fl))) <= TOL


# -- prefill / chunk parity --------------------------------------------------

def test_chunk_matches_full_prefill_reference(params):
    """One chunk covering the whole prompt == the apply_seq_kv prefill
    (logits AND pool contents) — the bridge that lets the chunk family
    replace whole-prompt prefill for pallas/quantized stores."""
    prompt = _prompt(12, 5)
    n, s_b = 12, 16
    ids = jnp.asarray(np.pad(prompt, (0, s_b - n))[None, :], jnp.int32)
    bi, bo = _targets(n, [3, 9], s_b)
    kp, vp = _pools()
    ref, kr, vr = paged_prefill(params, ids, bi, bo, kp, vp, n - 1)
    kp, vp = _pools()
    chk, kc, vc = paged_prefill_chunk(
        params, ids, jnp.int32(0), bi, bo, _table([3, 9]), kp, vp, n - 1)
    assert float(jnp.max(jnp.abs(ref - chk))) <= TOL
    assert float(jnp.max(jnp.abs(kr[:, 1:] - kc[:, 1:]))) <= TOL
    kp, vp = _pools()
    fl, kf, vf = paged_flash_prefill_chunk(
        params, ids, jnp.int32(0), bi, bo, _table([3, 9]), kp, vp, n - 1)
    assert float(jnp.max(jnp.abs(ref - fl))) <= TOL
    assert float(jnp.max(jnp.abs(kr[:, 1:] - kf[:, 1:]))) <= TOL


@pytest.mark.parametrize("flavor", ["xla", "pallas"])
def test_chunked_equals_unchunked(params, flavor):
    """Three 8-token chunks == one 24-token prefill: later chunks
    attend earlier chunks' pool KV through the table, and the causal
    mask is positional, not chunk-local."""
    fn = paged_prefill_chunk if flavor == "xla" \
        else paged_flash_prefill_chunk
    prompt = _prompt(24, 6)
    blocks = [2, 6, 13]                         # holes on purpose
    tab = _table(blocks)
    kp, vp = _pools()
    ref, _, _ = _prefill_ref(params, prompt, blocks, kp, vp)
    kp, vp = _pools()
    out = None
    for c0 in range(0, 24, 8):
        seg = prompt[c0:c0 + 8]
        ids = jnp.asarray(seg[None, :], jnp.int32)
        bi, bo = _targets(len(seg), blocks, 8, pos0=c0)
        out, kp, vp = fn(params, ids, jnp.int32(c0), bi, bo, tab,
                         kp, vp, len(seg) - 1)
    assert float(jnp.max(jnp.abs(ref - out))) <= TOL


def test_chunk_padded_tail_hits_scratch_only(params):
    """A short final chunk padded to its bucket must leave every live
    block untouched beyond the real tokens — padding rows write to the
    scratch block only."""
    prompt = _prompt(3, 7)
    ids = jnp.asarray(np.pad(prompt, (0, 5))[None, :], jnp.int32)
    bi, bo = _targets(3, [4], 8)
    kp, vp = _pools()
    _, kp, vp = paged_flash_prefill_chunk(
        params, ids, jnp.int32(0), bi, bo, _table([4]), kp, vp, 2)
    # block 4 slots beyond position 2 stay zero
    assert float(jnp.max(jnp.abs(kp[:, 4, 3:]))) == 0.0
    # every other non-scratch block is untouched
    live = np.ones(NB, bool)
    live[[0, 4]] = False
    assert float(jnp.max(jnp.abs(kp[:, live]))) == 0.0


# -- quantized (W8A8) cross-kernel parity ------------------------------------

def test_quantized_chunk_and_decode_parity(params):
    from nnstreamer_tpu.models.quant import quantize_transformer

    qp = quantize_transformer(params)
    prompt = _prompt(10, 8)
    ids = jnp.asarray(np.pad(prompt, (0, 6))[None, :], jnp.int32)
    bi, bo = _targets(10, [3, 8], 16)
    tab = _table([3, 8])
    kp, vp = _pools()
    ref, kr, vr = paged_prefill_chunk(
        qp, ids, jnp.int32(0), bi, bo, tab, kp, vp, 9)
    kp, vp = _pools()
    fl, kf, vf = paged_flash_prefill_chunk(
        qp, ids, jnp.int32(0), bi, bo, tab, kp, vp, 9)
    assert float(jnp.max(jnp.abs(ref - fl))) <= TOL
    tabs = jnp.asarray(np.array([[3, 8, 0, 0]], np.int32))
    cur = jnp.asarray([21], jnp.int32)
    pos = jnp.asarray([10], jnp.int32)
    refd = paged_decode_step(qp, cur, tabs, pos, kr, vr)[0]
    fld = paged_flash_decode_step(qp, cur, tabs, pos, kf, vf)[0]
    assert float(jnp.max(jnp.abs(refd - fld))) <= TOL


# -- engine-level: kernel knob, fallback, chunked serving --------------------

def _run_engine(params, prompts, **kw):
    eng = LLMEngine(dict(params), n_heads=4, block_size=8,
                    num_blocks=64, max_batch=4, max_len=128, **kw)
    reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.drain()
    return [tuple(r.tokens) for r in reqs], eng


def test_engine_pallas_equals_xla_tokens(params):
    prompts = [_prompt(9, 11), _prompt(21, 12), _prompt(4, 13)]
    base, _ = _run_engine(params, prompts)
    pal, eng = _run_engine(params, prompts, paged_kernel="pallas")
    assert base == pal
    ex = eng.stats()["executor"]
    assert ex["paged_kernel"] == "pallas"
    assert ex["kernel_invokes"]["pallas"] > 0
    assert ex["kernel_fallback"] == 0


def test_engine_chunked_prefill_equals_whole(params):
    prompts = [_prompt(40, 14), _prompt(7, 15)]
    base, _ = _run_engine(params, prompts)
    for kern in ("xla", "pallas"):
        chunked, eng = _run_engine(params, prompts, prefill_chunk=16,
                                   paged_kernel=kern)
        assert chunked == base, kern
        assert eng.stats()["executor"]["chunk_prefills"] >= 3


def test_engine_chunked_prefill_interleaves_decode(params):
    """The ITL-bounding structure itself: while a long prompt is mid
    chunk-prefill, every engine step still advances the live decode
    batch — the long admit never stalls token production."""
    eng = LLMEngine(dict(params), n_heads=4, block_size=8,
                    num_blocks=64, max_batch=4, max_len=128,
                    prefill_chunk=8)
    short = eng.submit(_prompt(4, 16), max_new_tokens=32)
    eng.step()                       # short admits + first token
    assert len(short.tokens) >= 1
    long_req = eng.submit(_prompt(48, 17), max_new_tokens=4)
    grew = []
    while long_req.state != "active" and eng.has_work:
        before = len(short.tokens)
        eng.step()
        grew.append(len(short.tokens) > before)
        assert long_req.state in ("prefilling", "active")
    # every chunk step also produced a decode token for the short req
    assert grew and all(grew)
    assert eng.executor.chunk_prefills >= 48 // 8
    eng.drain()
    assert long_req.finish_reason is not None


def test_engine_unavailable_pallas_counts_fallback(params, monkeypatch):
    from nnstreamer_tpu.backends import pallas_paged as pp

    monkeypatch.setattr(pp, "available", lambda: False)
    eng = LLMEngine(dict(params), n_heads=4, block_size=8,
                    num_blocks=32, max_batch=2, max_len=64,
                    paged_kernel="pallas")
    eng.submit(_prompt(5, 18), max_new_tokens=3)
    eng.drain()
    ex = eng.stats()["executor"]
    assert ex["paged_kernel"] == "xla"           # served anyway
    assert ex["kernel_fallback"] == 1
    assert ex["kernel_invokes"]["xla"] > 0
    assert ex["kernel_invokes"]["pallas"] == 0


def test_step_batches_prefill_syncs(params):
    """Satellite fix: a step admitting many requests resolves their
    logits with ONE forced device_sync (plus one for the decode batch),
    not one per admission."""
    from nnstreamer_tpu.runtime.sync import forced_sync_count

    eng = LLMEngine(dict(params), n_heads=4, block_size=8,
                    num_blocks=64, max_batch=4, max_len=64)
    for i in range(4):
        eng.submit(_prompt(5 + i, 20 + i), max_new_tokens=4)
    # absorb compile-time warm syncs by pre-compiling the buckets
    eng.prewarm(16)
    n0 = forced_sync_count()
    eng.step()                       # 4 admissions + 1 decode batch
    assert forced_sync_count() - n0 == 2
    n1 = forced_sync_count()
    eng.step()                       # steady state: decode only
    assert forced_sync_count() - n1 == 1
    eng.drain()


# -- metrics surface ---------------------------------------------------------

def test_llm_kernel_metrics_render(params):
    from nnstreamer_tpu.serving.metrics import (
        metrics_snapshot, parse_prometheus, render_prometheus)

    _, eng = _run_engine(params, [_prompt(6, 30)],
                         paged_kernel="pallas")
    text = render_prometheus(metrics_snapshot(
        llm={"llm0": eng.stats()}))
    fams = parse_prometheus(text)
    inv = fams["nns_llm_kernel_invokes_total"]
    assert inv["type"] == "counter"
    pallas_row = 'nns_llm_kernel_invokes_total' \
        '{element="llm0",kernel="pallas"}'
    assert inv["samples"][pallas_row] > 0
    assert fams["nns_llm_kernel_fallback_total"]["samples"][
        'nns_llm_kernel_fallback_total{element="llm0"}'] == 0
    info = fams["nns_llm_paged_kernel_info"]["samples"]
    assert info[
        'nns_llm_paged_kernel_info{element="llm0",kernel="pallas"}'] \
        == 1.0


def test_tracer_kernel_spans(params):
    from nnstreamer_tpu.runtime.tracing import Tracer

    tr = Tracer()
    eng = LLMEngine(dict(params), n_heads=4, block_size=8,
                    num_blocks=32, max_batch=2, max_len=64,
                    paged_kernel="pallas", tracer=tr)
    eng.submit(_prompt(5, 31), max_new_tokens=3)
    eng.drain()
    spans = tr.kernel_spans()
    assert spans.get(("llm", "pallas"), 0) > 0


# -- chip-only compiled run --------------------------------------------------

@pytest.mark.pallas
def test_compiled_pallas_on_tpu(params):
    """The same decode parity case, compiled for real (not interpret).
    Only meaningful where `jax.default_backend() == "tpu"`."""
    if jax.default_backend() != "tpu":
        pytest.skip("requires a TPU (interpret-mode twin runs in tier-1)")
    kp, vp = _pools()
    _, kp, vp = _prefill_ref(params, _prompt(12, 1), [3, 9], kp, vp)
    tabs = jnp.asarray(np.array([[3, 9, 0, 0]], np.int32))
    cur = jnp.asarray([17], jnp.int32)
    pos = jnp.asarray([12], jnp.int32)
    ref = paged_decode_step(params, cur, tabs, pos, kp, vp)[0]
    fl = paged_flash_decode_step(params, cur, tabs, pos, kp, vp)[0]
    assert float(jnp.max(jnp.abs(ref - fl))) <= 5e-5
