"""W8A8 quantized transformer path (models/quant.py).

Accuracy contract vs the float path; the perf reality (bf16 stays the
perf path at d_model~1024 on this backend) is documented in the module
docstring and PARITY — these tests pin the *correctness* claims."""

import numpy as np

from nnstreamer_tpu.models import transformer as T
from nnstreamer_tpu.models.quant import (
    apply_seq_w8a8,
    quantize_transformer,
    quantize_weight,
    w8a8_matmul,
)


def test_quantize_weight_roundtrip():
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    w = rng.normal(0, 0.5, (64, 96)).astype(np.float32)
    q, s = quantize_weight(jnp.asarray(w))
    assert q.dtype == jnp.int8 and s.shape == (1, 96)
    deq = np.asarray(q, np.float32) * np.asarray(s)
    # per-column max error bounded by half a quantization step
    step = np.asarray(s)[0]
    assert (np.abs(deq - w).max(axis=0) <= step * 0.5 + 1e-7).all()


def test_w8a8_matmul_tracks_float():
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    x = rng.normal(0, 1, (4, 32, 128)).astype(np.float32)
    w = rng.normal(0, 0.2, (128, 256)).astype(np.float32)
    q, s = quantize_weight(jnp.asarray(w))
    got = np.asarray(w8a8_matmul(jnp.asarray(x), q, s))
    ref = x @ w
    denom = np.abs(ref).max()
    assert np.abs(got - ref).max() / denom < 0.02


def test_apply_seq_w8a8_tracks_float_forward():
    import jax
    import jax.numpy as jnp

    d, H, L, V, B, S = 64, 4, 2, 64, 2, 64
    params = T.init_params(d_model=d, n_heads=H, n_layers=L, vocab=V)
    ids = jnp.asarray(np.random.default_rng(2).integers(
        0, V, (B, S), np.int32))
    ref = np.asarray(T.apply_seq(params, ids, n_heads=H, attn="xla"))
    pq = quantize_transformer(params)
    got = np.asarray(jax.jit(
        lambda p, i: apply_seq_w8a8(p, i, n_heads=H, attn="xla"))(pq, ids))
    assert got.shape == ref.shape
    denom = np.abs(ref).max()
    assert np.abs(got - ref).max() / denom < 0.05
    # quantization must not reorder most next-token decisions
    assert (got.argmax(-1) == ref.argmax(-1)).mean() > 0.9
    # the bf16-activation perf path holds the same accuracy contract
    got16 = np.asarray(jax.jit(
        lambda p, i: apply_seq_w8a8(p, i, n_heads=H, attn="xla",
                                    dtype=jnp.bfloat16))(pq, ids))
    assert np.abs(got16 - ref).max() / denom < 0.08
    assert (got16.argmax(-1) == ref.argmax(-1)).mean() > 0.9


def test_quantize_rows_kernel_exact_and_fallback():
    """The Pallas single-pass row quantizer must match the plain
    formula exactly (it replaced the XLA expression as the W8A8 hot
    path), including row counts not divisible by the 8-row Mosaic
    sublane — those now pad up to a multiple of 8 inside the kernel
    path and slice the outputs back (per-row scales make pad rows
    inert), instead of falling back to the multi-HBM-trip XLA twin."""
    import jax.numpy as jnp

    from nnstreamer_tpu.backends.pallas_ops import quantize_rows
    from nnstreamer_tpu.models.quant import w8a8_matmul, quantize_weight

    rng = np.random.default_rng(5)
    x = rng.normal(size=(48, 128)).astype(np.float32)
    x[7] = 0.0                                     # all-zero row: scale 1
    q, s = quantize_rows(jnp.asarray(x))
    amax = np.abs(x).max(-1, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0)
    ref = np.clip(np.round(x / scale), -127, 127).astype(np.int8)
    assert np.array_equal(np.asarray(q), ref)
    np.testing.assert_allclose(np.asarray(s), scale, rtol=1e-6)
    # ragged M (5 % 8 != 0): padded kernel path, outputs sliced to M
    q5, s5 = quantize_rows(jnp.asarray(x[:5]))
    assert np.asarray(q5).shape == (5, 128)
    assert np.array_equal(np.asarray(q5), ref[:5])
    np.testing.assert_allclose(np.asarray(s5), scale[:5], rtol=1e-6)
    # aligned and ragged paths agree through the full matmul
    w = rng.normal(size=(128, 32)).astype(np.float32)
    wq, ws = quantize_weight(jnp.asarray(w))
    kernel_out = np.asarray(w8a8_matmul(jnp.asarray(x), wq, ws))  # 48 % 8 == 0
    fb_out = np.asarray(w8a8_matmul(jnp.asarray(x[:5]), wq, ws))   # 5: padded
    assert kernel_out.shape == (48, 32)
    assert fb_out.shape == (5, 32)
    np.testing.assert_allclose(fb_out, kernel_out[:5], rtol=1e-5, atol=1e-5)


def test_apply_step_w8a8_tracks_float_decode():
    """The quantized decode step must track the float decode step over
    a teacher-forced greedy rollout: after a random prompt token, every
    subsequent input is the FLOAT path's argmax fed to both paths, so
    quantization error flowing through the KV cache cannot hide behind
    diverging inputs and must not compound across steps."""
    from nnstreamer_tpu.models.quant import apply_step_w8a8

    d, H, L, V, B = 64, 4, 2, 64, 2
    params = T.init_params(d_model=d, n_heads=H, n_layers=L, vocab=V)
    pq = quantize_transformer(params)
    rng = np.random.default_rng(3)
    kc, vc, pos = T.init_cache(batch=B, max_len=16, d_model=d,
                               n_heads=H, n_layers=L)
    qkc, qvc, qpos = T.init_cache(batch=B, max_len=16, d_model=d,
                                  n_heads=H, n_layers=L)
    ids = rng.integers(0, V, (B, 1)).astype(np.int32)
    agree = 0
    steps = 12
    for i in range(steps):
        ref, kc, vc, pos = T.apply_step(params, ids, kc, vc, pos,
                                        n_heads=H)
        got, qkc, qvc, qpos = apply_step_w8a8(pq, ids, qkc, qvc, qpos,
                                              n_heads=H)
        ref, got = np.asarray(ref), np.asarray(got)
        denom = np.abs(ref).max() or 1.0
        assert np.abs(got - ref).max() / denom < 0.12, f"step {i}"
        agree += int((got.argmax(-1) == ref.argmax(-1)).all())
        ids = ref.argmax(-1).astype(np.int32)[:, None]   # greedy feedback
    assert agree >= steps - 2       # greedy decisions essentially match
