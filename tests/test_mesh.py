"""Multi-host serving mesh (serving/mesh.py + traffic/netchaos.py):
host registration, lease-based liveness, cross-host failover, two-phase
swap, and deterministic network fault injection.

The load-bearing invariants are the same conservation pair the
single-host admission plane enforces, now summed ACROSS hosts — a
fenced host's in-flight frames are re-offered or typed-BUSY, never
silently lost (ISSUE 12 acceptance)."""

import itertools
import threading
import time

import numpy as np
import pytest

import nnstreamer_tpu.edge.protocol as P
from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.edge import QueryServer
from nnstreamer_tpu.serving.mesh import HostAgent, MeshRouter
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.traffic import ChaosProxy, EchoServer
from nnstreamer_tpu.traffic.loadgen import poisson_arrivals, run_open_loop

_sid = itertools.count(8800)


@pytest.fixture(autouse=True)
def _clean_servers():
    yield
    QueryServer.reset_all()


def _conserved(c: dict) -> bool:
    return (c["offered"] == c["admitted"] + sum(c["rejected"].values())
            and c["admitted"] == c["replied"] + sum(c["shed"].values())
            + c["depth"] + c["inflight"])


def _router(**kw) -> MeshRouter:
    kw.setdefault("sid", next(_sid))
    kw.setdefault("dims", "8:1")
    kw.setdefault("types", "float32")
    return MeshRouter(**kw)


def _join_echo(router: MeshRouter, name: str, *, via_port=None,
               service_ms: float = 5.0, reconnect=True, **echo_kw):
    """EchoServer + a HostAgent registering it with `router` (optionally
    through a chaos proxy at via_port). Returns (echo, agent)."""
    echo = EchoServer(service_ms=service_ms, **echo_kw)
    agent = HostAgent(
        "127.0.0.1", via_port if via_port is not None else router.port,
        name=name, local_port=echo.port, dims="8:1", types="float32",
        capacity_rps=1e3 / max(service_ms, 1e-3),
        connect_timeout_s=2.0, reconnect=reconnect).start()
    return echo, agent


def _flood(router: MeshRouter, n: int, rate: float, *, seed=0,
           trace=True, **kw) -> dict:
    x = np.zeros((8, 1), np.float32)
    return run_open_loop(
        "127.0.0.1", router.port, dims="8:1", types="float32",
        arrivals=poisson_arrivals(rate, n, np.random.default_rng(seed)),
        make_frame=lambda i: TensorBuffer.of(x, pts=i),
        depth_probe=router.depth_probe, trace=trace, **kw)


def _stop_all(*objs):
    for o in objs:
        if o is None:
            continue
        for meth in ("stop", "close"):
            fn = getattr(o, meth, None)
            if fn is not None:
                fn()
                break


# -- registration + routing --------------------------------------------------

class TestMeshBasics:
    def test_two_hosts_conserve_and_split_load(self):
        router = _router()
        ha = hb = aa = ab = None
        try:
            ha, aa = _join_echo(router, "hA")
            hb, ab = _join_echo(router, "hB")
            assert router.wait_hosts(2, timeout_s=10)
            r = _flood(router, 60, 200.0)
            assert r["completed"] == 60 and r["lost"] == 0
            assert r["rejected"] == 0
            c = router.admission_counters()
            assert _conserved(c)
            st = router.stats()
            per_host = {h["host"]: h["replied"] for h in st["hosts"]}
            # both hosts served, and the per-host ledger sums exactly
            # to the router's replied count (cross-host conservation)
            assert set(per_host) == {"hA", "hB"}
            assert all(v > 0 for v in per_host.values())
            assert sum(per_host.values()) == c["replied"]
        finally:
            _stop_all(aa, ab, ha, hb, router)

    def test_incompatible_host_caps_refused(self):
        router = _router(dims="8:1", types="float32")
        echo = agent = None
        try:
            echo = EchoServer(dims="4:1", service_ms=1.0)
            agent = HostAgent(
                "127.0.0.1", router.port, name="wrong",
                local_port=echo.port, dims="4:1", types="float32",
                reconnect=False)
            with pytest.raises(StreamError, match="no REGISTER_ACK"):
                agent.start(timeout_s=2.0)
            assert router.ready_hosts() == 0
        finally:
            _stop_all(agent, echo, router)

    def test_wait_hosts_times_out_without_hosts(self):
        router = _router()
        try:
            assert not router.wait_hosts(1, timeout_s=0.2)
        finally:
            router.close()

    def test_reregistration_replaces_incarnation_keeps_counters(self):
        router = _router()
        echo = a1 = a2 = None
        try:
            # reconnect=False: when a2 replaces this incarnation the
            # fenced a1 must not re-register and flap the name back
            echo, a1 = _join_echo(router, "hA", reconnect=False)
            assert router.wait_hosts(1, timeout_s=10)
            r = _flood(router, 10, 100.0, trace=False)
            assert r["completed"] == 10
            replied_before = router.stats()["hosts"][0]["replied"]
            assert replied_before == 10
            # same name, new connection: the old incarnation is fenced
            # and its monotone counters carry over
            a2 = HostAgent(
                "127.0.0.1", router.port, name="hA",
                local_port=echo.port, dims="8:1", types="float32").start()
            assert router.wait_hosts(1, timeout_s=10)
            st = router.stats()
            assert st["mesh"]["ready"] == 1
            assert st["hosts"][0]["replied"] == replied_before
            kinds = [(h, k) for _, h, k, _ in router.events]
            assert ("hA", "fence") in kinds
        finally:
            _stop_all(a1, a2, echo, router)


# -- lease liveness + cross-host failover ------------------------------------

class TestLeaseFailover:
    def test_blackhole_fences_reoffers_and_keeps_one_trace(self):
        """The acceptance drill, in-process: two hosts, one blackholed
        mid-flood. Zero lost, conservation exact across hosts, fence
        within the lease budget, and a redelivered frame's single trace
        shows BOTH hosts."""
        router = _router(lease_s=0.6, max_redeliver=2)
        proxy = ha = hb = aa = ab = None
        try:
            proxy = ChaosProxy("127.0.0.1", router.port, seed=3)
            ha, aa = _join_echo(router, "hA", via_port=proxy.port,
                                service_ms=60.0)
            hb, ab = _join_echo(router, "hB", service_ms=5.0)
            assert router.wait_hosts(2, timeout_s=10)
            t_bh = [0.0]

            def cut():
                t_bh[0] = time.monotonic()
                proxy.blackhole()

            timer = threading.Timer(0.15, cut)
            timer.start()
            try:
                r = _flood(router, 40, 120.0, drain_timeout_s=20.0)
            finally:
                timer.cancel()
            assert r["completed"] == 40 and r["lost"] == 0
            assert _conserved(router.admission_counters())
            fences = [(t, h, d) for t, h, k, d in router.events
                      if k == "fence" and t >= t_bh[0]]
            assert fences, "blackholed host was never fenced"
            t_f, h_f, cause = fences[0]
            assert h_f == "hA" and cause == "lease_expired"
            assert t_f - t_bh[0] <= 2 * 0.6 + 0.5, \
                "fence detection blew the lease budget"
            assert router.reoffered >= 1
            # the cross-host story: one trace id, both hosts on it
            redelivered = r.get("redelivered_examples") or []
            assert redelivered, "no redelivered frame carried a trace"
            ex = redelivered[0]
            assert ex["hosts"] == ["hA", "hB"]
            assert len(ex["trace_id"]) == 16
        finally:
            _stop_all(aa, ab, proxy, ha, hb, router)

    def test_heal_lets_the_host_rejoin(self):
        router = _router(lease_s=0.5)
        proxy = echo = agent = None
        try:
            proxy = ChaosProxy("127.0.0.1", router.port, seed=1)
            echo, agent = _join_echo(router, "hA", via_port=proxy.port)
            assert router.wait_hosts(1, timeout_s=10)
            proxy.blackhole()
            deadline = time.monotonic() + 5
            while router.ready_hosts() > 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert router.ready_hosts() == 0, "partition never detected"
            proxy.heal()
            # the agent's reconnect loop re-dials through the healed
            # proxy and re-registers under the same name
            assert router.wait_hosts(1, timeout_s=10), \
                "host never rejoined after heal"
            st = router.stats()["hosts"][0]
            assert st["state"] == "READY" and st["host"] == "hA"
        finally:
            _stop_all(agent, proxy, echo, router)

    def test_shed_when_no_alternative_host(self):
        """One host, fenced with frames in flight, nothing to re-offer
        to: frames come back as typed BUSY(host_lost) — counted, never
        lost."""
        router = _router(lease_s=0.5, max_redeliver=2)
        proxy = echo = agent = None
        try:
            proxy = ChaosProxy("127.0.0.1", router.port, seed=2)
            echo, agent = _join_echo(router, "only",
                                     via_port=proxy.port,
                                     service_ms=80.0)
            assert router.wait_hosts(1, timeout_s=10)
            timer = threading.Timer(0.1, proxy.blackhole)
            timer.start()
            try:
                r = _flood(router, 12, 80.0, drain_timeout_s=12.0)
            finally:
                timer.cancel()
            assert r["lost"] == 0
            assert r["completed"] + r["rejected"] == 12
            assert r["rejected"] > 0
            assert r["busy_causes"].get("host_lost", 0) > 0
            c = router.admission_counters()
            assert _conserved(c)
            assert c["shed"].get("host_lost", 0) > 0
        finally:
            _stop_all(agent, proxy, echo, router)


# -- typed-BUSY retry to a different host ------------------------------------

class TestBusyReroute:
    def test_host_busy_retries_on_sibling(self):
        # hA advertises a high capacity but its admission plane is
        # 1-deep and slow — the honest least-outstanding router keeps
        # offering it frames it refuses. Every typed BUSY must be
        # absorbed by re-offering to hB: the client sees zero
        # rejections.
        router = _router(busy_retry=2)
        ha = hb = aa = ab = None
        try:
            ha = EchoServer(service_ms=50.0, max_pending=1,
                            max_inflight=1)
            aa = HostAgent(
                "127.0.0.1", router.port, name="hA",
                local_port=ha.port, dims="8:1", types="float32",
                capacity_rps=500.0).start()   # the lie under test
            hb, ab = _join_echo(router, "hB", service_ms=5.0,
                                max_pending=64)
            assert router.wait_hosts(2, timeout_s=10)
            r = _flood(router, 30, 150.0, drain_timeout_s=20.0)
            assert r["completed"] == 30 and r["lost"] == 0
            assert r["rejected"] == 0
            st = router.stats()
            assert st["mesh"]["busy_reroutes"] >= 1, \
                "no BUSY ever rerouted — the fixture is vacuous"
            assert _conserved(router.admission_counters())
        finally:
            _stop_all(aa, ab, ha, hb, router)


# -- two-phase swap ----------------------------------------------------------

class _SwapHost:
    """EchoServer + agent with a scriptable on_swap hook."""

    def __init__(self, router, name, results=None):
        self.calls = []
        self.results = dict(results or {})
        self.echo = EchoServer(service_ms=1.0)
        # reconnect=False: a host fenced by a failed commit must STAY
        # fenced for the assertion, not quietly re-register
        self.agent = HostAgent(
            "127.0.0.1", router.port, name=name,
            local_port=self.echo.port, dims="8:1", types="float32",
            versions={"m": [0]}, on_swap=self._on_swap,
            reconnect=False).start()

    def _on_swap(self, phase, model, version):
        self.calls.append((phase, model, version))
        return self.results.get(phase, True)

    def stop(self):
        self.agent.stop()
        self.echo.stop()


class TestMeshSwap:
    def test_commit_bumps_epoch_on_all_ok(self):
        router = _router()
        a = b = None
        try:
            a = _SwapHost(router, "hA")
            b = _SwapHost(router, "hB")
            assert router.wait_hosts(2, timeout_s=10)
            rep = router.swap("m", 1, timeout_s=10)
            assert rep["ok"], rep
            assert rep["epoch"] == 1 and router.epoch == 1
            for h in (a, b):
                phases = [p for p, _, _ in h.calls]
                assert phases == ["prepare", "commit"]
            st = router.stats()
            assert all(1 in h["versions"]["m"] for h in st["hosts"])
        finally:
            _stop_all(a, b, router)

    def test_prepare_failure_aborts_everywhere_nobody_fenced(self):
        router = _router()
        a = b = None
        try:
            a = _SwapHost(router, "hA")
            b = _SwapHost(router, "hB",
                          results={"prepare": (False, "no space")})
            assert router.wait_hosts(2, timeout_s=10)
            rep = router.swap("m", 1, timeout_s=10)
            assert not rep["ok"]
            assert router.epoch == 0
            # all-or-none: the healthy host saw prepare then abort,
            # never commit — and stays READY
            assert [p for p, _, _ in a.calls] == ["prepare", "abort"]
            assert router.ready_hosts() == 2
        finally:
            _stop_all(a, b, router)

    def test_commit_failure_fences_the_divergent_host(self):
        router = _router()
        a = b = None
        try:
            a = _SwapHost(router, "hA")
            b = _SwapHost(router, "hB",
                          results={"commit": (False, "load failed")})
            assert router.wait_hosts(2, timeout_s=10)
            rep = router.swap("m", 1, timeout_s=10)
            assert not rep["ok"]
            assert router.epoch == 0, \
                "epoch must not move on a failed commit"
            # the host that acked prepare but failed commit would be
            # serving a different version than its siblings: fenced
            st = {h["host"]: h for h in router.stats()["hosts"]}
            assert st["hB"]["state"] == "FENCED"
            assert st["hB"]["fence_cause"] == "swap_commit_failed"
            assert st["hA"]["state"] == "READY"
        finally:
            _stop_all(a, b, router)


# -- deterministic network fault injection -----------------------------------

def _proxy_echo_run(n=30, **faults):
    """Send n frames through proxy→echo, wait for the replies that
    survive the faults, return (proxy stats, replied pts set)."""
    import queue as _q

    from nnstreamer_tpu.edge.wire import encode_buffer, peek_pts

    echo = EchoServer(service_ms=1.0, max_pending=64)
    proxy = ChaosProxy("127.0.0.1", echo.port, **faults)
    got = set()
    hello = _q.Queue()

    def on_msg(mtype, payload):
        if mtype == P.T_RESULT:
            got.add(peek_pts(payload))
        elif mtype == P.T_HELLO_ACK:
            hello.put(True)

    cli = P.MsgClient("127.0.0.1", proxy.port, on_message=on_msg)
    try:
        cli.send(P.T_HELLO, b'{"dims": "8:1", "types": "float32"}')
        hello.get(timeout=10)
        x = np.zeros((8, 1), np.float32)
        for i in range(n):
            cli.send(P.T_DATA, encode_buffer(TensorBuffer.of(x, pts=i)))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            s = proxy.stats()
            settled = s["dropped"] + s["forwarded"]
            if len(got) >= n or settled >= 2 * n:
                time.sleep(0.3)     # let stragglers land
                break
            time.sleep(0.02)
        return proxy.stats(), set(got)
    finally:
        cli.close()
        proxy.close()
        echo.stop()


class TestNetChaos:
    def test_same_seed_same_fault_schedule(self):
        s1, got1 = _proxy_echo_run(seed=11, drop_p=0.3)
        s2, got2 = _proxy_echo_run(seed=11, drop_p=0.3)
        assert s1["dropped"] == s2["dropped"] > 0
        assert s1["forwarded"] == s2["forwarded"]
        assert got1 == got2, "per-frame outcomes must reproduce"
        s3, _ = _proxy_echo_run(seed=12, drop_p=0.3)
        assert (s3["dropped"], s3["forwarded"]) \
            != (s1["dropped"], s1["forwarded"]), \
            "different seed produced the identical schedule (suspicious)"

    def test_duplicates_are_injected_not_corrupted(self):
        # dup_p=1: every unspared message is sent twice. The echo
        # server answers each copy — message-level duplication must
        # never corrupt framing, so ALL replies decode.
        s, got = _proxy_echo_run(n=10, seed=0, dup_p=1.0)
        assert s["duplicated"] > 0
        assert got == set(range(10))

    def test_delay_shifts_latency_not_outcomes(self):
        t0 = time.monotonic()
        s, got = _proxy_echo_run(n=8, seed=0, delay_ms=30.0)
        assert got == set(range(8))
        assert s["delayed"] > 0
        assert time.monotonic() - t0 >= 0.03

    def test_blackhole_discards_and_withholds_fin(self):
        echo = EchoServer(service_ms=1.0)
        proxy = ChaosProxy("127.0.0.1", echo.port, seed=0)
        closed = threading.Event()
        cli = P.MsgClient("127.0.0.1", proxy.port,
                          on_message=lambda *a: None,
                          on_close=lambda: closed.set())
        try:
            cli.send(P.T_HELLO, b'{"dims": "8:1", "types": "float32"}')
            time.sleep(0.2)
            proxy.blackhole()
            cli.send(P.T_HELLO, b"{}")
            time.sleep(0.3)
            # a partition is silence, not a clean close: the peer must
            # NOT learn anything (that is what the lease is for)
            assert not closed.is_set()
            assert proxy.stats()["discarded"] >= 1
            proxy.heal()
            assert closed.wait(5), "heal must close severed routes"
        finally:
            cli.close()
            proxy.close()
            echo.stop()

    def test_slow_close_wedges_then_closes(self):
        echo = EchoServer(service_ms=1.0)
        proxy = ChaosProxy("127.0.0.1", echo.port, seed=0)
        closed = threading.Event()
        cli = P.MsgClient("127.0.0.1", proxy.port,
                          on_message=lambda *a: None,
                          on_close=lambda: closed.set())
        try:
            cli.send(P.T_HELLO, b'{"dims": "8:1", "types": "float32"}')
            time.sleep(0.2)
            t0 = time.monotonic()
            proxy.slow_close(linger_s=0.3)
            assert closed.wait(5), "slow_close never closed"
            assert time.monotonic() - t0 >= 0.25, \
                "closed immediately — the linger (wedge) phase is the " \
                "point"
        finally:
            cli.close()
            proxy.close()
            echo.stop()


# -- outbound connect timeouts (satellite: edge dial bound) ------------------

class TestConnectTimeout:
    @staticmethod
    def _saturated_listener():
        """A listening socket whose accept queue is full and never
        drained: further connects hang in SYN limbo — exactly the
        silent-blackhole shape a raw connect() waits ~2min on."""
        import socket

        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.bind(("127.0.0.1", 0))
        srv.listen(0)
        port = srv.getsockname()[1]
        fillers = []
        for _ in range(4):   # overfill the tiny backlog
            f = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            f.setblocking(False)
            try:
                f.connect_ex(("127.0.0.1", port))
            except OSError:
                pass
            fillers.append(f)
        time.sleep(0.1)
        return srv, port, fillers

    def test_msgclient_dial_bounded(self):
        srv, port, fillers = self._saturated_listener()
        try:
            t0 = time.monotonic()
            with pytest.raises(StreamError, match="cannot connect"):
                P.MsgClient("127.0.0.1", port,
                            on_message=lambda *a: None,
                            connect_timeout=0.3, retries=1)
            elapsed = time.monotonic() - t0
            assert elapsed < 5.0, (
                f"dial took {elapsed:.1f}s — the connect timeout never "
                f"reached the socket (OS default is ~minutes)")
        finally:
            for f in fillers:
                f.close()
            srv.close()

    def test_query_client_exposes_connect_timeout_prop(self):
        from nnstreamer_tpu.edge.query import TensorQueryClient

        pd = TensorQueryClient.PROPS["connect_timeout"]
        assert pd.default == 0.0

    def test_default_connect_timeout_is_finite(self):
        assert 0 < P.DEFAULT_CONNECT_TIMEOUT_S < 60


# -- the chaos harness over real pool hosts ----------------------------------

class TestMeshHarness:
    def test_pool_blackhole_smoke(self):
        """Tier-1-safe end-to-end: 2 subprocess pool hosts behind one
        router, one blackholed mid-flood. Everything the full flood
        gates on, at a size that fits the tier-1 clock."""
        from nnstreamer_tpu.traffic import run_against_mesh

        r = run_against_mesh(hosts=2, workers_per_host=1, n=40,
                             service_ms=10.0, load_x=1.2, seed=0,
                             lease_s=0.8, max_redeliver=2)
        assert r["lost"] == 0 and r["conserved"]
        assert r["completed"] + r["rejected"] == 40
        assert r["recovered"], (
            f"fence took {r.get('fence_detect_s')}s against a "
            f"{r['lease_s']}s lease")
        assert r["perhost_replied_sum"] == \
            r["admission"]["replied"]
        assert r["orphans"] == []
        ex = r.get("redelivered_examples") or []
        assert ex and len(ex[0]["hosts"]) == 2, \
            "no frame was redelivered across hosts with one trace id"

    @pytest.mark.mesh
    @pytest.mark.slow
    def test_pool_blackhole_full_flood_with_heal(self):
        """The full ISSUE 12 acceptance: 1.5x aggregate capacity, a
        mid-flood partition, and a heal — the fenced host must rejoin
        and the ledger must balance to the last frame."""
        from nnstreamer_tpu.traffic import run_against_mesh

        r = run_against_mesh(hosts=2, workers_per_host=2, n=300,
                             service_ms=20.0, load_x=1.5, seed=42,
                             lease_s=1.0, max_redeliver=2,
                             heal_after_s=2.0)
        assert r["lost"] == 0 and r["conserved"]
        assert r["recovered"]
        assert r["rejoined"], "healed host never re-registered"
        assert r["perhost_replied_sum"] == r["admission"]["replied"]
        assert r["orphans"] == []
