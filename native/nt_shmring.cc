// Shared-memory SPSC frame ring — zero-copy local IPC transport.
//
// Native-runtime component (SURVEY.md §7: "native C++ only where latency
// demands — zero-copy ingest, wire protocol"). The reference gets local
// zero-copy from GStreamer's GstMemory ref-counting inside ONE process;
// crossing processes it falls back to TCP/MQTT serialization. This ring
// gives nnstreamer_tpu a faster primitive: frames move between local
// pipeline processes through /dev/shm with exactly one memcpy in, one
// out, and no socket stack.
//
// Layout in the shm segment:
//   [Header | data bytes ... capacity]
// Frames are length-prefixed (u64) and may wrap. Single producer, single
// consumer; a process-shared mutex + condvars coordinate blocking.
//
// Exported C ABI (ctypes-consumed from nnstreamer_tpu/native/__init__.py):
//   nt_ring_create / nt_ring_open / nt_ring_close / nt_ring_unlink
//   nt_ring_write(h, data, len, timeout_ms)      -> 0 ok, <0 error
//   nt_ring_next_len(h, timeout_ms)              -> frame len, 0 timeout,
//                                                   -1 closed+empty
//   nt_ring_read(h, out, cap)                    -> bytes read, <0 error
//   nt_ring_mark_closed(h)                       -> wake readers, EOS

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x544E524E47303131ULL;  // "TNRNG011"

struct Header {
  uint64_t magic;
  uint64_t capacity;    // data area size in bytes
  uint64_t head;        // producer write offset (monotonic)
  uint64_t tail;        // consumer read offset (monotonic)
  uint32_t closed;      // producer signalled EOS
  uint32_t _pad;
  pthread_mutex_t mu;
  pthread_cond_t can_read;
  pthread_cond_t can_write;
};

struct Ring {
  Header* h;
  uint8_t* data;
  uint64_t map_size;
  int fd;
  char name[128];
};

uint64_t used(const Header* h) { return h->head - h->tail; }

void abs_deadline(struct timespec* ts, int timeout_ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (long)(timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

// copy in/out with wrap-around
void ring_put(Header* h, uint8_t* data, const uint8_t* src, uint64_t len) {
  uint64_t pos = h->head % h->capacity;
  uint64_t first = len < h->capacity - pos ? len : h->capacity - pos;
  memcpy(data + pos, src, first);
  if (len > first) memcpy(data, src + first, len - first);
  h->head += len;
}

void ring_get(Header* h, const uint8_t* data, uint8_t* dst, uint64_t len) {
  uint64_t pos = h->tail % h->capacity;
  uint64_t first = len < h->capacity - pos ? len : h->capacity - pos;
  memcpy(dst, data + pos, first);
  if (len > first) memcpy(dst + first, data, len - first);
  h->tail += len;
}

void ring_peek_len(const Header* h, const uint8_t* data, uint64_t* out_len) {
  uint8_t tmp[8];
  uint64_t pos = h->tail % h->capacity;
  uint64_t first = 8 < h->capacity - pos ? 8 : h->capacity - pos;
  memcpy(tmp, data + pos, first);
  if (8 > first) memcpy(tmp + first, data, 8 - first);
  memcpy(out_len, tmp, 8);
}

}  // namespace

extern "C" {

Ring* nt_ring_create(const char* name, uint64_t capacity) {
  if (capacity < (1u << 12)) capacity = 1u << 12;
  uint64_t total = sizeof(Header) + capacity;
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Header* h = (Header*)mem;
  memset(h, 0, sizeof(Header));
  h->capacity = capacity;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->can_read, &ca);
  pthread_cond_init(&h->can_write, &ca);
  h->magic = kMagic;  // publish last

  Ring* r = new Ring{h, (uint8_t*)mem + sizeof(Header), total, fd, {0}};
  snprintf(r->name, sizeof(r->name), "%s", name);
  return r;
}

Ring* nt_ring_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* mem =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* h = (Header*)mem;
  if (h->magic != kMagic ||
      sizeof(Header) + h->capacity > (uint64_t)st.st_size) {
    munmap(mem, st.st_size);
    close(fd);
    return nullptr;
  }
  Ring* r = new Ring{h, (uint8_t*)mem + sizeof(Header), (uint64_t)st.st_size,
                     fd, {0}};
  snprintf(r->name, sizeof(r->name), "%s", name);
  return r;
}

static int lock_robust(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {  // peer died holding the lock: recover
    pthread_mutex_consistent(&h->mu);
    h->closed = 1;
    return 0;
  }
  return rc;
}

int nt_ring_write(Ring* r, const uint8_t* buf, uint64_t len, int timeout_ms) {
  Header* h = r->h;
  uint64_t need = len + 8;
  if (need > h->capacity) return -2;  // frame larger than the ring
  if (lock_robust(h) != 0) return -3;
  struct timespec ts;
  abs_deadline(&ts, timeout_ms);
  while (h->capacity - used(h) < need && !h->closed) {
    int rc = pthread_cond_timedwait(&h->can_write, &h->mu, &ts);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -4;  // timeout: consumer too slow
    }
    if (rc == EOWNERDEAD) {  // peer died mid-operation: recover + EOS
      pthread_mutex_consistent(&h->mu);
      h->closed = 1;
      break;
    }
    if (rc != 0) {  // inconsistent/invalid mutex: don't spin
      pthread_mutex_unlock(&h->mu);
      return -3;
    }
  }
  if (h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  uint8_t lenbuf[8];
  memcpy(lenbuf, &len, 8);
  ring_put(h, r->data, lenbuf, 8);
  ring_put(h, r->data, buf, len);
  pthread_cond_signal(&h->can_read);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

int64_t nt_ring_next_len(Ring* r, int timeout_ms) {
  Header* h = r->h;
  if (lock_robust(h) != 0) return -3;
  struct timespec ts;
  abs_deadline(&ts, timeout_ms);
  while (used(h) < 8) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -1;  // EOS and drained
    }
    int rc = pthread_cond_timedwait(&h->can_read, &h->mu, &ts);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return 0;  // timeout, retry
    }
    if (rc == EOWNERDEAD) {
      pthread_mutex_consistent(&h->mu);
      h->closed = 1;
    } else if (rc != 0) {
      pthread_mutex_unlock(&h->mu);
      return -3;
    }
  }
  uint64_t len;
  ring_peek_len(h, r->data, &len);
  pthread_mutex_unlock(&h->mu);
  return (int64_t)len;
}

int64_t nt_ring_read(Ring* r, uint8_t* out, uint64_t cap) {
  Header* h = r->h;
  if (lock_robust(h) != 0) return -3;
  if (used(h) < 8) {
    pthread_mutex_unlock(&h->mu);
    return h->closed ? -1 : 0;
  }
  uint64_t len;
  ring_peek_len(h, r->data, &len);
  if (len > cap) {
    pthread_mutex_unlock(&h->mu);
    return -2;  // caller buffer too small (use nt_ring_next_len first)
  }
  h->tail += 8;  // consume the length prefix
  ring_get(h, r->data, out, len);
  pthread_cond_signal(&h->can_write);
  pthread_mutex_unlock(&h->mu);
  return (int64_t)len;
}

void nt_ring_mark_closed(Ring* r) {
  Header* h = r->h;
  if (lock_robust(h) != 0) return;
  h->closed = 1;
  pthread_cond_broadcast(&h->can_read);
  pthread_cond_broadcast(&h->can_write);
  pthread_mutex_unlock(&h->mu);
}

void nt_ring_close(Ring* r) {
  if (!r) return;
  munmap((void*)((uint8_t*)r->data - sizeof(Header)), r->map_size);
  close(r->fd);
  delete r;
}

int nt_ring_unlink(const char* name) { return shm_unlink(name); }

uint64_t nt_ring_capacity(Ring* r) { return r->h->capacity; }
uint64_t nt_ring_used(Ring* r) {
  Header* h = r->h;
  if (lock_robust(h) != 0) return 0;
  uint64_t u = used(h);
  pthread_mutex_unlock(&h->mu);
  return u;
}

}  // extern "C"
