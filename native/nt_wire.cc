// Native wire-frame validation/scan — the hot header path of the edge
// transport (edge/wire.py documents the layout; this is the C twin used
// by relays and the IPC elements to validate frames without Python).
//
//   frame:  u32 magic('TPUF') u32 num s64 pts u64 client_id u32 meta_len
//           meta | per tensor: tensor-meta header + payload
//   tensor: u32 magic('TPUT') u32 ver u32 dtype u32 fmt u32 media u32 rank
//           u32 dims[rank] u32 extra
//
// nt_wire_frame_size(data, len) -> total frame bytes if a complete valid
// frame starts at data[0]; 0 if more bytes are needed; -1 if corrupt.

#include <cstdint>
#include <cstring>

namespace {

constexpr uint32_t kFrameMagic = 0x54505546;   // 'TPUF'
constexpr uint32_t kTensorMagic = 0x54505554;  // 'TPUT'
constexpr uint32_t kMaxTensors = 16;
constexpr uint32_t kMaxRank = 16;
constexpr uint64_t kMaxFrame = 1ull << 31;

// dtype sizes must match tensor/dtypes.py enum order:
// INT32 UINT32 INT16 UINT16 INT8 UINT8 FLOAT64 FLOAT32 INT64 UINT64
// FLOAT16 BFLOAT16
constexpr uint32_t kDtypeSize[] = {
    4, 4, 2, 2, 1, 1, 8, 4, 8, 8, 2, 2,
};
constexpr uint32_t kNumDtypes = sizeof(kDtypeSize) / sizeof(uint32_t);

uint32_t rd32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

}  // namespace

extern "C" {

// → total tensor block size (header+payload) or 0/-1 as frame_size
int64_t nt_wire_tensor_size(const uint8_t* p, uint64_t len) {
  const uint64_t fixed = 6 * 4;
  if (len < fixed) return 0;
  if (rd32(p) != kTensorMagic) return -1;
  uint32_t version = rd32(p + 4);
  uint32_t dtype = rd32(p + 8);
  uint32_t rank = rd32(p + 20);
  if (version != 1 || dtype >= kNumDtypes || rank < 1 || rank > kMaxRank)
    return -1;
  uint64_t hdr = fixed + 4ull * rank + 4;
  if (len < hdr) return 0;
  uint64_t elems = 1;
  for (uint32_t i = 0; i < rank; i++) {
    uint32_t d = rd32(p + fixed + 4ull * i);
    // d == 0 is legal: the python codec emits zero-element tensors
    // (e.g. an empty FLEXIBLE crop region) with a 0 dim
    elems *= d;
    if (elems > kMaxFrame) return -1;
  }
  uint64_t payload = elems * kDtypeSize[dtype];
  if (payload > kMaxFrame) return -1;
  if (len < hdr + payload) return 0;
  return (int64_t)(hdr + payload);
}

int64_t nt_wire_frame_size(const uint8_t* p, uint64_t len) {
  const uint64_t head = 4 + 4 + 8 + 8 + 4;
  if (len < head) return 0;
  if (rd32(p) != kFrameMagic) return -1;
  uint32_t num = rd32(p + 4);
  uint32_t meta_len = rd32(p + 24);
  if (num > kMaxTensors || meta_len > kMaxFrame) return -1;
  uint64_t off = head + meta_len;
  if (len < off) return 0;
  for (uint32_t i = 0; i < num; i++) {
    int64_t t = nt_wire_tensor_size(p + off, len - off);
    if (t < 0) return -1;
    if (t == 0) return 0;
    off += (uint64_t)t;
    if (off > kMaxFrame) return -1;
  }
  return (int64_t)off;
}

}  // extern "C"
