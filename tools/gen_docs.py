"""Generate docs/elements.md from the element/decoder registries.

The reference ships per-element docs (gst/nnstreamer/elements/
gsttensor_*.md + Documentation/component-description.md); here the
single source of truth is the registry itself — every PropDef and class
docstring (which carry the reference file:line citations) renders into
one browsable page.  CI regenerates and diffs, so the page cannot drift
from the code.

Usage:
    python tools/gen_docs.py          # writes docs/elements.md
    python tools/gen_docs.py --check  # exit 1 if the file is stale
"""
from __future__ import annotations

import os
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "docs", "elements.md")
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _doc(obj) -> str:
    d = obj.__doc__ or ""
    return textwrap.dedent("    " + d.strip()).strip() if d.strip() else ""


def _props_table(cls) -> str:
    rows = ["| property | default | description |",
            "|---|---|---|"]
    for prop, pd in cls.PROPS.items():
        doc = (pd.doc or "").replace("|", "\\|")
        rows.append(f"| `{prop.replace('_', '-')}` | `{pd.default!r}` "
                    f"| {doc} |")
    return "\n".join(rows) if len(rows) > 2 else ""


def render() -> str:
    import nnstreamer_tpu.decoders  # noqa: F401 (register)
    import nnstreamer_tpu.elements  # noqa: F401 (register)
    from nnstreamer_tpu.core.registry import PluginKind, registry

    parts = [
        "# Element reference",
        "",
        "Generated from the element registry by `tools/gen_docs.py` — "
        "do not edit by hand (`python tools/gen_docs.py` regenerates; "
        "CI diffs it).  The same information is available at the CLI "
        "via `python -m nnstreamer_tpu --inspect [element]`.",
        "",
        "Docstrings cite the reference implementation "
        "(`file.c:line`) each element is parity-matched against.",
        "",
    ]
    from nnstreamer_tpu.graph.pipeline import Element

    parts.append("## Common properties (every element)")
    parts.append("")
    parts.append("Resolved alongside each element's own property table "
                 "(see `docs/robustness.md` for semantics).")
    parts.append("")
    rows = ["| property | default | description |", "|---|---|---|"]
    for prop, pd in Element.COMMON_PROPS.items():
        doc = (pd.doc or "").replace("|", "\\|")
        rows.append(f"| `{prop.replace('_', '-')}` | `{str(pd.default)!r}` "
                    f"| {doc} |")
    parts.append("\n".join(rows))
    parts.append("")
    names = sorted(registry.names(PluginKind.ELEMENT))
    parts.append("## Elements")
    parts.append("")
    for n in names:
        # GitHub heading slugs preserve underscores
        parts.append(f"- [`{n}`](#{n})")
    parts.append("")
    from nnstreamer_tpu.analysis.contract import contract_badges

    for n in names:
        cls = registry.get(PluginKind.ELEMENT, n)
        parts.append(f"### {n}")
        parts.append("")
        parts.append(f"*class `{cls.__module__}.{cls.__name__}`*")
        parts.append("")
        # the same introspection the scheduler and the NNL001 lint rule
        # use — the docs cannot drift from the declared contract
        parts.append(f"*contract: {contract_badges(cls)}*")
        parts.append("")
        doc = _doc(cls)
        if doc:
            parts.append(doc)
            parts.append("")
        table = _props_table(cls)
        if table:
            parts.append(table)
            parts.append("")
    parts.append("## Decoder modes (`tensor_decoder mode=`)")
    parts.append("")
    for n in sorted(registry.names(PluginKind.DECODER)):
        cls = registry.get(PluginKind.DECODER, n)
        parts.append(f"### mode={n}")
        parts.append("")
        doc = _doc(cls) or _doc(sys.modules.get(cls.__module__))
        if doc:
            parts.append(doc)
            parts.append("")
    return "\n".join(parts).rstrip() + "\n"


def main() -> int:
    text = render()
    if "--check" in sys.argv:
        on_disk = open(OUT).read() if os.path.exists(OUT) else ""
        if on_disk != text:
            print("docs/elements.md is stale — run python "
                  "tools/gen_docs.py", file=sys.stderr)
            return 1
        print("docs/elements.md up to date")
        return 0
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        f.write(text)
    print(f"wrote {OUT} ({len(text.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
